"""HealthMonitor: the per-run evaluation loop the driver feeds.

One instance per Driver (``--health``).  The driver calls:

* :meth:`maybe_rotate` once per recorded run (same cadence as its own
  logs — the event log is a third rotating family);
* :meth:`observe` for every sample that produced a row,
  :meth:`observe_drop` for every dropped run;
* :meth:`heartbeat` at every stats boundary — capture-loss judgement
  over the window's drop counters plus the exporter refresh;
* :meth:`close` at driver exit — the final partial window is judged for
  capture loss (a bounded run shorter than ``stats_every`` never reaches
  a boundary), the exporter flushed, the event log closed.

The monitor never raises into the measurement loop: a failing textfile
write is reported to stderr and retried at the next boundary, the same
never-fatal stance the ingest hook takes (driver.RotatingCsvLog).
"""

from __future__ import annotations

import sys

from tpu_perf.health.detect import (
    SEVERITY_RANK, Finding, HealthConfig, PointDetector,
    capture_loss_finding,
)
from tpu_perf.health.events import HealthEvent
from tpu_perf.health.exporter import PointGauges, TextfileExporter
from tpu_perf.metrics import bus_bandwidth_gbps, metric_op
from tpu_perf.schema import timestamp_now, window_index


class _PointState:
    """Detector plus the row metadata the exporter needs."""

    def __init__(self, config: HealthConfig, iters: int, n_devices: int):
        self.detector = PointDetector(config)
        self.iters = iters
        self.n_devices = n_devices
        # severity of the standing regression (remembered from its entry
        # event while detector.regressed holds); None when not regressed
        self.regression_sev: str | None = None

    @property
    def last_severity(self) -> str:
        """The standing severity gauge: derived from the detector's
        CURRENT state, not the last event — a transient spike must not
        pin the gauge, and a cleared flatline must release it."""
        sev = "info"
        if self.detector.flatlined:
            sev = "warning"
        if self.detector.regressed and self.regression_sev is not None:
            if SEVERITY_RANK[self.regression_sev] > SEVERITY_RANK[sev]:
                sev = self.regression_sev
        return sev


class HealthMonitor:
    def __init__(
        self,
        config: HealthConfig,
        *,
        job_id: str,
        dtype: str,
        rank: int = 0,
        stats_every: int = 1000,
        event_log=None,   # RotatingCsvLog(prefix="health") or None
        textfile: str | None = None,
        err=None,
        phase_source=None,  # () -> {"compile_s": ..., ...} — the driver's
        #                     PhaseTimer.snapshot; the exporter publishes
        #                     harness-overhead gauges next to the health
        #                     gauges so dashboards can alert on e.g. a
        #                     compile-cache regression doubling compile_s
        adaptive_source=None,  # () -> the driver's cumulative adaptive
        #                     savings dict (or None while no controller
        #                     runs): runs-saved counter + last achieved
        #                     CI land next to the phase gauges
        push_source=None,  # () -> the push plane's cumulative meter
        #                     snapshot (or None while the plane is off):
        #                     sent/dropped/retried/spool gauges land
        #                     next to the health gauges so "is telemetry
        #                     flowing" alerts where "is the fleet
        #                     healthy" already does
    ):
        self.config = config
        self.job_id = job_id
        self.dtype = dtype
        self.rank = rank
        self.stats_every = max(1, stats_every)
        self.event_log = event_log
        self.exporter = TextfileExporter(textfile) if textfile else None
        self.phase_source = phase_source
        self.adaptive_source = adaptive_source
        self.push_source = push_source
        self.err = err if err is not None else sys.stderr
        self._points: dict[tuple[str, int], _PointState] = {}
        # heartbeat-window counters, cleared at each boundary
        self._window_seen: dict[str, int] = {}
        self._window_dropped: dict[str, int] = {}
        # last COMPLETED window's drop rates (the exporter gauge)
        self._drop_rates: dict[str, float] = {}
        self.events_total: dict[str, int] = {}
        self._last_run_id = 0  # close() flushes the final partial window

    # -- driver-facing hooks -------------------------------------------

    def maybe_rotate(self) -> None:
        if self.event_log is not None:
            self.event_log.maybe_rotate()

    def observe(
        self,
        op: str,
        nbytes: int,
        iters: int,
        n_devices: int,
        run_id: int,
        t: float,
        span_id: str = "",
    ) -> list[HealthEvent]:
        """Fold one recorded run into its point baseline; judge it.
        ``span_id`` (the driver's enclosing run span, --spans) is
        stamped into any event this run raises."""
        st = self._points.get((op, nbytes))
        if st is None:
            st = self._points[(op, nbytes)] = _PointState(
                self.config, iters, n_devices
            )
        self._window_seen[op] = self._window_seen.get(op, 0) + 1
        self._last_run_id = max(self._last_run_id, run_id)
        findings = st.detector.observe(t)
        events = [self._emit(f, op=op, nbytes=nbytes, run_id=run_id,
                             span_id=span_id)
                  for f in findings]
        for ev in events:
            if ev.kind == "regression":
                st.regression_sev = ev.severity
        if not st.detector.regressed:
            st.regression_sev = None
        return events

    def observe_drop(self, op: str, run_id: int) -> None:
        self._window_dropped[op] = self._window_dropped.get(op, 0) + 1
        self._last_run_id = max(self._last_run_id, run_id)

    def observe_hook_fail(self, run_id: int,
                          span_id: str = "") -> list[HealthEvent]:
        """The driver's rotation ingest hook raised: surface it as a
        health event — telemetry upload failing is fleet degradation
        even when every measured sample is clean.  Stateless per
        occurrence (the hook retries next rotation; each failure is its
        own event).  ``op`` is the synthetic ``ingest_hook`` point:
        hook failures belong to the pipeline, not to any kernel."""
        self._last_run_id = max(self._last_run_id, run_id)
        f = Finding("hook_fail", "warning", 1.0, 0.0, unit="failures")
        return [self._emit(f, op="ingest_hook", nbytes=0, run_id=run_id,
                           span_id=span_id)]

    def observe_drain_fail(self, host: str, run_id: int = 0,
                           span_id: str = "") -> list[HealthEvent]:
        """A `fleet report --drain-hook` invocation failed for a sick
        host: surface it as a health event — a drain that silently did
        NOT happen leaves the scheduler placing work on a host the
        grader already condemned.  ``op`` is the synthetic
        ``drain:<host>`` point (the hook belongs to the control plane,
        not to any kernel — the hook_fail precedent)."""
        f = Finding("drain_fail", "critical", 1.0, 0.0, unit="failures")
        return [self._emit(f, op=f"drain:{host}", nbytes=0,
                           run_id=run_id, span_id=span_id)]

    def observe_link(
        self,
        op: str,
        nbytes: int,
        run_id: int,
        observed: float,
        baseline: float,
        *,
        severity: str = "warning",
        rank: int | None = None,
        span_id: str = "",
    ) -> list[HealthEvent]:
        """A linkmap sweep graded one link non-ok: surface it as a
        ``link_degraded`` health event so the fleet learns "link
        (2,3)→(3,3) slow, rank 1" instead of a bare curve regression.
        ``op`` is the probe's link name (``link:(2,3)>(3,3)``), ``rank``
        the link's OWNING host (the src device's process — which may
        differ from the rank running the sweep, hence the override).
        Stateless per sweep, like hook_fail: each graded sweep speaks
        for itself; episode tracking lives in the sweep cadence."""
        self._last_run_id = max(self._last_run_id, run_id)
        f = Finding("link_degraded", severity, observed, baseline)
        return [self._emit(f, op=op, nbytes=nbytes, run_id=run_id,
                           rank=rank, span_id=span_id)]

    def heartbeat(self, run_id: int) -> list[HealthEvent]:
        """Stats-boundary work: capture-loss judgement over the window's
        drop counters, then the exporter refresh."""
        events = []
        window_ops = set(self._window_seen) | set(self._window_dropped)
        for op in self._drop_rates:
            # an op absent from this window had no drops in it — the
            # gauge names the LAST window, it must not pin an old rate
            if op not in window_ops:
                self._drop_rates[op] = 0.0
        for op in sorted(window_ops):
            dropped = self._window_dropped.get(op, 0)
            total = dropped + self._window_seen.get(op, 0)
            self._drop_rates[op] = dropped / total if total else 0.0
            finding = capture_loss_finding(dropped, total, self.config)
            if finding is not None:
                # op-level event: nbytes=0 = "all sizes of this op"
                events.append(self._emit(finding, op=op, nbytes=0,
                                         run_id=run_id))
        self._window_seen.clear()
        self._window_dropped.clear()
        self._refresh_exporter()
        return events

    def close(self) -> None:
        """Final partial window first: a bounded run shorter than
        stats_every would otherwise never judge capture loss (or export
        drop-rate gauges) at all.  heartbeat() refreshes the exporter."""
        if self._window_seen or self._window_dropped:
            self.heartbeat(self._last_run_id)
        else:
            self._refresh_exporter()
        if self.event_log is not None:
            self.event_log.close()

    # -- internals ------------------------------------------------------

    def _emit(self, f: Finding, *, op: str, nbytes: int,
              run_id: int, rank: int | None = None,
              span_id: str = "") -> HealthEvent:
        ev = HealthEvent(
            timestamp=timestamp_now(),
            job_id=self.job_id,
            rank=self.rank if rank is None else rank,
            kind=f.kind,
            severity=f.severity,
            op=op,
            nbytes=nbytes,
            dtype=self.dtype,
            run_id=run_id,
            # runs 1..stats_every share window 0 WITH the boundary
            # heartbeat that covers them (which fires at
            # run_id == stats_every), so events join back to the drop
            # counters and heartbeat line of their own window
            window=window_index(run_id, self.stats_every),
            observed=f.observed,
            baseline=f.baseline,
            unit=f.unit,
            span_id=span_id,
        )
        self.events_total[ev.kind] = self.events_total.get(ev.kind, 0) + 1
        if self.event_log is not None:
            self.event_log.write_row(ev)
        if ev.severity != "info":
            # warnings/criticals also go to stderr so a daemon without a
            # logfolder still surfaces degradation at the console
            print(
                f"[tpu-perf health] {ev.severity} {ev.kind}: {ev.op}"
                f"/{ev.nbytes or '*'} run {ev.run_id} observed "
                f"{ev.observed:.6g} vs baseline {ev.baseline:.6g} {ev.unit}",
                file=self.err, flush=True,
            )
        return ev

    def snapshot(self) -> list[PointGauges]:
        """Current per-point gauges (exporter rows)."""
        rows = []
        for (op, nbytes), st in sorted(self._points.items()):
            b = st.detector.baseline
            p50, p99 = b.p50.value(), b.p99.value()
            if p50 is None or p50 <= 0:
                continue
            per_op = p50 / st.iters
            try:
                busbw = bus_bandwidth_gbps(
                    metric_op(op), nbytes, per_op, st.n_devices
                )
            except ValueError:
                busbw = 0.0  # foreign op name: no wire model, gauge 0
            rows.append(PointGauges(
                op=op, nbytes=nbytes, dtype=self.dtype, samples=b.n,
                lat_p50_us=per_op * 1e6,
                lat_p99_us=(p99 or p50) / st.iters * 1e6,
                busbw_gbps=busbw,
                severity=st.last_severity,
            ))
        return rows

    def _refresh_exporter(self) -> None:
        if self.exporter is None:
            return
        try:
            self.exporter.write(
                self.snapshot(), dict(self._drop_rates),
                dict(self.events_total),
                phases=self.phase_source() if self.phase_source else None,
                adaptive=(self.adaptive_source()
                          if self.adaptive_source else None),
                push=self.push_source() if self.push_source else None,
            )
        except OSError as e:
            # never fatal: the gauges go stale for one window, the
            # daemon keeps measuring (same stance as the ingest hook)
            print(f"[tpu-perf health] textfile write failed: {e}",
                  file=self.err, flush=True)
