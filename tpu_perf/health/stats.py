"""Streaming per-point estimators: the rolling baseline with no sample
retention.

The monitoring daemon visits each (op, nbytes, dtype) sweep point forever
(driver._run_daemon round-robin), so per-point state must be O(1) in the
number of runs — a week-long soak cannot keep its samples.  Three
estimator families cover what the detectors need:

* :class:`Welford` — numerically stable running mean/variance (Welford
  1962), the z-score denominator for spike detection;
* :class:`EWMA` — exponentially weighted moving average, the short-term
  level a step regression moves;
* :class:`P2Quantile` — the P² streaming quantile (Jain & Chlamtac 1985,
  CACM): five markers tracking an arbitrary quantile with parabolic
  interpolation, no histogram, no samples.  The long-run p50 is the
  baseline a regressed EWMA is judged against; the p99 feeds the
  exporter's tail gauge.

:class:`PointBaseline` bundles one of each per sweep point with warm-up
gating — a point is never judged before ``warmup`` samples have shaped
its baseline (imbalanced arrival patterns make early windows noisy,
arXiv:1804.05349; per-link asymmetries make one global threshold
meaningless across points, arXiv:2006.13112 — hence a baseline PER
point, not per fleet).
"""

from __future__ import annotations

import math

from tpu_perf.metrics import percentile


class Welford:
    """Running mean and variance without sample retention."""

    def __init__(self) -> None:
        self.n = 0
        self.mean = 0.0
        self._m2 = 0.0

    def push(self, x: float) -> None:
        self.n += 1
        delta = x - self.mean
        self.mean += delta / self.n
        self._m2 += delta * (x - self.mean)

    def variance(self) -> float:
        """Sample variance (n-1 denominator); 0 before two samples."""
        return self._m2 / (self.n - 1) if self.n > 1 else 0.0

    def std(self) -> float:
        return math.sqrt(self.variance())


class EWMA:
    """Exponentially weighted moving average; seeded by the first sample."""

    def __init__(self, alpha: float = 0.3) -> None:
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = alpha
        self.value: float | None = None

    def push(self, x: float) -> None:
        if self.value is None:
            self.value = x
        else:
            self.value = self.alpha * x + (1.0 - self.alpha) * self.value


class P2Quantile:
    """P²-algorithm streaming quantile estimator (Jain & Chlamtac 1985).

    Five markers (min, q/2, q, (1+q)/2, max) track the target quantile
    ``q`` in (0, 1); each sample adjusts marker heights by piecewise-
    parabolic interpolation.  Before five samples the exact small-sample
    percentile is returned.
    """

    def __init__(self, q: float) -> None:
        if not 0.0 < q < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {q}")
        self.q = q
        self.count = 0
        self._init: list[float] = []   # first five samples, then retired
        self._h: list[float] | None = None  # marker heights
        self._n: list[float] = []      # marker positions
        self._np: list[float] = []     # desired positions
        self._dn = (0.0, q / 2.0, q, (1.0 + q) / 2.0, 1.0)

    def push(self, x: float) -> None:
        self.count += 1
        if self._h is None:
            self._init.append(x)
            if len(self._init) == 5:
                self._init.sort()
                self._h = list(self._init)
                self._n = [0.0, 1.0, 2.0, 3.0, 4.0]
                q = self.q
                self._np = [0.0, 2.0 * q, 4.0 * q, 2.0 + 2.0 * q, 4.0]
                self._init = []
            return
        h, n = self._h, self._n
        if x < h[0]:
            h[0] = x
            k = 0
        elif x < h[1]:
            k = 0
        elif x < h[2]:
            k = 1
        elif x < h[3]:
            k = 2
        elif x <= h[4]:
            k = 3
        else:
            h[4] = x
            k = 3
        for i in range(k + 1, 5):
            n[i] += 1.0
        for i in range(5):
            self._np[i] += self._dn[i]
        for i in (1, 2, 3):
            d = self._np[i] - n[i]
            if (d >= 1.0 and n[i + 1] - n[i] > 1.0) or (
                d <= -1.0 and n[i - 1] - n[i] < -1.0
            ):
                d = 1.0 if d >= 0.0 else -1.0
                hp = self._parabolic(i, d)
                if h[i - 1] < hp < h[i + 1]:
                    h[i] = hp
                else:
                    h[i] = self._linear(i, d)
                n[i] += d

    def _parabolic(self, i: int, d: float) -> float:
        h, n = self._h, self._n
        return h[i] + d / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + d) * (h[i + 1] - h[i]) / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - d) * (h[i] - h[i - 1]) / (n[i] - n[i - 1])
        )

    def _linear(self, i: int, d: float) -> float:
        h, n = self._h, self._n
        j = i + int(d)
        return h[i] + d * (h[j] - h[i]) / (n[j] - n[i])

    def value(self) -> float | None:
        """Current quantile estimate; None before the first sample."""
        if self._h is not None:
            return self._h[2]
        if not self._init:
            return None
        return percentile(self._init, self.q * 100.0)


class PointBaseline:
    """The rolling baseline one (op, nbytes, dtype) sweep point owns.

    ``update`` is O(1); ``ready`` gates every judgement on the warm-up
    sample count (an unshaped baseline would alert on its own start-up
    transient).  ``flat_run`` is the length of the current run of
    bit-identical samples (1 after any fresh value, 0 before the first
    sample) — wall-clock timings never repeat exactly, so a long run of
    them means a stuck clock or a wedged measurement path, not a fast one.
    """

    def __init__(self, *, warmup: int = 30, ewma_alpha: float = 0.3) -> None:
        if warmup < 1:
            raise ValueError(f"warmup must be >= 1, got {warmup}")
        self.warmup = warmup
        self.welford = Welford()
        self.ewma = EWMA(ewma_alpha)
        self.p50 = P2Quantile(0.5)
        self.p99 = P2Quantile(0.99)
        self.flat_run = 0
        self._last: float | None = None

    def update(self, x: float, *, longrun: bool = True) -> None:
        """Fold one sample.  ``longrun=False`` freezes the long-run
        estimators (Welford, p50, p99) and folds only the EWMA and the
        flatline run — the detector uses it during an active regression,
        where folding degraded samples would drift the median up to the
        degraded level and fire a false recovery."""
        if longrun:
            self.welford.push(x)
            self.p50.push(x)
            self.p99.push(x)
        self.ewma.push(x)
        if self._last is not None and x == self._last:
            self.flat_run += 1
        else:
            self.flat_run = 1
        self._last = x

    @property
    def n(self) -> int:
        return self.welford.n

    @property
    def ready(self) -> bool:
        """True once the warm-up window has shaped the baseline."""
        return self.n >= self.warmup
