"""Online fleet-health subsystem (L2.5): streaming baselines, anomaly
detection, and health-event telemetry for monitor mode.

The layer between measurement (driver) and telemetry (ingest): every
recorded run feeds a per-(op, nbytes, dtype) streaming baseline
(:mod:`stats`), detectors judge each point against its own history
(:mod:`detect`), verdicts become JSONL events riding the rotating-log +
ingest contract (:mod:`events`), and current gauges land in a Prometheus
textfile (:mod:`exporter`).  :class:`HealthMonitor` (:mod:`monitor`) is
the driver-facing facade.
"""

from tpu_perf.health.detect import (  # noqa: F401
    Finding,
    HealthConfig,
    PointDetector,
    capture_loss_finding,
)
from tpu_perf.health.events import (  # noqa: F401
    HealthEvent,
    events_to_json,
    events_to_markdown,
    read_events,
    summarize_events,
)
from tpu_perf.health.exporter import (  # noqa: F401
    PointGauges,
    TextfileExporter,
    render_textfile,
)
from tpu_perf.health.monitor import HealthMonitor  # noqa: F401
from tpu_perf.health.stats import (  # noqa: F401
    EWMA,
    P2Quantile,
    PointBaseline,
    Welford,
)
