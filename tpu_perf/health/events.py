"""Structured health events: one JSON line per detector verdict.

Events ride the exact telemetry contract the measurement rows use — a
``RotatingCsvLog`` with the ``health-`` prefix (schema.HEALTH_PREFIX),
rotated on the same period, picked up and deleted by the same
delete-only-after-success ingest pass (``tpu-perf ingest`` /
ingest.pipeline) as a third file family next to ``tcp-*`` and ``tpu-*``.
The payload is a JSON object instead of CSV because events are sparse
and self-describing — a Kusto/jq consumer should not need a column map
for a stream it sees a handful of lines a day from.

``tpu-perf health <dir>`` replays event logs into the summary table
(:func:`summarize_events` / :func:`events_to_markdown`).
"""

from __future__ import annotations

import dataclasses
import json
import sys
from typing import Iterable

from tpu_perf.sweep import format_size


@dataclasses.dataclass(frozen=True)
class HealthEvent:
    """One judged observation: what degraded, where, by how much.

    ``window`` is the heartbeat-window index ((run_id - 1) //
    stats_every) the event fell in — runs 1..stats_every and the
    boundary heartbeat covering them share window 0 — so events join
    back to the heartbeat lines and drop counters of the same window.
    ``rank`` attributes the event to the process that judged it (each
    rank runs its own detectors and log on a multi-host daemon — the
    degraded HOST is the answer fleet health exists to give).
    ``nbytes == 0`` marks op-level events (capture loss aggregates every
    size of an op; hook failures carry the synthetic ``ingest_hook``
    op).  ``unit`` names what ``observed``/``baseline`` measure: ``s``
    (run wall seconds) for per-sample detectors, ``drop_rate`` for
    capture loss, ``failures`` for ingest-hook failures.

    ``span_id`` names the enclosing run span when the harness tracer
    (tpu_perf.spans, --spans) is on — the exact join into the
    ``spans-*.log`` family.  Serialized ONLY when non-empty, so with
    tracing off the emitted JSON is byte-identical to pre-span events
    (and pre-span logs parse: the field defaults).
    """

    timestamp: str
    job_id: str
    kind: str      # regression | recovered | spike | flatline |
    #                capture_loss | hook_fail | link_degraded
    severity: str  # info | warning | critical
    op: str
    nbytes: int
    dtype: str
    run_id: int
    window: int
    observed: float
    baseline: float
    unit: str = "s"
    rank: int = 0  # defaulted so pre-rank event logs still parse
    span_id: str = ""  # enclosing run span (--spans); "" = untraced

    def to_json(self) -> str:
        data = dataclasses.asdict(self)
        if not data["span_id"]:
            del data["span_id"]  # untraced events keep pre-span bytes
        return json.dumps(data, sort_keys=True)

    # duck-typed row interface so an event log IS a RotatingCsvLog —
    # same rotation, same ingest family mechanics as the CSV schemas
    def to_csv(self) -> str:
        return self.to_json()

    @classmethod
    def from_json(cls, line: str) -> "HealthEvent":
        data = json.loads(line)
        if not isinstance(data, dict):
            raise ValueError(f"health event line is not an object: {line!r}")
        try:
            return cls(**data)
        except TypeError as e:
            raise ValueError(f"bad health event {line!r}: {e}") from None


def read_jsonl(paths: Iterable[str], parse_line, *, err=None) -> list:
    """Parse JSONL rows from files with ``parse_line`` (which raises
    ValueError on a bad line); blank lines are skipped.  A malformed
    FINAL line is an expected live-daemon state (mid-append or a hard
    kill tears the last line) — skipped with a warning so a replay
    still renders every intact row.  A malformed line anywhere else
    raises (a corrupt log must not silently thin out).  Shared by the
    health-event replay and the chaos-ledger reader: one torn-line
    policy for every JSONL family."""
    out: list = []
    for path in paths:
        with open(path) as fh:
            lines = fh.read().splitlines()
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(parse_line(line))
            except ValueError:
                if i != len(lines) - 1:
                    raise
                print(
                    f"tpu-perf: skipping torn final line of {path}",
                    file=err if err is not None else sys.stderr,
                )
    return out


def read_events(paths: Iterable[str], *, err=None) -> list[HealthEvent]:
    """Parse JSONL events from files (see :func:`read_jsonl` for the
    torn-final-line policy)."""
    return read_jsonl(paths, HealthEvent.from_json, err=err)


@dataclasses.dataclass(frozen=True)
class EventSummary:
    """All events of one (rank, op, nbytes, dtype, kind) key, aggregated
    — per rank, so a multi-host soak names WHICH host degraded."""

    rank: int
    op: str
    nbytes: int
    dtype: str
    kind: str
    severity: str  # worst seen
    count: int
    first_run: int
    last_run: int
    last_observed: float
    last_baseline: float
    unit: str


def summarize_events(events: list[HealthEvent]) -> list[EventSummary]:
    """Group events by (rank, op, nbytes, dtype, kind); keep counts, the
    run span, the worst severity, and the latest observed-vs-baseline
    pair."""
    from tpu_perf.health.detect import SEVERITY_RANK

    groups: dict[tuple, list[HealthEvent]] = {}
    for ev in events:
        groups.setdefault(
            (ev.rank, ev.op, ev.nbytes, ev.dtype, ev.kind), []
        ).append(ev)
    out = []
    for (rank, op, nbytes, dtype, kind), grp in sorted(groups.items()):
        grp = sorted(grp, key=lambda e: e.run_id)
        worst = max(grp, key=lambda e: SEVERITY_RANK.get(e.severity, -1))
        out.append(
            EventSummary(
                rank=rank, op=op, nbytes=nbytes, dtype=dtype, kind=kind,
                severity=worst.severity, count=len(grp),
                first_run=grp[0].run_id, last_run=grp[-1].run_id,
                last_observed=grp[-1].observed,
                last_baseline=grp[-1].baseline, unit=grp[-1].unit,
            )
        )
    # worst news first, then curve order
    out.sort(key=lambda s: (-SEVERITY_RANK.get(s.severity, -1), s.op,
                            s.nbytes, s.dtype, s.kind, s.rank))
    return out


def events_to_markdown(summaries: list[EventSummary]) -> str:
    lines = [
        "| severity | kind | rank | op | size | dtype | events | runs "
        "| last observed | baseline | unit |",
        "|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for s in summaries:
        size = format_size(s.nbytes) if s.nbytes else "—"
        lines.append(
            f"| {s.severity} | {s.kind} | {s.rank} | {s.op} | {size} "
            f"| {s.dtype} | {s.count} | {s.first_run}-{s.last_run} "
            f"| {s.last_observed:.6g} | {s.last_baseline:.6g} | {s.unit} |"
        )
    return "\n".join(lines)


def events_to_json(events: list[HealthEvent]) -> str:
    """Raw events as a JSON array (for jq / dashboards)."""
    return json.dumps([dataclasses.asdict(e) for e in events], indent=2)
