"""Payload-correctness selftest: numerics validation of every kernel.

The reference never validates what lands in the rx buffer — it is written
by MPI_Recv and never checked (mpi_perf.c:75-80), so a fabric that corrupts
payloads still reports healthy timings.  This module gives the operator a
first-class validation pass: every measurement kernel is executed on the
real mesh and its output compared element-wise against a NumPy model of the
op composed ``iters`` times (default 1 = exact single-application
semantics; higher values exercise the fori_loop carry).

`tpu-perf selftest` runs it from the CLI; ops whose topology constraints the
current mesh cannot satisfy (odd device count, missing (dcn, ici) axes, ...)
are reported as skipped, not failed.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np


def _mean_all(x: np.ndarray) -> np.ndarray:
    return np.broadcast_to(x.mean(axis=0), x.shape)


def _reduce_scatter(x: np.ndarray) -> np.ndarray:
    # pallas carry convention: device d ends with the mean of chunk d
    # over devices, tiled n times over the whole buffer
    n = x.shape[0]
    chunks = x.reshape(n, n, -1)
    red = chunks.mean(axis=0)  # (chunk_idx, chunk_elems)
    return np.stack([np.tile(red[d], n) for d in range(n)])


def _reduce_scatter_inplace(x: np.ndarray) -> np.ndarray:
    # XLA carry convention (round 5): device d keeps its full buffer with
    # only its OWN chunk replaced by the reduced mean — the body writes
    # exactly the collective's 1/n output shard per iteration, no tile
    # (VERDICT r4 weak #2)
    n = x.shape[0]
    chunks = x.reshape(n, n, -1).copy()
    red = chunks.mean(axis=0)
    for d in range(n):
        chunks[d, d] = red[d]
    return chunks.reshape(n, -1)


def _all_to_all(x: np.ndarray) -> np.ndarray:
    n = x.shape[0]
    chunks = x.reshape(n, n, -1)
    return chunks.transpose(1, 0, 2).reshape(n, -1)


def _pingpong(x: np.ndarray) -> np.ndarray:
    # payload there and back: group 0 (first half) gets its payload back,
    # group 1 ends at zero (XLA ppermute zero-fills non-targets)
    out = np.zeros_like(x)
    out[: x.shape[0] // 2] = x[: x.shape[0] // 2]
    return out


def _pingpong_unidir(x: np.ndarray) -> np.ndarray:
    # group 0 keeps its buffer and receives its own first element back as
    # the 1-element ack; group 1's ack slot is zeroed (no inbound ack)
    out = x.copy()
    out[x.shape[0] // 2:, 0] = 0
    return out


def _exchange(x: np.ndarray) -> np.ndarray:
    half = x.shape[0] // 2
    return np.concatenate([x[half:], x[:half]])


def _ring(x: np.ndarray) -> np.ndarray:
    return np.roll(x, 1, axis=0)


def _halo(x: np.ndarray) -> np.ndarray:
    # each device ends with [left neighbour's right edge, right neighbour's
    # left edge] (tpu_perf.ops.collectives._body_halo)
    n, elems = x.shape
    h = elems // 2
    out = np.empty_like(x)
    for d in range(n):
        out[d] = np.concatenate([x[(d - 1) % n][elems - h:], x[(d + 1) % n][:h]])
    return out


def _broadcast(x: np.ndarray) -> np.ndarray:
    return np.broadcast_to(x[0], x.shape)


def _identity(x: np.ndarray) -> np.ndarray:
    return x


def _hbm_stream(x: np.ndarray) -> np.ndarray:
    return x * 1.0000001 + 1e-7


def _hbm_read(x: np.ndarray) -> np.ndarray:
    # per device: slot 0 <- mean(max(row, row[0])); the rest untouched
    m = np.maximum(x, x[:, :1])
    out = x.copy()
    out[:, 0] = m.mean(axis=1)
    return out


def _hbm_write(x: np.ndarray) -> np.ndarray:
    # per device: the whole row becomes f(row[0])
    return np.broadcast_to(x[:, :1] * 1.0000001 + 1e-7, x.shape).copy()


def _hbm_triad(x: np.ndarray) -> np.ndarray:
    # first half <- a*k1 + b*k2 in place; second half untouched
    h = x.shape[1] // 2
    out = x.copy()
    out[:, :h] = x[:, :h] * 1.0000001 + x[:, h:] * 1e-7
    return out


def _pl_hbm_write_for(dtype) -> Callable[[np.ndarray], np.ndarray]:
    """The kernel tiles the once-seeded first DMA block over the buffer;
    the block size scales with the NATIVE itemsize, which must come from
    the measurement dtype, not from the model array (floats compose in
    float64, whose itemsize would pick the wrong block)."""
    from tpu_perf.ops.pallas_ring import hbm_dma_block_elems

    itemsize = np.dtype(dtype).itemsize

    def model(x: np.ndarray) -> np.ndarray:
        n, elems = x.shape
        block = hbm_dma_block_elems(itemsize, elems)
        nfull, rem = divmod(elems, block)
        full = np.tile(x[:, :block], nfull)
        # the kernel's trailing partial DMA writes the seed block's first
        # rem elements
        return np.concatenate([full, x[:, :rem]], axis=1) if rem else full

    return model


def _mxu_gemm(x: np.ndarray) -> np.ndarray:
    from tpu_perf.ops.collectives import _ortho

    n, elems = x.shape
    m = int(elems ** 0.5)
    y = (x.reshape(n, m, m) @ _ortho(m)).reshape(n, -1)
    return y * 1.0000001 + 1e-7  # the fold-blocking wrap-add in the body


def _overlap_ring(x: np.ndarray) -> np.ndarray:
    from tpu_perf.ops.collectives import _ortho, _overlap_split

    n, elems = x.shape
    r, m = _overlap_split(elems)
    moved = np.roll(x[:, :r], 1, axis=0)
    done = (x[:, r:].reshape(n, m, m) @ _ortho(m)).reshape(n, -1)
    done = done * 1.0000001 + 1e-7  # matches the body's fold-blocking op
    return np.concatenate([moved, done], axis=1)


#: op -> model of ONE application on the (n_devices, per_device) global array
EXPECTATIONS: dict[str, Callable[[np.ndarray], np.ndarray]] = {
    "allreduce": _mean_all,
    "hier_allreduce": _mean_all,
    "barrier": _mean_all,
    "all_gather": _identity,  # gather + take-own-shard carry convention
    "reduce_scatter": _reduce_scatter_inplace,
    "all_to_all": _all_to_all,
    "broadcast": _broadcast,
    "broadcast_psum": _broadcast,
    "pingpong": _pingpong,
    "pingpong_unidir": _pingpong_unidir,
    "exchange": _exchange,
    "ppermute": _exchange,
    "ring": _ring,
    "halo": _halo,
    "hbm_stream": _hbm_stream,
    "hbm_read": _hbm_read,
    "hbm_write": _hbm_write,
    "hbm_triad": _hbm_triad,
    "pl_ring": _ring,
    "pl_exchange": _exchange,
    "pl_all_gather": _identity,
    "pl_reduce_scatter": _reduce_scatter,
    "pl_allreduce": _mean_all,
    # round trip: both groups end with their own payload (group 1 keeps it
    # via the kernel's local copy) — an exact identity, so any wrong-kernel
    # dispatch (e.g. an exchange swapping the pairs) fails loudly
    "pl_pingpong": _identity,
    # gather + take-own-shard carry convention, like pl_all_gather
    "pl_all_gather_bidir": _identity,
    "pl_hbm_copy": _identity,  # a copy is an exact identity
    "pl_hbm_stream": _hbm_stream,  # same wrap-add body as the XLA op
    # read sweep never writes: output aliases the input — exact identity
    "pl_hbm_read": _identity,
    # placeholder for totality; run_selftest resolves the real model via
    # _EXPECTATIONS_BY_DTYPE (the DMA block scales with the native
    # itemsize, which a float64-composed array cannot supply)
    "pl_hbm_write": _pl_hbm_write_for("float32"),
    "pl_barrier": _identity,  # barrier + local 1-element copy
    "pl_all_to_all": _all_to_all,  # chunk transpose, like the XLA op
    "mxu_gemm": _mxu_gemm,
    "overlap_ring": _overlap_ring,
}

_RTOL = {"float32": 1e-5, "bfloat16": 2e-2, "float16": 2e-3}

# per-op loosening for the matmul ops: an m-deep dot accumulates ~m*eps of
# rounding against the float64 model even at full precision (CPU floor),
# and on real TPUs XLA's DEFAULT precision runs float32 matmuls as bf16
# passes (~4e-3 relative per element, measured 1.3e-2 max abs on the chip)
# — the wider TPU floor is gated on the backend so CPU CI keeps the tight
# safety net.  A wrong-kernel/wiring bug produces O(1) errors either way.
_MATMUL_OPS = ("mxu_gemm", "overlap_ring")
_MATMUL_RTOL_CPU = 1e-3
_MATMUL_RTOL_TPU = 3e-2


def _op_rtol_floor(op: str) -> float:
    if op not in _MATMUL_OPS:
        return 0.0
    import jax

    return _MATMUL_RTOL_TPU if jax.default_backend() == "tpu" else _MATMUL_RTOL_CPU

def _hbm_triad_int(x: np.ndarray) -> np.ndarray:
    # wrapping add in the NATIVE dtype (run_selftest composes integer
    # models on the native array, so uint8 wraparound matches exactly)
    h = x.shape[1] // 2
    out = x.copy()
    out[:, :h] = x[:, :h] + x[:, h:]
    return out


#: integer-dtype model overrides (the ops whose body is dtype-dependent)
_EXPECTATIONS_INT = {
    "hbm_stream": lambda x: x + 1,
    "pl_hbm_stream": lambda x: x + 1,
    "hbm_write": lambda x: np.broadcast_to(x[:, :1] + 1, x.shape).copy(),
    "hbm_triad": _hbm_triad_int,
}

#: ops whose numeric model depends on the measurement dtype itself (not
#: just int-vs-float): op -> factory(dtype) -> model.  Checked before the
#: int/float split.
_EXPECTATIONS_BY_DTYPE = {
    "pl_hbm_write": _pl_hbm_write_for,
}


@dataclasses.dataclass(frozen=True)
class SelftestResult:
    op: str
    status: str  # "ok" | "skip" | "fail"
    detail: str = ""


def _skip_reason(op: str, mesh) -> str | None:
    """Topology constraint the mesh fails to satisfy, if any."""
    n = mesh.size
    flat = len(mesh.axis_names) == 1
    if op == "hier_allreduce":
        return None if len(mesh.axis_names) == 2 else "needs a 2-axis (dcn, ici) mesh"
    if op in ("pingpong", "pingpong_unidir", "exchange", "ppermute",
              "pl_exchange", "pl_pingpong"):
        if not flat:
            return "needs a single-axis mesh"
        if n % 2:
            return "needs an even device count"
        return None
    if op in ("ring", "halo", "broadcast", "overlap_ring", "pl_ring",
              "pl_all_gather", "pl_all_gather_bidir", "pl_hbm_copy",
              "pl_hbm_stream", "pl_hbm_read", "pl_hbm_write",
              "pl_all_to_all"):
        return None if flat else "needs a single-axis mesh"
    if op in ("pl_reduce_scatter", "pl_allreduce", "pl_barrier"):
        if not flat:
            return "needs a single-axis mesh"
        if n < 2:
            return "needs at least 2 devices"
        return None
    return None


def run_selftest(
    mesh,
    *,
    ops: list[str] | None = None,
    nbytes: int = 4096,
    dtype: str = "float32",
    iters: int = 1,
    injector=None,
) -> list[SelftestResult]:
    """Validate each op's payload numerics on ``mesh``; never raises per-op —
    failures land in the result list so every op is always checked.

    ``iters > 1`` chains the kernel inside its fori_loop and composes the
    numeric model the same number of times — this exercises the carry
    convention (output fed back as the next iteration's input), which a
    single application cannot catch.

    ``injector`` (tpu_perf.faults.FaultInjector) corrupts the rx payload
    of ops named by ``corrupt`` faults before comparison — the chaos
    harness's proof that this validation catches a payload-corrupting
    fabric (a corrupted op MUST come back FAIL)."""
    import jax

    from tpu_perf.ops import OP_BUILDERS, build_op
    from tpu_perf.ops.pallas_ring import PALLAS_OPS

    known = sorted(list(OP_BUILDERS) + list(PALLAS_OPS))
    todo = ops if ops is not None else known
    unknown = [op for op in todo if op not in known]
    if unknown:
        # a typo must not silently pass the health check as a SKIP
        raise ValueError(f"unknown op(s) {unknown}; known: {known}")
    from tpu_perf.ops.collectives import FLOAT_ONLY_OPS, is_float_dtype

    is_int_dtype = not is_float_dtype(dtype)
    base_rtol = _RTOL.get(dtype, 1e-5)
    results: list[SelftestResult] = []
    for op in todo:
        rtol = max(base_rtol, _op_rtol_floor(op))
        if op not in EXPECTATIONS:
            results.append(SelftestResult(op, "skip", "no numeric model"))
            continue
        reason = _skip_reason(op, mesh)
        if reason:
            results.append(SelftestResult(op, "skip", reason))
            continue
        if is_int_dtype and op in FLOAT_ONLY_OPS:
            results.append(SelftestResult(op, "skip", "float dtypes only"))
            continue
        if op in _EXPECTATIONS_BY_DTYPE:
            model = _EXPECTATIONS_BY_DTYPE[op](dtype)
        else:
            model = (_EXPECTATIONS_INT.get(op, EXPECTATIONS[op])
                     if is_int_dtype else EXPECTATIONS[op])
        try:
            built = build_op(op, mesh, nbytes, iters=iters, dtype=dtype)
            x_native = np.asarray(jax.device_get(built.example_input))
            out = np.asarray(
                jax.device_get(built.step(built.example_input)), dtype=np.float64
            )
            if injector is not None:
                # the rx-buffer corruption point: what a payload-flipping
                # fabric would hand back (chaos `corrupt` faults)
                out = injector.corrupt_payload(op, out)
            n = built.n_devices
            # integer dtypes compose the model in the NATIVE dtype so
            # device-side wraparound (uint8 255+1 = 0) matches exactly;
            # floats compose in float64
            want = (x_native if is_int_dtype
                    else x_native.astype(np.float64)).reshape(n, -1)
            for _ in range(iters):  # model composed once per chained iter
                want = model(want)
            want = want.astype(np.float64)
            got = out.reshape(n, -1)
            if got.shape != want.shape:
                results.append(
                    SelftestResult(op, "fail", f"shape {got.shape} != {want.shape}")
                )
                continue
            # the bad-element mask uses the exact allclose criterion
            # (|got-want| <= rtol*|want| + atol, atol=rtol) so the count
            # always agrees with the pass/fail verdict; NaN/inf count as bad
            err = np.abs(got - want)
            bad_mask = ~np.isfinite(got) | (err > rtol * np.abs(want) + rtol)
            worst = float(np.nanmax(err)) if np.isfinite(err).any() else float("nan")
            if not bad_mask.any():
                results.append(SelftestResult(op, "ok", f"max abs err {worst:.2e}"))
            else:
                results.append(
                    SelftestResult(
                        op, "fail",
                        f"{int(bad_mask.sum())}/{got.size} elements off "
                        f"(max abs err {worst:.2e})",
                    )
                )
        except Exception as e:  # noqa: BLE001 — one op's failure must not
            # mask the others; the point is a complete health report
            results.append(SelftestResult(op, "fail", f"{type(e).__name__}: {e}"))
    return results


def format_results(results: list[SelftestResult]) -> str:
    width = max((len(r.op) for r in results), default=4)
    lines = []
    for r in results:
        tag = {"ok": "OK  ", "skip": "SKIP", "fail": "FAIL"}[r.status]
        lines.append(f"{r.op:<{width}}  {tag}  {r.detail}")
    n_ok = sum(r.status == "ok" for r in results)
    n_skip = sum(r.status == "skip" for r in results)
    n_fail = sum(r.status == "fail" for r in results)
    lines.append(f"{n_ok} ok, {n_skip} skipped, {n_fail} failed")
    return "\n".join(lines)
