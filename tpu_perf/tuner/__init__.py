"""Crossover auto-tuner (docs/design.md "Crossover auto-tuner"): the
measure→select loop closed — `tpu-perf tune` folds arena verdicts into
a versioned selection artifact, `--algo auto` resolves every sweep
point against it at plan time, `tune --check` gates CI on crossover
drift, and the fleet plane merges per-host winner tables into one
artifact.  Deterministic zone: everything here is a pure function of
artifact bytes + injected coordinates (no clock, no rank)."""

from tpu_perf.tuner.artifact import (
    TUNER_SCHEMA_VERSION,
    DriftFinding,
    LoadedSelection,
    SelectionArtifact,
    SelectionEntry,
    TuneRecord,
    build_selection,
    check_drift,
    current_device_kind,
    load_artifact,
    read_artifact,
    write_artifact,
)

__all__ = [
    "TUNER_SCHEMA_VERSION",
    "DriftFinding",
    "LoadedSelection",
    "SelectionArtifact",
    "SelectionEntry",
    "TuneRecord",
    "build_selection",
    "check_drift",
    "current_device_kind",
    "load_artifact",
    "read_artifact",
    "write_artifact",
]
