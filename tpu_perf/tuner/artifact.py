"""Crossover auto-tuner: the durable selection artifact and its lookup.

The arena (report.compare_arena) measures which decomposition wins per
(op, nbytes, dtype, skew, imbalance, load); nothing consumed those
verdicts until now — every sweep still ran whatever the operator
hand-picked.  This module closes the measure→select loop the way pMR
does for transports (arXiv 1701.08521): ``tpu-perf tune`` folds arena
rows into a versioned **selection artifact** — a winner table keyed on
the full crossover coordinate, with p50s, margins, sample counts, and a
fingerprint of the mesh/chip it was measured on — and ``--algo auto``
resolves every sweep point against it at PLAN time.

Lockstep by construction: the artifact is loaded once, staleness and
mesh-foreignness are judged ONCE at load (with an injected ``now`` —
this module is a deterministic zone and never reads a clock), and
:meth:`LoadedSelection.resolve` is a pure function of (artifact, point,
threshold).  Two ranks holding the same artifact bytes produce the same
plan; nothing here may branch on rank-local or timing state.

The fallback ladder (every rung LOUD, never silent — the inert-knob
precedent):

1. stale artifact (age > --tune-max-age, judged at load) → native, all points
2. foreign fingerprint (device kind / device count mismatch) → native, all points
3. no measured entry for the point's (op, dtype, skew, imbalance, load)
   group → native for that point
4. nearest size bucket by log-distance (ties to the smaller bucket) —
   the interpolation rule, applied within the matched group
5. low-margin entry (best-vs-runner-up ratio < --tune-margin, or a
   one-sided slot that never raced a runner-up) → native for that point
6. a winner the current mesh cannot build (validated by the caller,
   runner.algos_for_options) → native for that point
"""

from __future__ import annotations

import dataclasses
import json
import math
import os

from tpu_perf.schema import JsonlRecord

#: artifact schema version: bumped whenever the entry/fingerprint shape
#: changes; a loader refuses a version it does not speak (a versioned
#: artifact silently misread would select algorithms off garbage)
TUNER_SCHEMA_VERSION = 1

#: the sorted entry-field order the JSON artifact serializes (pinned so
#: two tunes over the same rows are byte-identical)
_ENTRY_FIELDS = (
    "op", "nbytes", "dtype", "skew_us", "imbalance", "load",
    "winner", "winner_p50_us", "runner_up", "runner_up_p50_us",
    "margin", "native_p50_us", "native_vs_best", "n_devices", "mesh",
    "samples", "algos",
)


class TuneRecord(JsonlRecord):
    """One JSONL record of the eighth rotating family (``tune-*.log``):
    the selection artifact flattened for the ingest pass — a
    ``tune_fingerprint`` record per artifact plus one ``tune_entry``
    per winner-table row, sharing the stream via the ``record``
    discriminator like every other JSONL family."""

    __slots__ = ()
    FAMILY = "tune"


@dataclasses.dataclass(frozen=True)
class SelectionEntry:
    """One winner-table row: the measured verdict at one crossover
    coordinate.  ``margin`` is the best-vs-runner-up p50 ratio (>= 1;
    0.0 marks a one-sided slot that never raced a runner-up — treated
    as low-confidence by every consumer).  ``samples`` is the winner
    curve's recorded run count; ``algos`` every decomposition raced."""

    op: str
    nbytes: int
    dtype: str
    skew_us: int
    imbalance: int
    load: str
    winner: str
    winner_p50_us: float
    runner_up: str
    runner_up_p50_us: float
    margin: float
    native_p50_us: float
    native_vs_best: float
    n_devices: int
    mesh: str
    samples: int
    algos: tuple[str, ...]

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["algos"] = list(self.algos)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "SelectionEntry":
        kw = {k: d[k] for k in _ENTRY_FIELDS}
        kw["algos"] = tuple(kw["algos"])
        return cls(**kw)


@dataclasses.dataclass(frozen=True)
class SelectionArtifact:
    """The versioned selection artifact: every winner-table entry plus
    the fingerprint of the mesh/chip the verdicts were measured on.
    ``generated``/``generated_unix`` are INJECTED by the caller (this
    module never reads a clock); ``source`` records where the rows came
    from, for the human reading the JSON."""

    version: int
    generated: str
    generated_unix: float
    fingerprint: dict
    entries: tuple[SelectionEntry, ...]
    source: str = ""

    def to_json(self) -> str:
        payload = {
            "version": self.version,
            "generated": self.generated,
            "generated_unix": self.generated_unix,
            "fingerprint": dict(self.fingerprint),
            "source": self.source,
            "entries": [e.to_dict() for e in self.entries],
        }
        return json.dumps(payload, sort_keys=True, indent=2) + "\n"

    @classmethod
    def from_json(cls, text: str) -> "SelectionArtifact":
        data = json.loads(text)
        if not isinstance(data, dict) or "version" not in data:
            raise ValueError("not a tuner selection artifact (no version)")
        version = data["version"]
        if version != TUNER_SCHEMA_VERSION:
            raise ValueError(
                f"selection artifact version {version!r} is not the "
                f"supported {TUNER_SCHEMA_VERSION} — re-run `tpu-perf "
                f"tune` against this tree's rows"
            )
        return cls(
            version=version,
            generated=data.get("generated", ""),
            generated_unix=float(data.get("generated_unix", 0.0)),
            fingerprint=dict(data.get("fingerprint", {})),
            entries=tuple(SelectionEntry.from_dict(e)
                          for e in data.get("entries", ())),
            source=data.get("source", ""),
        )

    def to_records(self, job_id: str) -> list[TuneRecord]:
        """The artifact flattened into the eighth rotating family's
        records: one fingerprint record, then one per entry."""
        recs = [TuneRecord(
            record="tune_fingerprint", job_id=job_id,
            version=self.version, generated=self.generated,
            generated_unix=self.generated_unix, source=self.source,
            **{f"fp_{k}": v for k, v in sorted(self.fingerprint.items())},
        )]
        for e in self.entries:
            recs.append(TuneRecord(record="tune_entry", job_id=job_id,
                                   **e.to_dict()))
        return recs


def _margin_of(lats: list[float]) -> float:
    """Best-vs-runner-up p50 ratio; 0.0 for a one-sided slot (no
    runner-up ever raced — an unverified winner must read as
    low-confidence, not infinitely confident)."""
    if len(lats) < 2 or not lats[0]:
        return 0.0
    ordered = sorted(lats)
    return round(ordered[1] / ordered[0], 6)


def build_selection(points, *, generated: str, generated_unix: float,
                    device_kind: str = "", source: str = "",
                    ) -> SelectionArtifact:
    """Fold aggregated curve points into the selection artifact via the
    arena's own pivot (report.compare_arena — ONE verdict definition, so
    tune and the report table can never disagree on a winner).  Keys
    with no arena row are dropped exactly as the crossover table drops
    them: a native-only sweep carries no verdict worth persisting."""
    from tpu_perf.chips import resolve_kind
    from tpu_perf.report import compare_arena

    entries: list[SelectionEntry] = []
    n_devices_seen = 0
    for c in compare_arena(points):
        algo, best = c.best
        lats = [p.lat_us["p50"] for p in c.entries.values()]
        ordered = sorted(c.entries.items(),
                         key=lambda kv: kv[1].lat_us["p50"])
        runner_up, runner_lat = ("", 0.0)
        if len(ordered) >= 2:
            runner_up = ordered[1][0]
            runner_lat = ordered[1][1].lat_us["p50"]
        native = c.entries.get("native")
        entries.append(SelectionEntry(
            op=c.op, nbytes=c.nbytes, dtype=c.dtype, skew_us=c.skew_us,
            imbalance=c.imbalance, load=c.load, winner=algo,
            winner_p50_us=round(best.lat_us["p50"], 3),
            runner_up=runner_up,
            runner_up_p50_us=round(runner_lat, 3),
            margin=_margin_of(lats),
            native_p50_us=round(native.lat_us["p50"], 3) if native else 0.0,
            native_vs_best=round(c.native_vs_best, 6)
            if c.native_vs_best else 0.0,
            n_devices=best.n_devices,
            mesh=c.mesh,
            samples=best.runs,
            algos=tuple(sorted(c.entries)),
        ))
        n_devices_seen = max(n_devices_seen, best.n_devices)
    fingerprint = {
        "tuner_schema": TUNER_SCHEMA_VERSION,
        "device_kind": device_kind,
        "chip": resolve_kind(device_kind) or "" if device_kind else "",
        "n_devices": n_devices_seen,
    }
    return SelectionArtifact(
        version=TUNER_SCHEMA_VERSION, generated=generated,
        generated_unix=generated_unix, fingerprint=fingerprint,
        entries=tuple(entries), source=source,
    )


def write_artifact(artifact: SelectionArtifact, path: str) -> None:
    """Atomic publish (tmp + rename on the same filesystem): a reader —
    or a crashed tune — never sees a torn artifact, only the old bytes
    or the new (the fleet/timeline artifact discipline)."""
    tmp = f"{path}.tmp"
    with open(tmp, "w") as fh:
        fh.write(artifact.to_json())
    os.replace(tmp, path)


def read_artifact(path: str) -> SelectionArtifact:
    with open(path) as fh:
        return SelectionArtifact.from_json(fh.read())


def current_device_kind() -> str:
    """The local accelerator's device-kind string for fingerprinting
    ("" when no backend is importable — a tune on a login host still
    produces an artifact; the load-side check only rejects when BOTH
    sides know their kind and disagree)."""
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:
        return ""


class LoadedSelection:
    """A selection artifact judged for use on THIS job: staleness and
    fingerprint foreignness are decided once at construction (with the
    caller's injected ``now``), so :meth:`resolve` stays a pure
    point→algorithm function — the property the two-rank lockstep test
    pins.  ``notes`` dedups the loud fallback messages (one per cause,
    not one per sweep point)."""

    def __init__(self, artifact: SelectionArtifact, *, n_devices: int = 0,
                 device_kind: str = "", max_age_sec: float = 0.0,
                 now: float | None = None, err=None):
        self.artifact = artifact
        self.stale = False
        self.foreign = False
        self._noted: set = set()
        fp = artifact.fingerprint
        if max_age_sec > 0 and now is not None and artifact.generated_unix:
            age = now - artifact.generated_unix
            if age > max_age_sec:
                self.stale = True
                self._say(err, f"selection artifact is stale (age "
                               f"{age:.0f}s > --tune-max-age "
                               f"{max_age_sec:.0f}s): --algo auto runs "
                               f"the native lowering for EVERY point — "
                               f"re-run `tpu-perf tune` on fresh rows")
        fp_kind = str(fp.get("device_kind", "") or "")
        if fp_kind and device_kind and fp_kind != device_kind:
            self.foreign = True
            self._say(err, f"selection artifact was measured on "
                           f"{fp_kind!r} and this job runs on "
                           f"{device_kind!r}: foreign fingerprint — "
                           f"--algo auto runs the native lowering for "
                           f"EVERY point")
        fp_n = int(fp.get("n_devices", 0) or 0)
        if fp_n and n_devices and fp_n != n_devices:
            self.foreign = True
            self._say(err, f"selection artifact was measured on "
                           f"{fp_n} devices and this job's collective "
                           f"axis holds {n_devices}: foreign mesh — "
                           f"--algo auto runs the native lowering for "
                           f"EVERY point")

    @staticmethod
    def _say(err, msg: str) -> None:
        if err is not None:
            print(f"[tpu-perf] tuner: {msg}", file=err)

    def note_once(self, key, msg: str, err=None) -> None:
        """Loud exactly once per cause: a per-point fallback note
        repeated for every size in a sweep would bury the signal."""
        if key in self._noted:
            return
        self._noted.add(key)
        self._say(err, msg)

    def resolve(self, op: str, nbytes: int, dtype: str, *,
                skew_us: int = 0, imbalance: int = 1, load: str = "",
                n_devices: int = 0, margin_min: float = 1.0,
                err=None) -> str:
        """The plan-time lookup: the artifact's winner at the nearest
        measured size bucket of this point's coordinate group, or
        ``native`` down the loud fallback ladder.  Pure in (self,
        args): no rank, no clock, no I/O — R2-lockstep by
        construction."""
        if self.stale or self.foreign:
            return "native"
        group = [e for e in self.artifact.entries
                 if e.op == op and e.dtype == dtype
                 and e.skew_us == skew_us and e.imbalance == imbalance
                 and e.load == load
                 and (not n_devices or e.n_devices == n_devices)]
        if not group:
            self.note_once(
                ("no-entry", op, dtype, skew_us, imbalance, load),
                f"no measured entry for {op}/{dtype} (skew={skew_us}us, "
                f"imbalance={imbalance}, load={load or 'idle'}): --algo "
                f"auto falls back to the native lowering there", err)
            return "native"
        # nearest measured size bucket by log-distance — latency curves
        # live on a log-size axis, so 64K is "between" 16K and 256K,
        # not 4x closer to 16K; ties break to the smaller bucket so the
        # interpolation is deterministic
        ref = math.log(max(1, nbytes))
        entry = min(group, key=lambda e: (abs(math.log(max(1, e.nbytes))
                                              - ref), e.nbytes))
        if entry.margin < margin_min:
            self.note_once(
                ("low-margin", op, dtype, entry.nbytes, skew_us,
                 imbalance, load),
                f"{op}@{entry.nbytes}B winner {entry.winner!r} holds a "
                f"{entry.margin:.3f}x margin < --tune-margin "
                f"{margin_min:.3f}: low confidence — --algo auto falls "
                f"back to the native lowering there", err)
            return "native"
        return entry.winner


def load_artifact(path: str, *, n_devices: int = 0, device_kind: str = "",
                  max_age_sec: float = 0.0, now: float | None = None,
                  err=None) -> LoadedSelection:
    """Read + judge an artifact for this job (the ONE loader --algo auto
    uses).  A missing or unversioned file is a hard error — auto with
    no table is a misconfiguration, not a fallback."""
    try:
        artifact = read_artifact(path)
    except FileNotFoundError:
        raise ValueError(
            f"--algo auto: selection artifact {path!r} does not exist "
            f"(produce one with `tpu-perf tune -d LOGDIR -o {path}`)"
        ) from None
    except json.JSONDecodeError:
        raise ValueError(
            f"--algo auto: {path!r} is not a JSON selection artifact"
        ) from None
    return LoadedSelection(artifact, n_devices=n_devices,
                           device_kind=device_kind,
                           max_age_sec=max_age_sec, now=now, err=err)


@dataclasses.dataclass(frozen=True)
class DriftFinding:
    """One crossover that moved against the published artifact: the
    fresh rows crown a different winner with a convincing margin."""

    op: str
    nbytes: int
    dtype: str
    skew_us: int
    imbalance: int
    load: str
    published: str
    fresh_winner: str
    fresh_margin: float

    def describe(self) -> str:
        coord = f"{self.op}@{self.nbytes}B/{self.dtype}"
        if self.skew_us:
            coord += f" skew={self.skew_us}us"
        if self.imbalance > 1:
            coord += f" imbalance={self.imbalance}"
        if self.load:
            coord += f" load={self.load}"
        return (f"{coord}: published winner {self.published!r} lost to "
                f"{self.fresh_winner!r} (fresh margin "
                f"{self.fresh_margin:.3f}x)")


def check_drift(published: SelectionArtifact, fresh: SelectionArtifact,
                *, margin_min: float = 1.0) -> list[DriftFinding]:
    """The drift gate: re-grade fresh verdicts against the published
    table.  A flip counts only when the fresh winner's own margin
    clears ``margin_min`` — a noise-level reshuffle between near-tied
    algorithms must not fail CI, a real crossover move must."""
    pub = {(e.op, e.nbytes, e.dtype, e.skew_us, e.imbalance, e.load): e
           for e in published.entries}
    findings = []
    for e in fresh.entries:
        key = (e.op, e.nbytes, e.dtype, e.skew_us, e.imbalance, e.load)
        old = pub.get(key)
        if old is None or old.winner == e.winner:
            continue
        if e.margin < margin_min:
            continue
        findings.append(DriftFinding(
            op=e.op, nbytes=e.nbytes, dtype=e.dtype, skew_us=e.skew_us,
            imbalance=e.imbalance, load=e.load, published=old.winner,
            fresh_winner=e.winner, fresh_margin=e.margin,
        ))
    return findings
