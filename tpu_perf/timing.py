"""Timing harness — honest wall-clock measurement under XLA async dispatch.

The reference times one *run* (= ``iters`` messages) between two
``MPI_Wtime`` calls with an ``MPI_Barrier`` in front (mpi_perf.c:499-533);
run 0 is discarded as warm-up (mpi_perf.c:545); min/max/avg come from three
``MPI_Allreduce`` calls (mpi_perf.c:560-562).

Here the same discipline under XLA's async dispatch model (SURVEY.md §7
"hard parts" (a)):

* the kernel's ``iters`` executions are chained inside the jitted step, so
  the device — not Python — owns the loop;
* the first call compiles *and* serves as the warm-up run;
* every timed call is fenced with ``jax.block_until_ready``;
* dispatch overhead can be measured with a null (identity) step and
  subtracted;
* aggregation across processes uses ``psum``-style collectives when running
  multi-host, else plain host math (single-controller JAX times all devices
  with one clock, which already *is* the barrier'd global view).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import jax
import numpy as np

from tpu_perf.metrics import summarize

#: how a timed call is fenced:
#:   block    — jax.block_until_ready (correct on standard runtimes)
#:   readback — device_get of one element of the result: forces full
#:              execution on runtimes whose block_until_ready resolves at
#:              dispatch-acknowledge (e.g. tunneled/relayed PJRT plugins),
#:              at the cost of including the host<->device round trip
#:   slope    — two readback-fenced runs at different iteration counts;
#:              (t_hi - t_lo)/(iters_hi - iters_lo) cancels every constant
#:              overhead including that round trip (see time_slope)
#:   trace    — the device's own clock: a jax.profiler capture around the
#:              runs, per-execution durations read from the XLA Modules
#:              device lane (see time_trace).  No host-side overhead is
#:              in the sample at all, so µs-scale kernels are resolvable
#:              even on relayed runtimes — the fence that unlocks the
#:              small-message half of the latency sweep
#:   fused    — the device-fused measurement loop: the whole sweep point
#:              (all measured runs) is ONE dispatch — an outer
#:              lax.fori_loop carries the (donated) example buffer
#:              through `reps` chained step executions, so no Python
#:              round trip is charged to any sample.  Per-run timings
#:              come back via a two-path extractor: the XLA trace's
#:              device-lane module durations when the runtime records
#:              them (traceparse.fused_run_durations), else a trace-free
#:              fallback that chunks the loop into K sub-dispatches and
#:              assigns chunk-mean times (see FusedRunner).  The fence
#:              that makes µs-scale message sizes honest: at 8 B the
#:              host dispatch IS the floor of every per-run fence.
#:   auto     — trace if the runtime records device lanes, else slope
#:              (one probe capture decides, see trace_fence_available);
#:              the resolved fence is what actually runs — bench's
#:              trace→slope fallback, available to every operator
#:              surface.  auto deliberately keeps resolving to a
#:              PER-RUN fence (trace/slope): fused changes the dispatch
#:              structure (batched captures, chunked stop votes), so it
#:              is opt-in, never a silent auto-resolution — the fused
#:              fence runs its own internal trace-vs-chunk probe off
#:              the same trace_fence_available memo.
FENCE_MODES = ("block", "readback", "slope", "trace", "fused", "auto")

#: slope mode compiles the kernel at `iters` and `iters * SLOPE_ITERS_FACTOR`;
#: both the runner and the driver build their hi/lo pair from this one knob.
SLOPE_ITERS_FACTOR = 4


#: trace_fence_available's memo: None = not probed yet.  Deliberately a
#: named, inspectable module attribute (tests reset it) rather than a
#: hidden mutation of behavior tables — the probed fact is a property of
#: the RUNTIME (a CPU backend never grows device lanes mid-process), so
#: one probe per process is correct, not an ordering hazard (ADVICE r4
#: retired bench's _FENCE_PREFERENCE list mutation in favor of this).
_TRACE_PROBED: bool | None = None


def trace_fence_available() -> bool:
    """Whether the runtime records device-lane module events — decided by
    ONE tiny probe capture (a trivial jitted kernel under
    ``jax.profiler``), cached for the process lifetime.

    The probe is what makes ``--fence auto`` lockstep-safe multi-host:
    every process runs the same local capture against the same runtime
    kind and deterministically resolves to the same fence, so no process
    can fall back alone mid-run.
    """
    global _TRACE_PROBED
    if _TRACE_PROBED is not None:
        return _TRACE_PROBED
    import shutil
    import tempfile

    import jax.numpy as jnp

    from tpu_perf.traceparse import (
        TraceCaptureMissingError, TraceParseError, TraceUnavailableError,
        device_module_durations,
    )

    probe = jax.jit(lambda y: y * jnp.asarray(2.0, y.dtype))
    x = jnp.zeros(8, jnp.float32)
    fence(probe(x), "readback")  # compile outside the capture
    tmp = tempfile.mkdtemp(prefix="tpu_perf_probe_")
    try:
        jax.profiler.start_trace(tmp)
        try:
            fence(probe(x), "readback")
        finally:
            jax.profiler.stop_trace()
        try:
            device_module_durations(tmp, None)
        except TraceUnavailableError:
            _TRACE_PROBED = False
            return False
        except TraceCaptureMissingError:
            # the probe produced NO trace files at all: a runtime that
            # writes no capture can never serve the trace fence.  This
            # used to fall into the blanket TraceParseError pass below
            # and latch trace-AVAILABLE, handing every sweep point a
            # doomed capture before its slope fallback.
            _TRACE_PROBED = False
            return False
        except TraceParseError:
            # device lanes exist but the probe's module wasn't matched —
            # the lane support (what auto selects on) is there
            pass
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    _TRACE_PROBED = True
    return True


def resolve_fence(fence_mode: str) -> str:
    """Resolve ``auto`` to the concrete fence this runtime supports
    (trace on device-lane runtimes, slope elsewhere); other modes pass
    through.  Callers resolve ONCE up front so the rest of the pipeline
    only ever sees concrete fences."""
    if fence_mode != "auto":
        return fence_mode
    return "trace" if trace_fence_available() else "slope"


class DegenerateSlopeError(RuntimeError):
    """Every slope sample of a run came out non-positive (t_hi <= t_lo):
    the kernel is lost in timing noise.  A distinct type so callers can
    retry noise without swallowing real device failures (XlaRuntimeError
    also subclasses RuntimeError)."""


def fence(out, mode: str = "block"):
    """Force completion of ``out`` according to ``mode`` (block/readback)."""
    if mode == "block":
        jax.block_until_ready(out)
    elif mode == "readback":
        # Pull ONE element of one device's shard to host: per-device streams
        # execute in order, so the element being available implies the whole
        # kernel finished on that device — a constant-size D2H round trip
        # regardless of payload size.
        leaf = jax.tree_util.tree_leaves(out)[0]
        shard = leaf.addressable_shards[0].data
        np.asarray(shard[(0,) * shard.ndim])
    else:
        raise ValueError(f"fence() takes block|readback, got {mode!r}")


def slope_sample(
    step_lo: Callable,
    step_hi: Callable,
    x_lo,
    x_hi,
    d_iters: int,
    *,
    perf_clock: Callable[[], float] = time.perf_counter,
    retries: int = 3,
) -> float | None:
    """One two-point slope measurement: marginal seconds per execution.

    A noise spike during the low run can make ``t_hi < t_lo``; such
    degenerate pairs are retried up to ``retries`` times and ``None`` is
    returned if the slope never comes out positive — callers drop the
    sample rather than record a fabricated near-zero time.
    """
    for _ in range(retries + 1):
        t0 = perf_clock()
        fence(step_lo(x_lo), "readback")
        t_lo = perf_clock() - t0
        t0 = perf_clock()
        fence(step_hi(x_hi), "readback")
        t_hi = perf_clock() - t0
        if t_hi > t_lo:
            return (t_hi - t_lo) / d_iters
    return None


@dataclasses.dataclass(frozen=True)
class RunTimes:
    """Per-run wall times for one sweep point (seconds)."""

    samples: list[float]  # one entry per *measured* run (warm-ups excluded)
    warmup_s: float  # duration of the compile+warm-up call
    overhead_s: float  # measured null-dispatch overhead, 0.0 if not measured

    def stats(self) -> dict[str, float]:
        return summarize(self.samples)


#: the null-dispatch identity, jitted ONCE at module scope.
#: measure_overhead used to mint a fresh ``jax.jit(lambda y: y)`` wrapper
#: per call — a new trace-cache entry (and, with the persistent compile
#: cache on, a new disk entry) for every sweep point under
#: --measure-dispatch.  One wrapper's internal cache keys on
#: (shape, dtype, sharding), so each distinct input spec compiles exactly
#: once per process and repeat calls are pure cache hits.
_identity_step = jax.jit(lambda y: y)


def measure_overhead(x, *, reps: int = 10, fence_mode: str = "block") -> float:
    """Median wall time of a fenced jitted-identity dispatch on ``x``.

    Bounds the Python+dispatch floor so tiny-message latencies are not
    dominated by host overhead.  Subtraction is the caller's choice; rows
    always record raw times.

    ``fence_mode`` must match the timed window's fence: on relayed
    runtimes (the reason readback exists) a block-fenced identity resolves
    at dispatch-acknowledge and would under-record the floor that readback
    -fenced samples actually pay.
    """
    fence(_identity_step(x), fence_mode)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fence(_identity_step(x), fence_mode)
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def time_step(
    step: Callable,
    x,
    num_runs: int,
    *,
    warmup_runs: int = 1,
    measure_dispatch: bool = False,
    fence_mode: str = "block",
) -> RunTimes:
    """Time ``num_runs`` fenced executions of ``step(x)``.

    ``warmup_runs`` extra executions run first and are discarded — the first
    of them also triggers compilation (the reference's run-0 skip,
    mpi_perf.c:545, folded together with jit warm-up).

    ``runner._adaptive_run_times`` mirrors this warm-up/fence discipline
    for the early-stop path (only the run COUNT differs) — a change here
    must be kept in step there, or adaptive and fixed-budget samples
    stop being comparable.
    """
    if num_runs <= 0:
        raise ValueError(f"num_runs must be positive, got {num_runs}")
    if fence_mode not in ("block", "readback"):
        raise ValueError(f"time_step fences with block|readback, got {fence_mode!r}")
    t0 = time.perf_counter()
    out = None
    for _ in range(max(1, warmup_runs)):
        out = step(x)
        fence(out, fence_mode)
    warmup_s = time.perf_counter() - t0

    overhead_s = (
        measure_overhead(x, fence_mode=fence_mode) if measure_dispatch else 0.0
    )

    samples = []
    for _ in range(num_runs):
        t0 = time.perf_counter()
        out = step(x)
        fence(out, fence_mode)
        samples.append(time.perf_counter() - t0)
    del out
    return RunTimes(samples=samples, warmup_s=warmup_s, overhead_s=overhead_s)


def time_trace(
    step_lo: Callable,
    step_hi: Callable,
    x,
    iters_lo: int,
    iters_hi: int,
    num_runs: int,
    *,
    warmup_runs: int = 1,
    name_hint: str | None = None,
    trace_dir: str | None = None,
) -> RunTimes:
    """Per-iteration time via the two-point slope on the DEVICE clock.

    One ``jax.profiler`` capture wraps ``num_runs`` alternating
    (lo, hi) executions; each sample is
    ``(dur_hi - dur_lo) / (iters_hi - iters_lo)`` where the durations
    are the XLA modules' own device-lane times (tpu_perf.traceparse).
    The slope discipline still applies on the device clock because a
    module's duration includes per-EXECUTION constants — measured on
    v5e: a 256 MiB hbm_stream module carries a ~0.8 ms input-copy
    prologue (exactly one extra read+write of the buffer), which read
    3-4% low when raw module durations were used as whole-run times.
    The difference cancels it, and device-clock precision (~0.02%
    run-to-run, vs the host slope's ~±10% under relay jitter) makes a
    single (lo, hi) pair per run decisive.

    Samples are per single execution, like :func:`time_slope` — callers
    multiply by their iters for whole-run times.  Unlike the other
    fences, ``warmup_runs=0`` is honored exactly (the driver warms both
    kernels at build time; repeating it would add two large fenced
    executions per measured point).  ``trace_dir`` keeps the raw
    capture; by default a temporary directory is parsed and deleted.
    Raises TraceUnavailableError when the runtime records no device
    lanes (CPU) — callers fall back to slope/readback explicitly, never
    silently.
    """
    import shutil
    import tempfile

    import jax as _jax

    from tpu_perf.traceparse import TraceParseError, device_module_durations

    if iters_hi <= iters_lo:
        raise ValueError(f"need iters_hi > iters_lo, got {iters_lo}, {iters_hi}")
    if num_runs <= 0:
        raise ValueError(f"num_runs must be positive, got {num_runs}")
    t0 = time.perf_counter()
    for _ in range(warmup_runs):
        fence(step_lo(x), "readback")
        fence(step_hi(x), "readback")
    warmup_s = time.perf_counter() - t0

    if trace_dir is not None:
        # a unique subdirectory per capture: the profiler names its
        # session dir by wall-clock SECOND, so two fast points captured
        # into one trace_dir within the same second would silently
        # overwrite each other's kept evidence (verified empirically)
        import os as _os

        _os.makedirs(trace_dir, exist_ok=True)
        tmp = tempfile.mkdtemp(prefix="capture_", dir=trace_dir)
    else:
        tmp = tempfile.mkdtemp(prefix="tpu_perf_trace_")
    try:
        _jax.profiler.start_trace(tmp)
        try:
            for _ in range(num_runs):
                fence(step_lo(x), "readback")
                fence(step_hi(x), "readback")
        finally:
            _jax.profiler.stop_trace()
        durs = device_module_durations(tmp, name_hint)
    finally:
        if trace_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)
    if len(durs) != 2 * num_runs:
        # more matches than executions = the hint caught someone else's
        # module; fewer = the device lane dropped launches.  Either way
        # the pairing would mislabel rows — fail loudly.
        raise TraceParseError(
            f"expected {2 * num_runs} module events for hint {name_hint!r}, "
            f"trace has {len(durs)}"
        )
    d_iters = iters_hi - iters_lo
    samples = []
    for i in range(num_runs):
        d_lo, d_hi = durs[2 * i], durs[2 * i + 1]
        if d_hi <= d_lo:
            # on the device clock a longer program cannot be faster; this
            # is a parse/pairing failure, not timing noise
            raise TraceParseError(
                f"device-time slope pair {i} is non-positive "
                f"({d_lo:.6f} -> {d_hi:.6f} s); trace is inconsistent"
            )
        samples.append((d_hi - d_lo) / d_iters)
    return RunTimes(samples=samples, warmup_s=warmup_s, overhead_s=0.0)


def fused_chunk_plan(num_runs: int, chunks: int = 1) -> tuple[int, ...]:
    """Split a point's run budget into per-dispatch chunk sizes.

    ``chunks=1`` is the headline shape — the whole budget in ONE device
    dispatch; larger values are the trace-free per-run recovery path
    (chunk means) and the adaptive engine's vote granularity (one
    lockstep stop vote per chunk).  Sizes differ by at most one so a
    point compiles at most two distinct fused programs."""
    if num_runs <= 0:
        raise ValueError(f"num_runs must be positive, got {num_runs}")
    k = max(1, min(chunks, num_runs))
    base, rem = divmod(num_runs, k)
    return tuple([base + 1] * rem + [base] * (k - rem))


@dataclasses.dataclass(frozen=True)
class FusedPoint:
    """One sweep point's fused-loop build artifact (ops.build_fused_step
    via runner.build_fused_point): the measured chunk plan plus one
    jitted program per distinct chunk size.  Holds no device buffers —
    the runner copies the (possibly canon-shared) example input into a
    private working buffer before any donation happens."""

    op: str
    plan: tuple[int, ...]   # measured runs per chunk dispatch
    programs: dict          # reps -> jitted fused program


#: one fresh device buffer with x's contents: add-zero through jit — the
#: output cannot alias an un-donated input, so the returned buffer is
#: safe to DONATE through the fused loop while the original (possibly
#: canon-shared across sweep points) example input stays intact.  Jitted
#: once at module scope like _identity_step: one cache entry per input
#: spec, not per sweep point.
_fresh_copy = jax.jit(lambda y: y + np.zeros((), y.dtype))


class FusedRunner:
    """Drives one sweep point's fused measurement loop.

    ``warm()`` makes the private working buffer and executes one
    unrecorded dispatch of the first chunk's program (compiles unless
    AOT-precompiled, and warms the fused executable itself — the inner
    kernel's generalized run-0 skip).  ``chunk(reps)`` then issues ONE
    measured dispatch covering ``reps`` whole runs and returns per-run
    times via the two-path extractor:

    * trace path — the dispatch is wrapped in a ``jax.profiler``
      capture and per-run durations parsed from the device lane
      (traceparse.fused_run_durations): device clock, zero host time in
      any sample.  A glitched capture falls back to the host path for
      that chunk (loudly); a runtime without device lanes latches the
      trace path off for the point.
    * host fallback — the chunk's fenced host wall divided evenly over
      its runs (chunk-mean times): the per-run dispatch overhead is
      amortized ``reps``-fold instead of charged to every sample.

    The working buffer round-trips through every dispatch (``x`` in,
    carried result out — donated on runtimes that support donation), so
    a point's entire budget touches exactly one resident buffer.

    ``dispatches`` counts MEASURED dispatches only (the ci.sh 0g
    exactly-one-dispatch-per-point counter); the warm dispatch is
    excluded, exactly as warm-up runs are excluded from samples."""

    def __init__(
        self,
        point: FusedPoint,
        built,                       # the inner BuiltOp (example source)
        *,
        fence_mode: str = "block",
        perf_clock: Callable[[], float] = time.perf_counter,
        use_trace: bool | None = None,
        trace_dir: str | None = None,
        err=None,
    ):
        if fence_mode not in ("block", "readback"):
            raise ValueError(
                f"FusedRunner fences with block|readback, got {fence_mode!r}"
            )
        self.point = point
        self.built = built
        self.fence_mode = fence_mode
        self.perf_clock = perf_clock
        self.trace_dir = trace_dir
        self.err = err
        self.use_trace = (trace_fence_available() if use_trace is None
                          else use_trace)
        self.dispatches = 0
        self.warmup_s = 0.0
        self._x = None
        self._parse_failures = 0

    def _note(self, msg: str) -> None:
        import sys as _sys

        print(msg, file=self.err if self.err is not None else _sys.stderr)

    def _dispatch(self, reps: int):
        y = self.point.programs[reps](self._x)
        fence(y, self.fence_mode)
        self._x = y

    def warm(self) -> None:
        """Private working copy + one unrecorded dispatch of the first
        chunk's program (the fused executable's own warm-up)."""
        x = self.built.example_input
        t0 = self.perf_clock()
        self._x = _fresh_copy(x)
        fence(self._x, self.fence_mode)
        self._dispatch(self.point.plan[0])
        self.warmup_s = self.perf_clock() - t0

    def chunk(self, reps: int) -> tuple[list[float], float, float]:
        """One measured dispatch of ``reps`` whole runs; returns
        ``(per_run_times_s, host_t0_s, host_wall_s)`` — t0/wall on
        ``perf_clock`` so callers can derive span geometry."""
        if self._x is None:
            self.warm()
        if self.use_trace:
            out = self._chunk_traced(reps)
            if out is not None:
                return out
        t0 = self.perf_clock()
        self._dispatch(reps)
        wall = self.perf_clock() - t0
        self.dispatches += 1
        return [wall / reps] * reps, t0, wall

    def _chunk_traced(self, reps: int):
        """The trace-path chunk; None = fall back to the host path for
        this chunk (the dispatch was NOT issued)."""
        import shutil
        import tempfile

        from tpu_perf.traceparse import (
            TraceParseError, TraceUnavailableError, fused_run_durations,
        )

        if self.trace_dir is not None:
            import os as _os

            _os.makedirs(self.trace_dir, exist_ok=True)
            tmp = tempfile.mkdtemp(prefix="capture_", dir=self.trace_dir)
        else:
            tmp = tempfile.mkdtemp(prefix="tpu_perf_fused_")
        try:
            jax.profiler.start_trace(tmp)
            try:
                t0 = self.perf_clock()
                self._dispatch(reps)
                wall = self.perf_clock() - t0
            finally:
                jax.profiler.stop_trace()
            self.dispatches += 1
            try:
                durs = fused_run_durations(
                    tmp, f"tpuperf_fused_{self.point.op}", reps
                )
            except TraceUnavailableError:
                # runtime property, not a transient: stop attempting
                # captures for this point and keep the host chunk means
                self.use_trace = False
                self._note("[tpu-perf] fused trace extraction "
                           "unavailable (no device lanes); using host "
                           "chunk means")
                return [wall / reps] * reps, t0, wall
            except TraceParseError as e:
                # a capture can transiently drop events; the chunk's
                # host wall is still honest — degrade THIS chunk only.
                # But a runtime that STABLY records an unsplittable
                # event shape would otherwise pay a full capture (and a
                # stderr line) per chunk forever — two consecutive
                # failures latch the trace path off for the point.
                self._parse_failures += 1
                latch = self._parse_failures >= 2
                if latch:
                    self.use_trace = False
                self._note(f"[tpu-perf] fused trace parse failed, chunk "
                           f"falls back to host means"
                           f"{' (trace path latched off)' if latch else ''}"
                           f": {e}")
                return [wall / reps] * reps, t0, wall
            self._parse_failures = 0
            return durs, t0, wall
        finally:
            if self.trace_dir is None:
                shutil.rmtree(tmp, ignore_errors=True)


def time_slope(
    step_lo: Callable,
    step_hi: Callable,
    x,
    iters_lo: int,
    iters_hi: int,
    num_runs: int,
    *,
    warmup_runs: int = 1,
) -> RunTimes:
    """Per-iteration time via the two-point slope, readback-fenced.

    ``step_lo``/``step_hi`` are the same kernel compiled for ``iters_lo`` and
    ``iters_hi`` chained executions.  Each sample is
    ``(t_hi - t_lo) / (iters_hi - iters_lo)`` — every constant cost (python
    dispatch, runtime queuing, host<->device round trip on relayed
    backends) appears in both terms and cancels, leaving the marginal cost
    of one kernel execution.  Samples are *per single execution*; callers
    multiply by their iters when they want a whole-run time.

    ``runner._adaptive_run_times`` mirrors this warm-up/fence/slope
    discipline for the early-stop path — keep the two in step.
    """
    if iters_hi <= iters_lo:
        raise ValueError(f"need iters_hi > iters_lo, got {iters_lo}, {iters_hi}")
    if num_runs <= 0:
        raise ValueError(f"num_runs must be positive, got {num_runs}")
    t0 = time.perf_counter()
    for _ in range(max(1, warmup_runs)):
        fence(step_lo(x), "readback")
        fence(step_hi(x), "readback")
    warmup_s = time.perf_counter() - t0

    d_iters = iters_hi - iters_lo
    samples = []
    for _ in range(num_runs):
        s = slope_sample(step_lo, step_hi, x, x, d_iters)
        if s is not None:
            samples.append(s)
    if not samples:
        raise DegenerateSlopeError(
            "slope timing produced no valid samples (t_hi never exceeded "
            "t_lo) — the measured kernel is lost in timing noise; raise "
            "iters or use more runs"
        )
    return RunTimes(samples=samples, warmup_s=warmup_s, overhead_s=0.0)
