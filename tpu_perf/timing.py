"""Timing harness — honest wall-clock measurement under XLA async dispatch.

The reference times one *run* (= ``iters`` messages) between two
``MPI_Wtime`` calls with an ``MPI_Barrier`` in front (mpi_perf.c:499-533);
run 0 is discarded as warm-up (mpi_perf.c:545); min/max/avg come from three
``MPI_Allreduce`` calls (mpi_perf.c:560-562).

Here the same discipline under XLA's async dispatch model (SURVEY.md §7
"hard parts" (a)):

* the kernel's ``iters`` executions are chained inside the jitted step, so
  the device — not Python — owns the loop;
* the first call compiles *and* serves as the warm-up run;
* every timed call is fenced with ``jax.block_until_ready``;
* dispatch overhead can be measured with a null (identity) step and
  subtracted;
* aggregation across processes uses ``psum``-style collectives when running
  multi-host, else plain host math (single-controller JAX times all devices
  with one clock, which already *is* the barrier'd global view).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import jax

from tpu_perf.metrics import summarize


@dataclasses.dataclass(frozen=True)
class RunTimes:
    """Per-run wall times for one sweep point (seconds)."""

    samples: list[float]  # one entry per *measured* run (warm-ups excluded)
    warmup_s: float  # duration of the compile+warm-up call
    overhead_s: float  # measured null-dispatch overhead, 0.0 if not measured

    def stats(self) -> dict[str, float]:
        return summarize(self.samples)


def measure_overhead(x, *, reps: int = 10) -> float:
    """Median wall time of a fenced jitted-identity dispatch on ``x``.

    Bounds the Python+dispatch floor so tiny-message latencies are not
    dominated by host overhead.  Subtraction is the caller's choice; rows
    always record raw times.
    """
    identity = jax.jit(lambda y: y)
    jax.block_until_ready(identity(x))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(identity(x))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def time_step(
    step: Callable,
    x,
    num_runs: int,
    *,
    warmup_runs: int = 1,
    measure_dispatch: bool = False,
) -> RunTimes:
    """Time ``num_runs`` fenced executions of ``step(x)``.

    ``warmup_runs`` extra executions run first and are discarded — the first
    of them also triggers compilation (the reference's run-0 skip,
    mpi_perf.c:545, folded together with jit warm-up).
    """
    if num_runs <= 0:
        raise ValueError(f"num_runs must be positive, got {num_runs}")
    t0 = time.perf_counter()
    out = None
    for _ in range(max(1, warmup_runs)):
        out = step(x)
        jax.block_until_ready(out)
    warmup_s = time.perf_counter() - t0

    overhead_s = measure_overhead(x) if measure_dispatch else 0.0

    samples = []
    for _ in range(num_runs):
        t0 = time.perf_counter()
        out = step(x)
        jax.block_until_ready(out)
        samples.append(time.perf_counter() - t0)
    del out
    return RunTimes(samples=samples, warmup_s=warmup_s, overhead_s=overhead_s)
