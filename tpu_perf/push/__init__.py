"""Live telemetry push plane (``--push``): streaming sinks for every
record family, teed from the rotating-log write boundary.

The pull plane (rotate -> cron ingest -> cron ``fleet report``) leaves
a detection-to-operator latency of one rotation plus one scan; this
plane closes it.  See docs/design.md "Live telemetry push plane" for
the architecture; the public surface:

* :class:`PushPlane` / :data:`NULL_PUSHER` — the bounded tee queue +
  background sender (plane.py), inert-by-default like the span tracer;
* :class:`HttpSink` (NDJSON POST, per-family endpoint routing
  mirroring the Kusto table map) and :class:`TextfileSink` (live
  Prometheus meters) — sinks.py;
* the dead-letter spool riding the ingest quarantine/requeue contract
  — spool.py, replayed by `tpu-perf push replay` or any healthy plane;
* :func:`plane_from_options` — the driver/CLI constructor.
"""

from tpu_perf.push.plane import (  # noqa: F401
    DEFAULT_QUEUE, NULL_PUSHER, NullPusher, PUSH_THREAD_NAME, PushPlane,
)
from tpu_perf.push.sinks import (  # noqa: F401
    HttpSink, METER_KEYS, PUSH_ROUTES, PushError, TEE_FREE_FAMILIES,
    TextfileSink, push_gauge_lines, push_records_once,
    render_push_textfile,
)
from tpu_perf.push.spool import (  # noqa: F401
    live_spool_files, parse_spool_family, read_spool, spool_depth,
    write_spool,
)


def plane_from_options(opts, *, rank: int = 0, tracer=None, err=None):
    """The driver's (and CLI's) one constructor: NULL_PUSHER unless a
    push knob is set; the textfile sink on rank 0 only (per-rank
    writers would fight over one path, the health-exporter precedent);
    the spool next to the rotating logs."""
    if not getattr(opts, "push_url", None) \
            and not getattr(opts, "push_textfile", None):
        return NULL_PUSHER
    sinks = []
    if opts.push_url:
        sinks.append(HttpSink(opts.push_url))
    textfile = None
    if opts.push_textfile and rank == 0:
        textfile = TextfileSink(opts.push_textfile, err=err)
    return PushPlane(
        sinks,
        job_id=opts.uuid,
        rank=rank,
        spool_dir=opts.logfolder,
        maxlen=opts.push_queue or DEFAULT_QUEUE,
        textfile=textfile,
        tracer=tracer,
        err=err,
    )
