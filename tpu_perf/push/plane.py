"""The push plane's core: bounded tee queue + background sender.

Design constraints, in priority order:

1. **The measurement loop never blocks.**  ``tee`` is a non-blocking
   ``put_nowait`` into a bounded queue; when the queue is full the
   record is DROPPED — counted in a gauge and noted on stderr, never
   silent, and never a stall (the reference forks its uploader for the
   same reason, mpi_perf.c:363-364).
2. **Off means provably off.**  With ``--push`` absent the driver holds
   :data:`NULL_PUSHER` — no thread, no clock reads, no allocation, no
   bytes — the NULL_TRACER stance.  The chaos ledger is never teed even
   when the plane is on (sinks.TEE_FREE_FAMILIES), so ledger
   byte-identity holds with the plane in either state.
3. **Delivery is at-least-once, loss is always counted.**  The sender
   batches per family, retries failures with jittered exponential
   backoff, dead-letters exhausted batches to the on-disk spool
   (tpu_perf.push.spool — requeue/replay via the ingest quarantine
   tooling), and closes by flushing-then-spooling so a finished soak
   never holds undelivered records only in memory.
4. **The plane observes itself.**  Cumulative sent/dropped/retried/
   spooled/replayed counters plus queue/spool/backoff gauges surface in
   the JSON heartbeat, the phase sidecar, the health exporter's
   textfile, and the plane's own live textfile sink; each delivery
   attempt is a ``push`` span in the harness trace when ``--spans`` is
   on.
"""

from __future__ import annotations

import os
import queue
import random
import sys
import threading
import time

from tpu_perf.push import spool as _spool
from tpu_perf.push.sinks import TEE_FREE_FAMILIES
from tpu_perf.spans import NULL_TRACER

#: the sender thread's name — its spans land on their own foreign lane
#: (the span tracer assigns t<N> lanes to non-main, non-worker threads)
PUSH_THREAD_NAME = "tpu-perf-push"

#: default tee-queue bound (records).  A heartbeat window's worth of
#: rows plus events plus spans fits comfortably; a sink outage longer
#: than the backoff window spools rather than growing memory.
DEFAULT_QUEUE = 10000


class NullPusher:
    """The push-plane-off stand-in: every operation a no-op, shared by
    every caller (the NULL_TRACER precedent — the hot path never
    branches on plane presence, and never pays a clock read or an
    allocation while the plane is off)."""

    enabled = False

    def tee_for(self, family: str):
        return None

    def tee(self, family: str, line: str) -> None:
        pass

    def totals(self) -> dict | None:
        return None

    def close(self) -> None:
        pass


#: the shared inert plane (stateless, one instance serves every user)
NULL_PUSHER = NullPusher()


class PushPlane:
    """One process's live telemetry push plane.

    ``sinks`` is the delivery list (usually one :class:`HttpSink`; an
    empty list with a ``textfile`` makes the plane a pure live-meter
    surface).  ``spool_dir`` (normally the logfolder) enables the
    dead-letter spool; without it, exhausted batches are dropped —
    counted, with a note.  ``clock``/``jitter`` are injectable so the
    backoff schedule is testable deterministically; ``start=False``
    skips the background thread for tests that drive :meth:`_cycle`
    by hand.
    """

    enabled = True

    def __init__(
        self,
        sinks,
        *,
        job_id: str,
        rank: int = 0,
        spool_dir: str | None = None,
        maxlen: int = DEFAULT_QUEUE,
        textfile=None,            # sinks.TextfileSink or None
        tracer=None,              # SpanTracer; settable after ctor
        err=None,                 # late-bound stderr
        clock=time.monotonic,
        jitter=random.random,
        flush_every: float = 0.25,
        max_attempts: int = 5,
        backoff_base: float = 0.25,
        backoff_max: float = 30.0,
        drop_note_every: int = 1000,
        replay_every: float = 5.0,
        start: bool = True,
    ):
        if maxlen < 1:
            raise ValueError(f"push queue bound must be >= 1, got {maxlen}")
        if max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {max_attempts}")
        self.sinks = list(sinks)
        self.job_id = job_id
        self.rank = rank
        self.spool_dir = spool_dir
        self.textfile = textfile
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.err = err
        self.clock = clock
        self.jitter = jitter
        self.flush_every = flush_every
        self.max_attempts = max_attempts
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.drop_note_every = max(1, drop_note_every)
        self.replay_every = replay_every
        self._q: queue.Queue = queue.Queue(maxsize=maxlen)
        self._maxlen = maxlen
        self._lock = threading.Lock()          # meters + pending sizes
        self._cycle_lock = threading.Lock()    # sender vs close()
        self._meters = {"sent": 0, "dropped": 0, "retried": 0,
                        "spooled": 0, "replayed": 0}
        self._sent_by_family: dict[str, int] = {}
        self._pending: dict[str, list[str]] = {}
        self._attempts = 0       # consecutive failed flush cycles
        self._next_try = 0.0     # clock() before which no send happens
        self._seq = 0            # spool-file sequence, per plane
        self._last_replay: float | None = None
        self._replay_skip: set[str] = set()  # delivered, undeletable
        self._depth_cache: tuple[float, int] | None = None
        self._last_err_note = 0  # retried count at the last stderr note
        self._closed = False
        self._stop = threading.Event()
        self._thread = None
        if start:
            self._thread = threading.Thread(
                target=self._run, name=PUSH_THREAD_NAME, daemon=True)
            self._thread.start()

    # -- the tee surface (measurement thread) ---------------------------

    def tee_for(self, family: str):
        """A bound tee callable for one family's RotatingCsvLog — or
        None for a tee-free family, so a mis-wired caller cannot tee
        the chaos ledger even by asking.  A sink-less plane
        (``--push-textfile`` alone) also tees nothing: it is a pure
        live-meter surface, and consuming records it can never deliver
        would inflate ``sent`` into a claim an operator might trust."""
        if not self.sinks or family in TEE_FREE_FAMILIES:
            return None
        return lambda line: self.tee(family, line)

    def tee(self, family: str, line: str) -> None:
        """Non-blocking enqueue; overflow drops are counted and noted,
        never silent, never a stall."""
        if not self.sinks or family in TEE_FREE_FAMILIES or self._closed:
            return
        try:
            self._q.put_nowait((family, line))
        except queue.Full:
            with self._lock:
                self._meters["dropped"] += 1
                n = self._meters["dropped"]
            if n == 1 or n % self.drop_note_every == 0:
                print(f"[tpu-perf push] tee queue full: {n} record(s) "
                      "dropped so far (counted in "
                      "tpu_perf_push_dropped_total; raise --push-queue "
                      "or revive the sink)", file=self._stream(),
                      flush=True)

    # -- self-observation ----------------------------------------------

    def totals(self) -> dict:
        """The cumulative meter snapshot every surface renders (JSON
        heartbeat, phase sidecar, exporter gauges, report table)."""
        with self._lock:
            m = dict(self._meters)
            pending = sum(len(v) for v in self._pending.values())
        m["queued"] = self._q.qsize() + pending
        m["backoff"] = 1 if self.clock() < self._next_try else 0
        m["spool_depth"] = self._spool_depth()
        return m

    def _spool_depth(self) -> int:
        """The spool-depth gauge, cached: totals() runs every sender
        cycle AND every heartbeat, and a full listdir of a week-long
        soak's log folder 4x a second is exactly the overhead the
        plane's bench pins as noise-floor.  The cache invalidates on
        the plane's own spool/replay transitions (it owns every one),
        so depth changes it CAUSES are exact; a rescan every
        ``replay_every`` picks up foreign ones (an operator's requeue)."""
        cached = self._depth_cache
        now = self.clock()
        if cached is not None and now - cached[0] < self.replay_every:
            return cached[1]
        depth = _spool.spool_depth(self.spool_dir)
        self._depth_cache = (now, depth)
        return depth

    # -- the sender (background thread) --------------------------------

    def _stream(self):
        return self.err if self.err is not None else sys.stderr

    def _run(self) -> None:
        deadline = None  # end of the current batching window
        while True:
            timeout = (self.flush_every if deadline is None
                       else deadline - self.clock())
            try:
                item = self._q.get(timeout=max(0.0, timeout))
            except queue.Empty:
                item = None
            if item is not None:
                self._absorb(item)
                # batch the flush window out: the first record of a
                # window opens a flush_every deadline, the backlog is
                # absorbed in one slice, and later records pile into
                # the same per-family batches — so steady state sends
                # a few POSTs per window, never one per record (and a
                # tee burst never saws the GIL against the measurement
                # thread with per-record flush cycles)
                self._drain_queue()
                if deadline is None:
                    deadline = self.clock() + self.flush_every
                if not self._stop.is_set() and self.clock() < deadline:
                    continue
            self._cycle()
            deadline = None
            if self._stop.is_set() and self._q.empty() \
                    and not self._pending:
                return

    def _absorb(self, item) -> None:
        family, line = item
        with self._lock:
            self._pending.setdefault(family, []).append(line)

    def _drain_queue(self) -> None:
        while True:
            try:
                self._absorb(self._q.get_nowait())
            except queue.Empty:
                return

    def _cycle(self) -> None:
        """One sender cycle: drain the queue into per-family pending
        batches, flush when not backing off, replay spool when healthy,
        refresh the live textfile.  Callable synchronously in tests
        (``start=False``) with an injected clock."""
        with self._cycle_lock:
            self._drain_queue()
            now = self.clock()
            if self._pending:
                if now >= self._next_try:
                    self._flush()
                else:
                    with self._lock:
                        over = sum(len(v) for v in
                                   self._pending.values()) > self._maxlen
                    if over:
                        # an outage longer than the backoff covers must
                        # not grow memory without bound: dead-letter the
                        # backlog now rather than hold it
                        self._spool_pending()
            if self._attempts == 0 and not self._pending:
                # replay whenever the plane is healthy — including right
                # after a successful flush, so a busy daemon (records in
                # every window) still drains a requeued spool instead of
                # starving it until the soak's first idle cycle
                self._maybe_replay(now)
            self._write_textfile()

    def _flush(self) -> None:
        ok_all = True
        for family in sorted(self._pending):
            lines = self._pending[family]
            if self._send(family, lines):
                with self._lock:
                    self._meters["sent"] += len(lines)
                    self._sent_by_family[family] = \
                        self._sent_by_family.get(family, 0) + len(lines)
                    del self._pending[family]
            else:
                ok_all = False
                with self._lock:
                    self._meters["retried"] += 1
        if ok_all:
            self._attempts = 0
            self._next_try = 0.0
            return
        self._attempts += 1
        delay = min(self.backoff_max,
                    self.backoff_base * (2 ** (self._attempts - 1)))
        delay *= 0.5 + self.jitter()  # jitter: a fleet of senders must
        #                               not re-converge on a recovering
        #                               sink in lockstep
        self._next_try = self.clock() + delay
        if self._attempts >= self.max_attempts:
            self._spool_pending()
            self._attempts = 0

    def _send(self, family: str, lines: list[str]) -> bool:
        """Deliver one family batch through every sink; all must accept
        (delivery is at-least-once — a partial success is re-sent, and
        collectors key on the records' identity columns)."""
        t0 = self.tracer.now() if self.tracer.enabled else 0
        err_msg = None
        for sink in self.sinks:
            try:
                sink.send(family, lines)
            except Exception as e:  # noqa: BLE001 — every sink failure
                # is one retryable delivery failure; the sender owns
                # the policy
                err_msg = str(e)
                break
        if self.tracer.enabled:
            attrs = {"family": family, "lines": len(lines)}
            if err_msg:
                attrs["error"] = True
            self.tracer.emit("push", t0, self.tracer.now() - t0, **attrs)
        if err_msg is not None:
            with self._lock:
                retried = self._meters["retried"]
            if retried == self._last_err_note or \
                    retried - self._last_err_note >= 20:
                self._last_err_note = retried
                print(f"[tpu-perf push] delivery failed for {len(lines)} "
                      f"{family} record(s): {err_msg} (retrying with "
                      "backoff; exhausted batches spool to disk)",
                      file=self._stream(), flush=True)
            return False
        return True

    def _spool_pending(self) -> None:
        """Dead-letter every pending batch (or drop, counted, when no
        spool dir exists — a push job without a logfolder has nowhere
        durable to put them)."""
        with self._lock:
            # snapshot under the meters lock: totals() iterates
            # _pending.values() from the measurement thread, and an
            # unlocked pop here would change the dict mid-iteration
            batches = [(f, self._pending.pop(f))
                       for f in sorted(self._pending)]
        for family, lines in batches:
            if not lines:
                continue
            if self.spool_dir is None:
                with self._lock:
                    self._meters["dropped"] += len(lines)
                print(f"[tpu-perf push] no spool dir (push without a "
                      f"logfolder): {len(lines)} {family} record(s) "
                      "dropped after exhausted retries (counted)",
                      file=self._stream(), flush=True)
                continue
            self._seq += 1
            try:
                path = _spool.write_spool(
                    self.spool_dir, family, self.job_id, self.rank,
                    lines, seq=self._seq)
            except OSError as e:
                with self._lock:
                    self._meters["dropped"] += len(lines)
                print(f"[tpu-perf push] spool write failed: {e} — "
                      f"{len(lines)} {family} record(s) dropped "
                      "(counted)", file=self._stream(), flush=True)
                continue
            self._depth_cache = None  # a file landed: re-gauge exactly
            with self._lock:
                self._meters["spooled"] += len(lines)
            print(f"[tpu-perf push] dead-lettered {len(lines)} {family} "
                  f"record(s) to {path} (requeue with `tpu-perf ingest "
                  "--requeue`, replay with `tpu-perf push replay`)",
                  file=self._stream(), flush=True)

    def _maybe_replay(self, now: float) -> None:
        """Replay ONE live spool file per interval while healthy — a
        requeued dead letter flows back out without a dedicated tool,
        and one file per cycle keeps replay from starving live
        records."""
        if self.spool_dir is None or not self.sinks:
            return
        if self._last_replay is not None \
                and now - self._last_replay < self.replay_every:
            return
        self._last_replay = now
        files = [pf for pf in _spool.live_spool_files(self.spool_dir)
                 if pf[0] not in self._replay_skip]
        if not files:
            return
        path, family = files[0]
        try:
            lines = _spool.read_spool(path)
        except OSError:
            return  # raced another replayer; the next scan re-resolves
        if lines and not self._send(family, lines):
            with self._lock:
                self._meters["retried"] += 1
            return
        self._depth_cache = None  # a file leaves (or sticks): re-gauge
        try:
            os.remove(path)  # delete only after successful delivery
        except OSError as e:
            # the batch WAS delivered; a file that cannot be deleted
            # must not be replayed (and re-counted) every interval —
            # skip it for this plane's lifetime and tell the operator
            self._replay_skip.add(path)
            print(f"[tpu-perf push] replayed spool {path} but could "
                  f"not delete it: {e} — remove it manually, or the "
                  "next plane will replay it again (at-least-once)",
                  file=self._stream(), flush=True)
        with self._lock:
            self._meters["replayed"] += len(lines)
            self._meters["sent"] += len(lines)
            self._sent_by_family[family] = \
                self._sent_by_family.get(family, 0) + len(lines)
        print(f"[tpu-perf push] replayed {len(lines)} spooled {family} "
              f"record(s) from {path}", file=self._stream(), flush=True)

    def _write_textfile(self) -> None:
        if self.textfile is not None:
            with self._lock:
                by_family = dict(self._sent_by_family)
            self.textfile.write(by_family, self.totals())

    # -- teardown -------------------------------------------------------

    def close(self, timeout: float = 10.0) -> None:
        """Flush-then-spool teardown: stop the sender, attempt one
        final delivery of everything still queued, and dead-letter the
        remainder — a finished soak never holds undelivered records
        only in memory.  Never raises (the ingest-hook stance)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        with self._cycle_lock:
            self._drain_queue()
            if self._pending:
                self._next_try = 0.0  # the final attempt ignores backoff
                self._flush()
            if self._pending:
                self._spool_pending()
            self._write_textfile()
