"""Push-plane sinks: where teed records go, live.

The pull plane (tpu_perf.ingest) ships *finished files* on rotation; a
detector that fires at t cannot reach an operator until the next
rotation + cron scan.  The push plane tees each record at the
rotating-log **write boundary** (driver.RotatingCsvLog) into a bounded
queue (tpu_perf.push.plane) whose background sender delivers batches
through the sinks here:

* :class:`HttpSink` — NDJSON POST over stdlib urllib, one endpoint per
  record family.  :data:`PUSH_ROUTES` mirrors the Kusto table map the
  ingest pipeline routes finished files by (pipeline.KustoBackend), so
  the live path and the batch path land records in the SAME logical
  tables — a collector behind the endpoint needs no second routing
  convention.
* :class:`TextfileSink` — a live Prometheus textfile of the plane's
  own meters plus per-family delivery counters, refreshed every sender
  cycle instead of once per rotation (the node-exporter textfile
  convention the health exporter already follows).

The chaos ledger (schema.CHAOS_PREFIX) is deliberately absent from the
routing map: its byte-identity contract (same seed + spec => identical
``chaos-*.log``) is the determinism proof every CI gate diffs, and a
tee is an observable the contract must not depend on.
:data:`TEE_FREE_FAMILIES` declares that exclusion where `tpu-perf lint`
R3 can prove it: every family in schema.ALL_PREFIXES must either route
here or be declared tee-free, so an eighth family cannot ship
half-wired — and a tee-free family can never gain a route by accident.
"""

from __future__ import annotations

import sys
import urllib.request

from tpu_perf.health.exporter import labels, write_textfile
from tpu_perf.ingest.pipeline import (
    FLEET_TABLE, HEALTH_TABLE, LINKMAP_TABLE, SPANS_TABLE, TPU_TABLE,
    TUNE_TABLE,
)
from tpu_perf.schema import (
    CHAOS_PREFIX, EXT_PREFIX, FLEET_PREFIX, HEALTH_PREFIX, LEGACY_PREFIX,
    LINKMAP_PREFIX, SPANS_PREFIX, TUNE_PREFIX,
)

#: family prefix -> endpoint table name, mirroring the ingest
#: pipeline's per-family Kusto routing (KustoBackend.ingest) so the
#: live and batch paths share one table convention.  `tpu-perf lint`
#: R3 cross-checks this map against schema.ALL_PREFIXES: a rotating
#: family wired for tee MUST appear here (half-wired families are a
#: parse-time finding, not a runtime surprise).
PUSH_ROUTES = {
    LEGACY_PREFIX: "PerfLogsMPI",  # the reference's default table
    EXT_PREFIX: TPU_TABLE,
    HEALTH_PREFIX: HEALTH_TABLE,
    LINKMAP_PREFIX: LINKMAP_TABLE,
    SPANS_PREFIX: SPANS_TABLE,
    FLEET_PREFIX: FLEET_TABLE,
    TUNE_PREFIX: TUNE_TABLE,
}

#: families that must NEVER tee: the chaos ledger's byte-identity
#: contract is the determinism proof (ci.sh 0b's a/b diff), and the
#: push plane must be provably absent from it.  R3 enforces both
#: directions — everything else routed, nothing here routed.
TEE_FREE_FAMILIES = (CHAOS_PREFIX,)


class PushError(RuntimeError):
    """A sink could not deliver a batch (retried by the sender)."""


class HttpSink:
    """NDJSON HTTP POST per family: ``<base>/v1/<Table>``.

    Stdlib urllib only (the no-new-deps contract); one request per
    batch, ``Content-Type: application/x-ndjson``, the family prefix
    echoed in a header so a generic collector can route without
    parsing the path.  Any non-2xx / connection / timeout failure
    raises — the sender owns retry, backoff, and the dead-letter
    spool; the sink stays a dumb pipe.  Delivery is at-least-once: a
    batch that failed AFTER the server processed it is re-sent, so
    collectors should key on the records' own identity columns
    (job_id, rank, run_id / span_id), which every family carries.
    """

    def __init__(self, base_url: str, *, timeout: float = 5.0,
                 routes: dict[str, str] | None = None):
        if not base_url:
            raise ValueError("HttpSink needs a base URL")
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.routes = dict(PUSH_ROUTES if routes is None else routes)

    def endpoint(self, family: str) -> str:
        table = self.routes.get(family)
        if table is None:
            raise PushError(
                f"no push route for family {family!r} (routes: "
                f"{sorted(self.routes)}) — a tee-free family can never "
                "be sent, and a new family must be added to PUSH_ROUTES"
            )
        return f"{self.base_url}/v1/{table}"

    def send(self, family: str, lines: list[str]) -> None:
        data = ("\n".join(lines) + "\n").encode()
        req = urllib.request.Request(
            self.endpoint(family),
            data=data,
            headers={
                "Content-Type": "application/x-ndjson",
                "X-TpuPerf-Family": family,
            },
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                status = getattr(resp, "status", 200)
                if status >= 300:
                    raise PushError(
                        f"{self.endpoint(family)} answered {status}")
        except PushError:
            raise
        except Exception as e:  # noqa: BLE001 — URLError, HTTPError,
            # socket timeouts, connection resets: all one retryable
            # delivery failure to the sender
            raise PushError(f"{self.endpoint(family)}: {e}") from e

    def describe(self) -> str:
        return self.base_url


#: cumulative meter keys the plane reports (heartbeat / sidecar /
#: exporter all render this one shape — one spelling, every surface)
METER_KEYS = ("sent", "dropped", "retried", "spooled", "replayed")


def push_gauge_lines(totals: dict) -> list[str]:
    """The plane's self-observation as Prometheus lines — shared by the
    live :class:`TextfileSink` and the health exporter's textfile
    (health.exporter.render_textfile), so a dashboard alerting on
    ``tpu_perf_push_dropped_total`` reads one metric name whichever
    file its collector scrapes."""
    lines = [
        "# HELP tpu_perf_push_sent_total Records delivered live through "
        "the push plane since start.",
        "# TYPE tpu_perf_push_sent_total counter",
        f"tpu_perf_push_sent_total {int(totals.get('sent', 0))}",
        "# HELP tpu_perf_push_dropped_total Records dropped at the "
        "bounded tee queue (overflow — counted, never silent).",
        "# TYPE tpu_perf_push_dropped_total counter",
        f"tpu_perf_push_dropped_total {int(totals.get('dropped', 0))}",
        "# HELP tpu_perf_push_retried_total Failed delivery attempts "
        "(each retried with jittered exponential backoff).",
        "# TYPE tpu_perf_push_retried_total counter",
        f"tpu_perf_push_retried_total {int(totals.get('retried', 0))}",
        "# HELP tpu_perf_push_spooled_total Records dead-lettered to "
        "the on-disk spool after exhausted retries.",
        "# TYPE tpu_perf_push_spooled_total counter",
        f"tpu_perf_push_spooled_total {int(totals.get('spooled', 0))}",
        "# HELP tpu_perf_push_replayed_total Spooled records replayed "
        "to a revived sink.",
        "# TYPE tpu_perf_push_replayed_total counter",
        f"tpu_perf_push_replayed_total {int(totals.get('replayed', 0))}",
        "# HELP tpu_perf_push_queued Records currently waiting in the "
        "tee queue + the sender's pending batches.",
        "# TYPE tpu_perf_push_queued gauge",
        f"tpu_perf_push_queued {int(totals.get('queued', 0))}",
        "# HELP tpu_perf_push_spool_depth Dead-letter spool files on "
        "disk (live + quarantined).",
        "# TYPE tpu_perf_push_spool_depth gauge",
        f"tpu_perf_push_spool_depth {int(totals.get('spool_depth', 0))}",
        "# HELP tpu_perf_push_backoff 1 while the sender is backing "
        "off a failing sink, else 0.",
        "# TYPE tpu_perf_push_backoff gauge",
        f"tpu_perf_push_backoff {int(totals.get('backoff', 0))}",
    ]
    return lines


def render_push_textfile(sent_by_family: dict[str, int],
                         totals: dict) -> str:
    """The live textfile's full contents: the shared gauge block plus
    per-family delivery counters (which family a stalled pipeline is
    starving is the first triage question)."""
    lines = push_gauge_lines(totals)
    lines.append("# HELP tpu_perf_push_family_sent_total Records "
                 "delivered per rotating family.")
    lines.append("# TYPE tpu_perf_push_family_sent_total counter")
    for family, n in sorted(sent_by_family.items()):
        lines.append(
            f"tpu_perf_push_family_sent_total{labels(family=family)} {n}"
        )
    return "\n".join(lines) + "\n"


class TextfileSink:
    """Atomic writer for the plane's live Prometheus textfile —
    refreshed every sender cycle, not once per rotation, so the
    exporter surface follows the fleet in near-real time.  Never
    raises into the sender (a full disk must not take the delivery
    path down with it)."""

    def __init__(self, path: str, *, err=None):
        self.path = path
        self.err = err

    def _stream(self):
        return self.err if self.err is not None else sys.stderr

    def write(self, sent_by_family: dict[str, int], totals: dict) -> None:
        try:
            write_textfile(self.path,
                           render_push_textfile(sent_by_family, totals))
        except OSError as e:
            print(f"[tpu-perf push] textfile write failed: {e}",
                  file=self._stream(), flush=True)


def push_records_once(url: str, family: str, lines: list[str], *,
                      err=None, timeout: float = 5.0) -> bool:
    """One-shot synchronous push for the CLI record writers (linkmap
    sweeps, fleet reports): the records are already durable on disk, so
    a delivery failure is reported — loudly — and never fatal, and no
    spool is involved (re-running the command re-pushes)."""
    stream = err if err is not None else sys.stderr
    if not lines:
        return True
    try:
        HttpSink(url, timeout=timeout).send(family, lines)
        return True
    except Exception as e:  # noqa: BLE001 — one-shot: report, never raise
        print(f"[tpu-perf push] could not push {len(lines)} {family} "
              f"record(s) to {url}: {e} (records remain on disk)",
              file=stream, flush=True)
        return False
