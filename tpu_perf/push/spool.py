"""Dead-letter spool: undeliverable push batches, durable on disk.

When the sender exhausts its retries for a batch (sink down longer than
the backoff window covers), the batch is **dead-lettered**: written to
``push-<family>-<job>-<rank>-<seq>.spool.quarantined`` next to the
rotating logs.  The naming is deliberate — it reuses the ingest
quarantine contract (ingest.pipeline.QUARANTINE_SUFFIX) end to end:

* ``tpu-perf ingest --list-quarantined`` lists spooled batches next to
  poison ingest files (one triage surface for both planes);
* ``tpu-perf ingest --requeue`` strips the suffix, turning the file
  into a *live* spool (``push-*.spool``) — and refuses to clobber an
  existing live spool, exactly as it refuses to clobber a live log;
* a live spool is replayed by the first healthy sender that sees it
  (a running ``--push`` soak's background plane, or ``tpu-perf push
  replay``), and deleted only after successful delivery — the
  delete-only-after-success stance the ingest pass takes with files.

Spool files can never collide with any other scan: the ingest pass
matches ``<prefix>-*.log`` only, the fleet collector's host discovery
matches family prefixes and ``phase-*.json``, and ``push`` is not a
family prefix.  The family rides in the file NAME (families are
dash-free by construction — schema.ALL_PREFIXES), so replay needs no
header line inside the payload and the payload bytes are exactly the
records that failed to send.
"""

from __future__ import annotations

import os

from tpu_perf.ingest.pipeline import QUARANTINE_SUFFIX
from tpu_perf.schema import ALL_PREFIXES

#: spool files are ``push-...`` — NOT a rotating family prefix, so no
#: ingest/collector scan ever matches them
SPOOL_PREFIX = "push"
SPOOL_SUFFIX = ".spool"


def spool_name(family: str, job_id: str, rank: int, seq: int) -> str:
    return f"{SPOOL_PREFIX}-{family}-{job_id}-{rank}-{seq:06d}{SPOOL_SUFFIX}"


def parse_spool_family(name: str) -> str | None:
    """The family a spool file (live or quarantined) holds, or None for
    a non-spool name.  Families carry no dash (schema.ALL_PREFIXES), so
    the second dash-field IS the family — job UUIDs after it may dash
    freely."""
    base = os.path.basename(name)
    if base.endswith(QUARANTINE_SUFFIX):
        base = base[: -len(QUARANTINE_SUFFIX)]
    if not base.startswith(SPOOL_PREFIX + "-") \
            or not base.endswith(SPOOL_SUFFIX):
        return None
    parts = base.split("-", 2)
    if len(parts) < 3 or parts[1] not in ALL_PREFIXES:
        return None
    return parts[1]


def write_spool(folder: str, family: str, job_id: str, rank: int,
                lines: list[str], *, seq: int,
                quarantine: bool = True) -> str:
    """Persist one dead-lettered batch atomically (tmp + rename: a
    replayer can never read a torn batch).  ``quarantine=True`` (the
    dead-letter default) lands the file under the ``.quarantined``
    suffix — exhausted retries mean the sink needs an operator, and the
    requeue step is their explicit "try again".  Returns the path."""
    os.makedirs(folder, exist_ok=True)
    stem = spool_name(family, job_id, rank, seq)[: -len(SPOOL_SUFFIX)]
    suffix = SPOOL_SUFFIX + (QUARANTINE_SUFFIX if quarantine else "")
    path = os.path.join(folder, stem + suffix)
    i = 0
    while os.path.exists(path):
        # seq is unique per plane instance; a collision means another
        # process shares the (job, rank) identity — disambiguate rather
        # than overwrite someone else's dead letters.  The counter goes
        # BEFORE the suffixes: a name that stopped ending in
        # .spool/.quarantined would be invisible to every recovery tool
        # (triage, requeue, replay, the depth gauge)
        i += 1
        path = os.path.join(folder, f"{stem}.{i}{suffix}")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        for line in lines:
            fh.write(line + "\n")
    os.replace(tmp, path)
    return path


def live_spool_files(folder: str) -> list[tuple[str, str]]:
    """Replayable (path, family) pairs — live spools only (quarantined
    ones need the operator's ``ingest --requeue`` first), oldest first
    so replay preserves rough record order."""
    try:
        names = os.listdir(folder)
    except FileNotFoundError:
        return []
    out = []
    for n in names:
        if n.endswith(QUARANTINE_SUFFIX) or n.endswith(".tmp"):
            continue
        family = parse_spool_family(n)
        if family is None:
            continue
        path = os.path.join(folder, n)
        try:
            # capture mtime in the same step as the existence check: a
            # concurrent replayer (another rank's plane sharing the
            # logfolder, or an operator's `push replay` against a live
            # soak) may delete the file between listdir and stat, and
            # a raise here would kill the caller's sender thread
            if not os.path.isfile(path):
                continue
            mtime = os.path.getmtime(path)
        except OSError:
            continue
        out.append((mtime, path, family))
    out.sort()
    return [(path, family) for _, path, family in out]


def spool_depth(folder: str | None) -> int:
    """Spool files on disk, live AND quarantined — the gauge an
    operator alerts on (any depth > 0 means undelivered telemetry)."""
    if not folder:
        return 0
    try:
        names = os.listdir(folder)
    except FileNotFoundError:
        return 0
    return sum(1 for n in names
               if parse_spool_family(n) is not None
               and not n.endswith(".tmp"))


def read_spool(path: str) -> list[str]:
    """A spool file's payload lines (written atomically, so no torn-
    line policy is needed here)."""
    with open(path) as fh:
        return [ln.rstrip("\n") for ln in fh if ln.strip()]
