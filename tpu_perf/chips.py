"""Per-chip hardware specs: the device-kind → {HBM, MXU, VMEM, ICI} table.

The reference encodes per-hardware operating profiles as one shell script
per SKU — run-hbv3.sh:22-28 pins the UCX segment sizes and core map for
HBv3, run-ib6hop/t4 likewise for their fabrics.  The TPU equivalent is
this table: bench and grid derive their physical ceilings, plateau
floors, and nominal targets from the chip they actually run on instead
of hardwiring v5e (VERDICT r4 #1: on a v5p the old constants would retry
against the wrong floor and mis-grade every grid cell).

Peak numbers are the public per-chip specs (the jax-ml scaling-book chip
table).  Floors and nominals are MEASURED operating constants where this
repo has defended them — v5e, rounds 2-4, BASELINE.md "Headline
methodology" — and ratio-derived defaults elsewhere (``defended=False``,
using v5e's measured-to-peak ratios).  A new chip's first `tpu-perf
grid` run should replace its derived floors with measured ones, exactly
like rounds 2-4 did for v5e; until then the derived floor is a sane
degraded-window tripwire, not a claim.

Explicit flags always win: every consumer (bench has no flags by design;
grid has ``--spec-*``/``--floor-*``) treats this table as the default,
never as an override.
"""

from __future__ import annotations

import dataclasses
import sys

_MIB = 1 << 20


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """One chip generation's physical ceilings and operating constants.

    ``hbm_gbps``/``mxu_bf16_tflops``/``vmem_bytes``/``ici_gbps`` are the
    public peak specs (``ici_gbps`` is one direction of one ICI link).
    The ``*_nominal_*`` fields are bench's vs_baseline denominators; the
    ``*_floor_*`` fields are the degraded-window thresholds (a pass whose
    best median lands under the floor is a bad chip/tunnel window, not
    the chip's capability)."""

    kind: str          # canonical short name ("v5e")
    device_kind: str   # the jax device_kind string it matches
    hbm_gbps: float
    mxu_bf16_tflops: float
    vmem_bytes: int
    ici_gbps: float
    stream_nominal_gbps: float
    stream_floor_gbps: float
    triad_nominal_gbps: float
    mxu_nominal_tflops: float
    mxu_floor_tflops: float
    allreduce_nominal_gbps: float
    defended: bool     # floors measured on hardware (BASELINE.md) vs derived


#: v5e's measured operating constants vs its peaks (BASELINE.md rounds
#: 2-4) — the ratios used to derive provisional floors for chips this
#: repo has not measured yet:
#:   stream nominal 500/819, floor 600/819 (plateau 650-667 measured);
#:   mxu nominal 150/197, floor 160/197 (plateau 186.8-192.7 measured);
#:   allreduce nominal 25/45 (per-link ICI).
_RATIOS = dict(
    stream_nominal=500 / 819, stream_floor=600 / 819,
    triad_nominal=520 / 819,
    mxu_nominal=150 / 197, mxu_floor=160 / 197,
    allreduce_nominal=25 / 45,
)


def _derived(kind, device_kind, hbm, mxu, vmem_mib, ici) -> ChipSpec:
    r = _RATIOS
    return ChipSpec(
        kind=kind, device_kind=device_kind, hbm_gbps=hbm,
        mxu_bf16_tflops=mxu, vmem_bytes=vmem_mib * _MIB, ici_gbps=ici,
        stream_nominal_gbps=round(hbm * r["stream_nominal"]),
        stream_floor_gbps=round(hbm * r["stream_floor"]),
        triad_nominal_gbps=round(hbm * r["triad_nominal"]),
        mxu_nominal_tflops=round(mxu * r["mxu_nominal"]),
        mxu_floor_tflops=round(mxu * r["mxu_floor"]),
        allreduce_nominal_gbps=round(ici * r["allreduce_nominal"]),
        defended=False,
    )


#: the chip every constant in BASELINE.md was measured on
V5E = ChipSpec(
    kind="v5e", device_kind="TPU v5 lite",
    hbm_gbps=819.0, mxu_bf16_tflops=197.0, vmem_bytes=128 * _MIB,
    ici_gbps=45.0,
    stream_nominal_gbps=500.0,   # ~60% of peak: realistic sustained 1R+1W
    stream_floor_gbps=600.0,     # under the measured 650-667 plateau
    triad_nominal_gbps=520.0,    # stream's 0.76 nominal-to-plateau ratio
                                 # applied to the measured 686.6 2R:1W
                                 # plateau (BASELINE.md round-5)
    mxu_nominal_tflops=150.0,    # solid-utilization bar
    mxu_floor_tflops=160.0,      # under the defended m>=2048 plateau
    allreduce_nominal_gbps=25.0,
    defended=True,
)

#: public peak specs (scaling-book chip table); floors ratio-derived
CHIPS: dict[str, ChipSpec] = {
    "v3": _derived("v3", "TPU v3", hbm=900, mxu=123, vmem_mib=32, ici=70),
    "v4": _derived("v4", "TPU v4", hbm=1228, mxu=275, vmem_mib=128, ici=45),
    "v5e": V5E,
    "v5p": _derived("v5p", "TPU v5p", hbm=2765, mxu=459, vmem_mib=128, ici=90),
    "v6e": _derived("v6e", "TPU v6 lite", hbm=1640, mxu=918, vmem_mib=128,
                    ici=90),
}

#: normalized device_kind → table key.  device_kind strings vary across
#: runtime versions ("TPU v5 lite" vs "TPU v5e", "TPU v5" vs "TPU v5p"),
#: so matching goes through this alias map, not string equality.
_KIND_ALIASES = {
    "v3": "v3",
    "v4": "v4",
    "v4i": "v4",
    "v5 lite": "v5e",
    "v5e": "v5e",
    "v5litepod": "v5e",
    "v5": "v5p",
    "v5p": "v5p",
    "v6 lite": "v6e",
    "v6e": "v6e",
}


def _normalize(device_kind: str) -> str:
    s = device_kind.strip().lower()
    if s.startswith("tpu"):
        s = s[3:].strip()
    return s


def resolve_kind(device_kind: str) -> str | None:
    """The table key ``device_kind`` resolves to, or None when the kind
    is unknown (callers that need to distinguish a real match from
    chip_spec's v5e fallback use this)."""
    return _KIND_ALIASES.get(_normalize(device_kind))


def chip_spec(device_kind: str | None = None, *, err=None) -> ChipSpec:
    """The spec for ``device_kind`` (default: the first local device's).

    Unknown kinds — including the CPU test backend — fall back to the
    v5e entry with a stderr note: bench/grid keep working everywhere,
    their constants are simply the ones rounds 2-4 defended, and the
    operator can override via flags.  The note goes to stderr so bench's
    one-JSON-line stdout contract is untouched.
    """
    if device_kind is None:
        import jax

        device_kind = jax.devices()[0].device_kind
    key = resolve_kind(device_kind)
    if key is None:
        print(
            f"[tpu-perf] unknown device kind {device_kind!r}: using the "
            "v5e spec table (override with explicit spec/floor flags)",
            file=err if err is not None else sys.stderr,
        )
        return V5E
    return CHIPS[key]
