"""The zone manifest: which invariants apply where.

The manifest is a checked-in JSON file (``tpu_perf/analysis/
manifest.json`` for this repo) — the analyzer's *declared* contract
surface, reviewed like code.  It names the deterministic zones (R1), the
collective call names and taint sources (R2), and the files/constants
that carry the family and row-schema contracts (R3/R4).  Rules read the
manifest instead of hard-coding repo paths, so the same engine lints the
fixture trees the test suite builds and any downstream fork's layout.

All paths are POSIX-relative to the lint root.  A zone entry ending in
``/`` covers the subtree; otherwise it names one file.
"""

from __future__ import annotations

import dataclasses
import json
import os

#: wall-clock / entropy calls banned in deterministic zones (canonical
#: dotted names after alias resolution — astutil.dotted_name)
DEFAULT_CLOCK_CALLS = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "time.process_time_ns", "time.clock_gettime", "time.clock_gettime_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
    "os.urandom", "uuid.uuid1", "uuid.uuid4",
})

#: module prefixes whose *global-state* RNG calls are banned in zones;
#: seeded constructors (random.Random(x), numpy.random.default_rng(x))
#: are the sanctioned alternative and stay legal WITH arguments
DEFAULT_RNG_PREFIXES = ("random.", "numpy.random.", "secrets.")
DEFAULT_SEEDED_CTORS = frozenset({
    "random.Random", "numpy.random.default_rng", "numpy.random.RandomState",
    "numpy.random.Generator", "numpy.random.PCG64", "numpy.random.Philox",
    "numpy.random.SeedSequence",
})

DEFAULT_CLOCK_PARAMS = frozenset({"perf_clock", "clock", "perf_ns"})

DEFAULT_COLLECTIVES = frozenset({
    "allreduce_times", "process_allgather", "psum", "psum_scatter",
    "all_gather", "all_to_all", "ppermute", "should_stop",
})

DEFAULT_RANK_NAMES = frozenset({
    "rank", "process_index", "local_rank", "host_id", "local_ip",
})


@dataclasses.dataclass(frozen=True)
class Manifest:
    """Parsed manifest + defaults.  ``root`` is the directory every
    relative path resolves against."""

    root: str
    source_path: str = "<manifest>"  # where the manifest was loaded
    #                                  from (root-relative when inside
    #                                  the root) — R6's finding anchor
    include: tuple[str, ...] = ("tpu_perf/**/*.py",)
    exclude: tuple[str, ...] = ()
    deterministic_zones: tuple[str, ...] = ()
    clock_calls: frozenset[str] = DEFAULT_CLOCK_CALLS
    rng_prefixes: tuple[str, ...] = DEFAULT_RNG_PREFIXES
    seeded_ctors: frozenset[str] = DEFAULT_SEEDED_CTORS
    clock_params: frozenset[str] = DEFAULT_CLOCK_PARAMS
    collectives: frozenset[str] = DEFAULT_COLLECTIVES
    rank_names: frozenset[str] = DEFAULT_RANK_NAMES
    family_contract: dict | None = None
    schema_drift: dict | None = None

    @staticmethod
    def zone_matches(zone: str, relpath: str) -> bool:
        """THE definition of zone membership (trailing ``/`` covers the
        subtree, else one file) — shared by R1's enforcement and R6's
        coverage check, so the two can never disagree about what a zone
        entry matches."""
        rel = relpath.replace(os.sep, "/")
        if zone.endswith("/"):
            return rel.startswith(zone)
        return rel == zone

    def in_zone(self, relpath: str) -> bool:
        return any(self.zone_matches(zone, relpath)
                   for zone in self.deterministic_zones)


def default_manifest_path() -> str:
    """The checked-in manifest shipped next to this module."""
    return os.path.join(os.path.dirname(__file__), "manifest.json")


def default_root() -> str:
    """The repo/package root the shipped manifest's paths are relative
    to: the directory CONTAINING the ``tpu_perf`` package."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def load_manifest(path: str, root: str) -> Manifest:
    with open(path) as fh:
        data = json.load(fh)
    if not isinstance(data, dict):
        raise ValueError(f"manifest {path!r} must be a JSON object")
    version = data.get("version", 1)
    if version != 1:
        raise ValueError(f"manifest {path!r}: unsupported version {version}")
    known = {
        "version", "include", "exclude", "deterministic_zones",
        "extra_clock_calls", "clock_params", "collectives", "rank_names",
        "family_contract", "schema_drift",
    }
    unknown = set(data) - known
    if unknown:
        raise ValueError(
            f"manifest {path!r}: unknown key(s) {sorted(unknown)} "
            f"(known: {sorted(known)})"
        )

    def _strings(key, default):
        val = data.get(key)
        if val is None:
            return default
        if (not isinstance(val, list)
                or not all(isinstance(v, str) for v in val)):
            raise ValueError(f"manifest {path!r}: {key} must be a string list")
        return tuple(val)

    clock_calls = DEFAULT_CLOCK_CALLS | set(
        _strings("extra_clock_calls", ())
    )
    abs_root = os.path.abspath(root)
    abs_path = os.path.abspath(path)
    source_path = (os.path.relpath(abs_path, abs_root).replace(os.sep, "/")
                   if abs_path.startswith(abs_root + os.sep)
                   else os.path.basename(path))
    return Manifest(
        root=abs_root,
        source_path=source_path,
        include=_strings("include", Manifest.include),
        exclude=_strings("exclude", ()),
        deterministic_zones=_strings("deterministic_zones", ()),
        clock_calls=frozenset(clock_calls),
        clock_params=frozenset(_strings("clock_params",
                                        tuple(DEFAULT_CLOCK_PARAMS))),
        collectives=frozenset(_strings("collectives",
                                       tuple(DEFAULT_COLLECTIVES))),
        rank_names=frozenset(_strings("rank_names",
                                      tuple(DEFAULT_RANK_NAMES))),
        family_contract=data.get("family_contract"),
        schema_drift=data.get("schema_drift"),
    )
