"""Static invariant analysis — ``tpu-perf lint``.

An AST-walking rule engine (stdlib ``ast`` only, no new dependencies)
that proves the framework's load-bearing contracts at parse time instead
of discovering them at runtime:

* **R1 no-wallclock** — deterministic zones (``faults/``, span-ID
  derivation, the adaptive vote path; declared in the checked-in
  manifest) never read wall clocks or unseeded RNGs, and any function
  taking an injectable clock parameter routes through it;
* **R2 lockstep** — collective call sites are never control-dependent
  on rank-local or timing-derived state, so every rank enters every
  collective in the same order;
* **R3 family-contract** — the ``*_PREFIX`` rotating-log families are
  fully wired across schema, ingest routing, Kusto tables, and the
  lazy no-newest-skip set;
* **R4 schema-drift** — every ``ResultRow`` field has a parser width
  that accepts it (the 12/13/15/18/19 ladder);
* **R5 guarded-by** — attributes annotated as lock-guarded are only
  touched under their lock (the compile-pipeline race detector).

Layout: ``manifest.py`` loads the checked-in zone manifest
(``manifest.json``), ``engine.py`` owns sources/pragmas/registry/output,
``rules.py`` implements R1-R5, ``findings.py`` the stable fingerprints
and the baseline file (``baseline.json`` ships EMPTY — every true
positive in this tree is fixed, not baselined).
"""

from tpu_perf.analysis.engine import (  # noqa: F401
    JSON_SCHEMA_VERSION, LintResult, Rule, all_rules, lint_tree,
    render_json, render_rule_catalog, render_text, resolve_rules,
)
from tpu_perf.analysis.findings import Finding, render_baseline  # noqa: F401
from tpu_perf.analysis.manifest import (  # noqa: F401
    Manifest, default_manifest_path, default_root, load_manifest,
)
