"""The invariant rules (R1-R5).

Each rule's docstring IS its catalog entry (``tpu-perf lint
--list-rules``).  The rules prove, at parse time, the contracts the
runtime suites can only catch by executing the violation: clock-free
deterministic zones (R1), rank-lockstep collective order (R2), the
fully-wired rotating-log family contract (R3), the row-schema /
parser-width ladder (R4), and lock-guarded shared attributes (R5).
"""

from __future__ import annotations

import ast

from tpu_perf.analysis.astutil import (
    TaintChecker, ancestors, dotted_name, enclosing_function,
    import_aliases, terminal_name,
)
from tpu_perf.analysis.engine import Rule, Source, register
from tpu_perf.analysis.findings import Finding
from tpu_perf.analysis.manifest import Manifest


def _call_args_empty(call: ast.Call) -> bool:
    return not call.args and not call.keywords


def _banned_clock_call(call: ast.Call, manifest: Manifest,
                       aliases: dict[str, str]) -> str | None:
    """The canonical dotted name when ``call`` is a forbidden clock/RNG
    read, else None."""
    dotted = dotted_name(call.func, aliases)
    if dotted is None:
        return None
    if dotted in manifest.clock_calls:
        return dotted
    if dotted in manifest.seeded_ctors:
        # seeded constructors are the sanctioned pattern — but only when
        # actually seeded; zero-arg default_rng()/Random() draw OS entropy
        return dotted if _call_args_empty(call) else None
    if dotted.startswith(manifest.rng_prefixes):
        return dotted
    return None


@register
class NoWallclockRule(Rule):
    """Deterministic zones must not read wall clocks or unseeded RNGs.

    The chaos ledger's byte-identical-per-seed contract, clock-free span
    IDs, and the adaptive vote's replayability all hang on the declared
    zones (manifest ``deterministic_zones``) deriving every value from
    injected clocks and seeded RNGs.  Two checks:

    * in a zone file, any call of ``time.*`` clocks, ``datetime.now``
      family, ``os.urandom``/``uuid.uuid1/4``, the global ``random``/
      ``numpy.random`` state, or an UNSEEDED seeded-ctor
      (``random.Random()``, ``numpy.random.default_rng()``) is a
      finding;
    * in ANY file, a function that takes an injectable clock parameter
      (manifest ``clock_params``: perf_clock/clock/perf_ns) must not
      also call a wall clock directly — the injected clock exists to be
      routed through, and a stray direct read silently splits a run's
      timeline across two clocks.

    Escape hatch: ``# tpuperf: allow-clock(<reason>)`` on the call's
    line; every use is counted and reported.
    """

    id = "R1"
    name = "no-wallclock"

    def check(self, source: Source, manifest: Manifest) -> list[Finding]:
        aliases = import_aliases(source.tree)
        findings: list[Finding] = []
        in_zone = manifest.in_zone(source.relpath)
        clock_only = frozenset(
            c for c in manifest.clock_calls
            if c.startswith(("time.", "datetime."))
        )
        for node in ast.walk(source.tree):
            if not isinstance(node, ast.Call):
                continue
            banned = _banned_clock_call(node, manifest, aliases)
            if banned is None:
                continue
            if in_zone:
                findings.append(source.finding(
                    self, node,
                    f"'{banned}' called in deterministic zone — route "
                    f"through an injected clock / seeded RNG or annotate "
                    f"'# tpuperf: allow-clock(<reason>)'",
                ))
                continue
            if banned not in clock_only:
                continue
            func = enclosing_function(node)
            while func is not None:
                params = {
                    a.arg for a in (func.args.posonlyargs + func.args.args
                                    + func.args.kwonlyargs)
                }
                hit = params & manifest.clock_params
                if hit:
                    findings.append(source.finding(
                        self, node,
                        f"'{banned}' called directly inside "
                        f"'{func.name}', which takes the injectable "
                        f"clock parameter '{sorted(hit)[0]}' — use the "
                        f"injected clock",
                    ))
                    break
                func = enclosing_function(func)
        return findings


def _condition_chain(call: ast.Call):
    """Yield (condition_expr, carrier_node) for every enclosing construct
    that makes ``call``'s execution conditional, up to the function
    boundary."""
    node: ast.AST = call
    for anc in ancestors(call):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return
        if isinstance(anc, ast.If) and node is not anc.test:
            yield anc.test, anc
        elif isinstance(anc, ast.While) and node is not anc.test:
            yield anc.test, anc
        elif isinstance(anc, (ast.For, ast.AsyncFor)) and node is not anc.iter:
            # a tainted ITERATION COUNT (for _ in range(self.rank): ...)
            # varies the per-rank entry count exactly like a tainted test
            yield anc.iter, anc
        elif isinstance(anc, ast.IfExp) and node is not anc.test:
            yield anc.test, anc
        elif isinstance(anc, ast.BoolOp):
            # short-circuit: every operand before the one holding the
            # call guards its evaluation
            for value in anc.values:
                if value is node or any(n is node for n in ast.walk(value)):
                    break
                yield value, anc
        elif isinstance(anc, (ast.ListComp, ast.SetComp, ast.DictComp,
                              ast.GeneratorExp)):
            for gen in anc.generators:
                for cond in gen.ifs:
                    yield cond, anc
                if not any(n is node for n in ast.walk(gen.iter)):
                    yield gen.iter, anc
        node = anc


def _exit_skips_call(if_stmt: ast.If, call: ast.Call) -> bool:
    """Can the tainted condition route SOME ranks around ``call``?
    Checked for both branches — a rank-guarded exit in the ``else`` arm
    splits the mesh exactly like one in the body.  Return/Raise exit the
    whole function, so yes.  Break/Continue exit only the innermost
    enclosing loop — they skip the call only when the call sits inside
    that SAME loop (a rank-local retry loop BEFORE a collective is
    lockstep-legal; every rank still reaches the collective)."""
    for stmt in if_stmt.body + if_stmt.orelse:
        if isinstance(stmt, (ast.Return, ast.Raise)):
            return True
        if isinstance(stmt, (ast.Break, ast.Continue)):
            loop = next(
                (a for a in ancestors(if_stmt)
                 if isinstance(a, (ast.For, ast.AsyncFor, ast.While))),
                None,
            )
            if loop is not None and any(a is loop for a in ancestors(call)):
                return True
    return False


@register
class LockstepRule(Rule):
    """Collective call sites must not be control-dependent on rank-local
    or timing-derived state.

    Every rank must enter every collective (``allreduce_times``, the
    ``psum``/``ppermute`` kernels, the adaptive ``should_stop`` vote) the
    same number of times in the same order, or the mesh deadlocks — and
    the variant that only deadlocks at 256 chips never fires in CI.  The
    rule walks each collective call's enclosing ``if``/``while``/ternary
    /short-circuit conditions (to the function boundary) and flags any
    condition tainted by a rank source (manifest ``rank_names``:
    rank/process_index/local_ip/...) or a timing read (wall clocks or an
    injected-clock parameter call), with one intra-function assignment
    fixed point so ``t = perf_clock(); if t > x: vote()`` is caught.  A
    rank-tainted early exit (``if rank != 0: return``) lexically before
    a collective in the same function is flagged the same way.  A
    one-level interprocedural summary registers this module's functions
    whose RETURN value is tainted (``def _lucky(self): return
    self.rank``) as sources themselves, so a helper cannot launder rank
    state past the walk; the summary is one level and module-local by
    design — deeper chains need a pragma, not whole-program analysis.

    Uniform-on-every-rank conditions (``n_hosts > 1``, config flags) are
    deliberately legal.  Audited sites annotate
    ``# tpuperf: allow-lockstep(<reason>)``.
    """

    id = "R2"
    name = "lockstep"

    def check(self, source: Source, manifest: Manifest) -> list[Finding]:
        aliases = import_aliases(source.tree)
        taint = TaintChecker(
            rank_names=manifest.rank_names,
            clock_calls=frozenset(
                c for c in manifest.clock_calls
                if c.startswith(("time.", "datetime."))
            ),
            clock_params=manifest.clock_params,
            aliases=aliases,
        # the one-level summary: module functions returning tainted
        # values become sources for every check below (the taint cache
        # is built AFTER this, so assignments from such helpers
        # propagate through the intra-function fixed point too)
        ).with_summaries(source.tree)
        findings: list[Finding] = []
        tainted_cache: dict[int, frozenset[str]] = {}

        def tainted_names_for(func) -> frozenset[str]:
            if func is None:
                return frozenset()
            key = id(func)
            if key not in tainted_cache:
                tainted_cache[key] = taint.tainted_names(func)
            return tainted_cache[key]

        collective_calls: list[ast.Call] = []
        for node in ast.walk(source.tree):
            if isinstance(node, ast.Call):
                callee = terminal_name(node.func)
                if callee in manifest.collectives:
                    collective_calls.append(node)

        for call in collective_calls:
            func = enclosing_function(call)
            tainted = tainted_names_for(func)
            callee = terminal_name(call.func)
            for cond, carrier in _condition_chain(call):
                if taint.seeded(cond, tainted):
                    findings.append(source.finding(
                        self, call,
                        f"collective '{callee}' is control-dependent on "
                        f"rank-local/timing-derived state "
                        f"(condition at line {cond.lineno}) — every rank "
                        f"must enter it in lockstep",
                    ))
                    break
            else:
                if func is None:
                    continue
                enclosing = set(map(id, ancestors(call)))
                for stmt in ast.walk(func):
                    if (stmt.lineno if hasattr(stmt, "lineno") else 0) \
                            >= call.lineno:
                        continue
                    # a return/raise inside a NESTED function exits only
                    # the closure — it cannot skip the outer function's
                    # collective
                    if isinstance(stmt, (ast.If, ast.Assert)) \
                            and enclosing_function(stmt) is not func:
                        continue
                    # `assert rank == 0` IS a conditional raise: every
                    # non-matching rank skips the collective
                    exits = (
                        isinstance(stmt, ast.Assert)
                        or (isinstance(stmt, ast.If)
                            and id(stmt) not in enclosing
                            and _exit_skips_call(stmt, call))
                    )
                    if exits and taint.seeded(stmt.test, tainted):
                        findings.append(source.finding(
                            self, stmt,
                            f"rank-local/timing-conditional early exit "
                            f"precedes collective '{callee}' (line "
                            f"{call.lineno}) in the same function — "
                            f"ranks taking the exit skip the collective",
                        ))
                        break
        return findings


def _module_consts(tree: ast.Module, suffix: str) -> dict[str, tuple[str, int]]:
    """Module-level ``NAME = "literal"`` assignments whose name carries
    ``suffix`` -> (value, line)."""
    out: dict[str, tuple[str, int]] = {}
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id.endswith(suffix)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)):
            out[stmt.targets[0].id] = (stmt.value.value, stmt.lineno)
    return out


def _name_tuple(node: ast.AST) -> list[str] | None:
    if isinstance(node, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Name) for e in node.elts):
        return [e.id for e in node.elts]
    return None


def _tree_finding(rule, path: str, line: int, message: str,
                  snippet: str = "") -> Finding:
    from tpu_perf.analysis.findings import normalize_snippet

    return Finding(rule=rule.id, name=rule.name, path=path, line=line,
                   col=0, scope="<module>", message=message,
                   snippet=normalize_snippet(snippet))


@register
class FamilyContractRule(Rule):
    """A rotating-log family must be fully wired or not exist.

    The rotating families (``tcp``/``tpu`` CSV + ``health``/``chaos``/
    ``linkmap``/``spans``/``fleet`` JSONL) share one contract spread
    over three files: ``schema.py`` declares ``*_PREFIX`` constants and
    sweeps them in ``ALL_PREFIXES``; the ingest pipeline routes each
    prefix to its own Kusto table and exempts the lazy
    (``.open``-suffixed) JSONL families from the newest-N skip; the
    push plane's sink module routes each family live (``PUSH_ROUTES``)
    or declares it tee-free (``TEE_FREE_FAMILIES`` — the chaos ledger's
    byte-identity exclusion).  The rule cross-checks all three
    (manifest ``family_contract`` names the files and which families
    are CSV), so a new family cannot ship half-wired: declared but not
    swept, swept but not routed, routed but starved by the newest-N
    heuristic, short a Kusto table, or absent from the push plane's
    routed-xor-tee-free partition.
    """

    id = "R3"
    name = "family-contract"
    scope = "tree"

    def check_tree(self, sources: dict[str, Source],
                   manifest: Manifest) -> list[Finding]:
        cfg = manifest.family_contract
        if not cfg:
            return []
        findings: list[Finding] = []
        schema_path = cfg.get("schema", "")
        ingest_path = cfg.get("ingest", "")
        csv_families = set(cfg.get("csv_families", ()))
        default_family = cfg.get("default_family", "")
        schema = sources.get(schema_path)
        pipeline = sources.get(ingest_path)
        for path, src in ((schema_path, schema), (ingest_path, pipeline)):
            if src is None:
                findings.append(_tree_finding(
                    self, path or "<manifest>", 1,
                    f"family-contract surface {path!r} is not among the "
                    f"linted sources",
                ))
        if schema is None or pipeline is None:
            return findings

        prefixes = _module_consts(schema.tree, "_PREFIX")
        all_prefixes: list[str] | None = None
        all_line = 1
        for stmt in schema.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "ALL_PREFIXES"):
                all_prefixes = _name_tuple(stmt.value)
                all_line = stmt.lineno
        if all_prefixes is None:
            findings.append(_tree_finding(
                self, schema.relpath, 1,
                "ALL_PREFIXES tuple of family constants not found",
            ))
            return findings

        for name, (_, line) in sorted(prefixes.items()):
            if name not in all_prefixes:
                findings.append(_tree_finding(
                    self, schema.relpath, line,
                    f"family constant {name} is declared but missing from "
                    f"ALL_PREFIXES — its logs would never be ingested",
                    schema.line_text(line),
                ))
        for name in all_prefixes:
            if name not in prefixes:
                findings.append(_tree_finding(
                    self, schema.relpath, all_line,
                    f"ALL_PREFIXES entry {name} has no string constant "
                    f"in {schema.relpath}",
                    schema.line_text(all_line),
                ))

        # --- ingest routing: every non-default family needs its own
        # startswith() branch in an ingest() method
        routed: set[str] = set()
        ingest_line = 1
        props_calls = 0
        for node in ast.walk(pipeline.tree):
            if isinstance(node, ast.Call):
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "startswith"
                        and node.args
                        and isinstance(node.args[0], ast.Name)):
                    routed.add(node.args[0].id)
                if terminal_name(node.func) == "IngestionProperties":
                    props_calls += 1
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name == "ingest"):
                ingest_line = max(ingest_line, node.lineno)
        for name in all_prefixes:
            if name == default_family:
                continue
            if name not in routed:
                findings.append(_tree_finding(
                    self, pipeline.relpath, ingest_line,
                    f"family {name} has no startswith() routing branch in "
                    f"{pipeline.relpath} — its rows would land in the "
                    f"default table and fail the column mapping",
                ))
        if props_calls < len(all_prefixes):
            # zero found is the LOUDEST case, not a disabled check: a
            # refactor that moves/renames the table construction must
            # fail here (and update the contract files), never silently
            # retire the Kusto-table surface
            findings.append(_tree_finding(
                self, pipeline.relpath, 1,
                f"{props_calls} IngestionProperties table route(s) for "
                f"{len(all_prefixes)} families — a family is missing its "
                f"Kusto table" if props_calls else
                f"no IngestionProperties table routes found in "
                f"{pipeline.relpath} for {len(all_prefixes)} families — "
                f"the Kusto-table surface is unwired (or moved; update "
                f"the family_contract manifest if so)",
            ))

        # --- lazy (.open) families: everything that is not CSV must be
        # exempt from the newest-N skip, and nothing CSV may be
        lazy: list[str] | None = None
        lazy_line = 1
        for node in ast.walk(pipeline.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "lazy_families"):
                lazy = _name_tuple(node.value)
                lazy_line = node.lineno
        if lazy is None:
            findings.append(_tree_finding(
                self, pipeline.relpath, 1,
                "lazy_families tuple not found — the JSONL families would "
                "all suffer the newest-N skip and starve",
            ))
        else:
            for name in all_prefixes:
                if name in csv_families:
                    if name in lazy:
                        findings.append(_tree_finding(
                            self, pipeline.relpath, lazy_line,
                            f"CSV family {name} is in lazy_families — its "
                            f"still-being-written newest files would be "
                            f"swept mid-row",
                            pipeline.line_text(lazy_line),
                        ))
                elif name not in lazy:
                    findings.append(_tree_finding(
                        self, pipeline.relpath, lazy_line,
                        f"JSONL family {name} is missing from "
                        f"lazy_families — the newest-N skip would starve "
                        f"its sparse logs",
                        pipeline.line_text(lazy_line),
                    ))

        # --- push routing (tpu_perf.push, --push): every family must be
        # either live-routed (a PUSH_ROUTES key) or declared tee-free
        # (TEE_FREE_FAMILIES) — exactly one of the two.  Missing from
        # both is the half-wired eighth family (its records rotate but
        # never reach a live sink, and nothing says that was a choice);
        # present in both means a family whose byte-identity contract
        # depends on the plane's absence just gained a route.
        push_path = cfg.get("push", "")
        if not push_path:
            return findings
        sink_src = sources.get(push_path)
        if sink_src is None:
            findings.append(_tree_finding(
                self, push_path, 1,
                f"family-contract push surface {push_path!r} is not "
                f"among the linted sources",
            ))
            return findings
        routes: list[str] | None = None
        routes_line = 1
        tee_free: list[str] | None = None
        tee_line = 1
        for stmt in sink_src.tree.body:
            if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                continue
            target = stmt.targets[0].id
            if target == "PUSH_ROUTES" and isinstance(stmt.value, ast.Dict):
                if all(isinstance(k, ast.Name) for k in stmt.value.keys):
                    routes = [k.id for k in stmt.value.keys]
                    routes_line = stmt.lineno
            elif target == "TEE_FREE_FAMILIES":
                tee_free = _name_tuple(stmt.value)
                tee_line = stmt.lineno
        if routes is None:
            findings.append(_tree_finding(
                self, sink_src.relpath, 1,
                "PUSH_ROUTES dict of family-constant keys not found — "
                "the live push routing surface is unwired (or moved; "
                "update the family_contract manifest if so)",
            ))
        if tee_free is None:
            findings.append(_tree_finding(
                self, sink_src.relpath, 1,
                "TEE_FREE_FAMILIES tuple not found — the chaos ledger's "
                "push-exclusion is undeclared and unprovable",
            ))
        if routes is None or tee_free is None:
            return findings
        for name in all_prefixes:
            in_routes = name in routes
            in_tee_free = name in tee_free
            if in_routes and in_tee_free:
                findings.append(_tree_finding(
                    self, sink_src.relpath, routes_line,
                    f"family {name} is declared tee-free AND routed in "
                    f"PUSH_ROUTES — a byte-identity family can never "
                    f"gain a live route",
                    sink_src.line_text(routes_line),
                ))
            elif not in_routes and not in_tee_free:
                findings.append(_tree_finding(
                    self, sink_src.relpath, routes_line,
                    f"family {name} is neither routed in PUSH_ROUTES nor "
                    f"declared in TEE_FREE_FAMILIES — a new family must "
                    f"choose (the half-wired-eighth-family check)",
                    sink_src.line_text(routes_line),
                ))
        for name in routes:
            if name.endswith("_PREFIX") and name not in all_prefixes:
                findings.append(_tree_finding(
                    self, sink_src.relpath, routes_line,
                    f"PUSH_ROUTES key {name} is not in ALL_PREFIXES — a "
                    f"route for a family that does not rotate delivers "
                    f"nothing",
                    sink_src.line_text(routes_line),
                ))
        for name in tee_free:
            if name not in all_prefixes:
                findings.append(_tree_finding(
                    self, sink_src.relpath, tee_line,
                    f"TEE_FREE_FAMILIES entry {name} is not in "
                    f"ALL_PREFIXES — the exclusion protects nothing",
                    sink_src.line_text(tee_line),
                ))
        return findings


@register
class SchemaDriftRule(Rule):
    """Every ``ResultRow`` field must be parseable back.

    Rows stream through rotating logs and replay through ``from_csv``;
    the parser accepts the historical width ladder (12/13/15/18/19
    columns) so old logs stay readable.  A new column appended to the
    dataclass without a parser branch fails at REPLAY time, in
    production, on the first row that carries it.  The rule counts the
    row class's fields, extracts the accepted-widths tuple from the
    ``len(parts) not in (...)`` guard, and requires (a) the max accepted
    width to equal the field count and (b) the emitted header's column
    count to be one of the accepted widths (manifest ``schema_drift``
    names the file, class, and header constant).
    """

    id = "R4"
    name = "schema-drift"
    scope = "tree"

    def check_tree(self, sources: dict[str, Source],
                   manifest: Manifest) -> list[Finding]:
        cfg = manifest.schema_drift
        if not cfg:
            return []
        findings: list[Finding] = []
        schema_path = cfg.get("schema", "")
        schema = sources.get(schema_path)
        if schema is None:
            return [_tree_finding(
                self, schema_path or "<manifest>", 1,
                f"schema-drift surface {schema_path!r} is not among the "
                f"linted sources",
            )]
        row_class = cfg.get("row_class", "ResultRow")
        header_const = cfg.get("header_const")

        cls = next(
            (n for n in schema.tree.body
             if isinstance(n, ast.ClassDef) and n.name == row_class), None)
        if cls is None:
            return [_tree_finding(
                self, schema.relpath, 1,
                f"row class {row_class} not found",
            )]
        fields = [stmt for stmt in cls.body
                  if isinstance(stmt, ast.AnnAssign)
                  and isinstance(stmt.target, ast.Name)]
        n_fields = len(fields)

        widths: tuple[int, ...] | None = None
        widths_line = cls.lineno
        for node in ast.walk(cls):
            if not isinstance(node, ast.Compare):
                continue
            if not any(isinstance(op, (ast.In, ast.NotIn))
                       for op in node.ops):
                continue
            left = node.left
            if not (isinstance(left, ast.Call)
                    and terminal_name(left.func) == "len"):
                continue
            comp = node.comparators[0]
            if isinstance(comp, (ast.Tuple, ast.List, ast.Set)) and all(
                    isinstance(e, ast.Constant) and isinstance(e.value, int)
                    for e in comp.elts):
                widths = tuple(e.value for e in comp.elts)
                widths_line = node.lineno
                break
        if widths is None:
            findings.append(_tree_finding(
                self, schema.relpath, cls.lineno,
                f"{row_class}: no accepted-widths guard "
                f"(len(parts) in/not in (...)) found in its parser",
            ))
            return findings
        if max(widths) != n_fields:
            findings.append(_tree_finding(
                self, schema.relpath, widths_line,
                f"{row_class} has {n_fields} fields but the parser's "
                f"accepted widths top out at {max(widths)} "
                f"({widths}) — a row carrying every column would fail "
                f"replay; add the new width (and a parser branch)",
                schema.line_text(widths_line),
            ))
        if header_const:
            consts = _module_consts(schema.tree, header_const)
            if header_const not in consts:
                findings.append(_tree_finding(
                    self, schema.relpath, 1,
                    f"header constant {header_const} not found",
                ))
            else:
                value, line = consts[header_const]
                n_cols = value.count(",") + 1
                if n_cols not in widths:
                    findings.append(_tree_finding(
                        self, schema.relpath, line,
                        f"{header_const} declares {n_cols} columns, which "
                        f"is not an accepted parser width {widths}",
                        schema.line_text(line),
                    ))
        return findings


@register
class GuardedByRule(Rule):
    """Lock-guarded attributes may only be touched under their lock.

    An attribute assignment annotated ``# tpuperf: guarded-by(<lock>)``
    declares that every OTHER access of that attribute in the module
    (the declaring line itself is the exemption — construction happens
    before the object is shared) must sit lexically inside a ``with
    <obj>.<lock>:`` block.  This is the compile-pipeline race detector:
    the driver's ``_canon``/``_canon_refs`` refcounts and the pipeline
    worker's result/credit state are exactly the words a worker thread
    and the main thread race on.  Deliberate unguarded access (a
    single-threaded reader, a monitoring read) annotates
    ``# tpuperf: allow-unguarded(<reason>)``.  Scope is the declaring
    CLASS within the declaring module: an unrelated class reusing a
    common attribute name is a different attribute, and cross-module
    (or cross-class) accesses are out of reach of a parse-time rule —
    they belong to code review.
    """

    id = "R5"
    name = "guarded-by"

    def check(self, source: Source, manifest: Manifest) -> list[Finding]:
        # keyed by (declaring class, attr): an unrelated same-module
        # class reusing a common name ('builds', '_done') is a different
        # attribute, not a violation of this one's lock contract
        guarded: dict[tuple[int, str], tuple[str, set[int]]] = {}
        findings: list[Finding] = []

        decl_pragmas = source.pragmas_of_kind("guarded-by")
        if not decl_pragmas:
            return []
        # map each pragma to the self.<attr> assignment(s) on its line
        # (a = b = 0 declares EVERY attribute target, or the annotation
        # would silently cover only the first).  Each entry carries the
        # assignment node's FULL line range: a pragma on a multi-line
        # declaration's continuation line must exempt the whole
        # statement, including the target's (earlier) line.
        def _enclosing_class(node):
            for anc in ancestors(node):
                if isinstance(anc, ast.ClassDef):
                    return anc
            return None

        assigns: dict[int, tuple[list[str], range, int]] = {}
        for node in ast.walk(source.tree):
            targets: list[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            # chained (a = b = 0) AND unpacking (a, b = 0, 1) forms both
            # declare every attribute target
            flat: list[ast.AST] = []
            for t in targets:
                if isinstance(t, (ast.Tuple, ast.List)):
                    flat.extend(t.elts)
                else:
                    flat.append(t)
            attrs = [t.attr for t in flat
                     if isinstance(t, ast.Attribute)
                     and isinstance(t.value, ast.Name)]
            if attrs:
                span = range(node.lineno, (node.end_lineno or node.lineno) + 1)
                cls = _enclosing_class(node)
                cls_key = id(cls) if cls is not None else 0
                for line in span:
                    prev = assigns.get(line)
                    merged = (prev[0] + attrs) if prev else list(attrs)
                    assigns[line] = (merged, span, cls_key)
        for pragma in decl_pragmas:
            # the annotation attaches to its own line, or — standalone
            # (comment-only line) — to the assignment directly below,
            # the same two placements every suppression pragma honors
            entry = assigns.get(pragma.line)
            if entry is None and source.is_comment_only_line(pragma.line):
                entry = assigns.get(pragma.line + 1)
            attrs, decl_span, cls_key = entry if entry else (None, None, 0)
            if not attrs:
                findings.append(Finding(
                    rule=self.id, name=self.name, path=source.relpath,
                    line=pragma.line, col=0, scope="<module>",
                    message="guarded-by pragma is not attached to an "
                            "attribute assignment",
                    snippet=source.line_text(pragma.line).strip(),
                ))
                continue
            for attr in attrs:
                lock, lines = guarded.setdefault(
                    (cls_key, attr), (pragma.arg, set()))
                if lock != pragma.arg:
                    findings.append(Finding(
                        rule=self.id, name=self.name, path=source.relpath,
                        line=pragma.line, col=0, scope="<module>",
                        message=f"attribute '{attr}' declared guarded by "
                                f"both '{lock}' and '{pragma.arg}'",
                    ))
                lines.update(decl_span)

        def _receiver_chain(node: ast.AST) -> tuple[str, ...] | None:
            """(``self``,) for ``self.x``, (``self``, ``pipe``) for
            ``self.pipe.x`` — None for anything not a plain chain."""
            parts: list[str] = []
            cur = node
            while isinstance(cur, ast.Attribute):
                parts.append(cur.attr)
                cur = cur.value
            if not isinstance(cur, ast.Name):
                return None
            parts.append(cur.id)
            return tuple(reversed(parts))

        def under_lock(node: ast.Attribute, lock: str) -> bool:
            # the held lock must live on the SAME receiver as the
            # guarded attribute: `with other._cond:` while touching
            # `self._results` is a real race, not a guarded access.
            # Unresolvable receivers (a local alias named after the
            # lock, a call result) fall back to the name match —
            # arbitrarily-named aliases need an allow-unguarded pragma,
            # not a guess.
            want = _receiver_chain(node.value)
            for anc in ancestors(node):
                if isinstance(anc, (ast.With, ast.AsyncWith)):
                    for item in anc.items:
                        expr = item.context_expr
                        if terminal_name(expr) != lock:
                            continue
                        have = (_receiver_chain(expr.value)
                                if isinstance(expr, ast.Attribute)
                                else None)
                        if want is None or have is None or want == have:
                            return True
            return False

        for node in ast.walk(source.tree):
            if not isinstance(node, ast.Attribute):
                continue
            cls = _enclosing_class(node)
            entry = guarded.get((id(cls) if cls is not None else 0,
                                 node.attr))
            if entry is None:
                continue
            lock, decl_lines = entry
            if node.lineno in decl_lines:
                continue  # the declaring assignment itself
            if under_lock(node, lock):
                continue
            findings.append(source.finding(
                self, node,
                f"'{node.attr}' is guarded by '{lock}' but accessed "
                f"outside any 'with ...{lock}:' block — annotate "
                f"'# tpuperf: allow-unguarded(<reason>)' if this access "
                f"is provably race-free",
            ))
        return findings


@register
class ZoneCoverageRule(Rule):
    """Every declared deterministic zone must match at least one file.

    The R1 zone manifest names paths (``tpu_perf/faults/``,
    ``tpu_perf/spans.py``, ...); a rename or move of the module behind
    one of them would not FAIL anything — the zone would simply stop
    matching and the no-wallclock contract would silently shrink to
    nothing for that subsystem.  This rule makes the shrink loud: a
    zone entry that matches no linted source is a finding anchored at
    the manifest itself (carried from the PR-8 follow-ons: cheap and
    loud).
    """

    id = "R6"
    name = "zone-coverage"
    scope = "tree"

    def check_tree(self, sources: dict[str, Source],
                   manifest: Manifest) -> list[Finding]:
        findings: list[Finding] = []
        for zone in manifest.deterministic_zones:
            hit = any(manifest.zone_matches(zone, rel) for rel in sources)
            if not hit:
                findings.append(_tree_finding(
                    self, manifest.source_path, 1,
                    f"deterministic zone {zone!r} matches no linted file "
                    f"— a renamed or moved module has silently left the "
                    f"no-wallclock contract (update the manifest or "
                    f"restore the path)",
                    zone,
                ))
        return findings
