"""Findings, stable fingerprints, and the baseline file.

A finding's **fingerprint** is what the baseline keys on, so it must
survive unrelated edits: it hashes the rule id, the file's path, the
enclosing scope's qualname, and the *normalized source of the flagged
line* — never the line number.  Adding code above a finding moves its
line but not its fingerprint; changing the flagged line itself (the only
edit that plausibly addresses the finding) retires the old fingerprint,
so a baseline entry can never mask a *different* violation that happens
to land on the same line later.  Identical snippets in one scope are
disambiguated by an occurrence index.

The baseline file is JSON (``{"version": 1, "findings": [...]}``); the
shipped one — ``tpu_perf/analysis/baseline.json`` — is **empty** by
contract: every true positive the analyzer finds in this tree gets
fixed, not baselined (ISSUE 8 dogfood).  The baseline mechanism exists
for downstream forks adopting the linter against an older tree.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one site."""

    rule: str       # rule id, e.g. "R1"
    name: str       # rule name, e.g. "no-wallclock"
    path: str       # repo-relative posix path
    line: int       # 1-based line of the flagged node
    col: int        # 0-based column
    scope: str      # enclosing qualname ("Driver._heartbeat", "<module>")
    message: str
    snippet: str = ""       # normalized source of the flagged line
    fingerprint: str = ""   # stable id (see module docstring)
    baselined: bool = False

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule}({self.name}) {self.message}")


def normalize_snippet(source_line: str) -> str:
    """Whitespace-collapsed source line — the fingerprint's code anchor."""
    return " ".join(source_line.split())


def fingerprint(rule: str, path: str, scope: str, snippet: str,
                occurrence: int = 0) -> str:
    payload = f"{rule}|{path}|{scope}|{snippet}|{occurrence}"
    return hashlib.sha1(payload.encode()).hexdigest()[:12]


def assign_fingerprints(findings: list[Finding]) -> list[Finding]:
    """Fill each finding's fingerprint, numbering duplicates of the same
    (rule, path, scope, snippet) in source order so two identical
    violations in one scope stay distinct baseline entries."""
    seen: dict[tuple, int] = {}
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        key = (f.rule, f.path, f.scope, f.snippet)
        n = seen.get(key, 0)
        seen[key] = n + 1
        out.append(dataclasses.replace(
            f, fingerprint=fingerprint(f.rule, f.path, f.scope, f.snippet, n)
        ))
    return out


def load_baseline(path: str) -> dict[str, dict]:
    """fingerprint -> baseline entry.  A malformed file is a hard error:
    CI silently gating against garbage would pass everything."""
    with open(path) as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or not isinstance(data.get("findings"), list):
        raise ValueError(
            f"baseline {path!r} must be a JSON object with a 'findings' list"
        )
    out: dict[str, dict] = {}
    for entry in data["findings"]:
        if not isinstance(entry, dict) or "fingerprint" not in entry:
            raise ValueError(
                f"baseline {path!r}: every entry needs a 'fingerprint'"
            )
        out[str(entry["fingerprint"])] = entry
    return out


def render_baseline(findings: list[Finding]) -> str:
    """The ``--write-baseline`` artifact: enough context per entry that a
    reviewer can audit what was waived without re-running the linter."""
    entries = [
        {"fingerprint": f.fingerprint, "rule": f.rule, "path": f.path,
         "scope": f.scope, "message": f.message}
        for f in findings
    ]
    return json.dumps({"version": 1, "findings": entries}, indent=2) + "\n"
