"""Rule engine: sources, pragmas, the registry, and the lint pass.

The engine parses every manifest-included file once, hands each rule a
:class:`Source` (text + AST with parent links + pragma table), collects
findings, applies pragma suppressions, fingerprints what remains, and
splits it against the baseline.  Rules are registered declaratively —
``tpu-perf lint --list-rules`` renders the catalog from their docstrings,
so a rule cannot ship undocumented.

Pragma grammar (one per comment, reason REQUIRED)::

    # tpuperf: <directive>(<reason or lock name>)

Directives: ``allow-clock`` (suppresses R1 on its line), ``allow-lockstep``
(R2), ``allow-unguarded`` (R5), ``guarded-by`` (R5's *annotation* — its
argument names the lock attribute protecting the assigned attribute).
Suppressions are never silent: every pragma site is counted and reported
in both output formats, so an audit reads the waivers next to the
findings.  A malformed or unknown directive is itself a finding (rule
``P0``) — a typo'd escape hatch must fail the lint, not silently stop
suppressing.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize

from tpu_perf.analysis.astutil import add_parents
from tpu_perf.analysis.findings import (
    Finding, assign_fingerprints, load_baseline, normalize_snippet,
)
from tpu_perf.analysis.manifest import Manifest

#: pragma comment shape: the pragma must be the WHOLE comment (anchored
#: at its first character), and everything after the marker must parse
#: as directive(argument) — held deliberately rigid so greps stay
#: trivial and prose that merely *mentions* the marker never arms one
PRAGMA_RE = re.compile(r"^#\s*tpuperf:\s*(?P<rest>.*)$")
DIRECTIVE_RE = re.compile(
    r"^(?P<kind>[a-z-]+)\s*\(\s*(?P<arg>[^()]*?)\s*\)\s*$"
)

#: directive -> rule id it suppresses (guarded-by is an annotation, not
#: a suppression; it is consumed by R5 directly)
SUPPRESS_KINDS = {
    "allow-clock": "R1",
    "allow-lockstep": "R2",
    "allow-unguarded": "R5",
}
KNOWN_KINDS = frozenset(SUPPRESS_KINDS) | {"guarded-by"}


@dataclasses.dataclass(frozen=True)
class Pragma:
    path: str
    line: int
    kind: str
    arg: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Source:
    """One parsed file: what every per-file rule receives."""

    relpath: str          # posix-relative to the lint root
    text: str
    tree: ast.Module
    lines: list[str]
    pragmas: dict[int, list[Pragma]]  # line -> pragmas on that line

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule, node: ast.AST, message: str) -> Finding:
        from tpu_perf.analysis.astutil import scope_qualname

        return Finding(
            rule=rule.id, name=rule.name, path=self.relpath,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            scope=scope_qualname(node), message=message,
            snippet=normalize_snippet(
                self.line_text(getattr(node, "lineno", 1))
            ),
        )

    def pragmas_of_kind(self, kind: str) -> list[Pragma]:
        return [p for ps in self.pragmas.values() for p in ps
                if p.kind == kind]

    def is_comment_only_line(self, lineno: int) -> bool:
        return self.line_text(lineno).lstrip().startswith("#")

    def suppressed(self, kind: str, lineno: int) -> Pragma | None:
        """The pragma of ``kind`` covering ``lineno``: inline on the line
        itself, or STANDALONE (comment-only line) directly above.  An
        inline pragma must never bleed onto the next line — each waiver
        covers exactly the one site its author audited."""
        for p in self.pragmas.get(lineno, ()):
            if p.kind == kind:
                return p
        if self.is_comment_only_line(lineno - 1):
            for p in self.pragmas.get(lineno - 1, ()):
                if p.kind == kind:
                    return p
        return None


def scan_pragmas(relpath: str, text: str) -> tuple[dict[int, list[Pragma]],
                                                   list[Finding]]:
    """Tokenize-based comment scan (never matches string contents).
    Returns (line -> pragmas, grammar findings)."""
    pragmas: dict[int, list[Pragma]] = {}
    findings: list[Finding] = []

    def bad(line: int, col: int, msg: str) -> None:
        findings.append(Finding(
            rule="P0", name="pragma", path=relpath, line=line, col=col,
            scope="<module>", message=msg,
            snippet=normalize_snippet(text.splitlines()[line - 1]
                                      if line <= len(text.splitlines())
                                      else ""),
        ))

    try:
        tokens = tokenize.generate_tokens(io.StringIO(text).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = PRAGMA_RE.search(tok.string)
            if not m:
                continue
            line, col = tok.start
            dm = DIRECTIVE_RE.match(m.group("rest"))
            if not dm:
                bad(line, col, "malformed pragma: expected "
                    "'# tpuperf: <directive>(<reason>)'")
                continue
            kind, arg = dm.group("kind"), dm.group("arg")
            if kind not in KNOWN_KINDS:
                bad(line, col,
                    f"unknown pragma directive {kind!r} "
                    f"(known: {', '.join(sorted(KNOWN_KINDS))})")
                continue
            if not arg:
                bad(line, col, f"pragma '{kind}' requires a "
                    f"{'lock name' if kind == 'guarded-by' else 'reason'}")
                continue
            pragmas.setdefault(line, []).append(
                Pragma(path=relpath, line=line, kind=kind, arg=arg)
            )
    except (tokenize.TokenError, SyntaxError):
        # IndentationError (a SyntaxError subclass) included: tokenize
        # raises it on bad dedents.  The parse rule reports the
        # underlying syntax problem as a P1 finding either way.
        pass
    return pragmas, findings


class Rule:
    """Base rule.  ``scope`` is ``"file"`` (check(source, manifest) per
    parsed file) or ``"tree"`` (check_tree(sources, manifest) once)."""

    id: str = ""
    name: str = ""
    scope: str = "file"

    def check(self, source: Source, manifest: Manifest) -> list[Finding]:
        return []

    def check_tree(self, sources: dict[str, Source],
                   manifest: Manifest) -> list[Finding]:
        return []

    @classmethod
    def doc(cls) -> str:
        return (cls.__doc__ or "").strip()


_REGISTRY: dict[str, Rule] = {}


def register(rule_cls: type[Rule]) -> type[Rule]:
    rule = rule_cls()
    for key in (rule.id, rule.name):
        if key in _REGISTRY:
            raise ValueError(f"duplicate rule registration: {key}")
    _REGISTRY[rule.id] = rule
    _REGISTRY[rule.name] = rule
    return rule_cls


def all_rules() -> list[Rule]:
    import tpu_perf.analysis.rules  # noqa: F401 — registers the rules

    seen, out = set(), []
    for rule in _REGISTRY.values():
        if rule.id not in seen:
            seen.add(rule.id)
            out.append(rule)
    return sorted(out, key=lambda r: r.id)


def resolve_rules(selectors: list[str] | None) -> list[Rule]:
    """Rule selection for ``--rule`` (ids or names, comma-splittable)."""
    if not selectors:
        return all_rules()
    all_rules()  # ensure the registry is populated
    out, seen = [], set()
    for sel in selectors:
        for token in sel.split(","):
            token = token.strip()
            if not token:
                continue
            rule = _REGISTRY.get(token)
            if rule is None:
                known = ", ".join(r.id + "/" + r.name for r in all_rules())
                raise ValueError(f"unknown rule {token!r} (known: {known})")
            if rule.id not in seen:
                seen.add(rule.id)
                out.append(rule)
    if not out:
        # a selector that dissolves to nothing (--rule ",") must not
        # silently run zero checks and report the tree clean
        raise ValueError(f"--rule {selectors!r} selected no rules")
    return out


def collect_files(manifest: Manifest) -> list[str]:
    """Manifest include/exclude globs -> sorted relative posix paths."""
    import glob

    root = manifest.root
    found: set[str] = set()
    for pattern in manifest.include:
        for path in glob.glob(os.path.join(root, pattern), recursive=True):
            if not os.path.isfile(path):
                continue
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if "__pycache__" in rel:
                continue
            found.add(rel)
    def glob_re(pattern: str):
        # glob where '*'/'?' stay INSIDE a path segment and only '**'
        # crosses '/' — fnmatch's '*' matches '/' and would let
        # "pkg/gen*" silently swallow pkg/gen/tool.py (and "a*.py" a
        # whole subtree), shrinking coverage with no finding
        out, i = [], 0
        while i < len(pattern):
            c = pattern[i]
            if c == "*":
                if pattern[i:i + 2] == "**":
                    out.append(".*")
                    i += 2
                    continue
                out.append("[^/]*")
            elif c == "?":
                out.append("[^/]")
            else:
                out.append(re.escape(c))
            i += 1
        return re.compile("".join(out) + r"\Z")

    def excluded(rel: str, pattern: str) -> bool:
        # segment-safe glob match, or a directory prefix WITH its '/'
        # boundary — never a bare string prefix ("pkg/gen" must not
        # silently drop pkg/genuine.py from coverage)
        if glob_re(pattern).match(rel):
            return True
        prefix = pattern.rstrip("*")
        return prefix.endswith("/") and rel.startswith(prefix)

    for pattern in manifest.exclude:
        found = {rel for rel in found if not excluded(rel, pattern)}
    return sorted(found)


def parse_source(root: str, relpath: str) -> tuple[Source | None,
                                                   list[Finding]]:
    path = os.path.join(root, relpath)
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except OSError as e:
        return None, [Finding(
            rule="P1", name="parse", path=relpath, line=1, col=0,
            scope="<module>", message=f"unreadable: {e}",
        )]
    pragmas, findings = scan_pragmas(relpath, text)
    try:
        tree = ast.parse(text, filename=relpath)
    except SyntaxError as e:
        findings.append(Finding(
            rule="P1", name="parse", path=relpath, line=e.lineno or 1,
            col=e.offset or 0, scope="<module>",
            message=f"syntax error: {e.msg}",
        ))
        return None, findings
    add_parents(tree)
    return Source(relpath=relpath, text=text, tree=tree,
                  lines=text.splitlines(), pragmas=pragmas), findings


@dataclasses.dataclass
class LintResult:
    root: str
    rules: list[Rule]
    findings: list[Finding]          # unsuppressed, fingerprinted, sorted
    suppressed: list[dict]           # {"finding": ..., "pragma": ...}
    pragmas: list[Pragma]            # every pragma site in the tree
    files: list[str]
    baseline_path: str | None = None
    baseline_stale: list[str] = dataclasses.field(default_factory=list)

    @property
    def unbaselined(self) -> list[Finding]:
        return [f for f in self.findings if not f.baselined]


def lint_tree(
    root: str,
    manifest: Manifest,
    *,
    rules: list[Rule] | None = None,
    baseline_path: str | None = None,
) -> LintResult:
    """The whole pass: scan, check, suppress, fingerprint, baseline."""
    import tpu_perf.analysis.rules  # noqa: F401 — registers the rules

    active = rules if rules is not None else all_rules()
    files = collect_files(manifest)
    sources: dict[str, Source] = {}
    raw: list[Finding] = []
    all_pragmas: list[Pragma] = []
    for rel in files:
        src, findings = parse_source(root, rel)
        raw.extend(findings)
        if src is not None:
            sources[rel] = src
            all_pragmas.extend(p for ps in src.pragmas.values() for p in ps)
    for rule in active:
        if rule.scope == "file":
            for src in sources.values():
                raw.extend(rule.check(src, manifest))
        else:
            raw.extend(rule.check_tree(sources, manifest))

    kept: list[Finding] = []
    waived: list[tuple[Finding, Pragma]] = []
    for f in raw:
        kind = next((k for k, rid in SUPPRESS_KINDS.items()
                     if rid == f.rule), None)
        src = sources.get(f.path)
        pragma = src.suppressed(kind, f.line) if src and kind else None
        if pragma is not None:
            waived.append((f, pragma))
        else:
            kept.append(f)
    kept = assign_fingerprints(kept)
    # suppressed findings are fingerprinted too (among themselves, so a
    # waiver-auditing consumer can key and diff them across runs) —
    # SEPARATELY from the kept set, so adding or removing a pragma at
    # one site never renumbers a kept finding's baseline identity
    waived_fps = assign_fingerprints([f for f, _ in waived])
    suppressed = [
        {"finding": f.to_dict(), "pragma": p.to_dict()}
        for f, (_, p) in zip(waived_fps, sorted(
            waived, key=lambda fp: (fp[0].path, fp[0].line, fp[0].col,
                                    fp[0].rule)))
    ]

    stale: list[str] = []
    if baseline_path is not None:
        baseline = load_baseline(baseline_path)
        live = {f.fingerprint for f in kept}
        kept = [dataclasses.replace(f, baselined=f.fingerprint in baseline)
                for f in kept]
        stale = sorted(set(baseline) - live)
    return LintResult(
        root=root, rules=active, findings=kept, suppressed=suppressed,
        pragmas=sorted(all_pragmas, key=lambda p: (p.path, p.line)),
        files=files, baseline_path=baseline_path, baseline_stale=stale,
    )


# ---------------------------------------------------------------- output

#: machine-consumption contract for --format json (docs/design.md
#: "Static analysis & invariant linting" documents it); bump on any
#: breaking shape change
JSON_SCHEMA_VERSION = 1


def render_json(result: LintResult) -> str:
    data = {
        "version": JSON_SCHEMA_VERSION,
        "root": result.root,
        "rules": [{"id": r.id, "name": r.name} for r in result.rules],
        "files": len(result.files),
        "findings": [f.to_dict() for f in result.findings],
        "suppressed": result.suppressed,
        "pragmas": [p.to_dict() for p in result.pragmas],
        "baseline": {
            "path": result.baseline_path,
            "matched": sum(1 for f in result.findings if f.baselined),
            "stale": result.baseline_stale,
        },
        "summary": {
            "files": len(result.files),
            "findings": len(result.findings),
            "unbaselined": len(result.unbaselined),
            "suppressed": len(result.suppressed),
        },
    }
    return json.dumps(data, indent=2, sort_keys=True) + "\n"


def render_text(result: LintResult) -> str:
    out = io.StringIO()
    for f in result.findings:
        mark = " [baselined]" if f.baselined else ""
        print(f.render() + mark, file=out)
    by_kind: dict[str, int] = {}
    for p in result.pragmas:
        by_kind[p.kind] = by_kind.get(p.kind, 0) + 1
    pragma_note = ", ".join(f"{k} x{n}" for k, n in sorted(by_kind.items()))
    print(
        f"{len(result.files)} file(s), "
        f"{len(result.unbaselined)} finding(s) "
        f"({sum(1 for f in result.findings if f.baselined)} baselined, "
        f"{len(result.suppressed)} pragma-suppressed"
        + (f"; pragmas: {pragma_note}" if pragma_note else "")
        + ")",
        file=out,
    )
    if result.baseline_stale:
        print(
            f"note: {len(result.baseline_stale)} stale baseline entr"
            f"{'y' if len(result.baseline_stale) == 1 else 'ies'} "
            f"(fixed or moved): {', '.join(result.baseline_stale)}",
            file=out,
        )
    return out.getvalue()


def render_rule_catalog() -> str:
    """--list-rules: the per-rule docs, from the docstrings."""
    import tpu_perf.analysis.rules  # noqa: F401 — registers the rules

    out = io.StringIO()
    for rule in all_rules():
        print(f"{rule.id} ({rule.name})", file=out)
        for line in rule.doc().splitlines():
            print(f"    {line.rstrip()}", file=out)
        print(file=out)
    return out.getvalue()
