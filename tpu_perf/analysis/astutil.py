"""Shared AST plumbing for the invariant rules.

Everything here is stdlib-``ast`` only (the analyzer must run in any
environment the package itself runs in, including the bare CI image —
no third-party parser).  The helpers are deliberately *syntactic*:
alias-aware dotted-name resolution, parent links, enclosing-scope
qualnames, and a small intra-function taint pass.  They trade soundness
for zero-configuration usefulness — a rule that needs to see through a
helper call uses the pragma escape hatch, not a whole-program analysis.
"""

from __future__ import annotations

import ast

_PARENT = "_tpuperf_parent"


def add_parents(tree: ast.AST) -> ast.AST:
    """Attach a parent link to every node (walkable with :func:`parent`)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            setattr(child, _PARENT, node)
    return tree


def parent(node: ast.AST) -> ast.AST | None:
    return getattr(node, _PARENT, None)


def ancestors(node: ast.AST):
    """Yield parents innermost-first, up to (and including) the Module."""
    cur = parent(node)
    while cur is not None:
        yield cur
        cur = parent(cur)


def enclosing_function(node: ast.AST) -> ast.AST | None:
    """Nearest enclosing FunctionDef/AsyncFunctionDef, else None."""
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def scope_qualname(node: ast.AST) -> str:
    """Dotted enclosing-scope name (``Class.method`` / ``<module>``) —
    part of the finding fingerprint, so a finding keeps its identity when
    unrelated code above it shifts line numbers."""
    names: list[str] = []
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            names.append(anc.name)
    return ".".join(reversed(names)) or "<module>"


def import_aliases(tree: ast.AST) -> dict[str, str]:
    """Local name -> dotted origin for every import in the module.

    ``import time as _time`` maps ``_time -> time``; ``from datetime
    import datetime`` maps ``datetime -> datetime.datetime`` — so a
    banned call resolves to the same canonical dotted name however the
    module spelled the import.
    """
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                target = a.name if a.asname else a.name.split(".")[0]
                aliases[local] = target
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted_name(node: ast.AST, aliases: dict[str, str]) -> str | None:
    """Resolve a Name/Attribute chain to a canonical dotted string
    (``np.random.rand`` under ``import numpy as np`` ->
    ``numpy.random.rand``); None for anything not a plain chain
    (a call result, a subscript)."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None
    parts.append(aliases.get(cur.id, cur.id))
    return ".".join(reversed(parts))


def terminal_name(node: ast.AST) -> str | None:
    """The last segment of a Name/Attribute chain (``self.rank`` ->
    ``rank``) — how rank-source and collective matching stays robust to
    the receiver's spelling."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def assigned_names(target: ast.AST) -> set[str]:
    """Plain local names bound by an assignment target (tuples unpacked;
    attribute/subscript targets are skipped — ``self.t = clock()`` binds
    no local name and must not taint ``self`` itself)."""
    out: set[str] = set()
    if isinstance(target, ast.Name):
        out.add(target.id)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            out |= assigned_names(elt)
    elif isinstance(target, ast.Starred):
        out |= assigned_names(target.value)
    return out


class TaintChecker:
    """Is an expression derived from rank-local or timing state?

    Seeds: any Name/Attribute whose terminal segment is a declared rank
    source, any call of a banned clock (canonical dotted name) or of a
    declared injectable-clock parameter name, plus function-local names
    assigned from such expressions (one intra-function fixed point over
    simple assignments — enough to catch ``t = perf_clock(); if t > x:``
    without whole-program dataflow).
    """

    def __init__(self, rank_names: frozenset[str],
                 clock_calls: frozenset[str],
                 clock_params: frozenset[str],
                 aliases: dict[str, str],
                 tainted_callees: frozenset[str] = frozenset()):
        self.rank_names = rank_names
        self.clock_calls = clock_calls
        self.clock_params = clock_params
        self.aliases = aliases
        #: function names whose RETURN value carries taint (the
        #: one-level interprocedural summary — return_taint_summary);
        #: calls of these names seed taint like a direct source
        self.tainted_callees = tainted_callees

    def seeded(self, expr: ast.AST, tainted: frozenset[str]) -> bool:
        """True when ``expr`` contains a taint source or tainted name."""
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and node.id in tainted:
                return True
            term = terminal_name(node)
            if term is not None and term in self.rank_names:
                return True
            if isinstance(node, ast.Call):
                dotted = dotted_name(node.func, self.aliases)
                callee = terminal_name(node.func)
                if dotted in self.clock_calls:
                    return True
                if (callee in self.clock_params
                        or callee in self.rank_names
                        or callee in self.tainted_callees):
                    return True
        return False

    def with_summaries(self, tree: ast.AST) -> "TaintChecker":
        """A checker that additionally treats calls of this module's
        taint-returning helpers as sources (one-level interprocedural
        summary — see :func:`return_taint_summary`).  Returns ``self``
        when the module defines no such helper, so the common case pays
        nothing."""
        summary = return_taint_summary(tree, self)
        if not summary:
            return self
        return TaintChecker(
            rank_names=self.rank_names, clock_calls=self.clock_calls,
            clock_params=self.clock_params, aliases=self.aliases,
            tainted_callees=self.tainted_callees | summary,
        )

    def tainted_names(self, func: ast.AST) -> frozenset[str]:
        """Fixed point of function-local names carrying taint."""
        tainted: set[str] = set()
        args = getattr(func, "args", None)
        if args is not None:
            for a in (args.posonlyargs + args.args + args.kwonlyargs
                      + ([args.vararg] if args.vararg else [])
                      + ([args.kwarg] if args.kwarg else [])):
                if a.arg in self.rank_names:
                    tainted.add(a.arg)
        assigns: list[tuple[set[str], ast.AST]] = []
        for node in ast.walk(func):
            if isinstance(node, ast.Assign):
                names = set()
                for t in node.targets:
                    names |= assigned_names(t)
                assigns.append((names, node.value))
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) \
                    and node.value is not None:
                assigns.append((assigned_names(node.target), node.value))
            elif isinstance(node, ast.NamedExpr):
                assigns.append((assigned_names(node.target), node.value))
        for _ in range(len(assigns) + 1):  # bounded fixed point
            grew = False
            frozen = frozenset(tainted)
            for names, value in assigns:
                if not names <= tainted and self.seeded(value, frozen):
                    tainted |= names
                    grew = True
            if not grew:
                break
        return frozenset(tainted)


def return_taint_summary(tree: ast.AST,
                         checker: TaintChecker) -> frozenset[str]:
    """One-level interprocedural taint: the names of this module's
    functions whose RETURN value derives from a rank/timing source
    (``def _lucky(self): return self.rank``).  A caller conditioning a
    collective on such a helper's result launders rank state past a
    purely intra-function walk; registering the helper as a taint
    SOURCE closes that hole without whole-program dataflow (the PR-8
    follow-on).

    Deliberately ONE level and module-local: the summary pass itself
    sees only direct sources — a helper returning another helper's
    result, or a helper imported from elsewhere, still needs its own
    direct source (or an allow-lockstep pragma at the call site) to
    register.  Matching is by bare function name, consistent with how
    collective and rank-source names match (``terminal_name``).
    Requires the tree to carry parent links (:func:`add_parents`)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        local = checker.tainted_names(node)
        for stmt in ast.walk(node):
            if (isinstance(stmt, ast.Return) and stmt.value is not None
                    and enclosing_function(stmt) is node
                    and checker.seeded(stmt.value, local)):
                out.add(node.name)
                break
    return frozenset(out)
