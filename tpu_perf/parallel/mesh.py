"""Named-mesh construction over ICI/DCN.

The TPU analogue of the reference's transport layer (SURVEY.md §1 L1): where
mpi-perf selects IB vs TCP via UCX env vars in the run scripts
(run-ib.sh:25-26, run-hbv3.sh:25-27), the TPU framework selects how the
device mesh maps onto the interconnect:

* a single-slice mesh axis rides **ICI**;
* a leading multi-slice axis (one element per slice / per host group) rides
  **DCN** — `jax.sharding.Mesh` with axis names like ``("dcn", "ici")``,
  hierarchical collectives by doing the op per-axis.

For tests and the driver's dry-run, ``claim_cpu_devices`` implements the
``--xla_force_host_platform_device_count`` trick (SURVEY.md §4).
"""

from __future__ import annotations

import math
import os
import re

import jax
from jax.sharding import Mesh


def claim_cpu_devices(n: int) -> bool:
    """Force this process onto exactly ``n`` virtual CPU devices.

    An image sitecustomize may force-register a single-chip TPU plugin,
    overriding ``JAX_PLATFORMS=cpu`` from the environment; the platform
    cannot be changed once a backend is initialized, so this must run
    before the first ``jax.devices()`` call.  Any pre-existing
    ``--xla_force_host_platform_device_count`` is replaced — the caller
    states the count it wants, and a leftover different count would
    surface later as confusing mesh-shape/fixture failures.

    Returns True if the CPU claim was applied, False if a backend was
    already initialized (in which case nothing is touched — the flags
    could no longer take effect and would only pollute the environment
    of child processes).  Used by tests/conftest.py and
    ``__graft_entry__.dryrun_multichip``.
    """
    try:
        initialized = bool(jax._src.xla_bridge._backends)
    except AttributeError as e:
        # Can't prove the backend is uninitialized (private attribute moved
        # in a JAX upgrade).  Mutating env here could silently misfire, and
        # returning False would misreport "already initialized" — fail loud
        # so the probe gets updated.
        raise RuntimeError(
            "cannot determine whether the JAX backend is initialized "
            "(jax._src.xla_bridge._backends moved?) — update "
            "claim_cpu_devices for this JAX version"
        ) from e
    if initialized:
        return False
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m and int(m.group(1)) != n:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n}"
        )
    elif not m:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    return True


def make_mesh(
    shape: tuple[int, ...] = (),
    axis_names: tuple[str, ...] = (),
    *,
    devices: list | None = None,
) -> Mesh:
    """Build a named Mesh.

    With no shape, all available devices go on a single ``"x"`` axis (the
    flat one-slice case).  Shapes may use ``-1`` for one inferred dimension.
    A leading axis intended for DCN should be named ``"dcn"`` by convention;
    profiles in scripts/ follow it.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if not shape:
        shape, axis_names = (n,), ("x",)
    if len(shape) != len(axis_names):
        raise ValueError(f"shape {shape} / axis_names {axis_names} length mismatch")
    shape = tuple(shape)
    if shape.count(-1) > 1:
        raise ValueError(f"at most one -1 in mesh shape, got {shape}")
    if -1 in shape:
        known = math.prod(s for s in shape if s != -1)
        if known == 0 or n % known:
            raise ValueError(f"cannot infer -1 in {shape} over {n} devices")
        shape = tuple(n // known if s == -1 else s for s in shape)
    if math.prod(shape) != n:
        raise ValueError(f"mesh shape {shape} does not cover {n} devices")
    import numpy as np

    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def mesh_devices_flat(mesh: Mesh) -> list:
    """Devices of a mesh in row-major mesh order (the order ppermute indices
    refer to when using a single flattened axis)."""
    return list(mesh.devices.flat)
