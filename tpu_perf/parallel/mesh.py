"""Named-mesh construction over ICI/DCN.

The TPU analogue of the reference's transport layer (SURVEY.md §1 L1): where
mpi-perf selects IB vs TCP via UCX env vars in the run scripts
(run-ib.sh:25-26, run-hbv3.sh:25-27), the TPU framework selects how the
device mesh maps onto the interconnect:

* a single-slice mesh axis rides **ICI**;
* a leading multi-slice axis (one element per slice / per host group) rides
  **DCN** — `jax.sharding.Mesh` with axis names like ``("dcn", "ici")``,
  hierarchical collectives by doing the op per-axis.

For tests and the driver's dry-run, ``virtual_cpu_devices`` documents the
``--xla_force_host_platform_device_count`` trick (SURVEY.md §4).
"""

from __future__ import annotations

import math
import os
import re

import jax
from jax.sharding import Mesh


def virtual_cpu_devices(n: int) -> None:
    """Arrange for ``n`` virtual CPU devices.  Must be called before JAX is
    initialized (i.e. before any ``jax.devices()`` call).  Raises ValueError
    if ``XLA_FLAGS`` already forces a *different* device count (a silent
    no-op there would surface later as a confusing mesh-shape error)."""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m:
        have = int(m.group(1))
        if have != n:
            raise ValueError(
                f"XLA_FLAGS already forces {have} host devices, wanted {n}"
            )
        return
    os.environ["XLA_FLAGS"] = (flags + f" --xla_force_host_platform_device_count={n}").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def make_mesh(
    shape: tuple[int, ...] = (),
    axis_names: tuple[str, ...] = (),
    *,
    devices: list | None = None,
) -> Mesh:
    """Build a named Mesh.

    With no shape, all available devices go on a single ``"x"`` axis (the
    flat one-slice case).  Shapes may use ``-1`` for one inferred dimension.
    A leading axis intended for DCN should be named ``"dcn"`` by convention;
    profiles in scripts/ follow it.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if not shape:
        shape, axis_names = (n,), ("x",)
    if len(shape) != len(axis_names):
        raise ValueError(f"shape {shape} / axis_names {axis_names} length mismatch")
    shape = tuple(shape)
    if shape.count(-1) > 1:
        raise ValueError(f"at most one -1 in mesh shape, got {shape}")
    if -1 in shape:
        known = math.prod(s for s in shape if s != -1)
        if known == 0 or n % known:
            raise ValueError(f"cannot infer -1 in {shape} over {n} devices")
        shape = tuple(n // known if s == -1 else s for s in shape)
    if math.prod(shape) != n:
        raise ValueError(f"mesh shape {shape} does not cover {n} devices")
    import numpy as np

    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def mesh_devices_flat(mesh: Mesh) -> list:
    """Devices of a mesh in row-major mesh order (the order ppermute indices
    refer to when using a single flattened axis)."""
    return list(mesh.devices.flat)
