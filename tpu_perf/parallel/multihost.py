"""Multi-host (multi-process) support: DCN-aware meshes and cross-host stats.

The reference scales across hosts with ``mpirun`` + per-rank processes and
aggregates timings with ``MPI_Allreduce`` (mpi_perf.c:560-562).  The JAX
equivalents:

* one controller process per host, joined via ``jax.distributed.initialize``
  (coordinator address from env or flags) — ICI inside a host/slice, DCN
  between them;
* a hybrid mesh whose leading ``"dcn"`` axis spans slices/hosts and whose
  trailing ``"ici"`` axis spans the chips inside one
  (``mesh_utils.create_hybrid_device_mesh``), so `hier_allreduce` and the
  DCN-axis collectives ride the right links;
* min/max/avg across *processes* via a tiny jitted ``psum`` on a
  process-spanning mesh — the Allreduce triple, but over DCN.

Single-process runs (and the CPU test mesh) take the no-op paths, so every
call here is safe to use unconditionally.
"""

from __future__ import annotations

import numbers

import jax
import numpy as np
from jax.sharding import Mesh


def initialize_distributed(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the multi-host job.  Must run before anything initializes the
    XLA backend (the CLI calls it before building the mesh).

    With no arguments, JAX auto-detects the cluster (TPU pod metadata on
    GCE, SLURM, coordinator env vars...); arguments override for manual
    setups, mirroring how the reference's mpirun passes rank/size via env.
    A machine with no detectable cluster falls back to single-process with
    a warning rather than crashing — so profiles can pass --distributed
    unconditionally.  Idempotent: a second call is a no-op (checked via
    the distributed client state, NOT jax.process_count(), which would
    itself initialize the backend and poison a later initialize()).
    """
    try:
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return  # already joined
    except ImportError:  # pragma: no cover - private module moved
        pass
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (RuntimeError, ValueError) as e:
        msg = str(e).lower()
        if "already" in msg:
            return
        explicit = coordinator is not None or num_processes is not None
        if not explicit and (
            "coordinator_address" in msg  # no cluster detected
            or "detect" in msg
            or "must be called before" in msg  # backend already up, no args:
            # a best-effort auto-join after init just stays single-process
        ):
            import sys

            print(
                "[tpu-perf] not joining a multi-host cluster; running "
                f"single-process ({e})",
                file=sys.stderr,
            )
            return
        raise


def make_hybrid_mesh(*, dcn_axis: str = "dcn", ici_axis: str = "ici") -> Mesh:
    """(dcn, ici) mesh: leading axis spans processes/slices (DCN), trailing
    axis the chips within one (ICI).

    Single-process: dcn axis has size 1, so the same code path (and the
    same ``hier_allreduce`` kernel) runs everywhere.
    """
    n_slices = max(1, jax.process_count())
    devices = jax.devices()
    if len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices do not divide evenly over {n_slices} "
            "processes — a degraded pod cannot form a (dcn, ici) mesh"
        )
    per_slice = len(devices) // n_slices
    if n_slices > 1:
        try:
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_hybrid_device_mesh(
                (per_slice,), (n_slices,), devices=devices
            )
            return Mesh(arr.reshape(n_slices, per_slice), (dcn_axis, ici_axis))
        except (ImportError, ValueError, AssertionError) as e:
            import sys

            print(
                f"[tpu-perf] hybrid mesh layout unavailable ({e}); using "
                "process-ordered device layout",
                file=sys.stderr,
            )
    arr = np.asarray(devices).reshape(n_slices, per_slice)
    return Mesh(arr, (dcn_axis, ici_axis))


def exchange_ips(ip: str) -> list[str]:
    """Allgather of per-process IP strings, indexed by process id — the
    reference's rank-card ``MPI_Allgather`` that backs peer discovery
    (mpi_perf.c:204-224).  Single-process: ``[ip]``."""
    n = max(1, jax.process_count())
    if n == 1:
        return [ip]
    from jax.experimental import multihost_utils

    buf = np.zeros(16, np.uint8)
    raw = ip.encode()[:16]
    buf[: len(raw)] = np.frombuffer(raw, np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(buf)).reshape(n, 16)
    return [bytes(row[row != 0]).decode() for row in gathered]


def allreduce_times(
    t_seconds: float | list[float],
) -> dict[str, float]:
    """The reference's MPI_Allreduce MIN/MAX/SUM triple (mpi_perf.c:560-562)
    across processes, over one sample or a whole stats window.

    A window is reduced LOCALLY to its (min, max, avg) first, so exactly
    three scalars cross the wire no matter the window length — the
    cross-host triple then covers every sample of every host's window
    (the reference reduces per run; reducing only the last sample gave a
    1000-run window a single-run cross-host signal, VERDICT r4 weak #3).
    The cross-host ``avg`` is the mean of the per-host averages — exact
    when hosts have equal valid-sample counts, the honest approximation
    when drops make them unequal (each host's health weighs equally,
    which is the fleet-monitoring reading).  Single-process: returns the
    local triple.

    A process with no data for this boundary passes NaN (or an empty
    window): it still enters the collective (skipping would deadlock the
    other processes) but its contribution is excluded from the triple
    instead of reading as a catastrophic-fast 0.0 outlier.  All-NaN
    returns NaNs.
    """
    # any real scalar counts as a single sample — numpy scalars included
    # (np.float32 is not a Python float, and a bare isinstance((int,
    # float)) check used to fall through to list(np.float64(...)), which
    # crashes; the adaptive controller's lockstep stop-vote allreduces
    # exactly such scalars).  np.isscalar covers 0-d numpy values the
    # numbers ABC registry misses.
    if isinstance(t_seconds, numbers.Real) or np.isscalar(t_seconds):
        samples = [float(t_seconds)]
    else:
        samples = [float(s) for s in t_seconds]
    valid_local = [s for s in samples if not np.isnan(s)]
    if valid_local:
        local = [min(valid_local), max(valid_local),
                 sum(valid_local) / len(valid_local)]
    else:
        local = [float("nan")] * 3
    n = max(1, jax.process_count())
    if n == 1:
        return {"min": local[0], "max": local[1], "avg": local[2]}
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(np.asarray(local))
    triples = np.asarray(gathered).reshape(n, 3)
    # a host contributes all three or none (NaN row)
    valid = triples[~np.isnan(triples[:, 0])]
    if valid.size == 0:
        nan = float("nan")
        return {"min": nan, "max": nan, "avg": nan}
    return {
        "min": float(valid[:, 0].min()),
        "max": float(valid[:, 1].max()),
        "avg": float(valid[:, 2].mean()),
    }
