"""Mesh construction and device-topology mapping (ICI/DCN)."""

from tpu_perf.parallel.mesh import (  # noqa: F401
    claim_cpu_devices,
    make_mesh,
    mesh_devices_flat,
)
from tpu_perf.parallel.multihost import (  # noqa: F401
    allreduce_times,
    exchange_ips,
    initialize_distributed,
    make_hybrid_mesh,
)
