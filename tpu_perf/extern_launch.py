"""External client/server launcher mode (the reference's dotnet path).

The reference's ``-d`` flag short-circuits the MPI kernels: each rank builds
a ``dotnet clientserverapp.dll server|client <ip> <port> <flows> <bytes>
<iters> ...`` command line from the pair topology — with the ``system()``
call commented out, so the mode only *prints* the command to stderr while
the run loop still records wall time and CSV rows (mpi_perf.c:147-168,
504-507).  MPI is used purely as a launcher there (SURVEY.md §2 "C1 in
depth", vestigial dotnet mode).

Here the same slot is generalised and kept print-only: a user-supplied
template with placeholders is rendered per process from the two-group pair
topology and written to stderr, never executed.

Placeholders: ``{role}`` (server|client), ``{ip}``, ``{port}``,
``{flows}``, ``{bytes}``, ``{iters}``.  Server rank r advertises its own IP
on ``DEF_PORT + r``; its paired client dials the server's IP and port
(mpi_perf.c:155-165, where group 1 is the server side).
"""

from __future__ import annotations

#: mpi_perf.c:150 — base TCP port; rank r's server listens on DEF_PORT + r.
DEF_PORT = 40000

#: rendered when ``-d`` is passed without a template; same argument shape as
#: the reference's hardwired dotnet command line (mpi_perf.c:155-165).
DEFAULT_TEMPLATE = "extern-bench {role} {ip} {port} {flows} {bytes} {iters}"


def pair_for_rank(rank: int, n_procs: int) -> tuple[int, int]:
    """Two-group positional pairing: ``(group, peer_rank)``.

    The reference splits ranks into two host groups and pairs equal
    group-communicator ranks (mpi_perf.c:200-238); positionally that is
    first half (group 0, clients) vs second half (group 1, servers).
    A single process is its own loopback pair on the server side.
    """
    if n_procs < 2:
        return 1, rank
    if n_procs % 2:
        raise ValueError(
            f"extern mode needs an even process count to form pairs, got {n_procs}"
        )
    half = n_procs // 2
    if rank >= half:
        return 1, rank - half
    return 0, rank + half


def render_extern_command(
    template: str,
    *,
    group: int,
    rank: int,
    peer_rank: int,
    my_ip: str,
    peer_ip: str,
    ppn: int,
    buff_sz: int,
    iters: int,
) -> str:
    """Substitute the pair topology into ``template`` (mpi_perf.c:153-165)."""
    if group == 1:
        role, ip, port = "server", my_ip, DEF_PORT + rank
    else:
        role, ip, port = "client", peer_ip, DEF_PORT + peer_rank
    try:
        return template.format(
            role=role, ip=ip, port=port, flows=ppn, bytes=buff_sz, iters=iters
        )
    except (KeyError, IndexError) as e:
        raise ValueError(
            f"bad placeholder in extern command template {template!r}: {e}"
        ) from None
