"""tpu_perf — TPU-native, backend-pluggable communication benchmark framework.

A ground-up TPU re-design of the capabilities of jithinjosepkl/mpi-perf
(reference: /root/reference/mpi_perf.c): timed message-size sweeps over
point-to-point ping-pong and collective patterns, a fleet network-health
monitoring daemon mode with rotating CSV logs, and a continuous-ingest
telemetry pipeline.  The compute path is JAX/XLA collectives (`psum`,
`all_gather`, `psum_scatter`, `all_to_all`, `ppermute`) under `shard_map`
over a named device mesh (ICI/DCN); the reference's MPI driver survives as
a native C baseline backend under ``backends/mpi/``.

Layer map (mirrors SURVEY.md §1):
  L4 telemetry  -> tpu_perf.ingest
  L3 harness    -> scripts/run-*.sh + tpu_perf.cli
  L2 driver     -> tpu_perf.driver (JAX) and backends/mpi/tpu_mpi_perf.c (C)
  L1 transport  -> tpu_perf.ops (XLA collectives over ICI/DCN) / MPI+UCX
"""

__version__ = "0.1.0"

from tpu_perf.config import Options  # noqa: F401
from tpu_perf.sweep import sweep_sizes, DEF_BUF_SZ, LEGACY_BW_BUF_SZ  # noqa: F401
from tpu_perf.schema import LegacyRow, ResultRow, LEGACY_HEADER, RESULT_HEADER  # noqa: F401
from tpu_perf.metrics import bus_bandwidth_gbps, alg_bandwidth_gbps  # noqa: F401
