"""Profiler-trace parsing: per-execution DEVICE durations.

The round-3 verdict's top gap: on a relayed PJRT runtime the host clock
cannot resolve microsecond kernels (every sample is ±10-20 ms of relay
jitter), so no small-message latency claim below 128 MiB was defensible.
The device's own trace can: ``jax.profiler`` records one "XLA Modules"
event per executable launch on the ``/device:*`` lanes, whose ``dur`` is
the device-side execution time — measured where the kernel runs, immune
to the relay entirely (measured spread on the v5e tunnel: ~0.04% across
repeats vs the host clock's orders-of-magnitude-larger jitter).

This module extracts those durations from the trace-viewer JSON the
profiler writes (``plugins/profile/<ts>/<host>.trace.json.gz``).  The
reference has no analogue — its only clock is host-side ``MPI_Wtime``
(mpi_perf.c:501,532); device-side timing is the TPU-native redesign of
SURVEY §5's "per-sweep-point trace capture" slot.
"""

from __future__ import annotations

import glob
import gzip
import json
import os

#: the profiler thread that carries one event per executable launch
_MODULE_THREAD = "XLA Modules"


class TraceParseError(RuntimeError):
    """The trace exists but its device-side module events are unusable
    (wrong count, inconsistent pairing, ...) — potentially transient."""


class TraceUnavailableError(TraceParseError):
    """The runtime records no device lanes at all (e.g. CPU backends
    trace host events only).  A property of the runtime, not of one
    capture: callers may permanently fall back to host-clock fences."""


class TraceCaptureMissingError(TraceParseError):
    """The capture directory holds no trace files at all — the profiler
    produced nothing, so nothing can be said about device lanes.  A
    distinct type because the availability probe must read it as "trace
    NOT available" (a runtime that writes no capture can never serve
    the trace fence), while a plain TraceParseError from a present
    capture means the lanes exist and only the module match failed."""


def _trace_files(trace_dir: str) -> list[str]:
    """All trace.json.gz files of the NEWEST capture under ``trace_dir``."""
    sessions = sorted(glob.glob(
        os.path.join(trace_dir, "plugins", "profile", "*")
    ))
    if not sessions:
        raise TraceCaptureMissingError(
            f"no profiler capture under {trace_dir!r} (expected "
            "plugins/profile/<timestamp>/)"
        )
    files = sorted(glob.glob(os.path.join(sessions[-1], "*.trace.json.gz")))
    if not files:
        raise TraceCaptureMissingError(
            f"capture {sessions[-1]!r} has no *.trace.json.gz"
        )
    return files


def device_module_durations(
    trace_dir: str,
    name_hint: str | None = None,
) -> list[float]:
    """Device-side durations (seconds) of executable launches, in launch
    order.

    ``name_hint`` filters module events whose name contains it (the jit
    name, e.g. ``tpuperf_hbm_stream`` -> module
    ``jit_tpuperf_hbm_stream(<fingerprint>)``); without a hint, every
    module event on the lane counts.

    Multi-device hosts record one "XLA Modules" lane PER device, each
    with one event per launch; durations are grouped per lane and ONE
    lane's view is returned (the lowest device pid of the first trace
    file — an SPMD module launches once per device, so lumping lanes
    together would multiply the event count and pair wrong durations).

    Raises :class:`TraceUnavailableError` when the runtime records no
    device lanes at all (CPU backends), :class:`TraceParseError` when
    lanes exist but nothing matches the hint — a wrong hint must fail
    loudly rather than time the wrong kernel.
    """
    by_lane: dict[tuple, list[tuple[float, float]]] = {}  # lane -> (ts, dur_s)
    seen_device_lane = False
    seen_names: set[str] = set()
    for path in _trace_files(trace_dir):
        try:
            with gzip.open(path, "rt") as fh:
                data = json.load(fh)
        except (OSError, EOFError, ValueError) as e:
            # a truncated/corrupt capture (disk full mid-write, ...) is a
            # TraceParseError like any other unusable capture — callers
            # with drop-the-sample protection must see the type they
            # handle, not a raw gzip/JSON error
            raise TraceParseError(f"unreadable capture {path!r}: {e}") from e
        events = data.get("traceEvents", [])
        if not isinstance(events, list):
            raise TraceParseError(f"capture {path!r} has no traceEvents list")
        device_pids = set()
        module_tids = set()
        for e in events:
            if e.get("ph") != "M":
                continue
            if e.get("name") == "process_name" and str(
                    e.get("args", {}).get("name", "")).startswith("/device:"):
                device_pids.add(e.get("pid"))
            if e.get("name") == "thread_name" and \
                    e.get("args", {}).get("name") == _MODULE_THREAD:
                module_tids.add((e.get("pid"), e.get("tid")))
        seen_device_lane = seen_device_lane or bool(device_pids)
        for e in events:
            if e.get("ph") != "X" or e.get("pid") not in device_pids:
                continue
            if (e.get("pid"), e.get("tid")) not in module_tids:
                continue
            name = e.get("name", "")
            seen_names.add(name)
            if name_hint is not None and name_hint not in name:
                continue
            try:
                by_lane.setdefault((path, e["pid"]), []).append(
                    (float(e["ts"]), float(e["dur"]) * 1e-6)
                )
            except (KeyError, TypeError, ValueError) as exc:
                raise TraceParseError(
                    f"malformed module event in {path!r}: {e!r}"
                ) from exc
    if not by_lane:
        if not seen_device_lane:
            raise TraceUnavailableError(
                "trace has no /device:* lanes — device-side timing needs a "
                "runtime that records them (TPU); the CPU backend traces "
                "host events only"
            )
        raise TraceParseError(
            f"no module events match name hint {name_hint!r}; "
            f"device modules present: {sorted(seen_names)[:8]}"
        )
    lane = min(by_lane)
    durations = sorted(by_lane[lane])
    return [d for _, d in durations]


def fused_run_durations(
    trace_dir: str,
    name_hint: str,
    num_runs: int,
) -> list[float]:
    """Per-run DEVICE durations (seconds) of one fused-loop dispatch.

    The fused fence (tpu_perf.timing.FusedRunner) batches a sweep
    point's whole budget into one device program — ``num_runs`` chained
    executions of the step body inside an outer ``lax.fori_loop`` — so
    the capture's module-event shape differs from the per-run fences'
    and :func:`device_module_durations` alone cannot label runs.  Two
    recorded shapes are split here:

    * ``num_runs`` matching events — the runtime recorded one device
      event per loop iteration (per-run sub-events): those ARE the
      per-run durations, in launch order, variance preserved.
    * a MULTIPLE of ``num_runs`` matching events — the runtime recorded
      per-ITERATION device events (some runtimes launch each fori_loop
      body iteration as its own module): consecutive groups of
      ``len/num_runs`` events sum to one run's duration, in launch
      order, so per-run variance survives at iteration granularity
      instead of collapsing to the mean.
    * exactly ONE matching event — the whole fused program is a single
      module launch (the standard XLA shape): its duration is split
      evenly, so every run carries the device-side mean.  Per-run
      variance is gone but so is every nanosecond of host/relay time —
      the statistic the headline tables publish (p50/mean over runs) is
      exactly this mean, and the chunked fallback recovers variance at
      chunk granularity when it matters (adaptive stopping).

    Any other count is a parse failure (a dropped launch or a hint
    matching someone else's module would mislabel runs — fail loudly,
    callers fall back to host chunk means).  Raises
    :class:`TraceUnavailableError` via the underlying walk when the
    runtime records no device lanes at all."""
    if num_runs <= 0:
        raise ValueError(f"num_runs must be positive, got {num_runs}")
    durs = device_module_durations(trace_dir, name_hint)
    if len(durs) == num_runs:
        return durs
    if len(durs) == 1:
        return [durs[0] / num_runs] * num_runs
    if len(durs) % num_runs == 0:
        # per-iteration sub-events: sum each run's consecutive group
        # (durations arrive in launch order from the single device
        # lane, so group i IS run i's iterations)
        per_run = len(durs) // num_runs
        return [sum(durs[i * per_run:(i + 1) * per_run])
                for i in range(num_runs)]
    raise TraceParseError(
        f"expected 1, {num_runs}, or a multiple of {num_runs} module "
        f"event(s) for fused hint {name_hint!r}, trace has {len(durs)}"
    )
