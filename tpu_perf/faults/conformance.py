"""Detector-conformance harness: did the health pipeline notice?

``tpu-perf chaos verify <dir>`` replays a chaos run's two durable
artifacts — the injection ledger (``chaos-*.log``) and the emitted
health events (``health-*.log``) — and verdicts every scheduled fault:

* **caught** — a health event of the fault's expected kind
  (spec.EXPECTED_EVENT), matching the fault's point filter, landed
  within the fault's fired-run span plus a grace tail (detectors are
  late by construction: a spike is confirmed by its successor, a
  regression needs EWMA convergence, capture loss fires at the next
  heartbeat boundary — so the default grace is two stats windows);
* **missed** — no such event (including faults that never fired: a
  window the soak never reached is a coverage miss, not a pass);
* **n/a** — jitter entries, which no detector is supposed to alert on.

Corrupt faults are judged from the ledger's ``selftest`` records (the
driver runs the rx-validation pass at exit): FAIL = the corruption was
caught, ok = it slipped through.

Events not attributable to any fault are **false alarms** (``recovered``
events are exempt: they are episode exits that legitimately trail a
fault window).  The per-detector table reports injected/caught/missed/
false-alarm counts with precision and recall — the "provably detects
faults" table the ISSUE asks the health subsystem to earn.
"""

from __future__ import annotations

import dataclasses
import json

from tpu_perf.faults.spec import EXPECTED_EVENT, FaultSpec, parse_spec
from tpu_perf.health.events import HealthEvent, read_jsonl
from tpu_perf.schema import base_op


def read_ledger(paths, *, err=None) -> list[dict]:
    """Parse JSONL chaos records through the family's own record class
    (schema.JsonlRecord — ONE parser per contract, so a torn-line or
    discriminator fix reaches verify too); torn-final-line policy shared
    with the health replay (health.events.read_jsonl — a killed soak can
    tear its last append; corruption anywhere else raises)."""
    from tpu_perf.faults.spec import ChaosRecord

    return read_jsonl(paths, lambda line: ChaosRecord.from_json(line).data,
                      err=err)


@dataclasses.dataclass(frozen=True)
class FaultVerdict:
    """One spec entry's judgement.

    ``context`` names the harness activity (rotations, ingest passes,
    pipeline builds, probe schedules — trace.export.ACTIVITY_KINDS)
    concurrent with the fault's fired runs, resolved through the span
    stream when the soak ran with ``--spans``: a MISSED fault that
    coincided with an ingest stall reads as exactly that, instead of a
    bare "no event" whose cause needs stderr archaeology."""

    spec_index: int
    fault: FaultSpec
    expected: str | None   # event kind, "selftest", or None (jitter)
    verdict: str           # caught | missed | n/a
    injected: int          # fired ledger records
    first_run: int         # 0 when never fired
    last_run: int
    detail: str
    context: str = ""      # concurrent harness activity ("" = untraced)


@dataclasses.dataclass(frozen=True)
class DetectorScore:
    """Aggregate per detector: the precision/recall row."""

    detector: str
    injected: int
    caught: int
    missed: int
    false_alarms: int

    @property
    def precision(self) -> float | None:
        d = self.caught + self.false_alarms
        return self.caught / d if d else None

    @property
    def recall(self) -> float | None:
        d = self.caught + self.missed
        return self.caught / d if d else None


@dataclasses.dataclass(frozen=True)
class ConformanceReport:
    meta: dict
    verdicts: list[FaultVerdict]
    scores: list[DetectorScore]
    false_alarms: list[HealthEvent]
    events_total: int

    @property
    def missed_critical(self) -> list[FaultVerdict]:
        return [v for v in self.verdicts
                if v.verdict == "missed" and v.fault.critical]


def _event_matches(f: FaultSpec, expected: str, ev: HealthEvent,
                   first: int, last: int, grace: int) -> bool:
    if ev.kind != expected:
        return False
    if not first <= ev.run_id <= last + grace:
        return False
    if f.rank is not None and ev.rank != f.rank and f.kind != "skew":
        # a rank-filtered fault is only caught by the host it degraded:
        # the event's rank column must NAME the sick host, or the
        # "which host" answer the filter exists for was never proven.
        # EXCEPT skew — a latency-coupled fault: staggering rank 1's
        # entry inflates every OTHER rank's observed collective (the
        # victims wait for the straggler), so detection legitimately
        # lands on the victim ranks' rows and any rank's event counts
        return False
    if expected == "hook_fail":
        return True  # not point-scoped (op is the synthetic "ingest_hook")
    # arena soaks key health points on the DECORATED op label
    # (``allreduce[ring]``, skew sweeps ``...@500us``, imbalance
    # sweeps ``...%8``, scenarios ``scenario[<name>]``) while fault
    # specs target the raw op the injector filters on — resolve the
    # base name through the ONE shared parser (schema.parse_op_label
    # via base_op) so an injected fault caught under any algorithm's/
    # spread's/ratio's baseline still counts as caught
    if f.op != "*" and ev.op != f.op and base_op(ev.op) != f.op:
        return False
    if expected == "capture_loss":
        return True  # op-level events carry nbytes=0 by contract
    return f.nbytes == 0 or ev.nbytes == f.nbytes


def _span_context(f: FaultSpec, fired: list[dict],
                  spans: list[dict]) -> str:
    """Concurrent-activity attribution for one fault: the harness
    activity spans (trace.export.ACTIVITY_KINDS) overlapping any of the
    fault's fired runs' span windows — the anomaly-context join
    (trace.anomaly_context), pointed at the LEDGER side so a missed
    fault names what the harness was doing when the detector stayed
    quiet."""
    from tpu_perf.trace.export import activity_label, overlapping_activity

    fired_ids = {int(r["run_id"]) for r in fired if r.get("run_id")}
    if not fired_ids or not spans:
        return ""
    hits: dict[str, str] = {}
    for s in spans:
        if s.get("kind") != "run":
            continue
        attrs = s.get("attrs") or {}
        if attrs.get("run_id") not in fired_ids:
            continue
        if f.op != "*" and attrs.get("op") not in (None, f.op):
            continue
        # one overlap test + one label rendering for the whole stack
        # (the report's anomaly-context table uses the same pair)
        for act in overlapping_activity(spans, s):
            hits[act["span_id"]] = activity_label(act)
    return "; ".join(hits[k] for k in sorted(hits))


def run_conformance(
    records: list[dict],
    events: list[HealthEvent],
    *,
    grace_runs: int | None = None,
    spans: list[dict] | None = None,
) -> ConformanceReport:
    """Join the ledger against the events; judge every scheduled fault.
    ``spans`` (spans.read_span_records of the soak's folder, if it ran
    with --spans) adds concurrent-activity attribution to each missed
    fault's verdict (:func:`_span_context`)."""
    metas = [r for r in records if r.get("record") == "meta"]
    if not metas:
        raise ValueError(
            "no meta record in the chaos ledger — was this folder written "
            "by `tpu-perf chaos`?"
        )
    # one soak writes ONE meta (a multi-rank soak writes one identical
    # meta per rank); distinct metas mean the folder holds ledgers from
    # different soaks, whose fault records would pool under each other's
    # spec indices and run-id space — a garbage join must not be judged
    if len({json.dumps(m, sort_keys=True) for m in metas}) > 1:
        raise ValueError(
            f"{len(metas)} disagreeing meta records: these ledgers mix "
            "more than one chaos soak — point verify at one soak's files "
            "(or clean the log folder between soaks)"
        )
    meta = metas[0]
    stats_every = int(meta.get("stats_every", 1000))
    if grace_runs is None:
        grace_runs = 2 * stats_every
    faults = parse_spec(meta.get("faults", []))
    fired: dict[int, list[dict]] = {}
    for r in records:
        if r.get("record") == "fault":
            fired.setdefault(int(r["spec"]), []).append(r)
    selftests = {r["op"]: r for r in records if r.get("record") == "selftest"}

    verdicts: list[FaultVerdict] = []
    attributed: set[int] = set()  # indices into `events`
    for idx, f in enumerate(faults):
        expected = EXPECTED_EVENT[f.kind]
        recs = fired.get(idx, [])
        runs = sorted(int(r["run_id"]) for r in recs)
        first, last = (runs[0], runs[-1]) if runs else (0, 0)
        if expected is None:
            verdicts.append(FaultVerdict(
                idx, f, None, "n/a", len(recs), first, last,
                "injected noise; no detector should fire",
            ))
            continue
        if expected == "selftest":
            st = selftests.get(f.op)
            if st is None:
                verdict, detail = "missed", "no selftest record in ledger"
            elif st["status"] == "fail":
                verdict, detail = "caught", f"selftest FAIL: {st['detail']}"
            else:
                verdict = "missed"
                detail = f"selftest {st['status']}: corruption slipped through"
            verdicts.append(FaultVerdict(
                idx, f, expected, verdict, len(recs), first, last, detail,
            ))
            continue
        if not recs:
            verdicts.append(FaultVerdict(
                idx, f, expected, "missed", 0, 0, 0,
                "never fired — the soak did not cover this window",
            ))
            continue
        hits = [
            i for i, ev in enumerate(events)
            if _event_matches(f, expected, ev, first, last, grace_runs)
        ]
        attributed.update(hits)
        if hits:
            ev = events[hits[0]]
            verdicts.append(FaultVerdict(
                idx, f, expected, "caught", len(recs), first, last,
                f"{ev.kind} event at run {ev.run_id} "
                f"({ev.severity}, observed {ev.observed:.6g})",
            ))
        else:
            verdicts.append(FaultVerdict(
                idx, f, expected, "missed", len(recs), first, last,
                f"no {expected} event in runs [{first}, {last + grace_runs}]",
                context=_span_context(f, recs, spans or []),
            ))
    # `recovered` events are exempt from false-alarm accounting
    # unconditionally: they are episode exits, not alerts (their entry
    # event is what gets attributed or flagged)
    false_alarms = [
        ev for i, ev in enumerate(events)
        if i not in attributed and ev.kind != "recovered"
    ]

    detectors: dict[str, dict[str, int]] = {}
    for v in verdicts:
        if v.expected is None:
            continue
        d = detectors.setdefault(
            v.expected, {"injected": 0, "caught": 0, "missed": 0, "fp": 0}
        )
        d["injected"] += 1
        if v.verdict == "caught":
            d["caught"] += 1
        elif v.verdict == "missed":
            d["missed"] += 1
    for ev in false_alarms:
        d = detectors.setdefault(
            ev.kind, {"injected": 0, "caught": 0, "missed": 0, "fp": 0}
        )
        d["fp"] += 1
    scores = [
        DetectorScore(k, d["injected"], d["caught"], d["missed"], d["fp"])
        for k, d in sorted(detectors.items())
    ]
    return ConformanceReport(
        meta=meta, verdicts=verdicts, scores=scores,
        false_alarms=false_alarms, events_total=len(events),
    )


def _pct(x: float | None) -> str:
    return "—" if x is None else f"{100.0 * x:.0f}%"


def report_to_markdown(rep: ConformanceReport) -> str:
    lines = [
        "| # | kind | op | size | window | fired | expected | verdict "
        "| detail | concurrent activity |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    from tpu_perf.sweep import format_size

    for v in rep.verdicts:
        f = v.fault
        size = format_size(f.nbytes) if f.nbytes else "*"
        end = f.end if f.end is not None else "∞"
        lines.append(
            f"| {v.spec_index} | {f.kind} | {f.op} | {size} "
            f"| {f.start}-{end} | {v.injected} | {v.expected or '—'} "
            f"| {v.verdict} | {v.detail} | {v.context or '—'} |"
        )
    lines += [
        "",
        "| detector | injected | caught | missed | false alarms "
        "| precision | recall |",
        "|---|---|---|---|---|---|---|",
    ]
    for s in rep.scores:
        lines.append(
            f"| {s.detector} | {s.injected} | {s.caught} | {s.missed} "
            f"| {s.false_alarms} | {_pct(s.precision)} | {_pct(s.recall)} |"
        )
    caught = sum(1 for v in rep.verdicts if v.verdict == "caught")
    judged = sum(1 for v in rep.verdicts if v.expected is not None)
    lines.append("")
    lines.append(
        f"{caught}/{judged} fault(s) caught, "
        f"{len(rep.missed_critical)} critical miss(es), "
        f"{len(rep.false_alarms)} false alarm(s) over "
        f"{rep.events_total} event(s)."
    )
    return "\n".join(lines)


def render_conformance_textfile(rep: ConformanceReport, *,
                                now: float) -> str:
    """Prometheus gauges for one ``chaos verify`` run — the dashboard
    feed for SCHEDULED conformance soaks, so detector drift shows up on
    a graph instead of in unread markdown.  Same label/escaping
    conventions as the health exporter; write through
    ``health.exporter.write_textfile`` (atomic)."""
    from tpu_perf.health.exporter import labels

    lines = []

    def family(name: str, help_: str, kind: str = "gauge") -> None:
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {kind}")

    per = {
        "injected": "Faults injected for this detector in the judged soak.",
        "caught": "Faults the detector caught.",
        "missed": "Faults the detector missed.",
        "false_alarms": "Events not attributable to any injected fault.",
    }
    for field, help_ in per.items():
        family(f"tpu_perf_chaos_detector_{field}", help_)
        for s in rep.scores:
            lines.append(
                f"tpu_perf_chaos_detector_{field}"
                f"{labels(detector=s.detector)} {getattr(s, field)}"
            )
    family("tpu_perf_chaos_missed_critical",
           "Critical faults missed — the exit-5 gate condition.")
    lines.append(f"tpu_perf_chaos_missed_critical {len(rep.missed_critical)}")
    family("tpu_perf_chaos_false_alarms_total",
           "Unattributable events across all detectors.")
    lines.append(f"tpu_perf_chaos_false_alarms_total {len(rep.false_alarms)}")
    family("tpu_perf_chaos_last_verify_timestamp_seconds",
           "Unix time of the last completed chaos verify run.")
    lines.append(f"tpu_perf_chaos_last_verify_timestamp_seconds {now:.3f}")
    return "\n".join(lines) + "\n"


def report_to_json(rep: ConformanceReport) -> str:
    return json.dumps({
        "meta": rep.meta,
        "faults": [
            {
                "spec_index": v.spec_index,
                **dataclasses.asdict(v.fault),
                "expected": v.expected,
                "verdict": v.verdict,
                "injected": v.injected,
                "first_run": v.first_run,
                "last_run": v.last_run,
                "detail": v.detail,
                "context": v.context,
            }
            for v in rep.verdicts
        ],
        "detectors": [
            {
                "detector": s.detector,
                "injected": s.injected,
                "caught": s.caught,
                "missed": s.missed,
                "false_alarms": s.false_alarms,
                "precision": s.precision,
                "recall": s.recall,
            }
            for s in rep.scores
        ],
        "false_alarms": [dataclasses.asdict(e) for e in rep.false_alarms],
        "missed_critical": [v.spec_index for v in rep.missed_critical],
    }, indent=2)
