"""Fault-schedule format: what to break, where, and when.

A chaos run is driven by a list of :class:`FaultSpec` entries — loaded
from JSON (``tpu-perf chaos --faults spec.json``) or spelled inline
(``--fault kind:op:nbytes:start-end:magnitude``).  Each entry keys on
``(op, nbytes, run-window)`` in the daemon's GLOBAL run-id space: the
round-robin visit order is deterministic, so a window plus a point
filter names an exact set of measured runs, and the same spec + seed
always perturbs the same ones.

Fault kinds, and the detector each one must trip (the conformance
contract, :data:`EXPECTED_EVENT`):

====== =============================================================
kind    meaning -> expected detection
====== =============================================================
``delay``     every matching run slowed by ``magnitude`` relative
              (0.5 = +50%) -> ``regression`` health event
``jitter``    seeded multiplicative noise of amplitude ``magnitude``
              -> nothing: detectors must NOT alert on noise (jitter
              entries are judged n/a, never missed)
``spike``     ONE matching run (the window's first) multiplied by
              ``magnitude`` -> ``spike`` health event
``flatline``  matching runs pinned to the window's first sample
              -> ``flatline`` health event
``drop_run``  matching runs dropped before recording (capture loss)
              -> ``capture_loss`` health event
``hook_fail`` the rotation ingest hook raises while the window is
              active (a rotation is forced at the window's first run
              so the failure is deterministic) -> ``hook_fail`` event
``corrupt``   one exponent bit of the op's selftest payload flipped
              -> a FAIL verdict from ``selftest``'s rx validation
====== =============================================================

The injection ledger rides a fourth rotating-log family,
``chaos-*.log`` (schema.CHAOS_PREFIX): JSON lines like the health
events, lazy + ``.open`` suffixed like them, swept by the same ingest
pass.  Ledger records carry NO wall-clock timestamps — run_id is the
clock — so the acceptance contract "same seed + spec => identical
ledger" holds byte-for-byte across real runs.
"""

from __future__ import annotations

import dataclasses
import json

#: every fault kind the injector implements
FAULT_KINDS = (
    "delay", "jitter", "spike", "flatline", "drop_run", "hook_fail",
    "corrupt",
)

#: fault kind -> the health-event kind (or "selftest") that proves the
#: fault was caught; None = injected noise no detector should fire on.
#: The conformance harness (faults.conformance) judges against this map.
EXPECTED_EVENT = {
    "delay": "regression",
    "jitter": None,
    "spike": "spike",
    "flatline": "flatline",
    "drop_run": "capture_loss",
    "hook_fail": "hook_fail",
    "corrupt": "selftest",
}

#: per-kind magnitude defaults (kinds absent here take no magnitude)
DEFAULT_MAGNITUDE = {"delay": 1.0, "jitter": 0.2, "spike": 20.0}


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault.

    ``op == "*"`` matches every op; ``nbytes == 0`` matches every size
    (the same wildcard conventions the health events use).  The run
    window is inclusive on both ends; ``end is None`` leaves it open.
    ``critical`` marks faults whose MISS fails ``tpu-perf chaos verify``
    (exit 5) — the CI conformance gate's teeth.
    """

    kind: str
    op: str = "*"
    nbytes: int = 0
    start: int = 1
    end: int | None = None
    magnitude: float | None = None
    critical: bool = True

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; known: {FAULT_KINDS}"
            )
        if self.magnitude is None:
            object.__setattr__(
                self, "magnitude", DEFAULT_MAGNITUDE.get(self.kind, 0.0)
            )
        if self.start < 1:
            raise ValueError(f"fault start must be >= 1, got {self.start}")
        if self.end is not None and self.end < self.start:
            raise ValueError(
                f"fault window [{self.start}, {self.end}] is empty"
            )
        if self.nbytes < 0:
            raise ValueError(f"nbytes must be >= 0, got {self.nbytes}")
        if self.kind in ("delay", "spike") and self.magnitude <= 0:
            raise ValueError(
                f"{self.kind} needs a positive magnitude, got {self.magnitude}"
            )
        if self.kind == "jitter" and not 0.0 < self.magnitude < 1.0:
            # amplitude >= 1 would drive samples to zero or negative
            raise ValueError(
                f"jitter magnitude must be in (0, 1), got {self.magnitude}"
            )
        if self.kind == "corrupt" and self.op == "*":
            # the corrupt pass runs a selftest per named op at driver
            # exit; a wildcard would mean "selftest everything", which
            # is a different (and unbounded) job
            raise ValueError("corrupt faults must name a concrete op")

    def in_window(self, run_id: int) -> bool:
        return run_id >= self.start and (self.end is None or run_id <= self.end)

    def matches(self, op: str, nbytes: int, run_id: int) -> bool:
        return (
            (self.op == "*" or self.op == op)
            and (self.nbytes == 0 or self.nbytes == nbytes)
            and self.in_window(run_id)
        )


def parse_spec(data) -> list[FaultSpec]:
    """Build the schedule from decoded JSON: a list of entries, or an
    object with a ``faults`` list.  Unknown keys fail loudly — a typo'd
    ``magntiude`` silently defaulting would make a chaos run test
    nothing."""
    if isinstance(data, dict):
        if set(data) != {"faults"}:
            raise ValueError(
                f"fault spec object must have exactly a 'faults' list, "
                f"got keys {sorted(data)}"
            )
        data = data["faults"]
    if not isinstance(data, list):
        raise ValueError(f"fault spec must be a list, got {type(data).__name__}")
    known = {f.name for f in dataclasses.fields(FaultSpec)}
    out = []
    for i, entry in enumerate(data):
        if not isinstance(entry, dict):
            raise ValueError(f"fault spec entry {i} is not an object: {entry!r}")
        unknown = set(entry) - known
        if unknown:
            raise ValueError(
                f"fault spec entry {i} has unknown key(s) {sorted(unknown)}; "
                f"known: {sorted(known)}"
            )
        if isinstance(entry.get("nbytes"), str):
            from tpu_perf.sweep import parse_size

            entry = dict(entry, nbytes=parse_size(entry["nbytes"]))
        out.append(FaultSpec(**entry))
    return out


def load_spec(path: str) -> list[FaultSpec]:
    """Parse a JSON fault-schedule file."""
    with open(path) as fh:
        try:
            data = json.load(fh)
        except json.JSONDecodeError as e:
            raise ValueError(f"bad fault spec {path}: {e}") from None
    return parse_spec(data)


def parse_fault_arg(arg: str) -> FaultSpec:
    """One CLI-spelled fault: ``kind[:op[:nbytes[:start-end[:magnitude]]]]``.

    Sizes take the sweep suffixes (``64K``); the window takes ``A-B``,
    ``A-`` (open end), or ``A`` (a single run).  Examples::

        delay:ring:32:100-400:2.0
        drop_run:*:0:60-100
        hook_fail::0:110-115
    """
    parts = arg.split(":")
    if not parts or not parts[0]:
        raise ValueError(f"empty fault argument {arg!r}")
    entry: dict = {"kind": parts[0]}
    if len(parts) > 1 and parts[1]:
        entry["op"] = parts[1]
    if len(parts) > 2 and parts[2]:
        from tpu_perf.sweep import parse_size

        entry["nbytes"] = parse_size(parts[2])
    if len(parts) > 3 and parts[3]:
        lo, sep, hi = parts[3].partition("-")
        entry["start"] = int(lo)
        if sep and hi:
            entry["end"] = int(hi)
        elif not sep:
            entry["end"] = int(lo)
    if len(parts) > 4 and parts[4]:
        entry["magnitude"] = float(parts[4])
    if len(parts) > 5:
        raise ValueError(f"too many ':' fields in fault argument {arg!r}")
    return FaultSpec(**entry)


class ChaosRecord:
    """One injection-ledger line.  Duck-typed as a row (``to_csv`` is
    the JSON line) so the ledger IS a RotatingCsvLog — same rotation,
    same lazy ``.open`` contract, same ingest family mechanics as the
    health events.  Three record types share the stream, discriminated
    by the ``record`` field: ``meta`` (one per log: seed, stats_every,
    the full spec), ``fault`` (one per fired injection), ``selftest``
    (corrupt-pass verdicts)."""

    __slots__ = ("data",)

    def __init__(self, **data):
        if "record" not in data:
            raise ValueError("chaos records need a 'record' discriminator")
        self.data = data

    def to_json(self) -> str:
        return json.dumps(self.data, sort_keys=True)

    to_csv = to_json  # the RotatingCsvLog row interface

    @classmethod
    def from_json(cls, line: str) -> "ChaosRecord":
        data = json.loads(line)
        if not isinstance(data, dict) or "record" not in data:
            raise ValueError(f"chaos ledger line is not a record: {line!r}")
        return cls(**data)
