"""Fault-schedule format: what to break, where, and when.

A chaos run is driven by a list of :class:`FaultSpec` entries — loaded
from JSON (``tpu-perf chaos --faults spec.json``) or spelled inline
(``--fault kind:op:nbytes:start-end:magnitude``).  Each entry keys on
``(op, nbytes, run-window)`` in the daemon's GLOBAL run-id space: the
round-robin visit order is deterministic, so a window plus a point
filter names an exact set of measured runs, and the same spec + seed
always perturbs the same ones.

Fault kinds, and the detector each one must trip (the conformance
contract, :data:`EXPECTED_EVENT`):

====== =============================================================
kind    meaning -> expected detection
====== =============================================================
``delay``     every matching run slowed by ``magnitude`` relative
              (0.5 = +50%) -> ``regression`` health event
``jitter``    seeded multiplicative noise of amplitude ``magnitude``
              (``shape``: bounded ``uniform``, or heavy-tailed
              ``lognormal``/``pareto`` for realistic tail noise)
              -> nothing: detectors must NOT alert on noise (jitter
              entries are judged n/a, never missed)
``spike``     ONE matching run (the window's first) multiplied by
              ``magnitude`` -> ``spike`` health event
``flatline``  matching runs pinned to the window's first sample
              -> ``flatline`` health event
``drop_run``  matching runs dropped before recording (capture loss)
              -> ``capture_loss`` health event
``hook_fail`` the rotation ingest hook raises while the window is
              active (a rotation is forced at the window's first run
              so the failure is deterministic) -> ``hook_fail`` event
``corrupt``   one exponent bit of the op's selftest payload flipped
              -> a FAIL verdict from ``selftest``'s rx validation
``skew``      every matching run's ENTRY into the collective staggered
              by a seeded per-(rank, run) arrival delay of scale
              ``magnitude`` MICROSECONDS (``shape``: ``uniform`` =
              arrival anywhere in [0, magnitude); ``lognormal``/
              ``pareto`` reuse the heavy-tailed machinery for
              straggler tails).  Unlike ``delay`` — which perturbs the
              measured value after the fact — skew staggers the
              DISPATCH, so the collective observes imbalanced arrival
              (arXiv 1804.05349); on the synthetic timing source the
              victim's arrival-wait cost (modeled worst arrival minus
              own arrival) is folded into the sample so CI soaks see
              the same latency coupling real victims do
              -> ``regression`` health event on the VICTIM's rows
====== =============================================================

The injection ledger rides a fourth rotating-log family,
``chaos-*.log`` (schema.CHAOS_PREFIX): JSON lines like the health
events, lazy + ``.open`` suffixed like them, swept by the same ingest
pass.  Ledger records carry NO wall-clock timestamps — run_id is the
clock — so the acceptance contract "same seed + spec => identical
ledger" holds byte-for-byte across real runs.
"""

from __future__ import annotations

import dataclasses
import json

from tpu_perf.schema import JsonlRecord

#: every fault kind the injector implements
FAULT_KINDS = (
    "delay", "jitter", "spike", "flatline", "drop_run", "hook_fail",
    "corrupt", "skew",
)

#: fault kind -> the health-event kind (or "selftest") that proves the
#: fault was caught; None = injected noise no detector should fire on.
#: The conformance harness (faults.conformance) judges against this map.
EXPECTED_EVENT = {
    "delay": "regression",
    "jitter": None,
    "spike": "spike",
    "flatline": "flatline",
    "drop_run": "capture_loss",
    "hook_fail": "hook_fail",
    "corrupt": "selftest",
    # skew is latency-coupled: the straggler's late entry inflates the
    # VICTIM ranks' samples, so the regression detector is the judge —
    # and the conformance join attributes detection to any rank's
    # events, not just the skewed rank's (a rank-filtered skew degrades
    # everyone ELSE's observed collective)
    "skew": "regression",
}

#: per-kind magnitude defaults (kinds absent here take no magnitude).
#: skew's magnitude is the arrival-spread scale in MICROSECONDS (the
#: repo's latency unit — lat_us, skew_us); 1000 = a 1 ms straggler.
DEFAULT_MAGNITUDE = {"delay": 1.0, "jitter": 0.2, "spike": 20.0,
                     "skew": 1000.0}

#: jitter noise shapes: ``uniform`` is the bounded multiplicative noise;
#: ``lognormal``/``pareto`` are the heavy-tailed models (seeded, like
#: everything else, and median-preserving — noise, not a level shift)
#: that exercise the zero-false-alarm gates and the linkmap MAD
#: thresholds against realistic tail noise instead of bounded noise — a
#: detector tuned only on uniform noise has never seen the
#: one-in-a-thousand 3x sample a real fabric produces.  Lognormal at
#: modest sigma is the zero-false-alarm-gate shape (ci.sh uses 0.1);
#: pareto's power-law tail intentionally produces isolated multi-x
#: samples that ARE spikes semantically — the spike detector firing on
#: them is correct behavior, so pareto belongs in threshold-tuning
#: soaks, not in gates that allow no alarms.
JITTER_SHAPES = ("uniform", "lognormal", "pareto")


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault.

    ``op == "*"`` matches every op; ``nbytes == 0`` matches every size
    (the same wildcard conventions the health events use).  ``rank``
    restricts the fault to ONE process/host (None = every rank): a
    multi-host chaos run can degrade a single host and assert the
    emitted event's ``rank`` column names it, and the linkmap
    localization gate targets one link's owning rank the same way.
    The run window is inclusive on both ends; ``end is None`` leaves it
    open.  ``shape`` selects the noise model (jitter) or the arrival
    distribution (skew); other kinds take ``uniform`` only.
    ``critical`` marks faults whose MISS fails ``tpu-perf chaos verify``
    (exit 5) — the CI conformance gate's teeth.
    """

    kind: str
    op: str = "*"
    nbytes: int = 0
    start: int = 1
    end: int | None = None
    magnitude: float | None = None
    critical: bool = True
    rank: int | None = None
    shape: str = "uniform"

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; known: {FAULT_KINDS}"
            )
        if self.magnitude is None:
            object.__setattr__(
                self, "magnitude", DEFAULT_MAGNITUDE.get(self.kind, 0.0)
            )
        if self.start < 1:
            raise ValueError(f"fault start must be >= 1, got {self.start}")
        if self.end is not None and self.end < self.start:
            raise ValueError(
                f"fault window [{self.start}, {self.end}] is empty"
            )
        if self.nbytes < 0:
            raise ValueError(f"nbytes must be >= 0, got {self.nbytes}")
        if self.kind in ("delay", "spike", "skew") and self.magnitude <= 0:
            raise ValueError(
                f"{self.kind} needs a positive magnitude, got {self.magnitude}"
            )
        if self.kind == "jitter" and not 0.0 < self.magnitude < 1.0:
            # amplitude >= 1 would drive samples to zero or negative
            raise ValueError(
                f"jitter magnitude must be in (0, 1), got {self.magnitude}"
            )
        if self.kind == "corrupt" and self.op == "*":
            # the corrupt pass runs a selftest per named op at driver
            # exit; a wildcard would mean "selftest everything", which
            # is a different (and unbounded) job
            raise ValueError("corrupt faults must name a concrete op")
        if self.rank is not None and self.rank < 0:
            raise ValueError(f"rank filter must be >= 0, got {self.rank}")
        if self.kind == "hook_fail" and self.rank not in (None, 0):
            # the rotation ingest hook exists on the rank-0 process only
            # (mpi_perf.c:359-362; Driver wires hook = on_rotate iff
            # rank == 0), so a hook_fail pinned to any other rank could
            # never fire — and would deterministically fail `chaos
            # verify` as a missed critical no detector can catch
            raise ValueError(
                f"hook_fail rank filter must be 0 (the only rank with an "
                f"ingest hook), got {self.rank}"
            )
        if self.shape not in JITTER_SHAPES:
            raise ValueError(
                f"unknown jitter shape {self.shape!r}; known: {JITTER_SHAPES}"
            )
        if self.shape != "uniform" and self.kind not in ("jitter", "skew"):
            raise ValueError(
                f"shape={self.shape!r} only applies to jitter and skew "
                f"faults, not {self.kind!r}"
            )

    def in_window(self, run_id: int) -> bool:
        return run_id >= self.start and (self.end is None or run_id <= self.end)

    def matches_rank(self, rank: int) -> bool:
        return self.rank is None or self.rank == rank

    def matches(self, op: str, nbytes: int, run_id: int,
                rank: int = 0) -> bool:
        return (
            (self.op == "*" or self.op == op)
            and (self.nbytes == 0 or self.nbytes == nbytes)
            and self.in_window(run_id)
            and self.matches_rank(rank)
        )


def parse_spec(data) -> list[FaultSpec]:
    """Build the schedule from decoded JSON: a list of entries, or an
    object with a ``faults`` list.  Unknown keys fail loudly — a typo'd
    ``magntiude`` silently defaulting would make a chaos run test
    nothing."""
    if isinstance(data, dict):
        if set(data) != {"faults"}:
            raise ValueError(
                f"fault spec object must have exactly a 'faults' list, "
                f"got keys {sorted(data)}"
            )
        data = data["faults"]
    if not isinstance(data, list):
        raise ValueError(f"fault spec must be a list, got {type(data).__name__}")
    known = {f.name for f in dataclasses.fields(FaultSpec)}
    out = []
    for i, entry in enumerate(data):
        if not isinstance(entry, dict):
            raise ValueError(f"fault spec entry {i} is not an object: {entry!r}")
        unknown = set(entry) - known
        if unknown:
            raise ValueError(
                f"fault spec entry {i} has unknown key(s) {sorted(unknown)}; "
                f"known: {sorted(known)}"
            )
        if isinstance(entry.get("nbytes"), str):
            from tpu_perf.sweep import parse_size

            entry = dict(entry, nbytes=parse_size(entry["nbytes"]))
        out.append(FaultSpec(**entry))
    return out


def load_spec(path: str) -> list[FaultSpec]:
    """Parse a JSON fault-schedule file."""
    with open(path) as fh:
        try:
            data = json.load(fh)
        except json.JSONDecodeError as e:
            raise ValueError(f"bad fault spec {path}: {e}") from None
    return parse_spec(data)


def parse_fault_arg(arg: str) -> FaultSpec:
    """One CLI-spelled fault: ``kind[:op[:nbytes[:start-end[:magnitude]]]]``.

    Sizes take the sweep suffixes (``64K``); the window takes ``A-B``,
    ``A-`` (open end), or ``A`` (a single run).  Examples::

        delay:ring:32:100-400:2.0
        drop_run:*:0:60-100
        hook_fail::0:110-115
        spike:link:(1,2)>(1,3):0:1-:30

    Linkmap probe ops carry a colon of their own (``link:(1,2)>(1,3)``);
    the parser re-joins that one split so the localization targets are
    spellable inline, not only in a JSON spec.
    """
    parts = arg.split(":")
    if not parts or not parts[0]:
        raise ValueError(f"empty fault argument {arg!r}")
    if len(parts) > 2 and parts[1] == "link" and parts[2].startswith("("):
        # a linkmap op name split on its own colon: stitch it back
        parts[1:3] = [f"{parts[1]}:{parts[2]}"]
    entry: dict = {"kind": parts[0]}
    if len(parts) > 1 and parts[1]:
        entry["op"] = parts[1]
    if len(parts) > 2 and parts[2]:
        from tpu_perf.sweep import parse_size

        entry["nbytes"] = parse_size(parts[2])
    if len(parts) > 3 and parts[3]:
        lo, sep, hi = parts[3].partition("-")
        entry["start"] = int(lo)
        if sep and hi:
            entry["end"] = int(hi)
        elif not sep:
            entry["end"] = int(lo)
    if len(parts) > 4 and parts[4]:
        entry["magnitude"] = float(parts[4])
    if len(parts) > 5:
        raise ValueError(f"too many ':' fields in fault argument {arg!r}")
    return FaultSpec(**entry)


class ChaosRecord(JsonlRecord):
    """One injection-ledger line (schema.JsonlRecord: duck-typed row,
    lazy-family mechanics shared with the health events and linkmap
    records).  Three record types share the stream, discriminated by
    the ``record`` field: ``meta`` (one per log: seed, stats_every, the
    full spec), ``fault`` (one per fired injection), ``selftest``
    (corrupt-pass verdicts)."""

    __slots__ = ()
    FAMILY = "chaos"
