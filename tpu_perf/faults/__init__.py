"""Deterministic fault injection + detector conformance (ISSUE 2).

The chaos layer that proves the health subsystem detects what it claims
to: ``spec`` (the JSON/CLI fault schedule), ``injector`` (the seeded
per-run perturbation engine the Driver consults), ``conformance`` (the
ledger-vs-events judge behind ``tpu-perf chaos verify``).
"""

from tpu_perf.faults.conformance import (  # noqa: F401
    ConformanceReport,
    read_ledger,
    report_to_json,
    report_to_markdown,
    run_conformance,
)
from tpu_perf.faults.injector import (  # noqa: F401
    FaultInjector,
    InjectedHookFailure,
    axis_skew,
)
from tpu_perf.faults.spec import (  # noqa: F401
    EXPECTED_EVENT,
    FAULT_KINDS,
    ChaosRecord,
    FaultSpec,
    load_spec,
    parse_fault_arg,
    parse_spec,
)
