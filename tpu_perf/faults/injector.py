"""Seeded fault injection at the driver/op boundary.

The :class:`FaultInjector` sits between ``Driver._measure`` and
``Driver._record_run``: every run's wall time passes through
:meth:`apply`, which perturbs (or drops) it according to the schedule
and writes one ledger record per fired injection.  Because injection
wraps the MEASURED VALUE — not the kernel, the fence, or the backend —
it behaves identically under ``block``/``readback``/``slope``/``trace``
and for both one-shot and daemon loops.

Determinism contract: all randomness is derived by hashing
``(seed, spec-index, run_id)`` (and, for synthetic samples,
``(seed, op, nbytes, visit-count)``) into a fresh ``random.Random`` —
no shared RNG stream whose consumption order could drift.  Same seed +
same spec + same run sequence => the same perturbation stream and a
byte-identical injection ledger (records carry no wall-clock fields).

``synthetic_s`` replaces the measured sample entirely with a seeded
series around a base latency (tiny relative noise, never bit-identical)
— the knob that makes the CI conformance and false-alarm gates
deterministic on shared machines, where real CPU timing outliers would
make a zero-false-alarm assertion flaky.
"""

from __future__ import annotations

import dataclasses
import math
import random
import sys

import numpy as np

from tpu_perf.faults.spec import ChaosRecord, FaultSpec
from tpu_perf.schema import window_index

#: relative amplitude of the synthetic series' seeded noise: big enough
#: that samples never repeat (no false flatline), small enough that a
#: spike fault's z-score clears any sane threshold
SYNTHETIC_NOISE = 1e-3

#: the smallest arrival-skew world: skew needs at least two parties, so
#: a single-process soak models a two-rank world (rank 0 = this process,
#: rank 1 = the phantom straggler) — otherwise max(arrivals) == own
#: arrival and the victim cost would be identically zero, making every
#: single-host conformance gate vacuous
MIN_SKEW_WORLD = 2


def skew_world(n_ranks: int, rank: int = 0) -> range:
    """The modeled arrival world: every real rank, padded to at least
    :data:`MIN_SKEW_WORLD` (and to include ``rank``) — ONE spelling for
    the axis, the fault kind, and the driver, so the padding semantics
    cannot drift between the production path and the test-facing
    wrappers."""
    return range(max(MIN_SKEW_WORLD, n_ranks, rank + 1))


def reduce_arrivals(totals: dict[int, float],
                    rank: int) -> tuple[float, float]:
    """The (own_stagger_s, victim_cost_s) reduction over one run's
    per-rank arrival totals in µs: this rank delays its dispatch by its
    own arrival, and waits — from its seat inside the collective — for
    the worst arrival in the world.  Shared by every skew source for
    the same reason as :func:`skew_world`."""
    own = totals[rank]
    return own * 1e-6, (max(totals.values()) - own) * 1e-6


def _arrival_mult(shape: str, rnd: random.Random) -> float:
    """One rank's arrival draw as a fraction of the skew scale.

    ``uniform`` models arrival anywhere in ``[0, scale)`` — the paper's
    bounded imbalanced-arrival window (arXiv 1804.05349).  The heavy-
    tailed shapes reuse the jitter machinery's median-1 normalization
    so ``scale`` stays the TYPICAL stagger while the tail produces the
    occasional multi-x straggler: ``lognormal`` at sigma 0.5,
    ``pareto`` at tail index 3 divided by its median 2**(1/3)."""
    if shape == "lognormal":
        return math.exp(0.5 * rnd.gauss(0.0, 1.0))
    if shape == "pareto":
        return rnd.paretovariate(3.0) / 2.0 ** (1.0 / 3.0)
    return rnd.random()


def axis_skew(seed: int, op: str, nbytes: int, spread_us: int,
              run_id: int, *, rank: int = 0,
              n_ranks: int = 1) -> tuple[float, float]:
    """The sweep-axis arrival scenario (``--skew-spread``): one run's
    ``(own_stagger_s, victim_cost_s)`` at arrival spread ``spread_us``.

    The scenario is the paper's question made literal ("what does a
    1 ms straggler cost?", arXiv 1804.05349): the world's LAST rank is
    the designated straggler and arrives at exactly the spread — the
    envelope is pinned, so the measured cost prices a ``spread``-late
    straggler, not a random sub-spread one — while every other rank
    draws a seeded uniform arrival in ``[0, spread_us)`` (key: seed,
    op, nbytes, spread, rank, run).  Arrivals are stateless hashes, so
    every rank computes every other's without wire exchange (lockstep
    by construction).  ``own_stagger_s`` is how long this rank delays
    its dispatch; ``victim_cost_s`` is the arrival wait the collective
    observes from this rank's seat — spread minus its own arrival —
    which the synthetic timing source folds into the sample (real
    multi-host runs observe it physically and add nothing).  A world
    smaller than :data:`MIN_SKEW_WORLD` is padded so a single-host
    sweep still has a straggler to wait for (the phantom last rank)."""
    if spread_us <= 0:
        return 0.0, 0.0
    arrivals = axis_arrivals_us(seed, op, nbytes, spread_us, run_id,
                                world=skew_world(n_ranks, rank))
    return reduce_arrivals(arrivals, rank)


def axis_arrivals_us(seed: int, op: str, nbytes: int, spread_us: int,
                     run_id: int, *, world) -> dict[int, float]:
    """Every rank's axis arrival for one run, in µs — the last rank of
    ``world`` is the designated straggler at exactly the spread, the
    rest draw uniformly in ``[0, spread)``.  Exposed per rank (not
    pre-reduced to a cost) so the driver can SUM arrivals across
    sources — the axis plus any scheduled skew faults — before taking
    the worst: per-source costs do not add (two sources' worst arrivals
    can land on different ranks), combined arrivals do."""
    straggler = max(world)
    return {
        r: (float(spread_us) if r == straggler
            else spread_us * random.Random(
                f"{seed}:skewaxis:{op}:{nbytes}:{spread_us}:{r}:{run_id}"
            ).random())
        for r in world
    }


class InjectedHookFailure(RuntimeError):
    """Raised by the chaos-wrapped ingest hook while a ``hook_fail``
    fault window is active — a distinct type so logs attribute the
    failure to injection, not a real telemetry outage."""


class FaultInjector:
    """One per Driver (``--faults`` / ``--synthetic``); shared by the
    run loop (:meth:`apply`, :meth:`synthetic_sample`), the rotation
    hook (:meth:`wrap_hook`), and the selftest corrupt pass
    (:meth:`corrupt_payload`)."""

    def __init__(
        self,
        faults: list[FaultSpec],
        *,
        seed: int = 0,
        stats_every: int = 1000,
        ledger=None,   # RotatingCsvLog(prefix="chaos", lazy=True) or None
        synthetic_s: float | None = None,
        rank: int = 0,  # this process's rank, judged against FaultSpec.rank
        #               # (a rank-filtered fault fires on ONE host of a
        #               # multi-host soak; the linkmap prober overrides it
        #               # per probe with the link's owning rank)
        err=None,
    ):
        self.faults = list(faults)
        self.seed = seed
        self.stats_every = max(1, stats_every)
        self.ledger = ledger
        self.synthetic_s = synthetic_s
        self.rank = rank
        self.err = err
        self._fired_once: set[int] = set()    # spike/hook_fail: one-shot
        self._flat_pin: dict[int, float] = {}  # flatline: pinned sample
        self._syn_count: dict[tuple[str, int], int] = {}
        self._current_run = 0
        self._force_rotation = False
        #: cumulative run-time injections fired (ledger records written
        #: or suppressed alike) — the driver samples the delta around
        #: each apply() to emit an ``inject`` trace span only for runs a
        #: fault actually touched, without adding any ledger field
        self.fired_total = 0

    # -- ledger ---------------------------------------------------------

    def write_meta(self) -> None:
        """The ledger's header record: everything conformance needs to
        re-derive the schedule (and everything reproduction needs to
        re-run it).  Written eagerly at driver start so even a chaos
        soak whose faults never fire leaves a ledger behind — a
        fault-free soak's conformance pass must know it was fault-free,
        not fileless."""
        self._write(ChaosRecord(
            record="meta",
            seed=self.seed,
            stats_every=self.stats_every,
            synthetic_s=self.synthetic_s,
            faults=[dataclasses.asdict(f) for f in self.faults],
        ))

    def _write(self, rec: ChaosRecord) -> None:
        if self.ledger is not None:
            self.ledger.write_row(rec)

    def _fault_record(self, idx: int, f: FaultSpec, run_id: int,
                      op: str, nbytes: int, **extra) -> None:
        self.fired_total += 1
        self._write(ChaosRecord(
            record="fault", spec=idx, kind=f.kind, op=op, nbytes=nbytes,
            run_id=run_id,
            window=window_index(run_id, self.stats_every), **extra,
        ))

    def maybe_rotate(self) -> None:
        if self.ledger is not None:
            self.ledger.maybe_rotate()

    def close(self) -> None:
        if self.ledger is not None:
            self.ledger.close()

    # -- deterministic randomness --------------------------------------

    def _rng(self, idx: int, run_id: int) -> random.Random:
        """THE seeded stream for (seed, spec-index, run_id) — one key
        spelling, so the byte-identical-ledger contract cannot desync
        between the uniform and shaped jitter paths."""
        return random.Random(f"{self.seed}:{idx}:{run_id}")

    def _rand(self, idx: int, run_id: int) -> float:
        """U(0, 1) from the per-(seed, spec, run) stream — stateless, so
        the stream cannot drift with evaluation order."""
        return self._rng(idx, run_id).random()

    def _jitter_multiplier(self, f: FaultSpec, idx: int, run_id: int) -> float:
        """The seeded noise multiplier for one jitter sample.

        ``uniform`` is the bounded 1 + magnitude * U(-1, 1).  The heavy-
        tailed shapes are MEDIAN-PRESERVING around 1 with a real right
        tail — noise, not a level shift, because the jitter contract is
        that detectors must NOT alert (a sustained shift is what the
        regression detector exists to catch, and would turn every
        shaped-jitter soak into a false-alarm factory): ``lognormal``
        uses magnitude as log-sigma (exp(sigma * N(0,1)), median 1);
        ``pareto`` draws a Pareto of tail index 1/magnitude and divides
        out its median 2**magnitude (magnitude 0.2 => alpha 5: bulk ~1,
        occasionally several-x).  Each sample's draw is a fresh
        (seed, spec, run) Random, so shapes stay exactly as
        reproducible as the uniform stream."""
        rnd = self._rng(idx, run_id)
        if f.shape == "lognormal":
            return math.exp(f.magnitude * rnd.gauss(0.0, 1.0))
        if f.shape == "pareto":
            return rnd.paretovariate(1.0 / f.magnitude) / 2.0 ** f.magnitude
        return 1.0 + f.magnitude * (2.0 * rnd.random() - 1.0)

    # -- synthetic timing source ---------------------------------------

    @property
    def synthetic(self) -> bool:
        return self.synthetic_s is not None

    def synthetic_sample(self, op: str, nbytes: int) -> float:
        """The next sample of this point's seeded series (replaces
        ``Driver._measure`` entirely in synthetic mode)."""
        key = (op, nbytes)
        n = self._syn_count[key] = self._syn_count.get(key, 0) + 1
        u = random.Random(f"{self.seed}:syn:{op}:{nbytes}:{n}").random()
        return self.synthetic_s * (1.0 + SYNTHETIC_NOISE * (u - 0.5))

    # -- the pre-dispatch injection point (arrival skew) ---------------

    def _skew_stagger_us(self, idx: int, f: FaultSpec, rank: int,
                         run_id: int) -> float:
        """One rank's drawn arrival stagger for one skew spec, in µs —
        a stateless (seed, spec, rank, run) hash, so every rank can
        reconstruct every other rank's arrival without communication
        (the same lockstep argument as the axis model)."""
        rnd = random.Random(f"{self.seed}:{idx}:skew:{rank}:{run_id}")
        return f.magnitude * _arrival_mult(f.shape, rnd)

    def entry_skew(self, op: str, nbytes: int, run_id: int, *,
                   n_ranks: int = 1) -> tuple[float, float]:
        """Scheduled arrival skew for one run: ``(own_stagger_s,
        victim_cost_s)`` summed over the matching skew specs.

        Called at the ENTRY boundary — before the dispatch — unlike
        :meth:`apply`, which perturbs the measured value afterwards:
        the driver sleeps ``own_stagger_s`` so the collective really
        observes imbalanced arrival, and (synthetic mode only) adds
        ``victim_cost_s`` — the modeled worst arrival minus this
        rank's own, per spec — to the sample, because a single
        synthetic process has no peers to physically wait for.  A
        rank-filtered spec staggers only the named rank; every other
        rank is a victim (cost > 0, stagger 0).  One ledger record per
        matching spec per run, on EVERY in-window rank — victims
        included, stagger_us 0 — so the conformance join sees the
        fault on the rows it degrades, and the per-rank ledgers stay
        byte-reproducible (no wall-clock fields; the stagger is a
        drawn value, not a clock read).

        The fault-only convenience over :meth:`skew_fault_world` +
        :meth:`skew_arrivals_us` — the driver calls those directly so
        it can merge the ``--skew-spread`` axis arrivals into the same
        per-rank totals before reducing."""
        totals = self.skew_arrivals_us(
            op, nbytes, run_id,
            world=self.skew_fault_world(n_ranks, op, nbytes, run_id))
        if totals is None:
            return 0.0, 0.0
        return reduce_arrivals(totals, self.rank)

    def skew_fault_world(self, n_ranks: int, op: str | None = None,
                         nbytes: int = 0, run_id: int = 0):
        """The ONE definition of the skew faults' modeled arrival
        world: the synthetic source models phantom stragglers (padded
        to every rank a matching spec names, and to MIN_SKEW_WORLD),
        so single-host conformance soaks stay meaningful; real timing
        can only observe a straggler that actually sleeps, so its
        world is EXACTLY the real ranks — a phantom-only spec neither
        fires nor ledgers there (and the driver rejects it up front).
        Shared by :meth:`entry_skew` and the driver's entry boundary,
        so the two spellings cannot drift."""
        if self.synthetic:
            return skew_world(
                self.skew_world_size(n_ranks, op, nbytes, run_id),
                self.rank)
        return range(n_ranks)

    def _skew_matches(self, f: FaultSpec, op: str, nbytes: int,
                      run_id: int) -> bool:
        """One definition of "this skew spec covers this run" — shared
        by the world sizing and the arrival draws, so the two can never
        disagree about which specs shape a run's modeled world."""
        return (f.kind == "skew"
                and (f.op == "*" or f.op == op)
                and (f.nbytes == 0 or f.nbytes == nbytes)
                and f.in_window(run_id))

    def skew_world_size(self, n_ranks: int, op: str | None = None,
                        nbytes: int = 0, run_id: int = 0) -> int:
        """The rank count the modeled arrival world must cover: every
        real rank PLUS every rank a skew spec names — a multi-host spec
        (``rank: 3``) reproduced on fewer hosts still models the named
        straggler (phantom, like the MIN_SKEW_WORLD pad), so the
        victims' cost, the detectors' signal, and the conformance
        verdict stay meaningful instead of silently zero.  With
        (op, nbytes, run_id) given, only specs MATCHING that run pad
        the world — an unrelated op's (or an expired window's) named
        straggler must not inflate this run's victim statistics."""
        return max([n_ranks] + [
            f.rank + 1 for f in self.faults
            if f.kind == "skew" and f.rank is not None
            and (op is None or self._skew_matches(f, op, nbytes, run_id))
        ])

    def skew_arrivals_us(self, op: str, nbytes: int, run_id: int, *,
                         world) -> dict[int, float] | None:
        """Every rank's summed skew-fault arrival for one run, in µs —
        or None when no spec matches (no ledger record either: a run a
        skew schedule never touched stays ledger-silent).  Summed
        ACROSS specs per rank before any reduction: two overlapping
        skew sources' worst arrivals can land on different ranks, so
        per-spec costs do not add — combined arrivals do (the driver
        folds the axis arrivals into the same totals for exactly that
        reason).  Ledger side effect: one record per matching spec,
        carrying this rank's own drawn stagger for it."""
        totals: dict[int, float] | None = None
        for idx, f in enumerate(self.faults):
            if not self._skew_matches(f, op, nbytes, run_id):
                continue
            if not any(f.matches_rank(r) for r in world):
                # the named straggler is outside the modeled world:
                # nothing was staggered anywhere, so nothing is
                # ledgered either — a "fired" record for a no-op
                # injection would let a coincidental event pass
                # conformance for a fault that never injected.
                # (skew_world_size pads the world to cover spec ranks,
                # so this guards only callers passing their own world.)
                continue
            if totals is None:
                totals = {r: 0.0 for r in world}
            draws = {
                r: (self._skew_stagger_us(idx, f, r, run_id)
                    if f.matches_rank(r) else 0.0)
                for r in world
            }
            for r in world:
                totals[r] += draws[r]
            self._fault_record(idx, f, run_id, op, nbytes,
                               stagger_us=round(draws[self.rank], 3))
        return totals

    def has_skew(self) -> bool:
        """True when the schedule holds any skew spec (the driver's
        entry-boundary hook is armed only then — zero overhead, and
        zero ledger drift, for every pre-skew schedule)."""
        return any(f.kind == "skew" for f in self.faults)

    # -- the per-run injection point -----------------------------------

    def apply(self, op: str, nbytes: int, run_id: int,
              t: float | None, *, rank: int | None = None) -> float | None:
        """Perturb one run's measured time per the schedule; ``None``
        drops the run (capture loss).  Faults apply in spec order;
        ``drop_run`` short-circuits (there is nothing left to perturb).
        ``rank`` overrides the injector's own rank for this sample (the
        linkmap prober attributes each probe to the link's owning rank);
        rank-filtered specs fire only on a matching rank.  Also advances
        the injector's run cursor, which arms the wrapped ingest hook
        and schedules the ``hook_fail`` forced rotation."""
        r = self.rank if rank is None else rank
        self._current_run = run_id
        for idx, f in enumerate(self.faults):
            if f.kind in ("corrupt", "skew"):
                # corrupt is selftest-time (corrupt_payload); skew is
                # ENTRY-time (entry_skew, before the dispatch) — neither
                # perturbs the measured value here
                continue
            if f.kind == "hook_fail":
                # keyed to the rotation, not to a point: fires once per
                # window, at the window's first run, by forcing a
                # rotation there — a 900 s refresh would otherwise make
                # the failure's run position wall-clock dependent and
                # the ledger non-reproducible.  Rank-filtered: only the
                # named host's ingest hook fails.
                if f.in_window(run_id) and f.matches_rank(self.rank) \
                        and idx not in self._fired_once:
                    self._fired_once.add(idx)
                    self._force_rotation = True
                    self._fault_record(idx, f, run_id, op="", nbytes=0)
                continue
            if not f.matches(op, nbytes, run_id, rank=r):
                continue
            if f.kind == "drop_run":
                self._fault_record(idx, f, run_id, op, nbytes)
                return None
            if t is None:
                continue  # naturally dropped run: nothing to perturb
            if f.kind == "delay":
                t *= 1.0 + f.magnitude
                self._fault_record(idx, f, run_id, op, nbytes)
            elif f.kind == "jitter":
                m = self._jitter_multiplier(f, idx, run_id)
                t *= m
                self._fault_record(idx, f, run_id, op, nbytes,
                                   m=round(m, 9))
            elif f.kind == "spike":
                if idx not in self._fired_once:
                    self._fired_once.add(idx)
                    t *= f.magnitude
                    self._fault_record(idx, f, run_id, op, nbytes)
            elif f.kind == "flatline":
                pin = self._flat_pin.get(idx)
                if pin is None:
                    pin = self._flat_pin[idx] = t
                t = pin
                self._fault_record(idx, f, run_id, op, nbytes)
        return t

    # -- rotation / ingest-hook faults ---------------------------------

    def hook_armed(self) -> bool:
        """True while any hook_fail window (for this rank) covers the
        current run."""
        return any(
            f.kind == "hook_fail" and f.in_window(self._current_run)
            and f.matches_rank(self.rank)
            for f in self.faults
        )

    def wrap_hook(self, hook):
        """The chaos ingest hook: raises while a hook_fail window is
        active (exercising the daemon's never-fatal contract and the
        health subsystem's ``hook_fail`` event), else delegates."""

        def chaos_hook():
            if self.hook_armed():
                raise InjectedHookFailure(
                    f"injected ingest-hook failure (chaos run "
                    f"{self._current_run})"
                )
            if hook is not None:
                hook()

        return chaos_hook

    def take_forced_rotation(self) -> bool:
        """One-shot flag the driver polls after :meth:`apply`: True
        exactly once per hook_fail window, at its first run."""
        fired, self._force_rotation = self._force_rotation, False
        return fired

    # -- payload corruption (selftest rx validation) -------------------

    def corrupt_ops(self) -> list[str]:
        return sorted({
            f.op for f in self.faults
            if f.kind == "corrupt" and f.matches_rank(self.rank)
        })

    def corrupt_payload(self, op: str, out: np.ndarray) -> np.ndarray:
        """Flip one high exponent bit of a deterministic element of the
        op's selftest output — guaranteed far outside any rtol, so an
        rx-validation pass that misses it is broken, not lenient."""
        hit = [
            (idx, f) for idx, f in enumerate(self.faults)
            if f.kind == "corrupt" and f.op == op
            and f.matches_rank(self.rank)
        ]
        if not hit:
            return out
        out = np.array(out, dtype=np.float64, copy=True).reshape(-1)
        for idx, f in hit:
            i = int(self._rand(idx, 0) * out.size) % out.size
            view = out[i:i + 1].view(np.uint64)
            view[:] = view ^ (np.uint64(1) << np.uint64(62))
            self._fault_record(idx, f, 0, op, 0, index=i, bit=62)
        return out

    def record_selftest(self, results) -> None:
        """Ledger the corrupt pass's verdicts (selftest.SelftestResult
        rows) so conformance can judge corrupt faults offline."""
        for r in results:
            self._write(ChaosRecord(
                record="selftest", op=r.op, status=r.status, detail=r.detail,
            ))

    def report(self, msg: str) -> None:
        print(msg, file=self.err if self.err is not None else sys.stderr,
              flush=True)
