"""Seeded fault injection at the driver/op boundary.

The :class:`FaultInjector` sits between ``Driver._measure`` and
``Driver._record_run``: every run's wall time passes through
:meth:`apply`, which perturbs (or drops) it according to the schedule
and writes one ledger record per fired injection.  Because injection
wraps the MEASURED VALUE — not the kernel, the fence, or the backend —
it behaves identically under ``block``/``readback``/``slope``/``trace``
and for both one-shot and daemon loops.

Determinism contract: all randomness is derived by hashing
``(seed, spec-index, run_id)`` (and, for synthetic samples,
``(seed, op, nbytes, visit-count)``) into a fresh ``random.Random`` —
no shared RNG stream whose consumption order could drift.  Same seed +
same spec + same run sequence => the same perturbation stream and a
byte-identical injection ledger (records carry no wall-clock fields).

``synthetic_s`` replaces the measured sample entirely with a seeded
series around a base latency (tiny relative noise, never bit-identical)
— the knob that makes the CI conformance and false-alarm gates
deterministic on shared machines, where real CPU timing outliers would
make a zero-false-alarm assertion flaky.
"""

from __future__ import annotations

import dataclasses
import math
import random
import sys

import numpy as np

from tpu_perf.faults.spec import ChaosRecord, FaultSpec
from tpu_perf.schema import window_index

#: relative amplitude of the synthetic series' seeded noise: big enough
#: that samples never repeat (no false flatline), small enough that a
#: spike fault's z-score clears any sane threshold
SYNTHETIC_NOISE = 1e-3


class InjectedHookFailure(RuntimeError):
    """Raised by the chaos-wrapped ingest hook while a ``hook_fail``
    fault window is active — a distinct type so logs attribute the
    failure to injection, not a real telemetry outage."""


class FaultInjector:
    """One per Driver (``--faults`` / ``--synthetic``); shared by the
    run loop (:meth:`apply`, :meth:`synthetic_sample`), the rotation
    hook (:meth:`wrap_hook`), and the selftest corrupt pass
    (:meth:`corrupt_payload`)."""

    def __init__(
        self,
        faults: list[FaultSpec],
        *,
        seed: int = 0,
        stats_every: int = 1000,
        ledger=None,   # RotatingCsvLog(prefix="chaos", lazy=True) or None
        synthetic_s: float | None = None,
        rank: int = 0,  # this process's rank, judged against FaultSpec.rank
        #               # (a rank-filtered fault fires on ONE host of a
        #               # multi-host soak; the linkmap prober overrides it
        #               # per probe with the link's owning rank)
        err=None,
    ):
        self.faults = list(faults)
        self.seed = seed
        self.stats_every = max(1, stats_every)
        self.ledger = ledger
        self.synthetic_s = synthetic_s
        self.rank = rank
        self.err = err
        self._fired_once: set[int] = set()    # spike/hook_fail: one-shot
        self._flat_pin: dict[int, float] = {}  # flatline: pinned sample
        self._syn_count: dict[tuple[str, int], int] = {}
        self._current_run = 0
        self._force_rotation = False
        #: cumulative run-time injections fired (ledger records written
        #: or suppressed alike) — the driver samples the delta around
        #: each apply() to emit an ``inject`` trace span only for runs a
        #: fault actually touched, without adding any ledger field
        self.fired_total = 0

    # -- ledger ---------------------------------------------------------

    def write_meta(self) -> None:
        """The ledger's header record: everything conformance needs to
        re-derive the schedule (and everything reproduction needs to
        re-run it).  Written eagerly at driver start so even a chaos
        soak whose faults never fire leaves a ledger behind — a
        fault-free soak's conformance pass must know it was fault-free,
        not fileless."""
        self._write(ChaosRecord(
            record="meta",
            seed=self.seed,
            stats_every=self.stats_every,
            synthetic_s=self.synthetic_s,
            faults=[dataclasses.asdict(f) for f in self.faults],
        ))

    def _write(self, rec: ChaosRecord) -> None:
        if self.ledger is not None:
            self.ledger.write_row(rec)

    def _fault_record(self, idx: int, f: FaultSpec, run_id: int,
                      op: str, nbytes: int, **extra) -> None:
        self.fired_total += 1
        self._write(ChaosRecord(
            record="fault", spec=idx, kind=f.kind, op=op, nbytes=nbytes,
            run_id=run_id,
            window=window_index(run_id, self.stats_every), **extra,
        ))

    def maybe_rotate(self) -> None:
        if self.ledger is not None:
            self.ledger.maybe_rotate()

    def close(self) -> None:
        if self.ledger is not None:
            self.ledger.close()

    # -- deterministic randomness --------------------------------------

    def _rng(self, idx: int, run_id: int) -> random.Random:
        """THE seeded stream for (seed, spec-index, run_id) — one key
        spelling, so the byte-identical-ledger contract cannot desync
        between the uniform and shaped jitter paths."""
        return random.Random(f"{self.seed}:{idx}:{run_id}")

    def _rand(self, idx: int, run_id: int) -> float:
        """U(0, 1) from the per-(seed, spec, run) stream — stateless, so
        the stream cannot drift with evaluation order."""
        return self._rng(idx, run_id).random()

    def _jitter_multiplier(self, f: FaultSpec, idx: int, run_id: int) -> float:
        """The seeded noise multiplier for one jitter sample.

        ``uniform`` is the bounded 1 + magnitude * U(-1, 1).  The heavy-
        tailed shapes are MEDIAN-PRESERVING around 1 with a real right
        tail — noise, not a level shift, because the jitter contract is
        that detectors must NOT alert (a sustained shift is what the
        regression detector exists to catch, and would turn every
        shaped-jitter soak into a false-alarm factory): ``lognormal``
        uses magnitude as log-sigma (exp(sigma * N(0,1)), median 1);
        ``pareto`` draws a Pareto of tail index 1/magnitude and divides
        out its median 2**magnitude (magnitude 0.2 => alpha 5: bulk ~1,
        occasionally several-x).  Each sample's draw is a fresh
        (seed, spec, run) Random, so shapes stay exactly as
        reproducible as the uniform stream."""
        rnd = self._rng(idx, run_id)
        if f.shape == "lognormal":
            return math.exp(f.magnitude * rnd.gauss(0.0, 1.0))
        if f.shape == "pareto":
            return rnd.paretovariate(1.0 / f.magnitude) / 2.0 ** f.magnitude
        return 1.0 + f.magnitude * (2.0 * rnd.random() - 1.0)

    # -- synthetic timing source ---------------------------------------

    @property
    def synthetic(self) -> bool:
        return self.synthetic_s is not None

    def synthetic_sample(self, op: str, nbytes: int) -> float:
        """The next sample of this point's seeded series (replaces
        ``Driver._measure`` entirely in synthetic mode)."""
        key = (op, nbytes)
        n = self._syn_count[key] = self._syn_count.get(key, 0) + 1
        u = random.Random(f"{self.seed}:syn:{op}:{nbytes}:{n}").random()
        return self.synthetic_s * (1.0 + SYNTHETIC_NOISE * (u - 0.5))

    # -- the per-run injection point -----------------------------------

    def apply(self, op: str, nbytes: int, run_id: int,
              t: float | None, *, rank: int | None = None) -> float | None:
        """Perturb one run's measured time per the schedule; ``None``
        drops the run (capture loss).  Faults apply in spec order;
        ``drop_run`` short-circuits (there is nothing left to perturb).
        ``rank`` overrides the injector's own rank for this sample (the
        linkmap prober attributes each probe to the link's owning rank);
        rank-filtered specs fire only on a matching rank.  Also advances
        the injector's run cursor, which arms the wrapped ingest hook
        and schedules the ``hook_fail`` forced rotation."""
        r = self.rank if rank is None else rank
        self._current_run = run_id
        for idx, f in enumerate(self.faults):
            if f.kind == "corrupt":
                continue  # selftest-time (corrupt_payload), not run-time
            if f.kind == "hook_fail":
                # keyed to the rotation, not to a point: fires once per
                # window, at the window's first run, by forcing a
                # rotation there — a 900 s refresh would otherwise make
                # the failure's run position wall-clock dependent and
                # the ledger non-reproducible.  Rank-filtered: only the
                # named host's ingest hook fails.
                if f.in_window(run_id) and f.matches_rank(self.rank) \
                        and idx not in self._fired_once:
                    self._fired_once.add(idx)
                    self._force_rotation = True
                    self._fault_record(idx, f, run_id, op="", nbytes=0)
                continue
            if not f.matches(op, nbytes, run_id, rank=r):
                continue
            if f.kind == "drop_run":
                self._fault_record(idx, f, run_id, op, nbytes)
                return None
            if t is None:
                continue  # naturally dropped run: nothing to perturb
            if f.kind == "delay":
                t *= 1.0 + f.magnitude
                self._fault_record(idx, f, run_id, op, nbytes)
            elif f.kind == "jitter":
                m = self._jitter_multiplier(f, idx, run_id)
                t *= m
                self._fault_record(idx, f, run_id, op, nbytes,
                                   m=round(m, 9))
            elif f.kind == "spike":
                if idx not in self._fired_once:
                    self._fired_once.add(idx)
                    t *= f.magnitude
                    self._fault_record(idx, f, run_id, op, nbytes)
            elif f.kind == "flatline":
                pin = self._flat_pin.get(idx)
                if pin is None:
                    pin = self._flat_pin[idx] = t
                t = pin
                self._fault_record(idx, f, run_id, op, nbytes)
        return t

    # -- rotation / ingest-hook faults ---------------------------------

    def hook_armed(self) -> bool:
        """True while any hook_fail window (for this rank) covers the
        current run."""
        return any(
            f.kind == "hook_fail" and f.in_window(self._current_run)
            and f.matches_rank(self.rank)
            for f in self.faults
        )

    def wrap_hook(self, hook):
        """The chaos ingest hook: raises while a hook_fail window is
        active (exercising the daemon's never-fatal contract and the
        health subsystem's ``hook_fail`` event), else delegates."""

        def chaos_hook():
            if self.hook_armed():
                raise InjectedHookFailure(
                    f"injected ingest-hook failure (chaos run "
                    f"{self._current_run})"
                )
            if hook is not None:
                hook()

        return chaos_hook

    def take_forced_rotation(self) -> bool:
        """One-shot flag the driver polls after :meth:`apply`: True
        exactly once per hook_fail window, at its first run."""
        fired, self._force_rotation = self._force_rotation, False
        return fired

    # -- payload corruption (selftest rx validation) -------------------

    def corrupt_ops(self) -> list[str]:
        return sorted({
            f.op for f in self.faults
            if f.kind == "corrupt" and f.matches_rank(self.rank)
        })

    def corrupt_payload(self, op: str, out: np.ndarray) -> np.ndarray:
        """Flip one high exponent bit of a deterministic element of the
        op's selftest output — guaranteed far outside any rtol, so an
        rx-validation pass that misses it is broken, not lenient."""
        hit = [
            (idx, f) for idx, f in enumerate(self.faults)
            if f.kind == "corrupt" and f.op == op
            and f.matches_rank(self.rank)
        ]
        if not hit:
            return out
        out = np.array(out, dtype=np.float64, copy=True).reshape(-1)
        for idx, f in hit:
            i = int(self._rand(idx, 0) * out.size) % out.size
            view = out[i:i + 1].view(np.uint64)
            view[:] = view ^ (np.uint64(1) << np.uint64(62))
            self._fault_record(idx, f, 0, op, 0, index=i, bit=62)
        return out

    def record_selftest(self, results) -> None:
        """Ledger the corrupt pass's verdicts (selftest.SelftestResult
        rows) so conformance can judge corrupt faults offline."""
        for r in results:
            self._write(ChaosRecord(
                record="selftest", op=r.op, status=r.status, detail=r.detail,
            ))

    def report(self, msg: str) -> None:
        print(msg, file=self.err if self.err is not None else sys.stderr,
              flush=True)
