"""Latency / bandwidth math.

The reference defines exactly one derived metric, opt-in Gbps at
mpi_perf.c:535-542::

    8 * buff_len * iters * (2 if bidir else 1) * 1e-9 / my_time

The TPU framework keeps that legacy formula (:func:`legacy_gbps`) and adds
the standard collective *algorithm* and *bus* bandwidth definitions (the
nccl-tests convention, also used by the allreduce literature in PAPERS.md):
bus bandwidth normalizes by the bytes each link must actually carry, so
numbers are comparable across ops and across rank counts.
"""

from __future__ import annotations

# Bus-bandwidth correction factor per collective, as a function of the number
# of participating devices n.  busbw = algbw * factor(n).
_BUS_FACTORS = {
    # ring allreduce moves 2(n-1)/n of the buffer over each link.
    "allreduce": lambda n: 2.0 * (n - 1) / n if n > 1 else 1.0,
    # barrier is latency-only: a 1-element psum, no meaningful bandwidth
    "barrier": lambda n: 0.0,
    "all_gather": lambda n: (n - 1) / n if n > 1 else 1.0,
    "reduce_scatter": lambda n: (n - 1) / n if n > 1 else 1.0,
    "all_to_all": lambda n: (n - 1) / n if n > 1 else 1.0,
    "broadcast": lambda n: 1.0,
    "broadcast_psum": lambda n: 1.0,
    # point-to-point patterns: the wire carries exactly the payload.
    "ppermute": lambda n: 1.0,
    "pingpong": lambda n: 1.0,
    "pingpong_unidir": lambda n: 1.0,
    "exchange": lambda n: 1.0,
    "ring": lambda n: 1.0,
    "halo": lambda n: 1.0,
    # local HBM baseline: each execution reads + writes the buffer once
    "hbm_stream": lambda n: 2.0,
    # single-sided HBM instruments: hbm_read reduces the buffer into one
    # scalar (reads nbytes, writes one element); hbm_write broadcasts one
    # scalar over the buffer (writes nbytes, reads one element).  Their
    # busbw IS the per-path ceiling; hbm_stream's factor-2 number is
    # bounded above by the harmonic mix 2/(1/read + 1/write) and below
    # (roughly) by min(read, write) — measured on v5e it lands on the
    # write path (BASELINE.md "HBM path decomposition").
    "hbm_read": lambda n: 1.0,
    "hbm_write": lambda n: 1.0,
    # triad mix: reads the whole buffer, writes half of it in place —
    # 1.5x nbytes of HBM traffic per iteration (2R:1W, the measured
    # point between hbm_stream's mix and the single-sided ceilings)
    "hbm_triad": lambda n: 1.5,
    # local MXU roofline: memory-traffic view (x and q read, y written);
    # FLOP/s = algbw_GB/s * 1e9 * 2m/itemsize — see _body_mxu_gemm
    "mxu_gemm": lambda n: 3.0,
    # overlap instrument: busbw counts only the ring payload, so the curve
    # is directly comparable to `ring` at the same nbytes
    "overlap_ring": lambda n: 1.0,
    # pallas RDMA kernels (tpu_perf.ops.pallas_ring)
    "pl_ring": lambda n: 1.0,
    "pl_exchange": lambda n: 1.0,
    "pl_all_gather": lambda n: (n - 1) / n if n > 1 else 1.0,
    "pl_reduce_scatter": lambda n: (n - 1) / n if n > 1 else 1.0,
    "pl_allreduce": lambda n: 2.0 * (n - 1) / n if n > 1 else 1.0,
    # serialized RDMA round trip: the wire carries exactly the payload each
    # way (rows use per-direction time, like the XLA pingpong)
    "pl_pingpong": lambda n: 1.0,
    "pl_all_gather_bidir": lambda n: (n - 1) / n if n > 1 else 1.0,
    # local HBM->HBM DMA copy: reads + writes the buffer once per execution
    "pl_hbm_copy": lambda n: 2.0,
    # local vector-path stream: reads + writes once, like hbm_stream
    "pl_hbm_stream": lambda n: 2.0,
    # single-direction DMA sweeps: the buffer crosses the DMA path once
    # per iteration (read into VMEM / written from VMEM), mirroring the
    # XLA hbm_read/hbm_write factors
    "pl_hbm_read": lambda n: 1.0,
    "pl_hbm_write": lambda n: 1.0,
    # semaphore-only global barrier: latency-only, like the XLA barrier
    "pl_barrier": lambda n: 0.0,
    "pl_all_to_all": lambda n: (n - 1) / n if n > 1 else 1.0,
    # print-only external launcher (mpi_perf.c:147-168): nothing crosses the
    # wire; rows record only the wall time, like the reference's CSV does
    "extern": lambda n: 0.0,
    # composed model-step scenarios (tpu_perf.scenarios): a step chains
    # several collectives over several window sizes, so no single
    # bus-bandwidth normalization is honest — rows carry step wall time
    # / lat_us only (the report's Scenario-steps table is the verdict
    # surface; per-phase wire volume comes from the attribution model)
    "scenario": lambda n: 0.0,
}

KNOWN_OPS = tuple(sorted(_BUS_FACTORS))

# kernel aliases that index the bus-factor table through another op
# (hier_allreduce is allreduce over a (dcn, ici) mesh — same wire math;
# the v-variants move the same aggregate volume as their balanced
# counterparts at the row's size semantics, so the standard factors
# keep their curves comparable across the imbalance axis)
_METRIC_ALIASES = {
    "hier_allreduce": "allreduce",
    "allgatherv": "all_gather",
    "reduce_scatter_v": "reduce_scatter",
    "all_to_all_v": "all_to_all",
    "seg_allreduce": "allreduce",
}


def metric_op(op: str) -> str:
    """Resolve a kernel name to the op that carries its bus factor."""
    return _METRIC_ALIASES.get(op, op)


def imbalance_volume_scale(op: str, imbalance: int, n_devices: int) -> float:
    """Wire-volume correction for v-ops whose *moved* bytes shrink with
    imbalance at fixed row nbytes.

    allgatherv / reduce_scatter_v keep aggregate volume pinned to the row
    size by construction (v_counts sizes the buffers so the union of all
    origins' windows IS the row payload), so their balanced bus factors
    are already honest and the scale is 1.0.  Two v-ops are different:

    - ``all_to_all_v``: the row nbytes covers the dense n x maxblock slot
      matrix, but only (n-1+ratio)/(n*ratio) of those slots carry data
      (n-1 base blocks + one hot block of ratio base blocks, out of
      n*ratio base-block slots per rank).
    - ``seg_allreduce``: --imbalance is the DENSITY ratio — only the
      first ceil(n/ratio) of n equal segments are reduced, the tail is
      carried untouched, so the reduced fraction is ceil(n/ratio)/n.

    Multiplied into bus bandwidth by the runner so busbw stays "bytes
    that actually crossed the wire per second" across the imbalance axis.
    """
    r = max(1, int(imbalance))
    if r == 1 or n_devices <= 1:
        return 1.0
    if op == "all_to_all_v":
        return (n_devices - 1 + r) / (n_devices * r)
    if op == "seg_allreduce":
        return -(-n_devices // r) / n_devices
    return 1.0


import math as _math  # noqa: E402 — placed by the table it serves

#: FLOPs one loop iteration performs, per compute op:
#: (nbytes, itemsize) -> flops.  mxu_gemm's buffer is the full m x m
#: operand (ops.payload_elems), one m x m x m matmul per iteration =
#: 2m^3 (the wrap-add's 2m^2 is noise and uncounted, per the BASELINE.md
#: MXU-roofline convention).  Consumed by the grid's --spec-tflops
#: verdicts and by report's derived TFLOP/s column.
FLOPS_PER_ITER = {
    "mxu_gemm":
        lambda nbytes, itemsize: 2.0 * _math.isqrt(nbytes // itemsize) ** 3,
}


#: itemsize per supported payload dtype (config.SUPPORTED_DTYPES),
#: deliberately NOT via np.dtype(): 'bfloat16' is not a stock numpy
#: dtype — it resolves only when ml_dtypes happens to be registered, and
#: the report path must work in a clean install with no jax import.
DTYPE_ITEMSIZE = {
    "float32": 4, "bfloat16": 2, "float16": 2, "int32": 4, "uint8": 1,
}

from tpu_perf.config import SUPPORTED_DTYPES as _SUPPORTED  # noqa: E402

# a dtype added to SUPPORTED_DTYPES without an itemsize here would
# silently render no TFLOP/s for its compute rows — pin the tables.
# A real raise, not assert: `python -O` strips asserts, which is exactly
# the deployment where a silent data gap would go unnoticed.
if set(DTYPE_ITEMSIZE) != set(_SUPPORTED):
    raise RuntimeError(
        "DTYPE_ITEMSIZE and config.SUPPORTED_DTYPES drifted apart: "
        f"{sorted(set(DTYPE_ITEMSIZE) ^ set(_SUPPORTED))}"
    )


def flops_per_iter(op: str, nbytes: int, itemsize: int) -> float | None:
    """FLOPs one iteration of ``op`` performs, or None for ops without a
    compute model (bandwidth/latency instruments)."""
    fn = FLOPS_PER_ITER.get(op)
    return None if fn is None else fn(nbytes, itemsize)


def flops_per_iter_dtype(op: str, nbytes: int, dtype: str) -> float | None:
    """Like :func:`flops_per_iter` but from the dtype NAME; None for
    non-compute ops and for dtypes outside the supported table (foreign
    artifacts must degrade to no-tflops, not crash the report)."""
    itemsize = DTYPE_ITEMSIZE.get(dtype)
    if itemsize is None or op not in FLOPS_PER_ITER:
        return None
    return flops_per_iter(op, nbytes, itemsize)


def is_latency_only(op: str, n_devices: int = 2) -> bool:
    """True for ops whose bus factor is 0 (barrier, extern): their rows
    carry wall time / latency only, bandwidth columns are zeroed."""
    try:
        return _BUS_FACTORS[op](n_devices) == 0.0
    except KeyError:
        raise ValueError(f"unknown op {op!r}; known: {KNOWN_OPS}") from None


def alg_bandwidth_gbps(nbytes: int, seconds: float) -> float:
    """Algorithm bandwidth in GB/s (decimal): payload bytes / wall time."""
    if seconds <= 0:
        raise ValueError(f"non-positive time {seconds}")
    return nbytes * 1e-9 / seconds


def bus_bandwidth_gbps(op: str, nbytes: int, seconds: float, n_devices: int) -> float:
    """Bus bandwidth in GB/s for one execution of ``op`` on ``nbytes``."""
    try:
        factor = _BUS_FACTORS[op](n_devices)
    except KeyError:
        raise ValueError(f"unknown op {op!r}; known: {KNOWN_OPS}") from None
    return alg_bandwidth_gbps(nbytes, seconds) * factor


def legacy_gbps(buff_len: int, iters: int, bidirectional: bool, seconds: float) -> float:
    """The reference's -DREPORT_BANDWIDTH Gbps formula (mpi_perf.c:538-539).

    Note: *bits* per second, decimal giga — unlike the GB/s metrics above.
    """
    if seconds <= 0:
        raise ValueError(f"non-positive time {seconds}")
    dirs = 2 if bidirectional else 1
    return 8.0 * buff_len * iters * dirs * 1e-9 / seconds


def latency_us(seconds: float, iters: int, *, round_trip: bool = False) -> float:
    """Per-operation latency in microseconds from a timed loop of ``iters``.

    With ``round_trip`` the time covers a full ping-pong RTT and the
    one-way latency is half of it (the reference reports full RTT wall time;
    we report one-way for comparability with standard latency benchmarks).
    """
    if iters <= 0:
        raise ValueError(f"non-positive iters {iters}")
    t = seconds / iters
    return (t / 2 if round_trip else t) * 1e6


def percentile(samples: list[float], q: float) -> float:
    """Linear-interpolated percentile (q in [0,100]) without numpy."""
    if not samples:
        raise ValueError("no samples")
    if not 0 <= q <= 100:
        raise ValueError(f"bad percentile {q}")
    xs = sorted(samples)
    if len(xs) == 1:
        return xs[0]
    pos = (len(xs) - 1) * q / 100.0
    lo = int(pos)
    frac = pos - lo
    if lo + 1 >= len(xs):
        return xs[-1]
    return xs[lo] * (1 - frac) + xs[lo + 1] * frac


def summarize(samples: list[float]) -> dict[str, float]:
    """min/max/avg like the reference's three MPI_Allreduce (mpi_perf.c:560-562),
    plus p50/p95/p99 which the reference cannot produce (mean-only)."""
    if not samples:
        raise ValueError("no samples")
    return {
        "min": min(samples),
        "max": max(samples),
        "avg": sum(samples) / len(samples),
        "p50": percentile(samples, 50),
        "p95": percentile(samples, 95),
        "p99": percentile(samples, 99),
    }
