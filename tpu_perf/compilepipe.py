"""Compile pipeline: overlapped AOT precompilation + harness self-profiling.

A wide sweep (8 B-1 GiB x a multi-op family, the BASELINE.json north-star
curve) spends a large share of its wall time *compiling*, not measuring:
every point builds its kernel -- and under the slope/trace fence a second
hi-iters kernel -- synchronously, inline, before the point can run
(tpu_perf/driver.py, tpu_perf/runner.py), and the linkmap all-pairs
tournament compiles one ppermute program per directed link the same way.
This module overlaps that host-CPU work with device measurement -- the
same communication/computation-overlap discipline the related work applies
inside collectives themselves (PiP multi-object collectives, arxiv
2305.10612; imbalanced-arrival allreduce, arxiv 1804.05349), applied to
the harness's own hot path.

Three pieces:

* :class:`CompilePipeline` -- a background-thread AOT precompiler that
  walks the sweep plan ahead of the measurement loop, building and
  compiling upcoming points (``jax.jit(...).lower(x).compile()`` via
  :func:`aot_compile`) while the main thread measures the current point.
  Compilation is **pure host work**: the worker never executes a kernel,
  so device execution order -- and multi-host collective lockstep -- is
  byte-for-byte what the serial engine produces.  Warm-up runs (which DO
  execute collectives) stay on the main thread, in plan order, identical
  on every process.  Look-ahead is bounded by ``depth`` so at most
  ``depth`` unconsumed points' buffers are resident beyond the one being
  measured (the HBM cap; the driver's ``_share_pair`` canon dedup caps it
  further at one buffer per distinct input spec).
* :class:`PhaseTimer` -- the self-profiling half: per-sweep ``compile`` /
  ``measure`` / ``log`` phase totals, accumulated from any thread (the
  pipeline worker adds its build time to ``compile``, so the total is the
  compile WORK done, wherever it ran -- under pipelining it can exceed
  its share of wall clock, which is exactly the overlap being claimed).
  Totals flow into the JSON heartbeat, the ``bench.py`` summary, a
  ``phase-<job>-<rank>.json`` sidecar next to the rotating logs, and the
  ``tpu-perf report`` phase breakdown.
* :func:`enable_compile_cache` -- wires JAX's persistent compilation
  cache (``--compile-cache DIR``) so daemon restarts and CI reruns skip
  recompilation entirely: the cache key is the serialized module +
  compile options, stable across processes for the deterministically
  named kernels the builders emit (``jit_tpuperf_<op>``).

Keying: a sweep point's build is identified by the full
:class:`CompileSpec` ``(op, nbytes, iters, dtype, axis, window, fused,
algo)`` -- distinct specs never collide (every field is load-bearing:
iters changes the fori trip count, window the in-flight buffer stack,
axis the collective's mesh slice, algo the arena decomposition's wire
schedule), equal specs are built once and served to every consumer.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import sys
import threading
import time
from collections import Counter
from typing import Callable, Hashable, Iterable


@dataclasses.dataclass(frozen=True)
class CompileSpec:
    """The full build identity of one sweep point.

    This is the compile-cache key: two points compile to the same
    program iff every field matches.  ``axis`` is normalized to a tuple
    (or None) so the str/tuple spellings of the same single axis hash
    identically, mirroring ``ops.collectives._flat_axes``.
    """

    op: str
    nbytes: int
    iters: int
    dtype: str = "float32"
    axis: tuple[str, ...] | None = None
    window: int = 1
    #: the fused fence's chunk-size set (sorted distinct reps values of
    #: the point's chunk plan; () = not a fused build).  Load-bearing
    #: like every other field: each distinct reps value is its own XLA
    #: program (a different outer trip count), so two jobs whose plans
    #: differ must never share a cache entry.
    fused: tuple[int, ...] = ()
    #: the collective decomposition (tpu_perf.arena; "native" = the XLA
    #: lowering).  Load-bearing: an arena step is a DIFFERENT program
    #: at the same (op, nbytes, iters) — two algorithms racing the same
    #: point must never share a precompiled pair.  Scenario points
    #: carry the scenario label here under op="scenario".
    algo: str = "native"
    #: the v-variant/scenario per-rank payload ratio (tpu_perf.
    #: scenarios, --imbalance; 1 = balanced).  Load-bearing: the counts
    #: are baked into the schedule, so two ratios at one (op, nbytes)
    #: are two different programs.
    imbalance: int = 1
    #: contention-role coordinates (tpu_perf.streams.contend).  The
    #: ordinary overlapped sweep leaves both at their defaults — a lane
    #: runs the SAME program the serial sweep would, so stream must NOT
    #: split the cache there.  The contend runner sets them: a victim
    #: and a load generator that happen to share (op, nbytes) are
    #: different build identities (``load`` names the race; ``stream``
    #: separates K split-channel siblings whose ppermute schedules
    #: differ per lane).
    stream: int = 0
    load: str = ""

    @staticmethod
    def normalize_axis(axis) -> tuple[str, ...] | None:
        if axis is None:
            return None
        if isinstance(axis, str):
            return (axis,)
        return tuple(axis)

    @classmethod
    def make(cls, op: str, nbytes: int, iters: int, *, dtype: str = "float32",
             axis=None, window: int = 1,
             fused: tuple[int, ...] = (),
             algo: str = "native",
             imbalance: int = 1,
             stream: int = 0,
             load: str = "") -> "CompileSpec":
        return cls(op=op, nbytes=nbytes, iters=iters, dtype=dtype,
                   axis=cls.normalize_axis(axis), window=window,
                   fused=tuple(sorted(set(fused))), algo=algo,
                   imbalance=imbalance, stream=stream, load=load)


class PhaseTimer:
    """Accumulates per-phase wall time: where does the harness spend it?

    Phases are ``compile`` (kernel build + XLA compilation + warm-up --
    everything a point needs before its first timed sample), ``measure``
    (the timed windows themselves), and ``log`` (rotation, row emission,
    heartbeats, health/injection bookkeeping).  ``add`` is thread-safe:
    the precompile worker contributes its build durations to ``compile``
    from its own thread, so the total is compile WORK done, not
    main-thread time -- under pipelining ``compile_s`` can exceed its
    share of the wall clock, which is the overlap made visible.
    """

    PHASES = ("compile", "measure", "log")

    def __init__(self, perf_clock: Callable[[], float] = time.perf_counter):
        self._clock = perf_clock
        self._lock = threading.Lock()
        self._totals = {name: 0.0 for name in self.PHASES}
        self._started: float | None = None
        self._wall = 0.0

    def start(self) -> None:
        """Open the wall-clock window (idempotent while open)."""
        if self._started is None:
            self._started = self._clock()

    def stop(self) -> None:
        if self._started is not None:
            self._wall += self._clock() - self._started
            self._started = None

    @property
    def wall_s(self) -> float:
        extra = 0.0 if self._started is None else self._clock() - self._started
        return self._wall + extra

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = self._clock()
        try:
            yield
        finally:
            self.add(name, self._clock() - t0)

    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            self._totals[name] = self._totals.get(name, 0.0) + seconds

    def snapshot(self) -> dict[str, float]:
        """``{"compile_s": ..., "measure_s": ..., "log_s": ...}`` -- the
        shape the heartbeat, bench payload, and sidecar all carry."""
        with self._lock:
            return {f"{k}_s": round(v, 6) for k, v in self._totals.items()}


def aot_compile_step(step, x, *, err=None):
    """Force XLA compilation of jitted ``step`` for input ``x`` NOW, on
    the calling thread; returns the compiled executable (callable like
    the jitted original, module name -- the trace fence's hint --
    preserved by the lowering).  Pure host work: nothing executes on the
    device.  Objects with no ``.lower`` (already-compiled executables,
    extern stand-ins) pass through; a compile failure falls back to the
    uncompiled step with a note, so pipelined mode can never fail where
    serial mode (which compiles lazily at first call) would succeed."""
    if step is None or not hasattr(step, "lower"):
        return step
    try:
        return step.lower(x).compile()
    except Exception as e:  # noqa: BLE001 -- deferred first-call compile
        # is the serial engine's behavior; keep it as the fallback
        print(f"[tpu-perf] AOT precompile failed (falling back to "
              f"compile-at-first-call): {e}",
              file=err if err is not None else sys.stderr)
        return step


def aot_compile(built, *, err=None):
    """AOT-compile a BuiltOp's step against its example input; returns a
    copy with ``step`` replaced by the compiled executable (``None`` and
    stand-ins without step/example pass through unchanged)."""
    if built is None:
        return None
    step = getattr(built, "step", None)
    x = getattr(built, "example_input", None)
    if step is None or x is None:
        return built
    compiled = aot_compile_step(step, x, err=err)
    if compiled is step:
        return built
    return dataclasses.replace(built, step=compiled)


class CompilePipeline:
    """Background-thread AOT precompiler over an ordered build plan.

    ``build(key)`` runs on ONE worker thread, at most ``depth`` plan
    entries ahead of what :meth:`get` has consumed (the look-ahead bound
    that caps resident example-buffer memory).  Equal keys build once:
    later occurrences are cache hits.  Build exceptions are captured and
    re-raised at the consumer's ``get`` -- the point that would have
    failed serially fails at the same place pipelined, and earlier
    points are unaffected.

    The worker must never execute device collectives: ``build`` is
    compile-side only (lower/compile/device_put).  Warm-up -- which runs
    the kernel -- belongs to the consumer, on the main thread, in plan
    order, so multi-host execution order is exactly the serial engine's.
    """

    def __init__(
        self,
        build: Callable[[Hashable], object],
        plan: Iterable[Hashable],
        *,
        depth: int = 2,
        phases: PhaseTimer | None = None,
        tracer=None,  # spans.SpanTracer: each worker build becomes a
        #               "build" span on the worker track, making the
        #               overlap the phase-sum invariant proves VISIBLE
        #               in the exported timeline
        err=None,
    ):
        if depth < 1:
            raise ValueError(f"look-ahead depth must be >= 1, got {depth}")
        self._build = build
        self._plan = list(plan)
        if tracer is None:
            from tpu_perf.spans import NULL_TRACER

            tracer = NULL_TRACER
        self._tracer = tracer
        if not self._plan:
            raise ValueError("empty build plan")
        self._pending = Counter(self._plan)  # tpuperf: guarded-by(_cond)
        self._depth = depth  # tpuperf: guarded-by(_cond)
        self._phases = phases
        self._err = err if err is not None else sys.stderr
        self._cond = threading.Condition()
        # worker/consumer shared state: every touch outside __init__
        # must hold _cond (tpu-perf lint R5 proves it at parse time)
        self._results: dict = {}  # tpuperf: guarded-by(_cond)
        self._consumed = 0  # tpuperf: guarded-by(_cond)
        self._closed = False  # tpuperf: guarded-by(_cond)
        self._done = False  # tpuperf: guarded-by(_cond)
        #: distinct keys actually built (equal specs hit, never rebuild)
        self.builds = 0  # tpuperf: guarded-by(_cond)
        self._thread = threading.Thread(
            target=self._worker, name="tpu-perf-precompile", daemon=True
        )
        self._thread.start()

    def _worker(self) -> None:
        # worker-local dedup: _results is NOT a record of what was built
        # (get() prunes fully-consumed entries), so inferring "already
        # built" from it races the consumer — a pruned duplicate would
        # be rebuilt, breaking the build-once guarantee and leaking the
        # rebuilt artifact's buffers until close()
        built_keys: set = set()
        try:
            for i, key in enumerate(self._plan):
                with self._cond:
                    while (i - self._consumed >= self._depth
                           and not self._closed):
                        self._cond.wait()
                    if self._closed:
                        return
                if key in built_keys:
                    continue  # equal spec: cache hit, nothing rebuilt
                built_keys.add(key)
                ctx = (self._phases.phase("compile")
                       if self._phases is not None else contextlib.nullcontext())
                art, exc = None, None
                with ctx, self._span(key):
                    try:
                        art = self._build(key)
                    except BaseException as e:  # noqa: BLE001 -- surfaces
                        # at the consumer's get(), like a serial failure
                        exc = e
                with self._cond:
                    self.builds += 1
                    self._results[key] = (art, exc)
                    self._cond.notify_all()
        finally:
            with self._cond:
                self._done = True
                self._cond.notify_all()

    def _span(self, key):
        """The worker build's trace span; a CompileSpec-like key labels
        it (op, nbytes), anything else (the linkmap prober's walk
        indices) is carried as its repr."""
        op, nbytes = getattr(key, "op", None), getattr(key, "nbytes", None)
        if op is not None:
            return self._tracer.span("build", op=op, nbytes=nbytes)
        return self._tracer.span("build", key=repr(key))

    @property
    def depth(self) -> int:
        """Current look-ahead bound (live-tunable, see set_depth)."""
        with self._cond:
            return self._depth

    def set_depth(self, depth: int) -> None:
        """Re-bound the look-ahead mid-run (``--precompile auto``: the
        tuner grows/shrinks the window as the measured compile/measure
        ratio evolves).  Thread-safe; growing wakes a waiting worker
        immediately, shrinking only throttles FUTURE builds — artifacts
        already built stay resident until consumed (memory ratchets
        down one consume at a time, never by discarding work)."""
        if depth < 1:
            raise ValueError(f"look-ahead depth must be >= 1, got {depth}")
        with self._cond:
            self._depth = depth
            self._cond.notify_all()

    def get(self, key):
        """Block until ``key``'s artifact is ready; re-raises its build
        exception.  Consuming releases one look-ahead credit.  Artifacts
        are dropped once every plan occurrence of the key has been
        consumed, so the window's memory stays bounded."""
        with self._cond:
            if self._pending.get(key, 0) <= 0:
                raise KeyError(
                    f"{key!r} is not in the pipeline's plan (or already "
                    "fully consumed)"
                )
            while key not in self._results:
                if self._done or self._closed:
                    raise RuntimeError(
                        f"precompile worker exited before building {key!r}"
                    )
                self._cond.wait()
            art, exc = self._results[key]
            self._consumed += 1
            self._pending[key] -= 1
            if self._pending[key] <= 0 and exc is None:
                del self._results[key]  # free the look-ahead slot's memory
            self._cond.notify_all()
        if exc is not None:
            raise exc
        return art

    def close(self, timeout: float = 60.0) -> None:
        """Stop the worker (it finishes any in-flight build first)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout)
        if self._thread.is_alive():
            print("[tpu-perf] precompile worker still busy at close "
                  "(daemon thread, will not block exit)", file=self._err)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def enable_compile_cache(path: str) -> str:
    """Point JAX's persistent compilation cache at ``path`` (created if
    missing) and zero the eligibility thresholds: the harness's kernels
    are small, fast-compiling programs that the default >=1 s /
    min-entry-size gates would skip -- exactly the entries a daemon
    restart or CI rerun wants to reuse.  Returns ``path``.

    Must run before the kernels compile (the Driver calls it in
    ``__init__``); the knobs are process-global, which is the point --
    one flag warms every compile in the job, including the precompile
    worker's.
    """
    os.makedirs(path, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, value)
        except (AttributeError, ValueError):
            # older jax: threshold knob absent -- the cache still works,
            # it just skips sub-threshold entries
            pass
    try:
        # the cache backend latches (enabled-or-not, and at which dir) at
        # the process's FIRST compilation; anything may have compiled
        # before this call (the --fence auto probe capture, a mesh
        # helper), which would latch "disabled" and silently ignore the
        # directory -- reset so the next compile re-initializes onto it
        from jax.experimental.compilation_cache import compilation_cache

        compilation_cache.reset_cache()
    except Exception:  # noqa: BLE001 -- a jax without reset_cache still
        # honors the config when nothing compiled yet
        pass
    return path
