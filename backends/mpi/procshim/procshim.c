/* procshim.c — process-per-rank MPI subset over Unix-domain sockets.
 *
 * Exists to compile and run /root/reference/mpi_perf.c UNMODIFIED on a
 * machine with no MPI installation (the interop proof: its tcp-*.log
 * rows must flow through `tpu-perf report --legacy` and the ingest
 * pipeline).  The reference keeps mutable state in file-scope globals
 * (world_rank, bench_options, log_fp — mpi_perf.c:18,270-271), so ranks
 * must be processes, not threads; shim_mpirun forks one process per
 * rank and this library connects them in a full mesh of SOCK_STREAM
 * Unix sockets under $SHIM_DIR.
 *
 * Model:
 *  - one listening socket per rank ($SHIM_DIR/s<rank>); rank r connects
 *    to every lower rank and accepts from every higher rank, so the
 *    mesh needs no rendezvous server;
 *  - frames are {int32 src, int32 tag, uint32 len} + payload; matching
 *    is by (source, wire tag) against a receive queue, where the wire
 *    tag folds the communicator in (p2p: ps_wire_tag; collectives: the
 *    reserved per-comm tag space), so collective traffic, the driver's
 *    tag-1/2 kernel traffic, and same-(src, tag) posts on different
 *    comms all interleave without aliasing; an oversized frame fails
 *    loudly (MPI_ERR_TRUNCATE analogue) instead of delivering a prefix;
 *  - sends copy into a per-peer out-queue and complete immediately; the
 *    progress loop (poll on all fds) drains out-queues and fills the
 *    receive queue whenever any MPI call waits.  Unbounded buffering is
 *    fine for a test harness — the reference's deepest pipeline is the
 *    256-slot window (mpi_perf.c:88);
 *  - collectives are rooted at the communicator's first member over the
 *    point-to-point layer (gather + fan-out).  All members call them in
 *    the same order, and Unix sockets are FIFO per peer, so one
 *    reserved tag per communicator suffices.
 *
 * Env (set by shim_mpirun): SHIM_NRANKS, SHIM_RANK, SHIM_DIR,
 * SHIM_HOSTNAME (per-rank "processor name" — numeric 127.0.x.1 strings
 * so the reference's getaddrinfo-based get_ipaddress (mpi_perf.c:180)
 * resolves them without /etc/hosts entries), plus
 * OMPI_COMM_WORLD_LOCAL_RANK which the reference reads directly
 * (mpi_perf.c:378).
 */
#include <mpi.h>

#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <time.h>
#include <unistd.h>

#include "uuid/uuid.h"

#define PS_MAX_RANKS 64
#define PS_MAX_COMMS 8
#define PS_COLL_TAG_BASE 0x40000000
/* p2p wire tags encode the communicator so two comms posting the same
 * (src, tag) cannot cross-match: wire = comm * SPAN + tag.  SPAN *
 * PS_MAX_COMMS == PS_COLL_TAG_BASE exactly, so encoded p2p tags and the
 * reserved collective tag space (which already folds the comm handle in,
 * ps_coll_tag) never overlap. */
#define PS_P2P_TAG_SPAN (PS_COLL_TAG_BASE / PS_MAX_COMMS)

static int ps_nranks = -1, ps_rank = -1;
static int ps_fd[PS_MAX_RANKS];

/* ---- frame queues ---- */

typedef struct ps_msg {
    int src, tag;
    uint32_t len;
    char *data;
    struct ps_msg *next;
} ps_msg;

static ps_msg *ps_inq_head, *ps_inq_tail;

typedef struct ps_out {
    char *data;
    size_t len, off;
    struct ps_out *next;
} ps_out;

static ps_out *ps_outq_head[PS_MAX_RANKS], *ps_outq_tail[PS_MAX_RANKS];

/* per-peer read reassembly state */
typedef struct {
    char hdr[12];
    size_t hdr_got;
    ps_msg *msg;  /* non-NULL while reading a payload */
    size_t payload_got;
} ps_rdstate;

static ps_rdstate ps_rd[PS_MAX_RANKS];

/* ---- requests (Isend completes at enqueue; only recvs are tracked) ---- */

typedef struct {
    int used;
    int done;
    int src, tag;     /* src is a WORLD rank, tag a WIRE tag (comm folded
                       * in via ps_wire_tag) — frames carry both */
    int src_local;    /* the comm-local rank the caller posted — what
                       * MPI_Status.MPI_SOURCE must report */
    int tag_posted;   /* the caller's tag, for MPI_Status.MPI_TAG */
    uint64_t seq;     /* posting order; slot indices recycle, so delivery
                       * matches the OLDEST pending request by seq, not
                       * the lowest slot index */
    void *buf;
    size_t cap;
    MPI_Status status;
} ps_req;

static uint64_t ps_req_seq;

/* Grows on demand: the reference's windowed kernel never waits the
 * request posted at slot 255 of each 256-iteration window
 * (mpi_perf.c:108-113 waits inflight=255 of the 256 posted), so two
 * slots leak per window and a fixed table would abort a long soak.
 * Unwaited-but-done slots are never reclaimed — scavenging would break
 * a caller that still holds the handle — so an infinite -r -1 soak
 * grows by ~64 bytes per 128 windowed iterations; acceptable for a
 * test harness. */
static ps_req *ps_reqs;
static int ps_nreqs;

/* ---- communicators ---- */

typedef struct {
    int size;
    int me;                      /* my index within members */
    int members[PS_MAX_RANKS];   /* world ranks */
} ps_comm;

static ps_comm ps_comms[PS_MAX_COMMS];
static int ps_ncomms;

static void ps_die(const char *what) {
    fprintf(stderr, "[procshim rank %d] %s: %s\n", ps_rank, what,
            strerror(errno));
    exit(EXIT_FAILURE);
}

static size_t ps_dtsize(MPI_Datatype dt) {
    switch (dt) {
    case MPI_BYTE:
    case MPI_CHAR:
        return 1;
    case MPI_INT:
    case MPI_FLOAT:
        return 4;
    case MPI_DOUBLE:
        return 8;
    }
    fprintf(stderr, "[procshim] unsupported datatype %d\n", dt);
    exit(EXIT_FAILURE);
}

/* ---- transport ---- */

static void ps_set_nonblock(int fd) {
    int fl = fcntl(fd, F_GETFL, 0);
    if (fl < 0 || fcntl(fd, F_SETFL, fl | O_NONBLOCK) < 0)
        ps_die("fcntl");
}

static void ps_sock_path(char *out, size_t cap, int rank) {
    const char *dir = getenv("SHIM_DIR");
    if (!dir) {
        fprintf(stderr, "[procshim] SHIM_DIR not set (run under shim_mpirun)\n");
        exit(EXIT_FAILURE);
    }
    snprintf(out, cap, "%s/s%d", dir, rank);
}

static void ps_enqueue_out(int peer, const void *hdr, size_t hlen,
                           const void *payload, size_t plen) {
    ps_out *o = malloc(sizeof *o);
    if (!o) ps_die("malloc");
    o->len = hlen + plen;
    o->off = 0;
    o->next = NULL;
    o->data = malloc(o->len ? o->len : 1);
    if (!o->data) ps_die("malloc");
    memcpy(o->data, hdr, hlen);
    if (plen) memcpy(o->data + hlen, payload, plen);
    if (ps_outq_tail[peer])
        ps_outq_tail[peer]->next = o;
    else
        ps_outq_head[peer] = o;
    ps_outq_tail[peer] = o;
}

static void ps_queue_frame(int peer, int tag, const void *payload, size_t len) {
    char hdr[12];
    int32_t src32 = ps_rank, tag32 = tag;
    uint32_t len32 = (uint32_t)len;
    memcpy(hdr, &src32, 4);
    memcpy(hdr + 4, &tag32, 4);
    memcpy(hdr + 8, &len32, 4);
    ps_enqueue_out(peer, hdr, sizeof hdr, payload, len);
}

/* real MPI would raise MPI_ERR_TRUNCATE; silently delivering a prefix
 * would mask a size-mismatch bug in the caller (ADVICE r4) */
static void ps_check_len(const ps_msg *m, size_t cap) {
    if (m->len > cap) {
        fprintf(stderr,
                "[procshim rank %d] truncation: %u-byte frame from rank "
                "%d (tag %d) exceeds the %zu-byte posted buffer\n",
                ps_rank, m->len, m->src, m->tag, cap);
        exit(EXIT_FAILURE);
    }
}

static void ps_deliver(ps_msg *m) {
    /* try posted Irecvs first (they were posted before the data arrived);
     * same-(src,tag) recvs must fill in POSTING order — slot indices
     * recycle, so the oldest pending request by seq wins */
    ps_req *oldest = NULL;
    for (int i = 0; i < ps_nreqs; i++) {
        ps_req *r = &ps_reqs[i];
        if (r->used && !r->done && r->buf != NULL && r->src == m->src &&
            r->tag == m->tag && (oldest == NULL || r->seq < oldest->seq))
            oldest = r;
    }
    if (oldest != NULL) {
        ps_req *r = oldest;
        ps_check_len(m, r->cap);
        memcpy(r->buf, m->data, m->len);
        /* MPI_SOURCE/MPI_TAG report what the caller POSTED (comm-local
         * rank, un-encoded tag), matching the immediate-match path and
         * blocking MPI_Recv */
        r->status.MPI_SOURCE = r->src_local;
        r->status.MPI_TAG = r->tag_posted;
        r->status.MPI_ERROR = MPI_SUCCESS;
        r->done = 1;
        free(m->data);
        free(m);
        return;
    }
    m->next = NULL;
    if (ps_inq_tail)
        ps_inq_tail->next = m;
    else
        ps_inq_head = m;
    ps_inq_tail = m;
}

static void ps_read_peer(int peer) {
    for (;;) {
        ps_rdstate *st = &ps_rd[peer];
        if (st->msg == NULL) {
            ssize_t n = read(ps_fd[peer], st->hdr + st->hdr_got,
                             sizeof st->hdr - st->hdr_got);
            if (n == 0) return; /* peer finished and closed: no more data */
            if (n < 0) {
                if (errno == EAGAIN || errno == EWOULDBLOCK) return;
                ps_die("read");
            }
            st->hdr_got += (size_t)n;
            if (st->hdr_got < sizeof st->hdr) return;
            int32_t src32, tag32;
            uint32_t len32;
            memcpy(&src32, st->hdr, 4);
            memcpy(&tag32, st->hdr + 4, 4);
            memcpy(&len32, st->hdr + 8, 4);
            st->msg = malloc(sizeof *st->msg);
            if (!st->msg) ps_die("malloc");
            st->msg->src = src32;
            st->msg->tag = tag32;
            st->msg->len = len32;
            st->msg->data = malloc(len32 ? len32 : 1);
            if (!st->msg->data) ps_die("malloc");
            st->payload_got = 0;
            st->hdr_got = 0;
        }
        while (st->payload_got < st->msg->len) {
            ssize_t n = read(ps_fd[peer], st->msg->data + st->payload_got,
                             st->msg->len - st->payload_got);
            if (n == 0) return;
            if (n < 0) {
                if (errno == EAGAIN || errno == EWOULDBLOCK) return;
                ps_die("read");
            }
            st->payload_got += (size_t)n;
        }
        ps_deliver(st->msg);
        st->msg = NULL;
    }
}

static void ps_write_peer(int peer) {
    while (ps_outq_head[peer]) {
        ps_out *o = ps_outq_head[peer];
        ssize_t n = write(ps_fd[peer], o->data + o->off, o->len - o->off);
        if (n < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK) return;
            ps_die("write");
        }
        o->off += (size_t)n;
        if (o->off < o->len) return;
        ps_outq_head[peer] = o->next;
        if (!ps_outq_head[peer]) ps_outq_tail[peer] = NULL;
        free(o->data);
        free(o);
    }
}

/* One bounded progress step: poll every peer fd, drain what's ready.
 * `block` waits for activity; otherwise returns immediately. */
static void ps_progress(int block) {
    struct pollfd pfds[PS_MAX_RANKS];
    int idx_to_peer[PS_MAX_RANKS];
    int n = 0;
    for (int p = 0; p < ps_nranks; p++) {
        if (p == ps_rank) continue;
        pfds[n].fd = ps_fd[p];
        pfds[n].events = POLLIN | (ps_outq_head[p] ? POLLOUT : 0);
        pfds[n].revents = 0;
        idx_to_peer[n] = p;
        n++;
    }
    int rc = poll(pfds, (nfds_t)n, block ? 1000 : 0);
    if (rc < 0) {
        if (errno == EINTR) return;
        ps_die("poll");
    }
    for (int i = 0; i < n; i++) {
        if (pfds[i].revents & (POLLIN | POLLHUP))
            ps_read_peer(idx_to_peer[i]);
        if (pfds[i].revents & POLLOUT)
            ps_write_peer(idx_to_peer[i]);
    }
}

static ps_msg *ps_match(int src, int tag) {
    ps_msg *prev = NULL;
    for (ps_msg *m = ps_inq_head; m; prev = m, m = m->next) {
        if (m->src == src && m->tag == tag) {
            if (prev)
                prev->next = m->next;
            else
                ps_inq_head = m->next;
            if (m == ps_inq_tail) ps_inq_tail = prev;
            return m;
        }
    }
    return NULL;
}

/* ---- MPI surface ---- */

int MPI_Init(int *argc, char ***argv) {
    (void)argc;
    (void)argv;
    const char *nr = getenv("SHIM_NRANKS"), *rk = getenv("SHIM_RANK");
    if (!nr || !rk) {
        fprintf(stderr, "[procshim] SHIM_NRANKS/SHIM_RANK not set "
                        "(run under shim_mpirun)\n");
        exit(EXIT_FAILURE);
    }
    ps_nranks = atoi(nr);
    ps_rank = atoi(rk);
    if (ps_nranks < 1 || ps_nranks > PS_MAX_RANKS || ps_rank < 0 ||
        ps_rank >= ps_nranks) {
        fprintf(stderr, "[procshim] bad SHIM_NRANKS=%s SHIM_RANK=%s\n", nr, rk);
        exit(EXIT_FAILURE);
    }
    for (int i = 0; i < PS_MAX_RANKS; i++) ps_fd[i] = -1;

    /* 1. listener first, so lower ranks can connect before we do */
    char path[108];
    ps_sock_path(path, sizeof path, ps_rank);
    int lfd = socket(AF_UNIX, SOCK_STREAM, 0);
    if (lfd < 0) ps_die("socket");
    struct sockaddr_un addr;
    memset(&addr, 0, sizeof addr);
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, path, sizeof addr.sun_path - 1);
    unlink(path);
    if (bind(lfd, (struct sockaddr *)&addr, sizeof addr) < 0) ps_die("bind");
    if (listen(lfd, PS_MAX_RANKS) < 0) ps_die("listen");

    /* 2. connect to every lower rank (their listener exists or will,
     *    retry briefly); identify ourselves with one rank byte */
    for (int p = 0; p < ps_rank; p++) {
        int fd = socket(AF_UNIX, SOCK_STREAM, 0);
        if (fd < 0) ps_die("socket");
        struct sockaddr_un pa;
        memset(&pa, 0, sizeof pa);
        pa.sun_family = AF_UNIX;
        ps_sock_path(pa.sun_path, sizeof pa.sun_path, p);
        int tries = 0;
        while (connect(fd, (struct sockaddr *)&pa, sizeof pa) < 0) {
            if (++tries > 10000) ps_die("connect (peer never listened)");
            struct timespec ts = {0, 1000000}; /* 1 ms */
            nanosleep(&ts, NULL);
        }
        unsigned char b = (unsigned char)ps_rank;
        if (write(fd, &b, 1) != 1) ps_die("hello write");
        ps_fd[p] = fd;
    }

    /* 3. accept from every higher rank */
    for (int k = ps_rank + 1; k < ps_nranks; k++) {
        int fd = accept(lfd, NULL, NULL);
        if (fd < 0) ps_die("accept");
        unsigned char b;
        if (read(fd, &b, 1) != 1) ps_die("hello read");
        if (b >= PS_MAX_RANKS || ps_fd[b] != -1) {
            fprintf(stderr, "[procshim] bad hello from rank %d\n", (int)b);
            exit(EXIT_FAILURE);
        }
        ps_fd[b] = fd;
    }
    close(lfd);
    for (int p = 0; p < ps_nranks; p++)
        if (p != ps_rank) ps_set_nonblock(ps_fd[p]);

    ps_comms[0].size = ps_nranks;
    ps_comms[0].me = ps_rank;
    for (int i = 0; i < ps_nranks; i++) ps_comms[0].members[i] = i;
    ps_ncomms = 1;
    return MPI_SUCCESS;
}

int MPI_Finalize(void) {
    MPI_Barrier(MPI_COMM_WORLD); /* nobody closes while peers still read */
    for (int p = 0; p < ps_nranks; p++) {
        while (ps_outq_head[p]) ps_progress(1);
        if (p != ps_rank && ps_fd[p] >= 0) close(ps_fd[p]);
    }
    return MPI_SUCCESS;
}

static ps_comm *ps_get_comm(MPI_Comm comm) {
    if (comm < 0 || comm >= ps_ncomms) {
        fprintf(stderr, "[procshim] bad communicator %d\n", comm);
        exit(EXIT_FAILURE);
    }
    return &ps_comms[comm];
}

int MPI_Comm_size(MPI_Comm comm, int *size) {
    *size = ps_get_comm(comm)->size;
    return MPI_SUCCESS;
}

int MPI_Comm_rank(MPI_Comm comm, int *rank) {
    *rank = ps_get_comm(comm)->me;
    return MPI_SUCCESS;
}

int MPI_Get_processor_name(char *name, int *resultlen) {
    const char *h = getenv("SHIM_HOSTNAME");
    if (!h) h = "shimhost";
    snprintf(name, MPI_MAX_PROCESSOR_NAME, "%s", h);
    *resultlen = (int)strlen(name);
    return MPI_SUCCESS;
}

/* Fold the communicator into a p2p wire tag (ADVICE r4: matching by
 * (src, tag) alone would cross-match two comms posting the same pair).
 * Collective-space tags (>= PS_COLL_TAG_BASE) already encode the comm
 * handle (ps_coll_tag) and pass through unchanged. */
static int ps_wire_tag(MPI_Comm comm, int tag) {
    if (tag >= PS_COLL_TAG_BASE) return tag;
    if (tag < 0 || tag >= PS_P2P_TAG_SPAN) {
        fprintf(stderr, "[procshim rank %d] tag %d outside [0, %d)\n",
                ps_rank, tag, PS_P2P_TAG_SPAN);
        exit(EXIT_FAILURE);
    }
    return (int)comm * PS_P2P_TAG_SPAN + tag;
}

int MPI_Send(const void *buf, int count, MPI_Datatype dt, int dest, int tag,
             MPI_Comm comm) {
    ps_comm *c = ps_get_comm(comm);
    ps_queue_frame(c->members[dest], ps_wire_tag(comm, tag), buf,
                   (size_t)count * ps_dtsize(dt));
    ps_progress(0); /* opportunistic flush; Recv/Waitall drain the rest */
    return MPI_SUCCESS;
}

int MPI_Recv(void *buf, int count, MPI_Datatype dt, int source, int tag,
             MPI_Comm comm, MPI_Status *status) {
    ps_comm *c = ps_get_comm(comm);
    int src_world = c->members[source];
    ps_msg *m;
    while ((m = ps_match(src_world, ps_wire_tag(comm, tag))) == NULL)
        ps_progress(1);
    ps_check_len(m, (size_t)count * ps_dtsize(dt));
    memcpy(buf, m->data, m->len);
    if (status && status != MPI_STATUS_IGNORE) {
        status->MPI_SOURCE = source;
        status->MPI_TAG = tag;
        status->MPI_ERROR = MPI_SUCCESS;
    }
    free(m->data);
    free(m);
    return MPI_SUCCESS;
}

static int ps_alloc_req(void) {
    for (int i = 0; i < ps_nreqs; i++)
        if (!ps_reqs[i].used) return i;
    int grown = ps_nreqs ? ps_nreqs * 2 : 1024;
    ps_req *p = realloc(ps_reqs, sizeof(ps_req) * (size_t)grown);
    if (!p) ps_die("realloc");
    memset(p + ps_nreqs, 0, sizeof(ps_req) * (size_t)(grown - ps_nreqs));
    ps_reqs = p;
    int i = ps_nreqs;
    ps_nreqs = grown;
    return i;
}

int MPI_Isend(const void *buf, int count, MPI_Datatype dt, int dest, int tag,
              MPI_Comm comm, MPI_Request *req) {
    /* the payload is copied into the out-queue, so the caller's buffer is
     * immediately reusable — the request is born complete */
    MPI_Send(buf, count, dt, dest, tag, comm);
    int i = ps_alloc_req();
    ps_reqs[i].used = 1;
    ps_reqs[i].done = 1;
    ps_reqs[i].buf = NULL;
    ps_reqs[i].seq = ps_req_seq++;
    ps_reqs[i].status.MPI_SOURCE = dest;
    ps_reqs[i].status.MPI_TAG = tag;
    ps_reqs[i].status.MPI_ERROR = MPI_SUCCESS;
    *req = i;
    return MPI_SUCCESS;
}

int MPI_Irecv(void *buf, int count, MPI_Datatype dt, int source, int tag,
              MPI_Comm comm, MPI_Request *req) {
    ps_comm *c = ps_get_comm(comm);
    int i = ps_alloc_req();
    ps_req *r = &ps_reqs[i];
    r->used = 1;
    r->done = 0;
    r->src = c->members[source];
    r->src_local = source;
    r->seq = ps_req_seq++;
    r->tag = ps_wire_tag(comm, tag);
    r->tag_posted = tag;
    r->buf = buf;
    r->cap = (size_t)count * ps_dtsize(dt);
    /* a matching frame may already sit in the queue */
    ps_msg *m = ps_match(r->src, r->tag);
    if (m) {
        ps_check_len(m, r->cap);
        memcpy(buf, m->data, m->len);
        r->status.MPI_SOURCE = source;
        r->status.MPI_TAG = tag;
        r->status.MPI_ERROR = MPI_SUCCESS;
        r->done = 1;
        free(m->data);
        free(m);
    }
    *req = i;
    return MPI_SUCCESS;
}

int MPI_Waitall(int count, MPI_Request reqs[], MPI_Status statuses[]) {
    for (;;) {
        int pending = 0;
        for (int i = 0; i < count; i++) {
            if (reqs[i] == MPI_REQUEST_NULL) continue;
            if (!ps_reqs[reqs[i]].done) pending = 1;
        }
        if (!pending) break;
        ps_progress(1);
    }
    for (int i = 0; i < count; i++) {
        if (reqs[i] == MPI_REQUEST_NULL) continue;
        if (statuses && statuses != MPI_STATUSES_IGNORE)
            statuses[i] = ps_reqs[reqs[i]].status;
        ps_reqs[reqs[i]].used = 0;
        reqs[i] = MPI_REQUEST_NULL;
    }
    return MPI_SUCCESS;
}

/* ---- rooted collectives ---- */

static int ps_coll_tag(ps_comm *c, MPI_Comm handle) {
    /* FIFO per peer + identical call order on every member make one tag
     * per (comm, collective kind) safe; 16 tags are reserved per
     * communicator so kinds never collide across comms (comm 0's
     * Barrier must not alias comm 1's Bcast) */
    (void)c;
    return PS_COLL_TAG_BASE + 16 * (int)handle;
}

int MPI_Bcast(void *buf, int count, MPI_Datatype dt, int root, MPI_Comm comm) {
    ps_comm *c = ps_get_comm(comm);
    int tag = ps_coll_tag(c, comm);
    if (c->me == root) {
        for (int i = 0; i < c->size; i++)
            if (i != root) MPI_Send(buf, count, dt, i, tag, comm);
    } else {
        MPI_Recv(buf, count, dt, root, tag, comm, MPI_STATUS_IGNORE);
    }
    return MPI_SUCCESS;
}

int MPI_Barrier(MPI_Comm comm) {
    ps_comm *c = ps_get_comm(comm);
    int tag = ps_coll_tag(c, comm) + 1;
    char z = 0;
    if (c->me == 0) {
        for (int i = 1; i < c->size; i++)
            MPI_Recv(&z, 1, MPI_CHAR, i, tag, comm, MPI_STATUS_IGNORE);
        for (int i = 1; i < c->size; i++)
            MPI_Send(&z, 1, MPI_CHAR, i, tag, comm);
    } else {
        MPI_Send(&z, 1, MPI_CHAR, 0, tag, comm);
        MPI_Recv(&z, 1, MPI_CHAR, 0, tag, comm, MPI_STATUS_IGNORE);
    }
    return MPI_SUCCESS;
}

int MPI_Allgather(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                  void *recvbuf, int recvcount, MPI_Datatype recvtype,
                  MPI_Comm comm) {
    ps_comm *c = ps_get_comm(comm);
    int tag = ps_coll_tag(c, comm) + 2;
    size_t chunk = (size_t)sendcount * ps_dtsize(sendtype);
    size_t rchunk = (size_t)recvcount * ps_dtsize(recvtype);
    if (chunk != rchunk) {
        fprintf(stderr, "[procshim] allgather send/recv byte mismatch\n");
        exit(EXIT_FAILURE);
    }
    char *out = recvbuf;
    if (c->me == 0) {
        memcpy(out, sendbuf, chunk);
        for (int i = 1; i < c->size; i++)
            MPI_Recv(out + (size_t)i * chunk, sendcount, sendtype, i, tag,
                     comm, MPI_STATUS_IGNORE);
        for (int i = 1; i < c->size; i++)
            MPI_Send(out, sendcount * c->size, sendtype, i, tag, comm);
    } else {
        MPI_Send(sendbuf, sendcount, sendtype, 0, tag, comm);
        MPI_Recv(out, sendcount * c->size, sendtype, 0, tag, comm,
                 MPI_STATUS_IGNORE);
    }
    return MPI_SUCCESS;
}

static void ps_reduce(void *acc, const void *in, int count, MPI_Datatype dt,
                      MPI_Op op) {
    for (int i = 0; i < count; i++) {
        if (dt == MPI_DOUBLE) {
            double *a = (double *)acc + i;
            double v = ((const double *)in)[i];
            if (op == MPI_SUM) *a += v;
            else if (op == MPI_MIN && v < *a) *a = v;
            else if (op == MPI_MAX && v > *a) *a = v;
        } else if (dt == MPI_FLOAT) {
            float *a = (float *)acc + i;
            float v = ((const float *)in)[i];
            if (op == MPI_SUM) *a += v;
            else if (op == MPI_MIN && v < *a) *a = v;
            else if (op == MPI_MAX && v > *a) *a = v;
        } else if (dt == MPI_INT) {
            int *a = (int *)acc + i;
            int v = ((const int *)in)[i];
            if (op == MPI_SUM) *a += v;
            else if (op == MPI_MIN && v < *a) *a = v;
            else if (op == MPI_MAX && v > *a) *a = v;
        } else {
            fprintf(stderr, "[procshim] unsupported reduce datatype %d\n", dt);
            exit(EXIT_FAILURE);
        }
    }
}

int MPI_Allreduce(const void *sendbuf, void *recvbuf, int count,
                  MPI_Datatype dt, MPI_Op op, MPI_Comm comm) {
    ps_comm *c = ps_get_comm(comm);
    int tag = ps_coll_tag(c, comm) + 3;
    size_t bytes = (size_t)count * ps_dtsize(dt);
    memcpy(recvbuf, sendbuf, bytes);
    if (c->me == 0) {
        char *tmp = malloc(bytes ? bytes : 1);
        if (!tmp) ps_die("malloc");
        for (int i = 1; i < c->size; i++) {
            MPI_Recv(tmp, count, dt, i, tag, comm, MPI_STATUS_IGNORE);
            ps_reduce(recvbuf, tmp, count, dt, op);
        }
        free(tmp);
        for (int i = 1; i < c->size; i++)
            MPI_Send(recvbuf, count, dt, i, tag, comm);
    } else {
        MPI_Send(recvbuf, count, dt, 0, tag, comm);
        MPI_Recv(recvbuf, count, dt, 0, tag, comm, MPI_STATUS_IGNORE);
    }
    return MPI_SUCCESS;
}

int MPI_Alltoall(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                 void *recvbuf, int recvcount, MPI_Datatype recvtype,
                 MPI_Comm comm) {
    ps_comm *c = ps_get_comm(comm);
    int tag = ps_coll_tag(c, comm) + 4;
    size_t chunk = (size_t)sendcount * ps_dtsize(sendtype);
    if (chunk != (size_t)recvcount * ps_dtsize(recvtype)) {
        fprintf(stderr, "[procshim] alltoall send/recv byte mismatch\n");
        exit(EXIT_FAILURE);
    }
    const char *in = sendbuf;
    char *out = recvbuf;
    /* sends buffer, so the full fan-out can be posted before any recv */
    for (int j = 0; j < c->size; j++) {
        if (j == c->me)
            memcpy(out + (size_t)j * chunk, in + (size_t)j * chunk, chunk);
        else
            MPI_Send(in + (size_t)j * chunk, sendcount, sendtype, j, tag,
                     comm);
    }
    for (int j = 0; j < c->size; j++) {
        if (j == c->me) continue;
        MPI_Recv(out + (size_t)j * chunk, recvcount, recvtype, j, tag, comm,
                 MPI_STATUS_IGNORE);
    }
    return MPI_SUCCESS;
}

int MPI_Reduce_scatter_block(const void *sendbuf, void *recvbuf, int recvcount,
                             MPI_Datatype dt, MPI_Op op, MPI_Comm comm) {
    /* root gathers the full n*recvcount contributions, reduces them
     * elementwise, and scatters block i to member i */
    ps_comm *c = ps_get_comm(comm);
    int tag = ps_coll_tag(c, comm) + 5;
    size_t esz = ps_dtsize(dt);
    size_t full = (size_t)recvcount * (size_t)c->size * esz;
    if (c->me == 0) {
        char *acc = malloc(full ? full : 1);
        char *tmp = malloc(full ? full : 1);
        if (!acc || !tmp) ps_die("malloc");
        memcpy(acc, sendbuf, full);
        for (int i = 1; i < c->size; i++) {
            MPI_Recv(tmp, recvcount * c->size, dt, i, tag, comm,
                     MPI_STATUS_IGNORE);
            ps_reduce(acc, tmp, recvcount * c->size, dt, op);
        }
        memcpy(recvbuf, acc, (size_t)recvcount * esz);
        for (int i = 1; i < c->size; i++)
            MPI_Send(acc + (size_t)i * recvcount * esz, recvcount, dt, i,
                     tag, comm);
        free(acc);
        free(tmp);
    } else {
        MPI_Send(sendbuf, recvcount * c->size, dt, 0, tag, comm);
        MPI_Recv(recvbuf, recvcount, dt, 0, tag, comm, MPI_STATUS_IGNORE);
    }
    return MPI_SUCCESS;
}

int MPI_Comm_split(MPI_Comm comm, int color, int key, MPI_Comm *newcomm) {
    /* INVARIANT the wire-tag encodings lean on (ps_coll_tag and, since
     * the comm went into p2p wire tags, ps_wire_tag): communicator
     * handles are slot indices handed out in call order, so every rank
     * that exchanges messages on a comm must have executed the same
     * sequence of comm-creating calls and hold the SAME index for it.
     * Split of MPI_COMM_WORLD (the only creation the drivers do) keeps
     * this true on all ranks; a split of a SUB-communicator advances
     * ps_ncomms on its members only, after which a later world-level
     * split would yield different indices per rank and cross-comm
     * traffic would never match.  Real MPI's handles are process-local
     * opaques, so this is a shim restriction — kept because encoding
     * the handle is what isolates same-(src, tag) posts on different
     * comms from each other. */
    ps_comm *c = ps_get_comm(comm);
    /* allgather (color, key, world_rank); membership and ordering are then
     * computed identically everywhere */
    int mine[3] = {color, key, ps_rank};
    int *all = malloc(sizeof(int) * 3 * (size_t)c->size);
    if (!all) ps_die("malloc");
    MPI_Allgather(mine, 3, MPI_INT, all, 3, MPI_INT, comm);

    if (ps_ncomms >= PS_MAX_COMMS) {
        fprintf(stderr, "[procshim] out of communicators\n");
        exit(EXIT_FAILURE);
    }
    ps_comm *nc = &ps_comms[ps_ncomms];
    nc->size = 0;
    /* stable selection sort by (key, world_rank) among my color */
    for (;;) {
        int best = -1;
        for (int i = 0; i < c->size; i++) {
            if (all[3 * i] != color) continue;
            int placed = 0;
            for (int j = 0; j < nc->size; j++)
                if (nc->members[j] == all[3 * i + 2]) placed = 1;
            if (placed) continue;
            if (best < 0 || all[3 * i + 1] < all[3 * best + 1] ||
                (all[3 * i + 1] == all[3 * best + 1] &&
                 all[3 * i + 2] < all[3 * best + 2]))
                best = i;
        }
        if (best < 0) break;
        nc->members[nc->size++] = all[3 * best + 2];
    }
    free(all);
    nc->me = -1;
    for (int j = 0; j < nc->size; j++)
        if (nc->members[j] == ps_rank) nc->me = j;
    *newcomm = ps_ncomms++;
    return MPI_SUCCESS;
}

int MPI_Comm_free(MPI_Comm *comm) {
    *comm = MPI_COMM_NULL;
    return MPI_SUCCESS;
}

int MPI_Abort(MPI_Comm comm, int errorcode) {
    (void)comm;
    fprintf(stderr, "[procshim rank %d] MPI_Abort(%d)\n", ps_rank, errorcode);
    exit(errorcode ? EXIT_FAILURE : EXIT_SUCCESS);
}

int MPI_Error_string(int errorcode, char *string, int *resultlen) {
    snprintf(string, MPI_MAX_ERROR_STRING, "procshim error %d", errorcode);
    *resultlen = (int)strlen(string);
    return MPI_SUCCESS;
}

double MPI_Wtime(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

/* ---- libuuid compat (mpi_perf.c:335-337 links -luuid for these) ---- */

void uuid_generate(uuid_t out) {
    FILE *fh = fopen("/dev/urandom", "rb");
    if (!fh || fread(out, 1, 16, fh) != 16) {
        /* fall back to a time-seeded fill; uniqueness only matters for
         * distinguishing job ids in test logs */
        srand((unsigned)(time(NULL) ^ getpid()));
        for (int i = 0; i < 16; i++) out[i] = (unsigned char)rand();
    }
    if (fh) fclose(fh);
    out[6] = (unsigned char)((out[6] & 0x0f) | 0x40); /* version 4 */
    out[8] = (unsigned char)((out[8] & 0x3f) | 0x80); /* RFC 4122 variant */
}

void uuid_unparse(const uuid_t uu, char *out) {
    sprintf(out,
            "%02x%02x%02x%02x-%02x%02x-%02x%02x-%02x%02x-"
            "%02x%02x%02x%02x%02x%02x",
            uu[0], uu[1], uu[2], uu[3], uu[4], uu[5], uu[6], uu[7], uu[8],
            uu[9], uu[10], uu[11], uu[12], uu[13], uu[14], uu[15]);
}
