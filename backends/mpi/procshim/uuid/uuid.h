/* procshim uuid/uuid.h — the two libuuid calls the reference driver
 * makes (uuid_generate/uuid_unparse, mpi_perf.c:335-337; the reference
 * links -luuid, Makefile:2).  Backed by /dev/urandom in procshim.c so
 * the interop build needs no libuuid package.
 */
#ifndef TPU_PERF_PROCSHIM_UUID_H
#define TPU_PERF_PROCSHIM_UUID_H

#ifdef __cplusplus
extern "C" {
#endif

typedef unsigned char uuid_t[16];

void uuid_generate(uuid_t out);
void uuid_unparse(const uuid_t uu, char *out);

#ifdef __cplusplus
}
#endif

#endif /* TPU_PERF_PROCSHIM_UUID_H */
