/* procshim mpi.h — the MPI subset the reference driver needs
 * (/root/reference/mpi_perf.c includes <mpi.h>), implemented as a
 * PROCESS-per-rank shim over Unix-domain stream sockets (procshim.c,
 * launched by shim_mpirun).  Unlike mpi_shim.h (thread-per-rank, for the
 * repo's own tpu_mpi_perf.c), processes give each rank its own copy of
 * the reference's file-scope globals (world_rank, bench_options, log_fp,
 * mpi_perf.c:18,270-271), so the reference source compiles and runs
 * UNMODIFIED — the interop proof VERDICT r3 "What's missing" #5 asked
 * for.  This is a test harness, not an MPI library: only the calls the
 * reference makes exist, and sends complete by copying into an
 * in-process queue that drains during any later MPI call's progress
 * loop.
 */
#ifndef TPU_PERF_PROCSHIM_MPI_H
#define TPU_PERF_PROCSHIM_MPI_H

/* The reference source calls time/localtime/strftime without including
 * <time.h> (mpi_perf.c:341-353); with an implicit declaration gcc
 * assumes an int return and truncates localtime's pointer on x86-64.
 * Real MPI headers drag in enough of libc to hide this; provide it. */
#include <time.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef int MPI_Comm;
typedef int MPI_Datatype;
typedef int MPI_Op;
typedef int MPI_Request;

typedef struct {
    int MPI_SOURCE;
    int MPI_TAG;
    int MPI_ERROR;
} MPI_Status;

#define MPI_COMM_WORLD 0
#define MPI_COMM_NULL (-1)

#define MPI_BYTE 1
#define MPI_CHAR 2
#define MPI_INT 3
#define MPI_DOUBLE 4
#define MPI_FLOAT 5

#define MPI_MIN 1
#define MPI_MAX 2
#define MPI_SUM 3

#define MPI_SUCCESS 0
#define MPI_ERR_OTHER 1
#define MPI_MAX_PROCESSOR_NAME 256
#define MPI_MAX_ERROR_STRING 256
#define MPI_STATUS_IGNORE ((MPI_Status *)0)
#define MPI_STATUSES_IGNORE ((MPI_Status *)0)
#define MPI_REQUEST_NULL (-1)

int MPI_Init(int *argc, char ***argv);
int MPI_Finalize(void);
int MPI_Comm_size(MPI_Comm comm, int *size);
int MPI_Comm_rank(MPI_Comm comm, int *rank);
int MPI_Get_processor_name(char *name, int *resultlen);
int MPI_Send(const void *buf, int count, MPI_Datatype dt, int dest, int tag,
             MPI_Comm comm);
int MPI_Recv(void *buf, int count, MPI_Datatype dt, int source, int tag,
             MPI_Comm comm, MPI_Status *status);
int MPI_Isend(const void *buf, int count, MPI_Datatype dt, int dest, int tag,
              MPI_Comm comm, MPI_Request *req);
int MPI_Irecv(void *buf, int count, MPI_Datatype dt, int source, int tag,
              MPI_Comm comm, MPI_Request *req);
int MPI_Waitall(int count, MPI_Request reqs[], MPI_Status statuses[]);
int MPI_Barrier(MPI_Comm comm);
int MPI_Bcast(void *buf, int count, MPI_Datatype dt, int root, MPI_Comm comm);
int MPI_Allgather(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                  void *recvbuf, int recvcount, MPI_Datatype recvtype,
                  MPI_Comm comm);
int MPI_Allreduce(const void *sendbuf, void *recvbuf, int count,
                  MPI_Datatype dt, MPI_Op op, MPI_Comm comm);
int MPI_Alltoall(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                 void *recvbuf, int recvcount, MPI_Datatype recvtype,
                 MPI_Comm comm);
int MPI_Reduce_scatter_block(const void *sendbuf, void *recvbuf, int recvcount,
                             MPI_Datatype dt, MPI_Op op, MPI_Comm comm);
int MPI_Comm_split(MPI_Comm comm, int color, int key, MPI_Comm *newcomm);
int MPI_Comm_free(MPI_Comm *comm);
int MPI_Abort(MPI_Comm comm, int errorcode);
int MPI_Error_string(int errorcode, char *string, int *resultlen);
double MPI_Wtime(void);

#ifdef __cplusplus
}
#endif

#endif /* TPU_PERF_PROCSHIM_MPI_H */
