/* shim_mpirun — process-per-rank launcher for procshim binaries.
 *
 *   shim_mpirun -np N [-p PPN] [-t TIMEOUT_SEC] -- prog [args...]
 *
 * Forks N processes running `prog`, each with the procshim environment:
 *   SHIM_NRANKS / SHIM_RANK / SHIM_DIR   — transport rendezvous
 *   SHIM_HOSTNAME                        — per-"node" processor name,
 *       numeric 127.0.<2 + rank/PPN>.1 so the reference driver's
 *       getaddrinfo-based get_ipaddress (mpi_perf.c:180) resolves it
 *       with no /etc/hosts entries, and so the two-group hostname match
 *       (mpi_perf.c:438-444) sees PPN ranks per host — the shim
 *       equivalent of `mpirun --map-by ppr:PPN:node`.  The host index
 *       lives in the THIRD octet with a constant ".1" suffix because
 *       the reference matches by strnicmp prefix (mpi_perf.c:441):
 *       a final-octet scheme would make host "127.0.0.2" a prefix of
 *       host "127.0.0.22" and misgroup every job with 19+ hosts
 *   OMPI_COMM_WORLD_LOCAL_RANK           — rank % PPN; the reference
 *       reads this OpenMPI-specific variable directly (mpi_perf.c:378)
 *
 * Exit code is the max across ranks; the first nonzero exit kills the
 * remaining ranks (fail-fast, like mpirun).  A watchdog kills the job
 * after TIMEOUT_SEC (default 120) so a deadlocked test cannot hang CI.
 */
#include <errno.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

#define MAX_NP 64

static pid_t pids[MAX_NP];
static int npids;
static char job_dir[64];

static void kill_all(int sig) {
    for (int i = 0; i < npids; i++)
        if (pids[i] > 0) kill(pids[i], sig);
}

static void cleanup_dir(void) {
    if (!job_dir[0]) return;
    for (int r = 0; r < npids; r++) {
        char path[128];
        snprintf(path, sizeof path, "%s/s%d", job_dir, r);
        unlink(path);
    }
    rmdir(job_dir);
}

static void on_alarm(int sig) {
    (void)sig;
    /* async-signal-safe enough for a fatal path: the sockets and the
     * rendezvous dir must not outlive a timed-out job */
    kill_all(SIGKILL);
    cleanup_dir();
    static const char msg[] = "shim_mpirun: timeout, killed job\n";
    ssize_t ignored = write(2, msg, sizeof msg - 1);
    (void)ignored;
    _exit(124);
}

int main(int argc, char **argv) {
    int np = -1, ppn = 1, timeout_sec = 120;
    int argi = 1;
    while (argi < argc) {
        if (strcmp(argv[argi], "-np") == 0 && argi + 1 < argc) {
            np = atoi(argv[++argi]);
        } else if (strcmp(argv[argi], "-p") == 0 && argi + 1 < argc) {
            ppn = atoi(argv[++argi]);
        } else if (strcmp(argv[argi], "-t") == 0 && argi + 1 < argc) {
            timeout_sec = atoi(argv[++argi]);
        } else if (strcmp(argv[argi], "--") == 0) {
            argi++;
            break;
        } else {
            break;
        }
        argi++;
    }
    if (np < 1 || np > MAX_NP || ppn < 1 || np % ppn != 0 || argi >= argc) {
        fprintf(stderr,
                "usage: shim_mpirun -np N [-p PPN] [-t SEC] -- prog [args]\n"
                "       (1 <= N <= %d, PPN divides N)\n", MAX_NP);
        return 2;
    }

    strcpy(job_dir, "/tmp/shim_mpirun.XXXXXX");
    if (!mkdtemp(job_dir)) {
        perror("mkdtemp");
        return 1;
    }
    const char *dir = job_dir;

    signal(SIGALRM, on_alarm);
    alarm((unsigned)timeout_sec);

    npids = np;
    for (int r = 0; r < np; r++) {
        pid_t pid = fork();
        if (pid < 0) {
            perror("fork");
            kill_all(SIGKILL);
            return 1;
        }
        if (pid == 0) {
            char buf[64];
            snprintf(buf, sizeof buf, "%d", np);
            setenv("SHIM_NRANKS", buf, 1);
            snprintf(buf, sizeof buf, "%d", r);
            setenv("SHIM_RANK", buf, 1);
            setenv("SHIM_DIR", dir, 1);
            snprintf(buf, sizeof buf, "127.0.%d.1", 2 + r / ppn);
            setenv("SHIM_HOSTNAME", buf, 1);
            snprintf(buf, sizeof buf, "%d", r % ppn);
            setenv("OMPI_COMM_WORLD_LOCAL_RANK", buf, 1);
            execvp(argv[argi], &argv[argi]);
            perror("execvp");
            _exit(127);
        }
        pids[r] = pid;
    }

    int rc = 0, failed = 0;
    for (int done = 0; done < np;) {
        int st;
        pid_t pid = wait(&st);
        if (pid < 0) {
            if (errno == EINTR) continue;
            break;
        }
        int code = WIFEXITED(st) ? WEXITSTATUS(st)
                                 : 128 + (WIFSIGNALED(st) ? WTERMSIG(st) : 0);
        for (int i = 0; i < np; i++)
            if (pids[i] == pid) pids[i] = -1;
        if (code > rc) rc = code;
        if (code != 0 && !failed) {
            failed = 1;
            kill_all(SIGTERM); /* fail-fast, like mpirun */
        }
        done++;
    }

    cleanup_dir();
    return rc;
}
