/* Launcher for the shim build: runs the driver's rank main on N threads.
 *
 *   mpi_perf_shim -np 4 [-hosts 2] -- <driver flags...>
 *
 * Rank r reports hostname shimhost<r/(np/hosts)>, matching how
 * `mpirun --map-by ppr:K:node` lays ranks onto nodes, so the driver's
 * two-group hostname split is exercised exactly as on a real cluster.
 */
#include "mpi_shim.h"

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

int tpu_mpi_perf_main(int argc, char **argv);

int main(int argc, char **argv) {
    int np = 2, hosts = 2, split = argc;
    for (int i = 1; i < argc; i++) {
        if (!strcmp(argv[i], "--")) {
            split = i;
            break;
        }
        if (!strcmp(argv[i], "-np") && i + 1 < argc) np = atoi(argv[++i]);
        else if (!strcmp(argv[i], "-hosts") && i + 1 < argc) hosts = atoi(argv[++i]);
        else {
            fprintf(stderr,
                    "usage: %s -np N [-hosts H] -- <driver flags>\n", argv[0]);
            return 2;
        }
    }
    /* argv for the driver: program name + everything after "--" */
    int dargc = 1 + (split < argc ? argc - split - 1 : 0);
    char **dargv = (char **)malloc(sizeof(char *) * (size_t)(dargc + 1));
    dargv[0] = argv[0];
    for (int i = split + 1, j = 1; i < argc; i++, j++) dargv[j] = argv[i];
    dargv[dargc] = NULL;
    int rc = shim_run(np, hosts, tpu_mpi_perf_main, dargc, dargv);
    free(dargv);
    return rc;
}
