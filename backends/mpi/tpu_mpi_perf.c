/* tpu_mpi_perf — native MPI baseline backend for the tpu_perf framework.
 *
 * A clean-room re-implementation of the reference driver's behavior
 * (described in SURVEY.md §2 "C1 in depth"; reference: mpi_perf.c in
 * jithinjosepkl/mpi-perf), kept so the MPI/IB baseline stays measurable
 * side-by-side with the JAX/ICI backend:
 *
 *   - ranks split into two host groups; rank-matched pairs run timed
 *     message loops (reference mpi_perf.c:200-238,447);
 *   - three kernels: blocking bidirectional ping-pong (:66-83), windowed
 *     non-blocking (:85-125; the reference's window-boundary off-by-one is
 *     fixed here, per SURVEY.md §2 "do not replicate"), unidirectional
 *     payload + 1-byte ack (:127-145);
 *   - per-run wall times, cross-rank min/max/avg via MPI_Allreduce
 *     (:560-562), stderr heartbeat every 1000 runs (:564-568);
 *   - group-1 ranks append legacy-schema CSV rows, skipping run 0 (:545),
 *     to rotating tcp-<uuid>-<rank>-<ts>.log files (:479-497);
 *   - node-local rank 0 triggers the ingest command at each rotation
 *     (:355-365) — here `TPU_PERF_INGEST_CMD` instead of a hardcoded
 *     python path;
 *   - runs = -1 loops forever: the fleet-monitoring daemon (:474).
 *
 * Build: `make` (real MPI via mpicc) or `make shim` (single-process
 * pthread shim, no MPI needed — see mpi_shim.h).
 *
 * Differences from the reference, on purpose:
 *   - group matching supports hostname (default) or IP (-m ip, adopting
 *     the Windows port's behavior, windows/mpi-perf.cpp:283-289);
 *   - rotation period and heartbeat cadence come from env vars
 *     (TPU_PERF_LOG_ROTATE_SEC, TPU_PERF_STATS_EVERY) so tests don't wait
 *     900 s;
 *   - node-local rank is computed from the hostname table instead of an
 *     OpenMPI-specific env var, so any MPI (or the shim) works;
 *   - UUID generated from /dev/urandom: no libuuid dependency.
 */
#ifdef TPU_PERF_USE_SHIM
#include "mpi_shim.h"
#else
#include <mpi.h>
#endif

#include <arpa/inet.h>
#include <ctype.h>
#include <errno.h>
#include <limits.h>
#include <netdb.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#define HOST_LEN 256
#define WINDOW_SLOTS 256
#define TAG_FWD 11
#define TAG_BWD 12
#define DEFAULT_BUFF 456131 /* reference DEF_BUF_SZ, mpi_perf.c:14 */
#define DEFAULT_ITERS 10    /* reference DEF_ITERS, mpi_perf.c:15 */

#define CHECK_MPI(call)                                                        \
    do {                                                                       \
        int rc_ = (call);                                                      \
        if (rc_ != MPI_SUCCESS) {                                              \
            char msg_[MPI_MAX_ERROR_STRING];                                   \
            int len_ = 0;                                                      \
            MPI_Error_string(rc_, msg_, &len_);                                \
            fprintf(stderr, "MPI failure at %s:%d: %.*s\n", __FILE__,          \
                    __LINE__, len_, msg_);                                     \
            MPI_Abort(MPI_COMM_WORLD, rc_);                                    \
        }                                                                      \
    } while (0)

typedef struct {
    long iters;
    long buff_sz;
    long num_runs; /* -1 = forever */
    int ppn;
    int n_group1; /* -n: expected group-1 host count (0 = unchecked) */
    int uni_dir;
    int nonblocking;
    int match_by_ip;
    int report_gbps;
    char op[24]; /* collective mode (-o): empty = pairwise kernels */
    char uuid[40];
    char logfolder[512];
    char group_file[512];
} bench_config;

typedef struct {
    int group;
    int group_rank;
    char host[HOST_LEN];
    char ip[64];
} rank_card;

static void make_uuid(char out[40]) {
    unsigned char b[16];
    FILE *f = fopen("/dev/urandom", "rb");
    if (!f || fread(b, 1, 16, f) != 16)
        for (int i = 0; i < 16; i++) b[i] = (unsigned char)(rand() & 0xFF);
    if (f) fclose(f);
    b[6] = (unsigned char)((b[6] & 0x0F) | 0x40); /* version 4 */
    b[8] = (unsigned char)((b[8] & 0x3F) | 0x80); /* variant */
    snprintf(out, 40,
             "%02x%02x%02x%02x-%02x%02x-%02x%02x-%02x%02x-"
             "%02x%02x%02x%02x%02x%02x",
             b[0], b[1], b[2], b[3], b[4], b[5], b[6], b[7], b[8], b[9], b[10],
             b[11], b[12], b[13], b[14], b[15]);
}

static void timestamp_ms(char *out, size_t n) {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    struct tm tmv;
    localtime_r(&ts.tv_sec, &tmv);
    size_t off = strftime(out, n, "%Y-%m-%d %H:%M:%S", &tmv);
    snprintf(out + off, n - off, ".%03ld", ts.tv_nsec / 1000000L);
}

static int ieq(const char *a, const char *b) {
    while (*a && *b) {
        if (tolower((unsigned char)*a) != tolower((unsigned char)*b)) return 0;
        a++;
        b++;
    }
    return *a == *b;
}

/* Scan the group-1 host list: returns 1 if `key` matches a line
 * (case-insensitive, trimmed) and reports the non-empty line count.
 * strtok_r throughout — in the shim build every rank is a thread. */
static int scan_group_list(const char *text, const char *key, int *nlines) {
    int member = 0, count = 0;
    char *copy = strdup(text); /* heap — the list has no size cap */
    if (!copy) {
        fprintf(stderr, "out of memory scanning group list\n");
        MPI_Abort(MPI_COMM_WORLD, 4);
    }
    char *save = NULL;
    for (char *line = strtok_r(copy, "\r\n", &save); line;
         line = strtok_r(NULL, "\r\n", &save)) {
        while (*line == ' ' || *line == '\t') line++;
        char *end = line + strlen(line);
        while (end > line && (end[-1] == ' ' || end[-1] == '\t')) *--end = 0;
        if (!*line) continue;
        count++;
        if (key && ieq(line, key)) member = 1;
    }
    free(copy);
    if (nlines) *nlines = count;
    return member;
}

/* Flag letters match the reference exactly (mpi_perf.c:273-339) so its
 * run scripts invoke this backend unchanged:
 *   -f group1 hostfile   -n expected group-1 host count   -i iters
 *   -b bytes  -r runs|-1  -p ppn  -u [0|1]  -x [0|1]  -l logfolder
 * plus this driver's additions: -o collective, -m ip|host, -B. */
static void usage(const char *prog) {
    fprintf(stderr,
            "usage: %s -f <group1-file> [-n group1-hosts] [-i iters]\n"
            "          [-b bytes] [-r runs|-1] [-p ppn] [-u [0|1]] [-x [0|1]]\n"
            "          [-l logfolder] [-m ip|host] [-B]\n"
            "       %s -o <collective> [same flags; no -f needed]\n"
            "collectives: allreduce all_gather reduce_scatter all_to_all\n"
            "             broadcast barrier (extended-schema rows, backend=mpi)\n"
            "             hbm_stream (local per-rank memory stream: the host\n"
            "             DRAM counterpart of the jax backend's HBM ceiling)\n"
            "-r N logs N rows per writing rank after one unlogged warm-up\n"
            "run; the original mpi-perf logs N-1 (it counts the warm-up\n"
            "inside N) — match sample sizes in side-by-side fleet configs\n",
            prog, prog);
}

/* collective mode: ops named exactly like the jax backend's so the
 * extended-schema rows line up side-by-side in `tpu-perf report` */
static const char *const COLL_OPS[] = {
    "allreduce", "all_gather", "reduce_scatter", "all_to_all",
    "broadcast", "barrier", "hbm_stream",
};

static int known_collective(const char *op) {
    for (size_t i = 0; i < sizeof COLL_OPS / sizeof *COLL_OPS; i++)
        if (!strcmp(op, COLL_OPS[i])) return 1;
    return 0;
}

static int parse_cli(bench_config *cfg, int argc, char **argv) {
    memset(cfg, 0, sizeof *cfg);
    cfg->iters = DEFAULT_ITERS;
    cfg->buff_sz = DEFAULT_BUFF;
    cfg->num_runs = 1;
    cfg->ppn = 1;
    for (int i = 1; i < argc; i++) {
        const char *a = argv[i];
        /* -u / -x take an optional 0|1 value: the reference spells them
         * "-u 1" (getopt with required arg, mpi_perf.c:276,312,322) while
         * this driver's scripts historically used the bare flag */
        if (!strcmp(a, "-u") || !strcmp(a, "-x")) {
            int val = 1;
            if (i + 1 < argc &&
                (!strcmp(argv[i + 1], "0") || !strcmp(argv[i + 1], "1")))
                val = atoi(argv[++i]);
            if (!strcmp(a, "-u")) cfg->uni_dir = val;
            else cfg->nonblocking = val;
        } else if (!strcmp(a, "-B")) {
            cfg->report_gbps = 1;
        } else if (!strcmp(a, "-h")) {
            usage(argv[0]);
            return -1;
        } else if (i + 1 < argc) {
            const char *v = argv[++i];
            if (!strcmp(a, "-i")) cfg->iters = atol(v);
            else if (!strcmp(a, "-n")) cfg->n_group1 = atoi(v);
            else if (!strcmp(a, "-b")) cfg->buff_sz = atol(v);
            else if (!strcmp(a, "-r")) cfg->num_runs = atol(v);
            else if (!strcmp(a, "-p")) cfg->ppn = atoi(v);
            else if (!strcmp(a, "-l")) snprintf(cfg->logfolder, sizeof cfg->logfolder, "%s", v);
            else if (!strcmp(a, "-f")) snprintf(cfg->group_file, sizeof cfg->group_file, "%s", v);
            else if (!strcmp(a, "-o")) snprintf(cfg->op, sizeof cfg->op, "%s", v);
            else if (!strcmp(a, "-m")) cfg->match_by_ip = !strcmp(v, "ip");
            else {
                fprintf(stderr, "unknown flag %s\n", a);
                usage(argv[0]);
                return -1;
            }
        } else {
            fprintf(stderr, "flag %s needs a value\n", a);
            usage(argv[0]);
            return -1;
        }
    }
    if (cfg->iters <= 0 || cfg->buff_sz <= 0 || cfg->ppn <= 0 ||
        (cfg->num_runs == 0 || cfg->num_runs < -1)) {
        fprintf(stderr, "invalid numeric argument\n");
        return -1;
    }
    if (cfg->uni_dir && cfg->nonblocking) {
        fprintf(stderr, "-u and -x are mutually exclusive\n");
        return -1;
    }
    if (cfg->op[0]) {
        if (!known_collective(cfg->op)) {
            fprintf(stderr, "unknown collective %s\n", cfg->op);
            usage(argv[0]);
            return -1;
        }
        if (cfg->uni_dir || cfg->nonblocking) {
            fprintf(stderr, "-o is exclusive with -u/-x\n");
            return -1;
        }
        if (cfg->buff_sz > (1L << 30)) {
            /* collective counts are MPI ints; 1 GiB is also the sweep's
             * documented ceiling (8 B..1 GiB) */
            fprintf(stderr, "-o supports -b up to 1 GiB, got %ld\n",
                    cfg->buff_sz);
            return -1;
        }
    } else if (!cfg->group_file[0]) {
        fprintf(stderr, "-f <group1-file> is required (or -o <collective>)\n");
        usage(argv[0]);
        return -1;
    }
    if (cfg->n_group1 < 0) {
        fprintf(stderr, "-n must be non-negative\n");
        return -1;
    }
    if (cfg->n_group1 > 0 && !cfg->group_file[0]) {
        /* -n means group-1 host count (reference semantics); a bare -n is
         * a stale pre-rename command line where it meant iters */
        fprintf(stderr, "-n needs -f <group1-file> (iters moved to -i)\n");
        return -1;
    }
    make_uuid(cfg->uuid); /* minted at parse time so all ranks share it */
    return 0;
}

/* --- the three measurement kernels (group is 0 or 1; peer = world rank) --- */

static void kernel_bidir(int group, int peer, char *tx, char *rx, long buff,
                         long iters) {
    for (long i = 0; i < iters; i++) {
        if (group == 1) {
            CHECK_MPI(MPI_Send(tx, (int)buff, MPI_BYTE, peer, TAG_FWD, MPI_COMM_WORLD));
            CHECK_MPI(MPI_Recv(rx, (int)buff, MPI_BYTE, peer, TAG_BWD, MPI_COMM_WORLD,
                               MPI_STATUS_IGNORE));
        } else {
            CHECK_MPI(MPI_Recv(rx, (int)buff, MPI_BYTE, peer, TAG_FWD, MPI_COMM_WORLD,
                               MPI_STATUS_IGNORE));
            CHECK_MPI(MPI_Send(tx, (int)buff, MPI_BYTE, peer, TAG_BWD, MPI_COMM_WORLD));
        }
    }
}

/* Windowed non-blocking: keep up to WINDOW_SLOTS send+recv pairs in flight,
 * waiting for the whole window each time it fills, with a final drain.  The
 * boundary includes every posted request (the reference dropped the last
 * slot from its boundary Waitall). */
static void kernel_windowed(int group, int peer, char *tx, char *rx, long buff,
                            long iters) {
    MPI_Request sends[WINDOW_SLOTS], recvs[WINDOW_SLOTS];
    int inflight = 0;
    int tag_out = group == 1 ? TAG_FWD : TAG_BWD;
    int tag_in = group == 1 ? TAG_BWD : TAG_FWD;
    for (long i = 0; i < iters; i++) {
        CHECK_MPI(MPI_Irecv(rx, (int)buff, MPI_BYTE, peer, tag_in, MPI_COMM_WORLD,
                            &recvs[inflight]));
        CHECK_MPI(MPI_Isend(tx, (int)buff, MPI_BYTE, peer, tag_out, MPI_COMM_WORLD,
                            &sends[inflight]));
        inflight++;
        if (inflight == WINDOW_SLOTS) {
            CHECK_MPI(MPI_Waitall(inflight, recvs, MPI_STATUSES_IGNORE));
            CHECK_MPI(MPI_Waitall(inflight, sends, MPI_STATUSES_IGNORE));
            inflight = 0;
        }
    }
    if (inflight) {
        CHECK_MPI(MPI_Waitall(inflight, recvs, MPI_STATUSES_IGNORE));
        CHECK_MPI(MPI_Waitall(inflight, sends, MPI_STATUSES_IGNORE));
    }
}

static void kernel_oneway(int group, int peer, char *tx, char *rx, long buff,
                          long iters) {
    char ack = 0;
    for (long i = 0; i < iters; i++) {
        if (group == 1) { /* group 1 sends the payload, gets a 1-byte ack */
            CHECK_MPI(MPI_Send(tx, (int)buff, MPI_BYTE, peer, TAG_FWD, MPI_COMM_WORLD));
            CHECK_MPI(MPI_Recv(&ack, 1, MPI_BYTE, peer, TAG_BWD, MPI_COMM_WORLD,
                               MPI_STATUS_IGNORE));
        } else {
            CHECK_MPI(MPI_Recv(rx, (int)buff, MPI_BYTE, peer, TAG_FWD, MPI_COMM_WORLD,
                               MPI_STATUS_IGNORE));
            CHECK_MPI(MPI_Send(&ack, 1, MPI_BYTE, peer, TAG_BWD, MPI_COMM_WORLD));
        }
    }
}

/* --- collective mode (-o) ---------------------------------------------
 * Size semantics follow the jax backend (tpu_perf/ops/collectives.py
 * payload_elems, the nccl-tests convention): all_gather's nbytes is the
 * gathered total, reduce_scatter/all_to_all's is the per-rank input
 * buffer, allreduce/broadcast's the per-rank buffer; barrier is a fixed
 * 1-byte latency-only op.  Reduction ops run on doubles (MPI needs an
 * arithmetic type), byte-movement ops on MPI_BYTE. */

/* All sizes are float32-granular (4-byte elements, rounded UP), exactly
 * like payload_elems with the jax backend's default dtype — so the two
 * backends log identical nbytes at every requested size and their rows
 * land on the same report curve points. */
static long coll_nbytes(const char *op, long buff, int world) {
    long elems = (buff + 3) / 4;
    if (elems < 1) elems = 1;
    if (!strcmp(op, "barrier")) return 4; /* one element, like the jax op */
    if (!strcmp(op, "allreduce") || !strcmp(op, "broadcast")) return elems * 4;
    if (!strcmp(op, "reduce_scatter") || !strcmp(op, "all_to_all")) {
        long per = (elems + world - 1) / world;
        return per * world * 4;
    }
    if (!strcmp(op, "all_gather")) { /* nbytes = gathered total */
        long shard = (elems + world - 1) / world;
        return shard * world * 4;
    }
    return elems * 4;
}

/* bus = alg * factor; mirrors tpu_perf/metrics.py _BUS_FACTORS so the
 * backend=mpi rows are directly comparable to the backend=jax ones */
static double coll_bus_factor(const char *op, int n) {
    if (!strcmp(op, "allreduce")) return n > 1 ? 2.0 * (n - 1) / n : 1.0;
    if (!strcmp(op, "all_gather") || !strcmp(op, "reduce_scatter") ||
        !strcmp(op, "all_to_all"))
        return n > 1 ? (double)(n - 1) / n : 1.0;
    if (!strcmp(op, "broadcast")) return 1.0;
    if (!strcmp(op, "hbm_stream")) return 2.0; /* reads + writes the buffer */
    return 0.0; /* barrier: latency-only */
}

/* Local per-rank memory stream: the exact wrap-add body of the jax
 * backend's hbm_stream (collectives.py _body_hbm_stream) over a float32
 * buffer, so `report --compare` pairs host-DRAM rows against TPU-HBM rows
 * at identical (op, nbytes, dtype) curve keys.  The compiler barrier
 * between passes forces each iteration's loads and stores to memory —
 * without it the iteration loop interchanges and the whole chain folds
 * into one register pass (the C-side analogue of the MXU invariant-chain
 * folding fixed in round 3, BASELINE.md). */
static void kernel_stream_local(float *x, long elems, long iters) {
    for (long i = 0; i < iters; i++) {
        for (long j = 0; j < elems; j++) x[j] = x[j] * 1.0000001f + 1e-7f;
        __asm__ __volatile__("" : : "r"(x) : "memory");
    }
}

static void kernel_collective(const char *op, int world, char *tx, char *rx,
                              long nbytes, long iters) {
    if (!strcmp(op, "hbm_stream")) {
        kernel_stream_local((float *)tx, nbytes / 4, iters);
        return;
    }
    for (long i = 0; i < iters; i++) {
        if (!strcmp(op, "allreduce")) {
            CHECK_MPI(MPI_Allreduce(tx, rx, (int)(nbytes / 4), MPI_FLOAT,
                                    MPI_SUM, MPI_COMM_WORLD));
        } else if (!strcmp(op, "reduce_scatter")) {
            CHECK_MPI(MPI_Reduce_scatter_block(tx, rx,
                                               (int)(nbytes / (4L * world)),
                                               MPI_FLOAT, MPI_SUM,
                                               MPI_COMM_WORLD));
        } else if (!strcmp(op, "all_gather")) {
            CHECK_MPI(MPI_Allgather(tx, (int)(nbytes / world), MPI_BYTE, rx,
                                    (int)(nbytes / world), MPI_BYTE,
                                    MPI_COMM_WORLD));
        } else if (!strcmp(op, "all_to_all")) {
            CHECK_MPI(MPI_Alltoall(tx, (int)(nbytes / world), MPI_BYTE, rx,
                                   (int)(nbytes / world), MPI_BYTE,
                                   MPI_COMM_WORLD));
        } else if (!strcmp(op, "broadcast")) {
            CHECK_MPI(MPI_Bcast(tx, (int)nbytes, MPI_BYTE, 0, MPI_COMM_WORLD));
        } else { /* barrier */
            CHECK_MPI(MPI_Barrier(MPI_COMM_WORLD));
        }
    }
}

/* One extended-schema row (tpu_perf/schema.py ResultRow, RESULT_HEADER
 * field order) — the single emission point for both the collective and the
 * pairwise dual-schema branches, so the format cannot drift between them. */
static void emit_result_row(FILE *f, const char *ts, const char *job_id,
                            const char *op, long nbytes, long iters, long run,
                            int n_devices, double per_op, double algbw,
                            double busbw, double total_s) {
    /* dtype column: this backend's payloads are float32 buffers (the
     * collectives reduce MPI_FLOAT; the pairwise kernels move bytes whose
     * element type convention is f32, matching the jax backend default) */
    fprintf(f, "%s,%s,mpi,%s,%ld,%ld,%ld,%d,%.3f,%g,%g,%.3f,float32\n", ts,
            job_id, op, nbytes, iters, run, n_devices, per_op * 1e6, algbw,
            busbw, total_s * 1e3);
    fflush(f);
}

static FILE *open_log(const bench_config *cfg, int world_rank,
                      const char *prefix) {
    char ts[32], path[1024];
    time_t now = time(NULL);
    struct tm tmv;
    localtime_r(&now, &tmv);
    strftime(ts, sizeof ts, "%Y%m%d-%H%M%S", &tmv);
    snprintf(path, sizeof path, "%s/%s-%s-%d-%s.log", cfg->logfolder, prefix,
             cfg->uuid, world_rank, ts);
    FILE *f = fopen(path, "a");
    if (!f) fprintf(stderr, "cannot open log %s: %s\n", path, strerror(errno));
    return f;
}

static long env_long(const char *name, long fallback) {
    const char *v = getenv(name);
    if (!v || !*v) return fallback;
    long parsed = atol(v);
    if (parsed <= 0) { /* atol of garbage is 0; 0 would divide-by-zero */
        fprintf(stderr, "ignoring %s=%s (need a positive integer)\n", name, v);
        return fallback;
    }
    return parsed;
}

int tpu_mpi_perf_main(int argc, char **argv) {
    CHECK_MPI(MPI_Init(&argc, &argv));
    int world = 0, rank = 0;
    CHECK_MPI(MPI_Comm_size(MPI_COMM_WORLD, &world));
    CHECK_MPI(MPI_Comm_rank(MPI_COMM_WORLD, &rank));

    bench_config cfg;
    int parse_rc = 0;
    if (rank == 0) parse_rc = parse_cli(&cfg, argc, argv);
    CHECK_MPI(MPI_Bcast(&parse_rc, 1, MPI_INT, 0, MPI_COMM_WORLD));
    if (parse_rc != 0) {
        MPI_Finalize();
        return 2;
    }
    /* options parsed on rank 0 only, shipped as raw bytes (the reference
     * broadcasts its packed struct the same way, mpi_perf.c:422) */
    CHECK_MPI(MPI_Bcast(&cfg, (int)sizeof cfg, MPI_BYTE, 0, MPI_COMM_WORLD));

    int coll_mode = cfg.op[0] != 0;

    /* group-1 host list: read whole on rank 0 (heap, no size cap — the
     * reference mallocs too, mpi_perf.c:406), broadcast length + content
     * (pairwise mode only — collectives run over the whole world) */
    long glen = 1;
    char *group1_text = NULL;
    if (rank == 0 && !coll_mode) {
        FILE *f = fopen(cfg.group_file, "r");
        if (!f) {
            fprintf(stderr, "cannot read %s: %s\n", cfg.group_file, strerror(errno));
            MPI_Abort(MPI_COMM_WORLD, 2);
        }
        long cap = 4096;
        group1_text = malloc((size_t)cap);
        long len = 0;
        while (group1_text) {
            size_t got = fread(group1_text + len, 1, (size_t)(cap - len - 1), f);
            len += (long)got;
            if (len < cap - 1) break;
            cap *= 2;
            char *grown = realloc(group1_text, (size_t)cap);
            if (!grown) { free(group1_text); group1_text = NULL; break; }
            group1_text = grown;
        }
        if (ferror(f)) { /* a short fread must be EOF, not an I/O error —
                          * a silently truncated host list mispairs ranks.
                          * (fread need not set errno, so no strerror here) */
            fprintf(stderr, "read error on %s\n", cfg.group_file);
            MPI_Abort(MPI_COMM_WORLD, 2);
        }
        fclose(f);
        if (!group1_text) {
            fprintf(stderr, "out of memory reading %s\n", cfg.group_file);
            MPI_Abort(MPI_COMM_WORLD, 4);
        }
        group1_text[len] = 0;
        glen = len + 1; /* ship the NUL */
        if (glen > INT_MAX) { /* MPI_Bcast counts are int; a >2 GiB host
                               * list would truncate silently below */
            fprintf(stderr, "group list %s too large (%ld bytes)\n",
                    cfg.group_file, glen);
            MPI_Abort(MPI_COMM_WORLD, 2);
        }
    }
    CHECK_MPI(MPI_Bcast(&glen, (int)sizeof glen, MPI_BYTE, 0, MPI_COMM_WORLD));
    if (group1_text == NULL) {
        group1_text = malloc((size_t)glen);
        if (!group1_text) {
            fprintf(stderr, "out of memory for group list (%ld bytes)\n", glen);
            MPI_Abort(MPI_COMM_WORLD, 4);
        }
        group1_text[0] = 0;
    }
    if (!coll_mode)
        CHECK_MPI(MPI_Bcast(group1_text, (int)glen, MPI_CHAR, 0, MPI_COMM_WORLD));

    char myhost[HOST_LEN] = {0};
    int hlen = 0;
    CHECK_MPI(MPI_Get_processor_name(myhost, &hlen));
    /* IPv4 of this host for log rows and -m ip matching (the reference
     * resolves via getaddrinfo the same way, mpi_perf.c:171-198); falls
     * back to the hostname when resolution fails (e.g. under the shim,
     * whose shimhostN names don't resolve). */
    char myip[64];
    snprintf(myip, sizeof myip, "%s", myhost);
    {
        struct addrinfo hints, *res = NULL;
        memset(&hints, 0, sizeof hints);
        hints.ai_family = AF_INET;
        hints.ai_socktype = SOCK_STREAM;
        if (getaddrinfo(myhost, NULL, &hints, &res) == 0 && res) {
            struct sockaddr_in *sa = (struct sockaddr_in *)res->ai_addr;
            if (!inet_ntop(AF_INET, &sa->sin_addr, myip, sizeof myip))
                snprintf(myip, sizeof myip, "%s", myhost);
            freeaddrinfo(res);
        }
    }

    /* membership + host count in one pass over the broadcast list */
    int nhosts = 0;
    int my_group = coll_mode ? 0
                             : scan_group_list(group1_text,
                                               cfg.match_by_ip ? myip : myhost,
                                               &nhosts);

    /* -n cross-check: the reference takes the group-1 host count on the
     * command line (mpi_perf.c:287-289) and reads that many lines; here
     * the file is authoritative, and a mismatching -n is a config error */
    if (rank == 0 && !coll_mode && cfg.n_group1 > 0 && cfg.n_group1 != nhosts) {
        fprintf(stderr,
                "group mismatch: -n %d but %s lists %d hosts\n",
                cfg.n_group1, cfg.group_file, nhosts);
        MPI_Abort(MPI_COMM_WORLD, 2);
    }
    /* sanity check (mpi_perf.c:399-403): bidirectional runs need the
     * group-1 hosts x ppn to be exactly half the (even) world */
    if (rank == 0 && !coll_mode && !cfg.uni_dir && nhosts * cfg.ppn * 2 != world) {
        fprintf(stderr,
                "group mismatch: %d group-1 hosts x ppn %d x 2 must equal "
                "world size %d\n",
                nhosts, cfg.ppn, world);
        MPI_Abort(MPI_COMM_WORLD, 2);
    }

    MPI_Comm group_comm;
    CHECK_MPI(MPI_Comm_split(MPI_COMM_WORLD, my_group, rank, &group_comm));
    int group_rank = 0, group_size = 0;
    CHECK_MPI(MPI_Comm_rank(group_comm, &group_rank));
    CHECK_MPI(MPI_Comm_size(group_comm, &group_size));

    /* pair discovery: allgather everyone's card; my peer is the rank in the
     * other group holding the same group rank (mpi_perf.c:200-238).  The
     * card table is heap-allocated like the reference's (mpi_perf.c:220) —
     * no MAX_WORLD cap, a fleet tool must scale with the world. */
    rank_card mine;
    rank_card *all = malloc((size_t)world * sizeof *all);
    if (!all) {
        fprintf(stderr, "out of memory for %d rank cards\n", world);
        MPI_Abort(MPI_COMM_WORLD, 4);
    }
    memset(&mine, 0, sizeof mine);
    mine.group = my_group;
    mine.group_rank = group_rank;
    snprintf(mine.host, sizeof mine.host, "%s", myhost);
    snprintf(mine.ip, sizeof mine.ip, "%s", myip);
    CHECK_MPI(MPI_Allgather(&mine, (int)sizeof mine, MPI_BYTE, all,
                            (int)sizeof mine, MPI_BYTE, MPI_COMM_WORLD));
    int peer = rank; /* collective mode: no pairing, rows cite self */
    if (!coll_mode) {
        peer = -1;
        for (int i = 0; i < world; i++)
            if (all[i].group != my_group && all[i].group_rank == group_rank)
                peer = i;
        if (peer < 0) {
            fprintf(stderr, "rank %d (%s, group %d): no peer found\n", rank,
                    myhost, my_group);
            MPI_Abort(MPI_COMM_WORLD, 3);
        }
    }
    /* node-local rank: position among ranks sharing my hostname (portable
     * replacement for OMPI_COMM_WORLD_LOCAL_RANK) */
    int local_rank = 0;
    for (int i = 0; i < rank; i++)
        if (ieq(all[i].host, myhost)) local_rank++;

    long nbytes = coll_mode ? coll_nbytes(cfg.op, cfg.buff_sz, world)
                            : cfg.buff_sz;
    /* hbm_stream is a local in-place stream: rx is never touched, and a
     * second nbytes-sized allocation would double a MEMORY benchmark's
     * resident set (2 GiB at the 1 GiB sweep ceiling on 2 ranks) */
    int stream_local = coll_mode && !strcmp(cfg.op, "hbm_stream");
    char *tx = NULL, *rx = NULL;
    if (posix_memalign((void **)&tx, 4096, (size_t)nbytes) ||
        (!stream_local && posix_memalign((void **)&rx, 4096, (size_t)nbytes))) {
        fprintf(stderr, "allocation of %ld bytes failed\n", nbytes);
        MPI_Abort(MPI_COMM_WORLD, 4);
    }
    memset(tx, my_group ? 'B' : 'A', (size_t)nbytes);
    if (rx) memset(rx, 0, (size_t)nbytes);

    long rotate_sec = env_long("TPU_PERF_LOG_ROTATE_SEC", 900);
    long stats_every = env_long("TPU_PERF_STATS_EVERY", 1000);
    const char *ingest_cmd = getenv("TPU_PERF_INGEST_CMD");

    /* pairwise mode: group-1 ranks write legacy tcp-* rows PLUS
     * extended-schema tpu-* rows (the jax driver's dual-schema behavior,
     * tpu_perf/driver.py), so `tpu-perf report` lands backend=mpi and
     * backend=jax pairwise rows on the same (op, nbytes) curve keys;
     * collective mode: rank 0 writes extended tpu-* rows only */
    const char *log_prefix = coll_mode ? "tpu" : "tcp";
    int writes_rows = coll_mode ? rank == 0 : my_group == 1;
    int dual_schema = !coll_mode && cfg.logfolder[0] && writes_rows;
    FILE *logf = NULL, *ext_logf = NULL;
    time_t log_opened = 0;
    if (cfg.logfolder[0] && writes_rows) {
        logf = open_log(&cfg, rank, log_prefix);
        if (dual_schema) ext_logf = open_log(&cfg, rank, "tpu");
        log_opened = time(NULL);
    }
    /* extended-row op names match the jax backend's kernels exactly
     * (tpu_perf/runner.py op_for_options) so report keys line up */
    const char *pw_op = cfg.nonblocking ? "exchange"
                        : (cfg.uni_dir ? "pingpong_unidir" : "pingpong");

    if (rank == 0)
        fprintf(stderr,
                "[tpu-mpi-perf] world=%d pairs=%d buff=%ld iters=%ld runs=%ld "
                "kernel=%s job=%s\n",
                world, world / 2, nbytes, cfg.iters, cfg.num_runs,
                coll_mode ? cfg.op
                          : (cfg.nonblocking
                                 ? "windowed"
                                 : (cfg.uni_dir ? "oneway" : "bidir")),
                cfg.uuid);

    for (long run = 0; cfg.num_runs == -1 || run < cfg.num_runs + 1; run++) {
        if (logf && time(NULL) - log_opened >= rotate_sec) {
            fclose(logf);
            if (ext_logf) fclose(ext_logf);
            if (ingest_cmd && local_rank == 0) {
                int rc = system(ingest_cmd);
                if (rc != 0)
                    fprintf(stderr, "[tpu-mpi-perf] ingest command rc=%d\n", rc);
            }
            logf = open_log(&cfg, rank, log_prefix);
            if (dual_schema) ext_logf = open_log(&cfg, rank, "tpu");
            log_opened = time(NULL);
        }

        CHECK_MPI(MPI_Barrier(MPI_COMM_WORLD));
        double t0 = MPI_Wtime();
        if (coll_mode)
            kernel_collective(cfg.op, world, tx, rx, nbytes, cfg.iters);
        else if (cfg.nonblocking)
            kernel_windowed(my_group, peer, tx, rx, cfg.buff_sz, cfg.iters);
        else if (cfg.uni_dir)
            kernel_oneway(my_group, peer, tx, rx, cfg.buff_sz, cfg.iters);
        else
            kernel_bidir(my_group, peer, tx, rx, cfg.buff_sz, cfg.iters);
        double dt = MPI_Wtime() - t0;

        CHECK_MPI(MPI_Barrier(MPI_COMM_WORLD));
        double tmin = 0, tmax = 0, tsum = 0;
        CHECK_MPI(MPI_Allreduce(&dt, &tmin, 1, MPI_DOUBLE, MPI_MIN, MPI_COMM_WORLD));
        CHECK_MPI(MPI_Allreduce(&dt, &tmax, 1, MPI_DOUBLE, MPI_MAX, MPI_COMM_WORLD));
        CHECK_MPI(MPI_Allreduce(&dt, &tsum, 1, MPI_DOUBLE, MPI_SUM, MPI_COMM_WORLD));

        /* run 0 is warm-up: measured but never logged (mpi_perf.c:545) */
        if (run > 0 && logf) {
            char ts[32];
            timestamp_ms(ts, sizeof ts);
            if (coll_mode) {
                /* extended schema (tpu_perf/schema.py ResultRow), rows
                 * directly comparable to the jax backend's.  The collective
                 * is complete only when the SLOWEST rank is done, so rows
                 * use tmax — rank 0's own dt can understate a rooted op
                 * (e.g. bcast root finishing while receivers still drain). */
                double per_op = tmax / (double)cfg.iters;
                double algbw = coll_bus_factor(cfg.op, world) == 0.0
                                   ? 0.0
                                   : (double)nbytes * 1e-9 / per_op;
                emit_result_row(logf, ts, cfg.uuid, cfg.op, nbytes, cfg.iters,
                                run, world, per_op, algbw,
                                algbw * coll_bus_factor(cfg.op, world), tmax);
            } else {
                /* pairwise rows keep the per-rank time, like the reference */
                fprintf(logf, "%s,%s,%d,%d,%s,%s,%d,%ld,%ld,%.3f,%ld\n", ts,
                        cfg.uuid, rank, world / cfg.ppn, mine.ip, all[peer].ip,
                        cfg.ppn, cfg.buff_sz, cfg.iters, dt * 1e3, run);
                if (ext_logf) {
                    /* jax conventions (tpu_perf/runner.py): ping-pong times
                     * cover a round trip so lat/bw use the one-way time;
                     * all pairwise bus factors are 1.0 */
                    double per_op = dt / (double)cfg.iters;
                    if (!cfg.nonblocking && !cfg.uni_dir) per_op /= 2.0;
                    double algbw = (double)cfg.buff_sz * 1e-9 / per_op;
                    emit_result_row(ext_logf, ts, cfg.uuid, pw_op, cfg.buff_sz,
                                    cfg.iters, run, world, per_op, algbw,
                                    algbw, dt);
                }
            }
            fflush(logf);
        }
        if (rank == 0 && run > 0 && run % stats_every == 0) {
            fprintf(stderr,
                    "[tpu-mpi-perf] run %ld: min %.3f max %.3f avg %.3f ms\n", run,
                    tmin * 1e3, tmax * 1e3, tsum / world * 1e3);
            if (cfg.report_gbps) {
                int dirs = cfg.uni_dir ? 1 : 2;
                fprintf(stderr, "[tpu-mpi-perf] run %ld: %.3f Gbps\n", run,
                        8.0 * (double)cfg.buff_sz * (double)cfg.iters * dirs *
                            1e-9 / dt);
            }
        }
    }

    if (logf) fclose(logf);
    if (ext_logf) fclose(ext_logf);
    free(all);
    free(group1_text);
    free(tx);
    free(rx);
    CHECK_MPI(MPI_Barrier(MPI_COMM_WORLD));
    MPI_Finalize();
    return 0;
}

#ifndef TPU_PERF_SHIM_LAUNCHER
int main(int argc, char **argv) { return tpu_mpi_perf_main(argc, argv); }
#endif
