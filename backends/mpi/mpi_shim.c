/* Single-process pthread MPI shim.  See mpi_shim.h for scope and caveats.
 *
 * Design: every rank is a thread; MPI_Send mallocs a copy of the payload
 * and appends it to the destination's mailbox (so sends never block);
 * MPI_Recv waits on the mailbox condition variable for a (src, tag, comm)
 * match.  Collectives and Comm_split are built on the point-to-point layer
 * with an internal tag space keyed by a per-comm operation sequence number
 * (legal because MPI requires all ranks of a comm to issue collectives in
 * the same order).
 */
#include "mpi_shim.h"

#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#define MAX_RANKS 64
#define MAX_COMMS 32
/* internal tags live far above any user tag */
#define TAG_BASE_COLL 0x40000000
#define TAG_BASE_SPLIT 0x20000000

typedef struct shim_msg {
    struct shim_msg *next;
    int src;   /* world rank of sender */
    int tag;
    int comm;  /* comm id, part of the match key */
    size_t len;
    char *data;
} shim_msg;

typedef struct {
    pthread_mutex_t mu;
    pthread_cond_t cv;
    shim_msg *head, *tail;
} mailbox;

typedef struct {
    int id;
    int size;
    int world_ranks[MAX_RANKS]; /* comm rank -> world rank */
} comm_info;

static struct {
    int nranks;
    int hosts;
    mailbox boxes[MAX_RANKS];
    comm_info comms[MAX_COMMS];
    int ncomms;
    int next_comm_id;
    pthread_mutex_t comms_mu;
    shim_rank_main_fn rank_main;
    int argc;
    char **argv;
    int exit_codes[MAX_RANKS];
} G;

typedef struct {
    int world_rank;
    /* per-comm collective sequence numbers (index = comm table slot) */
    unsigned coll_seq[MAX_COMMS];
    /* outstanding non-blocking requests */
    struct {
        int active;
        int is_recv;
        void *buf;
        size_t len;
        int peer; /* world rank */
        int tag;
        int comm;
    } reqs[512];
    int nreqs;
} rank_state;

static pthread_key_t tls_key;

static rank_state *me(void) { return (rank_state *)pthread_getspecific(tls_key); }

static size_t dt_size(MPI_Datatype dt) {
    switch (dt) {
    case MPI_BYTE:
    case MPI_CHAR:
        return 1;
    case MPI_INT:
    case MPI_FLOAT:
        return 4;
    case MPI_DOUBLE:
        return 8;
    default:
        fprintf(stderr, "mpi_shim: unknown datatype %d\n", dt);
        abort();
    }
}

static comm_info *comm_by_id(int id) {
    pthread_mutex_lock(&G.comms_mu);
    for (int i = 0; i < G.ncomms; i++) {
        if (G.comms[i].id == id) {
            pthread_mutex_unlock(&G.comms_mu);
            return &G.comms[i];
        }
    }
    pthread_mutex_unlock(&G.comms_mu);
    fprintf(stderr, "mpi_shim: unknown comm %d\n", id);
    abort();
}

static int comm_slot(int id) {
    pthread_mutex_lock(&G.comms_mu);
    for (int i = 0; i < G.ncomms; i++) {
        if (G.comms[i].id == id) {
            pthread_mutex_unlock(&G.comms_mu);
            return i;
        }
    }
    pthread_mutex_unlock(&G.comms_mu);
    abort();
}

static int comm_rank_of(comm_info *c, int world_rank) {
    for (int i = 0; i < c->size; i++)
        if (c->world_ranks[i] == world_rank) return i;
    return -1;
}

/* --- point-to-point core (world-rank addressed) --- */

static void raw_send(int dst_world, int tag, int comm, const void *buf, size_t len) {
    shim_msg *m = (shim_msg *)malloc(sizeof(shim_msg));
    m->next = NULL;
    m->src = me()->world_rank;
    m->tag = tag;
    m->comm = comm;
    m->len = len;
    m->data = (char *)malloc(len ? len : 1);
    if (len) memcpy(m->data, buf, len);
    mailbox *box = &G.boxes[dst_world];
    pthread_mutex_lock(&box->mu);
    if (box->tail) {
        box->tail->next = m;
        box->tail = m;
    } else {
        box->head = box->tail = m;
    }
    pthread_cond_broadcast(&box->cv);
    pthread_mutex_unlock(&box->mu);
}

static void raw_recv(int src_world, int tag, int comm, void *buf, size_t len) {
    mailbox *box = &G.boxes[me()->world_rank];
    pthread_mutex_lock(&box->mu);
    for (;;) {
        shim_msg *prev = NULL;
        for (shim_msg *m = box->head; m; prev = m, m = m->next) {
            if (m->src == src_world && m->tag == tag && m->comm == comm) {
                if (prev)
                    prev->next = m->next;
                else
                    box->head = m->next;
                if (box->tail == m) box->tail = prev;
                pthread_mutex_unlock(&box->mu);
                if (m->len < len) len = m->len;
                if (len) memcpy(buf, m->data, len);
                free(m->data);
                free(m);
                return;
            }
        }
        pthread_cond_wait(&box->cv, &box->mu);
    }
}

/* --- public API --- */

int MPI_Init(int *argc, char ***argv) {
    (void)argc;
    (void)argv;
    return MPI_SUCCESS;
}

int MPI_Finalize(void) { return MPI_SUCCESS; }

int MPI_Comm_size(MPI_Comm comm, int *size) {
    *size = comm_by_id(comm)->size;
    return MPI_SUCCESS;
}

int MPI_Comm_rank(MPI_Comm comm, int *rank) {
    *rank = comm_rank_of(comm_by_id(comm), me()->world_rank);
    return MPI_SUCCESS;
}

int MPI_Get_processor_name(char *name, int *resultlen) {
    int per_host = G.nranks / (G.hosts > 0 ? G.hosts : 1);
    if (per_host < 1) per_host = 1;
    int node = me()->world_rank / per_host;
    int n = snprintf(name, MPI_MAX_PROCESSOR_NAME, "shimhost%d", node);
    *resultlen = n;
    return MPI_SUCCESS;
}

int MPI_Send(const void *buf, int count, MPI_Datatype dt, int dest, int tag,
             MPI_Comm comm) {
    comm_info *c = comm_by_id(comm);
    raw_send(c->world_ranks[dest], tag, comm, buf, (size_t)count * dt_size(dt));
    return MPI_SUCCESS;
}

int MPI_Recv(void *buf, int count, MPI_Datatype dt, int source, int tag,
             MPI_Comm comm, MPI_Status *status) {
    comm_info *c = comm_by_id(comm);
    raw_recv(c->world_ranks[source], tag, comm, buf, (size_t)count * dt_size(dt));
    if (status) {
        status->MPI_SOURCE = source;
        status->MPI_TAG = tag;
        status->MPI_ERROR = MPI_SUCCESS;
    }
    return MPI_SUCCESS;
}

int MPI_Isend(const void *buf, int count, MPI_Datatype dt, int dest, int tag,
              MPI_Comm comm, MPI_Request *req) {
    /* buffered send completes immediately */
    MPI_Send(buf, count, dt, dest, tag, comm);
    rank_state *st = me();
    if (st->nreqs >= 512) {
        fprintf(stderr, "mpi_shim: too many outstanding requests\n");
        abort();
    }
    st->reqs[st->nreqs].active = 1;
    st->reqs[st->nreqs].is_recv = 0;
    *req = st->nreqs++;
    return MPI_SUCCESS;
}

int MPI_Irecv(void *buf, int count, MPI_Datatype dt, int source, int tag,
              MPI_Comm comm, MPI_Request *req) {
    rank_state *st = me();
    if (st->nreqs >= 512) {
        fprintf(stderr, "mpi_shim: too many outstanding requests\n");
        abort();
    }
    comm_info *c = comm_by_id(comm);
    st->reqs[st->nreqs].active = 1;
    st->reqs[st->nreqs].is_recv = 1;
    st->reqs[st->nreqs].buf = buf;
    st->reqs[st->nreqs].len = (size_t)count * dt_size(dt);
    st->reqs[st->nreqs].peer = c->world_ranks[source];
    st->reqs[st->nreqs].tag = tag;
    st->reqs[st->nreqs].comm = comm;
    *req = st->nreqs++;
    return MPI_SUCCESS;
}

int MPI_Waitall(int count, MPI_Request reqs[], MPI_Status statuses[]) {
    (void)statuses;
    rank_state *st = me();
    for (int i = 0; i < count; i++) {
        int r = reqs[i];
        if (r == MPI_REQUEST_NULL || r < 0 || r >= st->nreqs) continue;
        if (!st->reqs[r].active) continue;
        if (st->reqs[r].is_recv)
            raw_recv(st->reqs[r].peer, st->reqs[r].tag, st->reqs[r].comm,
                     st->reqs[r].buf, st->reqs[r].len);
        st->reqs[r].active = 0;
        reqs[i] = MPI_REQUEST_NULL;
    }
    /* compact: all complete -> reset the table */
    int live = 0;
    for (int i = 0; i < st->nreqs; i++) live += st->reqs[i].active;
    if (!live) st->nreqs = 0;
    return MPI_SUCCESS;
}

/* --- collectives over p2p; tags from the per-comm sequence --- */

static int next_coll_tag(MPI_Comm comm) {
    int slot = comm_slot(comm);
    return TAG_BASE_COLL + (int)(me()->coll_seq[slot]++ & 0xFFFFF);
}

int MPI_Barrier(MPI_Comm comm) {
    comm_info *c = comm_by_id(comm);
    int tag = next_coll_tag(comm);
    int rank = comm_rank_of(c, me()->world_rank);
    char token = 1;
    if (rank == 0) {
        for (int i = 1; i < c->size; i++)
            raw_recv(c->world_ranks[i], tag, comm, &token, 1);
        for (int i = 1; i < c->size; i++)
            raw_send(c->world_ranks[i], tag + 1, comm, &token, 1);
    } else {
        raw_send(c->world_ranks[0], tag, comm, &token, 1);
        raw_recv(c->world_ranks[0], tag + 1, comm, &token, 1);
    }
    me()->coll_seq[comm_slot(comm)]++; /* consume tag+1 too */
    return MPI_SUCCESS;
}

int MPI_Bcast(void *buf, int count, MPI_Datatype dt, int root, MPI_Comm comm) {
    comm_info *c = comm_by_id(comm);
    int tag = next_coll_tag(comm);
    int rank = comm_rank_of(c, me()->world_rank);
    size_t len = (size_t)count * dt_size(dt);
    if (rank == root) {
        for (int i = 0; i < c->size; i++)
            if (i != root) raw_send(c->world_ranks[i], tag, comm, buf, len);
    } else {
        raw_recv(c->world_ranks[root], tag, comm, buf, len);
    }
    return MPI_SUCCESS;
}

int MPI_Allgather(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                  void *recvbuf, int recvcount, MPI_Datatype recvtype,
                  MPI_Comm comm) {
    (void)recvcount;
    (void)recvtype;
    comm_info *c = comm_by_id(comm);
    int tag = next_coll_tag(comm);
    int rank = comm_rank_of(c, me()->world_rank);
    size_t chunk = (size_t)sendcount * dt_size(sendtype);
    char *out = (char *)recvbuf;
    memcpy(out + (size_t)rank * chunk, sendbuf, chunk);
    /* everyone sends to everyone (n^2 is fine at shim scale) */
    for (int i = 0; i < c->size; i++)
        if (i != rank) raw_send(c->world_ranks[i], tag, comm, sendbuf, chunk);
    for (int i = 0; i < c->size; i++)
        if (i != rank) raw_recv(c->world_ranks[i], tag, comm, out + (size_t)i * chunk, chunk);
    return MPI_SUCCESS;
}

static void reduce_doubles(double *acc, const double *in, int count, MPI_Op op) {
    for (int i = 0; i < count; i++) {
        switch (op) {
        case MPI_MIN:
            if (in[i] < acc[i]) acc[i] = in[i];
            break;
        case MPI_MAX:
            if (in[i] > acc[i]) acc[i] = in[i];
            break;
        case MPI_SUM:
            acc[i] += in[i];
            break;
        }
    }
}

static void reduce_floats(float *acc, const float *in, int count, MPI_Op op) {
    for (int i = 0; i < count; i++) {
        switch (op) {
        case MPI_MIN:
            if (in[i] < acc[i]) acc[i] = in[i];
            break;
        case MPI_MAX:
            if (in[i] > acc[i]) acc[i] = in[i];
            break;
        case MPI_SUM:
            acc[i] += in[i];
            break;
        }
    }
}

static void reduce_ints(int *acc, const int *in, int count, MPI_Op op) {
    for (int i = 0; i < count; i++) {
        switch (op) {
        case MPI_MIN:
            if (in[i] < acc[i]) acc[i] = in[i];
            break;
        case MPI_MAX:
            if (in[i] > acc[i]) acc[i] = in[i];
            break;
        case MPI_SUM:
            acc[i] += in[i];
            break;
        }
    }
}

int MPI_Allreduce(const void *sendbuf, void *recvbuf, int count,
                  MPI_Datatype dt, MPI_Op op, MPI_Comm comm) {
    comm_info *c = comm_by_id(comm);
    int tag = next_coll_tag(comm);
    int rank = comm_rank_of(c, me()->world_rank);
    size_t len = (size_t)count * dt_size(dt);
    memcpy(recvbuf, sendbuf, len);
    if (rank == 0) {
        char *tmp = (char *)malloc(len);
        for (int i = 1; i < c->size; i++) {
            raw_recv(c->world_ranks[i], tag, comm, tmp, len);
            if (dt == MPI_DOUBLE)
                reduce_doubles((double *)recvbuf, (const double *)tmp, count, op);
            else if (dt == MPI_FLOAT)
                reduce_floats((float *)recvbuf, (const float *)tmp, count, op);
            else if (dt == MPI_INT)
                reduce_ints((int *)recvbuf, (const int *)tmp, count, op);
            else {
                fprintf(stderr, "mpi_shim: allreduce datatype %d unsupported\n", dt);
                abort();
            }
        }
        free(tmp);
        for (int i = 1; i < c->size; i++)
            raw_send(c->world_ranks[i], tag + 1, comm, recvbuf, len);
    } else {
        raw_send(c->world_ranks[0], tag, comm, recvbuf, len);
        raw_recv(c->world_ranks[0], tag + 1, comm, recvbuf, len);
    }
    me()->coll_seq[comm_slot(comm)]++; /* consume tag+1 */
    return MPI_SUCCESS;
}

int MPI_Alltoall(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                 void *recvbuf, int recvcount, MPI_Datatype recvtype,
                 MPI_Comm comm) {
    (void)recvcount;
    (void)recvtype;
    comm_info *c = comm_by_id(comm);
    int tag = next_coll_tag(comm);
    int rank = comm_rank_of(c, me()->world_rank);
    size_t chunk = (size_t)sendcount * dt_size(sendtype);
    const char *in = (const char *)sendbuf;
    char *out = (char *)recvbuf;
    memcpy(out + (size_t)rank * chunk, in + (size_t)rank * chunk, chunk);
    for (int i = 0; i < c->size; i++)
        if (i != rank)
            raw_send(c->world_ranks[i], tag, comm, in + (size_t)i * chunk, chunk);
    for (int i = 0; i < c->size; i++)
        if (i != rank)
            raw_recv(c->world_ranks[i], tag, comm, out + (size_t)i * chunk, chunk);
    return MPI_SUCCESS;
}

int MPI_Reduce_scatter_block(const void *sendbuf, void *recvbuf, int recvcount,
                             MPI_Datatype dt, MPI_Op op, MPI_Comm comm) {
    /* allreduce-then-slice: correct and simple, which is all a shim needs */
    comm_info *c = comm_by_id(comm);
    int rank = comm_rank_of(c, me()->world_rank);
    int total = recvcount * c->size;
    size_t chunk = (size_t)recvcount * dt_size(dt);
    char *tmp = (char *)malloc((size_t)total * dt_size(dt));
    if (!tmp) abort();
    int rc = MPI_Allreduce(sendbuf, tmp, total, dt, op, comm);
    if (rc == MPI_SUCCESS) memcpy(recvbuf, tmp + (size_t)rank * chunk, chunk);
    free(tmp);
    return rc;
}

int MPI_Comm_split(MPI_Comm comm, int color, int key, MPI_Comm *newcomm) {
    comm_info *c = comm_by_id(comm);
    int rank = comm_rank_of(c, me()->world_rank);
    int tag = TAG_BASE_SPLIT + (int)(me()->coll_seq[comm_slot(comm)]++ & 0xFFFF);
    int pair[2] = {color, key};
    if (rank == 0) {
        int colors[MAX_RANKS], keys[MAX_RANKS];
        colors[0] = color;
        keys[0] = key;
        for (int i = 1; i < c->size; i++) {
            int got[2];
            raw_recv(c->world_ranks[i], tag, comm, got, sizeof got);
            colors[i] = got[0];
            keys[i] = got[1];
        }
        /* one new comm per distinct color; membership stable-sorted by
         * (key, parent rank); record which id each parent rank landed in */
        int assigned[MAX_RANKS];
        pthread_mutex_lock(&G.comms_mu);
        int done_colors[MAX_RANKS], ndone = 0;
        for (int i = 0; i < c->size; i++) {
            int seen = 0;
            for (int d = 0; d < ndone; d++)
                if (done_colors[d] == colors[i]) seen = 1;
            if (seen) continue;
            done_colors[ndone++] = colors[i];
            comm_info *nc = &G.comms[G.ncomms++];
            nc->id = G.next_comm_id++;
            nc->size = 0;
            int idx[MAX_RANKS], nidx = 0;
            for (int i2 = 0; i2 < c->size; i2++)
                if (colors[i2] == colors[i]) idx[nidx++] = i2;
            for (int a = 0; a < nidx; a++)
                for (int b = a + 1; b < nidx; b++)
                    if (keys[idx[b]] < keys[idx[a]]) {
                        int t = idx[a];
                        idx[a] = idx[b];
                        idx[b] = t;
                    }
            for (int a = 0; a < nidx; a++) {
                nc->world_ranks[nc->size++] = c->world_ranks[idx[a]];
                assigned[idx[a]] = nc->id;
            }
        }
        pthread_mutex_unlock(&G.comms_mu);
        for (int i = 0; i < c->size; i++) {
            if (i == 0)
                *newcomm = assigned[0];
            else
                raw_send(c->world_ranks[i], tag + 1, comm, &assigned[i], sizeof assigned[i]);
        }
    } else {
        raw_send(c->world_ranks[0], tag, comm, pair, sizeof pair);
        raw_recv(c->world_ranks[0], tag + 1, comm, newcomm, sizeof *newcomm);
    }
    me()->coll_seq[comm_slot(comm)]++; /* consume tag+1 */
    return MPI_SUCCESS;
}

int MPI_Comm_free(MPI_Comm *comm) {
    *comm = MPI_COMM_NULL;
    return MPI_SUCCESS;
}

int MPI_Abort(MPI_Comm comm, int errorcode) {
    (void)comm;
    fprintf(stderr, "mpi_shim: MPI_Abort(%d)\n", errorcode);
    exit(errorcode ? errorcode : 1);
}

int MPI_Error_string(int errorcode, char *string, int *resultlen) {
    *resultlen = snprintf(string, MPI_MAX_ERROR_STRING, "shim error %d", errorcode);
    return MPI_SUCCESS;
}

double MPI_Wtime(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

/* --- launcher --- */

static void *thread_main(void *arg) {
    long rank = (long)arg;
    rank_state *st = (rank_state *)calloc(1, sizeof(rank_state));
    st->world_rank = (int)rank;
    pthread_setspecific(tls_key, st);
    G.exit_codes[rank] = G.rank_main(G.argc, G.argv);
    free(st);
    return NULL;
}

int shim_run(int nranks, int hosts, shim_rank_main_fn rank_main, int argc,
             char **argv) {
    if (nranks < 1 || nranks > MAX_RANKS) {
        fprintf(stderr, "mpi_shim: nranks %d out of range 1..%d\n", nranks, MAX_RANKS);
        return 1;
    }
    memset(&G, 0, sizeof G);
    G.nranks = nranks;
    G.hosts = hosts > 0 ? hosts : 2;
    G.rank_main = rank_main;
    G.argc = argc;
    G.argv = argv;
    pthread_mutex_init(&G.comms_mu, NULL);
    for (int i = 0; i < nranks; i++) {
        pthread_mutex_init(&G.boxes[i].mu, NULL);
        pthread_cond_init(&G.boxes[i].cv, NULL);
    }
    G.comms[0].id = MPI_COMM_WORLD;
    G.comms[0].size = nranks;
    for (int i = 0; i < nranks; i++) G.comms[0].world_ranks[i] = i;
    G.ncomms = 1;
    G.next_comm_id = 1000;
    pthread_key_create(&tls_key, NULL);

    pthread_t threads[MAX_RANKS];
    for (long i = 0; i < nranks; i++)
        pthread_create(&threads[i], NULL, thread_main, (void *)i);
    int rc = 0;
    for (int i = 0; i < nranks; i++) {
        pthread_join(threads[i], NULL);
        if (G.exit_codes[i] > rc) rc = G.exit_codes[i];
    }
    return rc;
}
