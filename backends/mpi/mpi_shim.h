/* mpi_shim — a single-process, pthread-backed implementation of the MPI
 * subset used by tpu_mpi_perf.c, so the native baseline backend can be
 * compiled and smoke-tested on machines with no MPI installation (this
 * repo's CI image has no mpicc).  Each MPI "rank" is a thread; messages are
 * malloc'd copies passed through per-destination mailboxes.
 *
 * This is a test harness, not an MPI library: sends are buffered (never
 * block), collectives are O(n^2) over the point-to-point layer, and only
 * the calls used by the driver exist.  Build the real thing with mpicc
 * (see Makefile target `mpi_perf`); build this with `make shim`.
 */
#ifndef TPU_PERF_MPI_SHIM_H
#define TPU_PERF_MPI_SHIM_H

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef int MPI_Comm;
typedef int MPI_Datatype;
typedef int MPI_Op;
typedef int MPI_Request;

typedef struct {
    int MPI_SOURCE;
    int MPI_TAG;
    int MPI_ERROR;
} MPI_Status;

#define MPI_COMM_WORLD 0
#define MPI_COMM_NULL (-1)

#define MPI_BYTE 1
#define MPI_CHAR 2
#define MPI_INT 3
#define MPI_DOUBLE 4
#define MPI_FLOAT 5

#define MPI_MIN 1
#define MPI_MAX 2
#define MPI_SUM 3

#define MPI_SUCCESS 0
#define MPI_ERR_OTHER 1
#define MPI_MAX_PROCESSOR_NAME 256
#define MPI_MAX_ERROR_STRING 256
#define MPI_STATUS_IGNORE ((MPI_Status *)0)
#define MPI_STATUSES_IGNORE ((MPI_Status *)0)
#define MPI_REQUEST_NULL (-1)

int MPI_Init(int *argc, char ***argv);
int MPI_Finalize(void);
int MPI_Comm_size(MPI_Comm comm, int *size);
int MPI_Comm_rank(MPI_Comm comm, int *rank);
int MPI_Get_processor_name(char *name, int *resultlen);
int MPI_Send(const void *buf, int count, MPI_Datatype dt, int dest, int tag,
             MPI_Comm comm);
int MPI_Recv(void *buf, int count, MPI_Datatype dt, int source, int tag,
             MPI_Comm comm, MPI_Status *status);
int MPI_Isend(const void *buf, int count, MPI_Datatype dt, int dest, int tag,
              MPI_Comm comm, MPI_Request *req);
int MPI_Irecv(void *buf, int count, MPI_Datatype dt, int source, int tag,
              MPI_Comm comm, MPI_Request *req);
int MPI_Waitall(int count, MPI_Request reqs[], MPI_Status statuses[]);
int MPI_Barrier(MPI_Comm comm);
int MPI_Bcast(void *buf, int count, MPI_Datatype dt, int root, MPI_Comm comm);
int MPI_Allgather(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                  void *recvbuf, int recvcount, MPI_Datatype recvtype,
                  MPI_Comm comm);
int MPI_Allreduce(const void *sendbuf, void *recvbuf, int count,
                  MPI_Datatype dt, MPI_Op op, MPI_Comm comm);
int MPI_Alltoall(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                 void *recvbuf, int recvcount, MPI_Datatype recvtype,
                 MPI_Comm comm);
int MPI_Reduce_scatter_block(const void *sendbuf, void *recvbuf, int recvcount,
                             MPI_Datatype dt, MPI_Op op, MPI_Comm comm);
int MPI_Comm_split(MPI_Comm comm, int color, int key, MPI_Comm *newcomm);
int MPI_Comm_free(MPI_Comm *comm);
int MPI_Abort(MPI_Comm comm, int errorcode);
int MPI_Error_string(int errorcode, char *string, int *resultlen);
double MPI_Wtime(void);

/* --- shim launcher API (used by shim_main.c, not by the driver) --- */

typedef int (*shim_rank_main_fn)(int argc, char **argv);

/* Run `nranks` threads through `rank_main`; each sees an MPI world of size
 * nranks.  `hosts` controls MPI_Get_processor_name: rank r reports hostname
 * "shimhost<r / (nranks/hosts)>", emulating `mpirun --map-by ppr:N:node`
 * placement so the driver's two-group hostname matching is exercised.
 * Returns the max exit code across ranks. */
int shim_run(int nranks, int hosts, shim_rank_main_fn rank_main, int argc,
             char **argv);

#ifdef __cplusplus
}
#endif

#endif /* TPU_PERF_MPI_SHIM_H */
