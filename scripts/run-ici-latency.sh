#!/usr/bin/env bash
# Ping-pong latency sweep (BASELINE.json config 1: "2-rank MPI ping-pong
# latency sweep" -> the blocking bidirectional kernel, mpi_perf.c:66-83,
# as chained ppermute round trips over pair partners).  Rows report the
# one-way latency (RTT/2) in lat_us; p50/p95/p99 come from tpu-perf report.
set -euo pipefail

SWEEP=${SWEEP:-8:1M}
ITERS=${ITERS:-100}
RUNS=${RUNS:-20}
LOGDIR=${LOGDIR:-}

args=(run --op pingpong --sweep "$SWEEP" -i "$ITERS" -r "$RUNS" --csv)
[[ -n "$LOGDIR" ]] && args+=(-l "$LOGDIR")
exec python -m tpu_perf "${args[@]}"
