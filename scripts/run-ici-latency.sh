#!/usr/bin/env bash
# Latency sweep (BASELINE.json config 1: "2-rank MPI ping-pong latency
# sweep" -> the blocking bidirectional kernel, mpi_perf.c:66-83, as
# chained ppermute round trips over pair partners).  Rows report the
# one-way latency (RTT/2) in lat_us; p50/p95/p99 come from tpu-perf report.
#
# OP widens the profile to any kernel (on the single tunneled chip the
# pairwise ops cannot run, so the defended small-size curve uses the
# local instruments: OP=hbm_stream,hbm_read,hbm_write).  FENCE=trace is
# the device-clock slope — the only fence that resolves sub-128MiB
# points on a relayed runtime (BASELINE.md round-4); FENCE=auto probes
# the runtime once and picks trace (device lanes present) or slope, so
# one command line serves both runtimes.  The default stays block (the
# CLI's default, what this profile always used): rows from different
# fences are not comparable, so changing fence is an explicit operator
# act.
set -euo pipefail

OP=${OP:-pingpong}
SWEEP=${SWEEP:-8:1M}
ITERS=${ITERS:-100}
RUNS=${RUNS:-20}
FENCE=${FENCE:-block}
DTYPE=${DTYPE:-float32}
LOGDIR=${LOGDIR:-}

args=(run --op "$OP" --sweep "$SWEEP" -i "$ITERS" -r "$RUNS"
      --fence "$FENCE" --dtype "$DTYPE" --csv)
[[ -n "$LOGDIR" ]] && args+=(-l "$LOGDIR")
exec python -m tpu_perf "${args[@]}" "$@"
