# Sourced helper: render a command array as one copy-pasteable line,
# quoting only the args that need it.  Shared by the DRY_RUN modes of the
# run-mpi-*.sh profile scripts so the safety regex cannot drift.
render_cmd() {
    local a
    for a in "$@"; do
        if [[ $a =~ ^[A-Za-z0-9_./:=,@%+-]+$ ]]; then printf '%s ' "$a"
        else printf '%q ' "$a"; fi
    done
    echo
}
