#!/usr/bin/env bash
# Fleet-health monitoring profile — run-ici-monitor.sh with the online
# health subsystem on: per-(op, size, dtype) streaming baselines,
# step/spike/flatline/capture-loss detectors, rotating health-*.log JSONL
# events (ingested next to the CSV rows), and a Prometheus textfile of
# current gauges for the node-exporter textfile collector.
set -euo pipefail

BUFF=${BUFF:-456131}
ITERS=${ITERS:-10}
LOGDIR=${LOGDIR:-/mnt/tcp-logs}   # = tpu_perf.config.DEFAULT_LOG_DIR
# OPS: empty = the reference-faithful unidirectional kernel; a comma
# family rotates the whole instrument set through one judged daemon
OPS=${OPS:-}
# SWEEP: empty = single buffer (BUFF); a size list gives every sweep
# point its own baseline, e.g. SWEEP=64K,1M,16M
SWEEP=${SWEEP:-}
FENCE=${FENCE:-block}             # trace = device clock (TPU runtimes)
THRESHOLD=${THRESHOLD:-0.5}       # step-regression threshold (+50%)
WARMUP=${WARMUP:-30}              # baseline samples before a point is judged
TEXTFILE=${TEXTFILE:-}            # e.g. /var/lib/node_exporter/tpu-perf.prom
MAX_RUNS=${MAX_RUNS:-}            # bound the daemon (soaks/CI); empty = forever
export TPU_PERF_INGEST=${TPU_PERF_INGEST:-none}

args=(--health --health-threshold "$THRESHOLD" --health-warmup "$WARMUP"
      -i "$ITERS" --fence "$FENCE" -l "$LOGDIR")
if [ -n "$TEXTFILE" ]; then
    args+=(--health-textfile "$TEXTFILE")
fi
if [ -n "$MAX_RUNS" ]; then
    args+=(--max-runs "$MAX_RUNS")
fi
if [ -n "$SWEEP" ]; then
    args+=(--sweep "$SWEEP")
else
    args+=(-b "$BUFF")
fi

# extra args pass through to the CLI (like run-ici-monitor.sh), so a soak
# can override e.g. --log-refresh-sec / --heartbeat-format json
if [ -n "$OPS" ]; then
    exec python -m tpu_perf monitor --op "$OPS" "${args[@]}" "$@"
fi
exec python -m tpu_perf monitor -u "${args[@]}" "$@"
