#!/usr/bin/env bash
# Fleet-monitoring daemon profile — the TPU analogue of the reference's
# run-hbv3.sh / run-ib.sh / run-t4.sh monitors: unidirectional kernel at the
# legacy 456,131-byte buffer, infinite runs (-r -1), rotating logs +
# continuous ingest (reference run-hbv3.sh:3-9,22-28).
set -euo pipefail

BUFF=${BUFF:-456131}
ITERS=${ITERS:-10}
LOGDIR=${LOGDIR:-/mnt/tcp-logs}   # = tpu_perf.config.DEFAULT_LOG_DIR
# OPS: empty = the reference-faithful unidirectional kernel; set a comma
# family to rotate the whole instrument set through one daemon, e.g.
#   OPS=hbm_stream,hbm_read,hbm_write,mxu_gemm bash run-ici-monitor.sh
OPS=${OPS:-}
FENCE=${FENCE:-block}   # trace = device clock (TPU runtimes)
# PRECOMPILE: AOT-compile this many upcoming points on a background
# thread while the daemon measures (0 = inline builds); COMPILE_CACHE: a
# persistent XLA compile-cache dir so daemon RESTARTS skip recompiling
# the whole instrument family (docs/design.md "Sweep engine & compile
# pipeline")
PRECOMPILE=${PRECOMPILE:-0}
COMPILE_CACHE=${COMPILE_CACHE:-}
# SPANS=1: harness span tracing — spans-*.log next to the row logs,
# exported with `tpu-perf timeline` (docs/design.md "Tracing &
# correlation"); rows/events gain the enclosing-run join key
SPANS=${SPANS:-0}
extra=(--precompile "$PRECOMPILE")
[ -n "$COMPILE_CACHE" ] && extra+=(--compile-cache "$COMPILE_CACHE")
[ "$SPANS" = "1" ] && extra+=(--spans)
# TPU_PERF_INGEST selects the telemetry sink, e.g.
#   kusto:https://ingest-<cluster>.kusto.windows.net   (reference pipeline)
#   local:/mnt/tcp-ingested                            (air-gapped)
export TPU_PERF_INGEST=${TPU_PERF_INGEST:-none}

# extra args pass through to the CLI (like run-multislice.sh), so a
# soak can override e.g. --log-refresh-sec / --stats-every without
# editing the profile
if [ -n "$OPS" ]; then
    exec python -m tpu_perf monitor --op "$OPS" -b "$BUFF" -i "$ITERS" \
        --fence "$FENCE" "${extra[@]}" -l "$LOGDIR" "$@"
fi
exec python -m tpu_perf monitor -u -b "$BUFF" -i "$ITERS" \
    --fence "$FENCE" "${extra[@]}" -l "$LOGDIR" "$@"
