#!/usr/bin/env bash
# Legacy MPI baseline, 1-pair IB bandwidth profile — reproduces the
# reference's scripts/run-1-pair.sh (2 hosts x 1 flow, windowed
# non-blocking, 4 MiB x 5000 iters x 10 runs, UCX IB RC; reference
# run-1-pair.sh:3-9,24-28) against this repo's native driver.
#
# HOSTS      comma-separated host pair, e.g. "node-a,node-b"
# GROUP1     file listing the second host (the group-1 side)
# NUMA_NODE  numactl cpu+mem bind (reference run-1-pair.sh:27 pins node 0);
#            set NUMA_NODE= (empty) to disable
# DRY_RUN=1  print the mpirun command instead of executing it
set -euo pipefail

HOSTS=${HOSTS:?set HOSTS=host0,host1}
GROUP1=${GROUP1:?set GROUP1=/path/to/group1-hostfile}
ITERS=${ITERS:-5000}
RUNS=${RUNS:-10}
BUFF=${BUFF:-4194304}
LOGDIR=${LOGDIR:-/mnt/tcp-logs}   # = tpu_perf.config.DEFAULT_LOG_DIR
NET=${NET:-mlx5_ib0:1}
NUMA_NODE=${NUMA_NODE-0}

HERE=$(cd "$(dirname "$0")/.." && pwd)

numa=()
[[ -n "$NUMA_NODE" ]] && numa=(numactl --cpunodebind="$NUMA_NODE" --membind "$NUMA_NODE")

cmd=(mpirun -np 2 --host "$HOSTS" --map-by ppr:1:node --bind-to core
     -x UCX_NET_DEVICES="$NET" -x UCX_TLS=rc
     "${numa[@]}"
     "$HERE/backends/mpi/mpi_perf"
     -f "$GROUP1" -n 1 -i "$ITERS" -r "$RUNS" -b "$BUFF" -x 1 -l "$LOGDIR")

if [[ -n "${DRY_RUN:-}" ]]; then
    source "$HERE/scripts/_render.sh"
    render_cmd "${cmd[@]}"
    exit 0
fi
make -C "$HERE/backends/mpi" mpi_perf
exec "${cmd[@]}"
