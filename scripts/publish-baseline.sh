#!/usr/bin/env bash
# Publish the single-chip baseline curves THROUGH the framework's own
# pipeline (VERDICT r2 #3): run the instrument family on the real chip,
# keep the raw rows, and emit the aggregated JSON/markdown via
# `tpu-perf report` — so BASELINE.md's tables are rendered artifacts a
# fresh checkout can regenerate and diff, not hand-transcribed prose.
#
#   bash scripts/publish-baseline.sh            # writes results/r3/
#   OUT=results/r4 bash scripts/publish-baseline.sh
#
# Instruments (single-chip honest set, BASELINE.md "Headline methodology"):
#   hbm_stream   f32 128/256/384 MiB   the HBM read+write plateau
#   hbm_stream   bf16 256/384 MiB      the dtype real workloads move
#   hbm_stream   int32 256 MiB         integer movement (wrap-add body)
#   hbm_read     f32 256/384/512 MiB + bf16 256 MiB   read-path ceiling
#                                      (reduce into slot 0)
#   hbm_write    f32 256/384/512 MiB + bf16 256 MiB   write-path ceiling
#                                      (broadcast carry)
#   hbm_triad    f32 256/384 MiB      the 2R:1W mixed point (round 5:
#                                      686.6 GB/s — ABOVE the 1R:1W
#                                      stream, using read-path headroom)
# The single-sided points run at iters 40+ (slope 40/160): they move HALF
# of hbm_stream's per-iteration traffic, so at the default 16 they sit in
# the relay-jitter regime (measured: p50 above the 819 GB/s physical spec —
# the same failure mode as the retracted round-1 64 MiB points).  The f32
# 256M write point needs 80: at 40 it read an unphysical 972 p50 twice.
#   pl_hbm_copy  f32 64/256 MiB        DMA copy-path ceiling (Pallas)
#   pl_hbm_read  f32 256/384 + bf16 256 MiB   DMA read-path (HBM->VMEM sweep)
#   pl_hbm_write f32 256/384 + bf16 256 MiB   DMA write-path (VMEM->HBM sweep)
#   mxu_gemm     bf16 32 MiB           m=4096 MXU roofline headline (97.8%
#                                      of peak under the trace fence,
#                                      round 4; the m-cap rose from 2048)
#   mxu_gemm     bf16 8 MiB, f32 16 MiB   m=2048 roofline (iters 250/500:
#                                      at 25 the lo slope run is ~2 ms and
#                                      the p50 converts to >100% of peak.
#                                      bf16 is pinned at 8 MiB — 16 MiB
#                                      bf16 would round to m=2944 under
#                                      the raised cap, not the m=2048
#                                      the r3 artifacts recorded)
#   mxu_gemm     bf16 128K/512K/2M     m=256/512/1024 utilization-vs-size
#                                      curve.  The m>=1024 lo slope runs
#                                      clear ≳18 ms of device time and are
#                                      the CLAIM; the m=256/512 marginals
#                                      (0.3-1.2 us/iter; lo runs only
#                                      ~4-11 ms even at these iter counts)
#                                      cannot clear the relay noise at any
#                                      practical trip count — recorded for
#                                      the raw artifact, excluded from
#                                      claims (BASELINE.md "Round-3
#                                      correction")
# All slope-fenced (the only honest fence on relay-acknowledged runtimes);
# small sizes are excluded as relay-jitter-dominated (BASELINE.md).
set -euo pipefail

OUT=${OUT:-results/r3}
ITERS=${ITERS:-16}
RUNS=${RUNS:-8}
# FENCE=trace publishes on the device clock (round 4) — same points,
# ~100x less window noise; slope stays the default so regenerating an
# r3-era artifact set keeps its semantics
FENCE=${FENCE:-slope}
# POINTS: op:dtype:size[:iters] triples — override for a quick smoke run
# (e.g. POINTS="hbm_stream:float32:1M" on the CPU mesh in CI)
POINTS=${POINTS:-"
hbm_stream:float32:128M
hbm_stream:float32:256M
hbm_stream:float32:384M
hbm_stream:bfloat16:256M
hbm_stream:bfloat16:384M
hbm_stream:int32:256M
hbm_stream:float16:256M
hbm_stream:uint8:256M
hbm_read:float32:256M:40
hbm_read:float32:384M:40
hbm_read:float32:512M:40
hbm_read:bfloat16:256M:40
hbm_write:float32:256M:80
hbm_write:float32:384M:40
hbm_write:float32:512M:40
hbm_write:bfloat16:256M:40
hbm_triad:float32:256M
hbm_triad:float32:384M
pl_hbm_copy:float32:64M
pl_hbm_copy:float32:256M
pl_hbm_read:float32:256M:40
pl_hbm_read:float32:384M:80
pl_hbm_read:bfloat16:256M:80
pl_hbm_write:float32:256M:40
pl_hbm_write:float32:384M:80
pl_hbm_write:bfloat16:256M:80
pl_hbm_stream:float32:384M
pl_hbm_stream:bfloat16:384M
mxu_gemm:bfloat16:32M:100
mxu_gemm:bfloat16:8M:250
mxu_gemm:float32:16M:500
mxu_gemm:bfloat16:128K:12000
mxu_gemm:bfloat16:512K:12000
mxu_gemm:bfloat16:2M:1500
"}
HERE=$(cd "$(dirname "$0")/.." && pwd)
RAW="$OUT/raw"

cd "$HERE"
mkdir -p "$RAW"

for point in $POINTS; do
    IFS=: read -r op dtype size iters <<< "$point"
    iters=${iters:-$ITERS}
    echo "[publish-baseline] $op $dtype $size x$iters" >&2
    python -m tpu_perf run --op "$op" --dtype "$dtype" -b "$size" \
        --fence "$FENCE" -i "$iters" -r "$RUNS" -l "$RAW" \
        || echo "[publish-baseline] $op $dtype $size FAILED (continuing)" >&2
done

python -m tpu_perf report "$RAW" --format json     > "$OUT/single-chip.json"
python -m tpu_perf report "$RAW"                   > "$OUT/single-chip.md"
python -m tpu_perf report "$RAW" --compare-pallas  > "$OUT/pallas-vs-xla.md"

{
    echo "# Generated by scripts/publish-baseline.sh"
    echo "# $(python -c 'import datetime;print(datetime.datetime.now().isoformat())')"
    echo "# device: $(python - <<'EOF'
import jax
d = jax.devices()[0]
print(f"{d.platform}:{d.device_kind} x{len(jax.devices())}")
EOF
)"
    echo "# ITERS=$ITERS RUNS=$RUNS FENCE=$FENCE"
} > "$OUT/PROVENANCE.txt"

echo "[publish-baseline] artifacts in $OUT/" >&2
