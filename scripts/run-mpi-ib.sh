#!/usr/bin/env bash
# IB 6-hop fleet-monitor profile (reference run-ib.sh:22-27): UCX IB RC on
# mlx5_ib2 port 1 with service level 1, pinned to the odd cores 5..23.
set -euo pipefail
# ${VAR-default} (not :-) so an explicit empty override still reaches
# run-mpi-monitor.sh, which treats empty SL/CPU_LIST as "omit the knob"
export NET=${NET-mlx5_ib2:1}
export TLS=${TLS-rc}
export SL=${SL-1}
export CPU_LIST=${CPU_LIST-5,7,9,11,13,15,17,19,21,23}
exec "$(dirname "$0")/run-mpi-monitor.sh"
