#!/usr/bin/env bash
# Raw-transport vs XLA-collective comparison sweep: each Pallas RDMA kernel
# next to its XLA counterpart on the same sizes, so `tpu-perf report` shows
# the overhead XLA's collective algorithms add over the raw link
# (docs/design.md "publishing both curves is the point").
set -euo pipefail

PAIRS=${PAIRS:-"pl_ring:ring pl_exchange:exchange pl_all_gather:all_gather \
pl_reduce_scatter:reduce_scatter pl_allreduce:allreduce \
pl_all_to_all:all_to_all pl_pingpong:pingpong pl_barrier:barrier \
pl_hbm_copy:hbm_stream pl_hbm_stream:hbm_stream \
pl_hbm_read:hbm_read pl_hbm_write:hbm_write"}
SWEEP=${SWEEP:-8:16M}
ITERS=${ITERS:-20}
RUNS=${RUNS:-10}
LOGDIR=${LOGDIR:-}
FENCE=${FENCE:-block}   # trace = device clock (TPU runtimes)
# DRY_RUN=1 prints each command instead of executing it (the convention
# the run-mpi-*.sh profiles follow — a full PAIRS sweep is hours of
# device time, so the rendered plan must be inspectable first)
source "$(dirname "$0")/_render.sh"

fail=0
for pair in $PAIRS; do
    for op in ${pair/:/ }; do
        args=(run --op "$op" --sweep "$SWEEP" -i "$ITERS" -r "$RUNS"
              --fence "$FENCE" --csv)
        [[ -n "$LOGDIR" ]] && args+=(-l "$LOGDIR")
        if [[ -n "${DRY_RUN:-}" ]]; then
            render_cmd python -m tpu_perf "${args[@]}" "$@"
            continue
        fi
        # extra script args pass through to every invocation
        python -m tpu_perf "${args[@]}" "$@" \
            || { echo "run-ici-pallas: $op failed" >&2; fail=1; }
    done
done
exit $fail
