#!/usr/bin/env bash
# MPI collective baseline profile: the backend=mpi side of the side-by-side
# collective comparison (jax/ICI rows come from run-ici-allreduce.sh).
# Mirrors the reference's script shape (env-tunable, mpirun launch); UCX
# transport env goes here exactly as in run-ib.sh:25-26 / run-hbv3.sh:25-27.
set -euo pipefail

NP=${NP:-8}                 # ranks
OP=${OP:-allreduce}         # allreduce all_gather reduce_scatter all_to_all broadcast barrier
BUF=${BUF:-4194304}         # bytes (per-rank buffer; see -o size semantics)
ITERS=${ITERS:-100}
RUNS=${RUNS:-10}
LOGDIR=${LOGDIR:-/mnt/tcp-logs}   # = tpu_perf.config.DEFAULT_LOG_DIR

cd "$(dirname "$0")/../backends/mpi"

if command -v mpirun >/dev/null 2>&1 && [ -x ./mpi_perf ]; then
    # real MPI: UCX env (e.g. UCX_NET_DEVICES/UCX_TLS) is inherited
    exec mpirun -np "$NP" ./mpi_perf -o "$OP" -b "$BUF" -i "$ITERS" \
        -r "$RUNS" -l "$LOGDIR"
else
    # no MPI installation: pthread shim (single host, functional baseline)
    make -s shim
    exec ./mpi_perf_shim -np "$NP" -- -o "$OP" -b "$BUF" -i "$ITERS" \
        -r "$RUNS" -l "$LOGDIR"
fi
