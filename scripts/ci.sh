#!/usr/bin/env bash
# The full CI gate as one local command (VERDICT r1 #7: the check that
# would have caught a red suite before it was committed).  Used verbatim by
# .github/workflows/ci.yml.
set -euxo pipefail
cd "$(dirname "$0")/.."

# 0h. static invariant lint gate (ISSUE 8), ordered FIRST: the analyzer
#     proves the determinism/lockstep/record-plane contracts at parse
#     time in ~a second, so an invariant break fails here before any
#     suite spends minutes executing it.  Exit-code contract: lint exits
#     8 on any unbaselined finding (set -e trips), and the JSON schema
#     assertions below pin the machine-consumption format collectors
#     parse (docs/design.md "Static analysis & invariant linting").  The
#     shipped baseline is EMPTY by contract — a finding is fixed or
#     pragma-annotated, never baselined in this tree.
# a tripped gate must SHOW its findings in the CI log — and a lint
# CONFIG error (exit 2: bad manifest/baseline, nothing on stdout) must
# not masquerade as "unbaselined findings"
lint_rc=0
JAX_PLATFORMS=cpu python -m tpu_perf lint --format json \
    --baseline tpu_perf/analysis/baseline.json > /tmp/ci-lint.json \
    || lint_rc=$?
if [ "$lint_rc" -ne 0 ]; then
    cat /tmp/ci-lint.json
    echo "tpu-perf lint exited $lint_rc (8 = unbaselined findings," \
         "2 = lint configuration error)"
    exit "$lint_rc"
fi
python - <<'EOF'
import json
data = json.load(open("/tmp/ci-lint.json"))
assert data["version"] == 1, data["version"]
assert data["summary"]["unbaselined"] == 0, data["findings"]
assert [r["id"] for r in data["rules"]] == ["R1", "R2", "R3", "R4", "R5",
                                            "R6"]
assert json.load(open("tpu_perf/analysis/baseline.json"))["findings"] == []
# the sanctioned escape hatches stay visible (counted, never silent)
# pin the pragma-report SCHEMA (the escape hatches stay visible), not
# today's annotation inventory — which sites carry pragmas is pinned by
# tests/test_analysis.py's live-tree self-check, where a failure names
# the missing site instead of dying on a bare set
for p in data["pragmas"]:
    assert set(p) == {"path", "line", "kind", "arg"}, p
assert len(data["suppressed"]) <= len(data["pragmas"])
print(f"lint: {data['summary']['files']} files clean, "
      f"{len(data['pragmas'])} pragma site(s)")
EOF
JAX_PLATFORMS=cpu python -m pytest tests/test_analysis.py -q
# ruff is the fast third-party layer UNDER the custom analyzer
# (pyproject.toml [tool.ruff]): generic rot — undefined names, unused
# imports — caught in milliseconds.  Gated on availability: the hermetic
# CI image deliberately adds no third-party tooling.
if command -v ruff >/dev/null 2>&1; then
    ruff check .
else
    echo "ruff not installed; skipping the third-party lint layer"
fi

# 0. fleet-health subsystem: the health suites as their own named gate,
#    BEFORE the full suite — set -e would otherwise never reach them
#    when the full suite is red for unrelated reasons, which is exactly
#    when a targeted signal matters; plus a compileall smoke
JAX_PLATFORMS=cpu python -m pytest tests/test_health_stats.py \
    tests/test_health_detect.py tests/test_health_monitor.py -q
python -m compileall -q tpu_perf/health

# 0b. chaos conformance gate (ISSUE 2): a seeded spec with one fault per
#     detector kind through a bounded SYNTHETIC soak (seeded timing
#     source — a real CPU outlier on a shared runner must not decide the
#     gate) must be judged ALL CAUGHT; the same seed+spec must reproduce
#     a byte-identical injection ledger; and a fault-free soak must
#     report zero events/false alarms after warm-up.
JAX_PLATFORMS=cpu python -m pytest tests/test_faults.py tests/test_chaos.py -q
export PYTHONPATH= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8
rm -rf /tmp/ci-chaos && mkdir -p /tmp/ci-chaos
cat > /tmp/ci-chaos/spec.json <<'EOF'
{"faults": [
  {"kind": "spike",     "op": "ring", "nbytes": 32, "start": 60,  "end": 80, "magnitude": 30.0},
  {"kind": "drop_run",  "op": "ring", "nbytes": 8,  "start": 81,  "end": 120},
  {"kind": "hook_fail",                             "start": 130, "end": 135},
  {"kind": "delay",     "op": "ring", "nbytes": 32, "start": 150, "end": 400, "magnitude": 3.0},
  {"kind": "flatline",  "op": "ring", "nbytes": 8,  "start": 200, "end": 400},
  {"kind": "corrupt",   "op": "ring"}
]}
EOF
# soak `b` runs under --precompile 4 (ISSUE 4) AND with the adaptive
# controller flag enabled (ISSUE 5): the a/b ledger diff below proves
# (1) a pipelined soak reproduces the serial soak's ledger byte for
# byte — the precompile worker never executes a kernel, so the injector
# sees the identical (op, nbytes, run_id) stream — and (2) --ci-rel is
# BYPASSED under --faults/--synthetic (an early stop would change the
# run sequence the ledger hashes)
extra=()
for d in a b; do
    python -m tpu_perf chaos --faults /tmp/ci-chaos/spec.json --seed 7 \
        --max-runs 400 --synthetic 0.001 --op ring --sweep 8,32 -i 1 \
        --stats-every 20 --health-warmup 20 "${extra[@]}" \
        -l "/tmp/ci-chaos/$d" >/dev/null 2>&1
    extra=(--precompile 4 --ci-rel 0.05)
done
python -m tpu_perf chaos verify /tmp/ci-chaos/a \
    | grep '6/6 fault(s) caught, 0 critical miss(es), 0 false alarm(s)'
diff <(cat /tmp/ci-chaos/a/chaos-*.log) <(cat /tmp/ci-chaos/b/chaos-*.log)
# false-alarm gate: no faults -> no health events at all, strict verify
python -m tpu_perf chaos --seed 7 --max-runs 200 --synthetic 0.001 \
    --op ring --sweep 8,32 -i 1 --stats-every 20 --health-warmup 20 \
    -l /tmp/ci-chaos/clean >/dev/null 2>&1
# tail-noise false-alarm gate (satellite): seeded LOGNORMAL jitter is
# the realistic-tail shape detectors must tolerate at zero false
# alarms.  (Pareto is the adversarial shape: its power-law tail draws
# ARE isolated multi-x samples, semantically spikes — use it to tune
# thresholds, never in a zero-false-alarm gate.)
cat > /tmp/ci-chaos/tail.json <<'EOF'
{"faults": [{"kind": "jitter", "shape": "lognormal", "magnitude": 0.1,
             "start": 1}]}
EOF
python -m tpu_perf chaos --faults /tmp/ci-chaos/tail.json --seed 7 \
    --max-runs 400 --synthetic 0.001 --op ring --sweep 8,32 -i 1 \
    --stats-every 20 --health-warmup 20 \
    -l /tmp/ci-chaos/tail >/dev/null 2>&1
python -m tpu_perf chaos verify /tmp/ci-chaos/tail --fail-on-false-alarm \
    | grep '0 false alarm(s)'
python -m tpu_perf chaos verify /tmp/ci-chaos/clean --fail-on-false-alarm \
    --textfile /tmp/ci-chaos/conformance.prom \
    | grep '0 false alarm(s) over 0 event(s)'
# conformance gauges landed for the dashboard feed (satellite: scheduled
# verify runs must not need markdown parsing)
grep -q 'tpu_perf_chaos_last_verify_timestamp_seconds' \
    /tmp/ci-chaos/conformance.prom

# 0c. linkmap localization gate (ISSUE 3): a synthetic (seeded) sweep of
#     a 2D mesh must grade every link ok fault-free (exit 0, zero false
#     alarms), and with a rank-targeted spike on ONE link must grade
#     exactly that link non-ok (exit 6), naming its device coordinates
#     and rank in both the verdict and the link_degraded health event;
#     linkmap-*.log records round-trip through the ingest pipeline.
rm -rf /tmp/ci-linkmap && mkdir -p /tmp/ci-linkmap
python -m tpu_perf linkmap --mesh 2x4 --synthetic 0.001 --seed 7 -b 64K \
    -l /tmp/ci-linkmap/clean | grep 'all 24 link(s) ok'
test -z "$(ls /tmp/ci-linkmap/clean/health-*.log 2>/dev/null)"
cat > /tmp/ci-linkmap/fault.json <<'EOF'
{"faults": [{"kind": "spike", "op": "link:(1,2)>(1,3)", "rank": 0,
             "magnitude": 30.0}]}
EOF
rc=0; python -m tpu_perf linkmap --mesh 2x4 --synthetic 0.001 --seed 7 \
    -b 64K --faults /tmp/ci-linkmap/fault.json -l /tmp/ci-linkmap/fault \
    > /tmp/ci-linkmap/fault.out 2>&1 || rc=$?
test "$rc" -eq 6
grep '23 ok, 1 slow, 0 dead' /tmp/ci-linkmap/fault.out
grep 'link:(1,2)>(1,3) slow (rank 0' /tmp/ci-linkmap/fault.out
grep -h 'link_degraded' /tmp/ci-linkmap/fault/health-*.log \
    | grep '"op": "link:(1,2)>(1,3)"' | grep -q '"rank": 0'
# the durable records replay to the same verdict (exit 6 again)
rc=0; python -m tpu_perf linkmap report /tmp/ci-linkmap/fault \
    > /tmp/ci-linkmap/replay.out 2>&1 || rc=$?
test "$rc" -eq 6
grep -q '1 slow' /tmp/ci-linkmap/replay.out
# fifth family rides the ingest pipeline into its own routed table
TPU_PERF_INGEST=local:/tmp/ci-linkmap/sink \
    python -m tpu_perf ingest -d /tmp/ci-linkmap/clean -f 0 2>&1 \
    | grep 'ingested 1 files'
ls /tmp/ci-linkmap/sink/linkmap-*.log >/dev/null

# 0d. pipelined sweep engine gate (ISSUE 4): a multi-op sweep serial vs
#     --precompile 4 must emit the exact same row set
#     (op/nbytes/iters/run_id — the precompile worker never executes a
#     kernel, so nothing observable may move; asserted on the block
#     fence, whose row stream is drop-free by construction — slope
#     drops are timing NOISE, so exact equality across two noisy runs
#     would gate on the weather, and the slope-path engine parity is
#     pinned deterministically by tests/test_compilepipe.py and the
#     chaos-ledger diff in 0b) and report a non-zero, genuinely
#     OVERLAPPED compile phase on the slope-fence sweep (the fence that
#     doubles the compile count).  Two overlap assertions:
#     *  phase concurrency: in the pipelined run compile_s + measure_s
#        exceeds the wall clock — impossible for a serial engine, whose
#        phases are disjoint slices of the wall (the sharp, machine-
#        independent proof that compile ran DURING measurement);
#     *  wall clock: best-of-two pipelined walls <= best-of-two serial
#        walls x1.15 — a REGRESSION guard (pipelining must never make a
#        sweep meaningfully slower), not a speedup assertion: on a
#        CPU-only runner the "device" work is host work and this
#        backend's per-program cost is mostly GIL-bound Python tracing
#        (measured ~0.2-0.5 s tracing vs ~0.02 s C++ XLA compile per
#        ppermute program), so the overlappable slice is thin and wall
#        parity is the expectation.  The wall REDUCTION is a hardware
#        property — on TPU, measurement occupies the device while
#        multi-second C++ compiles free the host — and what CI can
#        prove machine-independently is the concurrency itself, via the
#        phase-sum invariant above.
#     Plus the persistent-cache restart proof: a daemon restarted onto a
#     warm --compile-cache adds ZERO fresh cache entries (zero fresh
#     compiles), and `tpu-perf report` renders the harness-phases table
#     from the phase sidecars.
rm -rf /tmp/ci-pipe && mkdir -p /tmp/ci-pipe
python - <<'EOF'
import glob, json, subprocess, sys
def sweep(folder, extra):
    subprocess.run(
        [sys.executable, "-m", "tpu_perf", "run", "--op", "ring,exchange",
         "--sweep", "64K,128K,256K,512K,1M,2M,4M,8M", "-i", "4", "-r", "2",
         "--fence", "slope", "-l", folder, *extra], check=True,
        capture_output=True, text=True)
    (ph,) = glob.glob(folder + "/phase-*.json")
    with open(ph) as fh:
        return json.load(fh)
from tpu_perf.schema import ResultRow
def row_keys(folder):
    (log,) = glob.glob(folder + "/tpu-*.log")
    with open(log) as fh:
        return sorted((r.op, r.nbytes, r.iters, r.run_id)
                      for r in map(ResultRow.from_csv,
                                   fh.read().splitlines()))
# exact row-set identity on the drop-free block fence
def block_sweep(folder, extra):
    subprocess.run(
        [sys.executable, "-m", "tpu_perf", "run", "--op", "ring,exchange",
         "--sweep", "8,64,4K,64K", "-i", "2", "-r", "2", "--fence",
         "block", "-l", folder, *extra], check=True,
        capture_output=True, text=True)
    return row_keys(folder)
rows_serial = block_sweep("/tmp/ci-pipe/rows-serial", [])
rows_pipe = block_sweep("/tmp/ci-pipe/rows-pipe", ["--precompile", "4"])
assert rows_serial == rows_pipe and len(rows_pipe) == 16, \
    "pipelined row set differs from serial"
# overlap + wall on the slope fence (two compiles per point)
runs = {"serial": [], "pipe": []}
for attempt in ("a", "b"):  # interleaved: load drift hits both modes
    for mode, extra in (("serial", []), ("pipe", ["--precompile", "4"])):
        folder = f"/tmp/ci-pipe/{mode}-{attempt}"
        runs[mode].append((sweep(folder, extra), row_keys(folder)))
for mode in ("serial", "pipe"):
    for _, rows in runs[mode]:
        # every (op, size) point must have produced rows — noise may
        # drop individual slope samples, never whole points
        assert len({(op, nb) for op, nb, _, _ in rows}) == 16, \
            f"{mode} slope sweep lost whole points"
ph = runs["pipe"][0][0]["phase"]
assert ph["compile_s"] > 0 and ph["measure_s"] > 0, ph
for sidecar, _ in runs["pipe"]:
    # the machine-independent concurrency proof: a serial engine's
    # phases are disjoint slices of the wall, so their sum can only
    # exceed it when compile genuinely ran DURING measurement (the
    # blocked-wait is unphased, so this cannot be faked by accounting)
    p = sidecar["phase"]
    assert p["compile_s"] + p["measure_s"] > 1.05 * sidecar["wall_s"], \
        f"no phase overlap: {p} in wall {sidecar['wall_s']}"
serial_wall = min(s["wall_s"] for s, _ in runs["serial"])
pipe_wall = min(s["wall_s"] for s, _ in runs["pipe"])
assert pipe_wall <= 1.15 * serial_wall, \
    f"pipelined wall {pipe_wall:.1f}s regresses past serial " \
    f"{serial_wall:.1f}s x1.15"
print(f"pipelined sweep engine: serial {serial_wall:.1f}s "
      f"pipelined {pipe_wall:.1f}s, compile {ph['compile_s']:.1f}s "
      f"overlapped, identical block-fence row sets")
EOF
# the heartbeat's phase split is machine-readable at every boundary
# (no -m1: early grep exit would SIGPIPE the still-writing run under
# pipefail)
python -m tpu_perf run --op ring -b 4K -i 1 -r 4 --stats-every 2 \
    --heartbeat-format json --precompile 2 2>&1 >/dev/null \
    | grep '"phase": {"compile_s":' >/dev/null
# report renders the sidecars as the harness-phases breakdown
python -m tpu_perf report /tmp/ci-pipe/pipe-a | grep -A3 'Harness phases' \
    | grep -q 'compile/wall'
# warm-restart proof: run 2 adds zero fresh persistent-cache entries
for i in 1 2; do
    python -m tpu_perf monitor --op ring,exchange --sweep 8,32 -i 2 \
        --max-runs 4 --precompile 4 --compile-cache /tmp/ci-pipe/cache \
        -l "/tmp/ci-pipe/daemon$i" >/dev/null 2>&1
done
n_cache=$(ls /tmp/ci-pipe/cache/*-cache | wc -l)
test "$n_cache" -gt 0
python -m tpu_perf monitor --op ring,exchange --sweep 8,32 -i 2 \
    --max-runs 4 --precompile 4 --compile-cache /tmp/ci-pipe/cache \
    -l /tmp/ci-pipe/daemon3 >/dev/null 2>&1
test "$(ls /tmp/ci-pipe/cache/*-cache | wc -l)" -eq "$n_cache"

# 0e. adaptive sampling gate (ISSUE 5): on a seeded synthetic series
#     (Driver._measure replaced by a deterministic tight-noise stream —
#     the --synthetic flag deliberately BYPASSES the controller, so the
#     gate plants its series one layer up), --ci-rel 0.05 must take
#     >=30% fewer total measurement runs than the fixed -r budget while
#     every point's final-row ci_rel lands under the target; the rows'
#     adaptive columns must survive the rotating log and render as the
#     report's "Adaptive savings" table.  The chaos-bypass half of the
#     acceptance bar is the a/b ledger diff in 0b (soak b runs with
#     --ci-rel 0.05).
rm -rf /tmp/ci-adaptive && mkdir -p /tmp/ci-adaptive
python - <<'EOF'
import glob, random
from tpu_perf.config import Options
from tpu_perf.driver import Driver
from tpu_perf.parallel import make_mesh
from tpu_perf.schema import ResultRow

class SeededDriver(Driver):
    def _measure(self, built, built_hi):
        counts = self.__dict__.setdefault("_seed_counts", {})
        key = (built.name, built.nbytes)
        n = counts[key] = counts.get(key, 0) + 1
        rnd = random.Random(f"{built.name}:{built.nbytes}:{n}")
        return 1e-3 * (1.0 + 0.01 * (rnd.random() - 0.5))

mesh = make_mesh()
def run(folder, **kw):
    opts = Options(op="ring,exchange", sweep="8,64,4096", iters=1,
                   num_runs=30, fence="block", logfolder=folder, **kw)
    return SeededDriver(opts, mesh).run()

fixed = run("/tmp/ci-adaptive/fixed")
adaptive = run("/tmp/ci-adaptive/adaptive", ci_rel=0.05, min_runs=5)
assert len(fixed) == 6 * 30, len(fixed)
saved = 1 - len(adaptive) / len(fixed)
assert saved >= 0.30, f"adaptive saved only {saved:.0%} of the budget"
by_point = {}
for r in adaptive:
    by_point.setdefault((r.op, r.nbytes), []).append(r)
assert len(by_point) == 6  # early stopping must not lose whole points
for rows in by_point.values():
    final = max(rows, key=lambda r: r.run_id)
    assert final.runs_requested == 30
    assert 0 < final.ci_rel <= 0.05, (final.op, final.nbytes, final.ci_rel)
# the columns survive the rotating log byte-for-byte
(log,) = glob.glob("/tmp/ci-adaptive/adaptive/tpu-*.log")
with open(log) as fh:
    parsed = [ResultRow.from_csv(ln) for ln in fh.read().splitlines()]
assert len(parsed) == len(adaptive)
assert all(r.runs_requested == 30 for r in parsed)
print(f"adaptive sampling: {len(adaptive)}/{len(fixed)} runs "
      f"({saved:.0%} saved), every point ci_rel <= 5%")
EOF
# the savings table renders from the rows alone (replayable evidence)
python -m tpu_perf report /tmp/ci-adaptive/adaptive \
    | grep -A12 'Adaptive savings' | grep -q 'runs saved'
# the adaptive flags parse end-to-end on the real CLI (real timing, so
# only the plumbing is asserted, not the run count)
python -m tpu_perf run --op ring -b 4K -i 1 -r 6 --ci-rel 0.5 \
    --ci-confidence 0.90 --min-runs 2 --csv >/dev/null
# --precompile auto: depth tuned live, the landed depth in the sidecar
python -m tpu_perf run --op ring,exchange --sweep 8,64,4K -i 1 -r 2 \
    --precompile auto -l /tmp/ci-adaptive/auto >/dev/null
grep -q '"precompile": "auto"' /tmp/ci-adaptive/auto/phase-*.json
grep -q '"precompile_depth":' /tmp/ci-adaptive/auto/phase-*.json

# 0f. span-tracing gate (ISSUE 6): a seeded synthetic soak with
#     --precompile 4 --ci-rel 0.05 and --spans must (1) keep its chaos
#     ledger BYTE-IDENTICAL to the spans-off soak 0b ran with the same
#     seed/spec/flags — the tracer writes only its own family; (2)
#     export a timeline that validates as Chrome trace-event JSON with
#     complete cross-family joins (every row / health event / ledger
#     entry resolves to exactly one enclosing run span — `timeline
#     --check` exits 7 otherwise); and (3) show >= 1 worker-track build
#     span overlapping a main-track measure span — the 0d phase-sum
#     concurrency proof, now visible geometry.
rm -rf /tmp/ci-spans && mkdir -p /tmp/ci-spans
python -m tpu_perf chaos --faults /tmp/ci-chaos/spec.json --seed 7 \
    --max-runs 400 --synthetic 0.001 --op ring --sweep 8,32 -i 1 \
    --stats-every 20 --health-warmup 20 --precompile 4 --ci-rel 0.05 \
    --spans -l /tmp/ci-spans/on >/dev/null 2>&1
diff <(cat /tmp/ci-chaos/b/chaos-*.log) <(cat /tmp/ci-spans/on/chaos-*.log)
python -m tpu_perf timeline /tmp/ci-spans/on --check \
    -o /tmp/ci-spans/timeline.json 2>&1 | grep 'join complete'
python - <<'EOF'
import glob, json
from tpu_perf.spans import read_span_records
from tpu_perf.trace import build_measure_overlaps, validate_chrome_trace

with open("/tmp/ci-spans/timeline.json") as fh:
    data = json.load(fh)
problems = validate_chrome_trace(data)
assert not problems, problems
tracks = {e["tid"] for e in data["traceEvents"] if e.get("ph") == "X"}
# 0 = main, 1 = precompile worker, 2 = ingest hook (the spec's
# hook_fail window guarantees at least one hook execution span)
assert {0, 1, 2} <= tracks, f"expected main+worker+ingest tracks: {tracks}"
spans = read_span_records(glob.glob("/tmp/ci-spans/on/spans-*.log"))
overlaps = build_measure_overlaps(spans)
assert overlaps, "no worker-track build span overlaps a main-track measure"
print(f"span tracing: {len(spans)} spans, valid trace-event JSON, "
      f"{len(overlaps)} build/measure overlap(s), joins complete, "
      "ledger byte-identical spans on vs off")
EOF

# 0g. device-fused measurement loop gate (ISSUE 7): (1) fence
#     conformance — fused per-run p50 within 1.25x of the block fence
#     on the drop-free path (both fences time the same kernel; fused
#     amortizes the per-run dispatch, so it may read LOWER, bounded by
#     a generous floor against loop elision); (2) the headline claim as
#     a counter — a fixed-budget sweep point under --fence fused issues
#     EXACTLY ONE measured device dispatch (phase-sidecar fused audit);
#     (3) --ci-rel under fused early-stops via chunk-relayed lockstep
#     votes (planted chunk series, like 0e's seeded driver) with no
#     loud bypass; (4) a chaos soak under --fence fused reproduces 0b's
#     injection ledger byte for byte (the fence changes dispatch
#     structure, never the run sequence the ledger hashes).
rm -rf /tmp/ci-fused && mkdir -p /tmp/ci-fused
python - <<'EOF'
import glob, json, subprocess, sys
from tpu_perf.metrics import percentile
from tpu_perf.schema import ResultRow

def run(folder, *args):
    return subprocess.run(
        [sys.executable, "-m", "tpu_perf", "run", *args, "-l", folder],
        check=True, capture_output=True, text=True)

def rows_of(folder):
    (log,) = glob.glob(folder + "/tpu-*.log")
    with open(log) as fh:
        return [ResultRow.from_csv(ln) for ln in fh.read().splitlines()]

# (1) fence conformance on a kernel large enough that real work — not
# dispatch — dominates the block fence's samples
common = ["--op", "hbm_stream", "-b", "1M", "-i", "8", "-r", "8"]
run("/tmp/ci-fused/block", *common, "--fence", "block")
run("/tmp/ci-fused/fused", *common, "--fence", "fused")
bp = percentile([r.time_ms for r in rows_of("/tmp/ci-fused/block")], 50)
fp = percentile([r.time_ms for r in rows_of("/tmp/ci-fused/fused")], 50)
assert fp <= 1.25 * bp, f"fused p50 {fp:.3f}ms not within 1.25x of block {bp:.3f}ms"
assert fp >= bp / 4, f"fused p50 {fp:.3f}ms implausibly below block {bp:.3f}ms (loop elided?)"

# (2) exactly one measured dispatch per sweep point on a fixed budget
run("/tmp/ci-fused/count", "--op", "ring,exchange", "--sweep", "8,64,4K",
    "-i", "2", "-r", "6", "--fence", "fused")
assert len(rows_of("/tmp/ci-fused/count")) == 36
(ph,) = glob.glob("/tmp/ci-fused/count/phase-*.json")
with open(ph) as fh:
    fused = json.load(fh)["fused"]
assert fused["points"] == 6 and fused["measure_dispatches"] == 6, fused
assert fused["runs"] == 36 and fused["plan"] == [6], fused
print(f"fused fence: p50 {fp:.3f}ms vs block {bp:.3f}ms, "
      "6 points = 6 dispatches = 36 rows")
EOF
python - <<'EOF'
# (3) chunk-relayed adaptive stopping: a planted deterministic chunk
# series (the fused analogue of 0e's seeded Driver._measure) must
# early-stop under --ci-rel with rank-lockstep vote order — here the
# single-process vote path; the multi-rank lockstep is pinned by
# tests/test_timing_fused.py's simulated-rank vote harness.
import io, contextlib
import tpu_perf.timing as timing
from tpu_perf.config import Options
from tpu_perf.driver import Driver
from tpu_perf.parallel import make_mesh

counts = {}
def planted(self, reps):
    key = self.point.op
    n = counts[key] = counts.get(key, 0) + 1
    mean = 1e-3 * (1.0 + 0.002 * (n % 3))
    return [mean] * reps, 0.0, mean * reps
timing.FusedRunner.chunk = planted

mesh = make_mesh()
err = io.StringIO()
opts = Options(op="ring,exchange", sweep="8,4096", iters=1, num_runs=30,
               fence="fused", ci_rel=0.05, min_runs=5)
drv = Driver(opts, mesh, err=err)
rows = drv.run()
assert "bypassed" not in err.getvalue(), err.getvalue()
assert drv._fused_plan == (5,) * 6
by_point = {}
for r in rows:
    by_point.setdefault((r.op, r.nbytes), []).append(r)
assert len(by_point) == 4
for rows_ in by_point.values():
    final = max(rows_, key=lambda r: r.run_id)
    assert final.runs_requested == 30
    assert final.run_id < 30 and final.run_id % 5 == 0
    assert 0 < final.ci_rel <= 0.05, (final.op, final.ci_rel)
saved = drv.adaptive_totals["runs_saved"]
assert saved >= 4 * 10, drv.adaptive_totals
print(f"fused adaptive: {drv.adaptive_totals['runs_attempted']}/"
      f"{drv.adaptive_totals['runs_requested']} runs, chunk votes, no bypass")
EOF
# (4) the chaos ledger is byte-identical under --fence fused (synthetic
# sampling bypasses measurement, but the fence plumbing — fused builds,
# runner wiring, dispatch accounting — must not perturb the run
# sequence the ledger hashes)
python -m tpu_perf chaos --faults /tmp/ci-chaos/spec.json --seed 7 \
    --max-runs 400 --synthetic 0.001 --op ring --sweep 8,32 -i 1 \
    --stats-every 20 --health-warmup 20 --fence fused \
    -l /tmp/ci-fused/chaos >/dev/null 2>&1
diff <(cat /tmp/ci-chaos/a/chaos-*.log) <(cat /tmp/ci-fused/chaos/chaos-*.log)

# 0i. fleet observability gate (ISSUE 9): three synthesized host
#     folders — one planted slow host (3x the synthetic base latency),
#     one stale host (records backdated past --stale-after) — must be
#     NAMED: cross-host MAD grading flags host-c and exits 9, the
#     staleness gauge renders host-b, the stitched fleet timeline is
#     Perfetto-valid with complete joins on every host, and the
#     heartbeat-anchored clock alignment recovers a planted
#     inter-process skew exactly.
rm -rf /tmp/ci-fleet && mkdir -p /tmp/ci-fleet/root
for h in host-a:0.001 host-b:0.001 host-c:0.003; do
    n=${h%%:*}; s=${h##*:}
    python -m tpu_perf chaos --seed 7 --max-runs 60 --synthetic "$s" \
        --op ring --sweep 8,32 -i 1 --stats-every 20 --health-warmup 20 \
        --spans -l "/tmp/ci-fleet/root/$n" >/dev/null 2>&1
done
find /tmp/ci-fleet/root/host-b -type f -exec touch -d '3 hours ago' {} +
fleet_rc=0
python -m tpu_perf fleet report /tmp/ci-fleet/root \
    --textfile /tmp/ci-fleet/fleet.prom -o /tmp/ci-fleet/fleet.json \
    -l /tmp/ci-fleet/rollups \
    > /tmp/ci-fleet/report.md 2> /tmp/ci-fleet/report.err || fleet_rc=$?
test "$fleet_rc" -eq 9
grep -q '1 sick (host-c), 1 stale (host-b)' /tmp/ci-fleet/report.md
grep -q 'graded sick: host-c' /tmp/ci-fleet/report.err
grep -q 'tpu_perf_fleet_host_stale{host="host-b"} 1' /tmp/ci-fleet/fleet.prom
grep -q 'tpu_perf_fleet_host_sick{host="host-c"} 1' /tmp/ci-fleet/fleet.prom
# the seventh family landed and routes through the ingest pass
ls /tmp/ci-fleet/rollups/fleet-*.log >/dev/null
# a second report diffed against the first artifact is shift-free
# (same data), proving the baseline plumbing reads what -o wrote
python -m tpu_perf fleet report /tmp/ci-fleet/root \
    --baseline /tmp/ci-fleet/fleet.json > /tmp/ci-fleet/report2.md \
    2>/dev/null || true
grep -q '0 fleet-wide shift(s)' /tmp/ci-fleet/report2.md
# stitched timeline: Perfetto-valid, joins complete on all three hosts
python -m tpu_perf fleet timeline /tmp/ci-fleet/root --check \
    -o /tmp/ci-fleet/timeline.json 2> /tmp/ci-fleet/timeline.err
test "$(grep -c 'join complete' /tmp/ci-fleet/timeline.err)" -eq 3
python - <<'EOF'
import json
from tpu_perf.trace import validate_chrome_trace
data = json.load(open("/tmp/ci-fleet/timeline.json"))
assert validate_chrome_trace(data) == [], validate_chrome_trace(data)[:3]
procs = {e["args"]["name"] for e in data["traceEvents"]
         if e.get("ph") == "M" and e["name"] == "process_name"}
assert procs == {f"host-{h}/rank 0" for h in "abc"}, procs
assert any(e.get("cat") == "heartbeat" for e in data["traceEvents"])
print(f"fleet timeline: {len(data['traceEvents'])} events, 3 hosts")
EOF
python - <<'EOF'
# heartbeat-anchored clock alignment: a planted 5 ms inter-process skew
# must be recovered EXACTLY from the shared heartbeat boundaries, and
# the single-folder timeline CLI must land both ranks' barriers on one
# instant (the PR-6 carried bugfix: ranks launched seconds apart)
import contextlib, io, json, os
from tpu_perf.cli import main
from tpu_perf.fleet import clock_offsets

def rank_spans(job, rank, skew):
    out = []
    for i, (rid, barrier) in enumerate(((20, 10_000_000),
                                        (40, 20_000_000))):
        out.append({"record": "span", "job_id": job,
                    "span_id": f"r{i}", "parent_id": None, "rank": rank,
                    "thread": "main", "t_start_ns": barrier - 500_000 - skew,
                    "dur_ns": 400_000, "kind": "run",
                    "attrs": {"run_id": rid, "op": "ring", "nbytes": 32}})
        out.append({"record": "span", "job_id": job,
                    "span_id": f"m{i}", "parent_id": None, "rank": rank,
                    "thread": "main", "t_start_ns": barrier - 100_000 - skew,
                    "dur_ns": 100_000, "kind": "heartbeat",
                    "attrs": {"run_id": rid}})
    return out

folder = "/tmp/ci-fleet/skew"
os.makedirs(folder, exist_ok=True)
for rank, skew in ((0, 0), (1, 5_000_000)):
    with open(f"{folder}/spans-J-{rank}-20260801-000000.log", "w") as fh:
        for s in rank_spans("J", rank, skew):
            fh.write(json.dumps(s) + "\n")
spans = [json.loads(line)
         for p in sorted(os.listdir(folder))
         for line in open(os.path.join(folder, p))]
offs = clock_offsets(spans, err=io.StringIO())
assert offs == {("J", 0): 0, ("J", 1): 5_000_000}, offs
buf = io.StringIO()
with contextlib.redirect_stdout(buf):
    assert main(["timeline", folder]) == 0
data = json.loads(buf.getvalue())
ends = {e["pid"]: e["ts"] + e["dur"] for e in data["traceEvents"]
        if e.get("cat") == "heartbeat" and e["args"]["run_id"] == 20}
assert ends[0] == ends[1], ends
print("clock alignment: planted 5 ms skew recovered exactly")
EOF
# 0j. collective-algorithm arena gate (ISSUE 10): (1) every registered
#     (collective, algorithm) pair's step output equals the native
#     lowering on the seeded example inputs (movement bit-exact,
#     reductions within fp tolerance); (2) a real head-to-head arena
#     sweep under --fence fused covers >= 4 algorithms across 2
#     collectives at one dispatch per point, and `report` renders the
#     crossover table with a winner named at every size while the
#     clean compare pivot excludes every arena row; (3) arena rows
#     (20-field, algo column) round-trip through the rotating log and
#     the ingest pass's extended-family routing; (4) the chaos ledger
#     is byte-identical under the algo plumbing — 0b's exact soak with
#     --algo native spelled out reproduces 0b's ledger, and a seeded
#     arena soak reproduces its own ledger under --precompile.
JAX_PLATFORMS=cpu python -m pytest tests/test_arena.py -q
rm -rf /tmp/ci-arena && mkdir -p /tmp/ci-arena
python - <<'EOF'
# (1) numerics parity for ALL registered algorithms
import jax, numpy as np
from tpu_perf.arena import ARENA_ALGORITHMS
from tpu_perf.ops import build_op
from tpu_perf.parallel import make_mesh

mesh = make_mesh()
for (coll, algo) in sorted(ARENA_ALGORITHMS):
    native = build_op(coll, mesh, 256, 2)
    arena = build_op(coll, mesh, 256, 2, algo=algo)
    want = np.asarray(jax.block_until_ready(
        native.step(native.example_input)), dtype=np.float64)
    got = np.asarray(jax.block_until_ready(
        arena.step(arena.example_input)), dtype=np.float64)
    if coll == "all_gather":
        np.testing.assert_array_equal(got, want, err_msg=f"{coll}@{algo}")
    else:
        np.testing.assert_allclose(got, want, rtol=5e-6,
                                   err_msg=f"{coll}@{algo}")
print(f"arena parity: {len(ARENA_ALGORITHMS)} (collective, algorithm) "
      "pairs match the native lowering")
EOF
# (2) head-to-head sweep under the fused fence: one dispatch per
# (op, algo, size) point, audited from the phase sidecar
python -m tpu_perf arena --op allreduce,all_gather --sweep 8,4096 \
    -i 1 -r 4 --fence fused -l /tmp/ci-arena/run >/dev/null 2>&1
python -m tpu_perf report /tmp/ci-arena/run > /tmp/ci-arena/report.md
grep -q '### Arena crossover' /tmp/ci-arena/report.md
python - <<'EOF'
import glob, json
from tpu_perf.report import aggregate, compare, compare_arena, read_rows

rows = read_rows(sorted(glob.glob("/tmp/ci-arena/run/tpu-*.log")))
algos = {r.algo or "native" for r in rows}
assert {"native", "ring", "rhd", "bruck", "binomial"} <= algos, algos
assert {r.op for r in rows} == {"allreduce", "all_gather"}
points = aggregate(rows)
cross = compare_arena(points)
# a winner is NAMED at every (op, size) the arena measured (all_gather
# rounds the 8 B request up to one element per device: nbytes differs
# per op, so derive the expected keys from the rows themselves)
keys = {(c.op, c.nbytes) for c in cross}
assert keys == {(r.op, r.nbytes) for r in rows} and len(keys) == 4, keys
for c in cross:
    best_algo, best = c.best
    assert best_algo and best.lat_us["p50"] > 0, (c.op, c.nbytes)
    assert c.native_vs_best is not None and c.native_vs_best > 0
# the clean backend pivot never seats an arena row
for cmp in compare(points):
    assert cmp.jax is None or cmp.jax.algo == "native"
(ph,) = glob.glob("/tmp/ci-arena/run/phase-*.json")
fused = json.load(open(ph))["fused"]
assert fused["points"] == 18 and fused["measure_dispatches"] == 18, fused
print("arena sweep: 18 points = 18 dispatches, winner at every size, "
      f"native/best ratios: "
      f"{[round(c.native_vs_best, 2) for c in cross]}")
EOF
# (3) arena rows ride the ingest pass's extended-family routing
TPU_PERF_INGEST=local:/tmp/ci-arena/sink \
    python -m tpu_perf ingest -d /tmp/ci-arena/run -f 0 >/dev/null
python - <<'EOF'
import glob
from tpu_perf.report import read_rows
rows = read_rows(sorted(glob.glob("/tmp/ci-arena/sink/tpu-*.log")))
assert any(r.algo for r in rows), "algo column lost in ingest round-trip"
print(f"arena ingest: {len(rows)} rows round-tripped with algo intact")
EOF
# (4a) 0b's exact soak with --algo native spelled out: ledger bytes
# identical — the algo plumbing is provably inert for native jobs
python -m tpu_perf chaos --faults /tmp/ci-chaos/spec.json --seed 7 \
    --max-runs 400 --synthetic 0.001 --op ring --sweep 8,32 -i 1 \
    --stats-every 20 --health-warmup 20 --algo native \
    -l /tmp/ci-arena/native-chaos >/dev/null 2>&1
diff <(cat /tmp/ci-chaos/a/chaos-*.log) \
     <(cat /tmp/ci-arena/native-chaos/chaos-*.log)
# (4b) a seeded arena chaos soak reproduces its own ledger byte for
# byte under --precompile (the 0b a/b discipline, arena plan)
cat > /tmp/ci-arena/spec.json <<'EOF'
{"faults": [{"kind": "spike", "op": "allreduce", "nbytes": 32,
             "start": 10, "end": 30, "magnitude": 20.0}]}
EOF
extra=()
for d in a b; do
    python -m tpu_perf chaos --faults /tmp/ci-arena/spec.json --seed 7 \
        --max-runs 120 --synthetic 0.001 --op allreduce --algo all \
        --sweep 8,32 -i 1 --stats-every 20 --health-warmup 20 \
        "${extra[@]}" -l "/tmp/ci-arena/chaos-$d" >/dev/null 2>&1
    extra=(--precompile 4)
done
diff <(cat /tmp/ci-arena/chaos-a/chaos-*.log) \
     <(cat /tmp/ci-arena/chaos-b/chaos-*.log)

# 0k. arrival-skew gate (ISSUE 11): (1) a seeded skew soak reproduces a
#     byte-identical chaos ledger a/b (soak b pipelined, the 0b
#     discipline) and `chaos verify` catches 100% of planted skew
#     faults — attributed through the victim's rows — with zero false
#     alarms on the skew-free control; (2) the --skew-spread plumbing
#     is provably inert at spread 0 (0b's exact soak + --skew-spread 0
#     reproduces 0b's ledger byte for byte); (3) a skew-axis sweep on
#     the synthetic source renders the straggler-cost table with a
#     planted 1 ms skew showing > 1 slowdown, and its 21-field rows
#     round-trip rotate -> ingest twice: through the local-sink backend
#     (files survive byte-for-byte) and through the fake Kusto endpoint
#     (the 21-column PerfLogsTPU mapping types SkewUs; narrower rows
#     ingest with null trailers — tests/test_ingest.py -k skew);
#     (4) skew + --fence fused is a loud Options error; (5) an arena
#     sweep under --skew-spread verdicts the crossover per
#     (size, spread).
JAX_PLATFORMS=cpu python -m pytest tests/test_skew.py -q
rm -rf /tmp/ci-skew && mkdir -p /tmp/ci-skew
cat > /tmp/ci-skew/spec.json <<'EOF'
{"faults": [{"kind": "skew", "op": "ring", "nbytes": 32, "start": 60,
             "end": 400, "magnitude": 8000}]}
EOF
extra=()
for d in a b; do
    python -m tpu_perf chaos --faults /tmp/ci-skew/spec.json --seed 7 \
        --max-runs 400 --synthetic 0.001 --op ring --sweep 8,32 -i 1 \
        --stats-every 20 --health-warmup 20 "${extra[@]}" \
        -l "/tmp/ci-skew/$d" >/dev/null 2>&1
    extra=(--precompile 4)
done
diff <(cat /tmp/ci-skew/a/chaos-*.log) <(cat /tmp/ci-skew/b/chaos-*.log)
python -m tpu_perf chaos verify /tmp/ci-skew/a \
    | grep '1/1 fault(s) caught, 0 critical miss(es), 0 false alarm(s)'
# the skew-free control: the zero-false-alarm gate extended to skew
python -m tpu_perf chaos --seed 7 --max-runs 200 --synthetic 0.001 \
    --op ring --sweep 8,32 -i 1 --stats-every 20 --health-warmup 20 \
    -l /tmp/ci-skew/clean >/dev/null 2>&1
python -m tpu_perf chaos verify /tmp/ci-skew/clean --fail-on-false-alarm \
    | grep '0 false alarm(s) over 0 event(s)'
# (2) spread 0 is the synchronized plan: 0b's soak with --skew-spread 0
# spelled out reproduces 0b's ledger — the axis plumbing is inert
python -m tpu_perf chaos --faults /tmp/ci-chaos/spec.json --seed 7 \
    --max-runs 400 --synthetic 0.001 --op ring --sweep 8,32 -i 1 \
    --stats-every 20 --health-warmup 20 --skew-spread 0 \
    -l /tmp/ci-skew/zero >/dev/null 2>&1
diff <(cat /tmp/ci-chaos/a/chaos-*.log) <(cat /tmp/ci-skew/zero/chaos-*.log)
# (3) the straggler-cost table: planted 1 ms spread on the 1 ms
# synthetic base must price the straggler > 1x at these (small) sizes
python -m tpu_perf chaos --seed 7 --max-runs 240 --synthetic 0.001 \
    --op ring --sweep 8,32 -i 1 --stats-every 20 --health-warmup 20 \
    --skew-spread 0,1000 -l /tmp/ci-skew/axis >/dev/null 2>&1
python -m tpu_perf report /tmp/ci-skew/axis > /tmp/ci-skew/report.md
grep -q '### Straggler cost' /tmp/ci-skew/report.md
python - <<'EOF'
import glob
from tpu_perf.report import aggregate, compare, read_rows, straggler_cost

rows = read_rows(sorted(glob.glob("/tmp/ci-skew/axis/tpu-*.log")))
assert {r.skew_us for r in rows} == {0, 1000}, {r.skew_us for r in rows}
# zero-skew rows keep the pre-skew 18-field width byte-for-byte
assert all(len(r.to_csv().split(",")) == 18 for r in rows if not r.skew_us)
assert all(len(r.to_csv().split(",")) == 21 for r in rows if r.skew_us)
points = aggregate(rows)
st = straggler_cost(points)
assert len(st) == 2 and all(s.base is not None for s in st), st
assert all(s.slowdown is not None and s.slowdown > 1.0 for s in st), \
    [(s.op, s.nbytes, s.slowdown) for s in st]
# skewed points never seat a clean pivot slot
for cmp in compare(points):
    assert cmp.jax is None or cmp.jax.skew_us == 0
print("straggler cost: slowdowns",
      [round(s.slowdown, 3) for s in st], "at 1 ms spread")
EOF
TPU_PERF_INGEST=local:/tmp/ci-skew/sink \
    python -m tpu_perf ingest -d /tmp/ci-skew/axis -f 0 >/dev/null
python - <<'EOF'
import glob
from tpu_perf.report import read_rows
rows = read_rows(sorted(glob.glob("/tmp/ci-skew/sink/tpu-*.log")))
assert any(r.skew_us == 1000 for r in rows), \
    "skew_us column lost in ingest round-trip"
print(f"skew ingest: {len(rows)} rows round-tripped with skew_us intact")
EOF
# ...and through the fake Kusto endpoint: the 21st SkewUs column lands
# typed in PerfLogsTPU, narrower widths ingest with null trailers
JAX_PLATFORMS=cpu python -m pytest \
    tests/test_ingest.py::test_kusto_ingests_skew_rows_with_skew_column -q
# (4) skew + fused: loud Options error, never a silent no-op
rc=0; python -m tpu_perf run --op ring --fence fused -b 4K -i 1 -r 2 \
    --skew-spread 0,500 >/dev/null 2>/tmp/ci-skew/fused.err || rc=$?
test "$rc" -eq 2
grep -q 'fused' /tmp/ci-skew/fused.err
# (5) arena x skew: the crossover verdicts per (size, spread)
python -m tpu_perf arena --op allreduce --sweep 8 -i 1 -r 2 \
    --skew-spread 0,1000 -l /tmp/ci-skew/arena >/dev/null 2>&1
python -m tpu_perf report /tmp/ci-skew/arena > /tmp/ci-skew/arena.md
grep -q '| spread (us) |' /tmp/ci-skew/arena.md
python - <<'EOF'
import glob
from tpu_perf.report import aggregate, compare_arena, read_rows
rows = read_rows(sorted(glob.glob("/tmp/ci-skew/arena/tpu-*.log")))
cross = compare_arena(aggregate(rows))
spreads = {c.skew_us for c in cross}
assert spreads == {0, 1000}, spreads
for c in cross:
    assert c.best[0] and c.native_vs_best is not None, (c.op, c.skew_us)
print(f"arena x skew: {len(cross)} per-(size, spread) verdicts")
EOF

# 0l. live telemetry push plane gate (ISSUE 12): (1) 0b's exact chaos
#     soak with `--push` at a loopback NDJSON collector delivers EVERY
#     durable row / health event live with zero drops (per-family
#     routing = the Kusto table map), keeps the chaos ledger
#     byte-identical to 0b's push-off soak AND un-POSTed
#     (TEE_FREE_FAMILIES), while the streaming single-host report
#     renders markdown byte-identical to the buffered path plus the
#     "Push plane" counter table; (2) the same soak against a DEAD sink
#     dead-letters to push-*.spool.quarantined, triages + requeues
#     through the INGEST quarantine tooling, and `push replay` delivers
#     every spooled record to the revived collector (the genuinely
#     mid-soak kill — delivered-then-dead, injected clock — is pinned
#     by tests/test_push.py); (3) `fleet report --drain-hook` on 0i's
#     synthesized fleet invokes the hook EXACTLY ONCE per sick host
#     (argv + $TPU_PERF_SICK_HOST), ledgers the drain outcome in the
#     fleet-*.log rollup and the live --push tee, and rate-limits a
#     second pass; (4) the run-push-monitor.sh profile lands live push
#     gauges in its textfile.
JAX_PLATFORMS=cpu python -m pytest tests/test_push.py -q
rm -rf /tmp/ci-push && mkdir -p /tmp/ci-push/recv
cat > /tmp/ci-push/collector.py <<'EOF'
"""Loopback NDJSON collector: appends each POST body to
/tmp/ci-push/recv/<Table>.ndjson; port written atomically once bound."""
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

LOCK = threading.Lock()


class Handler(BaseHTTPRequestHandler):
    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n).decode()
        table = self.path.rstrip("/").split("/")[-1]
        with LOCK:
            with open(f"/tmp/ci-push/recv/{table}.ndjson", "a") as fh:
                fh.write(body if body.endswith("\n") else body + "\n")
        self.send_response(204)
        self.end_headers()

    def log_message(self, *a):
        pass


srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
with open("/tmp/ci-push/port.tmp", "w") as fh:
    fh.write(str(srv.server_address[1]))
os.replace("/tmp/ci-push/port.tmp", "/tmp/ci-push/port")
srv.serve_forever()
EOF
# stdio detached so the daemonized server can never hold CI's pipes open
python /tmp/ci-push/collector.py </dev/null >/dev/null 2>&1 &
PUSH_COLLECTOR_PID=$!
for _ in $(seq 50); do [ -s /tmp/ci-push/port ] && break; sleep 0.1; done
PUSH_PORT=$(cat /tmp/ci-push/port)
# (1) ledger byte-identity + zero-drop full-fidelity live delivery
python -m tpu_perf chaos --faults /tmp/ci-chaos/spec.json --seed 7 \
    --max-runs 400 --synthetic 0.001 --op ring --sweep 8,32 -i 1 \
    --stats-every 20 --health-warmup 20 --spans \
    --push "http://127.0.0.1:$PUSH_PORT" -l /tmp/ci-push/on >/dev/null 2>&1
diff <(cat /tmp/ci-chaos/a/chaos-*.log) <(cat /tmp/ci-push/on/chaos-*.log)
python - <<'EOF'
import glob, json, os

def durable(pat):
    return [ln for p in sorted(glob.glob(f"/tmp/ci-push/on/{pat}"))
            for ln in open(p).read().splitlines()]

def recv(table):
    path = f"/tmp/ci-push/recv/{table}.ndjson"
    return open(path).read().splitlines() if os.path.exists(path) else []

side, = glob.glob("/tmp/ci-push/on/phase-*.json")
push = json.load(open(side))["push"]
assert push["sent"] > 0 and push["dropped"] == 0, push
assert push["spool_depth"] == 0 and push["queued"] == 0, push
assert sorted(recv("PerfLogsTPU")) == sorted(durable("tpu-*.log")), \
    (len(recv("PerfLogsTPU")), len(durable("tpu-*.log")))
assert sorted(recv("PerfLogsMPI")) == sorted(durable("tcp-*.log"))
assert sorted(recv("HealthEventsTPU")) == sorted(durable("health-*.log"))
spans = recv("SpanEventsTPU")
assert spans and set(spans) <= set(durable("spans-*.log"))
assert any(json.loads(ln)["kind"] == "run" for ln in spans)
assert recv("ChaosEventsTPU") == []  # the ledger NEVER pushes
print(f"push soak: {push['sent']} records live, 0 dropped, "
      "ledger tee-free")
EOF
python -m tpu_perf report /tmp/ci-push/on > /tmp/ci-push/report.md
grep -q '### Push plane' /tmp/ci-push/report.md
python - <<'EOF'
import glob
from tpu_perf.report import (aggregate, read_rows, stream_aggregate,
                             to_markdown)
paths = sorted(glob.glob("/tmp/ci-push/on/tpu-*.log"))
buffered = to_markdown(aggregate(read_rows(paths)))
assert to_markdown(stream_aggregate(paths)) == buffered
print("streaming report: markdown byte-identical to the buffered path")
EOF
# (2) dead sink -> dead-letter spool -> ingest --requeue -> push replay
rm -f /tmp/ci-push/recv/*.ndjson
python -m tpu_perf chaos --seed 7 --max-runs 120 --synthetic 0.001 \
    --op ring --sweep 8,32 -i 1 --stats-every 20 --health-warmup 20 \
    --push "http://127.0.0.1:9" -l /tmp/ci-push/outage >/dev/null 2>&1
ls /tmp/ci-push/outage/push-*.spool.quarantined >/dev/null
cat /tmp/ci-push/outage/tpu-*.log > /tmp/ci-push/outage-rows.snapshot
python -m tpu_perf ingest -d /tmp/ci-push/outage --list-quarantined \
    > /tmp/ci-push/quarantined.log
grep -q 'push-tpu-' /tmp/ci-push/quarantined.log
TPU_PERF_INGEST=none python -m tpu_perf ingest -d /tmp/ci-push/outage \
    --requeue > /tmp/ci-push/requeue.log 2>&1
grep -q 'requeued 2 quarantined file(s)' /tmp/ci-push/requeue.log
ls /tmp/ci-push/outage/push-*.spool >/dev/null
python -m tpu_perf push replay /tmp/ci-push/outage \
    --url "http://127.0.0.1:$PUSH_PORT" > /tmp/ci-push/replay.log 2>&1
grep -q 'spool file(s) replayed' /tmp/ci-push/replay.log
python - <<'EOF'
import glob
got = sorted(open("/tmp/ci-push/recv/PerfLogsTPU.ndjson").read()
             .splitlines())
want = sorted(open("/tmp/ci-push/outage-rows.snapshot").read()
              .splitlines())
assert got == want, (len(got), len(want))
assert not glob.glob("/tmp/ci-push/outage/push-*")  # spool drained
print(f"spool -> requeue -> replay: {len(got)} rows recovered")
EOF
# (3) exit 9 ACTS: one drain per sick host, rate-limited on the repeat
# (gate 0i rebuilds the fleet root, but a partial ci.sh re-run must not
# inherit an armed rate limiter from a previous pass)
rm -f /tmp/ci-fleet/root/.drain-state.json
cat > /tmp/ci-push/drain.sh <<'EOF'
#!/bin/sh
echo "$1 ${TPU_PERF_SICK_HOST}" >> /tmp/ci-push/drained.txt
EOF
chmod +x /tmp/ci-push/drain.sh
rc=0; python -m tpu_perf fleet report /tmp/ci-fleet/root \
    --drain-hook /tmp/ci-push/drain.sh -l /tmp/ci-push/rollups \
    --push "http://127.0.0.1:$PUSH_PORT" \
    >/dev/null 2>/tmp/ci-push/drain.err || rc=$?
test "$rc" -eq 9
test "$(cat /tmp/ci-push/drained.txt)" = "host-c host-c"
grep -q 'drain hook invoked for host-c' /tmp/ci-push/drain.err
grep -q '"record": "drain"' /tmp/ci-push/rollups/fleet-*.log
grep -q '"record": "drain"' /tmp/ci-push/recv/FleetRollupTPU.ndjson
rc=0; python -m tpu_perf fleet report /tmp/ci-fleet/root \
    --drain-hook /tmp/ci-push/drain.sh \
    >/dev/null 2>/tmp/ci-push/drain2.err || rc=$?
test "$rc" -eq 9
test "$(wc -l < /tmp/ci-push/drained.txt)" -eq 1
grep -q 'rate-limited' /tmp/ci-push/drain2.err
# (4) the operator profile, live against the collector
LOGDIR=/tmp/ci-push/profile OPS=ring BUFF=4K ITERS=2 MAX_RUNS=6 WARMUP=3 \
    PUSH_URL="http://127.0.0.1:$PUSH_PORT" \
    PUSH_TEXTFILE=/tmp/ci-push/push.prom \
    bash scripts/run-push-monitor.sh >/dev/null 2>&1
grep -q 'tpu_perf_push_sent_total' /tmp/ci-push/push.prom
grep -q 'tpu_perf_push_dropped_total 0' /tmp/ci-push/push.prom
kill "$PUSH_COLLECTOR_PID" 2>/dev/null || true

# 0m. hierarchical multislice collectives gate (ISSUE 13): (1) numerics
#     parity for EVERY registered hier* (collective, base) pair against
#     the native flat lowering on a simulated 2x4 (dcn, ici) mesh, with
#     the resolved algo carrying the mesh-axis key; the legacy
#     hier_allreduce kernel must agree with allreduce@hier (same
#     construction, two spellings); (2) the bytes-per-axis accounting
#     identity: the model's DCN total is payload/n_slice for the
#     composition vs payload*(n-1)/n for the flat schedule; (3) a
#     head-to-head race on the mixed mesh renders the crossover table
#     WITH its mesh-shape column and the DCN traffic-model table, and
#     the clean backend pivot never seats a hier row; (4) the chaos
#     ledger is byte-identical a/b with hier algorithms in the plan
#     (soak b pipelined — the 0b discipline); (5) an explicit
#     --algo hier on a single-axis mesh degrades LOUDLY to the native
#     lowering (note on stderr, plain native rows); (6) the refreshed
#     run-multislice.sh profile is exercised live in 2d below.
JAX_PLATFORMS=cpu python -m pytest tests/test_hierarchy.py -q
rm -rf /tmp/ci-hier && mkdir -p /tmp/ci-hier
python - <<'EOF'
import jax, numpy as np
from tpu_perf.arena.hierarchy import HIER_ALGORITHMS
from tpu_perf.ops import build_op
from tpu_perf.parallel import make_mesh

mesh = make_mesh((2, 4), ("dcn", "ici"))
for (coll, base) in sorted(HIER_ALGORITHMS):
    native = build_op(coll, mesh, 260, 2)
    hier = build_op(coll, mesh, 260, 2, algo=base)
    want = np.asarray(jax.block_until_ready(
        native.step(native.example_input)), dtype=np.float64)
    got = np.asarray(jax.block_until_ready(
        hier.step(hier.example_input)), dtype=np.float64)
    if coll == "all_gather":
        np.testing.assert_array_equal(got, want, err_msg=f"{coll}@{base}")
    else:
        np.testing.assert_allclose(got, want, rtol=5e-6,
                                   err_msg=f"{coll}@{base}")
    assert hier.algo == f"{base}:dcn=2+ici=4", hier.algo
# the legacy 2-axis kernel is the same construction under its old name
legacy = build_op("hier_allreduce", mesh, 4096, 2)
modern = build_op("allreduce", mesh, 4096, 2, algo="hier")
np.testing.assert_allclose(
    np.asarray(jax.block_until_ready(legacy.step(legacy.example_input)),
               dtype=np.float64),
    np.asarray(jax.block_until_ready(modern.step(modern.example_input)),
               dtype=np.float64), rtol=5e-6)
print(f"hier parity: {len(HIER_ALGORITHMS)} (collective, base) pairs "
      "match the native flat lowering on 2x(4); hier_allreduce agrees "
      "with allreduce@hier")
EOF
# (2) the accounting identity, asserted: DCN total = payload/n_slice
# for the composition vs payload*(n-1)/n for the flat schedule
python - <<'EOF'
from tpu_perf.arena.hierarchy import (
    axis_bytes, dcn_bound_bytes, flat_dcn_bytes,
)

pairs = (("dcn", 2), ("ici", 4))
m, n, n_slice = 1 << 20, 8, 4
assert dcn_bound_bytes("allreduce", m, pairs) == m / n_slice
assert flat_dcn_bytes("allreduce", m, n) == m * (n - 1) / n
assert dcn_bound_bytes("allreduce", m, pairs) \
    < flat_dcn_bytes("allreduce", m, n)
per_axis = axis_bytes("allreduce", m, pairs)
# the per-phase wire model agrees with the composition: both ici
# phases move m(I-1)/I each, the dcn phase 2*(m/I)*(D-1)/D
assert per_axis["ici"] == 2 * m * 3 / 4
assert per_axis["dcn"] == 2 * (m / 4) * 1 / 2
print("bytes-per-axis identity: hier DCN total = payload/n_slice, "
      "flat = payload*(n-1)/n")
EOF
# (3) head-to-head race on the mixed mesh: mesh-shaped crossover +
# DCN traffic model rendered, clean pivots stay hier-free
python -m tpu_perf arena --mesh 2x4 --axes dcn,ici \
    --op allreduce,all_gather --sweep 8,4096 -i 1 -r 3 \
    -l /tmp/ci-hier/run >/dev/null 2>&1
python -m tpu_perf report /tmp/ci-hier/run > /tmp/ci-hier/report.md
grep -q '### Arena crossover' /tmp/ci-hier/report.md
grep -q '| mesh |' /tmp/ci-hier/report.md
grep -q '### Hierarchical DCN traffic model' /tmp/ci-hier/report.md
python - <<'EOF'
import glob
from tpu_perf.report import (
    aggregate, compare, compare_arena, hier_traffic, read_rows,
)

rows = read_rows(sorted(glob.glob("/tmp/ci-hier/run/tpu-*.log")))
algos = {r.algo or "native" for r in rows}
assert "native" in algos and "hier:dcn=2+ici=4" in algos, algos
assert "hier-ring:dcn=2+ici=4" in algos, algos
points = aggregate(rows)
cross = compare_arena(points)
assert cross and all(c.mesh == "2x(4)" for c in cross), \
    [(c.op, c.mesh) for c in cross]
for c in cross:
    assert c.best[0] and c.native_vs_best is not None, (c.op, c.nbytes)
model = hier_traffic(points)
assert model and all(m.dcn_reduction and m.dcn_reduction > 1
                     for m in model), \
    [(m.op, m.algo, m.dcn_reduction) for m in model]
assert all(m.native is not None and m.native_vs_hier for m in model)
for cmp in compare(points):
    assert cmp.jax is None or cmp.jax.algo == "native"
print(f"hier race: {len(cross)} mesh-shaped verdicts, "
      f"{len(model)} DCN-model rows, clean pivots hier-free")
EOF
# (4) chaos-ledger byte-identity with hier algorithms in the plan
# (soak b pipelined, the 0b a/b discipline)
cat > /tmp/ci-hier/spec.json <<'EOF'
{"faults": [{"kind": "spike", "op": "allreduce", "nbytes": 32,
             "start": 10, "end": 30, "magnitude": 20.0}]}
EOF
extra=()
for d in a b; do
    python -m tpu_perf chaos --faults /tmp/ci-hier/spec.json --seed 7 \
        --max-runs 120 --synthetic 0.001 --op allreduce \
        --algo hier,native --mesh 2x4 --axes dcn,ici --sweep 8,32 -i 1 \
        --stats-every 20 --health-warmup 20 "${extra[@]}" \
        -l "/tmp/ci-hier/chaos-$d" >/dev/null 2>&1
    extra=(--precompile 4)
done
diff <(cat /tmp/ci-hier/chaos-a/chaos-*.log) \
     <(cat /tmp/ci-hier/chaos-b/chaos-*.log)
# (5) single-axis degradation: explicit hier on a flat mesh runs the
# native lowering with a LOUD note, never a silent hier-labeled row
python -m tpu_perf run --op allreduce --algo hier -b 4K -i 1 -r 2 \
    --csv > /tmp/ci-hier/flat.csv 2> /tmp/ci-hier/flat.err
grep -q 'needs a 2-axis' /tmp/ci-hier/flat.err
grep -q 'native lowering in its place' /tmp/ci-hier/flat.err
python - <<'EOF'
from tpu_perf.report import read_rows
rows = read_rows(["/tmp/ci-hier/flat.csv"])
assert rows and all(not r.algo for r in rows), \
    [(r.op, r.algo) for r in rows[:3]]
print("single-axis hier: native fallback rows, loudly noted")
EOF

# 0n. model-step scenario engine gate (ISSUE 15): (1) the scenario /
#     v-variant test suite (numerics vs NumPy at ratios {1,2,8} on 1D
#     and 2D meshes, int32 bit-exact allgatherv, the lockstep proof,
#     spec/composition validation, the hier mixed-inner grammar);
#     (2) the acceptance sweep — allgatherv at --imbalance 1,2,8 —
#     lands 22-field rows that round-trip rotate -> ingest twice:
#     through the local sink (byte-for-byte) and through the fake
#     Kusto endpoint (the 22-column PerfLogsTPU mapping types
#     Imbalance; narrower rows ingest with null trailers); (3) the
#     moe-dispatch-combine scenario renders the Scenario-steps table
#     with per-phase attribution and the cost-vs-balanced column, and
#     the clean backend pivot never seats a scenario/imbalanced row;
#     (4) the chaos ledger is byte-identical a/b with scenarios (and
#     the imbalance axis) in the plan under --precompile 4 (the 0b
#     discipline).
JAX_PLATFORMS=cpu python -m pytest tests/test_scenarios.py -q
rm -rf /tmp/ci-scn && mkdir -p /tmp/ci-scn
# (2) the acceptance sweep + both ingest round trips
python -m tpu_perf run --op allgatherv --imbalance 1,2,8 --sweep 4K \
    -i 2 -r 3 -l /tmp/ci-scn/vrun >/dev/null 2>&1
TPU_PERF_INGEST=local:/tmp/ci-scn/sink \
    python -m tpu_perf ingest -d /tmp/ci-scn/vrun -f 0 >/dev/null
python - <<'EOF'
import glob
from tpu_perf.report import read_rows

rows = read_rows(sorted(glob.glob("/tmp/ci-scn/sink/tpu-*.log")))
ratios = {r.imbalance for r in rows}
assert ratios == {1, 2, 8}, ratios
assert all(len(r.to_csv().split(",")) == 22
           for r in rows if r.imbalance > 1)
assert all(len(r.to_csv().split(",")) == 18
           for r in rows if r.imbalance == 1)
print(f"imbalance ingest: {len(rows)} rows round-tripped with the "
      f"trailing column intact, ratios {sorted(ratios)}")
EOF
JAX_PLATFORMS=cpu python -m pytest \
    tests/test_ingest.py::test_kusto_ingests_imbalance_rows_with_imbalance_column -q
# (3) the moe scenario: attribution + cost + clean-pivot exclusion
python -m tpu_perf scenario moe-dispatch-combine --imbalance 1,8 \
    --sweep 4K -i 2 -r 3 --precompile 2 -l /tmp/ci-scn/moe >/dev/null 2>&1
python -m tpu_perf report /tmp/ci-scn/moe > /tmp/ci-scn/report.md
grep -q '### Scenario steps' /tmp/ci-scn/report.md
grep -q 'all_to_all_v 50%' /tmp/ci-scn/report.md
grep -q 'scenario\[moe-dispatch-combine\]%8' /tmp/ci-scn/report.md
python - <<'EOF'
import glob
from tpu_perf.report import aggregate, compare, read_rows, scenario_steps

rows = read_rows(sorted(glob.glob("/tmp/ci-scn/moe/tpu-*.log")))
points = aggregate(rows)
steps = scenario_steps(points)
assert {s.imbalance for s in steps} == {1, 8}, steps
imb = [s for s in steps if s.imbalance == 8][0]
assert imb.cost is not None and imb.phases and len(imb.phases) == 2
assert not compare(points), "scenario rows must never seat a clean pivot"
print(f"moe scenario: cost {imb.cost:.3f} vs balanced at ratio 8, "
      "attribution rendered, clean pivots empty")
EOF
# (4) chaos-ledger byte-identity with scenarios in the plan (soak b
# pipelined — the 0b discipline)
cat > /tmp/ci-scn/spec.json <<'EOF'
{"faults": [{"kind": "spike", "op": "scenario", "nbytes": 0,
             "start": 10, "end": 30, "magnitude": 20.0}]}
EOF
extra=()
for d in a b; do
    python -m tpu_perf chaos --faults /tmp/ci-scn/spec.json --seed 7 \
        --max-runs 120 --synthetic 0.001 \
        --scenario moe-dispatch-combine,pipeline-chain --imbalance 1,8 \
        -b 4K -i 1 --stats-every 20 --health-warmup 20 "${extra[@]}" \
        -l "/tmp/ci-scn/chaos-$d" >/dev/null 2>&1
    extra=(--precompile 4)
done
diff <(cat /tmp/ci-scn/chaos-a/chaos-*.log) \
     <(cat /tmp/ci-scn/chaos-b/chaos-*.log)
# ...and the identity is not vacuous: the planted fault really fired
# against a scenario point
grep -q '"op": "scenario", "record": "fault"' /tmp/ci-scn/chaos-a/chaos-*.log

# 0o. async dispatch + contention gate (ISSUE 17): (1) the streams
#     test suite (engine lockstep, per-stream span lanes, canon
#     refcounting under K lanes, split-channel numerics parity);
#     (2) an overlapped sweep (--streams 4) lands the same row SET as
#     the serial spelling — rows ride lanes 1..4, the sidecar's
#     streams block proves real overlap (window_s > wall_s) and the
#     overlapped measure wall stays within 1.15x of serial (plus a
#     small absolute slack: CPU walls here are milliseconds);
#     (3) --streams changes NOTHING about a chaos ledger — the driver
#     bypasses overlap under injection, loudly, and a/b ledgers stay
#     byte-identical; (4) the synthetic contend round-trip: the loaded
#     twins slow down by the seeded contention constant while the
#     no-load control sits at the nominal synthetic latency.
JAX_PLATFORMS=cpu python -m pytest tests/test_streams.py -q
rm -rf /tmp/ci-str && mkdir -p /tmp/ci-str
# (2) overlapped row-set identity + the sidecar overlap proof
python -m tpu_perf run --op allreduce,ppermute --sweep 8K,64K -i 2 \
    -r 10 -l /tmp/ci-str/serial >/dev/null 2>&1
python -m tpu_perf run --op allreduce,ppermute --sweep 8K,64K -i 2 \
    -r 10 --streams 4 -l /tmp/ci-str/lanes >/dev/null 2>&1
python - <<'EOF'
import glob, json
from tpu_perf.report import read_rows

def load(d):
    return read_rows(sorted(glob.glob(f"/tmp/ci-str/{d}/tpu-*.log")))

def keys(rows):
    return {(r.op, r.nbytes, r.run_id) for r in rows}

serial, lanes = load("serial"), load("lanes")
assert keys(serial) == keys(lanes), \
    (len(keys(serial)), len(keys(lanes)))
assert {r.stream for r in serial} == {0}
streams = {r.stream for r in lanes}
assert streams <= {1, 2, 3, 4} and max(streams) > 1, streams

def sidecar(d):
    [p] = glob.glob(f"/tmp/ci-str/{d}/phase-*.json")
    return json.load(open(p))

blk = sidecar("lanes")["streams"]
assert blk["k"] == 4 and blk["waves"] >= 1, blk
assert blk["window_s"] > blk["wall_s"] > 0, blk
serial_s = sidecar("serial")["phase"]["measure_s"]
lanes_s = sidecar("lanes")["phase"]["measure_s"]
assert lanes_s <= 1.15 * serial_s + 0.05, (lanes_s, serial_s)
print(f"overlapped sweep: {len(lanes)} rows identical to serial set, "
      f"lanes {sorted(streams)}, window {blk['window_s']:.4f}s > wall "
      f"{blk['wall_s']:.4f}s, measure {lanes_s:.3f}s vs {serial_s:.3f}s")
EOF
# (3) chaos-ledger a/b byte-identity with --streams in the plan
cat > /tmp/ci-str/spec.json <<'EOF'
{"faults": [{"kind": "spike", "op": "allreduce", "nbytes": 0,
             "start": 10, "end": 30, "magnitude": 20.0}]}
EOF
extra=()
for d in a b; do
    python -m tpu_perf chaos --faults /tmp/ci-str/spec.json --seed 11 \
        --max-runs 100 --synthetic 0.001 -b 4K -i 1 --stats-every 20 \
        --health-warmup 20 "${extra[@]}" -l "/tmp/ci-str/chaos-$d" \
        >/dev/null 2>"/tmp/ci-str/chaos-$d.err"
    extra=(--streams 4)
done
diff <(cat /tmp/ci-str/chaos-a/chaos-*.log) \
     <(cat /tmp/ci-str/chaos-b/chaos-*.log)
# ...and the bypass was loud, not silent
grep -q 'overlapped dispatch (--streams) bypassed' /tmp/ci-str/chaos-b.err
# (4) the synthetic contend round-trip: planted slowdown + idle control
python -m tpu_perf contend --op allreduce --load hbm_stream \
    --synthetic 0.001 --mesh 8 -b 32K -i 10 -r 12 --seed 7 \
    -l /tmp/ci-str/contend >/dev/null 2>&1
python - <<'EOF'
import glob
from tpu_perf.report import aggregate, interference_matrix, read_rows

rows = read_rows(sorted(glob.glob("/tmp/ci-str/contend/tpu-*.log")))
[cell] = interference_matrix(aggregate(rows))
assert cell.load == "hbm_stream" and cell.idle is not None
# seeded jitter around streams.contend.SYNTHETIC_CONTENTION (1.6)
assert cell.slowdown is not None and 1.4 <= cell.slowdown <= 1.8, \
    cell.slowdown
# the no-load control: idle p50 at the nominal synthetic per-iter
# latency (0.001 s / 10 iters = 100 us), ratio ~1.0
idle_ratio = cell.idle.lat_us["p50"] / 100.0
assert 0.8 <= idle_ratio <= 1.2, idle_ratio
print(f"contend synthetic: slowdown {cell.slowdown:.3g}x under load, "
      f"idle control ratio {idle_ratio:.3g}")
EOF

# 0p. crossover auto-tuner gate (ISSUE 19): (1) the tuner test suite
#     (artifact round-trips, the LOUD fallback ladder, two-rank
#     lockstep resolution, drift grading, fleet winner rollup);
#     (2) the closed loop on a real CPU arena soak: `tune` folds the
#     verdicts into the selection artifact, an `--algo auto` replay
#     must land EXACTLY the algorithm the artifact resolves per size;
#     (3) the eighth family: `tune -l` rotates tune-*.log and one
#     ingest pass sweeps it into the sink (fingerprint + entries);
#     (4) the drift gate: the honest artifact re-checks clean (exit 0),
#     a planted regression — winner and runner-up swapped in the
#     published artifact — exits 10 and names the flip; (5) --algo auto
#     changes NOTHING about a chaos ledger: a/b seeded soaks (native
#     vs auto) stay byte-identical.
JAX_PLATFORMS=cpu python -m pytest tests/test_tuner.py -q
rm -rf /tmp/ci-tune && mkdir -p /tmp/ci-tune
# (2) measure -> select -> steer
python -m tpu_perf run --op allreduce --algo all --sweep 256,4096 \
    -i 2 -r 8 -l /tmp/ci-tune/arena >/dev/null 2>&1
python -m tpu_perf tune -d /tmp/ci-tune/arena \
    -o /tmp/ci-tune/selection.json -l /tmp/ci-tune/arena >/dev/null
python -m tpu_perf run --op allreduce --algo auto \
    --algo-artifact /tmp/ci-tune/selection.json --sweep 256,4096 \
    -i 2 -r 4 -l /tmp/ci-tune/auto >/dev/null 2>&1
python - <<'EOF'
import glob, io
from tpu_perf.report import read_rows
from tpu_perf.tuner import load_artifact, read_artifact

art = read_artifact("/tmp/ci-tune/selection.json")
assert art.entries and art.fingerprint["n_devices"] == 8, art.fingerprint
sel = load_artifact("/tmp/ci-tune/selection.json", n_devices=8,
                    err=io.StringIO())
rows = read_rows(sorted(glob.glob("/tmp/ci-tune/auto/tpu-*.log")))
by_size = {}
for r in rows:
    by_size.setdefault(r.nbytes, set()).add(r.algo or "native")
assert set(by_size) == {256, 4096}, sorted(by_size)
for nb, algos in sorted(by_size.items()):
    want = sel.resolve("allreduce", nb, "float32", n_devices=8,
                       margin_min=1.02, err=io.StringIO())
    assert algos == {want}, (nb, algos, want)
print("auto plan matches artifact: " + ", ".join(
    f"{nb} -> {next(iter(a))}" for nb, a in sorted(by_size.items())))
EOF
# (3) eighth-family rotate -> ingest round-trip
python - <<'EOF'
import glob, json
from tpu_perf.ingest.pipeline import LocalDirBackend, run_all_ingest_passes

assert glob.glob("/tmp/ci-tune/arena/tune-*.log"), "tune -l wrote no log"
run_all_ingest_passes("/tmp/ci-tune/arena", skip_newest=10,
                      backend=LocalDirBackend("/tmp/ci-tune/sink"))
[sunk] = glob.glob("/tmp/ci-tune/sink/tune-*.log")
recs = [json.loads(l) for l in open(sunk)]
kinds = {r["record"] for r in recs}
assert kinds == {"tune_fingerprint", "tune_entry"}, kinds
assert not glob.glob("/tmp/ci-tune/arena/tune-*.log")  # swept, deleted
print(f"tune family ingested: {len(recs)} records")
EOF
# (4) drift gate: honest artifact clean, planted regression exits 10
python -m tpu_perf tune -d /tmp/ci-tune/arena \
    --check /tmp/ci-tune/selection.json >/dev/null
python - <<'EOF'
import json

doc = json.load(open("/tmp/ci-tune/selection.json"))
flipped = [e for e in doc["entries"] if e["runner_up"]]
assert flipped, "arena soak produced no two-sided verdict to flip"
for e in flipped:
    e["winner"], e["runner_up"] = e["runner_up"], e["winner"]
json.dump(doc, open("/tmp/ci-tune/doctored.json", "w"))
EOF
rc=0; python -m tpu_perf tune -d /tmp/ci-tune/arena \
    --check /tmp/ci-tune/doctored.json 2> /tmp/ci-tune/drift.out || rc=$?
[[ $rc -eq 10 ]] || { echo "planted regression: expected exit 10, got $rc" >&2; exit 1; }
grep -q 'crossover drift' /tmp/ci-tune/drift.out
# (5) chaos-ledger a/b byte-identity with --algo auto in the plan
cat > /tmp/ci-tune/spec.json <<'EOF'
{"faults": [{"kind": "spike", "op": "allreduce", "nbytes": 0,
             "start": 10, "end": 30, "magnitude": 20.0}]}
EOF
extra=()
for d in a b; do
    python -m tpu_perf chaos --faults /tmp/ci-tune/spec.json --seed 23 \
        --max-runs 80 --synthetic 0.001 -b 4K -i 1 --stats-every 20 \
        --health-warmup 20 "${extra[@]}" -l "/tmp/ci-tune/chaos-$d" \
        >/dev/null 2>&1
    extra=(--algo auto --algo-artifact /tmp/ci-tune/selection.json)
done
diff <(cat /tmp/ci-tune/chaos-a/chaos-*.log) \
     <(cat /tmp/ci-tune/chaos-b/chaos-*.log)

# 0q. irregular-payload schedules gate (ISSUE 20): (1) the v-opt test
#     suite (NumPy parity for every registered (v-op, algo) pair at
#     ratios {1,2,8} on 1D and 2D meshes, int32 bit-exactness for the
#     movement schedules, the lockstep proof, the wire models, the
#     algo-aware Imbalance-cost table, the tuner round trip);
#     (2) an imbalanced arena sweep — allgatherv --algo all at
#     --imbalance 1,8 — renders the best-algo Imbalance-cost column
#     while the clean pivots stay v-free; (3) the closed loop on the
#     imbalance axis: sweep -> tune -> --algo auto resolves the
#     IMBALANCED coordinate to the artifact's winner; (4) the chaos
#     ledger is byte-identical a/b with the optimized v-schedules in
#     the plan under --precompile 4 (the 0b discipline); (5) the
#     all_to_all_v / seg_allreduce wire-bytes identities.
JAX_PLATFORMS=cpu python -m pytest tests/test_vopt.py -q
rm -rf /tmp/ci-vopt && mkdir -p /tmp/ci-vopt
# (2) imbalanced arena race -> algo-aware Imbalance cost
python -m tpu_perf run --op allgatherv --algo all --sweep 4096 \
    --imbalance 1,8 -i 2 -r 4 -l /tmp/ci-vopt/arena >/dev/null 2>&1
python -m tpu_perf report /tmp/ci-vopt/arena > /tmp/ci-vopt/report.md
grep -q '### Imbalance cost' /tmp/ci-vopt/report.md
grep -q '| best algo | best/naive |' /tmp/ci-vopt/report.md
python - <<'EOF'
import glob
from tpu_perf.report import (aggregate, compare, compare_arena,
                             imbalance_cost, read_rows)

rows = read_rows(sorted(glob.glob("/tmp/ci-vopt/arena/tpu-*.log")))
assert {r.algo or "native" for r in rows} == \
    {"native", "sortring", "doubling"}
points = aggregate(rows)
cmp = imbalance_cost(points)
assert cmp and all(c.raced == 3 and c.best_algo for c in cmp), cmp
# imbalance is a crossover DIMENSION: each ratio verdicts its own
# slot; the clean backend pivot seats ONLY the balanced native row —
# never an imbalanced or v-algo point
cross = compare_arena(points)
assert {c.imbalance for c in cross} == {1, 8}, cross
clean = compare(points)
assert all(c.jax.imbalance == 1 and c.jax.algo == "native"
           for c in clean), clean
print(f"imbalance cost algo-aware: {len(cmp)} rows, best "
      f"{cmp[0].best_algo} at {cmp[0].best_vs_native:.3g}x native")
EOF
# (3) the closed loop lands the imbalanced coordinate's winner
python -m tpu_perf tune -d /tmp/ci-vopt/arena \
    -o /tmp/ci-vopt/selection.json >/dev/null
python -m tpu_perf run --op allgatherv --algo auto \
    --algo-artifact /tmp/ci-vopt/selection.json --sweep 4096 \
    --imbalance 8 -i 2 -r 2 -l /tmp/ci-vopt/auto >/dev/null 2>&1
python - <<'EOF'
import glob, io
from tpu_perf.report import read_rows
from tpu_perf.tuner import load_artifact

sel = load_artifact("/tmp/ci-vopt/selection.json", n_devices=8,
                    err=io.StringIO())
want = sel.resolve("allgatherv", 4140, "float32", imbalance=8,
                   n_devices=8, margin_min=1.02, err=io.StringIO())
rows = read_rows(sorted(glob.glob("/tmp/ci-vopt/auto/tpu-*.log")))
got = {(r.imbalance, r.algo or "native") for r in rows}
assert got == {(8, want)}, (got, want)
print(f"auto resolved the imbalanced coordinate: allgatherv%8 -> {want}")
EOF
# (4) chaos-ledger byte-identity with v-schedules in the plan
cat > /tmp/ci-vopt/spec.json <<'EOF'
{"faults": [{"kind": "spike", "op": "allgatherv", "nbytes": 0,
             "start": 10, "end": 30, "magnitude": 20.0}]}
EOF
extra=()
for d in a b; do
    python -m tpu_perf chaos --faults /tmp/ci-vopt/spec.json --seed 31 \
        --max-runs 100 --synthetic 0.001 --op allgatherv \
        --algo sortring,doubling --imbalance 1,8 \
        -b 4K -i 1 --stats-every 20 --health-warmup 20 "${extra[@]}" \
        -l "/tmp/ci-vopt/chaos-$d" >/dev/null 2>&1
    extra=(--precompile 4)
done
diff <(cat /tmp/ci-vopt/chaos-a/chaos-*.log) \
     <(cat /tmp/ci-vopt/chaos-b/chaos-*.log)
grep -q '"op": "allgatherv", "record": "fault"' /tmp/ci-vopt/chaos-a/chaos-*.log
# (5) wire-bytes identities for the promoted ops
python - <<'EOF'
from tpu_perf.arena import valgos
from tpu_perf.metrics import imbalance_volume_scale
from tpu_perf.scenarios.vops import v_counts

n = 8
blocks, _, elems, _ = v_counts("all_to_all_v", 4 * 64, n, 4, 8)
# native ships n-1 blocks per source; the dense slot matrix is only
# (n-1+ratio)/(n*ratio) occupied — the busbw correction's identity
assert valgos.a2av_wire_elems("native", blocks) == (n - 1) * sum(blocks)
assert sum(blocks) == elems * imbalance_volume_scale("all_to_all_v", 8, n)
assert valgos.a2av_wire_elems("ring", blocks) == \
    sum(blocks) * n * (n - 1) // 2
counts, _, elems, _ = v_counts("seg_allreduce", 4 * 64, n, 4, 8)
w = sum(counts)
# density: ratio 8 on 8 devices selects exactly one of n segments
assert w == elems * imbalance_volume_scale("seg_allreduce", 8, n)
assert valgos.seg_wire_elems("binomial", w, n) == 2 * (n - 1) * w
assert valgos.seg_wire_elems("bruck", w, n) == n * w * 7
print("wire-bytes identities hold: all_to_all_v + seg_allreduce")
EOF

unset XLA_FLAGS

# 1. test suite on 8 virtual CPU devices (conftest.py claims them)
python -m pytest tests/ -q

# 2. native backend: pthread-shim build + ASan/UBSan build + smoke over
#    all three pairwise kernels and a collective
make -C backends/mpi shim
make -C backends/mpi asan
printf 'shimhost1\n' > /tmp/ci-group1
./backends/mpi/mpi_perf_shim -np 2 -- -f /tmp/ci-group1 -i 50 -b 65536 -r 2
./backends/mpi/mpi_perf_asan -np 2 -- -f /tmp/ci-group1 -i 50 -b 65536 -r 2
./backends/mpi/mpi_perf_asan -np 2 -- -f /tmp/ci-group1 -i 600 -b 4096 -r 2 -x
./backends/mpi/mpi_perf_asan -np 2 -- -f /tmp/ci-group1 -i 50 -b 65536 -r 2 -u
./backends/mpi/mpi_perf_asan -np 4 -- -o allreduce -b 65536 -i 5 -r 2

# 2a. reference-binary interop (round 4, VERDICT r3 #5): compile the
#     UNMODIFIED reference driver against the process-per-rank shim and
#     prove its rows flow through report --legacy (full row-level
#     assertions live in tests/test_refbinary.py, run in step 1)
if [ -f /root/reference/mpi_perf.c ]; then
    make -C backends/mpi procshim ref
    rm -rf /tmp/ci-ref && mkdir -p /tmp/ci-ref
    printf '127.0.3.1\n' > /tmp/ci-ref-group1
    ./backends/mpi/shim_mpirun -np 2 -p 1 -- ./backends/mpi/ref_mpi_perf \
        -f /tmp/ci-ref-group1 -n 1 -p 1 -i 5 -b 65536 -r 3 -l /tmp/ci-ref
    PYTHONPATH= JAX_PLATFORMS=cpu \
        python -m tpu_perf report /tmp/ci-ref --legacy | grep "| 64K |" >/dev/null
fi

# 2a'. this repo's OWN C driver as real processes under the same shim
#      (the pthread build shares one address space; production mpirun
#      does not — this config catches shared-state assumptions)
make -C backends/mpi procshim proc
rm -rf /tmp/ci-proc && mkdir -p /tmp/ci-proc
printf '127.0.3.1\n' > /tmp/ci-proc-group1
./backends/mpi/shim_mpirun -np 2 -p 1 -- ./backends/mpi/mpi_perf_proc \
    -f /tmp/ci-proc-group1 -i 20 -b 65536 -r 3 -l /tmp/ci-proc
./backends/mpi/shim_mpirun -np 4 -p 1 -- ./backends/mpi/mpi_perf_proc \
    -o allreduce -b 65536 -i 10 -r 2 -l /tmp/ci-proc
PYTHONPATH= JAX_PLATFORMS=cpu \
    python -m tpu_perf report /tmp/ci-proc | grep "| allreduce |" >/dev/null

# 2b. the one-CLI-over-both-backends path (round 3): a backend=mpi run
#     through the launcher, paired against a jax run by report --compare
rm -rf /tmp/ci-both && mkdir -p /tmp/ci-both
TPU_PERF_INGEST_CMD=true JAX_PLATFORMS=cpu PYTHONPATH= \
    python -m tpu_perf run --backend mpi --op exchange -b 64K -i 40 -r 2 \
    -l /tmp/ci-both
PYTHONPATH= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m tpu_perf run --backend jax --op exchange -b 64K -i 10 -r 2 \
    -l /tmp/ci-both
PYTHONPATH= JAX_PLATFORMS=cpu \
    python -m tpu_perf report /tmp/ci-both --compare | grep "| exchange |" >/dev/null

# 2c. the regression gate (round 3): a folder diffed against its own
#     rendered artifact is all-ok (exit 0); a subset run missing base
#     points fails strict (exit 3) and passes with --diff-ignore-missing
PYTHONPATH= JAX_PLATFORMS=cpu \
    python -m tpu_perf report /tmp/ci-both --format json > /tmp/ci-both.json
PYTHONPATH= JAX_PLATFORMS=cpu \
    python -m tpu_perf report /tmp/ci-both --diff /tmp/ci-both.json | grep "| ok |" >/dev/null
rm -rf /tmp/ci-sub && mkdir -p /tmp/ci-sub
PYTHONPATH= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m tpu_perf run --backend jax --op exchange -b 32K -i 10 -r 2 \
    -l /tmp/ci-sub
rc=0; PYTHONPATH= JAX_PLATFORMS=cpu \
    python -m tpu_perf report /tmp/ci-sub --diff /tmp/ci-both.json \
    >/dev/null 2>&1 || rc=$?
test "$rc" -eq 3
PYTHONPATH= JAX_PLATFORMS=cpu \
    python -m tpu_perf report /tmp/ci-sub --diff /tmp/ci-both.json \
    --diff-ignore-missing >/dev/null

# 2d. every locally runnable profile script, LIVE on the 8-device virtual
#     mesh (round 4, VERDICT r3 #4: rendered-line pinning does not catch
#     flag/env rot — the scripts are the operator surface).  Tiny
#     ITERS/RUNS/SWEEP overrides; rows land in one folder and report must
#     see every op.  The run-mpi-{1-pair,ib,t4,monitor} profiles need real
#     cluster hosts + mpirun and stay covered by their DRY_RUN pin tests.
export PYTHONPATH= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8
rm -rf /tmp/ci-profiles && mkdir -p /tmp/ci-profiles
LOGDIR=/tmp/ci-profiles SWEEP=4K ITERS=2 RUNS=2 \
    bash scripts/run-ici-latency.sh >/dev/null
LOGDIR=/tmp/ci-profiles SWEEP=4K ITERS=2 RUNS=2 \
    bash scripts/run-ici-allreduce.sh >/dev/null
LOGDIR=/tmp/ci-profiles SWEEP=4K ITERS=2 RUNS=2 \
    bash scripts/run-ici-collectives.sh >/dev/null
LOGDIR=/tmp/ci-profiles MSGS=8 WINDOW=4 RUNS=2 BUFF=4K \
    bash scripts/run-ici-pair.sh >/dev/null
LOGDIR=/tmp/ci-profiles SWEEP=4K ITERS=1 RUNS=1 \
    bash scripts/run-ici-pallas.sh >/dev/null
SLICES=2 SWEEP=4K ITERS=2 RUNS=2 \
    bash scripts/run-multislice.sh -l /tmp/ci-profiles >/dev/null
# the multislice profile races the hierarchical arena against the flat
# native lowering on the (dcn, ici) mesh — the decorated mesh-keyed
# labels must land in the report next to the plain single-axis rows
python -m tpu_perf report /tmp/ci-profiles \
    | grep 'allreduce\[hier:dcn=2+ici=4\]' >/dev/null
# the monitoring daemon: runs until the timeout kills it (exit 124),
# must have written + rotated logs by then
rc=0; LOGDIR=/tmp/ci-profiles OPS=ring BUFF=4K ITERS=2 \
    timeout 8 bash scripts/run-ici-monitor.sh >/dev/null 2>&1 || rc=$?
test "$rc" -eq 124
ls /tmp/ci-profiles/tcp-*.log >/dev/null  # legacy rows landed too
# the health-monitoring profile: --max-runs bounds the daemon (no timeout
# kill needed) and the exporter textfile must hold the point's gauges by
# exit; a clean run emits no events, so no health-*.log is asserted
LOGDIR=/tmp/ci-profiles OPS=ring BUFF=4K ITERS=2 MAX_RUNS=6 WARMUP=3 \
    TEXTFILE=/tmp/ci-profiles/tpu-perf.prom \
    bash scripts/run-ici-health.sh >/dev/null 2>&1
grep -q 'tpu_perf_health_lat_p50_us{op=' /tmp/ci-profiles/tpu-perf.prom
# phase gauges ride the same textfile (ISSUE 5 satellite / ROADMAP PR-4
# follow-on): harness overhead is alertable next to the health gauges
grep -q 'tpu_perf_harness_phase_seconds{phase="compile"}' \
    /tmp/ci-profiles/tpu-perf.prom
# the link-map profile, LIVE probes on the virtual mesh: the operator
# surface only — CPU timing noise is not under test, so the grading
# thresholds are parked out of reach and the roofline disabled
LOGDIR=/tmp/ci-profiles MESH=2x4 BUFF=4K ITERS=1 RUNS=1 ROOFLINE=0 \
    bash scripts/run-ici-linkmap.sh --mad-z 1e9 --rel-threshold 1e6 \
    --dead-ratio 1e9 >/dev/null
ls /tmp/ci-profiles/linkmap-*.log >/dev/null
# the C-collective profile's no-MPI shim fallback path
LOGDIR=/tmp/ci-profiles NP=4 OP=allreduce BUF=65536 ITERS=5 RUNS=2 \
    bash scripts/run-mpi-collective.sh >/dev/null 2>&1
for op in pingpong allreduce broadcast all_gather reduce_scatter \
          all_to_all ring halo exchange pl_ring \
          pl_allreduce pl_hbm_read; do
    python -m tpu_perf report /tmp/ci-profiles | grep "| $op |" >/dev/null \
        || { echo "profile rows missing op: $op" >&2; exit 1; }
done

# 2e. the REAL multi-device bench path (round 5, VERDICT r4 weak #1):
#     bench.main() unmocked on the 8-device virtual mesh — the n>=2
#     allreduce headline that fires the day multichip hardware appears.
#     The fence probe finds no device lanes and goes straight to slope;
#     the JSON line must parse and carry the 8-device metric.
python - <<'EOF'
import json, subprocess, sys
out = subprocess.run([sys.executable, "bench.py"], check=True,
                     capture_output=True, text=True).stdout
data = json.loads(out.strip().splitlines()[-1])
assert data["metric"] == "allreduce_busbw_p50@4MiB[8dev]", data["metric"]
assert data["value"] > 0 and data["metrics"][0]["fence"] == "slope", data
print("unmocked 8-device bench: OK", data["value"], data["unit"])
EOF

# 3. graft gates: single-chip compile check + 8-device sharded dry run
export PYTHONPATH= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8
python - <<'EOF'
import jax
import __graft_entry__ as g

fn, args = g.entry()
jax.jit(fn).lower(*args).compile()
print("entry() compile OK")
g.dryrun_multichip(8)
print("dryrun_multichip(8) OK")
EOF
