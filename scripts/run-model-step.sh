#!/usr/bin/env bash
# Model-step scenario profile — the replayable-workload engine
# (docs/design.md "Model-step scenarios", arXiv 2006.13112): each named
# scenario composes its phase sequence (TP allreduce burst, MoE
# dispatch/combine all-to-all, pipeline ppermute chain, or a custom
# spec.json) into ONE fused step per sweep point, and IMBALANCE sweeps
# the v-variant phases' per-rank payload ratio — the hot expert /
# ragged-batch tail (keep 1 in the list: it is the balanced baseline
# the cost table divides by).  `tpu-perf report` on LOGDIR renders the
# Scenario-steps table (p50/p95 step time, modeled per-phase
# attribution, cost vs the balanced equivalent); ALGO names one flat
# arena inner to swap into every registered phase (pMR-style per-class
# transport selection — run once per inner to race them).  Health is ON
# with per-(scenario, ratio) baselines, so an imbalanced point never
# pollutes the balanced curve's detectors.
set -euo pipefail

SCENARIOS=${SCENARIOS:-tp-allreduce-burst,moe-dispatch-combine,pipeline-chain}
SWEEP=${SWEEP:-4K:4M}
IMBALANCE=${IMBALANCE:-1,2,8}       # the axis; 1 = the balanced baseline
ALGO=${ALGO:-native}                # one flat inner (ring/rhd/bruck/binomial)
ITERS=${ITERS:-10}
RUNS=${RUNS:-20}
PRECOMPILE=${PRECOMPILE:-4}         # scenario programs are the costliest
                                    # builds in the tree; overlap them
WARMUP=${WARMUP:-30}                # health baseline samples per point
LOGDIR=${LOGDIR:-/mnt/tcp-logs}     # = tpu_perf.config.DEFAULT_LOG_DIR
export TPU_PERF_INGEST=${TPU_PERF_INGEST:-none}

# extra args pass through to the CLI (e.g. --ci-rel 0.05 for adaptive
# budgets, --skew-spread 0,1ms to cross the straggler axis in)
python -m tpu_perf scenario "$SCENARIOS" --algo "$ALGO" \
    --sweep "$SWEEP" --imbalance "$IMBALANCE" -i "$ITERS" -r "$RUNS" \
    --precompile "$PRECOMPILE" --health --health-warmup "$WARMUP" \
    -l "$LOGDIR" "$@"

python -m tpu_perf report "$LOGDIR"
