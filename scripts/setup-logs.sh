#!/usr/bin/env bash
# Log-folder prep (the reference's scripts/setup-disk.sh:1-2).
set -euo pipefail
DIR=${1:-/mnt/tcp-logs}   # = tpu_perf.config.DEFAULT_LOG_DIR
sudo mkdir -p "$DIR"
sudo chmod 777 "$DIR"
