#!/usr/bin/env bash
# Telemetry-sink dependencies (the reference's
# scripts/install-kusto-dependencies.sh:2-4).  Only needed for
# TPU_PERF_INGEST=kusto:...; the local/none backends have no deps.
set -euo pipefail
pip install azure-identity azure-kusto-ingest pyopenssl
