#!/usr/bin/env bash
# Per-profile host-prep slot (the reference's scripts/map-irq.sh pinned NIC
# IRQs to cores; SURVEY.md §3.4 notes no TPU equivalent is needed because
# XLA owns device queues, but the slot should exist).  Add per-fleet host
# tuning here: THP settings, transparent hugepages for the host staging
# buffers, dcn NIC IRQ affinity on multi-slice pods, etc.
set -euo pipefail
echo "host-prep: nothing to do on this profile (XLA owns TPU device queues)"
