#!/usr/bin/env bash
# Per-profile host preparation — the slot the reference fills with
# scripts/map-irq.sh (NIC IRQ->core pinning for its TCP/IB profiles,
# map-irq.sh:23-75).  XLA owns the TPU device queues (SURVEY.md §3.4), so
# there is no IRQ map here; what *does* matter on a TPU host is the memory
# path the host<->HBM staging traffic takes and the fd budget of the
# monitoring daemon.  Default is a read-only audit; APPLY=1 writes the
# recommended settings (needs root).
set -uo pipefail

APPLY=${APPLY:-}
LOGDIR=${LOGDIR:-/mnt/tcp-logs}   # = tpu_perf.config.DEFAULT_LOG_DIR
fail=0

note() { printf 'host-prep: %s\n' "$*"; }
warn() { printf 'host-prep: WARN %s\n' "$*"; fail=1; }

# --- TPU device visibility ---------------------------------------------
if compgen -G "/dev/accel*" > /dev/null || compgen -G "/dev/vfio/*" > /dev/null; then
    note "TPU device nodes present: $(ls /dev/accel* /dev/vfio/* 2>/dev/null | tr '\n' ' ')"
else
    note "no /dev/accel* or /dev/vfio nodes (CPU host or remote/relayed TPU) — skipping device checks"
fi

# --- transparent hugepages ---------------------------------------------
# Host staging buffers for large host<->device transfers fragment badly
# with THP=always on long-running daemons; madvise is the recommended mode.
THP=/sys/kernel/mm/transparent_hugepage/enabled
if [[ -r $THP ]]; then
    cur=$(cat "$THP")
    if [[ $cur == *'[always]'* ]]; then
        if [[ -n $APPLY ]]; then
            if { echo madvise > "$THP"; } 2>/dev/null; then
                note "THP: always -> madvise"
            else
                warn "THP is [always] and could not be changed (need root)"
            fi
        else
            warn "THP is [always]; recommend madvise (APPLY=1 to set)"
        fi
    else
        note "THP mode ok: $cur"
    fi
fi

# --- locked-memory + fd limits -----------------------------------------
# The daemon keeps one rotating log per rank per schema plus the ingest
# pass's scan handles; 10 flows x 2 schemas x rotation overlap needs
# comfortably more than the 1024 default.
nofile=$(ulimit -n)
if [[ $nofile != unlimited && $nofile -lt 4096 ]]; then
    warn "ulimit -n is $nofile; recommend >= 4096 for the monitoring daemon"
else
    note "ulimit -n ok: $nofile"
fi
memlock=$(ulimit -l)
if [[ $memlock != unlimited && $memlock -lt 65536 ]]; then
    warn "ulimit -l is ${memlock} KiB; pinned staging buffers may fail (recommend unlimited)"
else
    note "ulimit -l ok: $memlock"
fi

# --- log folder (the reference's setup-disk.sh, kept in its own script) --
if [[ -d $LOGDIR && -w $LOGDIR ]]; then
    note "log folder ok: $LOGDIR"
else
    warn "log folder $LOGDIR missing or unwritable — run scripts/setup-logs.sh"
fi

# --- environment hints --------------------------------------------------
[[ -n "${TPU_PERF_INGEST:-}" ]] \
    && note "telemetry sink: TPU_PERF_INGEST=$TPU_PERF_INGEST" \
    || note "telemetry sink unset (TPU_PERF_INGEST=none|local:DIR|kusto:URI)"

exit $fail
