#!/usr/bin/env bash
# Collective-pattern sweeps (BASELINE.json configs 3-4): broadcast /
# all_gather / reduce_scatter, then all_to_all + the ppermute ring/halo
# exchange patterns, each over the size sweep.  One tpu-perf invocation per
# op so a crash in one kernel doesn't lose the others' rows; all rows land
# in the same LOGDIR (or stdout) for a single side-by-side report.
#
# DTYPE sweeps the payload element type (the dtype column keys the report
# curves): DTYPE="float32 bfloat16" runs the matrix — bf16 rows move twice
# the elements per byte and are the dtype real workloads communicate in.
set -euo pipefail

OPS=${OPS:-broadcast all_gather reduce_scatter all_to_all ring halo}
SWEEP=${SWEEP:-8:64M}
ITERS=${ITERS:-20}
RUNS=${RUNS:-10}
LOGDIR=${LOGDIR:-}
DTYPE=${DTYPE:-float32}
FENCE=${FENCE:-block}   # trace = device clock (TPU runtimes)
# PRECOMPILE overlaps the next points' kernel compilation with the
# current point's measurement (each op's sweep compiles one kernel per
# size — two under slope/trace); COMPILE_CACHE persists compiled
# programs so re-running the profile skips compilation entirely
PRECOMPILE=${PRECOMPILE:-0}
COMPILE_CACHE=${COMPILE_CACHE:-}

fail=0
for dtype in $DTYPE; do
    for op in $OPS; do
        args=(run --op "$op" --sweep "$SWEEP" -i "$ITERS" -r "$RUNS"
              --dtype "$dtype" --fence "$FENCE" --csv
              --precompile "$PRECOMPILE")
        [[ -n "$COMPILE_CACHE" ]] && args+=(--compile-cache "$COMPILE_CACHE")
        [[ -n "$LOGDIR" ]] && args+=(-l "$LOGDIR")
        # extra script args pass through to every invocation
        python -m tpu_perf "${args[@]}" "$@" || { echo "run-ici-collectives: $op ($dtype) failed" >&2; fail=1; }
    done
done
exit $fail
