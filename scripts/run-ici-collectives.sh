#!/usr/bin/env bash
# Collective-pattern sweeps (BASELINE.json configs 3-4): broadcast /
# all_gather / reduce_scatter, then all_to_all + the ppermute ring/halo
# exchange patterns, each over the size sweep.  One tpu-perf invocation per
# op so a crash in one kernel doesn't lose the others' rows; all rows land
# in the same LOGDIR (or stdout) for a single side-by-side report.
set -euo pipefail

OPS=${OPS:-broadcast all_gather reduce_scatter all_to_all ring halo}
SWEEP=${SWEEP:-8:64M}
ITERS=${ITERS:-20}
RUNS=${RUNS:-10}
LOGDIR=${LOGDIR:-}

fail=0
for op in $OPS; do
    args=(run --op "$op" --sweep "$SWEEP" -i "$ITERS" -r "$RUNS" --csv)
    [[ -n "$LOGDIR" ]] && args+=(-l "$LOGDIR")
    python -m tpu_perf "${args[@]}" || { echo "run-ici-collectives: $op failed" >&2; fail=1; }
done
exit $fail
