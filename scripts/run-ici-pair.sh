#!/usr/bin/env bash
# ICI pair-bandwidth profile — the TPU analogue of the reference's
# scripts/run-1-pair.sh (windowed non-blocking, 4 MiB, 5000 iters x 10 runs;
# reference run-1-pair.sh:3-9,28).  Where the reference selects IB RC via
# UCX env (run-1-pair.sh:26), the mesh here rides ICI by construction.
set -euo pipefail

ITERS=${ITERS:-5000}
RUNS=${RUNS:-10}
BUFF=${BUFF:-4M}
WINDOW=${WINDOW:-256}
LOGDIR=${LOGDIR:-}

args=(run --op exchange --window "$WINDOW" -n "$ITERS" -r "$RUNS" -b "$BUFF" --csv)
[[ -n "$LOGDIR" ]] && args+=(-f "$LOGDIR")
exec python -m tpu_perf "${args[@]}"
