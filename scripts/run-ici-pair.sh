#!/usr/bin/env bash
# ICI pair-bandwidth profile — the TPU analogue of the reference's
# scripts/run-1-pair.sh (windowed non-blocking, 4 MiB, 5000 iters x 10 runs;
# reference run-1-pair.sh:3-9,28).  Where the reference selects IB RC via
# UCX env (run-1-pair.sh:26), the mesh here rides ICI by construction.
#
# One fori iteration moves WINDOW stacked 4 MiB buffers, so a run is
# MSGS total messages (default 5120 =~ the reference's 5000) executed as
# MSGS/WINDOW fori iterations, and rows log nbytes=4 MiB / iters=MSGS —
# the same (op, nbytes) report curve key as run-mpi-1-pair.sh's rows
# (BufferSize is per-message in the reference schema, mpi_perf.c:551-554).
set -euo pipefail

if [[ -n "${ITERS:-}" ]]; then
    # the old ITERS knob meant total messages; it would now be multiplied
    # by WINDOW — refuse rather than silently run WINDOW times the work
    echo "run-ici-pair.sh: ITERS is gone; set MSGS (total messages per run)" >&2
    exit 2
fi
MSGS=${MSGS:-5120}
RUNS=${RUNS:-10}
BUFF=${BUFF:-4M}
WINDOW=${WINDOW:-256}
LOGDIR=${LOGDIR:-}
FENCE=${FENCE:-block}   # trace = device clock (TPU runtimes)
if (( WINDOW < 1 )); then
    echo "run-ici-pair.sh: WINDOW must be >= 1, got $WINDOW" >&2
    exit 2
fi
FORI_ITERS=$(( (MSGS + WINDOW - 1) / WINDOW ))

args=(run --op exchange --window "$WINDOW" -i "$FORI_ITERS" -r "$RUNS"
      -b "$BUFF" --fence "$FENCE" --csv)
[[ -n "$LOGDIR" ]] && args+=(-l "$LOGDIR")
exec python -m tpu_perf "${args[@]}" "$@"
