#!/usr/bin/env bash
# Arrival-skew / straggler profile — the imbalanced-entry scenario axis
# (docs/design.md "Arrival skew & straggler scenarios", arXiv
# 1804.05349): every (op, size) point is measured once per arrival
# spread in SKEW_SPREAD, each run's collective entry staggered — the
# last rank exactly spread late (the priced straggler), the rest by
# seeded arrivals in [0, spread).  `tpu-perf report` on LOGDIR
# renders the straggler-cost table (slowdown vs the spread-0 baseline —
# keep 0 in the list) and, with ALGO=all, the per-(size, spread) arena
# crossover.  Health is ON with per-spread baselines, so a skewed point
# never pollutes the synchronized curve's detectors.
set -euo pipefail

OPS=${OPS:-allreduce}
SWEEP=${SWEEP:-8:4M}
SKEW_SPREAD=${SKEW_SPREAD:-0,250us,1ms}  # the axis; 0 = the baseline
ALGO=${ALGO:-native}                     # all = race the arena per spread
ITERS=${ITERS:-10}
RUNS=${RUNS:-20}
FENCE=${FENCE:-block}                    # fused cannot stagger runs (loud
                                         # Options error); keep a per-run fence
WARMUP=${WARMUP:-30}                     # health baseline samples per point
LOGDIR=${LOGDIR:-/mnt/tcp-logs}          # = tpu_perf.config.DEFAULT_LOG_DIR
export TPU_PERF_INGEST=${TPU_PERF_INGEST:-none}

# extra args pass through to the CLI (e.g. --seed N for a different
# arrival draw stream, --ci-rel 0.05 for adaptive budgets)
python -m tpu_perf run --op "$OPS" --algo "$ALGO" --sweep "$SWEEP" \
    --skew-spread "$SKEW_SPREAD" -i "$ITERS" -r "$RUNS" --fence "$FENCE" \
    --health --health-warmup "$WARMUP" -l "$LOGDIR" "$@"

python -m tpu_perf report "$LOGDIR"
