#!/usr/bin/env bash
# T4-VM TCP fleet-monitor profile (reference run-t4.sh:22-28): identical to
# the HBv3 TCP profile except the CPU pinning (cores 6..15).
set -euo pipefail
export CPU_LIST=${CPU_LIST-6,7,8,9,10,11,12,13,14,15}
exec "$(dirname "$0")/run-mpi-monitor.sh"
