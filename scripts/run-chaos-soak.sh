#!/usr/bin/env bash
# Chaos-soak profile — run-ici-health.sh with deterministic fault
# injection on top: a seeded FaultInjector degrades the daemon's own
# measurements per a JSON schedule (FAULTS), ledgers every injection to
# rotating chaos-*.log files, and the health subsystem (forced on) must
# notice.  Judge the run afterwards with
#   python -m tpu_perf chaos verify "$LOGDIR"
# which joins the ledger against the emitted health-*.log events and
# exits 5 on a missed critical fault.
set -euo pipefail

FAULTS=${FAULTS:?path to a fault-schedule JSON (tpu_perf.faults.spec)}
SEED=${SEED:-7}                   # same seed+spec => identical ledger
MAX_RUNS=${MAX_RUNS:-400}         # bounded soak; empty = run forever
BUFF=${BUFF:-456131}
ITERS=${ITERS:-10}
LOGDIR=${LOGDIR:-/mnt/tcp-logs}   # = tpu_perf.config.DEFAULT_LOG_DIR
OPS=${OPS:-ring}                  # comma family rotates the instrument set
SWEEP=${SWEEP:-}                  # size list: one baseline per point
FENCE=${FENCE:-block}             # trace = device clock (TPU runtimes)
THRESHOLD=${THRESHOLD:-0.5}       # step-regression threshold (+50%)
WARMUP=${WARMUP:-30}              # baseline samples before judging
STATS_EVERY=${STATS_EVERY:-1000}  # heartbeat/capture-loss window
SYNTHETIC=${SYNTHETIC:-}          # base seconds: seeded synthetic samples
                                  # instead of real timings (CI determinism)
export TPU_PERF_INGEST=${TPU_PERF_INGEST:-none}

args=(--faults "$FAULTS" --seed "$SEED"
      --health-threshold "$THRESHOLD" --health-warmup "$WARMUP"
      --stats-every "$STATS_EVERY" -i "$ITERS" --fence "$FENCE"
      -l "$LOGDIR")
if [ -n "$MAX_RUNS" ]; then
    args+=(--max-runs "$MAX_RUNS")
fi
if [ -n "$SYNTHETIC" ]; then
    args+=(--synthetic "$SYNTHETIC")
fi
if [ -n "$SWEEP" ]; then
    args+=(--sweep "$SWEEP")
else
    args+=(-b "$BUFF")
fi

# extra args pass through to the CLI (like run-ici-health.sh), so a soak
# can override e.g. --log-refresh-sec / --heartbeat-format json
exec python -m tpu_perf chaos --op "$OPS" "${args[@]}" "$@"
