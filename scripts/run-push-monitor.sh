#!/usr/bin/env bash
# Live-telemetry monitoring profile — run-ici-health.sh with the push
# plane on: every record family (rows, health events, spans; never the
# chaos ledger) teed at the rotating-log write boundary and streamed to
# an NDJSON collector (PUSH_URL/v1/<Table>, the Kusto table routing),
# with a live Prometheus textfile of the plane's own meters, jittered-
# backoff retries, and a dead-letter spool next to the logs that
# `tpu-perf ingest --requeue` + `tpu-perf push replay` recover.
set -euo pipefail

BUFF=${BUFF:-456131}
ITERS=${ITERS:-10}
LOGDIR=${LOGDIR:-/mnt/tcp-logs}   # = tpu_perf.config.DEFAULT_LOG_DIR
# OPS: empty = the reference-faithful unidirectional kernel; a comma
# family rotates the whole instrument set through one judged daemon
OPS=${OPS:-}
# SWEEP: empty = single buffer (BUFF); a size list gives every sweep
# point its own baseline, e.g. SWEEP=64K,1M,16M
SWEEP=${SWEEP:-}
FENCE=${FENCE:-block}             # trace = device clock (TPU runtimes)
THRESHOLD=${THRESHOLD:-0.5}       # step-regression threshold (+50%)
WARMUP=${WARMUP:-30}              # baseline samples before a point is judged
MAX_RUNS=${MAX_RUNS:-}            # bound the daemon (soaks/CI); empty = forever
# PUSH_URL: the live collector's base URL (records POST to
# PUSH_URL/v1/<Table>).  Required — a push profile without a sink is
# run-ici-health.sh; use that instead.
PUSH_URL=${PUSH_URL:?run-push-monitor.sh needs PUSH_URL (the NDJSON \
collector base URL; records POST to PUSH_URL/v1/<Table>)}
# PUSH_TEXTFILE: live Prometheus meters, refreshed every sender cycle
# (e.g. /var/lib/node_exporter/tpu-perf-push.prom); empty = none
PUSH_TEXTFILE=${PUSH_TEXTFILE:-}
PUSH_QUEUE=${PUSH_QUEUE:-}        # tee-queue bound; empty = default 10000
TEXTFILE=${TEXTFILE:-}            # health-gauge textfile (carries the push
                                  # gauges too); empty = none
export TPU_PERF_INGEST=${TPU_PERF_INGEST:-none}

args=(--health --health-threshold "$THRESHOLD" --health-warmup "$WARMUP"
      -i "$ITERS" --fence "$FENCE" -l "$LOGDIR"
      --push "$PUSH_URL" --heartbeat-format json)
if [ -n "$PUSH_TEXTFILE" ]; then
    args+=(--push-textfile "$PUSH_TEXTFILE")
fi
if [ -n "$PUSH_QUEUE" ]; then
    args+=(--push-queue "$PUSH_QUEUE")
fi
if [ -n "$TEXTFILE" ]; then
    args+=(--health-textfile "$TEXTFILE")
fi
if [ -n "$MAX_RUNS" ]; then
    args+=(--max-runs "$MAX_RUNS")
fi
if [ -n "$SWEEP" ]; then
    args+=(--sweep "$SWEEP")
else
    args+=(-b "$BUFF")
fi

# extra args pass through to the CLI (like run-ici-health.sh), so a soak
# can override e.g. --spans / --log-refresh-sec
if [ -n "$OPS" ]; then
    exec python -m tpu_perf monitor --op "$OPS" "${args[@]}" "$@"
fi
exec python -m tpu_perf monitor -u "${args[@]}" "$@"
