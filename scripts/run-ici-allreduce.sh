#!/usr/bin/env bash
# The north-star sweep (BASELINE.json): all-reduce bus bandwidth + p50
# latency, 8 B - 1 GiB, over the full ICI mesh.  Upper-bound the sweep with
# SWEEP=8:64M etc. on small-HBM parts.
set -euo pipefail

SWEEP=${SWEEP:-8:1G}
ITERS=${ITERS:-20}
RUNS=${RUNS:-10}
DTYPE=${DTYPE:-bfloat16}
FENCE=${FENCE:-block}   # trace = device clock (TPU runtimes)
LOGDIR=${LOGDIR:-}

args=(run --op allreduce --sweep "$SWEEP" -i "$ITERS" -r "$RUNS"
      --dtype "$DTYPE" --fence "$FENCE" --csv)
[[ -n "$LOGDIR" ]] && args+=(-l "$LOGDIR")
exec python -m tpu_perf "${args[@]}" "$@"
