#!/usr/bin/env bash
# Contention profile — collectives measured under concurrent load
# (docs/design.md "Async dispatch & contention", arXiv 2305.10612):
# every (op, size) point is measured twice in one job, idle (the
# victim alone — the quiet-fabric baseline every other profile
# publishes) and loaded (the victim raced against LOAD on the stream
# engine's dispatch lanes).  `tpu-perf report` on LOGDIR renders the
# interference matrix (op x load -> slowdown vs idle); ALGO=all also
# teaches the arena crossover table the LOADED winner.  A second
# contend pass with a disjoint-axis LOAD_AXIS (multi-axis meshes) is
# the control: slowdown ~1.0 there means the loaded slowdown is
# fabric contention, not dispatch overhead.
set -euo pipefail

OP=${OP:-allreduce}                      # the victim (single op)
LOAD=${LOAD:-hbm_stream}                 # mxu_gemm | hbm_stream | a collective
SWEEP=${SWEEP:-64K:4M}
ALGO=${ALGO:-native}                     # all = race the arena under load
ITERS=${ITERS:-10}
RUNS=${RUNS:-20}
FENCE=${FENCE:-block}                    # contend needs a per-run fence that
                                         # tolerates concurrent lanes
LOGDIR=${LOGDIR:-/mnt/tcp-logs}          # = tpu_perf.config.DEFAULT_LOG_DIR
export TPU_PERF_INGEST=${TPU_PERF_INGEST:-none}

# extra args pass through to the CLI (e.g. --load-axis ici for the
# disjoint-axis control, --split 2 instead of --load for the
# split-channel shape, --mesh/--axes for a multi-axis fabric)
python -m tpu_perf contend --op "$OP" --load "$LOAD" --algo "$ALGO" \
    --sweep "$SWEEP" -i "$ITERS" -r "$RUNS" --fence "$FENCE" \
    -l "$LOGDIR" "$@"

python -m tpu_perf report "$LOGDIR"
