#!/usr/bin/env bash
# ICI link-map profile — the fleet-triage sweep: probe every directed
# neighbor link of the mesh (or all host pairs with ALL_PAIRS=1), grade
# each against the chip's per-link ICI roofline and its row/column MAD
# peers, persist linkmap-*.log records (fifth rotating family, own Kusto
# table) and surface sick links as link_degraded health events.
# Exit 6 = at least one link graded slow/dead (the cron/CI gate).
set -euo pipefail

BUFF=${BUFF:-4M}                  # per-probe message (bandwidth-shaped)
ITERS=${ITERS:-10}                # chained ppermutes per timed sample
RUNS=${RUNS:-5}                   # samples per link (mean-graded)
LOGDIR=${LOGDIR:-/mnt/tcp-logs}   # = tpu_perf.config.DEFAULT_LOG_DIR
FENCE=${FENCE:-block}             # block|readback (single timed calls)
MESH=${MESH:-}                    # e.g. 2x4; empty = all devices, one axis
AXES=${AXES:-}                    # e.g. dcn,ici
ALL_PAIRS=${ALL_PAIRS:-}          # 1 = mpiGraph-style all-ordered-pairs
CONCURRENT=${CONCURRENT:-}        # 1 = batched link-disjoint schedules
ROOFLINE=${ROOFLINE:-}            # GB/s per link; empty = chip table; 0 off
export TPU_PERF_INGEST=${TPU_PERF_INGEST:-none}

args=(-b "$BUFF" -i "$ITERS" -r "$RUNS" --fence "$FENCE" -l "$LOGDIR")
if [ -n "$MESH" ]; then
    args+=(--mesh "$MESH")
fi
if [ -n "$AXES" ]; then
    args+=(--axes "$AXES")
fi
if [ -n "$ALL_PAIRS" ]; then
    args+=(--all-pairs)
fi
if [ -n "$CONCURRENT" ]; then
    args+=(--concurrent)
fi
if [ -n "$ROOFLINE" ]; then
    args+=(--roofline-gbps "$ROOFLINE")
fi

# extra args pass through (e.g. --no-wrap for line fabrics, --mad-z)
exec python -m tpu_perf linkmap "${args[@]}" "$@"
