#!/usr/bin/env bash
# Collective-algorithm arena profile (docs/design.md "Collective-algorithm
# arena"): race every registered decomposition (ring, recursive
# halving/doubling, Bruck, binomial) against the native XLA lowering per
# (collective, size), one tpu-perf invocation per collective so a crash in
# one kernel doesn't lose the others' rows.  All rows land in the same
# LOGDIR; `tpu-perf report LOGDIR` then renders the per-size
# best-algorithm crossover table with native-vs-best ratios — the per-chip
# answer to WHERE a hand-built schedule beats the native lowering.
#
# FENCE defaults to fused: at small message sizes the host dispatch is
# every per-run fence's floor, and honest small-message crossovers need
# the one-dispatch-per-point loop (ROADMAP direction 4's follow-on).
set -euo pipefail

OPS=${OPS:-allreduce all_gather reduce_scatter}
ALGO=${ALGO:-all}       # all | native | ring,rhd,bruck,binomial subset
SWEEP=${SWEEP:-8:4M}
ITERS=${ITERS:-20}
RUNS=${RUNS:-20}
LOGDIR=${LOGDIR:-}
DTYPE=${DTYPE:-float32}
FENCE=${FENCE:-fused}
PRECOMPILE=${PRECOMPILE:-4}   # each algorithm is its own program per
                              # size — the worker hides the extra compiles
COMPILE_CACHE=${COMPILE_CACHE:-}

fail=0
for dtype in $DTYPE; do
    for op in $OPS; do
        args=(run --op "$op" --algo "$ALGO" --sweep "$SWEEP"
              -i "$ITERS" -r "$RUNS" --dtype "$dtype" --fence "$FENCE"
              --csv --precompile "$PRECOMPILE")
        [[ -n "$COMPILE_CACHE" ]] && args+=(--compile-cache "$COMPILE_CACHE")
        [[ -n "$LOGDIR" ]] && args+=(-l "$LOGDIR")
        # extra script args pass through to every invocation
        python -m tpu_perf "${args[@]}" "$@" || { echo "run-ici-arena: $op ($dtype) failed" >&2; fail=1; }
    done
done
exit $fail
