#!/usr/bin/env bash
# Crossover auto-tune loop (docs/design.md "Crossover auto-tuner"): the
# measure -> select -> steer -> re-check loop as one profile.
#
#   1. arena sweep: race every buildable algorithm per (op, size) so the
#      logs hold a graded crossover table (run-ici-arena.sh's core),
#   2. `tpu-perf tune`: fold the arena verdicts into the versioned
#      selection artifact (and its tune-*.log eighth-family record),
#   3. auto-steered run: `--algo auto` resolves every sweep point against
#      the artifact at plan time — the piecewise-best schedule,
#   4. drift check: re-grade fresh rows against the published artifact;
#      a flipped crossover exits 10 and fails this script, which is the
#      cron hook — a selection artifact must not rot silently.
#
# LOGDIR is required: the artifact and the drift gate only mean something
# against durable rows.  Extra script args pass through to the RUN
# invocations (not to `tune`).
set -euo pipefail

OPS=${OPS:-allreduce all_gather reduce_scatter}
SWEEP=${SWEEP:-8:4M}
ITERS=${ITERS:-20}
RUNS=${RUNS:-20}
LOGDIR=${LOGDIR:?run-auto-tune: set LOGDIR (durable rows feed the tuner)}
ARTIFACT=${ARTIFACT:-$LOGDIR/selection.json}
DTYPE=${DTYPE:-float32}
FENCE=${FENCE:-fused}
PRECOMPILE=${PRECOMPILE:-4}
TUNE_MARGIN=${TUNE_MARGIN:-1.02}   # verdicts under 2% are noise
SKIP_CHECK=${SKIP_CHECK:-}         # non-empty: stop after the auto run

fail=0

# 1. measure: full arena race per collective.
for op in $OPS; do
    python -m tpu_perf run --op "$op" --algo all --sweep "$SWEEP" \
        -i "$ITERS" -r "$RUNS" --dtype "$DTYPE" --fence "$FENCE" \
        --csv --precompile "$PRECOMPILE" -l "$LOGDIR" "$@" \
        || { echo "run-auto-tune: arena $op failed" >&2; fail=1; }
done
[[ $fail -ne 0 ]] && exit $fail

# 2. select: fold the verdicts into the artifact (+ tune-*.log family).
python -m tpu_perf tune -d "$LOGDIR" -o "$ARTIFACT" -l "$LOGDIR" \
    --margin "$TUNE_MARGIN"

# 3. steer: replay the sweep with each point on its measured winner.
for op in $OPS; do
    python -m tpu_perf run --op "$op" --algo auto \
        --algo-artifact "$ARTIFACT" --tune-margin "$TUNE_MARGIN" \
        --sweep "$SWEEP" -i "$ITERS" -r "$RUNS" --dtype "$DTYPE" \
        --fence "$FENCE" --csv --precompile "$PRECOMPILE" \
        -l "$LOGDIR" "$@" \
        || { echo "run-auto-tune: auto $op failed" >&2; fail=1; }
done
[[ $fail -ne 0 ]] && exit $fail

# 4. re-check: fresh rows (steps 1+3 both landed in LOGDIR) against the
# published artifact; exit 10 = a crossover flipped since publication.
if [[ -z "$SKIP_CHECK" ]]; then
    python -m tpu_perf tune -d "$LOGDIR" --check "$ARTIFACT" \
        --margin "$TUNE_MARGIN"
fi
