#!/usr/bin/env bash
# Legacy MPI baseline, fleet-monitor profile — parameterized over the
# reference's three monitoring profiles (all: 2 hosts x 10 flows,
# unidirectional, 456,131 B, infinite runs):
#
#   defaults        -> run-hbv3.sh   (UCX TCP eth0 + TCP tuning, cores 8-17;
#                      reference run-hbv3.sh:3-9,22-28)
#   NET=mlx5_ib2:1 TLS=rc SL=1 CPU_LIST=5,7,9,11,13,15,17,19,21,23
#                   -> run-ib.sh    (IB RC, service level 1, odd cores;
#                      reference run-ib.sh:22-27)
#   CPU_LIST=6,7,8,9,10,11,12,13,14,15
#                   -> run-t4.sh    (same TCP tuning, T4 pinning;
#                      reference run-t4.sh:22-28)
#
# CPU pinning is part of the measurement config (BASELINE.md): the
# reference binds with --use-hwthread-cpus --bind-to cpulist:ordered.
# Set CPU_LIST= (empty) to disable pinning.  DRY_RUN=1 prints the mpirun
# command instead of executing it.
set -euo pipefail

HOSTS=${HOSTS:?set HOSTS=host0,host1}
GROUP1=${GROUP1:?set GROUP1=/path/to/group1-hostfile}
FLOWS=${FLOWS:-10}
ITERS=${ITERS:-10}
RUNS=${RUNS:--1}
BUFF=${BUFF:-456131}
LOGDIR=${LOGDIR:-/mnt/tcp-logs}   # = tpu_perf.config.DEFAULT_LOG_DIR (kusto_ingest.py:47)
NET=${NET:-eth0}
TLS=${TLS:-tcp}
SL=${SL:-}                                # UCX_IB_SL (run-ib.sh:25), IB only
CPU_LIST=${CPU_LIST-8,9,10,11,12,13,14,15,16,17}  # HBv3 default (run-hbv3.sh:23)

HERE=$(cd "$(dirname "$0")/.." && pwd)

# TPU_PERF_INGEST_CMD fires on each log rotation from node-local rank 0
# (the reference hardcoded its kusto_ingest.py invocation there)
export TPU_PERF_INGEST_CMD=${TPU_PERF_INGEST_CMD:-"python3 -m tpu_perf ingest -d $LOGDIR -f $FLOWS"}

bind=(--bind-to core)
[[ -n "$CPU_LIST" ]] && bind=(--use-hwthread-cpus --bind-to cpulist:ordered --cpu-list "$CPU_LIST")

env_args=(-x UCX_NET_DEVICES="$NET" -x UCX_TLS="$TLS")
if [[ "$TLS" == tcp ]]; then
    # the reference's full TCP tuning block (run-hbv3.sh:25-27)
    env_args+=(-x UCX_TCP_MAX_NUM_EPS=1
               -x UCX_TCP_TX_SEG_SIZE=1mb -x UCX_TCP_RX_SEG_SIZE=1mb
               -x UCX_TCP_PUT_ENABLE=n
               -x UCX_TCP_SNDBUF=1mb -x UCX_TCP_RCVBUF=1mb)
fi
[[ -n "$SL" ]] && env_args+=(-x UCX_IB_SL="$SL")
env_args+=(-x TPU_PERF_INGEST_CMD)

cmd=(mpirun -np $((2 * FLOWS)) --host "$HOSTS" --map-by ppr:"$FLOWS":node
     "${bind[@]}" "${env_args[@]}"
     "$HERE/backends/mpi/mpi_perf"
     -f "$GROUP1" -n 1 -i "$ITERS" -r "$RUNS" -b "$BUFF" -p "$FLOWS" -u 1 -l "$LOGDIR")

if [[ -n "${DRY_RUN:-}" ]]; then
    source "$HERE/scripts/_render.sh"
    render_cmd "${cmd[@]}"
    exit 0
fi
make -C "$HERE/backends/mpi" mpi_perf
exec "${cmd[@]}"
