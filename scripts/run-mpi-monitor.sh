#!/usr/bin/env bash
# Legacy MPI baseline, TCP fleet-monitor profile — reproduces the
# reference's run-hbv3.sh (2 hosts x 10 flows, unidirectional, 456,131 B,
# infinite runs, UCX TCP tuning; reference run-hbv3.sh:3-9,22-28).
set -euo pipefail

HOSTS=${HOSTS:?set HOSTS=host0,host1}
GROUP1=${GROUP1:?set GROUP1=/path/to/group1-hostfile}
FLOWS=${FLOWS:-10}
ITERS=${ITERS:-10}
RUNS=${RUNS:--1}
BUFF=${BUFF:-456131}
LOGDIR=${LOGDIR:-/mnt/tcp-logs}

HERE=$(cd "$(dirname "$0")/.." && pwd)
make -C "$HERE/backends/mpi" mpi_perf

# TPU_PERF_INGEST_CMD fires on each log rotation from node-local rank 0
# (the reference hardcoded its kusto_ingest.py invocation there)
export TPU_PERF_INGEST_CMD=${TPU_PERF_INGEST_CMD:-"python3 -m tpu_perf ingest -d $LOGDIR -f $FLOWS"}

exec mpirun -np $((2 * FLOWS)) --host "$HOSTS" --map-by ppr:"$FLOWS":node \
    -x UCX_TLS=tcp -x UCX_NET_DEVICES=eth0 \
    -x UCX_TCP_MAX_NUM_EPS=1 -x UCX_TCP_TX_SEG_SIZE=1m -x UCX_TCP_RX_SEG_SIZE=1m \
    -x TPU_PERF_INGEST_CMD \
    "$HERE/backends/mpi/mpi_perf" \
    -l "$GROUP1" -n "$ITERS" -r "$RUNS" -b "$BUFF" -p "$FLOWS" -u -f "$LOGDIR"
