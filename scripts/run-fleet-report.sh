#!/usr/bin/env bash
# Fleet-report profile — the cross-host collector as a cron job.  Point
# FLEET_ROOT at a directory holding one subfolder of rotating logs per
# host (a shared mount, or an rsync target each daemon's -l folder
# lands in) and this renders the fleet report, refreshes the Prometheus
# staleness/sick gauges, writes the JSON artifact, and — when a
# previous artifact exists — compares the CURRENT fleet medians against
# it so a fleet-wide shift is flagged instead of being absorbed into
# every host's local baseline.  Exit 9 = sick hosts or a fleet-wide
# shift (wire the cron wrapper to alert on it).
set -euo pipefail

FLEET_ROOT=${FLEET_ROOT:?fleet root (one host record folder per subdir)}
ARTIFACT=${ARTIFACT:-$FLEET_ROOT/fleet.json}     # also the next baseline
TEXTFILE=${TEXTFILE:-}            # e.g. /var/lib/node_exporter/fleet.prom
ROLLUP_DIR=${ROLLUP_DIR:-}        # persist fleet-*.log records here
STALE_AFTER=${STALE_AFTER:-3600}  # seconds without a write = stale
MAD_Z=${MAD_Z:-6.0}               # robust-z bar vs peer hosts
REL=${REL:-0.25}                  # AND-gate relative excess
MIN_HOSTS=${MIN_HOSTS:-3}         # peers before a point is graded
SHIFT=${SHIFT:-0.25}              # fleet-median move that flags a shift

args=(--stale-after "$STALE_AFTER" --mad-z "$MAD_Z"
      --rel-threshold "$REL" --min-hosts "$MIN_HOSTS"
      --shift-threshold "$SHIFT")
if [ -n "$TEXTFILE" ]; then
    args+=(--textfile "$TEXTFILE")
fi
if [ -n "$ROLLUP_DIR" ]; then
    args+=(-l "$ROLLUP_DIR")
fi
# the previous artifact is the shift baseline; write the fresh one to a
# temp name first so a failed run never clobbers the baseline
if [ -f "$ARTIFACT" ]; then
    args+=(--baseline "$ARTIFACT")
fi

rc=0
python -m tpu_perf fleet report "$FLEET_ROOT" \
    -o "$ARTIFACT.next" "${args[@]}" "$@" || rc=$?
if [ -f "$ARTIFACT.next" ]; then
    mv "$ARTIFACT.next" "$ARTIFACT"
fi
exit "$rc"
