#!/usr/bin/env bash
# Multi-slice profile: collectives over a (dcn, ici) mesh, the
# hierarchical arena racing the composed DCN-minimal algorithms
# (reduce-scatter inside each slice over ICI, all-reduce across slices
# over DCN, all-gather back over ICI — and the hier-<inner> per-axis
# variants) head-to-head against the flat native lowering (BASELINE.json
# config 5, pod scale).  `tpu-perf report` then renders the mesh-shaped
# crossover table and the DCN bytes-per-axis model next to measured time.
# SLICES must divide the device count.
set -euo pipefail

SLICES=${SLICES:-2}
OPS=${OPS:-allreduce,all_gather,reduce_scatter}
ALGOS=${ALGOS:-hier,native}   # hier | hier-ring | ... | all | native
SWEEP=${SWEEP:-8:64M}
ITERS=${ITERS:-20}
RUNS=${RUNS:-10}
FENCE=${FENCE:-block}         # trace = device clock (TPU runtimes)
PRECOMPILE=${PRECOMPILE:-0}   # AOT look-ahead depth (0 = serial builds)
SPANS=${SPANS:-0}             # 1 = harness span tracing (needs -l)
PUSH_URL=${PUSH_URL:-}        # live telemetry push plane endpoint

EXTRA=()
[ "$PRECOMPILE" != "0" ] && EXTRA+=(--precompile "$PRECOMPILE")
[ "$SPANS" = "1" ] && EXTRA+=(--spans)
[ -n "$PUSH_URL" ] && EXTRA+=(--push "$PUSH_URL")

exec python -m tpu_perf run --op "$OPS" --algo "$ALGOS" \
    --mesh "${SLICES}x-1" --axes dcn,ici --sweep "$SWEEP" \
    -i "$ITERS" -r "$RUNS" --fence "$FENCE" "${EXTRA[@]}" --csv "$@"
