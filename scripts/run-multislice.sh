#!/usr/bin/env bash
# Multi-slice profile: hierarchical all-reduce over a (dcn, ici) mesh —
# reduce-scatter inside each slice over ICI, all-reduce across slices over
# DCN, all-gather back over ICI (BASELINE.json config 5, pod scale).
# SLICES must divide the device count.
set -euo pipefail

SLICES=${SLICES:-2}
SWEEP=${SWEEP:-8:64M}
ITERS=${ITERS:-20}
RUNS=${RUNS:-10}
FENCE=${FENCE:-block}   # trace = device clock (TPU runtimes)

exec python -m tpu_perf run --op hier_allreduce \
    --mesh "${SLICES}x-1" --axes dcn,ici --sweep "$SWEEP" \
    -i "$ITERS" -r "$RUNS" --fence "$FENCE" --csv "$@"
