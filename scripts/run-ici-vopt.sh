#!/usr/bin/env bash
# Irregular-payload arena profile (docs/design.md "Irregular-payload
# schedules"): race the optimized v-variant schedules (sortring /
# doubling allgatherv, ring/doubling all_to_all_v, the seg_allreduce
# transport family) against the native per-origin ring per (collective,
# size, imbalance ratio), one tpu-perf invocation per collective so a
# crash in one kernel doesn't lose the others' rows.  All rows land in
# the same LOGDIR; `tpu-perf report LOGDIR` then renders the algo-aware
# Imbalance-cost table (best algo + best/naive per coordinate) next to
# the arena crossover — the per-chip answer to WHICH schedule to ship
# for a given hot-rank ratio.
#
# On a 2-axis (dcn, ici) mesh set MESH/AXES (e.g. MESH=2x4
# AXES=dcn,ici) to race the keyed vhier composition for allgatherv
# against the whole-mesh native schedule instead.
set -euo pipefail

OPS=${OPS:-allgatherv reduce_scatter_v all_to_all_v seg_allreduce}
ALGO=${ALGO:-all}       # all | native | an explicit schedule subset
SWEEP=${SWEEP:-4K:4M}
IMBALANCE=${IMBALANCE:-1,2,8}  # seg_allreduce reads it as the DENSITY ratio
ITERS=${ITERS:-20}
RUNS=${RUNS:-20}
LOGDIR=${LOGDIR:-}
DTYPE=${DTYPE:-float32}
FENCE=${FENCE:-fused}
MESH=${MESH:-}
AXES=${AXES:-}
PRECOMPILE=${PRECOMPILE:-4}   # each (algo, ratio) is its own program
                              # per size — the worker hides the compiles
COMPILE_CACHE=${COMPILE_CACHE:-}

fail=0
for dtype in $DTYPE; do
    for op in $OPS; do
        args=(run --op "$op" --algo "$ALGO" --sweep "$SWEEP"
              --imbalance "$IMBALANCE" -i "$ITERS" -r "$RUNS"
              --dtype "$dtype" --fence "$FENCE"
              --csv --precompile "$PRECOMPILE")
        [[ -n "$MESH" ]] && args+=(--mesh "$MESH")
        [[ -n "$AXES" ]] && args+=(--axes "$AXES")
        [[ -n "$COMPILE_CACHE" ]] && args+=(--compile-cache "$COMPILE_CACHE")
        [[ -n "$LOGDIR" ]] && args+=(-l "$LOGDIR")
        # extra script args pass through to every invocation
        python -m tpu_perf "${args[@]}" "$@" || { echo "run-ici-vopt: $op ($dtype) failed" >&2; fail=1; }
    done
done
exit $fail
