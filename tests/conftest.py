"""Test environment: 8 virtual CPU devices, no TPU required.

Must run before the first `import jax` anywhere in the test session —
pytest imports conftest.py before collecting test modules, which guarantees
that ordering (SURVEY.md §4: the standard JAX multi-device-without-a-cluster
trick).
"""

# A sitecustomize.py may have pre-registered a TPU plugin and forced
# jax_platforms to it (overriding the env var); reclaim CPU before any
# backend is initialized.
from tpu_perf.parallel import claim_cpu_devices

if not claim_cpu_devices(8):
    raise RuntimeError("JAX backend initialized before conftest ran")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devices = jax.devices()
    assert len(devices) == 8, f"expected 8 virtual devices, got {len(devices)}"
    return devices
