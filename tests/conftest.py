"""Test environment: 8 virtual CPU devices, no TPU required.

Must run before the first `import jax` anywhere in the test session —
pytest imports conftest.py before collecting test modules, which guarantees
that ordering (SURVEY.md §4: the standard JAX multi-device-without-a-cluster
trick).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# A sitecustomize.py may have pre-registered a TPU plugin and forced
# jax_platforms to it (overriding the env var); reclaim CPU before any
# backend is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devices = jax.devices()
    assert len(devices) == 8, f"expected 8 virtual devices, got {len(devices)}"
    return devices
