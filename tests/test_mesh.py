import pytest

from tpu_perf.parallel import make_mesh, mesh_devices_flat


def test_default_flat_mesh(eight_devices):
    mesh = make_mesh()
    assert mesh.axis_names == ("x",)
    assert mesh.shape == {"x": 8}
    assert len(mesh_devices_flat(mesh)) == 8


def test_two_axis_mesh(eight_devices):
    mesh = make_mesh((2, 4), ("dcn", "ici"))
    assert mesh.shape == {"dcn": 2, "ici": 4}


def test_inferred_dim(eight_devices):
    mesh = make_mesh((2, -1), ("dcn", "ici"))
    assert mesh.shape == {"dcn": 2, "ici": 4}


def test_bad_shapes(eight_devices):
    with pytest.raises(ValueError):
        make_mesh((3,), ("x",))
    with pytest.raises(ValueError):
        make_mesh((2, 4), ("x",))
    with pytest.raises(ValueError):
        make_mesh((-1, -1), ("a", "b"))
    with pytest.raises(ValueError):
        make_mesh((16,), ("x",))


def test_claim_cpu_devices_noop_after_init(eight_devices):
    # The backend is initialized (conftest claimed it); a late claim must
    # refuse and must not touch the environment of child processes.
    import os

    from tpu_perf.parallel import claim_cpu_devices

    before = os.environ.get("XLA_FLAGS")
    assert claim_cpu_devices(32) is False
    assert os.environ.get("XLA_FLAGS") == before
