import re

import pytest

from tpu_perf.schema import (
    LEGACY_HEADER,
    RESULT_HEADER,
    LegacyRow,
    ResultRow,
    rows_to_csv,
    timestamp_now,
)


def _legacy_row(run_id=1):
    return LegacyRow(
        timestamp="2026-07-29 12:00:00.123",
        job_id="ab12cd34-0000-0000-0000-000000000000",
        rank=3,
        vm_count=2,
        local_ip="10.0.0.1",
        remote_ip="10.0.0.2",
        num_flows=10,
        buffer_size=456131,
        num_buffers=10,
        time_taken_ms=12.345,
        run_id=run_id,
    )


def test_legacy_header_matches_reference_schema():
    # mpi_perf.c:550-554 field order, verbatim
    assert LEGACY_HEADER.split(",") == [
        "Timestamp", "JobId", "Rank", "VMCount", "LocalIP", "RemoteIP",
        "NumOfFlows", "BufferSize", "NumOfBuffers", "TimeTakenms", "RunId",
    ]


def test_legacy_row_roundtrip():
    row = _legacy_row()
    line = row.to_csv()
    assert len(line.split(",")) == 11
    back = LegacyRow.from_csv(line)
    assert back == row


def test_legacy_row_rejects_bad_line():
    with pytest.raises(ValueError):
        LegacyRow.from_csv("a,b,c")


def test_result_row_roundtrip():
    row = ResultRow(
        timestamp=timestamp_now(),
        job_id="j",
        backend="jax",
        op="allreduce",
        nbytes=1 << 20,
        iters=100,
        run_id=2,
        n_devices=8,
        lat_us=12.5,
        algbw_gbps=3.1234,
        busbw_gbps=5.4661,
        time_ms=1.25,
    )
    back = ResultRow.from_csv(row.to_csv())
    assert back.op == "allreduce"
    assert back.nbytes == 1 << 20
    assert back.busbw_gbps == pytest.approx(5.4661)
    assert len(row.to_csv().split(",")) == len(RESULT_HEADER.split(","))


def test_timestamp_format():
    # reference format YYYY-MM-DD HH:MM:SS.mmm (mpi_perf.c:341-353)
    assert re.fullmatch(r"\d{4}-\d{2}-\d{2} \d{2}:\d{2}:\d{2}\.\d{3}", timestamp_now())


def test_rows_to_csv():
    rows = [_legacy_row(1), _legacy_row(2)]
    text = rows_to_csv(rows)
    assert text.count("\n") == 2  # header-less, like the reference
    with_header = rows_to_csv(rows, header=LEGACY_HEADER)
    assert with_header.splitlines()[0] == LEGACY_HEADER
