"""The legacy MPI profile scripts must render the reference's launch
configurations (SURVEY.md §2 C5-C8): transport env, CPU pinning, driver
flags.  DRY_RUN=1 makes each script print its mpirun command instead of
executing it, so the rendered line is testable without an MPI install."""

import pathlib
import subprocess

SCRIPTS = pathlib.Path(__file__).resolve().parent.parent / "scripts"


def _render(script, extra_env=None, tmp_path=None):
    group1 = tmp_path / "group1"
    group1.write_text("host1\n")
    env = {
        "PATH": "/usr/bin:/bin",
        "HOSTS": "host0,host1",
        "GROUP1": str(group1),
        "DRY_RUN": "1",
    }
    if extra_env:
        env.update(extra_env)
    res = subprocess.run(
        ["bash", str(SCRIPTS / script)], env=env,
        capture_output=True, text=True, timeout=30,
    )
    assert res.returncode == 0, res.stderr
    return res.stdout.strip()


def test_ici_profiles_pass_extra_args_through(tmp_path):
    """Round 5: extra args reach the CLI (a soak must be able to set
    --log-refresh-sec/--stats-every without editing the profile).  An
    unknown flag therefore makes the CLI itself exit 2 — proof the arg
    crossed the exec boundary instead of being silently dropped."""
    import os

    base = dict(os.environ)
    # ambient profile knobs from the developer's shell must not leak in
    # (run-ici-pair.sh's stale-ITERS guard, FENCE=... argparse choices)
    for knob in ("ITERS", "FENCE", "OP", "OPS", "DTYPE", "WINDOW", "MSGS",
                 "LOGDIR", "SWEEP", "RUNS", "BUFF", "DRY_RUN", "PAIRS"):
        base.pop(knob, None)
    base.update({"PYTHONPATH": str(SCRIPTS.parent), "JAX_PLATFORMS": "cpu",
                 "SWEEP": "4K", "RUNS": "1", "BUFF": "4K", "OPS": "ring"})
    # exec-style scripts surface the CLI's own exit 2 (argparse); the
    # loop-style ones catch per-invocation failures and exit 1
    per_script = {
        "run-ici-latency.sh": ({"ITERS": "1"}, 2),
        "run-ici-allreduce.sh": ({"ITERS": "1"}, 2),
        "run-ici-pair.sh": ({"MSGS": "2"}, 2),  # rejects a stale ITERS env
        "run-ici-monitor.sh": ({"ITERS": "1"}, 2),
        "run-ici-collectives.sh": ({"ITERS": "1", "OPS": "ring"}, 1),
        "run-ici-pallas.sh": ({"ITERS": "1", "PAIRS": "pl_ring:ring"}, 1),
    }
    for script, (extra, want_rc) in per_script.items():
        env = dict(base)
        env.update(extra)
        res = subprocess.run(
            ["bash", str(SCRIPTS / script), "--definitely-not-a-flag"],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert res.returncode == want_rc, \
            (script, res.returncode, res.stderr[-300:])
        assert "--definitely-not-a-flag" in res.stderr, script


def test_monitor_defaults_render_hbv3_profile(tmp_path):
    # reference run-hbv3.sh:22-28: 10 flows/node, TCP eth0 with the full
    # tuning block, cores 8-17, unidirectional, infinite runs
    line = _render("run-mpi-monitor.sh", tmp_path=tmp_path)
    assert "-np 20" in line and "ppr:10:node" in line
    assert "-x UCX_NET_DEVICES=eth0 -x UCX_TLS=tcp" in line
    for tuning in ("UCX_TCP_MAX_NUM_EPS=1", "UCX_TCP_TX_SEG_SIZE=1mb",
                   "UCX_TCP_RX_SEG_SIZE=1mb", "UCX_TCP_PUT_ENABLE=n",
                   "UCX_TCP_SNDBUF=1mb", "UCX_TCP_RCVBUF=1mb"):
        assert tuning in line
    assert "--cpu-list 8,9,10,11,12,13,14,15,16,17" in line
    assert "--use-hwthread-cpus --bind-to cpulist:ordered" in line
    assert "UCX_IB_SL" not in line
    # reference flag letters (mpi_perf.c:273-339): -f group1, -n count,
    # -i iters, -u 1, -l logfolder
    assert "-u 1" in line and "-r -1" in line and "-b 456131" in line
    assert "-f " in line and "-n 1 -i 10" in line and "-l /mnt/tcp-logs" in line


def test_monitor_ib_profile_renders_run_ib(tmp_path):
    # VERDICT r1 #3 "done" check: NET/TLS/SL env renders the reference's
    # run-ib.sh:22-27 line (IB RC mlx5_ib2:1, service level 1, odd cores)
    line = _render(
        "run-mpi-monitor.sh",
        {"NET": "mlx5_ib2:1", "TLS": "rc", "SL": "1",
         "CPU_LIST": "5,7,9,11,13,15,17,19,21,23"},
        tmp_path=tmp_path,
    )
    assert "-x UCX_NET_DEVICES=mlx5_ib2:1 -x UCX_TLS=rc" in line
    assert "-x UCX_IB_SL=1" in line
    assert "UCX_TCP_MAX_NUM_EPS" not in line  # TCP tuning only applies to tcp
    assert "--cpu-list 5,7,9,11,13,15,17,19,21,23" in line


def test_ib_wrapper_sets_the_ib_profile(tmp_path):
    line = _render("run-mpi-ib.sh", tmp_path=tmp_path)
    assert "-x UCX_NET_DEVICES=mlx5_ib2:1 -x UCX_TLS=rc" in line
    assert "-x UCX_IB_SL=1" in line
    assert "--cpu-list 5,7,9,11,13,15,17,19,21,23" in line


def test_t4_wrapper_keeps_tcp_moves_pinning(tmp_path):
    # reference run-t4.sh differs from run-hbv3.sh only in the CPU list
    line = _render("run-mpi-t4.sh", tmp_path=tmp_path)
    assert "-x UCX_NET_DEVICES=eth0 -x UCX_TLS=tcp" in line
    assert "UCX_TCP_PUT_ENABLE=n" in line
    assert "--cpu-list 6,7,8,9,10,11,12,13,14,15" in line


def test_monitor_pinning_can_be_disabled(tmp_path):
    line = _render("run-mpi-monitor.sh", {"CPU_LIST": ""}, tmp_path=tmp_path)
    assert "--cpu-list" not in line
    assert "--bind-to core" in line


def test_1_pair_renders_numactl_node0(tmp_path):
    # reference run-1-pair.sh:24-28: IB RC mlx5_ib0:1, numactl node 0,
    # windowed non-blocking 4 MiB x 5000 x 10
    line = _render("run-mpi-1-pair.sh", tmp_path=tmp_path)
    assert "-x UCX_NET_DEVICES=mlx5_ib0:1 -x UCX_TLS=rc" in line
    assert "numactl --cpunodebind=0 --membind 0" in line
    assert "-i 5000" in line and "-r 10" in line and "-b 4194304" in line
    assert "-x 1" in line  # windowed kernel, reference spelling
    assert "-l /mnt/tcp-logs" in line


def test_1_pair_numa_can_be_disabled(tmp_path):
    line = _render("run-mpi-1-pair.sh", {"NUMA_NODE": ""}, tmp_path=tmp_path)
    assert "numactl" not in line


def test_pallas_profile_dry_run_renders_every_pair(tmp_path):
    lines = _render("run-ici-pallas.sh", tmp_path=tmp_path).splitlines()
    # two commands per pair; hbm_stream is the shared counterpart of
    # three pallas kernels, so ops repeat but every family member shows
    ops = [ln.split("--op ")[1].split()[0] for ln in lines]
    assert len(ops) == 24
    for op in ("pl_hbm_read", "hbm_read", "pl_hbm_write", "hbm_write",
               "pl_hbm_copy", "hbm_stream", "pl_barrier", "barrier"):
        assert op in ops, op
