"""End-to-end fleet-health acceptance (ISSUE 1): a bounded ``--health``
daemon on a synthetic latency series with an injected 2x step regression
must emit a regression event for exactly the degraded (op, nbytes) point;
the rotating ``health-*.log`` rides one ingest pass (LocalDirBackend,
delete-after-success) and ``tpu-perf health <dir>`` renders the summary
table.  HealthMonitor-level behavior (windows, drops, exporter refresh)
is pinned here too — detector math lives in test_health_detect.py."""

import io
import json
import math

import pytest

from tpu_perf.cli import main
from tpu_perf.config import Options
from tpu_perf.driver import Driver
from tpu_perf.health import HealthConfig, HealthMonitor
from tpu_perf.health.events import read_events
from tpu_perf.ingest.pipeline import LocalDirBackend, run_all_ingest_passes
from tpu_perf.parallel import make_mesh


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh()


def _noisy(base, i, scale=1e-6):
    """Deterministic jitter so wall-clock samples never repeat exactly."""
    return base + scale * (math.sin(i * 12.9898) * 0.5 + 0.5)


def test_bounded_health_daemon_end_to_end(mesh, tmp_path, capsys):
    """The acceptance scenario: monitor --max-runs --health, fake clock,
    CPU backend, synthetic series, one injected 2x step on ONE point."""
    logdir = tmp_path / "logs"
    textfile = tmp_path / "metrics" / "tpu-perf.prom"
    opts = Options(
        op="ring", iters=1, num_runs=-1, sweep="8,32",
        logfolder=str(logdir), stats_every=10, log_refresh_sec=900,
        health=True, health_warmup=10, health_threshold=0.5,
        health_textfile=str(textfile),
    )
    clock = iter(range(10**6)).__next__  # fake clock: one tick per call
    drv = Driver(opts, mesh, err=io.StringIO(), clock=clock, max_runs=60)

    # synthetic measurement: the 32-byte point steps 2x after its 15th
    # sample; the 8-byte point stays clean for the whole soak
    seen = {}

    def synthetic_measure(built, built_hi):
        n = seen[built.nbytes] = seen.get(built.nbytes, 0) + 1
        base = 2.0 if built.nbytes == 32 and n > 15 else 1.0
        return _noisy(base, n)

    drv._measure = synthetic_measure
    drv.run()

    # exactly one regression event, for exactly the degraded point
    logs = sorted(logdir.glob("health-*.log"))
    assert len(logs) == 1
    events = read_events([str(p) for p in logs])
    assert [e.kind for e in events] == ["regression"]
    (ev,) = events
    assert (ev.op, ev.nbytes) == ("ring", 32)
    assert ev.severity in ("warning", "critical")
    assert ev.observed > ev.baseline * 1.4  # EWMA near the 2x level
    assert ev.job_id == opts.uuid
    assert ev.rank == 0
    assert ev.window == (ev.run_id - 1) // opts.stats_every

    # the exporter textfile holds both points' gauges and pins the
    # degraded point's standing severity
    text = textfile.read_text()
    assert 'tpu_perf_health_lat_p50_us{op="ring",nbytes="8"' in text
    assert 'tpu_perf_health_lat_p50_us{op="ring",nbytes="32"' in text
    assert ('tpu_perf_health_point_severity{op="ring",nbytes="32",'
            'dtype="float32"}') in text
    sev = {
        line.rsplit(" ", 1)[0]: float(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("tpu_perf_health_point_severity")
    }
    assert sev['tpu_perf_health_point_severity{op="ring",nbytes="8",'
               'dtype="float32"}'] == 0
    assert sev['tpu_perf_health_point_severity{op="ring",nbytes="32",'
               'dtype="float32"}'] >= 1
    assert 'tpu_perf_health_events_total{kind="regression"} 1' in text

    # one ingest pass sweeps all three file families; health logs are
    # picked up and deleted (delete-only-after-success)
    sink = tmp_path / "sink"
    n = run_all_ingest_passes(
        str(logdir), skip_newest=0, backend=LocalDirBackend(str(sink))
    )
    assert n >= 3  # tcp-*, tpu-*, health-*
    assert not list(logdir.glob("health-*.log"))
    assert len(list(sink.glob("health-*.log"))) == 1

    # the health subcommand replays the ingested events into the table
    rc = main(["health", str(sink)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "| severity |" in out and "| regression |" in out
    assert "| ring |" in out and "| 32 |" in out

    # and --format json round-trips the raw events
    rc = main(["health", str(sink), "--format", "json"])
    assert rc == 0
    raw = json.loads(capsys.readouterr().out)
    assert len(raw) == 1 and raw[0]["kind"] == "regression"


def test_health_subcommand_no_logs(tmp_path, capsys):
    rc = main(["health", str(tmp_path)])
    assert rc == 1
    assert "no health logs" in capsys.readouterr().err


def test_health_subcommand_tolerates_torn_final_line(tmp_path, capsys):
    """A live daemon's current log can end mid-append (or a hard kill
    tears the last line): the replay must still render every intact
    event — incident time is exactly when the operator runs this."""
    ev = ('{"timestamp": "ts", "job_id": "j", "kind": "regression", '
          '"severity": "warning", "op": "ring", "nbytes": 32, '
          '"dtype": "float32", "run_id": 7, "window": 0, '
          '"observed": 2.0, "baseline": 1.0}')
    (tmp_path / "health-u-0-x.log").write_text(ev + '\n{"kind": "regre')
    rc = main(["health", str(tmp_path)])
    assert rc == 0
    captured = capsys.readouterr()
    assert "torn final line" in captured.err
    assert "| regression |" in captured.out  # the intact event rendered


def test_health_subcommand_midfile_corruption_fails(tmp_path, capsys):
    # corruption ANYWHERE but the final line is not a live-append state:
    # diagnostic + exit 1, never a silently thinned-out replay
    (tmp_path / "health-u-0-x.log").write_text('{"kind": "regre\n\n')
    rc = main(["health", str(tmp_path)])
    assert rc == 1
    assert "bad health event log" in capsys.readouterr().err


def test_health_subcommand_reads_active_open_log(tmp_path, capsys):
    # the ACTIVE lazy log (health-*.log.open) holds the events judged
    # since the last rotation; a dir replay must include them
    ev = ('{"timestamp": "ts", "job_id": "j", "kind": "spike", '
          '"severity": "warning", "op": "ring", "nbytes": 32, '
          '"dtype": "float32", "run_id": 7, "window": 0, '
          '"observed": 2.0, "baseline": 1.0}')
    (tmp_path / "health-u-0-x.log.open").write_text(ev + "\n")
    rc = main(["health", str(tmp_path)])
    assert rc == 0
    assert "| spike |" in capsys.readouterr().out


def test_monitor_cli_accepts_health_and_max_runs(eight_devices, tmp_path):
    """The CLI surface of the satellites: a REAL bounded --health daemon
    run through `tpu-perf monitor` exits cleanly and leaves rotating
    logs behind (no fake clock — real CPU timings, no events expected
    inside the warm-up window)."""
    rc = main([
        "monitor", "--op", "ring", "-b", "32", "-i", "1",
        "--max-runs", "4", "--health", "--health-warmup", "30",
        "-l", str(tmp_path),
    ])
    assert rc == 0
    assert list(tmp_path.glob("tcp-*.log"))  # the daemon really ran
    # the event log is lazy: a clean run leaves NO health-*.log behind
    # (no empty-file churn through the ingest backend)
    assert not list(tmp_path.glob("health-*.log"))


# --- HealthMonitor unit behavior (windows, drops, exporter refresh) ------


def _monitor(tmp_path=None, **cfg):
    return HealthMonitor(
        HealthConfig(**cfg), job_id="job", dtype="float32", stats_every=10,
        err=io.StringIO(),
    )


def test_monitor_capture_loss_event_at_heartbeat():
    mon = _monitor(drop_rate=0.25)
    for i in range(6):
        mon.observe("ring", 64, 1, 8, i + 1, _noisy(1.0, i))
    for i in range(4):
        mon.observe_drop("ring", 7 + i)
    events = mon.heartbeat(10)
    assert [e.kind for e in events] == ["capture_loss"]
    (ev,) = events
    assert ev.op == "ring" and ev.nbytes == 0  # op-level: all sizes
    assert ev.observed == pytest.approx(0.4)
    # the boundary heartbeat (run 10) carries ITS window's id: runs 1-10
    # and this capture_loss event all join on window 0
    assert ev.window == 0
    # the window counters reset: a clean next window emits nothing
    for i in range(10):
        mon.observe("ring", 64, 1, 8, 11 + i, _noisy(1.0, 100 + i))
    assert mon.heartbeat(20) == []


def test_drop_rate_gauge_resets_for_absent_ops():
    """The gauge names the LAST completed window: an op absent from the
    next window (round-robin points vs. small stats_every) had no drops
    in it — a finished capture-loss incident must not stay exported."""
    mon = _monitor(drop_rate=0.25)
    for i in range(10):
        mon.observe_drop("ring", i + 1)
    mon.heartbeat(10)
    assert mon._drop_rates["ring"] == 1.0
    for i in range(10):
        mon.observe("exchange", 64, 1, 8, 11 + i, _noisy(1.0, i))
    mon.heartbeat(20)  # ring absent from this window
    assert mon._drop_rates["ring"] == 0.0
    assert mon._drop_rates["exchange"] == 0.0


def test_close_flushes_final_partial_window(tmp_path):
    """A bounded run shorter than stats_every never reaches a heartbeat
    boundary; close() must still judge the final window's capture loss
    and land the drop-rate gauge in the textfile."""
    textfile = tmp_path / "tpu-perf.prom"
    mon = HealthMonitor(
        HealthConfig(drop_rate=0.25), job_id="job", dtype="float32",
        stats_every=1000, textfile=str(textfile), err=io.StringIO(),
    )
    for i in range(3):
        mon.observe("ring", 64, 1, 8, i + 1, _noisy(1.0, i))
    for i in range(3):
        mon.observe_drop("ring", 4 + i)
    mon.close()
    assert mon.events_total == {"capture_loss": 1}
    text = textfile.read_text()
    assert 'tpu_perf_health_drop_rate{op="ring"} 0.5' in text
    assert 'tpu_perf_health_events_total{kind="capture_loss"} 1' in text


def test_close_without_observations_is_clean(tmp_path):
    mon = HealthMonitor(
        HealthConfig(), job_id="job", dtype="float32",
        textfile=str(tmp_path / "tpu-perf.prom"), err=io.StringIO(),
    )
    mon.close()
    assert mon.events_total == {}


def test_spike_does_not_pin_severity_gauge():
    mon = _monitor(warmup=10)
    for i in range(50):
        mon.observe("ring", 64, 1, 8, i + 1, _noisy(1.0, i))
    mon.observe("ring", 64, 1, 8, 51, 10.0)  # candidate spike
    events = mon.observe("ring", 64, 1, 8, 52, _noisy(1.0, 52))
    assert [e.kind for e in events] == ["spike"]
    (row,) = mon.snapshot()
    assert row.severity == "info"  # transient: the gauge is not pinned


def test_regression_pins_gauge_until_recovery():
    mon = _monitor(warmup=10)
    for i in range(20):
        mon.observe("ring", 64, 1, 8, i + 1, _noisy(1.0, i))
    for i in range(20, 40):
        mon.observe("ring", 64, 1, 8, i + 1, _noisy(2.0, i))
    (row,) = mon.snapshot()
    assert row.severity in ("warning", "critical")  # standing regression
    for i in range(40, 80):
        mon.observe("ring", 64, 1, 8, i + 1, _noisy(1.0, i))
    (row,) = mon.snapshot()
    assert row.severity == "info"  # released by the recovery


def test_monitor_snapshot_gauges():
    mon = _monitor(warmup=5)
    for i in range(20):
        mon.observe("allreduce", 1024, 2, 8, i + 1, _noisy(1.0, i))
    (row,) = mon.snapshot()
    assert (row.op, row.nbytes, row.dtype) == ("allreduce", 1024, "float32")
    assert row.samples == 20
    assert row.lat_p50_us == pytest.approx(5e5, rel=0.01)  # 1 s / 2 iters
    assert row.busbw_gbps > 0
    assert row.severity == "info"
