"""Device-fused measurement loop (ISSUE 7): the `fused` fence.

One dispatch per sweep point (an outer fori_loop carrying the donated
example buffers), per-run timings recovered from the device trace or
from chunked sub-dispatch means, chunk-relayed adaptive stopping, and
the satellites (p50 stop statistic, span sampling, HBM depth cap,
old-row parsing under the new fence value)."""

from __future__ import annotations

import glob
import gzip
import json
import math
import os

import pytest

from tpu_perf.config import Options
from tpu_perf.timing import (
    FENCE_MODES, FusedRunner, fused_chunk_plan, resolve_fence,
)


@pytest.fixture(scope="module")
def mesh(eight_devices):
    from tpu_perf.parallel import make_mesh

    return make_mesh()


# --- plan / config surface ---------------------------------------------


def test_fused_is_a_fence_mode():
    assert "fused" in FENCE_MODES
    assert resolve_fence("fused") == "fused"  # explicit, never auto
    # auto keeps resolving to a per-run fence (trace/slope) — fused
    # changes the dispatch structure and stays opt-in
    assert resolve_fence("auto") in ("trace", "slope")
    Options(fence="fused")  # validates


def test_fused_chunk_plan_shapes():
    assert fused_chunk_plan(10, 1) == (10,)
    assert fused_chunk_plan(10, 3) == (4, 3, 3)
    assert fused_chunk_plan(10, 5) == (2, 2, 2, 2, 2)
    assert fused_chunk_plan(3, 8) == (1, 1, 1)  # chunks capped at runs
    assert sum(fused_chunk_plan(50, 7)) == 50
    assert len(set(fused_chunk_plan(50, 7))) <= 2  # at most two programs
    with pytest.raises(ValueError):
        fused_chunk_plan(0, 1)


def test_options_validate_fused_knobs():
    with pytest.raises(ValueError):
        Options(fused_chunks=-1, fence="fused")
    with pytest.raises(ValueError):
        Options(ci_statistic="p99", ci_rel=0.05)
    with pytest.raises(ValueError):
        Options(spans_sample=0)
    # inert combinations are loud errors, never silent no-ops (the
    # --max-runs-without---ci-rel precedent)
    with pytest.raises(ValueError):
        Options(fused_chunks=4)                    # fence is not fused
    with pytest.raises(ValueError):
        Options(fused_chunks=4, fence="fused", num_runs=-1)  # daemon
    with pytest.raises(ValueError):
        Options(ci_statistic="p50")                # nothing consults it
    Options(fence="fused", fused_chunks=4, ci_rel=0.05,
            ci_statistic="p50", spans_sample=5, num_runs=50)


def test_fused_plan_for_policy():
    from tpu_perf.runner import fused_plan_for

    # fixed budget: ONE dispatch per point (the headline shape)
    assert fused_plan_for(Options(num_runs=20, fence="fused")) == (20,)
    # adaptive: one vote per chunk, first no earlier than min_runs
    plan = fused_plan_for(Options(num_runs=20, fence="fused"),
                          budget=20, min_runs=5)
    assert len(plan) == 4 and sum(plan) == 20
    # explicit --fused-chunks overrides both
    assert fused_plan_for(
        Options(num_runs=20, fence="fused", fused_chunks=2)) == (10, 10)
    assert fused_plan_for(
        Options(num_runs=20, fence="fused", fused_chunks=2),
        budget=20, min_runs=5) == (10, 10)


# --- the fused program -------------------------------------------------


def test_build_fused_step_validation_and_hint(mesh):
    from tpu_perf.compilepipe import aot_compile
    from tpu_perf.ops import build_fused_step, build_op

    built = build_op("ring", mesh, 256, 2)
    with pytest.raises(ValueError):
        build_fused_step(built, 0)
    prog = build_fused_step(built, 3, donate=False)
    # the jit name is the trace extractor's hint (it becomes the
    # device-lane module name jit_tpuperf_fused_<op>), and the per-run
    # fences' hint tpuperf_ring is NOT a substring of it — the two
    # extractors can never steal each other's module events
    module_line = prog.lower(built.example_input).as_text().splitlines()[0]
    assert "jit_tpuperf_fused_ring" in module_line
    assert "tpuperf_ring" not in module_line.replace(
        "tpuperf_fused_ring", "")
    # an AOT-compiled inner step cannot be traced through: loud error
    compiled = aot_compile(built)
    with pytest.raises(ValueError):
        build_fused_step(compiled, 2)


def test_fused_matches_unfused_numerics(mesh):
    """reps fused executions == reps sequential step calls, bit-for-bit
    (the loop carries the buffer; nothing is elided or reordered)."""
    import numpy as np

    from tpu_perf.ops import build_fused_step, build_op

    built = build_op("hbm_stream", mesh, 1024, 2)
    prog = build_fused_step(built, 3, donate=False)
    want = built.example_input
    for _ in range(3):
        want = built.step(want)
    got = prog(built.example_input)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_donation_round_trip(mesh):
    """The working buffer round-trips through every chunk dispatch while
    the (possibly canon-shared) example input stays intact — the runner
    copies before the first donation."""
    import warnings

    import numpy as np

    from tpu_perf.ops import build_op
    from tpu_perf.runner import build_fused_point

    built = build_op("hbm_stream", mesh, 1024, 2)
    before = np.asarray(built.example_input).copy()
    fp = build_fused_point(built, (2, 2), donate=True)
    runner = FusedRunner(fp, built, use_trace=False)
    with warnings.catch_warnings():
        # CPU backends may warn that donation is unimplemented; the
        # round-trip contract (fresh copy in, carry out) holds anyway
        warnings.simplefilter("ignore")
        runner.warm()
        s1, _, _ = runner.chunk(2)
        s2, _, _ = runner.chunk(2)
    assert len(s1) == len(s2) == 2 and all(t > 0 for t in s1 + s2)
    assert runner.dispatches == 2  # warm dispatch not counted
    np.testing.assert_array_equal(np.asarray(built.example_input), before)


def test_fused_runner_chunk_mean_math(mesh):
    """Trace-free fallback: per-run samples are exactly the chunk wall
    divided over its runs (deterministic via an injected clock)."""
    from tpu_perf.ops import build_op
    from tpu_perf.runner import build_fused_point

    built = build_op("ring", mesh, 256, 1)
    fp = build_fused_point(built, (4,))
    ticks = iter(range(1000))

    def clock():  # 10 ms per clock read
        return next(ticks) * 0.010

    runner = FusedRunner(fp, built, use_trace=False, perf_clock=clock)
    runner.warm()
    samples, t0, wall = runner.chunk(4)
    # chunk() reads the clock twice around the dispatch: wall = 10 ms
    assert wall == pytest.approx(0.010)
    assert samples == pytest.approx([0.010 / 4] * 4)
    assert runner.dispatches == 1


def test_fused_trace_path_latches_off_on_cpu(mesh, capsys):
    """use_trace=True on a runtime with no device lanes: the first
    chunk's capture fails TraceUnavailable, latches the trace path off
    for the point, and the chunk still returns honest host means."""
    from tpu_perf.ops import build_op
    from tpu_perf.runner import build_fused_point

    built = build_op("ring", mesh, 256, 1)
    fp = build_fused_point(built, (2, 2))
    runner = FusedRunner(fp, built, use_trace=True)
    runner.warm()
    samples, _, wall = runner.chunk(2)
    assert runner.use_trace is False
    assert samples == pytest.approx([wall / 2] * 2)
    assert runner.dispatches == 1  # the captured dispatch still counted


def test_fused_and_block_stats_agree(mesh):
    """Fence conformance, the verification spine: the same kernel timed
    by the block fence (one fenced dispatch per run) and the fused loop
    must tell the same story.  A compute-heavy point keeps the per-run
    dispatch overhead small relative to the kernel, so the p50s agree
    within a generous CPU-CI band (the tight 1.25x bound is ci.sh 0g's
    job, on a quieter profile); a fused loop that XLA elided would read
    orders of magnitude low and fail the floor."""
    from tpu_perf.metrics import percentile
    from tpu_perf.runner import run_point

    def p50(fence):
        opts = Options(op="hbm_stream", iters=8, num_runs=4, fence=fence)
        pt = run_point(opts, mesh, 1 << 20)
        assert len(pt.times.samples) == 4
        return percentile(pt.times.samples, 50)

    block, fused = p50("block"), p50("fused")
    assert fused <= 2.5 * block
    assert fused >= block / 4


# --- traceparse: iteration splitting -----------------------------------


def _write_capture(tmp_path, events):
    """A minimal trace-viewer capture with one device lane."""
    session = tmp_path / "plugins" / "profile" / "2026_08_03_00_00_00"
    os.makedirs(session)
    meta = [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "thread_name", "pid": 7, "tid": 1,
         "args": {"name": "XLA Modules"}},
    ]
    body = [
        {"ph": "X", "pid": 7, "tid": 1, "ts": ts, "dur": dur_us,
         "name": name}
        for ts, dur_us, name in events
    ]
    with gzip.open(session / "host.trace.json.gz", "wt") as fh:
        json.dump({"traceEvents": meta + body}, fh)
    return str(tmp_path)


def test_fused_run_durations_even_split(tmp_path):
    """One module event (the standard XLA shape: the whole fused program
    is a single launch) splits evenly over the runs — the device-side
    mean, zero host time in any sample."""
    from tpu_perf.traceparse import fused_run_durations

    d = _write_capture(tmp_path,
                       [(10.0, 400.0, "jit_tpuperf_fused_ring(f1)")])
    durs = fused_run_durations(d, "tpuperf_fused_ring", 4)
    assert durs == pytest.approx([100e-6] * 4)


def test_fused_run_durations_per_iteration_events(tmp_path):
    """A runtime that records one device event per loop iteration hands
    back true per-run durations, variance preserved, in launch order."""
    from tpu_perf.traceparse import fused_run_durations

    d = _write_capture(tmp_path, [
        (10.0, 90.0, "jit_tpuperf_fused_ring(f1)"),
        (110.0, 110.0, "jit_tpuperf_fused_ring(f1)"),
        (230.0, 100.0, "jit_tpuperf_fused_ring(f1)"),
    ])
    durs = fused_run_durations(d, "tpuperf_fused_ring", 3)
    assert durs == pytest.approx([90e-6, 110e-6, 100e-6])


def test_fused_run_durations_bad_count_and_validation(tmp_path):
    from tpu_perf.traceparse import TraceParseError, fused_run_durations

    d = _write_capture(tmp_path, [
        (10.0, 90.0, "jit_tpuperf_fused_ring(f1)"),
        (110.0, 110.0, "jit_tpuperf_fused_ring(f1)"),
    ])
    with pytest.raises(TraceParseError):
        fused_run_durations(d, "tpuperf_fused_ring", 4)  # 2 != 1, != 4
    with pytest.raises(ValueError):
        fused_run_durations(d, "tpuperf_fused_ring", 0)


def test_fused_run_durations_no_device_lane(tmp_path):
    from tpu_perf.traceparse import TraceUnavailableError, fused_run_durations

    session = tmp_path / "plugins" / "profile" / "x"
    os.makedirs(session)
    with gzip.open(session / "host.trace.json.gz", "wt") as fh:
        json.dump({"traceEvents": []}, fh)
    with pytest.raises(TraceUnavailableError):
        fused_run_durations(str(tmp_path), "tpuperf_fused_ring", 2)


# --- chunk-relayed adaptive stopping -----------------------------------


def test_observe_chunk_counts_runs_but_one_moment_per_chunk():
    from tpu_perf.adaptive import AdaptiveConfig, PointController

    c = PointController(AdaptiveConfig(min_runs=5, max_runs=50))
    c.observe_chunk(1e-3, 5)
    assert c.taken == 5 and c.welford.n == 1
    assert math.isinf(c.ci_rel())  # one chunk mean cannot shape a CI
    c.observe_chunk(1.01e-3, 5)
    assert c.taken == 10 and c.welford.n == 2
    assert math.isfinite(c.ci_rel())
    c.observe_chunk(None, 5)  # a dropped chunk consumes budget only
    assert c.dropped == 5 and c.welford.n == 2
    with pytest.raises(ValueError):
        c.observe_chunk(1e-3, 0)


def test_chunk_votes_lockstep_across_simulated_ranks():
    """Two simulated ranks under chunked observation: one vote per
    chunk, unanimous-stop, both ranks execute the same chunk count."""
    from tpu_perf.adaptive import AdaptiveConfig, PointController

    cfg = AdaptiveConfig(ci_rel=0.05, min_runs=5, max_runs=40)
    locals_: dict[str, bool] = {}

    def vote_for(rank):
        def vote(local):
            assert local == locals_[rank]
            return all(locals_.values())
        return vote

    a = PointController(cfg, n_hosts=2, vote=vote_for("a"))
    b = PointController(cfg, n_hosts=2, vote=vote_for("b"))
    # rank a's chunk means converge by chunk 2; rank b's first pair is
    # too spread, tightening only by chunk 4 — the unanimous vote makes
    # both ranks run 4 chunks
    means_a = [1e-3, 1.0001e-3, 1.0001e-3, 1.0002e-3]
    means_b = [1.50e-3, 1.53e-3, 1.515e-3, 1.52e-3]
    runs = 0
    stops = []
    a_alone = None
    for ma, mb in zip(means_a, means_b):
        runs += 5
        a.observe_chunk(ma, 5)
        b.observe_chunk(mb, 5)
        locals_.update(a=a._local_stop(runs), b=b._local_stop(runs))
        if locals_["a"] and a_alone is None:
            a_alone = runs
        sa, sb = a.should_stop(runs), b.should_stop(runs)
        assert sa == sb, "ranks diverged on the chunk vote"
        stops.append(sa)
        if sa:
            break
    assert stops[-1] is True and runs < 40
    assert a.stopped_at == b.stopped_at == runs
    assert a_alone is not None and runs > a_alone  # b's spread held a back


def test_run_point_fused_adaptive_early_stops(mesh, monkeypatch):
    """run_point under the fused fence + adaptive config: chunk-relayed
    stopping, deterministic via a planted chunk series."""
    import tpu_perf.timing as timing
    from tpu_perf.adaptive import AdaptiveConfig
    from tpu_perf.runner import run_point

    counts: dict[str, int] = {}

    def planted(self, reps):
        n = counts[self.point.op] = counts.get(self.point.op, 0) + 1
        mean = 1e-3 * (1.0 + 0.001 * (n % 3))
        return [mean] * reps, 0.0, mean * reps

    monkeypatch.setattr(timing.FusedRunner, "chunk", planted)
    opts = Options(op="ring", iters=1, num_runs=40, buff_sz=256,
                   fence="fused")
    pt = run_point(opts, mesh, 256,
                   adaptive=AdaptiveConfig(ci_rel=0.05, min_runs=5,
                                           max_runs=40))
    assert pt.runs_requested == 40
    assert len(pt.times.samples) < 40          # early-stopped
    assert len(pt.times.samples) % 5 == 0      # whole chunks only
    assert 0 < pt.ci_rel <= 0.05
    assert pt.adaptive["saved"] > 0


# --- the p50 stop statistic --------------------------------------------


def test_p50_statistic_config_and_minimum_n():
    from tpu_perf.adaptive import AdaptiveConfig, PointController

    with pytest.raises(ValueError):
        AdaptiveConfig(statistic="p42")
    c = PointController(AdaptiveConfig(statistic="p50", min_runs=2,
                                       max_runs=50))
    for t in [1e-3] * 5:
        c.observe(t)
    # the order-statistic bracket does not fit inside n=5 at 95%
    assert math.isinf(c.ci_rel())
    c.observe(1e-3)
    # n=6: the extreme order statistics bracket the median (a valid,
    # conservative >=95% interval); identical samples give width 0
    assert c.ci_rel() == 0.0
    assert c.summary()["statistic"] == "p50"


def test_p50_stops_under_heavy_tail_where_mean_does_not():
    """Satellite: a seeded pareto-tail series (planted via the fault
    machinery, the same shapes chaos soaks inject) — the median's
    order-statistic CI converges while the mean's t-CI is held open by
    the tail draws."""
    from tpu_perf.adaptive import AdaptiveConfig, PointController
    from tpu_perf.faults import FaultInjector
    from tpu_perf.faults.spec import FaultSpec

    inj = FaultInjector(
        [FaultSpec(kind="jitter", shape="pareto", magnitude=0.45, start=1)],
        seed=7, stats_every=1000,
    )
    series = [inj.apply("ring", 8, i, 1e-3) for i in range(1, 61)]
    assert max(series) / min(series) > 3  # the tail is real

    def drive(statistic):
        c = PointController(AdaptiveConfig(ci_rel=0.10, min_runs=9,
                                           max_runs=60,
                                           statistic=statistic))
        for runs, t in enumerate(series, start=1):
            c.observe(t)
            if c.should_stop(runs):
                return runs
        return len(series)

    p50_runs = drive("p50")
    mean_runs = drive("mean")
    assert p50_runs < mean_runs
    assert p50_runs < 60  # the median CI actually converged


def test_p50_downgrades_loudly_under_fused(mesh, monkeypatch, capsys):
    """A median of chunk means is not the run median: --ci-statistic
    p50 under --fence fused falls back to the mean statistic with a
    loud note, never stamping rows with a median verdict that was
    never computed."""
    import tpu_perf.timing as timing
    from tpu_perf.driver import Driver

    counts: dict[str, int] = {}

    def planted(self, reps):
        n = counts[self.point.op] = counts.get(self.point.op, 0) + 1
        mean = 1e-3 * (1.0 + 0.001 * (n % 3))
        return [mean] * reps, 0.0, mean * reps

    monkeypatch.setattr(timing.FusedRunner, "chunk", planted)
    opts = Options(op="ring", iters=1, num_runs=30, buff_sz=256,
                   fence="fused", ci_rel=0.05, min_runs=5,
                   ci_statistic="p50")
    drv = Driver(opts, mesh)
    assert drv._adaptive_cfg.statistic == "mean"
    assert "p50 is not available" in capsys.readouterr().err
    rows = drv.run()
    assert 0 < len(rows) < 30  # the controller still ran (on the mean)


def test_fused_trace_latches_off_after_repeated_parse_failures(
        mesh, monkeypatch, capsys):
    """A runtime that STABLY records an unsplittable module-event shape
    must not pay a profiler capture (plus a stderr line) per chunk
    forever: two consecutive parse failures latch the trace path off."""
    import tpu_perf.traceparse as traceparse
    from tpu_perf.ops import build_op
    from tpu_perf.runner import build_fused_point
    from tpu_perf.traceparse import TraceParseError

    def bad_parse(trace_dir, hint, n):
        raise TraceParseError("2 events for a 4-run program")

    monkeypatch.setattr(traceparse, "fused_run_durations", bad_parse)
    built = build_op("ring", mesh, 256, 1)
    fp = build_fused_point(built, (2, 2, 2))
    runner = FusedRunner(fp, built, use_trace=True)
    runner.warm()
    runner.chunk(2)
    assert runner.use_trace is True   # one failure could be transient
    runner.chunk(2)
    assert runner.use_trace is False  # two in a row: latched off
    assert "latched off" in capsys.readouterr().err
    samples, _, wall = runner.chunk(2)  # no capture attempted anymore
    assert samples == pytest.approx([wall / 2] * 2)
    assert runner.dispatches == 3


# --- driver integration ------------------------------------------------


def test_driver_fused_one_dispatch_per_point_and_sidecar(mesh, tmp_path):
    from tpu_perf.driver import Driver

    folder = str(tmp_path)
    opts = Options(op="ring,exchange", sweep="8,4096", iters=1, num_runs=4,
                   fence="fused", logfolder=folder)
    drv = Driver(opts, mesh)
    rows = drv.run()
    assert len(rows) == 4 * 4  # 4 points x 4 runs
    assert all(r.time_ms > 0 for r in rows)
    # the headline claim, counter-asserted: fixed budget => one measured
    # dispatch per sweep point
    assert drv.fused_totals == {"points": 4, "measure_dispatches": 4,
                                "runs": 16}
    (sidecar,) = glob.glob(os.path.join(folder, "phase-*.json"))
    with open(sidecar) as fh:
        data = json.load(fh)
    assert data["fused"]["measure_dispatches"] == data["fused"]["points"] == 4
    assert data["fused"]["plan"] == [4]
    # rows round-trip the rotating log
    from tpu_perf.schema import ResultRow

    (log,) = glob.glob(os.path.join(folder, "tpu-*.log"))
    with open(log) as fh:
        parsed = [ResultRow.from_csv(ln) for ln in fh.read().splitlines()]
    assert len(parsed) == 16


def test_driver_fused_adaptive_no_bypass(mesh, monkeypatch, capsys):
    """--ci-rel under the fused fence must RUN (chunk-relayed), not
    loudly bypass like the trace fence."""
    import tpu_perf.timing as timing
    from tpu_perf.driver import Driver

    counts: dict[str, int] = {}

    def planted(self, reps):
        n = counts[self.point.op] = counts.get(self.point.op, 0) + 1
        mean = 1e-3 * (1.0 + 0.001 * (n % 3))
        return [mean] * reps, 0.0, mean * reps

    monkeypatch.setattr(timing.FusedRunner, "chunk", planted)
    opts = Options(op="ring", iters=1, num_runs=30, buff_sz=256,
                   fence="fused", ci_rel=0.05, min_runs=5)
    drv = Driver(opts, mesh)
    rows = drv.run()
    err = capsys.readouterr().err
    assert "bypassed" not in err
    assert "adaptive: ring" in err  # the early-stop narration fired
    assert 0 < len(rows) < 30
    final = max(rows, key=lambda r: r.run_id)
    assert final.runs_requested == 30 and 0 < final.ci_rel <= 0.05
    assert drv.adaptive_totals["runs_saved"] > 0
    # the plan chunked at min_runs granularity: 6 chunks of 5
    assert drv._fused_plan == (5, 5, 5, 5, 5, 5)


def test_driver_daemon_fused_one_dispatch_per_visit(mesh):
    from tpu_perf.driver import Driver

    opts = Options(op="ring", iters=1, num_runs=-1, buff_sz=4096,
                   fence="fused")
    drv = Driver(opts, mesh, max_runs=5)
    drv.run()
    assert drv._fused_plan == (1,)
    assert drv.fused_totals["measure_dispatches"] == 5
    assert drv.fused_totals["runs"] == 5


def test_driver_fused_run_spans_carry_real_geometry(mesh, tmp_path):
    """PR-6 follow-on: batched-capture runs get per-run span geometry
    from the extractor instead of near-zero host windows, and every row
    still joins exactly one enclosing run span."""
    from tpu_perf.driver import Driver
    from tpu_perf.trace import join_completeness

    opts = Options(op="ring", iters=1, num_runs=4, buff_sz=4096,
                   fence="fused", spans=True, logfolder=str(tmp_path))
    drv = Driver(opts, mesh)
    rows = drv.run()
    runs = [r for r in drv.tracer.records if r["kind"] == "run"]
    assert len(runs) == 4
    assert all(r["dur_ns"] > 0 for r in runs)
    # laid consecutively inside the chunk's host window
    starts = sorted(int(r["t_start_ns"]) for r in runs)
    assert starts == [int(r["t_start_ns"]) for r in runs]
    assert all(r.span_id for r in rows)
    assert join_completeness(drv.tracer.records, rows=rows) == []


def test_fused_row_csv_round_trip_and_old_rows_still_parse(mesh):
    """Old-row parsing with the new fence value in play: rows produced
    under --fence fused render/parse like any other, and the historical
    12/13/15/18-field rows still load."""
    from tpu_perf.runner import run_point
    from tpu_perf.schema import ResultRow

    opts = Options(op="ring", iters=1, num_runs=2, buff_sz=256,
                   fence="fused")
    pt = run_point(opts, mesh, 256)
    for row in pt.rows("job-1"):
        # CSV formatting rounds; the parsed form must be a fixed point
        once = ResultRow.from_csv(row.to_csv())
        assert ResultRow.from_csv(once.to_csv()) == once
        assert once.op == "ring" and once.time_ms > 0
    old = ("2026-01-01 00:00:00.000,j,jax,ring,8,10,1,8,"
           "1.000,0.1,0.1,0.001")
    assert ResultRow.from_csv(old).dtype == "float32"         # 12 fields
    assert ResultRow.from_csv(old + ",bfloat16").mode == "oneshot"  # 13
    assert ResultRow.from_csv(old + ",bfloat16,daemon,0.5").runs_taken == 0
    assert ResultRow.from_csv(
        old + ",bfloat16,daemon,0.5,30,7,0.04").ci_rel == 0.04  # 18


# --- precompile pipeline -----------------------------------------------


def test_compile_spec_fused_field_keys_programs():
    from tpu_perf.compilepipe import CompileSpec

    a = CompileSpec.make("ring", 8, 2)
    b = CompileSpec.make("ring", 8, 2, fused=(5, 5, 4))
    c = CompileSpec.make("ring", 8, 2, fused=(4, 5))
    assert a != b and b == c  # sorted-distinct normalization
    assert len({a, b, c}) == 2


def test_run_sweep_fused_precompiled_matches_serial(mesh):
    from tpu_perf.runner import run_sweep

    def keys(precompile):
        opts = Options(op="ring", sweep="8,64,4096", iters=1, num_runs=3,
                       fence="fused", precompile=precompile)
        return [
            (p.op, p.nbytes, p.iters, len(p.times.samples))
            for p in run_sweep(opts, mesh)
        ]

    assert keys(0) == keys(2)


def test_driver_fused_with_precompile_counts_one_dispatch(mesh):
    from tpu_perf.driver import Driver

    opts = Options(op="ring", sweep="8,4096", iters=1, num_runs=3,
                   fence="fused", precompile=2)
    drv = Driver(opts, mesh)
    rows = drv.run()
    assert len(rows) == 6
    assert drv.fused_totals == {"points": 2, "measure_dispatches": 2,
                                "runs": 6}


# --- span sampling (--spans-sample) ------------------------------------


def test_span_sampling_keeps_anchors_and_every_nth_tree():
    from tpu_perf.spans import SpanTracer

    clock = iter(range(10000))
    tr = SpanTracer("job", retain=True, sample=3,
                    perf_ns=lambda: next(clock))
    for run_id in range(1, 7):
        with tr.run_span(run_id, op="ring"):
            with tr.span("measure", run_id=run_id):
                pass
            tr.emit("inject", 0, 1, run_id=run_id)   # always kept
        tr.emit("rotate", 0, 1, run_id=run_id)       # always kept
    kinds = {}
    for r in tr.records:
        kinds.setdefault(r["kind"], []).append(r["attrs"].get("run_id"))
    assert kinds["run"] == [1, 2, 3, 4, 5, 6]        # anchors survive
    assert kinds["measure"] == [1, 4]                # every 3rd tree
    assert kinds["inject"] == [1, 2, 3, 4, 5, 6]
    assert kinds["rotate"] == [1, 2, 3, 4, 5, 6]
    with pytest.raises(ValueError):
        SpanTracer("job", sample=0)


def test_span_sampling_keeps_error_spans():
    from tpu_perf.spans import SpanTracer

    clock = iter(range(10000))
    tr = SpanTracer("job", retain=True, sample=100,
                    perf_ns=lambda: next(clock))
    with pytest.raises(RuntimeError):
        with tr.run_span(2, op="ring"):
            with tr.span("measure", run_id=2):
                raise RuntimeError("boom")
    measures = [r for r in tr.records if r["kind"] == "measure"]
    assert len(measures) == 1 and measures[0]["attrs"]["error"] is True


def test_daemon_spans_sample_bounds_volume(mesh, tmp_path):
    from tpu_perf.driver import Driver
    from tpu_perf.spans import read_span_records

    def soak(folder, sample):
        opts = Options(op="ring", iters=1, num_runs=-1, buff_sz=4096,
                       spans=True, spans_sample=sample,
                       logfolder=str(tmp_path / folder))
        Driver(opts, mesh, max_runs=6).run()
        return read_span_records(
            glob.glob(str(tmp_path / folder / "spans-*.log")))

    full = soak("full", 1)
    sampled = soak("sampled", 3)
    assert len(sampled) < len(full)
    runs = [s for s in sampled if s["kind"] == "run"]
    assert len(runs) == 6  # anchors never sampled out
    measures = [s["attrs"]["run_id"] for s in sampled
                if s["kind"] == "measure"]
    assert measures == [1, 4]


# --- HBM-headroom depth cap --------------------------------------------


class _FakeDevice:
    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        if isinstance(self._stats, Exception):
            raise self._stats
        return self._stats


def test_hbm_depth_cap_from_memory_stats():
    from tpu_perf.adaptive import hbm_depth_cap

    gib = 1 << 30
    dev = _FakeDevice({"bytes_limit": 16 * gib, "bytes_in_use": 8 * gib})
    # 8 GiB free * 0.5 fraction / 1 GiB points = 4
    assert hbm_depth_cap(gib, device=dev) == 4
    # huge headroom clamps at the ceiling; tiny headroom floors at 1
    assert hbm_depth_cap(1024, device=dev, ceiling=64) == 64
    assert hbm_depth_cap(32 * gib, device=dev) == 1
    # no stats (CPU) and errors keep the historical fixed cap
    assert hbm_depth_cap(gib, device=_FakeDevice(None)) == 8
    assert hbm_depth_cap(gib, device=_FakeDevice(RuntimeError("n/a"))) == 8
    assert hbm_depth_cap(gib, device=_FakeDevice({"bytes_in_use": 1})) == 8
    with pytest.raises(ValueError):
        hbm_depth_cap(-1, device=dev)


def test_driver_precompile_auto_uses_headroom_cap(mesh, monkeypatch):
    import tpu_perf.adaptive as adaptive
    from tpu_perf.driver import Driver

    seen = {}

    def fake_cap(point_bytes, **kw):
        seen["point_bytes"] = point_bytes
        return 3

    monkeypatch.setattr(adaptive, "hbm_depth_cap", fake_cap)
    opts = Options(op="ring", sweep="8,64,4096", iters=1, num_runs=1,
                   precompile=1, precompile_auto=True)
    drv = Driver(opts, mesh)
    assert drv._pipe_tuner.max_depth == 3
    assert seen["point_bytes"] == 4096


# --- bench satellite ---------------------------------------------------


def test_bench_dispatch_overhead_payload(mesh):
    from tpu_perf.bench import _dispatch_overhead

    out = _dispatch_overhead(sizes=(8,), runs=4)
    assert set(out) == {"lanes", "points", "speedup_p50",
                        "overlap_speedup_p50"}
    (p,) = out["points"]
    assert p["nbytes"] == 8
    assert p["host_us"] > 0 and p["fused_us"] > 0
    assert p["overlapped_us"] > 0
    assert p["speedup"] == pytest.approx(p["host_us"] / p["fused_us"],
                                         rel=1e-2)
    assert p["overlap_speedup"] == pytest.approx(
        p["host_us"] / p["overlapped_us"], rel=1e-2)


# --- CLI ---------------------------------------------------------------


def test_cli_fused_flags_parse(mesh, capsys):
    from tpu_perf.cli import main

    rc = main(["run", "--op", "ring", "-b", "256", "-i", "1", "-r", "2",
               "--fence", "fused", "--fused-chunks", "2",
               "--ci-rel", "0.5", "--ci-statistic", "p50",
               "--spans-sample", "4", "--csv"])
    assert rc == 0
    out = capsys.readouterr().out
    assert len([ln for ln in out.splitlines() if ",ring," in ln]) == 2
