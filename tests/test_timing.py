import pytest

from tpu_perf.ops import build_op
from tpu_perf.parallel import make_mesh
from tpu_perf.timing import measure_overhead, time_step


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh()


def test_time_step_sample_count(mesh):
    built = build_op("allreduce", mesh, 64, 2)
    rt = time_step(built.step, built.example_input, 5)
    assert len(rt.samples) == 5
    assert all(t > 0 for t in rt.samples)
    assert rt.warmup_s > 0
    assert rt.overhead_s == 0.0


def test_time_step_warmup_absorbs_compile(mesh):
    built = build_op("ring", mesh, 64, 4)
    rt = time_step(built.step, built.example_input, 3, warmup_runs=2)
    # compile happened inside warm-up: measured runs are much faster
    assert rt.warmup_s > max(rt.samples)


def test_measure_dispatch_overhead(mesh):
    built = build_op("exchange", mesh, 64, 1)
    rt = time_step(built.step, built.example_input, 2, measure_dispatch=True)
    assert rt.overhead_s > 0


def test_stats(mesh):
    built = build_op("allreduce", mesh, 64, 1)
    rt = time_step(built.step, built.example_input, 4)
    s = rt.stats()
    assert s["min"] <= s["p50"] <= s["max"]
    assert s["min"] <= s["avg"] <= s["max"]


def test_time_step_validation(mesh):
    built = build_op("allreduce", mesh, 64, 1)
    with pytest.raises(ValueError):
        time_step(built.step, built.example_input, 0)


def test_overhead_helper(mesh):
    built = build_op("allreduce", mesh, 64, 1)
    oh = measure_overhead(built.example_input, reps=3)
    assert oh >= 0
