"""Single-process behavior of the multi-host helpers (multi-process paths
run on real pods; here we pin the degenerate contracts)."""

import jax

from tpu_perf.config import Options
from tpu_perf.driver import Driver
from tpu_perf.parallel import (
    allreduce_times,
    initialize_distributed,
    make_hybrid_mesh,
)


def test_initialize_distributed_single_process_noop(monkeypatch, eight_devices):
    for v in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS", "SLURM_JOB_ID",
              "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(v, raising=False)
    initialize_distributed()  # must not raise or hang
    assert jax.process_count() == 1


def test_hybrid_mesh_single_process(eight_devices):
    mesh = make_hybrid_mesh()
    assert mesh.axis_names == ("dcn", "ici")
    assert mesh.shape["dcn"] == 1
    assert mesh.shape["ici"] == 8


def test_hybrid_mesh_runs_hier_allreduce(eight_devices):
    import io

    mesh = make_hybrid_mesh()
    opts = Options(op="hier_allreduce", iters=1, num_runs=1, buff_sz=256)
    rows = Driver(opts, mesh, err=io.StringIO()).run()
    assert rows[0].n_devices == 8


def test_allreduce_times_single_process():
    out = allreduce_times(1.5)
    assert out == {"min": 1.5, "max": 1.5, "avg": 1.5}
