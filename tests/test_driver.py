import io

import jax
import pytest

from tpu_perf.config import Options
from tpu_perf.driver import Driver, RotatingCsvLog, log_file_name
from tpu_perf.parallel import make_mesh
from tpu_perf.schema import LegacyRow


class FakeClock:
    def __init__(self, t0=1000.0):
        self.t = t0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh()


def test_local_ip_falls_back_past_loopback(monkeypatch):
    """Satellite (ISSUE 2): gethostbyname(gethostname()) returning
    127.0.0.1 (hostname mapped to loopback in /etc/hosts) must not
    poison the CSV ip column — the UDP-connect trick reports the real
    outbound interface instead, with 0.0.0.0 as the last resort."""
    import socket as socket_mod

    from tpu_perf.driver import local_ip

    class FakeUdpSocket:
        def __init__(self, *a, **k):
            self.peer = None

        def connect(self, addr):
            self.peer = addr  # no packet leaves: connect() only routes

        def getsockname(self):
            return ("10.0.0.42", 54321)

        def close(self):
            pass

    monkeypatch.setattr(socket_mod, "gethostbyname", lambda h: "127.0.0.1")
    monkeypatch.setattr(socket_mod, "socket",
                        lambda *a, **k: FakeUdpSocket())
    assert local_ip() == "10.0.0.42"

    # a resolvable non-loopback hostname short-circuits (no UDP socket)
    monkeypatch.setattr(socket_mod, "gethostbyname", lambda h: "10.1.2.3")

    def boom(*a, **k):
        raise AssertionError("UDP fallback must not run")

    monkeypatch.setattr(socket_mod, "socket", boom)
    assert local_ip() == "10.1.2.3"

    # resolution fails AND no route: the existing 0.0.0.0 last resort
    def no_dns(h):
        raise OSError("no dns")

    class DeadSocket(FakeUdpSocket):
        def connect(self, addr):
            raise OSError("unreachable")

    monkeypatch.setattr(socket_mod, "gethostbyname", no_dns)
    monkeypatch.setattr(socket_mod, "socket", lambda *a, **k: DeadSocket())
    assert local_ip() == "0.0.0.0"


def test_log_file_name_format():
    name = log_file_name("my-uuid", 3, 0.0)
    assert name.startswith("tcp-my-uuid-3-")
    assert name.endswith(".log")


def test_rotation_contract(tmp_path):
    """The 900s rotation with a fake clock (mpi_perf.c:479-497):
    no rotation before the period, rotation + ingest hook after."""
    clock = FakeClock()
    fired = []
    log = RotatingCsvLog(
        str(tmp_path), "u", 0, refresh_sec=900, clock=clock,
        on_rotate=lambda: fired.append(clock()),
    )
    row = LegacyRow("ts", "u", 0, 1, "ip", "ip", 1, 8, 10, 1.0, 1)
    log.write_row(row)
    first = log.current_path
    assert not log.maybe_rotate()  # fresh file: no rotation
    clock.advance(899)
    assert not log.maybe_rotate()
    clock.advance(2)  # past 900s
    assert log.maybe_rotate()
    assert log.current_path is None or True  # new file opens lazily on write
    log.write_row(row)
    assert log.current_path != first
    assert fired == [clock()]  # hook fired exactly once, at rotation
    log.close()


def test_lazy_log_creates_no_file_until_first_write(tmp_path):
    """The health-event family is lazy: maybe_rotate never opens it, so
    a healthy daemon (zero events) churns no empty files through the
    ingest backend; rotation closes without eagerly reopening."""
    clock = FakeClock()
    log = RotatingCsvLog(
        str(tmp_path), "u", 0, refresh_sec=10, clock=clock,
        prefix="health", lazy=True,
    )
    assert not log.maybe_rotate()
    clock.advance(11)
    assert not log.maybe_rotate()  # nothing open: nothing to rotate
    assert list(tmp_path.glob("health-*.log")) == []
    row = LegacyRow("ts", "u", 0, 1, "ip", "ip", 1, 8, 10, 1.0, 1)
    log.write_row(row)  # first event opens the file
    first = log.current_path
    # active lazy file carries .open until closed, so a health-*.log on
    # disk is by construction finished (ingest needs no newest-N skip)
    assert first.endswith(".log.open")
    assert list(tmp_path.glob("health-*.log")) == []
    clock.advance(11)
    assert log.maybe_rotate()
    assert log.current_path is None  # closed; next event opens a new one
    assert len(list(tmp_path.glob("health-*.log"))) == 1
    assert list(tmp_path.glob("health-*.log.open")) == []
    log.close()


def test_lazy_log_same_second_rotations_lose_no_rows(tmp_path):
    """Same-second rotations reuse the timestamped filename; the lazy
    close renames .open over the bare name, so without disambiguation a
    collision silently overwrites the earlier file's rows (a chaos
    ledger's one meta record, a health incident's first events)."""
    clock = FakeClock()  # frozen: every file gets the same timestamp
    log = RotatingCsvLog(
        str(tmp_path), "u", 0, refresh_sec=0, clock=clock,
        prefix="health", lazy=True,
    )
    row = LegacyRow("ts", "u", 0, 1, "ip", "ip", 1, 8, 10, 1.0, 1)
    for _ in range(3):
        log.write_row(row)
        assert log.maybe_rotate()  # refresh 0: closes after every row
    log.close()
    files = sorted(tmp_path.glob("health-*.log"))
    assert len(files) == 3  # disambiguated, not overwritten
    assert sum(len(f.read_text().splitlines()) for f in files) == 3


def test_rotation_skips_hook_on_first_open(tmp_path):
    clock = FakeClock()
    fired = []
    log = RotatingCsvLog(
        str(tmp_path), "u", 0, refresh_sec=900, clock=clock,
        on_rotate=lambda: fired.append(1),
    )
    assert not log.maybe_rotate()  # first open is not a rotation
    assert fired == []
    log.close()


def test_driver_one_shot_rows(mesh, tmp_path):
    opts = Options(
        op="allreduce", iters=2, num_runs=3, buff_sz=64,
        logfolder=str(tmp_path), stats_every=10**9,
    )
    rows = Driver(opts, mesh, err=io.StringIO()).run()
    assert len(rows) == 3
    assert [r.run_id for r in rows] == [1, 2, 3]
    # legacy rows landed in the rotating log
    logs = list(tmp_path.glob("tcp-*.log"))
    assert len(logs) == 1
    lines = logs[0].read_text().splitlines()
    assert len(lines) == 3
    parsed = LegacyRow.from_csv(lines[0])
    assert parsed.buffer_size == 64
    assert parsed.num_buffers == 2  # iters
    assert parsed.job_id == opts.uuid


def test_driver_daemon_mode_bounded_by_max_runs(mesh, tmp_path):
    opts = Options(op="ring", iters=1, num_runs=-1, buff_sz=32, logfolder=str(tmp_path))
    drv = Driver(opts, mesh, err=io.StringIO(), max_runs=5)
    rows = drv.run()
    assert opts.infinite
    # daemon mode never accumulates rows in memory (unbounded growth);
    # the rotating log on disk is the record
    assert rows == []
    logs = list(tmp_path.glob("tcp-*.log"))
    assert len(logs) == 1
    assert len(logs[0].read_text().splitlines()) == 5


def test_driver_honors_warmup_runs(mesh):
    opts = Options(op="ring", iters=1, num_runs=2, buff_sz=32, warmup_runs=3)
    rows = Driver(opts, mesh, err=io.StringIO()).run()
    assert len(rows) == 2  # warm-ups are extra, never logged


def test_driver_ingest_failure_does_not_kill_daemon(mesh, tmp_path, capsys):
    clock = FakeClock()

    def boom():
        raise IOError("kusto down")

    log = RotatingCsvLog(
        str(tmp_path), "u", 0, refresh_sec=10, clock=clock, on_rotate=boom
    )
    from tpu_perf.schema import LegacyRow as LR

    log.write_row(LR("ts", "u", 0, 1, "ip", "ip", 1, 8, 10, 1.0, 1))
    clock.advance(11)
    assert log.maybe_rotate()  # rotation survives the failing hook
    log.close()


def test_rotation_failing_hook_leaves_file_for_next_pass(tmp_path):
    """The kusto_ingest retry contract end-to-end (driver.py:124-133): a
    hook that raises must leave the closed file on disk, and the NEXT
    rotation's pass picks it up together with the newly closed file —
    delete-only-after-success, retried at the next rotation."""
    import os

    from tpu_perf.ingest.pipeline import LocalDirBackend, run_ingest_pass

    clock = FakeClock()
    logs, sink = tmp_path / "logs", tmp_path / "sink"
    fail = {"on": True}

    def hook():
        if fail["on"]:
            raise IOError("kusto down")
        run_ingest_pass(str(logs), skip_newest=0,
                        backend=LocalDirBackend(str(sink)))

    log = RotatingCsvLog(
        str(logs), "u", 0, refresh_sec=10, clock=clock, on_rotate=hook
    )
    row = LegacyRow("ts", "u", 0, 1, "ip", "ip", 1, 8, 10, 1.0, 1)
    log.write_row(row)
    first = log.current_path
    clock.advance(11)
    assert log.maybe_rotate()  # hook raised; the daemon survived
    assert os.path.exists(first)  # the un-ingested file stays put
    fail["on"] = False
    log.write_row(row)
    second = log.current_path
    clock.advance(11)
    assert log.maybe_rotate()
    # the retried pass swept BOTH the stranded file and the fresh one
    assert not os.path.exists(first) and not os.path.exists(second)
    assert len(list(sink.glob("tcp-*.log"))) == 2
    log.close()


def test_driver_group1_file_validation(mesh, tmp_path):
    good = tmp_path / "hosts"
    good.write_text("host-a\nhost-b\nhost-c\nhost-d\n")  # 8/(2*1) = 4 hosts
    opts = Options(op="ring", iters=1, num_runs=1, buff_sz=32, group1_file=str(good))
    Driver(opts, mesh, err=io.StringIO())  # validates without raising
    bad = tmp_path / "hosts_bad"
    bad.write_text("host-a\n")
    opts2 = Options(op="ring", iters=1, num_runs=1, buff_sz=32, group1_file=str(bad))
    with pytest.raises(ValueError):
        Driver(opts2, mesh, err=io.StringIO())


def test_driver_daemon_round_robins_sweep(mesh, tmp_path):
    opts = Options(
        op="ring", iters=1, num_runs=-1, sweep="8,32", logfolder=str(tmp_path)
    )
    Driver(opts, mesh, err=io.StringIO(), max_runs=4).run()
    logs = list(tmp_path.glob("tpu-*.log"))
    assert len(logs) == 1
    from tpu_perf.schema import ResultRow

    rows = [ResultRow.from_csv(ln) for ln in logs[0].read_text().splitlines()]
    # both sweep sizes measured, alternating
    assert [r.nbytes for r in rows] == [8, 32, 8, 32]


def test_driver_writes_extended_rows(mesh, tmp_path):
    opts = Options(op="ring", iters=1, num_runs=2, buff_sz=64, logfolder=str(tmp_path))
    Driver(opts, mesh, err=io.StringIO()).run()
    ext = list(tmp_path.glob("tpu-*.log"))
    assert len(ext) == 1
    from tpu_perf.schema import ResultRow

    rows = [ResultRow.from_csv(ln) for ln in ext[0].read_text().splitlines()]
    assert len(rows) == 2 and rows[0].busbw_gbps > 0


def test_odd_device_count_ring_and_halo(eight_devices):
    import jax

    from tpu_perf.ops import build_op

    mesh5 = make_mesh(devices=jax.devices()[:5])
    for op in ("ring", "halo"):
        built = build_op(op, mesh5, 40, 1)
        assert built.n_devices == 5
        jax.block_until_ready(built.step(built.example_input))
    import pytest as _p

    with _p.raises(ValueError):
        build_op("pingpong", mesh5, 40, 1)


def test_dtype_validation():
    with pytest.raises(ValueError):
        Options(dtype="float64")


def test_fence_validation():
    with pytest.raises(ValueError):
        Options(fence="maybe")
    Options(fence="readback")
    Options(fence="slope")


def test_driver_readback_fence(mesh):
    opts = Options(op="ring", iters=1, num_runs=2, buff_sz=64, fence="readback")
    rows = Driver(opts, mesh, err=io.StringIO()).run()
    assert len(rows) == 2 and all(r.time_ms > 0 for r in rows)


def test_driver_slope_fence(mesh):
    opts = Options(op="ring", iters=2, num_runs=2, buff_sz=64, fence="slope")
    rows = Driver(opts, mesh, err=io.StringIO()).run()
    assert len(rows) == 2 and all(r.time_ms > 0 for r in rows)


def test_profile_dir_writes_trace(mesh, tmp_path):
    opts = Options(op="ring", iters=1, num_runs=1, buff_sz=64,
                   profile_dir=str(tmp_path / "trace"))
    Driver(opts, mesh, err=io.StringIO()).run()
    # jax.profiler writes a plugins/profile tree under the trace dir
    assert any((tmp_path / "trace").rglob("*"))


def test_driver_heartbeat(mesh):
    err = io.StringIO()
    opts = Options(op="ring", iters=1, num_runs=4, buff_sz=32, stats_every=2)
    Driver(opts, mesh, err=err).run()
    beat = err.getvalue()
    assert "min" in beat and "p50" in beat


def test_driver_heartbeat_json(mesh):
    """--heartbeat-format json: one parseable JSON object per stats
    boundary on stderr, carrying the human line's triple + p50 + drops,
    so collectors never scrape the human string."""
    import json

    err = io.StringIO()
    opts = Options(op="ring", iters=1, num_runs=4, buff_sz=32,
                   stats_every=2, heartbeat_format="json")
    Driver(opts, mesh, err=err).run()
    beats = [json.loads(ln) for ln in err.getvalue().splitlines()
             if ln.startswith("{")]
    assert len(beats) == 2  # 4 runs / stats_every=2
    for b in beats:
        assert b["event"] == "heartbeat"
        assert b["samples"] == 2 and b["dropped"] == 0
        assert b["min_ms"] <= b["p50_ms"] <= b["max_ms"]
    assert [b["run"] for b in beats] == [2, 4]


def test_driver_heartbeat_json_multi_op_sweep_windows(mesh):
    """Satellite (ISSUE 2): under multi-op sweep rotation every boundary
    emits exactly ONE JSON heartbeat carrying the heartbeat-window index
    health events share ((run_id - 1) // stats_every) and the window's
    per-(op, nbytes) recorded-run counts — the indexing the chaos
    conformance join relies on."""
    import json

    err = io.StringIO()
    # 2 ops x 2 sizes = 4 points; stats_every=8 = two full rotations per
    # window; 24 runs = 3 boundaries
    opts = Options(op="ring,hbm_stream", iters=1, num_runs=-1, sweep="8,32",
                   stats_every=8, heartbeat_format="json")
    Driver(opts, mesh, err=err, max_runs=24).run()
    beats = [json.loads(ln) for ln in err.getvalue().splitlines()
             if ln.startswith("{")]
    assert [b["run"] for b in beats] == [8, 16, 24]  # one per boundary
    assert [b["window"] for b in beats] == [0, 1, 2]
    for b in beats:
        # every point visited exactly twice per window, none missing
        assert b["points"] == {"ring/8": 2, "ring/32": 2,
                               "hbm_stream/8": 2, "hbm_stream/32": 2}
        assert b["samples"] == 8
        assert b["window"] == (b["run"] - 1) // opts.stats_every


def test_drop_counter_in_heartbeat_and_rotation(mesh, tmp_path):
    """VERDICT r4 weak #5: dropped runs are counted per instrument and
    surfaced in the heartbeat line and the rotation summary — a soak's
    capture-loss rate is visible from its logs alone."""
    import tpu_perf.driver as driver_mod

    real = driver_mod.slope_sample
    seen = {"n": 0}

    def flaky_slope_sample(*args, **kwargs):
        seen["n"] += 1
        s = real(*args, **kwargs)
        return None if seen["n"] % 2 == 0 else s  # drop every 2nd run

    driver_mod.slope_sample = flaky_slope_sample
    try:
        clock = FakeClock()
        err = io.StringIO()
        opts = Options(op="ring", iters=1, num_runs=-1, buff_sz=32,
                       fence="slope", stats_every=4,
                       logfolder=str(tmp_path), log_refresh_sec=900)
        drv = Driver(opts, mesh, clock=clock, err=err, max_runs=8)
        orig_rotate = drv.log.maybe_rotate

        def advancing_rotate():
            clock.advance(300)
            return orig_rotate()

        drv.log.maybe_rotate = advancing_rotate
        drv.run()
    finally:
        driver_mod.slope_sample = real
    out = err.getvalue()
    # heartbeat carries the cumulative total (4 of 8 runs dropped)
    assert "dropped 2" in out and "dropped 4" in out
    # rotation summary names the instrument
    assert "dropped runs so far: ring=" in out
    assert drv.dropped_runs == {"ring": 4}


def test_driver_sweep(mesh):
    opts = Options(op="ring", iters=1, num_runs=1, sweep="8,32")
    rows = Driver(opts, mesh, err=io.StringIO()).run()
    assert [r.nbytes for r in rows] == [8, 32]


def test_driver_rotation_triggers_ingest(mesh, tmp_path):
    """End-to-end: daemon run with a tiny refresh period rotates and fires
    the ingest hook (mpi_perf.c:490)."""
    clock = FakeClock()
    fired = []
    opts = Options(
        op="ring", iters=1, num_runs=-1, buff_sz=32,
        logfolder=str(tmp_path), log_refresh_sec=900, stats_every=10**9,
    )
    drv = Driver(opts, mesh, clock=clock, on_rotate=lambda: fired.append(1), max_runs=6)
    # advance the fake clock a lot per run via perf hook wrapping
    orig_rotate = drv.log.maybe_rotate

    def advancing_rotate():
        clock.advance(400)
        return orig_rotate()

    drv.log.maybe_rotate = advancing_rotate
    drv.run()
    assert fired  # at least one rotation happened
    assert len(list(tmp_path.glob("tcp-*.log"))) >= 2


def test_daemon_cadence_unaffected_by_slow_ingest(mesh, tmp_path):
    """VERDICT r2 #4: a slow ingest pass must not stall the next measured
    run — the hook spawns a subprocess and returns immediately (the
    reference pins its uploader into a separate process the same way,
    mpi_perf.c:363-364)."""
    import time as wall

    from tpu_perf.ingest.pipeline import SubprocessIngest

    clock = FakeClock()
    hook = SubprocessIngest(["sleep", "30"])
    opts = Options(
        op="ring", iters=1, num_runs=-1, buff_sz=32,
        logfolder=str(tmp_path), log_refresh_sec=900, stats_every=10**9,
    )
    drv = Driver(opts, mesh, clock=clock, on_rotate=hook, max_runs=6)
    orig_rotate = drv.log.maybe_rotate

    def advancing_rotate():
        clock.advance(400)  # rotation fires every other run
        return orig_rotate()

    drv.log.maybe_rotate = advancing_rotate
    t0 = wall.perf_counter()
    drv.run()
    elapsed = wall.perf_counter() - t0
    try:
        # 6 runs completed in wall-time seconds while the 30 s ingest pass
        # is still alive in the background: cadence was never blocked
        assert elapsed < 15
        assert hook._proc is not None and hook._proc.poll() is None
    finally:
        if hook._proc is not None:
            hook._proc.kill()
            hook._proc.wait()


def test_driver_multi_op_family_finite(mesh):
    # --op a,b runs every (op, size) point; rows carry each op's name
    opts = Options(op="ring,hbm_stream", iters=1, num_runs=2, sweep="32,64")
    rows = Driver(opts, mesh, err=io.StringIO()).run()
    by_op = {}
    for r in rows:
        by_op.setdefault(r.op, set()).add(r.nbytes)
    assert set(by_op) == {"ring", "hbm_stream"}
    assert by_op["ring"] == by_op["hbm_stream"] == {32, 64}


def test_driver_multi_op_family_daemon_round_robin(mesh, tmp_path):
    # the daemon rotates the whole instrument family: 2 ops x 2 sizes = 4
    # points, so 8 runs visit each point exactly twice
    opts = Options(op="ring,hbm_stream", iters=1, num_runs=-1, sweep="32,64",
                   logfolder=str(tmp_path))
    Driver(opts, mesh, err=io.StringIO(), max_runs=8).run()
    from tpu_perf.schema import ResultRow

    (log,) = tmp_path.glob("tpu-*.log")
    rows = [ResultRow.from_csv(line) for line in log.read_text().splitlines()]
    from collections import Counter

    counts = Counter((r.op, r.nbytes) for r in rows)
    assert counts == {("ring", 32): 2, ("ring", 64): 2,
                      ("hbm_stream", 32): 2, ("hbm_stream", 64): 2}


def test_driver_shares_slope_lo_hi_example_buffer(mesh):
    # ADVICE r3 (daemon HBM footprint): the hi trip-count kernel reuses
    # the lo kernel's input buffer — same spec, same make_fill contents
    opts = Options(op="ring", iters=1, num_runs=1, buff_sz=64, fence="slope")
    d = Driver(opts, mesh, err=io.StringIO())
    built, built_hi = d._build("ring", "native", 64)
    assert built_hi.example_input is built.example_input


def test_daemon_family_dedupes_equal_spec_buffers(mesh):
    # equal-spec points across ops share one canonical device buffer;
    # distinct specs keep their own
    opts = Options(op="ring,hbm_stream", iters=1, num_runs=-1, sweep="32,64")
    d = Driver(opts, mesh, err=io.StringIO(), max_runs=0)
    canon = {}
    pairs = [d._share_pair(d._build(op, "native", nbytes), canon)
             for op in ("ring", "hbm_stream") for nbytes in (32, 64)]
    buffers = [b.example_input for b, _ in pairs]
    # ring@32 and hbm_stream@32 share; 32- and 64-byte specs do not
    assert buffers[0] is buffers[2] and buffers[1] is buffers[3]
    assert buffers[0] is not buffers[1]
    # deduped points still execute (the freed duplicates are truly gone
    # only for their own arrays; the canonical buffer stays live)
    for b, _ in pairs:
        jax.block_until_ready(b.step(b.example_input))


def test_daemon_rows_carry_daemon_mode(mesh, tmp_path):
    # VERDICT r3 #9: daemon points run systematically hot; the mode
    # column keeps them off one-shot curves and diff baselines
    from tpu_perf.schema import ResultRow

    opts = Options(op="ring", iters=1, num_runs=-1, buff_sz=64,
                   logfolder=str(tmp_path))
    Driver(opts, mesh, err=io.StringIO(), max_runs=3).run()
    (log,) = tmp_path.glob("tpu-*.log")
    rows = [ResultRow.from_csv(ln) for ln in log.read_text().splitlines()]
    assert rows and all(r.mode == "daemon" for r in rows)


def test_oneshot_rows_carry_oneshot_mode(mesh):
    opts = Options(op="ring", iters=1, num_runs=2, buff_sz=64)
    rows = Driver(opts, mesh, err=io.StringIO()).run()
    assert rows and all(r.mode == "oneshot" for r in rows)


def test_measure_dispatch_records_overhead(mesh):
    # VERDICT r3 #8: --measure-dispatch wires timing.measure_overhead
    # into the rows' overhead_us column (recorded, never subtracted)
    opts = Options(op="ring", iters=1, num_runs=2, buff_sz=64,
                   measure_dispatch=True)
    rows = Driver(opts, mesh, err=io.StringIO()).run()
    assert rows and all(r.overhead_us > 0 for r in rows)
    # slope mode cancels constants by construction: overhead stays 0
    opts = Options(op="ring", iters=1, num_runs=1, buff_sz=64,
                   measure_dispatch=True, fence="slope")
    rows = Driver(opts, mesh, err=io.StringIO()).run()
    assert all(r.overhead_us == 0 for r in rows)


def test_driver_multi_op_fixed_payload_collapses_per_op(mesh):
    # barrier is latency-only with a clamped payload: it contributes ONE
    # point regardless of the sweep, while ring keeps both sizes
    opts = Options(op="barrier,ring", iters=1, num_runs=1, sweep="32,64")
    rows = Driver(opts, mesh, err=io.StringIO()).run()
    points = {(r.op, r.nbytes) for r in rows}
    assert ("ring", 32) in points and ("ring", 64) in points
    assert len([p for p in points if p[0] == "barrier"]) == 1


def test_driver_multi_op_unknown_fails_before_any_run(mesh):
    opts = Options(op="ring,nope", iters=1, num_runs=1, buff_sz=32)
    with pytest.raises(ValueError, match="unknown op"):
        Driver(opts, mesh, err=io.StringIO()).run()


def test_daemon_ignores_profile_dir(mesh, tmp_path, capsys):
    # an enclosing capture accumulating for the life of an infinite soak
    # would grow without bound: daemons keep only rotating logs
    import os

    err = io.StringIO()
    opts = Options(op="ring", iters=1, num_runs=-1, buff_sz=64,
                   profile_dir=str(tmp_path / "prof"))
    Driver(opts, mesh, err=err, max_runs=2).run()
    assert "--profile-dir is ignored in daemon mode" in err.getvalue()
    assert not os.path.exists(tmp_path / "prof")
