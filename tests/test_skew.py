"""Arrival-skew fault kind + straggler scenario axis (ISSUE 11).

Units for the seeded skew machinery (spec validation, entry-boundary
draws, the axis model's lockstep reconstruction), the Options-level
fence conflicts, the driver's skew-axis sweep end-to-end on the
synthetic timing source, the straggler-cost / skewed-crossover report
views, detector conformance with victim attribution, the spans-sample
retention satellite, and the simulated multi-rank lockstep proof."""

import io
import json

import pytest

from tpu_perf.config import Options
from tpu_perf.faults import FaultInjector, FaultSpec, axis_skew, parse_spec
from tpu_perf.faults.injector import MIN_SKEW_WORLD
from tpu_perf.schema import ResultRow


class LedgerSpy:
    def __init__(self):
        self.rows = []

    def write_row(self, row):
        self.rows.append(json.loads(row.to_csv()))

    def maybe_rotate(self):
        pass

    def close(self):
        pass


def _injector(faults, **kw):
    kw.setdefault("ledger", LedgerSpy())
    kw.setdefault("stats_every", 10)
    return FaultInjector(faults, **kw)


# --- spec: the skew kind ------------------------------------------------


def test_skew_spec_defaults_and_validation():
    f = FaultSpec(kind="skew")
    assert f.magnitude == 1000.0  # scale default: a 1 ms straggler (µs)
    assert f.shape == "uniform" and f.critical
    with pytest.raises(ValueError, match="positive magnitude"):
        FaultSpec(kind="skew", magnitude=0.0)
    # the heavy-tailed shapes apply to skew too (straggler tails)
    assert FaultSpec(kind="skew", shape="pareto").shape == "pareto"
    (f,) = parse_spec([{"kind": "skew", "op": "allreduce", "rank": 1,
                        "magnitude": 500, "shape": "lognormal"}])
    assert (f.op, f.rank, f.magnitude, f.shape) == (
        "allreduce", 1, 500, "lognormal")
    from tpu_perf.faults.spec import EXPECTED_EVENT

    assert EXPECTED_EVENT["skew"] == "regression"


def test_apply_never_touches_the_sample_for_skew():
    """Skew is an ENTRY-time fault: the after-the-fact apply() boundary
    (where delay lives) must neither perturb nor ledger it."""
    inj = _injector([FaultSpec(kind="skew", magnitude=1000.0)])
    assert inj.apply("ring", 32, 1, 1.0) == 1.0
    assert inj.ledger.rows == []


# --- injector: entry-boundary skew --------------------------------------


def test_entry_skew_is_seeded_and_ledgered_without_wallclock():
    spec = [FaultSpec(kind="skew", op="ring", nbytes=32, start=3, end=6,
                      magnitude=2000.0)]
    a = _injector(spec, seed=7)
    b = _injector(spec, seed=7)
    c = _injector(spec, seed=8)
    xa = [a.entry_skew("ring", 32, i) for i in range(1, 10)]
    xb = [b.entry_skew("ring", 32, i) for i in range(1, 10)]
    xc = [c.entry_skew("ring", 32, i) for i in range(1, 10)]
    assert xa == xb and xa != xc
    # outside the window / wrong point: inert
    assert xa[0] == (0.0, 0.0) and xa[8] == (0.0, 0.0)
    assert a.entry_skew("ring", 8, 4) == (0.0, 0.0)
    assert a.entry_skew("halo", 32, 4) == (0.0, 0.0)
    # in-window runs fired: stagger in [0, 2000 µs), one record per run
    for own, cost in xa[2:6]:
        assert 0.0 <= own < 2000e-6
        assert cost >= 0.0
    recs = [r for r in a.ledger.rows if r["record"] == "fault"]
    assert [r["run_id"] for r in recs] == [3, 4, 5, 6]
    assert all(r["kind"] == "skew" and "stagger_us" in r for r in recs)
    assert not any("timestamp" in r for r in recs)  # run_id is the clock


def test_entry_skew_phantom_world_makes_single_rank_soaks_non_vacuous():
    """A single-process SYNTHETIC soak models a MIN_SKEW_WORLD-rank
    fabric: the victim cost (modeled worst arrival minus own) must be
    non-zero for typical draws, or every single-host conformance gate
    is vacuous.  (Real timing models no phantoms — the driver rejects
    single-process skew faults outright.)"""
    assert MIN_SKEW_WORLD >= 2
    inj = _injector([FaultSpec(kind="skew", magnitude=5000.0)], seed=7,
                    synthetic_s=0.001)
    costs = [inj.entry_skew("ring", 32, i, n_ranks=1)[1]
             for i in range(1, 50)]
    assert all(c >= 0.0 for c in costs)
    # roughly half the runs this rank itself drew the worst arrival
    # (cost 0 — it IS the straggler); the rest wait for the phantom
    assert 10 < sum(1 for c in costs if c > 0.0) < 40
    assert sum(costs) / len(costs) > 0.0


def test_entry_skew_rank_filter_staggers_straggler_victimizes_rest():
    """A rank-filtered skew staggers ONE rank; every other rank is a
    victim — cost > 0, stagger 0 — and each rank reconstructs the
    other's draw without communication (lockstep by hashes)."""
    spec = [FaultSpec(kind="skew", rank=1, magnitude=3000.0)]
    r0 = _injector(spec, seed=7, rank=0)
    r1 = _injector(spec, seed=7, rank=1)
    for run in range(1, 20):
        own0, cost0 = r0.entry_skew("ring", 32, run, n_ranks=2)
        own1, cost1 = r1.entry_skew("ring", 32, run, n_ranks=2)
        assert own0 == 0.0          # not the straggler
        assert cost1 == 0.0         # the straggler waits for nobody
        assert cost0 == own1 > 0.0  # victim's wait IS the straggler's lag
    # victims ledger the fault too (stagger 0): conformance joins the
    # fault to the rows it degrades, not just the skewed rank's
    recs0 = [r for r in r0.ledger.rows if r["record"] == "fault"]
    recs1 = [r for r in r1.ledger.rows if r["record"] == "fault"]
    assert len(recs0) == len(recs1) == 19
    assert all(r["stagger_us"] == 0 for r in recs0)
    assert all(r["stagger_us"] > 0 for r in recs1)


def test_multihost_spec_reproduced_on_fewer_hosts_models_the_straggler():
    """A rank-filtered skew spec whose rank exceeds the real world must
    still inject ON THE SYNTHETIC SOURCE: the world pads to cover the
    named straggler (phantom, like MIN_SKEW_WORLD), so single-host
    reproduction of a multi-host spec measures a modeled victim cost
    instead of silently zero.  Real timing can only observe a
    straggler that actually sleeps, so there the same spec neither
    fires nor ledgers — a 'fired' record for a no-op injection would
    demand a detection that cannot exist."""
    spec = [FaultSpec(kind="skew", rank=3, magnitude=2000.0)]
    inj = _injector(spec, seed=7, rank=0, synthetic_s=0.001)
    assert inj.skew_world_size(1) == 4
    # world sizing is scoped to the RUN: an unmatching op/window must
    # not inflate another run's modeled world
    assert inj.skew_world_size(1, "ring", 32, 1) == 4
    scoped = [FaultSpec(kind="skew", op="halo", magnitude=500.0),
              FaultSpec(kind="skew", op="ring", rank=5, magnitude=500.0)]
    inj_scoped = _injector(scoped, seed=7, rank=0, synthetic_s=0.001)
    # (the MIN_SKEW_WORLD pad is skew_world's job, applied on top)
    assert inj_scoped.skew_world_size(1, "halo", 32, 1) == 1
    assert inj_scoped.skew_world_size(1, "ring", 32, 1) == 6
    # ...and behaviorally: adding an unrelated op's spec must not shift
    # this op's modeled victim cost (same seed, same spec index)
    halo_only = _injector(scoped[:1], seed=7, rank=0, synthetic_s=0.001)
    both = _injector(scoped, seed=7, rank=0, synthetic_s=0.001)
    for run in range(1, 10):
        assert both.entry_skew("halo", 32, run, n_ranks=1) \
            == halo_only.entry_skew("halo", 32, run, n_ranks=1)
    costs = [inj.entry_skew("ring", 32, run, n_ranks=1)[1]
             for run in range(1, 20)]
    assert all(c > 0.0 for c in costs)  # rank 3 modeled, rank 0 waits
    recs = [r for r in inj.ledger.rows if r["record"] == "fault"]
    assert len(recs) == 19 and all(r["stagger_us"] == 0 for r in recs)
    # real timing: the phantom spec is inert AND ledger-silent
    real = _injector(spec, seed=7, rank=0)
    assert real.entry_skew("ring", 32, 1, n_ranks=1) == (0.0, 0.0)
    assert real.ledger.rows == []
    # an explicit world that cannot contain the straggler: same
    quiet = _injector(spec, seed=7, rank=0)
    assert quiet.skew_arrivals_us("ring", 32, 1, world=range(2)) is None
    assert quiet.ledger.rows == []


def test_overlapping_skew_sources_combine_arrivals_not_costs():
    """Two concurrent skew sources must SUM each rank's arrivals and
    then take the worst — per-source victim costs do not add (both
    sources' worst arrivals can land on the same other rank, or on this
    one): cost == max(per-rank totals) - own total, exactly."""
    spec = [FaultSpec(kind="skew", rank=0, magnitude=3000.0),
            FaultSpec(kind="skew", rank=1, magnitude=3000.0)]
    for run in range(1, 30):
        inj0 = _injector(spec, seed=7, rank=0)
        inj1 = _injector(spec, seed=7, rank=1)
        own0, cost0 = inj0.entry_skew("ring", 32, run, n_ranks=2)
        own1, cost1 = inj1.entry_skew("ring", 32, run, n_ranks=2)
        worst = max(own0, own1)
        assert cost0 == pytest.approx(worst - own0)
        assert cost1 == pytest.approx(worst - own1)
        # exactly one of the two is the straggler: its cost is zero
        assert min(cost0, cost1) == pytest.approx(0.0)
    # the driver folds the AXIS arrivals into the same totals: a rank-1
    # skew fault plus a spread on rank 0's seat must not double-bill
    from tpu_perf.faults.injector import axis_arrivals_us

    arr = axis_arrivals_us(7, "ring", 32, 1000, 5, world=range(2))
    assert arr[1] == 1000.0 and 0.0 <= arr[0] < 1000.0


@pytest.mark.parametrize("shape", ["lognormal", "pareto"])
def test_entry_skew_heavy_tailed_shapes(shape):
    spec = [FaultSpec(kind="skew", magnitude=1000.0, shape=shape)]
    a = _injector(spec, seed=7)
    b = _injector(spec, seed=7)
    xs = [a.entry_skew("ring", 32, i)[0] for i in range(1, 500)]
    ys = [b.entry_skew("ring", 32, i)[0] for i in range(1, 500)]
    assert xs == ys
    assert all(x >= 0.0 for x in xs)
    assert max(xs) > 1000e-6  # a real right tail past the uniform cap
    med = sorted(xs)[len(xs) // 2]
    assert 0.5e-3 < med < 1.5e-3  # scale stays the TYPICAL stagger


# --- the sweep-axis arrival model ---------------------------------------


def test_axis_skew_zero_spread_is_inert():
    assert axis_skew(7, "ring", 32, 0, 1) == (0.0, 0.0)


def test_axis_skew_seeded_and_lockstep_reconstructible():
    a = axis_skew(7, "ring", 32, 1000, 5, rank=0, n_ranks=2)
    assert a == axis_skew(7, "ring", 32, 1000, 5, rank=0, n_ranks=2)
    assert a != axis_skew(8, "ring", 32, 1000, 5, rank=0, n_ranks=2)
    # the world's LAST rank is the designated straggler: it arrives at
    # exactly the spread (the envelope is pinned — the table prices a
    # spread-late straggler), waits for nobody, and every other rank's
    # cost is spread minus its own drawn arrival
    own0, cost0 = axis_skew(7, "ring", 32, 1000, 5, rank=0, n_ranks=2)
    own1, cost1 = axis_skew(7, "ring", 32, 1000, 5, rank=1, n_ranks=2)
    assert own1 == 1000e-6 and cost1 == 0.0
    assert 0.0 <= own0 < 1000e-6
    assert cost0 == pytest.approx(1000e-6 - own0)
    # single-host: rank 0 always waits for the phantom straggler, so
    # the measured slowdown can never be vacuously 1.0
    for run in range(1, 50):
        own, cost = axis_skew(7, "ring", 32, 1000, run)
        assert 0.0 <= own < 1000e-6
        assert cost == pytest.approx(1000e-6 - own) and cost > 0.0


def test_axis_straggler_stays_on_a_real_rank_despite_phantom_fault_ranks():
    """A rank-filtered skew fault naming a rank beyond the real world
    pads the FAULT world with a phantom straggler — but the axis's
    designated straggler must stay the last REAL rank: the envelope
    contract prices a spread-late straggler that actually enters late,
    so the phantom can never steal its seat (driver._entry_skew merges
    the two sources' per-rank totals over separate worlds)."""
    import types

    from tpu_perf.driver import Driver

    spec = [FaultSpec(kind="skew", op="ring", rank=7, magnitude=500.0)]
    built = types.SimpleNamespace(name="ring", nbytes=32)

    def entry(rank, synthetic=None):
        inj = _injector(spec, seed=7, rank=rank, synthetic_s=synthetic)
        fake = types.SimpleNamespace(
            opts=types.SimpleNamespace(fault_seed=7),
            n_hosts=2, rank=rank, injector=inj,
        )
        return Driver._entry_skew(fake, built, 5, 1000), inj

    # synthetic: the fault's world pads to phantom rank 7 (its cost is
    # modeled), but the axis pins the last REAL rank (1) at exactly the
    # spread — the per-rank totals merge over the union, so rank 1's
    # own arrival still carries the full 1000 us envelope
    (own0, cost0), _ = entry(0, synthetic=0.001)
    (own1, _), inj1 = entry(1, synthetic=0.001)
    assert own1 >= 1000e-6 > own0
    assert cost0 > 0.0  # rank 0 waits for the real straggler
    assert any(r["record"] == "fault" for r in inj1.ledger.rows)
    # real timing: a phantom straggler cannot actually sleep, so the
    # spec is skipped — no stagger beyond the axis, and critically no
    # "fired" ledger record demanding a detection that cannot exist
    (own1r, _), inj1r = entry(1)
    assert own1r == pytest.approx(1000e-6)  # axis only
    assert not any(r["record"] == "fault" for r in inj1r.ledger.rows)
    # ...including the MIN_SKEW_WORLD pad: a rank-1 spec on ONE real
    # host is just as phantom as rank 7 on two (the commonest
    # single-host repro of a 2-host spec), so on real timing it must
    # not fire either — the world is EXACTLY the real ranks
    spec1 = [FaultSpec(kind="skew", op="ring", rank=1, magnitude=500.0)]
    inj = _injector(spec1, seed=7, rank=0)
    fake = types.SimpleNamespace(
        opts=types.SimpleNamespace(fault_seed=7),
        n_hosts=1, rank=0, injector=inj,
    )
    own, cost = Driver._entry_skew(fake, built, 5, 0)
    assert (own, cost) == (0.0, 0.0)
    assert not any(r["record"] == "fault" for r in inj.ledger.rows)
    # ...while the synthetic source still models it (the conformance
    # gates' whole premise)
    inj_syn = _injector(spec1, seed=7, rank=0, synthetic_s=0.001)
    fake_syn = types.SimpleNamespace(
        opts=types.SimpleNamespace(fault_seed=7),
        n_hosts=1, rank=0, injector=inj_syn,
    )
    own, cost = Driver._entry_skew(fake_syn, built, 5, 0)
    assert own == 0.0 and cost > 0.0
    assert any(r["record"] == "fault" for r in inj_syn.ledger.rows)


# --- Options: the fence conflicts (satellite) ---------------------------


def test_skew_plus_fused_is_a_loud_options_error():
    with pytest.raises(ValueError, match="fused"):
        Options(skew_spread=(0, 500), fence="fused")
    with pytest.raises(ValueError, match="fused"):
        Options(faults=[FaultSpec(kind="skew")], fence="fused")


def test_skew_plus_finite_trace_is_a_loud_options_error(tmp_path):
    with pytest.raises(ValueError, match="trace"):
        Options(skew_spread=(500,), fence="trace")
    # daemon-mode trace captures per run and supports entry stagger
    assert Options(skew_spread=(500,), fence="trace", num_runs=-1)
    # a spec FILE is loaded so the conflict fails at Options time
    spec = tmp_path / "spec.json"
    spec.write_text(json.dumps({"faults": [{"kind": "skew"}]}))
    with pytest.raises(ValueError, match="fused"):
        Options(faults=str(spec), fence="fused")
    # an unreadable path surfaces as the ValueError Options speaks
    # (cli.main exit 2), never a bare OSError out of the dataclass
    with pytest.raises(ValueError, match="cannot read fault spec"):
        Options(faults=str(tmp_path / "missing.json"))


def test_skew_spread_validation():
    assert Options(skew_spread=(0, 500, 1000)).skew_spread == (0, 500, 1000)
    with pytest.raises(ValueError, match=">= 0"):
        Options(skew_spread=(-1,))
    with pytest.raises(ValueError, match="backend"):
        Options(skew_spread=(500,), backend="mpi")
    with pytest.raises(ValueError, match="extern"):
        Options(skew_spread=(500,), extern_cmd="echo {role}")
    # an all-zero spread is the synchronized plan: no conflict to flag
    assert Options(skew_spread=(0,), fence="fused")


def test_parse_skew_spread_cli_forms():
    from tpu_perf.sweep import parse_skew_spread, parse_time_us

    assert parse_time_us("500") == 500
    assert parse_time_us("250us") == 250
    assert parse_time_us("1ms") == 1000
    assert parse_time_us("2s") == 2_000_000
    with pytest.raises(ValueError, match="unparseable"):
        parse_time_us("fast")
    assert parse_skew_spread("0,250us,1ms") == (0, 250, 1000)
    with pytest.raises(ValueError, match="empty"):
        parse_skew_spread(",")


def test_skew_faults_on_real_timing_without_peers_are_loud_errors(
        tmp_path, capsys):
    """Skew faults the harness provably cannot realize must be exit-2
    errors, not warnings: on real (non-synthetic) timing a
    single-process soak has no peer to observe the stagger, and a
    phantom-rank spec has no process to sleep at all — either way
    `chaos verify` would be guaranteed a critical miss for a detection
    that cannot exist (the --fused-chunks-without-fused precedent).
    Only the Driver knows n_hosts, so the conflict is judged there."""
    from tpu_perf.cli import main

    spec = tmp_path / "spec.json"
    spec.write_text(json.dumps({"faults": [{"kind": "skew",
                                            "op": "ring"}]}))
    rc = main(["chaos", "--faults", str(spec), "--max-runs", "4",
               "--op", "ring", "-b", "32", "-i", "1",
               "-l", str(tmp_path / "d")])
    assert rc == 2
    assert "no peer process" in capsys.readouterr().err
    # --synthetic models the victim cost: the same spec is legal
    rc = main(["chaos", "--faults", str(spec), "--max-runs", "4",
               "--synthetic", "0.001", "--op", "ring", "-b", "32",
               "-i", "1", "--stats-every", "2",
               "-l", str(tmp_path / "ok")])
    assert rc == 0


def test_linkmap_rejects_skew_faults(tmp_path, capsys):
    """The probe stream has no entry boundary to stagger — a skew fault
    reaching linkmap would be a silent no-op, so it is a loud exit 2."""
    from tpu_perf.cli import main

    spec = tmp_path / "spec.json"
    spec.write_text(json.dumps({"faults": [{"kind": "skew"}]}))
    rc = main(["linkmap", "--mesh", "2x4", "--synthetic", "0.001",
               "--faults", str(spec)])
    assert rc == 2
    assert "skew faults apply to the run loop" in capsys.readouterr().err


def test_run_sweep_rejects_the_axis():
    from tpu_perf.runner import run_sweep

    with pytest.raises(ValueError, match="driver path"):
        list(run_sweep(Options(skew_spread=(0, 500)), None))


# --- conformance: victim attribution ------------------------------------


def test_event_matches_attributes_skew_to_victim_ranks():
    from tpu_perf.faults.conformance import _event_matches
    from tpu_perf.health.events import HealthEvent

    def ev(op="ring", rank=0, kind="regression"):
        return HealthEvent(
            timestamp="", job_id="j", kind=kind, severity="warning",
            op=op, nbytes=32, dtype="float32", run_id=70, window=3,
            observed=2.0, baseline=1.0, rank=rank,
        )

    skew = FaultSpec(kind="skew", op="ring", nbytes=32, rank=1)
    # rank 1 staggered; detection on rank 0 (a VICTIM) still counts
    assert _event_matches(skew, "regression", ev(rank=0), 60, 80, 40)
    assert _event_matches(skew, "regression", ev(rank=1), 60, 80, 40)
    # a rank-filtered DELAY keeps the strict rank join
    delay = FaultSpec(kind="delay", op="ring", nbytes=32, rank=1)
    assert not _event_matches(delay, "regression", ev(rank=0), 60, 80, 40)
    # skew-decorated point labels resolve to the base op
    assert _event_matches(skew, "regression", ev(op="ring@500us"), 60, 80, 40)
    assert _event_matches(skew, "regression", ev(op="ring[rhd]@500us"),
                          60, 80, 40)


# --- lockstep proof (satellite): simulated multi-rank -------------------


def test_skewed_rank_keeps_lockstep_run_counts_and_votes():
    """Only rank 1 is skewed; both ranks must execute the SAME runs in
    the same order and the unanimous stop vote must land on the same
    run — the skewed rank enters late but never takes a different code
    path."""
    from tpu_perf.adaptive import AdaptiveConfig, PointController

    spec = [FaultSpec(kind="skew", rank=1, magnitude=2000.0)]
    base = 1e-3
    injectors = {r: _injector(spec, seed=7, rank=r) for r in (0, 1)}
    cfg = AdaptiveConfig(ci_rel=0.5, min_runs=5, max_runs=60)

    # the unanimous vote: the allreduced min of both ranks' local
    # verdicts, exactly what the cross-process collective computes on a
    # real pod — injected here so one process can simulate both seats
    shared_vote = [False]
    controllers = {r: PointController(cfg, n_hosts=2,
                                      vote=lambda local: shared_vote[0])
                   for r in (0, 1)}
    samples = {0: [], 1: []}
    order = {0: [], 1: []}
    stopped = {}
    run = 0
    while not stopped and run < 60:
        run += 1
        for r in (0, 1):
            inj = injectors[r]
            own, cost = inj.entry_skew("ring", 32, run, n_ranks=2)
            # rank 1 sleeps `own` then measures base; rank 0 waits for
            # the straggler inside the collective: base + cost
            t = base + cost
            samples[r].append(t)
            order[r].append(("ring", 32, run))  # the collective call site
            controllers[r].observe(t)
        shared_vote[0] = min(c._local_stop(run)
                             for c in controllers.values())
        for r in (0, 1):
            if controllers[r].should_stop(run):
                stopped[r] = run
    # identical run counts + collective order on both ranks
    assert order[0] == order[1]
    assert stopped and stopped.get(0) == stopped.get(1)
    # the ledgers agree on WHICH runs were skewed (byte-identical
    # modulo each rank's own stagger_us value)
    def fired(inj):
        return [r["run_id"] for r in inj.ledger.rows
                if r.get("record") == "fault"]

    assert fired(injectors[0]) == fired(injectors[1])
    # and the skewed rank really was the slow one's cause: rank 0 saw
    # the inflated samples, rank 1 measured clean
    assert sum(samples[0]) > sum(samples[1]) == pytest.approx(
        base * len(samples[1]))


# --- driver end-to-end: the axis on the synthetic source ----------------


def _axis_soak(tmp_path, logdir, *, spread="0,1000", max_runs=120,
               extra=()):
    from tpu_perf.cli import main

    args = ["chaos", "--seed", "7", "--max-runs", str(max_runs),
            "--synthetic", "0.001", "--op", "ring", "--sweep", "8",
            "-i", "1", "--stats-every", "20", "--health-warmup", "20",
            "--skew-spread", spread, *extra, "-l", str(logdir)]
    assert main(args) == 0
    return logdir


def _rows(logdir):
    rows = []
    for p in sorted(logdir.glob("tpu-*.log")):
        rows += [ResultRow.from_csv(ln)
                 for ln in p.read_text().splitlines()]
    return rows


def test_axis_sweep_rows_and_straggler_cost(eight_devices, tmp_path):
    """A --skew-spread sweep on the synthetic source: rows carry the
    spread coordinate, skewed samples are slower by the modeled arrival
    wait, zero-skew rows keep the pre-skew width, and the report
    renders a straggler-cost table with slowdown > 1."""
    logdir = _axis_soak(tmp_path, tmp_path / "axis")
    rows = _rows(logdir)
    assert {r.skew_us for r in rows} == {0, 1000}
    base = [r for r in rows if r.skew_us == 0]
    skewed = [r for r in rows if r.skew_us == 1000]
    assert len(base) == len(skewed) == 60
    # zero-skew rows render the pre-skew 18-field width byte-for-byte
    assert all(len(r.to_csv().split(",")) == 18 for r in base)
    assert all(len(r.to_csv().split(",")) == 21 for r in skewed)
    # the modeled victim cost is real: skewed p50 above the base p50
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    assert med([r.time_ms for r in skewed]) > med([r.time_ms for r in base])

    from tpu_perf.report import aggregate, compare, straggler_cost

    points = aggregate(rows)
    st = straggler_cost(points)
    assert len(st) == 1
    assert st[0].skew_us == 1000 and st[0].base is not None
    assert st[0].slowdown is not None and st[0].slowdown > 1.0
    # the clean backend pivot never seats a skewed point
    for cmp in compare(points):
        assert cmp.jax is None or cmp.jax.skew_us == 0


def test_skew_axis_builds_once_and_keeps_canon_balanced(eight_devices):
    """Skew is dispatch timing, not build identity: a pipelined (and a
    serial) skew sweep builds each (op, algo, nbytes) triple ONCE,
    measures it per spread on the same pair, and retires exactly the
    references it adopted — the canon must be empty at exit (an
    unbalanced retire would evict shared buffers early and silently
    lose the dedup the plan comment promises)."""
    import io

    from tpu_perf.driver import Driver
    from tpu_perf.parallel import make_mesh

    mesh = make_mesh()
    for precompile in (0, 2):
        opts = Options(op="ring", sweep="8,32", iters=1, num_runs=2,
                       skew_spread=(0, 500), precompile=precompile)
        driver = Driver(opts, mesh, err=io.StringIO())
        rows = driver.run()
        assert not driver._canon and not driver._canon_refs
        assert {(r.op, r.nbytes, r.skew_us) for r in rows} == {
            ("ring", 8, 0), ("ring", 8, 500),
            ("ring", 32, 0), ("ring", 32, 500)}


def test_axis_sweep_is_byte_reproducible(eight_devices, tmp_path):
    """Same seed + spread => byte-identical row payloads (timestamps
    aside — the sample values, coordinates, and widths) and identical
    ledgers: the axis rides the same determinism contract as faults."""
    a = _rows(_axis_soak(tmp_path, tmp_path / "a"))
    b = _rows(_axis_soak(tmp_path, tmp_path / "b"))

    def payload(rows):
        return [(r.op, r.nbytes, r.run_id, r.time_ms, r.skew_us)
                for r in rows]

    assert payload(a) == payload(b)


def test_skew_fault_soak_caught_by_regression_with_identical_ledgers(
        eight_devices, tmp_path, capsys):
    """The conformance loop closed for skew: a planted skew fault on
    the synthetic soak is verdicted CAUGHT by the regression detector,
    and the seeded ledger reproduces byte-identically a/b (with the
    pipelined engine on soak b, the 0b discipline)."""
    from tpu_perf.cli import main

    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps({"faults": [
        {"kind": "skew", "op": "ring", "nbytes": 32, "start": 60,
         "end": 400, "magnitude": 8000},
    ]}))
    extra = []
    for d in ("a", "b"):
        args = ["chaos", "--faults", str(spec_path), "--seed", "7",
                "--max-runs", "400", "--synthetic", "0.001",
                "--op", "ring", "--sweep", "8,32", "-i", "1",
                "--stats-every", "20", "--health-warmup", "20",
                *extra, "-l", str(tmp_path / d)]
        assert main(args) == 0
        extra = ["--precompile", "4"]

    def ledger(d):
        return "".join(p.read_text()
                       for p in sorted((tmp_path / d).glob("chaos-*.log")))

    assert "skew" in ledger("a")
    assert ledger("a") == ledger("b")
    capsys.readouterr()
    rc = main(["chaos", "verify", str(tmp_path / "a")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "| skew |" in out
    assert "1/1 fault(s) caught, 0 critical miss(es)" in out


def test_sampled_soak_keeps_skew_inject_spans_and_join_completeness(
        eight_devices, tmp_path, capsys):
    """Satellite: --spans-sample must always retain skew injection
    spans (`inject` is in SAMPLE_KEEP_KINDS) and `timeline --check`
    must stay join-complete on the sampled soak."""
    from tpu_perf.cli import main
    from tpu_perf.spans import SAMPLE_KEEP_KINDS, read_span_records

    assert "inject" in SAMPLE_KEEP_KINDS
    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps({"faults": [
        {"kind": "skew", "op": "ring", "nbytes": 32, "start": 10,
         "end": 120, "magnitude": 2000},
    ]}))
    logdir = tmp_path / "logs"
    rc = main(["chaos", "--faults", str(spec_path), "--seed", "7",
               "--max-runs", "120", "--synthetic", "0.001",
               "--op", "ring", "--sweep", "8,32", "-i", "1",
               "--stats-every", "20", "--health-warmup", "20",
               "--spans", "--spans-sample", "7", "-l", str(logdir)])
    assert rc == 0
    spans = read_span_records(
        sorted(str(p) for p in logdir.glob("spans-*.log")))
    injects = [s for s in spans if s.get("kind") == "inject"
               and (s.get("attrs") or {}).get("skew")]
    fired = []
    for p in sorted(logdir.glob("chaos-*.log")):
        fired += [json.loads(ln)["run_id"]
                  for ln in p.read_text().splitlines()
                  if json.loads(ln).get("record") == "fault"
                  and json.loads(ln).get("kind") == "skew"]
    # one kept inject span per fired skew run — sampling dropped none
    assert sorted((s.get("attrs") or {}).get("run_id")
                  for s in injects) == sorted(set(fired))
    capsys.readouterr()
    rc = main(["timeline", str(logdir), "--check", "-o",
               str(tmp_path / "trace.json")])
    err = capsys.readouterr().err
    assert rc == 0
    assert "join complete" in err
