import pytest

from tpu_perf.config import Options
from tpu_perf.ops import build_op
from tpu_perf.parallel import make_mesh
from tpu_perf.runner import run_point
from tpu_perf.timing import fence, time_slope, time_step


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh()


def test_readback_fence_matches_block(mesh):
    built = build_op("ring", mesh, 1024, 4)
    rb = time_step(built.step, built.example_input, 3, fence_mode="readback")
    bl = time_step(built.step, built.example_input, 3, fence_mode="block")
    assert all(t > 0 for t in rb.samples + bl.samples)


def test_fence_rejects_unknown():
    with pytest.raises(ValueError):
        fence(None, "maybe")
    built = None
    with pytest.raises(ValueError):
        time_step(lambda x: x, built, 1, fence_mode="slope")


def test_time_slope_positive_and_sane(mesh):
    lo = build_op("hbm_stream", mesh, 1 << 20, 2)
    hi = build_op("hbm_stream", mesh, 1 << 20, 16)
    rt = time_slope(lo.step, hi.step, lo.example_input, 2, 16, 4)
    # noise on a loaded CI host may drop a sample even after the
    # per-sample retries (that drop-not-clamp behavior is itself the
    # contract); most samples surviving, all positive, is the assertion
    assert len(rt.samples) >= 3
    assert all(t > 0 for t in rt.samples)


def test_time_slope_validation(mesh):
    lo = build_op("ring", mesh, 64, 2)
    with pytest.raises(ValueError):
        time_slope(lo.step, lo.step, lo.example_input, 4, 2, 1)
    with pytest.raises(ValueError):
        time_slope(lo.step, lo.step, lo.example_input, 2, 4, 0)


def test_run_point_slope_mode(mesh):
    opts = Options(op="hbm_stream", iters=2, num_runs=3, fence="slope")
    point = run_point(opts, mesh, 1 << 20)
    assert len(point.times.samples) == 3
    rows = point.rows(opts.uuid)
    # hbm_stream busbw counts read+write: 2x algbw
    assert rows[0].busbw_gbps == pytest.approx(2 * rows[0].algbw_gbps, rel=1e-6)


@pytest.mark.parametrize("op,dtype", [
    ("hbm_read", "float32"),
    ("hbm_write", "float32"),
    # bf16 is the dtype where the "carry varies every iteration" argument
    # numerically fails (1.0000001 rounds to 1.0 and +1e-7 rounds away, so
    # the broadcast value is a fixed point): elision is prevented only by
    # XLA not proving the add an identity — which this fence pins.
    ("hbm_write", "bfloat16"),
    # the triad's b half is semantically loop-invariant; this fence pins
    # that XLA does not exploit that to collapse the 2R:1W loop
    ("hbm_triad", "float32"),
])
def test_single_sided_hbm_ops_scale_with_iters(mesh, op, dtype):
    """The single-sided bodies must not be hoisted or dead-store-eliminated
    across fori iterations: 64 iters must cost measurably more than 2.
    This is the load-bearing guard for hbm_write, whose intermediate
    broadcasts are only read back at one element."""
    lo = build_op(op, mesh, 8 << 20, 2, dtype=dtype)
    hi = build_op(op, mesh, 8 << 20, 64, dtype=dtype)
    for attempt in range(2):
        t_lo = min(time_step(lo.step, lo.example_input, 5).samples)
        t_hi = min(time_step(hi.step, hi.example_input, 5).samples)
        if t_hi > t_lo * 1.5:
            return
    assert t_hi > t_lo * 1.5


def test_trace_probe_and_auto_fence_on_cpu(mesh):
    """On the CPU runtime the REAL probe finds no device lanes, so auto
    resolves to slope everywhere — run_point, Driver, grid."""
    import tpu_perf.timing as timing
    from tpu_perf.driver import Driver
    from tpu_perf.timing import resolve_fence, trace_fence_available

    saved = timing._TRACE_PROBED
    timing._TRACE_PROBED = None
    try:
        assert trace_fence_available() is False
        # memoized: second call answers from the cache
        assert timing._TRACE_PROBED is False
        assert resolve_fence("auto") == "slope"
    finally:
        timing._TRACE_PROBED = saved
    assert resolve_fence("slope") == "slope"
    assert resolve_fence("block") == "block"

    opts = Options(op="hbm_stream", iters=2, num_runs=2, fence="auto")
    point = run_point(opts, mesh, 1 << 16)
    assert len(point.times.samples) == 2
    drv = Driver(Options(op="ring", iters=2, num_runs=1, buff_sz=256,
                         fence="auto"), mesh)
    assert drv.opts.fence == "slope"  # resolved once at construction
    assert len(drv.run()) == 1


def test_trace_probe_distinguishes_no_capture_from_unmatched_module(
        monkeypatch):
    """Satellite (ISSUE 5, timing.py): the probe used to latch trace-
    AVAILABLE on ANY TraceParseError — including "the probe produced no
    trace files at all", which means the runtime cannot capture and
    every subsequent trace-fence point is doomed.  A missing capture
    (TraceCaptureMissingError) must resolve to unavailable/slope; only
    lanes-present-but-module-unmatched keeps meaning available."""
    import tpu_perf.timing as timing
    import tpu_perf.traceparse as traceparse
    from tpu_perf.timing import resolve_fence, trace_fence_available
    from tpu_perf.traceparse import TraceCaptureMissingError, TraceParseError

    saved = timing._TRACE_PROBED

    def probe_with(exc):
        def fake_durations(trace_dir, name_hint):
            raise exc
        monkeypatch.setattr(traceparse, "device_module_durations",
                            fake_durations)
        timing._TRACE_PROBED = None
        return trace_fence_available()

    try:
        # no capture at all -> unavailable, auto falls back to slope
        assert probe_with(TraceCaptureMissingError("no capture")) is False
        assert timing._TRACE_PROBED is False
        assert resolve_fence("auto") == "slope"
        # lanes present, probe module unmatched -> the lane support the
        # auto fence selects on IS there
        assert probe_with(TraceParseError("no module matches hint")) is True
        assert timing._TRACE_PROBED is True
    finally:
        timing._TRACE_PROBED = saved


def test_trace_files_raise_capture_missing(tmp_path):
    """traceparse._trace_files types the no-capture cases so the probe
    (and only the probe) can tell them apart from parse failures; both
    remain TraceParseError subclasses for every drop-the-sample caller."""
    import os

    from tpu_perf.traceparse import (
        TraceCaptureMissingError, TraceParseError, device_module_durations,
    )

    with pytest.raises(TraceCaptureMissingError):
        device_module_durations(str(tmp_path), None)  # no session dir
    os.makedirs(tmp_path / "plugins" / "profile" / "2026_01_01")
    with pytest.raises(TraceCaptureMissingError) as ei:
        device_module_durations(str(tmp_path), None)  # no trace.json.gz
    assert isinstance(ei.value, TraceParseError)  # callers' contract


def test_hbm_stream_scales_with_iters(mesh):
    """The stream body must not fold across iterations: 64 iters must cost
    measurably more than 2 (guards against XLA collapsing the loop)."""
    lo = build_op("hbm_stream", mesh, 8 << 20, 2)
    hi = build_op("hbm_stream", mesh, 8 << 20, 64)
    # A collapsed loop shows ratio ~1.0 regardless of load; a real 32x iter
    # ratio sits far above 1.5 even on a contended CI host. The 1.5 bound is
    # deliberately looser than proportional scaling would suggest: the point
    # is to catch total collapse (~1.0), not to pin the scaling constant.
    for attempt in range(2):
        t_lo = min(time_step(lo.step, lo.example_input, 5).samples)
        t_hi = min(time_step(hi.step, hi.example_input, 5).samples)
        if t_hi > t_lo * 1.5:
            return
    assert t_hi > t_lo * 1.5
