"""Numeric correctness of the Pallas RDMA kernels under the TPU interpreter
(pltpu.InterpretParams simulates semaphores + remote DMA on CPU devices)."""

import jax
import numpy as np
import pytest

from tpu_perf.ops import build_op
from tpu_perf.ops.pallas_ring import build_pallas_step
from tpu_perf.parallel import make_mesh


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh()


def _run(built):
    return np.asarray(jax.device_get(built.step(built.example_input)))


def test_pl_ring_single_shift(mesh):
    built = build_op("pl_ring", mesh, 16 * 4, 1)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    np.testing.assert_allclose(out, np.roll(x, 1, axis=0), rtol=1e-6)


def test_pl_ring_identity_after_n(mesh):
    built = build_op("pl_ring", mesh, 16 * 4, 8)
    x = np.asarray(jax.device_get(built.example_input))
    np.testing.assert_allclose(_run(built), x, rtol=1e-6)


def test_pl_all_to_all_transposes_chunks(mesh):
    built = build_op("pl_all_to_all", mesh, 8 * 4 * 4, 1)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, 8, -1)
    out = _run(built).reshape(8, 8, -1)
    # out[m] chunk s == x[s] chunk m (the XLA all_to_all transpose)
    np.testing.assert_allclose(out, x.transpose(1, 0, 2), rtol=1e-6)


def test_pl_all_to_all_involution(mesh):
    # two applications = identity, so chained even iters return the input
    built = build_op("pl_all_to_all", mesh, 8 * 4 * 4, 2)
    x = np.asarray(jax.device_get(built.example_input))
    np.testing.assert_allclose(_run(built), x, rtol=1e-6)


def test_pl_barrier_rejects_single_device():
    # VERDICT r2 #7: at n=1 every signal is a self-signal — a run would
    # record a local semaphore round-trip under an ICI-latency label
    mesh1 = make_mesh(devices=jax.devices()[:1])
    with pytest.raises(ValueError, match="self-signal"):
        build_pallas_step("pl_barrier", mesh1, 4, 1)


def test_pl_barrier_identity_and_latency_only(mesh):
    # the barrier moves no payload: output is the (1-element) input, and
    # rows carry latency only (bus factor 0)
    from tpu_perf.config import Options
    from tpu_perf.runner import run_point, sizes_for

    built = build_op("pl_barrier", mesh, 4096, 3)
    x = np.asarray(jax.device_get(built.example_input))
    assert built.nbytes == 4  # fixed 1 float32 element regardless of -b
    np.testing.assert_array_equal(_run(built), x)

    opts = Options(op="pl_barrier", iters=2, num_runs=1, sweep="8,64K,1M")
    assert len(sizes_for(opts)) == 1  # sweep collapses, like barrier
    (row,) = run_point(opts, mesh, 4096).rows("job")
    assert row.busbw_gbps == 0.0 and row.lat_us > 0


def test_pl_hbm_copy_identity(mesh):
    # a local HBM->HBM DMA copy is an exact identity, chained or not
    built = build_op("pl_hbm_copy", mesh, 16 * 4, 3)
    x = np.asarray(jax.device_get(built.example_input))
    np.testing.assert_allclose(_run(built), x, rtol=0)


def test_pl_hbm_copy_rows_busbw_factor_two(mesh):
    # rows count read + write traffic, like hbm_stream
    from tpu_perf.config import Options
    from tpu_perf.runner import run_point

    opts = Options(op="pl_hbm_copy", iters=2, num_runs=1)
    point = run_point(opts, mesh, 4096)
    (row,) = point.rows("job")
    assert row.busbw_gbps == pytest.approx(2 * row.algbw_gbps)


def test_pl_exchange_swaps_pairs(mesh):
    built = build_op("pl_exchange", mesh, 16 * 4, 1)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    for i in range(4):
        np.testing.assert_allclose(out[i], x[i + 4], rtol=1e-6)
        np.testing.assert_allclose(out[i + 4], x[i], rtol=1e-6)


def test_pl_exchange_involution(mesh):
    built = build_op("pl_exchange", mesh, 16 * 4, 2)
    x = np.asarray(jax.device_get(built.example_input))
    np.testing.assert_allclose(_run(built), x, rtol=1e-6)


def test_pl_all_gather_identity(mesh):
    # gather + take-own-shard == identity (same contract as the XLA op)
    built = build_op("pl_all_gather", mesh, 8 * 8 * 4, 2)
    x = np.asarray(jax.device_get(built.example_input))
    np.testing.assert_allclose(_run(built), x, rtol=1e-6)
    assert built.nbytes == 8 * 8 * 4  # gathered-total semantics


def test_pl_all_gather_gathers_every_chunk(mesh):
    """Drive the pallas_call directly (iters wrapper slices own shard) to
    check every chunk lands in ring order."""
    from jax.sharding import PartitionSpec as P

    from tpu_perf.ops.pallas_ring import build_pallas_step

    step, x, actual, n = build_pallas_step("pl_all_gather", make_mesh(), 8 * 4 * 4, 1)
    # one iteration returns own shard; instead check via numerics of 2 iters
    out = np.asarray(jax.device_get(step(x)))
    np.testing.assert_allclose(out, np.asarray(jax.device_get(x)), rtol=1e-6)
    assert n == 8 and actual == 8 * 4 * 4
    assert P  # silence linters


def test_pl_allreduce_matches_mean(mesh):
    # one application == per-element mean over devices (the 1/n-scaled psum
    # convention of the XLA allreduce body); every device gets the same value
    built = build_op("pl_allreduce", mesh, 16 * 4, 1)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    want = np.broadcast_to(x.mean(axis=0), x.shape)
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_pl_allreduce_idempotent_when_chained(mesh):
    # mean-of-identical-rows is a fixed point, so chained iters are stable
    built = build_op("pl_allreduce", mesh, 16 * 4, 3)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    want = np.broadcast_to(x.mean(axis=0), x.shape)
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_pl_reduce_scatter_matches_psum_scatter(mesh):
    # device d's chunk == mean over devices of chunk d, tiled n times
    # (the same carry convention as the XLA reduce_scatter body)
    built = build_op("pl_reduce_scatter", mesh, 8 * 4 * 4, 1)
    n = 8
    x = np.asarray(jax.device_get(built.example_input)).reshape(n, n, -1)
    out = _run(built).reshape(n, n, -1)
    red = x.mean(axis=0)  # (chunk_idx, chunk_elems)
    for d in range(n):
        for rep in range(n):
            np.testing.assert_allclose(out[d, rep], red[d], rtol=1e-5)


def test_pl_allreduce_multi_tile_accumulation(mesh, monkeypatch):
    # force chunk > tile so the VMEM-tiled accumulate loop runs with
    # ntiles > 1 (and chunk rounds up to a whole number of tiles)
    import tpu_perf.ops.pallas_ring as pr

    monkeypatch.setattr(pr, "_ACC_TILE_ELEMS", 4)
    built = build_op("pl_allreduce", mesh, 8 * 10 * 4, 1)  # raw chunk 10 -> 12
    assert built.nbytes == 8 * 12 * 4
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    np.testing.assert_allclose(
        out, np.broadcast_to(x.mean(axis=0), x.shape), rtol=1e-5
    )


def test_pl_reduce_scatter_rounds_to_device_multiple(mesh):
    built = build_op("pl_reduce_scatter", mesh, 13, 1)
    assert built.nbytes % (8 * 4) == 0  # whole chunks of float32 per device


def test_pl_allreduce_odd_device_count(eight_devices):
    # ring reduce-scatter/all-gather are valid for any n >= 2
    mesh5 = make_mesh(devices=jax.devices()[:5])
    built = build_op("pl_allreduce", mesh5, 5 * 4 * 4, 1)
    x = np.asarray(jax.device_get(built.example_input)).reshape(5, -1)
    out = _run(built).reshape(5, -1)
    np.testing.assert_allclose(
        out, np.broadcast_to(x.mean(axis=0), x.shape), rtol=1e-5
    )


def test_pallas_ops_reject_multi_axis_mesh(eight_devices):
    # a sub-axis ring would RDMA to wrong logical devices and deadlock
    mesh2d = make_mesh((2, 4), ("dcn", "ici"))
    with pytest.raises(ValueError):
        build_op("pl_exchange", mesh2d, 64, 1)


def test_pallas_ops_reject_window(mesh):
    with pytest.raises(ValueError):
        build_op("pl_ring", mesh, 64, 1, window=4)


def test_pl_pingpong_round_trip_identity(mesh):
    # the round trip returns group 0's payload and group 1 keeps its own via
    # the local copy — an exact identity on every device.  A mis-dispatch to
    # the exchange kernel would swap the pair halves and fail here.
    built = build_op("pl_pingpong", mesh, 16 * 4, 1)
    x = np.asarray(jax.device_get(built.example_input))
    np.testing.assert_allclose(_run(built), x, rtol=1e-6)


def test_pl_pingpong_chained_iters(mesh):
    built = build_op("pl_pingpong", mesh, 16 * 4, 3)
    x = np.asarray(jax.device_get(built.example_input))
    np.testing.assert_allclose(_run(built), x, rtol=1e-6)


def test_pl_pingpong_needs_even(eight_devices):
    mesh5 = make_mesh(devices=jax.devices()[:5])
    with pytest.raises(ValueError):
        build_op("pl_pingpong", mesh5, 64, 1)


def test_pl_all_gather_bidir_identity(mesh):
    # gather + take-own-shard == identity (same contract as pl_all_gather)
    built = build_op("pl_all_gather_bidir", mesh, 8 * 8 * 4, 2)
    x = np.asarray(jax.device_get(built.example_input))
    np.testing.assert_allclose(_run(built), x, rtol=1e-6)
    assert built.nbytes == 8 * 8 * 4  # gathered-total semantics


def test_pl_all_gather_bidir_rounds_chunk_to_even(mesh):
    # per-device shard splits into two half-chunks, so odd chunks round up
    built = build_op("pl_all_gather_bidir", mesh, 8 * 3 * 4, 1)  # chunk 3 -> 4
    assert built.nbytes == 8 * 4 * 4


def test_pl_all_gather_bidir_gathers_every_chunk(eight_devices):
    """Drive the raw kernel (no take-own-shard wrapper) and check every
    device ends with the full gathered buffer in ring order — both the
    clockwise half-chunks and the counter-clockwise ones."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from tpu_perf.ops.pallas_ring import (
        _COLLECTIVE_IDS,
        _all_gather_bidir_kernel,
    )

    n, chunk = 8, 4
    mesh = make_mesh()
    axis = mesh.axis_names[0]
    kern = _all_gather_bidir_kernel(axis, n, chunk)
    step_sems = pltpu.SemaphoreType.DMA((n - 1,))

    def call(x):
        return pl.pallas_call(
            kern,
            out_shape=jax.ShapeDtypeStruct((chunk * n,), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA,
                step_sems, step_sems, step_sems, step_sems,
            ],
            compiler_params=pltpu.CompilerParams(
                collective_id=_COLLECTIVE_IDS["pl_all_gather_bidir"]
            ),
            interpret=pltpu.InterpretParams(),
        )(x)

    step = jax.jit(
        jax.shard_map(call, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
                      check_vma=False)
    )
    host = np.arange(n * chunk, dtype=np.float32)
    x = jax.device_put(
        jnp.asarray(host), NamedSharding(mesh, P(axis))
    )
    out = np.asarray(jax.device_get(step(x))).reshape(n, n * chunk)
    for d in range(n):
        np.testing.assert_allclose(out[d], host, rtol=1e-6)


def test_pl_exchange_needs_even(eight_devices):
    mesh5 = make_mesh(devices=jax.devices()[:5])
    with pytest.raises(ValueError):
        build_op("pl_exchange", mesh5, 64, 1)
    # ring works on odd counts
    built = build_op("pl_ring", mesh5, 40, 5)
    x = np.asarray(jax.device_get(built.example_input))
    np.testing.assert_allclose(_run(built), x, rtol=1e-6)


def test_pl_hbm_stream_matches_xla_body(mesh):
    # the vector-path stream applies the exact wrap-add body of the XLA
    # hbm_stream, chained over iters
    built = build_op("pl_hbm_stream", mesh, 64 * 1024, 3)
    x = np.asarray(jax.device_get(built.example_input))
    exp = x
    for _ in range(3):
        exp = exp * np.float32(1.0000001) + np.float32(1e-7)
    np.testing.assert_allclose(_run(built), exp, rtol=1e-5)


def test_pl_hbm_stream_int_wrap_add(mesh):
    # integer dtypes use the wrapping +1 (the honesty fix for int
    # payloads: the float constants cast to an XLA-elidable identity)
    built = build_op("pl_hbm_stream", mesh, 4096, 5, dtype="int32")
    x = np.asarray(jax.device_get(built.example_input))
    np.testing.assert_array_equal(_run(built), x + 5)


def test_pl_hbm_stream_lands_on_hbm_stream_curve_key(mesh, monkeypatch):
    # sizes that are NOT a tile multiple must still record the exact
    # hbm_stream nbytes (the partial last block is masked, not padded) —
    # otherwise --compare-pallas cannot pair the triangulation rows
    import tpu_perf.ops.pallas_ring as pr

    monkeypatch.setattr(pr, "_STREAM_TILE_ELEMS", 64)
    odd = 8 * 100 * 4  # 100 elems/device: 1 full tile of 64 + partial 36
    pl_built = build_op("pl_hbm_stream", mesh, odd, 2)
    xla_built = build_op("hbm_stream", mesh, odd, 2)
    assert pl_built.nbytes == xla_built.nbytes == odd
    x = np.asarray(jax.device_get(pl_built.example_input))
    exp = x
    for _ in range(2):
        exp = exp * np.float32(1.0000001) + np.float32(1e-7)
    np.testing.assert_allclose(_run(pl_built), exp, rtol=1e-5)


def test_pl_hbm_stream_bf16_small_tile_masking(mesh, monkeypatch):
    # bf16 tiles are half the f32 element count (scoped-VMEM limit on
    # packed sublanes); a non-multiple size still computes correctly
    # through the masked last block
    import tpu_perf.ops.pallas_ring as pr

    monkeypatch.setattr(pr, "_STREAM_TILE_ELEMS", 128)  # bf16 tile: 64
    built = build_op("pl_hbm_stream", mesh, 8 * 100 * 2, 2, dtype="bfloat16")
    assert built.nbytes == 8 * 100 * 2
    x = np.asarray(jax.device_get(built.example_input)).astype(np.float64)
    exp = x
    for _ in range(2):
        exp = exp * 1.0000001 + 1e-7
    np.testing.assert_allclose(
        _run(built).astype(np.float64), exp, rtol=1e-2
    )


def test_pl_hbm_read_exact_identity(mesh):
    # the read sweep never writes: output aliases the input buffer
    built = build_op("pl_hbm_read", mesh, 16 * 4, 3)
    x = np.asarray(jax.device_get(built.example_input))
    np.testing.assert_array_equal(_run(built), x)


def test_pl_hbm_write_tiles_first_block(mesh, monkeypatch):
    # shrink the DMA block so multiple blocks fit an interpreter-sized
    # buffer; output = first block tiled, with a trailing partial block
    # (elems rounds UP to the 4 KiB Mosaic tile, like build_pallas_step —
    # the assertion below pins 770 -> 1024 elems)
    import tpu_perf.ops.pallas_ring as pr

    monkeypatch.setattr(pr, "_STREAM_TILE_ELEMS", 256)
    built = build_op("pl_hbm_write", mesh, 3 * 256 * 4 + 8, 2)
    per = built.nbytes // 4
    assert per == 1024  # rounds UP to the 4 KiB Mosaic tile, then 4 blocks
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    np.testing.assert_allclose(out, np.tile(x[:, :256], 4), rtol=1e-6)


def test_pl_hbm_write_partial_tail_block(mesh, monkeypatch):
    # a 4 KiB-aligned size that is NOT a whole number of DMA blocks: the
    # kernel's trailing partial DMA writes the seed block's prefix
    import tpu_perf.ops.pallas_ring as pr

    monkeypatch.setattr(pr, "_STREAM_TILE_ELEMS", 2048)  # f32 block = 2048
    built = build_op("pl_hbm_write", mesh, 3 * 4096, 2)  # 3072 elems
    per = built.nbytes // 4
    assert per == 3072  # one full 2048 block + a 1024 partial tail
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    want = np.concatenate([x[:, :2048], x[:, :1024]], axis=1)
    np.testing.assert_allclose(out, want, rtol=1e-6)


def test_pl_hbm_single_sided_land_on_xla_curve_keys(mesh):
    # any 4 KiB-multiple size (every practical sweep point) must produce
    # the SAME nbytes as the XLA counterpart so --compare-pallas pairs
    # the rows; below that granularity the DMA tiling forces a rounding
    # the XLA family does not have, reported via actual nbytes
    for pl_op, xla_op in (("pl_hbm_read", "hbm_read"),
                          ("pl_hbm_write", "hbm_write")):
        pl_built = build_op(pl_op, mesh, 11 * 4096, 1)
        xla_built = build_op(xla_op, mesh, 11 * 4096, 1)
        assert pl_built.nbytes == xla_built.nbytes
        odd = build_op(pl_op, mesh, 4 * 1000 + 3, 1)
        assert odd.nbytes == 4096  # rounded to the Mosaic tile


def test_pl_hbm_write_selftest_model_uses_native_itemsize(mesh, monkeypatch):
    # regression: the selftest composes float models in float64, whose
    # itemsize would pick a 2x DMA block and fail exactly half the buffer
    import tpu_perf.ops.pallas_ring as pr
    from tpu_perf.selftest import run_selftest

    monkeypatch.setattr(pr, "_STREAM_TILE_ELEMS", 256)
    for dtype in ("float32", "bfloat16", "uint8"):
        results = run_selftest(mesh, ops=["pl_hbm_read", "pl_hbm_write"],
                               nbytes=8 * 2 * 256 * 4 + 8, dtype=dtype, iters=2)
        assert all(r.status == "ok" for r in results), (dtype, results)


def test_pl_hbm_single_sided_rows_busbw_factor_one(mesh):
    from tpu_perf.config import Options
    from tpu_perf.runner import run_point

    for op in ("pl_hbm_read", "pl_hbm_write"):
        opts = Options(op=op, iters=2, num_runs=1)
        point = run_point(opts, mesh, 4096)
        (row,) = point.rows("job")
        assert row.busbw_gbps == pytest.approx(row.algbw_gbps)
