"""The operating-point grid tool: verdict rules, chosen-cell marking, and
the CLI end to end (BASELINE.md headline methodology as a command)."""

import dataclasses

import pytest

from tpu_perf.grid import GridCell, grid_to_markdown, judge, mark_chosen


def _cell(p50, verdict, **kw):
    base = dict(op="hbm_stream", nbytes=1 << 20, dtype="float32", iters=4,
                n_devices=1, runs=8, drops=0, busbw_p25=p50 * 0.9,
                busbw_p50=p50, busbw_p75=p50 * 1.1, busbw_max=p50 * 1.2,
                lat_p50_us=10.0, verdict=verdict)
    base.update(kw)
    return GridCell(**base)


def test_judge_rules():
    # the round-2/3 conventions: above spec = jitter, below floor = soft
    # window, otherwise ok; each bound optional
    assert judge(900.0, 819.0, 600.0) == "unphysical"
    assert judge(650.0, 819.0, 600.0) == "ok"
    assert judge(500.0, 819.0, 600.0) == "degraded"
    assert judge(1e9, None, None) == "ok"  # no spec: nothing to reject
    assert judge(1.0, None, 600.0) == "degraded"


def test_mark_chosen_picks_best_ok():
    cells = [
        _cell(900.0, "unphysical"),
        _cell(650.0, "ok"),
        _cell(660.0, "ok", iters=16),
        _cell(500.0, "degraded"),
    ]
    marked = mark_chosen(cells)
    chosen = [c for c in marked if c.chosen]
    assert len(chosen) == 1
    assert chosen[0].busbw_p50 == 660.0
    # an unphysical cell with the highest p50 must never win
    assert not any(c.chosen for c in marked if c.verdict != "ok")


def test_mark_chosen_no_ok_cells():
    cells = [_cell(900.0, "unphysical"), _cell(1.0, "failed", runs=0, drops=8)]
    assert not any(c.chosen for c in mark_chosen(cells))


def test_grid_markdown_renders_verdicts_and_notes():
    cells = mark_chosen([
        _cell(650.0, "ok"),
        dataclasses.replace(_cell(900.0, "unphysical"),
                            note="max>spec (slope artifact)"),
    ])
    md = grid_to_markdown(cells)
    assert "**ok — chosen**" in md
    assert "unphysical (max>spec (slope artifact))" in md
    assert "iters (lo/hi)" in md and "| 4/16 |" in md
    # non-slope fences time a single compilation: no lo/hi pair
    md_block = grid_to_markdown(cells, fence="block")
    assert "| 4 |" in md_block and "lo/hi" not in md_block


def test_run_grid_records_failures_without_losing_the_grid(eight_devices):
    from tpu_perf.grid import run_grid
    from tpu_perf.parallel import make_mesh

    mesh = make_mesh()
    # hier_allreduce needs a (dcn, ici) mesh: every cell fails to build,
    # but the grid returns one failed cell per point instead of raising
    cells = run_grid(mesh, "hier_allreduce", [1024], [2], runs=2)
    (cell,) = cells
    assert cell.verdict == "failed"
    assert "2-axis" in cell.note
    assert not cell.chosen


def test_cli_grid_end_to_end(eight_devices, capsys):
    from tpu_perf.cli import main

    rc = main(["grid", "--op", "ring", "--sizes", "4K,64K", "--iters",
               "2", "-r", "2", "--spec-gbps", "1e9"])
    captured = capsys.readouterr()
    assert rc == 0
    assert captured.out.count("| ring |") == 2
    assert "chosen operating point: ring" in captured.err
    # an impossible spec rejects every cell -> exit 4, nothing chosen
    rc = main(["grid", "--op", "ring", "--sizes", "4K", "--iters", "2",
               "-r", "2", "--spec-gbps", "1e-9"])
    captured = capsys.readouterr()
    assert rc == 4
    assert "no ok operating point" in captured.err
    assert "unphysical" in captured.out


def test_mark_chosen_is_per_op():
    # a family grid picks one operating point per instrument
    cells = mark_chosen([
        _cell(650.0, "ok", op="hbm_stream"),
        _cell(660.0, "ok", op="hbm_stream", iters=16),
        _cell(700.0, "ok", op="hbm_read"),
    ])
    chosen = {c.op: c.busbw_p50 for c in cells if c.chosen}
    assert chosen == {"hbm_stream": 660.0, "hbm_read": 700.0}


def test_run_grid_family_measures_every_op(eight_devices):
    from tpu_perf.grid import run_grid
    from tpu_perf.parallel import make_mesh

    cells = run_grid(make_mesh(), "ring,hbm_stream", [1024], [2], runs=2)
    assert {c.op for c in cells} == {"ring", "hbm_stream"}
    assert sum(c.chosen for c in cells) == 2  # one per op


def test_run_grid_rejects_latency_only_ops(eight_devices):
    import pytest as _pytest

    from tpu_perf.grid import run_grid
    from tpu_perf.parallel import make_mesh

    with _pytest.raises(ValueError, match="latency-only"):
        run_grid(make_mesh(), "barrier", [1024], [2], runs=2)


def test_op_for_options_rejects_family():
    # regression: a comma family reaching a single-kernel path must fail
    # loudly, not silently truncate to the first op
    from tpu_perf.config import Options
    from tpu_perf.runner import op_for_options

    with pytest.raises(ValueError, match="family"):
        op_for_options(Options(op="ring,hbm_stream"))


def test_cli_grid_family_exit_on_partial_failure(eight_devices, capsys):
    # one op chooses a point, the other fails every cell -> exit 4 naming
    # the op that has no operating point
    from tpu_perf.cli import main

    rc = main(["grid", "--op", "ring,hier_allreduce", "--sizes", "4K",
               "--iters", "2", "-r", "2"])
    captured = capsys.readouterr()
    assert rc == 4
    assert "chosen operating point: ring" in captured.err
    assert "no ok operating point for hier_allreduce" in captured.err


def test_run_grid_rejects_unknown_and_empty_ops(eight_devices):
    import pytest as _pytest

    from tpu_perf.grid import run_grid
    from tpu_perf.parallel import make_mesh

    mesh = make_mesh()
    with _pytest.raises(ValueError, match="unknown op"):
        run_grid(mesh, "hbm_read,hbm_raed", [1024], [2], runs=2)
    with _pytest.raises(ValueError, match="at least one op"):
        run_grid(mesh, ",", [1024], [2], runs=2)


def test_ops_for_options_rejects_empty_family():
    import pytest as _pytest

    from tpu_perf.config import Options
    from tpu_perf.runner import ops_for_options

    with _pytest.raises(ValueError, match="empty op family"):
        ops_for_options(Options(op=","))


def test_judge_p75_above_spec_is_unphysical():
    # a hot window can keep p50 under the spec while a quarter of the
    # samples exceed it — the cell is jitter-widened, not a plateau
    assert judge(762.0, 819.0, 600.0, busbw_p75=955.0) == "unphysical"
    assert judge(762.0, 819.0, 600.0, busbw_p75=800.0) == "ok"
    assert judge(762.0, None, 600.0, busbw_p75=955.0) == "ok"  # no spec


def test_mark_chosen_prefers_stability_over_max_p50():
    # the jitter-inflated cell has the highest p50 but a wide IQR; the
    # plateau cell's tight IQR wins
    wide = _cell(762.0, "ok", busbw_p25=633.0, busbw_p75=810.0)
    tight = _cell(665.0, "ok", iters=16, busbw_p25=650.0, busbw_p75=672.0)
    marked = mark_chosen([wide, tight])
    (chosen,) = [c for c in marked if c.chosen]
    assert chosen.busbw_p50 == 665.0


def test_mark_chosen_bandwidth_guard_excludes_low_cells():
    # a tiny latency-dominated cell with quantized samples has rel IQR ~0
    # but must NOT beat the plateau: stability only competes within 80%
    # of the best ok p50
    quantized = _cell(15.0, "ok", nbytes=1 << 20,
                      busbw_p25=15.0, busbw_p75=15.0)
    plateau = _cell(640.0, "ok", iters=25,
                    busbw_p25=626.0, busbw_p75=669.0)
    marked = mark_chosen([quantized, plateau])
    (chosen,) = [c for c in marked if c.chosen]
    assert chosen.busbw_p50 == 640.0


def test_run_grid_notes_jitter_widened_cells(eight_devices, monkeypatch):
    # wire the p75 rule through run_grid with a fake measurement
    from tpu_perf import grid as grid_mod
    from tpu_perf.parallel import make_mesh

    class FakeTimes:
        samples = [0.001, 0.001, 0.0001]  # one wild sample -> p75 blows up
        overhead_s = 0.0

    class FakePoint:
        op, nbytes, n_devices, iters, dtype = "ring", 1024, 8, 2, "float32"
        times = FakeTimes()

        def rows(self, job):
            from tpu_perf.runner import SweepPointResult

            return SweepPointResult(
                op="ring", nbytes=1024, iters=2, n_devices=8,
                times=FakeTimes(),
            ).rows(job)

    monkeypatch.setattr(grid_mod, "run_point",
                        lambda opts, mesh, nbytes: FakePoint())
    cells = grid_mod.run_grid(make_mesh(), "ring", [1024], [2], runs=3,
                              spec_gbps=0.005)
    (cell,) = cells
    assert cell.verdict == "unphysical"
    # the p50 must be UNDER the spec (else the plain rule fires and this
    # test stops exercising the p75 path) and the note must say why
    assert cell.busbw_p50 <= 0.005
    assert "jitter-widened" in cell.note
