"""The operating-point grid tool: verdict rules, chosen-cell marking, and
the CLI end to end (BASELINE.md headline methodology as a command)."""

import dataclasses

import pytest

from tpu_perf.grid import GridCell, grid_to_markdown, judge, mark_chosen


def _cell(p50, verdict, **kw):
    base = dict(op="hbm_stream", nbytes=1 << 20, dtype="float32", iters=4,
                n_devices=1, runs=8, drops=0, p25=p50 * 0.9,
                p50=p50, p75=p50 * 1.1, vmax=p50 * 1.2,
                lat_p50_us=10.0, verdict=verdict)
    base.update(kw)
    return GridCell(**base)


def test_judge_rules():
    # the round-2/3 conventions: above spec = jitter, below floor = soft
    # window, otherwise ok; each bound optional
    assert judge(900.0, 819.0, 600.0) == "unphysical"
    assert judge(650.0, 819.0, 600.0) == "ok"
    assert judge(500.0, 819.0, 600.0) == "degraded"
    assert judge(1e9, None, None) == "ok"  # no spec: nothing to reject
    assert judge(1.0, None, 600.0) == "degraded"


def test_mark_chosen_picks_best_ok():
    cells = [
        _cell(900.0, "unphysical"),
        _cell(650.0, "ok"),
        _cell(660.0, "ok", iters=16),
        _cell(500.0, "degraded"),
    ]
    marked = mark_chosen(cells)
    chosen = [c for c in marked if c.chosen]
    assert len(chosen) == 1
    assert chosen[0].p50 == 660.0
    # an unphysical cell with the highest p50 must never win
    assert not any(c.chosen for c in marked if c.verdict != "ok")


def test_mark_chosen_no_ok_cells():
    cells = [_cell(900.0, "unphysical"), _cell(1.0, "failed", runs=0, drops=8)]
    assert not any(c.chosen for c in mark_chosen(cells))


def test_grid_markdown_renders_verdicts_and_notes():
    cells = mark_chosen([
        _cell(650.0, "ok"),
        dataclasses.replace(_cell(900.0, "unphysical"),
                            note="max>spec (slope artifact)"),
    ])
    md = grid_to_markdown(cells)
    assert "**ok — chosen**" in md
    assert "unphysical (max>spec (slope artifact))" in md
    assert "iters (lo/hi)" in md and "| 4/16 |" in md
    # non-slope fences time a single compilation: no lo/hi pair
    md_block = grid_to_markdown(cells, fence="block")
    assert "| 4 |" in md_block and "lo/hi" not in md_block


def test_run_grid_records_failures_without_losing_the_grid(eight_devices):
    from tpu_perf.grid import run_grid
    from tpu_perf.parallel import make_mesh

    mesh = make_mesh()
    # hier_allreduce needs a (dcn, ici) mesh: every cell fails to build,
    # but the grid returns one failed cell per point instead of raising
    cells = run_grid(mesh, "hier_allreduce", [1024], [2], runs=2)
    (cell,) = cells
    assert cell.verdict == "failed"
    assert "2-axis" in cell.note
    assert not cell.chosen


def test_cli_grid_end_to_end(eight_devices, capsys):
    from tpu_perf.cli import main

    rc = main(["grid", "--op", "ring", "--sizes", "4K,64K", "--iters",
               "2", "-r", "2", "--spec-gbps", "1e9"])
    captured = capsys.readouterr()
    assert rc == 0
    assert captured.out.count("| ring |") == 2
    assert "chosen operating point: ring" in captured.err
    # an impossible spec rejects every cell -> exit 4, nothing chosen
    rc = main(["grid", "--op", "ring", "--sizes", "4K", "--iters", "2",
               "-r", "2", "--spec-gbps", "1e-9"])
    captured = capsys.readouterr()
    assert rc == 4
    assert "no ok operating point" in captured.err
    assert "unphysical" in captured.out


def test_mark_chosen_is_per_op():
    # a family grid picks one operating point per instrument
    cells = mark_chosen([
        _cell(650.0, "ok", op="hbm_stream"),
        _cell(660.0, "ok", op="hbm_stream", iters=16),
        _cell(700.0, "ok", op="hbm_read"),
    ])
    chosen = {c.op: c.p50 for c in cells if c.chosen}
    assert chosen == {"hbm_stream": 660.0, "hbm_read": 700.0}


def test_run_grid_family_measures_every_op(eight_devices):
    from tpu_perf.grid import run_grid
    from tpu_perf.parallel import make_mesh

    cells = run_grid(make_mesh(), "ring,hbm_stream", [1024], [2], runs=2)
    assert {c.op for c in cells} == {"ring", "hbm_stream"}
    assert sum(c.chosen for c in cells) == 2  # one per op


def test_run_grid_rejects_latency_only_ops(eight_devices):
    import pytest as _pytest

    from tpu_perf.grid import run_grid
    from tpu_perf.parallel import make_mesh

    with _pytest.raises(ValueError, match="latency-only"):
        run_grid(make_mesh(), "barrier", [1024], [2], runs=2)


def test_op_for_options_rejects_family():
    # regression: a comma family reaching a single-kernel path must fail
    # loudly, not silently truncate to the first op
    from tpu_perf.config import Options
    from tpu_perf.runner import op_for_options

    with pytest.raises(ValueError, match="family"):
        op_for_options(Options(op="ring,hbm_stream"))


def test_cli_grid_family_exit_on_partial_failure(eight_devices, capsys):
    # one op chooses a point, the other fails every cell -> exit 4 naming
    # the op that has no operating point
    from tpu_perf.cli import main

    rc = main(["grid", "--op", "ring,hier_allreduce", "--sizes", "4K",
               "--iters", "2", "-r", "2"])
    captured = capsys.readouterr()
    assert rc == 4
    assert "chosen operating point: ring" in captured.err
    assert "no ok operating point for hier_allreduce" in captured.err


def test_run_grid_rejects_unknown_and_empty_ops(eight_devices):
    import pytest as _pytest

    from tpu_perf.grid import run_grid
    from tpu_perf.parallel import make_mesh

    mesh = make_mesh()
    with _pytest.raises(ValueError, match="unknown op"):
        run_grid(mesh, "hbm_read,hbm_raed", [1024], [2], runs=2)
    with _pytest.raises(ValueError, match="at least one op"):
        run_grid(mesh, ",", [1024], [2], runs=2)


def test_ops_for_options_rejects_empty_family():
    import pytest as _pytest

    from tpu_perf.config import Options
    from tpu_perf.runner import ops_for_options

    with _pytest.raises(ValueError, match="empty op family"):
        ops_for_options(Options(op=","))


def test_judge_p75_above_spec_is_unphysical():
    # a hot window can keep p50 under the spec while a quarter of the
    # samples exceed it — the cell is jitter-widened, not a plateau
    assert judge(762.0, 819.0, 600.0, p75=955.0) == "unphysical"
    assert judge(762.0, 819.0, 600.0, p75=800.0) == "ok"
    assert judge(762.0, None, 600.0, p75=955.0) == "ok"  # no spec


def test_mark_chosen_prefers_stability_over_max_p50():
    # the jitter-inflated cell has the highest p50 but a wide IQR; the
    # plateau cell's tight IQR wins
    wide = _cell(762.0, "ok", p25=633.0, p75=810.0)
    tight = _cell(665.0, "ok", iters=16, p25=650.0, p75=672.0)
    marked = mark_chosen([wide, tight])
    (chosen,) = [c for c in marked if c.chosen]
    assert chosen.p50 == 665.0


def test_mark_chosen_bandwidth_guard_excludes_low_cells():
    # a tiny latency-dominated cell with quantized samples has rel IQR ~0
    # but must NOT beat the plateau: stability only competes within 80%
    # of the best ok p50
    quantized = _cell(15.0, "ok", nbytes=1 << 20,
                      p25=15.0, p75=15.0)
    plateau = _cell(640.0, "ok", iters=25,
                    p25=626.0, p75=669.0)
    marked = mark_chosen([quantized, plateau])
    (chosen,) = [c for c in marked if c.chosen]
    assert chosen.p50 == 640.0


def test_compute_grid_judges_tflops(eight_devices):
    # VERDICT r3 #3: the MXU instrument gets the grid discipline.  On CPU
    # devices the absolute numbers are meaningless; what is pinned is the
    # unit switch, the FLOP model (2*m^3 per iteration), and the verdict
    # plumbing.
    from tpu_perf.grid import _FLOPS_PER_ITER, run_grid
    from tpu_perf.parallel import make_mesh

    # m for a 128x128 f32 operand: 64 KiB
    nbytes = 128 * 128 * 4
    assert _FLOPS_PER_ITER["mxu_gemm"](nbytes, 4) == 2 * 128**3
    cells = run_grid(make_mesh(), "mxu_gemm", [nbytes], [2], runs=2,
                     spec_tflops=1e9)  # absurd spec: every cell ok
    (cell,) = cells
    assert cell.unit == "TFLOP/s"
    assert cell.verdict == "ok" and cell.chosen
    assert cell.p50 > 0
    md = grid_to_markdown(cells)
    assert "TFLOP/s p25/p50/p75 (TFLOP/s)" in md
    # an impossible ceiling rejects every cell, same rules as bandwidth
    cells = run_grid(make_mesh(), "mxu_gemm", [nbytes], [2], runs=2,
                     spec_tflops=1e-12)
    assert cells[0].verdict == "unphysical"


def test_compute_grid_rejects_ops_without_flop_model(eight_devices):
    import pytest as _pytest

    from tpu_perf.grid import run_grid
    from tpu_perf.parallel import make_mesh

    with _pytest.raises(ValueError, match="no FLOP model"):
        run_grid(make_mesh(), "hbm_stream", [1024], [2], runs=2,
                 spec_tflops=197.0)
    with _pytest.raises(ValueError, match="ONE metric"):
        run_grid(make_mesh(), "mxu_gemm", [1024], [2], runs=2,
                 spec_tflops=197.0, spec_gbps=819.0)


def test_cli_grid_spec_tflops(eight_devices, capsys):
    from tpu_perf.cli import main

    rc = main(["grid", "--op", "mxu_gemm", "--sizes", "64K", "--iters",
               "2", "-r", "2", "--spec-tflops", "1e9"])
    captured = capsys.readouterr()
    assert rc == 0
    assert "TFLOP/s" in captured.out
    assert "chosen operating point: mxu_gemm" in captured.err
    assert "TFLOP/s p50" in captured.err


def test_run_grid_notes_jitter_widened_cells(eight_devices, monkeypatch):
    # wire the p75 rule through run_grid with a fake measurement
    from tpu_perf import grid as grid_mod
    from tpu_perf.parallel import make_mesh

    class FakeTimes:
        samples = [0.001, 0.001, 0.0001]  # one wild sample -> p75 blows up
        overhead_s = 0.0

    class FakePoint:
        op, nbytes, n_devices, iters, dtype = "ring", 1024, 8, 2, "float32"
        times = FakeTimes()

        def rows(self, job):
            from tpu_perf.runner import SweepPointResult

            return SweepPointResult(
                op="ring", nbytes=1024, iters=2, n_devices=8,
                times=FakeTimes(),
            ).rows(job)

    monkeypatch.setattr(grid_mod, "run_point",
                        lambda opts, mesh, nbytes: FakePoint())
    cells = grid_mod.run_grid(make_mesh(), "ring", [1024], [2], runs=3,
                              spec_gbps=0.005)
    (cell,) = cells
    assert cell.verdict == "unphysical"
    # the p50 must be UNDER the spec (else the plain rule fires and this
    # test stops exercising the p75 path) and the note must say why
    assert cell.p50 <= 0.005
    assert "jitter-widened" in cell.note


def test_mark_chosen_sub_floor_iqrs_tie_to_higher_p50():
    # trace-fence cells' quartiles agree to ~1e-4; a microscopic IQR
    # difference must not outrank a 5% higher p50 (round-4 live grid:
    # 177.4 was chosen over 186.8 before the floor)
    tight_low = _cell(177.4, "ok", p25=177.4, p75=177.4)
    tight_high = _cell(186.8, "ok", iters=16, p25=186.79, p75=186.81)
    marked = mark_chosen([tight_low, tight_high])
    (chosen,) = [c for c in marked if c.chosen]
    assert chosen.p50 == 186.8


def test_cli_grid_writes_raw_rows(eight_devices, tmp_path, capsys):
    # -l leaves the raw evidence behind the verdict table (claims cite
    # artifacts: a rendered table alone is not reproducible)
    from tpu_perf.cli import main
    from tpu_perf.schema import ResultRow

    rc = main(["grid", "--op", "ring", "--sizes", "4K", "--iters", "2",
               "-r", "3", "--spec-gbps", "1e9", "-l", str(tmp_path)])
    assert rc == 0
    (log,) = tmp_path.glob("tpu-*.log")
    rows = [ResultRow.from_csv(ln) for ln in log.read_text().splitlines()]
    assert len(rows) == 3  # one row per run of the single cell
    assert all(r.op == "ring" and r.nbytes == 4096 for r in rows)
    # rows are stamped with the SAME job id the file name carries, so
    # ingested rows join back to this run's verdict table
    assert len({r.job_id for r in rows}) == 1
    assert rows[0].job_id in log.name
