"""Numeric correctness of every measurement kernel (SURVEY.md §4: assert
numerics — allreduce of known ramps, ppermute ring identity — before timing
them; the reference never validates payloads, mpi_perf.c:75-80)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_perf.ops import build_op, payload_elems
from tpu_perf.parallel import make_mesh


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh()


@pytest.fixture(scope="module")
def mesh2d(eight_devices):
    return make_mesh((2, 4), ("dcn", "ici"))


def _run(built):
    return np.asarray(jax.device_get(built.step(built.example_input)))


def test_payload_elems():
    # float32 (itemsize 4)
    assert payload_elems("allreduce", 16, 8, 4) == (4, 16)
    assert payload_elems("allreduce", 9, 8, 4) == (3, 12)  # rounds up to elems
    assert payload_elems("all_gather", 64, 8, 4) == (2, 64)  # shard = total/n
    assert payload_elems("all_gather", 8, 8, 4) == (1, 32)  # min 1 elem/device
    assert payload_elems("reduce_scatter", 16, 8, 4) == (8, 32)  # multiple of n
    assert payload_elems("all_to_all", 32, 8, 4) == (8, 32)
    assert payload_elems("halo", 4, 8, 4) == (2, 8)  # even, >= 2
    assert payload_elems("pingpong", 1, 8, 4) == (1, 4)


def test_allreduce_of_known_ramp(mesh):
    built = build_op("allreduce", mesh, 8 * 4, 1)
    x = np.asarray(jax.device_get(built.example_input))
    out = _run(built)
    # psum / n == global mean of each position across device shards
    per_dev = x.reshape(8, -1)
    np.testing.assert_allclose(out.reshape(8, -1), np.tile(per_dev.mean(0), (8, 1)), rtol=1e-6)


def test_allreduce_iters_chain(mesh):
    # after k iterations the value is idempotent (mean of means)
    b1 = build_op("allreduce", mesh, 64, 1)
    b5 = build_op("allreduce", mesh, 64, 5)
    np.testing.assert_allclose(_run(b1), _run(b5), rtol=1e-6)


def test_hier_allreduce_matches_flat(mesh, mesh2d):
    flat = build_op("allreduce", mesh, 256, 1)
    hier = build_op("hier_allreduce", mesh2d, 256, 1)
    np.testing.assert_allclose(_run(flat), _run(hier), rtol=1e-5)


def test_all_gather_identity(mesh):
    # gather + take-own-shard == identity
    built = build_op("all_gather", mesh, 8 * 8 * 4, 3)
    x = np.asarray(jax.device_get(built.example_input))
    np.testing.assert_allclose(_run(built), x, rtol=1e-6)


def test_reduce_scatter_values(mesh):
    built = build_op("reduce_scatter", mesh, 8 * 4, 1)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, 8)
    out = _run(built).reshape(8, 8)
    # device d keeps its buffer with only its OWN chunk replaced by the
    # cross-device mean of that chunk (the in-place carry convention —
    # the body writes exactly the collective's 1/n output shard)
    mean = x.mean(0)  # (elems,) global mean per position
    expected = x.copy()
    for d in range(8):
        expected[d, d] = mean[d]
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_hbm_triad_values(mesh):
    # 2R:1W mix: first half <- a*k1 + b*k2 in place, second half untouched
    built = build_op("hbm_triad", mesh, 8 * 16 * 4, 2)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    h = x.shape[1] // 2
    want = x.copy()
    for _ in range(2):  # iters=2 composes the model
        want[:, :h] = want[:, :h] * np.float32(1.0000001) \
            + want[:, h:] * np.float32(1e-7)
    np.testing.assert_allclose(out, want, rtol=1e-6)


def test_hbm_triad_payload_rounds_even():
    # both halves must exist: odd element counts round up
    assert payload_elems("hbm_triad", 9 * 4, 8, 4) == (10, 40)
    from tpu_perf.metrics import bus_bandwidth_gbps

    # traffic = 1.5x nbytes per iteration (read all, write half)
    assert bus_bandwidth_gbps("hbm_triad", 1000, 1e-6, 1) == \
        pytest.approx(1.5 * 1.0)


def test_all_to_all_transpose(mesh):
    built = build_op("all_to_all", mesh, 8 * 4, 1)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, 8)
    out = _run(built).reshape(8, 8)
    # block (i,j) of the device-matrix transposes
    np.testing.assert_allclose(out, x.T, rtol=1e-6)


def test_all_to_all_involution(mesh):
    # applying all_to_all twice = identity => even iters give back the input
    built = build_op("all_to_all", mesh, 8 * 4, 2)
    x = np.asarray(jax.device_get(built.example_input))
    np.testing.assert_allclose(_run(built), x, rtol=1e-6)


def test_broadcast_from_root(mesh):
    # binomial tree over log2(n) ppermute rounds (the real MPI_Bcast shape)
    built = build_op("broadcast", mesh, 16, 4)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    np.testing.assert_allclose(out, np.tile(x[0], (8, 1)), rtol=1e-6)


@pytest.mark.parametrize("n", [2, 3, 5, 6, 7])
def test_broadcast_tree_non_power_of_two(n):
    # the tree's last round is partial when n is not a power of two
    mesh = make_mesh(devices=jax.devices()[:n])
    built = build_op("broadcast", mesh, 16, 1)
    x = np.asarray(jax.device_get(built.example_input)).reshape(n, -1)
    out = _run(built).reshape(n, -1)
    np.testing.assert_allclose(out, np.tile(x[0], (n, 1)), rtol=1e-6)


def test_broadcast_psum_matches_tree(mesh):
    # the legacy masked-psum emulation stays available and agrees
    tree = build_op("broadcast", mesh, 16, 1)
    psum = build_op("broadcast_psum", mesh, 16, 1)
    np.testing.assert_allclose(_run(tree), _run(psum), rtol=1e-6)


def test_broadcast_needs_single_axis(eight_devices):
    mesh2 = make_mesh((2, 4), ("dcn", "ici"))
    with pytest.raises(ValueError, match="single mesh axis"):
        build_op("broadcast", mesh2, 16, 1)
    built = build_op("broadcast_psum", mesh2, 16, 1)  # multi-axis fallback
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    np.testing.assert_allclose(out, np.tile(x[0], (8, 1)), rtol=1e-6)


def test_float_only_ops_reject_integer_dtypes(mesh):
    # reductions scale by 1/n (zero under an int cast): rejecting loudly
    # beats silently measuring a different computation
    for op in ("allreduce", "reduce_scatter", "mxu_gemm", "pl_allreduce"):
        with pytest.raises(ValueError, match="float dtype"):
            build_op(op, mesh, 64, 1, dtype="int32")


def test_hbm_stream_integer_body_not_elided(mesh):
    # the float body's constants round to (1, 0) under an int cast, which
    # would let XLA elide the loop entirely (observed as impossible
    # bandwidth on hardware); the int body is a wrapping +1
    built = build_op("hbm_stream", mesh, 64, 3, dtype="uint8")
    x = np.asarray(jax.device_get(built.example_input))
    out = np.asarray(jax.device_get(built.step(built.example_input)))
    np.testing.assert_array_equal(out, x + 3)


def test_selftest_integer_dtype(mesh):
    from tpu_perf.selftest import run_selftest

    results = run_selftest(
        mesh, ops=["hbm_stream", "ring", "exchange", "allreduce",
                   "broadcast_psum"],
        nbytes=256, dtype="int32", iters=2,
    )
    by_op = {r.op: r for r in results}
    assert by_op["hbm_stream"].status == "ok"
    assert by_op["ring"].status == "ok"
    assert by_op["exchange"].status == "ok"
    assert by_op["allreduce"].status == "skip"  # float-only
    # masked psum is exact in integer arithmetic — not float-only
    assert by_op["broadcast_psum"].status == "ok"


def test_integer_fill_is_not_constant(mesh):
    # [1, 2) float fill truncates to all-ones under an int cast, which
    # would make every movement-op selftest vacuous; ints get a 0..250 ramp
    built = build_op("ring", mesh, 512, 1, dtype="uint8")
    x = np.asarray(jax.device_get(built.example_input))
    assert len(np.unique(x)) > 100


def test_selftest_uint8_wraparound_matches_device(mesh):
    # model composed in the NATIVE dtype: uint8 255+1 wraps to 0 on both
    # sides, so a correctly wrapping kernel is not reported as a failure
    from tpu_perf.selftest import run_selftest

    (res,) = run_selftest(mesh, ops=["hbm_stream"], nbytes=512,
                          dtype="uint8", iters=10)
    assert res.status == "ok", res.detail


def test_hbm_read_reduces_into_slot0(mesh):
    # one iteration: slot 0 <- mean(max(row, row[0])); the rest untouched
    built = build_op("hbm_read", mesh, 1024, 1)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    np.testing.assert_allclose(
        out[:, 0], np.maximum(x, x[:, :1]).mean(axis=1), rtol=1e-5
    )
    np.testing.assert_array_equal(out[:, 1:], x[:, 1:])


def test_hbm_read_carry_is_bounded(mesh):
    # the reduction scalar converges up to max(row) and stays there — a
    # drifting carry would overflow a daemon-length fori chain
    b_many = build_op("hbm_read", mesh, 1024, 200)
    hi = float(np.max(np.asarray(jax.device_get(b_many.example_input))))
    out = _run(b_many)
    assert np.isfinite(out).all()
    assert float(np.max(out)) <= hi + 1e-5


def test_hbm_read_is_float_only(mesh):
    # the mean is zero/garbage under an int cast, like the reductions
    with pytest.raises(ValueError, match="float dtype"):
        build_op("hbm_read", mesh, 64, 1, dtype="int32")


def test_hbm_write_broadcasts_carry_scalar(mesh):
    # k iterations: the row becomes f^k(row[0]) everywhere (f applied to
    # the previous iteration's broadcast value — the carry chain)
    built = build_op("hbm_write", mesh, 1024, 3)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    want = x[:, 0]
    for _ in range(3):
        want = want * 1.0000001 + 1e-7
    np.testing.assert_allclose(out, np.broadcast_to(want[:, None], out.shape),
                               rtol=1e-5)


def test_hbm_write_integer_wraps(mesh):
    # same wrapping +1 convention as hbm_stream's int body
    built = build_op("hbm_write", mesh, 64, 4, dtype="uint8")
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    want = (x[:, :1] + 4).astype(np.uint8)
    np.testing.assert_array_equal(out, np.broadcast_to(want, out.shape))


def test_hbm_read_write_selftest(mesh):
    from tpu_perf.selftest import run_selftest

    results = run_selftest(mesh, ops=["hbm_read", "hbm_write"], iters=3)
    assert all(r.status == "ok" for r in results), results
    # int pass: hbm_read skips (float-only), hbm_write wraps
    results = run_selftest(mesh, ops=["hbm_read", "hbm_write"],
                           dtype="uint8", iters=5)
    by_op = {r.op: r for r in results}
    assert by_op["hbm_read"].status == "skip"
    assert by_op["hbm_write"].status == "ok", by_op["hbm_write"].detail


def test_mxu_gemm_norm_preserved(mesh):
    # the orthogonal multiplier keeps the carry bounded over many iters
    built = build_op("mxu_gemm", mesh, 128 * 128 * 4, 5)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    np.testing.assert_allclose(
        np.linalg.norm(out, axis=1), np.linalg.norm(x, axis=1), rtol=1e-4
    )
    assert built.nbytes == 128 * 128 * 4


def test_mxu_gemm_matches_model(mesh):
    from tpu_perf.ops.collectives import _ortho

    built = build_op("mxu_gemm", mesh, 128 * 128 * 4, 2)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, 128, 128)
    out = _run(built).reshape(8, 128, 128)
    want = x @ _ortho(128) @ _ortho(128)
    np.testing.assert_allclose(out, want, rtol=1e-3, atol=1e-3)


def test_overlap_ring_moves_and_computes(mesh):
    from tpu_perf.ops.collectives import _ortho, _overlap_split

    built = build_op("overlap_ring", mesh, 256 * 4, 1)
    per_dev = built.example_input.size // 8
    r, m = _overlap_split(per_dev)
    assert r == 256  # nbytes names the ring payload
    assert built.nbytes == 256 * 4
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    np.testing.assert_allclose(out[:, :r], np.roll(x[:, :r], 1, axis=0),
                               rtol=1e-6)
    want = x[:, r:].reshape(8, m, m) @ _ortho(m)
    np.testing.assert_allclose(out[:, r:].reshape(8, m, m), want,
                               rtol=1e-3, atol=1e-3)


def test_overlap_split_roundtrips_payload_sizes():
    from tpu_perf.ops import payload_elems
    from tpu_perf.ops.collectives import (
        _OVERLAP_MAX_M, _gemm_m, _overlap_split,
    )

    for nbytes in (8, 4096, 456131, 4 * 1024 * 1024, 64 * 1024 * 1024):
        elems, actual = payload_elems("overlap_ring", nbytes, 8, 4)
        r, m = _overlap_split(elems)
        assert r * 4 == actual
        # overlap_ring keeps the round-2/3 compute-block cap so its
        # published busbw-vs-ring gap stays comparable across rounds,
        # even though mxu_gemm's own cap rose to 4096
        assert m == _gemm_m(r, _OVERLAP_MAX_M)
    assert _overlap_split(
        payload_elems("overlap_ring", 64 * 1024 * 1024, 8, 4)[0]
    )[1] == _OVERLAP_MAX_M == 2048


def test_pingpong_round_trip_identity(mesh):
    # payload goes group0 -> group1 -> back: group0 keeps its data,
    # group1 ends zeroed (ppermute zero-fills non-destinations)
    built = build_op("pingpong", mesh, 16, 3)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    np.testing.assert_allclose(out[:4], x[:4], rtol=1e-6)
    np.testing.assert_allclose(out[4:], 0.0)


def test_pingpong_unidir_ack(mesh):
    built = build_op("pingpong_unidir", mesh, 16, 2)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    # senders (group 0) get their own first element back as the ack
    np.testing.assert_allclose(out[:4], x[:4], rtol=1e-6)
    # receivers' first element is zeroed by the ack-permute backfill
    np.testing.assert_allclose(out[4:, 0], 0.0)
    np.testing.assert_allclose(out[4:, 1:], x[4:, 1:], rtol=1e-6)


def test_exchange_swaps_pairs(mesh):
    built = build_op("exchange", mesh, 16, 1)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    for i in range(4):
        np.testing.assert_allclose(out[i], x[i + 4], rtol=1e-6)
        np.testing.assert_allclose(out[i + 4], x[i], rtol=1e-6)


def test_exchange_windowed(mesh):
    built = build_op("exchange", mesh, 16, 2, window=4)
    assert built.example_input.shape[0] == 4
    x = np.asarray(jax.device_get(built.example_input))
    out = np.asarray(jax.device_get(built.step(built.example_input)))
    # two exchanges = identity
    np.testing.assert_allclose(out, x, rtol=1e-6)
    # nbytes stays per-message; the window multiplies the message count
    assert built.nbytes == 16
    assert built.iters == 2 * 4


def test_ring_identity_after_n_shifts(mesh):
    # SURVEY.md §4: ppermute ring identity
    built = build_op("ring", mesh, 16, 8)
    x = np.asarray(jax.device_get(built.example_input))
    np.testing.assert_allclose(_run(built), x, rtol=1e-6)


def test_ring_single_shift(mesh):
    built = build_op("ring", mesh, 16, 1)
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    np.testing.assert_allclose(out, np.roll(x, 1, axis=0), rtol=1e-6)


def test_halo_exchange(mesh):
    built = build_op("halo", mesh, 32, 1)  # 8 elems/device, h=4
    x = np.asarray(jax.device_get(built.example_input)).reshape(8, -1)
    out = _run(built).reshape(8, -1)
    h = x.shape[1] // 2
    for d in range(8):
        np.testing.assert_allclose(out[d, :h], x[(d - 1) % 8, h:], rtol=1e-6)
        np.testing.assert_allclose(out[d, h:], x[(d + 1) % 8, :h], rtol=1e-6)


def test_bfloat16_payload(mesh):
    built = build_op("allreduce", mesh, 64, 1, dtype="bfloat16")
    assert built.example_input.dtype == jnp.bfloat16
    out = built.step(built.example_input)
    assert jax.device_get(out) is not None


def test_build_op_validation(mesh, mesh2d):
    with pytest.raises(ValueError):
        build_op("nope", mesh, 8, 1)
    with pytest.raises(ValueError):
        build_op("allreduce", mesh, 8, 0)
    with pytest.raises(ValueError):
        build_op("hier_allreduce", mesh, 8, 1)  # needs 2-axis mesh
    with pytest.raises(ValueError):
        build_op("pingpong", mesh2d, 8, 1)  # pairwise needs single axis
    with pytest.raises(ValueError):
        build_op("allreduce", mesh, 8, 1, window=2)  # window only for exchange
