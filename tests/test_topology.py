import pytest

from tpu_perf.topology import (
    Member,
    assign_groups,
    flat_device_index,
    format_axis_tuple,
    one_way_permutation,
    pair_permutation,
    parse_axis_tuple,
    peer_map,
    ring_permutation,
    split_groups,
    unflatten_device_index,
    validate_groups,
)


def _members(hosts):
    return [Member(rank=i, host=h) for i, h in enumerate(hosts)]


def test_assign_groups_case_insensitive():
    # mirrors strnicmp matching at mpi_perf.c:433-444
    members = _members(["NodeA", "nodeb", "NODEC", "noded"])
    groups = assign_groups(members, ["nodeC", "NodeD", ""])
    assert groups == [0, 0, 1, 1]


def test_split_groups_preserves_rank_order():
    members = _members(["a", "b", "c", "d"])
    g0, g1 = split_groups(members, [1, 0, 1, 0])
    assert [m.rank for m in g0] == [1, 3]
    assert [m.rank for m in g1] == [0, 2]


def test_validate_groups():
    # world=4, ppn=1 -> group1 must be 2 (mpi_perf.c:399-403)
    validate_groups(4, 2, 1)
    with pytest.raises(ValueError):
        validate_groups(4, 1, 1)
    # world=40, ppn=10 -> group1 hosts = 2
    validate_groups(40, 2, 10)
    with pytest.raises(ValueError):
        validate_groups(5, 2, 1)  # odd world


def test_peer_map_same_group_rank():
    # peer = same group-communicator rank in the other group (mpi_perf.c:225-234)
    members = _members(["h0", "h1", "h0", "h1"])
    groups = assign_groups(members, ["h1"])
    peers = peer_map(members, groups)
    # g0 = ranks [0, 2] (h0), g1 = ranks [1, 3] (h1)
    assert peers == {0: 1, 1: 0, 2: 3, 3: 2}


def test_peer_map_unpaired_raises():
    members = _members(["a", "b", "c"])
    with pytest.raises(ValueError):
        peer_map(members, [0, 0, 1])


def test_pair_permutation():
    perm = pair_permutation(8)
    assert (0, 4) in perm and (4, 0) in perm
    assert (3, 7) in perm and (7, 3) in perm
    assert len(perm) == 8
    # every destination exactly once (ppermute requirement)
    dsts = [d for _, d in perm]
    assert sorted(dsts) == list(range(8))
    with pytest.raises(ValueError):
        pair_permutation(3)


def test_one_way_permutation():
    fwd = one_way_permutation(8)
    assert fwd == [(0, 4), (1, 5), (2, 6), (3, 7)]
    back = one_way_permutation(8, reverse=True)
    assert back == [(4, 0), (5, 1), (6, 2), (7, 3)]


def test_ring_permutation():
    ring = ring_permutation(4)
    assert ring == [(0, 1), (1, 2), (2, 3), (3, 0)]
    rev = ring_permutation(4, shift=-1)
    assert rev == [(0, 3), (1, 0), (2, 1), (3, 2)]


# --- mixed-mesh helpers (hierarchical multislice collectives) ---------


def test_axis_tuple_round_trip():
    pairs = (("dcn", 2), ("ici", 4))
    spec = format_axis_tuple(pairs)
    assert spec == "dcn=2+ici=4"
    assert parse_axis_tuple(spec) == pairs


def test_axis_tuple_digit_suffixed_names_stay_unambiguous():
    # auto-named axes end in digits (ax0, ax1): name=size keeps the
    # grammar parseable where a bare name+digits spelling would not be
    pairs = (("ax0", 2), ("ax1", 4))
    assert parse_axis_tuple(format_axis_tuple(pairs)) == pairs


def test_axis_tuple_rejects_garbage():
    for bad in ("", "dcn", "dcn=0+ici=4", "dcn=x+ici=4", "dcn=2,ici=4",
                "dcn=2+", "=2+ici=4"):
        with pytest.raises(ValueError):
            parse_axis_tuple(bad)
    with pytest.raises(ValueError):
        format_axis_tuple(())
    with pytest.raises(ValueError):
        format_axis_tuple((("d+c", 2),))
    with pytest.raises(ValueError):
        format_axis_tuple((("dcn", 0),))


def test_flat_device_index_row_major():
    # the ONE flattening order the stack shares: first axis outermost —
    # on a (dcn, ici) mesh device (d, i) sits at flat d * n_ici + i
    sizes = (2, 4)
    assert flat_device_index((0, 0), sizes) == 0
    assert flat_device_index((0, 3), sizes) == 3
    assert flat_device_index((1, 0), sizes) == 4
    assert flat_device_index((1, 2), sizes) == 6
    for idx in range(8):
        coords = unflatten_device_index(idx, sizes)
        assert flat_device_index(coords, sizes) == idx
    with pytest.raises(ValueError):
        flat_device_index((2, 0), sizes)
    with pytest.raises(ValueError):
        flat_device_index((0,), sizes)
    with pytest.raises(ValueError):
        unflatten_device_index(8, sizes)


def test_flat_device_index_matches_mesh_flat_order():
    # the helper's order IS Mesh.devices.flat's (and _flat_index's):
    # numpy row-major reshape of the flat device list
    import numpy as np

    sizes = (2, 4)
    grid = np.arange(8).reshape(sizes)
    for d in range(2):
        for i in range(4):
            assert flat_device_index((d, i), sizes) == grid[d, i]
