"""End-to-end chaos acceptance (ISSUE 2) plus conformance-judge units.

The acceptance scenario: a bounded `tpu-perf chaos` soak with one fault
per detector kind, on the synthetic (seeded, deterministic) timing
source, must be judged ALL CAUGHT by `tpu-perf chaos verify`; a
fault-free soak must report zero false alarms; and the same seed + spec
must reproduce a byte-identical injection ledger."""

import io
import json

import pytest

from tpu_perf.cli import main
from tpu_perf.faults import run_conformance
from tpu_perf.faults.conformance import report_to_json, report_to_markdown
from tpu_perf.health.events import HealthEvent

SPEC = {"faults": [
    {"kind": "spike", "op": "ring", "nbytes": 32, "start": 60, "end": 80,
     "magnitude": 30.0},
    {"kind": "drop_run", "op": "ring", "nbytes": 8, "start": 81, "end": 120},
    {"kind": "hook_fail", "start": 130, "end": 135},
    {"kind": "delay", "op": "ring", "nbytes": 32, "start": 150, "end": 400,
     "magnitude": 3.0},
    {"kind": "flatline", "op": "ring", "nbytes": 8, "start": 200, "end": 400},
]}


def _soak(tmp_path, logdir, *, spec=SPEC, max_runs=400, seed=7):
    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps(spec))
    args = ["chaos", "--faults", str(spec_path), "--seed", str(seed),
            "--max-runs", str(max_runs), "--synthetic", "0.001",
            "--op", "ring", "--sweep", "8,32", "-i", "1",
            "--stats-every", "20", "--health-warmup", "20",
            "-l", str(logdir)]
    assert main(args) == 0
    return logdir


def test_chaos_soak_catches_every_fault_kind(eight_devices, tmp_path, capsys):
    """The acceptance criterion: every injected fault kind (spike,
    drop_run, hook_fail, delay, flatline) verdicted CAUGHT by the
    matching detector, exit 0."""
    logdir = _soak(tmp_path, tmp_path / "logs")
    capsys.readouterr()
    rc = main(["chaos", "verify", str(logdir)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "5/5 fault(s) caught, 0 critical miss(es), 0 false alarm(s)" in out
    for kind, detector in [("delay", "regression"), ("spike", "spike"),
                           ("flatline", "flatline"),
                           ("drop_run", "capture_loss"),
                           ("hook_fail", "hook_fail")]:
        assert f"| {kind} |" in out
        assert f"| {detector} | 1 | 1 | 0 | 0 | 100% | 100% |" in out

    # machine format round-trips the same verdicts
    rc = main(["chaos", "verify", str(logdir), "--format", "json"])
    assert rc == 0
    data = json.loads(capsys.readouterr().out)
    assert [f["verdict"] for f in data["faults"]] == ["caught"] * 5
    assert data["missed_critical"] == []

    # the injected hook failure reached the health family as an event
    # (the daemon survived it — the soak exited 0 above)
    events = []
    for p in logdir.glob("health-*.log"):
        events += [json.loads(ln) for ln in p.read_text().splitlines()]
    assert any(e["kind"] == "hook_fail" and e["op"] == "ingest_hook"
               for e in events)


def test_chaos_ledger_reproducible_for_same_seed(eight_devices, tmp_path):
    """Same seed + spec => byte-identical injection ledger (records
    carry no wall-clock fields; run_id is the clock)."""
    a = _soak(tmp_path, tmp_path / "a", max_runs=200)
    b = _soak(tmp_path, tmp_path / "b", max_runs=200)

    def ledger(d):
        return "".join(p.read_text() for p in sorted(d.glob("chaos-*.log")))

    assert ledger(a) == ledger(b)
    c = _soak(tmp_path, tmp_path / "c", max_runs=200, seed=8)
    assert ledger(a) != ledger(c)  # the seed is real


def test_fault_free_soak_has_zero_false_alarms(eight_devices, tmp_path,
                                               capsys):
    """The false-alarm gate: a fault-free synthetic soak emits no health
    events at all, and verify --fail-on-false-alarm passes."""
    logdir = tmp_path / "clean"
    rc = main(["chaos", "--seed", "7", "--max-runs", "200",
               "--synthetic", "0.001", "--op", "ring", "--sweep", "8,32",
               "-i", "1", "--stats-every", "20", "--health-warmup", "20",
               "-l", str(logdir)])
    assert rc == 0
    assert not list(logdir.glob("health-*.log"))  # nothing fired at all
    capsys.readouterr()
    rc = main(["chaos", "verify", str(logdir), "--fail-on-false-alarm"])
    assert rc == 0
    assert "0 false alarm(s) over 0 event(s)" in capsys.readouterr().out


def test_chaos_soak_keeps_rotated_ledger(eight_devices, tmp_path,
                                         monkeypatch, capsys):
    """A chaos soak outlasting --log-refresh-sec must NOT feed its own
    ledger to the default (delete-only) ingest pass: with no real
    backend configured, rotation keeps every chaos-*.log and
    health-*.log on disk, so verify still finds the meta record that
    only the FIRST ledger file carries."""
    monkeypatch.delenv("TPU_PERF_INGEST", raising=False)
    monkeypatch.delenv("TPU_PERF_INGEST_CMD", raising=False)
    logdir = tmp_path / "logs"
    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps(SPEC))
    rc = main(["chaos", "--faults", str(spec_path), "--seed", "7",
               "--max-runs", "400", "--synthetic", "0.001",
               "--op", "ring", "--sweep", "8,32", "-i", "1",
               "--stats-every", "20", "--health-warmup", "20",
               "--log-refresh-sec", "0", "-l", str(logdir)])
    assert rc == 0
    # refresh 0 => rotations throughout the soak (same-second rotations
    # share a filename): CLOSED ledger files stay on disk, none deleted
    # by an ingest pass — in particular the first file, with the meta
    # record, which verify below needs
    assert len(list(logdir.glob("chaos-*.log"))) >= 2
    capsys.readouterr()
    assert main(["chaos", "verify", str(logdir)]) == 0
    assert "0 critical miss(es)" in capsys.readouterr().out


def test_chaos_verify_no_ledger(tmp_path, capsys):
    rc = main(["chaos", "verify", str(tmp_path)])
    assert rc == 1
    assert "no chaos ledger" in capsys.readouterr().err


def test_chaos_rejects_mpi_backend(capsys):
    rc = main(["chaos", "--backend", "mpi", "--max-runs", "1"])
    assert rc == 2
    assert "jax backend" in capsys.readouterr().err


def test_lognormal_tail_noise_has_zero_false_alarms(eight_devices, tmp_path,
                                                    capsys):
    """The tail-noise gate (ROADMAP satellite): seeded lognormal jitter
    at realistic sigma must not trip any detector — the zero-false-alarm
    property exercised against heavy-tailed noise, not just bounded
    uniform noise."""
    logdir = _soak(tmp_path, tmp_path / "logs", spec={"faults": [
        {"kind": "jitter", "shape": "lognormal", "magnitude": 0.1,
         "start": 1},
    ]})
    capsys.readouterr()
    rc = main(["chaos", "verify", str(logdir), "--fail-on-false-alarm"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "0 false alarm(s)" in out
    assert "| n/a |" in out  # jitter is judged n/a, never missed


def test_chaos_rows_carry_chaos_mode_and_compare(eight_devices, tmp_path,
                                                 capsys):
    """Chaos rows in the curve tables (ROADMAP satellite): a fault soak's
    extended rows carry mode=chaos, a fault-free soak's stay daemon, and
    `report --compare-chaos` joins them so the injected degradation is
    visible as a latency ratio, not just an event stream."""
    from tpu_perf.schema import ResultRow

    logdir = tmp_path / "logs"
    _soak(tmp_path, logdir, spec={"faults": [
        {"kind": "delay", "op": "ring", "nbytes": 32, "start": 1,
         "magnitude": 3.0},
    ]}, max_runs=60)
    # the clean control soak of the same spec (no faults => daemon mode)
    rc = main(["chaos", "--seed", "7", "--max-runs", "60",
               "--synthetic", "0.001", "--op", "ring", "--sweep", "8,32",
               "-i", "1", "--stats-every", "20", "--health-warmup", "20",
               "-l", str(logdir)])
    assert rc == 0
    rows = []
    for p in logdir.glob("tpu-*.log"):
        rows += [ResultRow.from_csv(ln)
                 for ln in p.read_text().splitlines()]
    assert {r.mode for r in rows} == {"chaos", "daemon"}
    capsys.readouterr()
    assert main(["report", str(logdir), "--compare-chaos"]) == 0
    out = capsys.readouterr().out
    # the delayed point shows the 4x latency ratio; the untouched point
    # joins at ~1
    lines = [ln for ln in out.splitlines() if ln.startswith("| ring | 32 |")]
    assert lines and " | 4 |" in lines[0]
    assert main(["report", str(logdir), "--compare-chaos",
                 "--format", "json"]) == 2  # markdown only
    # an all-clean folder has nothing to show and says so
    clean = tmp_path / "clean-only"
    rc = main(["chaos", "--seed", "7", "--max-runs", "40",
               "--synthetic", "0.001", "--op", "ring", "--sweep", "32",
               "-i", "1", "--stats-every", "20", "-l", str(clean)])
    assert rc == 0
    capsys.readouterr()
    assert main(["report", str(clean), "--compare-chaos"]) == 1
    assert "no chaos-mode rows" in capsys.readouterr().err


def test_chaos_verify_textfile_gauges(eight_devices, tmp_path, capsys):
    """Conformance exporter gauges (ROADMAP satellite): chaos verify
    --textfile publishes per-detector caught/missed/false-alarm counters
    and a last-verify timestamp, atomically, for scheduled runs."""
    logdir = _soak(tmp_path, tmp_path / "logs")
    prom = tmp_path / "metrics" / "chaos.prom"
    capsys.readouterr()
    rc = main(["chaos", "verify", str(logdir), "--textfile", str(prom)])
    assert rc == 0
    text = prom.read_text()
    for detector in ("regression", "spike", "flatline", "capture_loss",
                     "hook_fail"):
        assert (f'tpu_perf_chaos_detector_injected{{detector='
                f'"{detector}"}} 1') in text
        assert (f'tpu_perf_chaos_detector_caught{{detector='
                f'"{detector}"}} 1') in text
        assert (f'tpu_perf_chaos_detector_missed{{detector='
                f'"{detector}"}} 0') in text
    assert "tpu_perf_chaos_missed_critical 0" in text
    assert "tpu_perf_chaos_false_alarms_total 0" in text
    import re

    m = re.search(r"^tpu_perf_chaos_last_verify_timestamp_seconds (\S+)",
                  text, re.M)
    assert m and float(m.group(1)) > 0
    assert not prom.with_suffix(".prom.tmp").exists()  # atomic rename


# --- conformance judging on crafted artifacts ---------------------------


def _meta(faults, stats_every=20, seed=7):
    return {"record": "meta", "seed": seed, "stats_every": stats_every,
            "synthetic_s": None, "faults": faults}


def _fault(spec, kind, run_id, op="ring", nbytes=32):
    return {"record": "fault", "spec": spec, "kind": kind, "op": op,
            "nbytes": nbytes, "run_id": run_id, "window": (run_id - 1) // 20}


def _event(kind, run_id, op="ring", nbytes=32, severity="warning"):
    return HealthEvent(
        timestamp="ts", job_id="j", kind=kind, severity=severity, op=op,
        nbytes=nbytes, dtype="float32", run_id=run_id,
        window=(run_id - 1) // 20, observed=2.0, baseline=1.0,
    )


def test_conformance_caught_missed_and_false_alarm():
    records = [
        _meta([{"kind": "delay", "op": "ring", "nbytes": 32, "start": 10,
                "end": 30},
               {"kind": "spike", "op": "ring", "nbytes": 32, "start": 40,
                "end": 45}]),
        _fault(0, "delay", 10), _fault(0, "delay", 12),
        _fault(1, "spike", 40),
    ]
    events = [
        _event("regression", 14),           # catches the delay
        _event("recovered", 35, severity="info"),  # exit: never an alarm
        _event("flatline", 90, op="halo", nbytes=8),  # unattributable
    ]
    rep = run_conformance(records, events)
    assert [v.verdict for v in rep.verdicts] == ["caught", "missed"]
    assert rep.verdicts[1].detail.startswith("no spike event")
    assert [e.kind for e in rep.false_alarms] == ["flatline"]
    assert [v.spec_index for v in rep.missed_critical] == [1]
    scores = {s.detector: s for s in rep.scores}
    assert scores["regression"].recall == 1.0
    assert scores["spike"].recall == 0.0
    assert scores["flatline"].false_alarms == 1
    assert scores["flatline"].precision == 0.0


def test_conformance_attributes_missed_faults_to_concurrent_activity():
    """A missed fault that coincided with harness activity (a rotation,
    an ingest pass) names that activity in its verdict — the
    anomaly-context join pointed at the ledger side (span-traced soaks
    only; untraced soaks keep an empty context column)."""
    records = [
        _meta([{"kind": "spike", "op": "ring", "nbytes": 32, "start": 40,
                "end": 45}]),
        _fault(0, "spike", 40),
    ]

    def span(kind, sid, t0, dur, **attrs):
        return {"record": "span", "job_id": "j", "span_id": sid,
                "parent_id": None, "rank": 0, "thread": "main",
                "t_start_ns": t0, "dur_ns": dur, "kind": kind,
                "attrs": attrs}

    spans = [
        span("run", "r40", 1000, 500, run_id=40, op="ring", nbytes=32),
        span("ingest_hook", "m9", 900, 800),        # overlaps run 40
        span("rotate", "m10", 5000, 100, run_id=41),  # does not
    ]
    rep = run_conformance(records, [], spans=spans)
    (v,) = rep.verdicts
    assert v.verdict == "missed"
    assert "ingest_hook (m9" in v.context
    assert "rotate" not in v.context
    # the context lands in both output formats
    md = report_to_markdown(rep)
    assert "concurrent activity" in md and "ingest_hook (m9" in md
    data = json.loads(report_to_json(rep))
    assert "ingest_hook (m9" in data["faults"][0]["context"]
    # untraced: same verdict, empty context
    plain = run_conformance(records, [])
    assert plain.verdicts[0].context == ""


def test_conformance_grace_window():
    records = [
        _meta([{"kind": "drop_run", "op": "ring", "start": 10, "end": 20}]),
        _fault(0, "drop_run", 10, nbytes=0), _fault(0, "drop_run", 20,
                                                    nbytes=0),
    ]
    # capture loss fires at the NEXT heartbeat boundary: inside the
    # default grace (2 x stats_every), outside a grace of 5
    late = [_event("capture_loss", 40, nbytes=0)]
    assert run_conformance(records, late).verdicts[0].verdict == "caught"
    rep = run_conformance(records, late, grace_runs=5)
    assert rep.verdicts[0].verdict == "missed"
    # and the now-unattributed event becomes the false alarm it would be
    assert [e.kind for e in rep.false_alarms] == ["capture_loss"]


def test_conformance_rank_filtered_fault_needs_matching_event_rank():
    """Multi-host fault placement: a rank-1 fault is only CAUGHT by an
    event whose rank column names rank 1 — the sick host must be named,
    not merely noticed somewhere on the fleet."""
    import dataclasses

    records = [
        _meta([{"kind": "delay", "op": "ring", "nbytes": 32, "start": 10,
                "end": 30, "rank": 1}]),
        _fault(0, "delay", 10),
    ]
    wrong_rank = [_event("regression", 14)]  # rank 0 event
    rep = run_conformance(records, wrong_rank)
    assert rep.verdicts[0].verdict == "missed"
    assert [e.kind for e in rep.false_alarms] == ["regression"]
    right = [dataclasses.replace(_event("regression", 14), rank=1)]
    rep = run_conformance(records, right)
    assert rep.verdicts[0].verdict == "caught"
    assert rep.false_alarms == []


def test_conformance_never_fired_is_a_miss():
    records = [_meta([{"kind": "delay", "op": "ring", "start": 10**6}])]
    rep = run_conformance(records, [_event("regression", 14)])
    (v,) = rep.verdicts
    assert v.verdict == "missed" and "never fired" in v.detail


def test_conformance_jitter_is_not_judged():
    records = [
        _meta([{"kind": "jitter", "op": "ring", "magnitude": 0.2}]),
        _fault(0, "jitter", 5),
    ]
    rep = run_conformance(records, [])
    assert rep.verdicts[0].verdict == "n/a"
    assert rep.missed_critical == []  # n/a never fails the gate
    assert rep.scores == []


def test_conformance_corrupt_judged_from_selftest_records():
    meta = _meta([{"kind": "corrupt", "op": "ring"}])
    fail = {"record": "selftest", "op": "ring", "status": "fail",
            "detail": "1/64 elements off"}
    rep = run_conformance([meta, fail], [])
    assert rep.verdicts[0].verdict == "caught"
    ok = dict(fail, status="ok")
    rep = run_conformance([meta, ok], [])
    assert rep.verdicts[0].verdict == "missed"
    assert "slipped through" in rep.verdicts[0].detail
    rep = run_conformance([meta], [])
    assert rep.verdicts[0].verdict == "missed"


def test_conformance_requires_meta():
    with pytest.raises(ValueError, match="no meta record"):
        run_conformance([_fault(0, "delay", 1)], [])


def test_conformance_rejects_mixed_soaks():
    """Chaos keeps rotated ledgers on disk, so a reused log folder can
    hold two soaks: pooling their fault records under one spec would be
    a garbage join — refuse loudly.  Identical metas (one per rank of a
    multi-host soak) are fine."""
    a = _meta([{"kind": "delay", "op": "ring"}], seed=7)
    b = _meta([{"kind": "spike", "op": "ring"}], seed=8)
    with pytest.raises(ValueError, match="more than one chaos soak"):
        run_conformance([a, b], [])
    rep = run_conformance([a, dict(a)], [])  # multi-rank: same meta twice
    assert len(rep.verdicts) == 1


def test_chaos_verify_exit_5_on_missed_critical(tmp_path, capsys):
    """The CI gate's teeth: a ledger whose critical fault produced no
    event exits 5 (and names the spec index)."""
    records = [
        _meta([{"kind": "delay", "op": "ring", "start": 10, "end": 30}]),
        _fault(0, "delay", 10),
    ]
    (tmp_path / "chaos-u-0-x.log").write_text(
        "".join(json.dumps(r) + "\n" for r in records))
    rc = main(["chaos", "verify", str(tmp_path)])
    err = capsys.readouterr().err
    assert rc == 5
    assert "critical fault(s) MISSED" in err

    # a non-critical miss passes (reported, not fatal)
    records[0]["faults"][0]["critical"] = False
    (tmp_path / "chaos-u-0-x.log").write_text(
        "".join(json.dumps(r) + "\n" for r in records))
    assert main(["chaos", "verify", str(tmp_path)]) == 0


def test_chaos_verify_fail_on_false_alarm_flag(tmp_path, capsys):
    (tmp_path / "chaos-u-0-x.log").write_text(json.dumps(_meta([])) + "\n")
    ev = _event("spike", 50)
    import dataclasses
    (tmp_path / "health-u-0-x.log").write_text(
        json.dumps(dataclasses.asdict(ev)) + "\n")
    assert main(["chaos", "verify", str(tmp_path)]) == 0  # lenient default
    rc = main(["chaos", "verify", str(tmp_path), "--fail-on-false-alarm"])
    assert rc == 5
    assert "false alarm" in capsys.readouterr().err


def test_chaos_verify_accepts_file_and_glob_targets(tmp_path, capsys):
    """A file (or glob) target names the LEDGER; the health events are
    found next to it — the chaos file must never reach the event
    parser."""
    records = [
        _meta([{"kind": "delay", "op": "ring", "start": 10, "end": 30}]),
        _fault(0, "delay", 10),
    ]
    ledger = tmp_path / "chaos-u-0-x.log"
    ledger.write_text("".join(json.dumps(r) + "\n" for r in records))
    import dataclasses
    (tmp_path / "health-u-0-x.log").write_text(
        json.dumps(dataclasses.asdict(_event("regression", 14))) + "\n")
    rc = main(["chaos", "verify", str(ledger)])  # file target
    assert rc == 0
    assert "1/1 fault(s) caught" in capsys.readouterr().out
    rc = main(["chaos", "verify", str(tmp_path / "chaos-*.log")])  # glob
    assert rc == 0
    assert "1/1 fault(s) caught" in capsys.readouterr().out


def test_chaos_verify_reads_open_ledger(tmp_path, capsys):
    # a killed soak leaves the active lazy log under .open; verify must
    # still see its records
    (tmp_path / "chaos-u-0-x.log.open").write_text(
        json.dumps(_meta([])) + "\n")
    assert main(["chaos", "verify", str(tmp_path)]) == 0
    assert "0 critical miss(es)" in capsys.readouterr().out


def test_driver_hook_fail_survives_and_is_evented(eight_devices, tmp_path):
    """Driver-level contract, no CLI: an injected hook failure mid-soak
    never kills the daemon, lands a hook_fail health event at the forced
    rotation's exact run, and the real on_rotate hook is NOT reached
    while the window is armed."""
    from tpu_perf.config import Options
    from tpu_perf.driver import Driver
    from tpu_perf.faults import FaultSpec
    from tpu_perf.parallel import make_mesh

    reached = []
    err = io.StringIO()
    opts = Options(
        op="ring", iters=1, num_runs=-1, buff_sz=32,
        logfolder=str(tmp_path), stats_every=5, health=True,
        health_warmup=30,
        faults=[FaultSpec(kind="hook_fail", start=3, end=4)],
        synthetic_s=1e-3,
    )
    drv = Driver(opts, make_mesh(), err=err,
                 on_rotate=lambda: reached.append(1), max_runs=8)
    drv.run()
    assert reached == []  # armed for the whole (short) soak's rotation
    assert drv.log.hook_failures == 1
    (health_log,) = tmp_path.glob("health-*.log")
    events = [json.loads(ln) for ln in health_log.read_text().splitlines()]
    assert [(e["kind"], e["run_id"]) for e in events] == [("hook_fail", 3)]
    # the warning surfaced on the driver's stream too (console operator)
    assert "warning hook_fail: ingest_hook" in err.getvalue()
