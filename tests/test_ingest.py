import os
import time

import pytest

from tpu_perf.ingest import (
    LocalDirBackend,
    NullBackend,
    build_backend_from_env,
    eligible_files,
    run_ingest_pass,
)


def _mk(folder, name, mtime):
    p = folder / name
    p.write_text("row\n")
    os.utime(p, (mtime, mtime))
    return str(p)


def test_eligible_files_skips_newest(tmp_path):
    """kusto_ingest.py:32-40: tcp* only, oldest first, newest N skipped."""
    t = time.time()
    old = _mk(tmp_path, "tcp-a.log", t - 300)
    mid = _mk(tmp_path, "tcp-b.log", t - 200)
    new = _mk(tmp_path, "tcp-c.log", t - 100)
    _mk(tmp_path, "other.log", t - 500)  # non-tcp prefix ignored
    # the full rotating-log shape is required, not a bare prefix match:
    # a --health-textfile named tpu-perf.prom sitting in the log folder
    # must never be swept into the tpu-* CSV table (or deleted)
    _mk(tmp_path, "tpu-perf.prom", t - 400)
    _mk(tmp_path, "tcpdump.log", t - 400)  # prefix needs its dash
    got = eligible_files(str(tmp_path), 1)
    assert got == [old, mid]
    assert eligible_files(str(tmp_path), 0) == [old, mid, new]
    assert eligible_files(str(tmp_path), 5) == []  # skip more than exist
    assert eligible_files(str(tmp_path), 0, prefix="tpu") == []


def test_eligible_files_missing_folder():
    assert eligible_files("/nonexistent/nowhere", 10) == []


def test_eligible_files_validation(tmp_path):
    with pytest.raises(ValueError):
        eligible_files(str(tmp_path), -1)


def test_run_ingest_pass_local_backend(tmp_path):
    src = tmp_path / "logs"
    sink = tmp_path / "sink"
    src.mkdir()
    t = time.time()
    _mk(src, "tcp-1.log", t - 300)
    _mk(src, "tcp-2.log", t - 200)
    _mk(src, "tcp-3.log", t - 100)
    n = run_ingest_pass(str(src), skip_newest=1, backend=LocalDirBackend(str(sink)))
    assert n == 2
    # ingested files deleted from source (kusto_ingest.py:41-44)
    assert sorted(p.name for p in src.iterdir()) == ["tcp-3.log"]
    assert sorted(p.name for p in sink.iterdir()) == ["tcp-1.log", "tcp-2.log"]


def test_all_passes_health_family_never_skipped(tmp_path):
    """The health family ingests with NO newest-skip: its lazy log keeps
    the active file under .open, so every health-*.log is finished — and
    the count heuristic would starve a sparse family whose newest file
    can stay newest forever (nothing churns on a healthy fleet)."""
    from tpu_perf.ingest.pipeline import run_all_ingest_passes

    src = tmp_path / "logs"
    sink = tmp_path / "sink"
    src.mkdir()
    t = time.time()
    _mk(src, "tcp-1.log", t - 300)
    _mk(src, "tcp-2.log", t - 200)
    _mk(src, "health-1.log", t - 100)  # the family's one (newest) file
    _mk(src, "health-2.log.open", t - 50)  # active: invisible to ingest
    n = run_all_ingest_passes(str(src), skip_newest=1,
                              backend=LocalDirBackend(str(sink)))
    assert n == 2  # tcp-1 (oldest of 2, newest skipped) + health-1
    assert sorted(p.name for p in src.iterdir()) == [
        "health-2.log.open", "tcp-2.log"
    ]
    assert sorted(p.name for p in sink.iterdir()) == [
        "health-1.log", "tcp-1.log"
    ]


class Boom(NullBackend):
    """Backend that always fails — the kusto-down scenario."""

    def ingest(self, path):
        raise IOError("upload failed")


def test_failed_ingest_keeps_file(tmp_path):
    t = time.time()
    _mk(tmp_path, "tcp-1.log", t - 300)
    _mk(tmp_path, "tcp-2.log", t - 200)

    with pytest.raises(IOError):
        run_ingest_pass(str(tmp_path), skip_newest=0, backend=Boom())
    # no log deleted: retry next pass (the failure-counter sidecar is
    # the only new file)
    assert (tmp_path / "tcp-1.log").exists()
    assert (tmp_path / "tcp-2.log").exists()
    assert not list(tmp_path.glob("*.quarantined"))


class PoisonOnly(NullBackend):
    """Fails only the named files — the healthy-backend poison-row
    scenario (a success in the same pass proves the backend alive)."""

    def __init__(self, *names):
        self.fail_names = set(names)

    def ingest(self, path):
        if os.path.basename(path) in self.fail_names:
            raise IOError("mapping rejected")


def test_poison_file_quarantined_after_consecutive_failures(tmp_path, capsys):
    """Satellite (ISSUE 2): a file that re-fails every pass while the
    rest of the backlog flows must not spam retries forever — after
    MAX_INGEST_FAILURES consecutive counted failures it is renamed out
    of the scan (<name>.quarantined), and the counter persists across
    passes (each rotation spawns a fresh ingest process) via the
    sidecar state file."""
    from tpu_perf.ingest.pipeline import (
        FAILURE_STATE_FILE, MAX_INGEST_FAILURES,
    )

    t = time.time()
    backend = PoisonOnly("tcp-poison.log")
    _mk(tmp_path, "tcp-poison.log", t - 300)
    for i in range(MAX_INGEST_FAILURES - 1):
        # a rotation delivers a fresh good file before each pass, like a
        # live daemon's backlog
        _mk(tmp_path, f"tcp-good{i}.log", t - 200 + i)
        with pytest.raises(IOError):
            run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend)
        assert (tmp_path / "tcp-poison.log").exists()  # still retried
        assert not (tmp_path / f"tcp-good{i}.log").exists()  # backlog flows
        assert (tmp_path / FAILURE_STATE_FILE).exists()  # counter persisted
    # the quarantining pass does NOT raise: the poison file is handled,
    # not retried
    _mk(tmp_path, "tcp-goodN.log", t - 100)
    n = run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend)
    assert n == 1  # the good file
    assert not (tmp_path / "tcp-poison.log").exists()
    assert (tmp_path / "tcp-poison.log.quarantined").exists()
    assert "quarantined" in capsys.readouterr().err
    # quarantined files drop out of the scan: the next pass is clean,
    # and the state file is gone once nothing is failing
    assert run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend) == 0
    assert not (tmp_path / FAILURE_STATE_FILE).exists()


def test_quarantine_triage_list_and_requeue(tmp_path):
    """Quarantine triage tooling (ROADMAP): list names every quarantined
    file; requeue strips the suffix AND resets the sidecar counter, so a
    requeued file gets a full fresh round of retries (a manual rename
    left the old count armed)."""
    import json

    from tpu_perf.ingest.pipeline import (
        FAILURE_STATE_FILE, list_quarantined, requeue_quarantined,
    )

    t = time.time()
    _mk(tmp_path, "tcp-a.log.quarantined", t - 300)
    _mk(tmp_path, "health-b.log.quarantined", t - 200)
    _mk(tmp_path, "tcp-live.log", t - 100)
    # a stale counter survives from before quarantine (manual-rename
    # scenario); requeue must clear it
    (tmp_path / FAILURE_STATE_FILE).write_text(
        json.dumps({"tcp-a.log": 2, "tcp-other.log": 1}))
    assert [os.path.basename(p) for p in list_quarantined(str(tmp_path))] \
        == ["tcp-a.log.quarantined", "health-b.log.quarantined"]
    restored = requeue_quarantined(str(tmp_path))
    assert sorted(restored) == ["health-b.log", "tcp-a.log"]
    assert (tmp_path / "tcp-a.log").exists()
    assert (tmp_path / "health-b.log").exists()
    assert not list(tmp_path.glob("*.quarantined"))
    counts = json.loads((tmp_path / FAILURE_STATE_FILE).read_text())
    assert counts == {"tcp-other.log": 1}  # only the requeued key reset
    # requeued files are eligible again on the next pass
    assert run_ingest_pass(str(tmp_path), skip_newest=0,
                           backend=NullBackend()) == 2
    assert list_quarantined(str(tmp_path)) == []


def test_requeue_refuses_to_clobber_a_live_log(tmp_path, capsys):
    t = time.time()
    _mk(tmp_path, "tcp-a.log.quarantined", t - 300)
    _mk(tmp_path, "tcp-a.log", t - 100)  # the name has been reused
    assert requeue_quarantined_names(tmp_path) == []
    assert (tmp_path / "tcp-a.log.quarantined").exists()
    assert "not requeueing" in capsys.readouterr().err


def requeue_quarantined_names(tmp_path):
    from tpu_perf.ingest.pipeline import requeue_quarantined

    return requeue_quarantined(str(tmp_path))


def test_cli_ingest_list_and_requeue(tmp_path, capsys):
    from tpu_perf.cli import main

    t = time.time()
    _mk(tmp_path, "tcp-a.log.quarantined", t - 300)
    assert main(["ingest", "-d", str(tmp_path), "--list-quarantined"]) == 0
    cap = capsys.readouterr()
    assert "tcp-a.log.quarantined" in cap.out
    assert "1 quarantined file(s)" in cap.err
    assert (tmp_path / "tcp-a.log.quarantined").exists()  # list mutates nothing
    # --requeue restores, then runs the normal pass (which ingests it)
    assert main(["ingest", "-d", str(tmp_path), "-f", "0", "--requeue"]) == 0
    cap = capsys.readouterr()
    assert "requeued 1 quarantined file(s): tcp-a.log" in cap.err
    assert "ingested 1 files" in cap.err
    assert not list(tmp_path.iterdir())  # swept clean
    # combining the flags is an error, not a silent list-only run (the
    # operator would believe the files were requeued)
    assert main(["ingest", "-d", str(tmp_path), "--list-quarantined",
                 "--requeue"]) == 2
    assert "exclusive" in capsys.readouterr().err


def test_backend_outage_never_quarantines(tmp_path):
    """A pass where NOTHING succeeds proves only that the backend is
    down: failures must not count toward quarantine, or a ~45-minute
    endpoint outage would silently quarantine the entire backlog."""
    from tpu_perf.ingest.pipeline import MAX_INGEST_FAILURES

    t = time.time()
    _mk(tmp_path, "tcp-1.log", t - 300)
    _mk(tmp_path, "tcp-2.log", t - 200)
    for _ in range(MAX_INGEST_FAILURES + 2):
        with pytest.raises(IOError):
            run_ingest_pass(str(tmp_path), skip_newest=0, backend=Boom())
    # outage over: every file is still there and still eligible
    assert not list(tmp_path.glob("*.quarantined"))
    assert run_ingest_pass(str(tmp_path), skip_newest=0,
                           backend=NullBackend()) == 2


def test_poison_file_does_not_starve_the_backlog(tmp_path):
    """One bad upload must not abort the pass: files behind the poison
    one still ingest (delete-after-success), and a later success of a
    previously failing file resets its counter."""
    from tpu_perf.ingest.pipeline import FAILURE_STATE_FILE

    t = time.time()
    _mk(tmp_path, "tcp-poison.log", t - 300)
    _mk(tmp_path, "tcp-good.log", t - 200)
    backend = PoisonOnly("tcp-poison.log")
    with pytest.raises(IOError):
        run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend)
    # the good file behind the poison one was still ingested + deleted
    assert not (tmp_path / "tcp-good.log").exists()
    assert (tmp_path / "tcp-poison.log").exists()
    # the poison file recovers (backend fixed): counter resets, state
    # file cleaned, file ingested
    backend.fail_names = set()
    assert run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend) == 1
    assert not (tmp_path / "tcp-poison.log").exists()
    assert not (tmp_path / FAILURE_STATE_FILE).exists()


@pytest.mark.parametrize("corrupt", [
    "{torn",                      # bad JSON
    '{"tcp-1.log": null}',        # non-int value (TypeError path)
    '{"tcp-1.log": [1]}',         # non-scalar value
    '"just a string"',            # non-object document
])
def test_corrupt_failure_state_restarts_counters(tmp_path, corrupt):
    from tpu_perf.ingest.pipeline import FAILURE_STATE_FILE

    (tmp_path / FAILURE_STATE_FILE).write_text(corrupt)
    t = time.time()
    _mk(tmp_path, "tcp-1.log", t - 300)
    _mk(tmp_path, "tcp-good.log", t - 200)  # a success: failures count
    with pytest.raises(IOError):
        run_ingest_pass(str(tmp_path), skip_newest=0,
                        backend=PoisonOnly("tcp-1.log"))
    # the pass survived the corrupt sidecar and rewrote it
    import json

    assert json.loads((tmp_path / FAILURE_STATE_FILE).read_text()) == {
        "tcp-1.log": 1
    }


def test_backend_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv("TPU_PERF_INGEST", raising=False)
    assert isinstance(build_backend_from_env(), NullBackend)
    monkeypatch.setenv("TPU_PERF_INGEST", "none")
    assert isinstance(build_backend_from_env(), NullBackend)
    monkeypatch.setenv("TPU_PERF_INGEST", f"local:{tmp_path}")
    b = build_backend_from_env()
    assert isinstance(b, LocalDirBackend)
    assert b.sink_dir == str(tmp_path)
    monkeypatch.setenv("TPU_PERF_INGEST", "local:")
    with pytest.raises(ValueError):
        build_backend_from_env()
    monkeypatch.setenv("TPU_PERF_INGEST", "bogus:x")
    with pytest.raises(ValueError):
        build_backend_from_env()


# --- SubprocessIngest: the rotation hook off the measurement thread ---


class _FakeProc:
    def __init__(self, rc=None):
        self.rc = rc  # None = still running
        self.killed = False

    def poll(self):
        return self.rc

    def wait(self, timeout=None):
        import subprocess

        if self.rc is None:
            raise subprocess.TimeoutExpired("cmd", timeout)
        return self.rc


def _spy_popen(procs):
    spawned = []

    def popen(cmd, **kw):
        spawned.append(cmd)
        return procs[len(spawned) - 1]

    return popen, spawned


def test_subprocess_ingest_skip_if_still_running(capsys):
    from tpu_perf.ingest.pipeline import SubprocessIngest

    running = _FakeProc(rc=None)
    popen, spawned = _spy_popen([running, _FakeProc()])
    hook = SubprocessIngest(["ingest-cmd"], popen=popen)
    hook()
    assert len(spawned) == 1
    hook()  # previous pass still alive: skip, don't stack processes
    assert len(spawned) == 1
    assert "still running" in capsys.readouterr().err
    running.rc = 0  # pass finished
    hook()  # retried at the next rotation
    assert len(spawned) == 2


def test_subprocess_ingest_failure_reported_not_fatal(capsys):
    from tpu_perf.ingest.pipeline import SubprocessIngest

    popen, spawned = _spy_popen([_FakeProc(rc=7), _FakeProc(rc=0)])
    hook = SubprocessIngest(["ingest-cmd"], popen=popen)
    hook()
    hook()  # reaps the rc=7 pass, reports it, spawns the retry
    assert len(spawned) == 2
    assert "exited 7" in capsys.readouterr().err


def test_subprocess_ingest_finish_drains_and_reports(capsys):
    from tpu_perf.ingest.pipeline import SubprocessIngest

    popen, _ = _spy_popen([_FakeProc(rc=3)])
    hook = SubprocessIngest(["ingest-cmd"], popen=popen)
    hook()
    hook.finish()
    assert "exited 3" in capsys.readouterr().err
    hook.finish()  # idempotent

    popen, _ = _spy_popen([_FakeProc(rc=None)])
    hook = SubprocessIngest(["ingest-cmd"], popen=popen)
    hook()
    hook.finish(timeout=0.01)  # never blocks the exit path for long
    assert "leaving it to finish" in capsys.readouterr().err


def test_ingest_command_default_and_override(monkeypatch):
    import sys

    from tpu_perf.ingest.pipeline import ingest_command

    monkeypatch.delenv("TPU_PERF_INGEST_CMD", raising=False)
    assert ingest_command("/mnt/tcp-logs", 10) == [
        sys.executable, "-m", "tpu_perf", "ingest",
        "-d", "/mnt/tcp-logs", "-f", "10",
    ]
    # the C backend's env contract (tpu_mpi_perf.c TPU_PERF_INGEST_CMD):
    # a shell line, so numactl pinning prefixes work like mpi_perf.c:363
    monkeypatch.setenv("TPU_PERF_INGEST_CMD",
                       "numactl -N 1 python3 -m tpu_perf ingest -d /x -f 2")
    assert ingest_command("/mnt/tcp-logs", 10) == [
        "/bin/sh", "-c",
        "numactl -N 1 python3 -m tpu_perf ingest -d /x -f 2",
    ]


def test_subprocess_ingest_end_to_end(tmp_path):
    # a real subprocess: the pass ingests (local backend) and deletes,
    # asynchronously from the caller
    import sys

    from tpu_perf.ingest.pipeline import SubprocessIngest

    sink = tmp_path / "sink"
    logs = tmp_path / "logs"
    logs.mkdir()
    (logs / "tcp-old.log").write_text("r\n")
    env_script = (
        "import os; os.environ['TPU_PERF_INGEST'] = 'local:%s';"
        "from tpu_perf.ingest.pipeline import run_ingest_pass, build_backend_from_env;"
        "run_ingest_pass('%s', skip_newest=0, backend=build_backend_from_env())"
        % (sink, logs)
    )
    hook = SubprocessIngest([sys.executable, "-c", env_script])
    hook()
    hook.finish(timeout=60)
    assert (sink / "tcp-old.log").exists()
    assert not (logs / "tcp-old.log").exists()


# --- KustoBackend contract, with stub azure modules (VERDICT r2 #8) ---


def _install_azure_stubs(monkeypatch, calls, on_ingest=None):
    """Minimal azure SDK fakes covering exactly what KustoBackend touches.
    ``on_ingest(path, props)`` hooks the upload (the fake-endpoint tests
    route it into FakeKustoEndpoint.upload_csv)."""
    import sys
    import types

    identity = types.ModuleType("azure.identity")
    identity.ManagedIdentityCredential = type("ManagedIdentityCredential", (), {})

    data = types.ModuleType("azure.kusto.data")

    class KCSB:
        @staticmethod
        def with_aad_managed_service_identity_authentication(uri):
            calls.append(("kcsb", uri))
            return ("kcsb", uri)

    data.KustoConnectionStringBuilder = KCSB

    ingest = types.ModuleType("azure.kusto.ingest")

    class QueuedIngestClient:
        def __init__(self, kcsb):
            calls.append(("client", kcsb))

        def ingest_from_file(self, path, ingestion_properties):
            calls.append(("ingest", path, ingestion_properties))
            if getattr(self, "fail", False):
                raise RuntimeError("kusto unavailable")
            if on_ingest is not None:
                on_ingest(path, ingestion_properties)

    class IngestionProperties:
        def __init__(self, database, table, data_format):
            self.database = database
            self.table = table
            self.data_format = data_format

    ingest.QueuedIngestClient = QueuedIngestClient
    ingest.IngestionProperties = IngestionProperties
    props_mod = types.ModuleType("azure.kusto.ingest.ingestion_properties")

    class DataFormat:
        CSV = "csv"
        JSON = "json"

    props_mod.DataFormat = DataFormat

    azure = types.ModuleType("azure")
    kusto = types.ModuleType("azure.kusto")
    for name, mod in {
        "azure": azure, "azure.identity": identity, "azure.kusto": kusto,
        "azure.kusto.data": data, "azure.kusto.ingest": ingest,
        "azure.kusto.ingest.ingestion_properties": props_mod,
    }.items():
        monkeypatch.setitem(sys.modules, name, mod)
    return QueuedIngestClient


def test_kusto_backend_contract_with_stubs(tmp_path, monkeypatch):
    """pipeline.py KustoBackend against kusto_ingest.py:24-44: MSI auth on
    the ingest URI, CSV props into WarpPPE.PerfLogsMPI, delete only after
    a successful ingest, keep on failure."""
    calls = []
    client_cls = _install_azure_stubs(monkeypatch, calls)

    from tpu_perf.ingest.pipeline import KustoBackend, run_ingest_pass

    backend = KustoBackend("https://ingest-x.kusto.windows.net")
    assert ("kcsb", "https://ingest-x.kusto.windows.net") in calls
    assert backend._props.database == "WarpPPE"
    assert backend._props.table == "PerfLogsMPI"
    assert backend._props.data_format == "csv"

    ok = _mk(tmp_path, "tcp-ok.log", time.time() - 100)
    n = run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend)
    assert n == 1
    ingest_calls = [c for c in calls if c[0] == "ingest"]
    assert ingest_calls[-1][1] == ok
    assert ingest_calls[-1][2] is backend._props
    assert not os.path.exists(ok)  # delete-after-success

    # health-*.log events route into the JSON-format props (third family)
    assert backend._props_health.table == "HealthEventsTPU"
    assert backend._props_health.data_format == "json"
    hev = _mk(tmp_path, "health-ev.log", time.time() - 100)
    n = run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend,
                        prefix="health")
    assert n == 1
    ingest_calls = [c for c in calls if c[0] == "ingest"]
    assert ingest_calls[-1][1] == hev
    assert ingest_calls[-1][2] is backend._props_health

    kept = _mk(tmp_path, "tcp-kept.log", time.time() - 100)
    backend._client.fail = True
    with pytest.raises(RuntimeError, match="kusto unavailable"):
        run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend)
    assert os.path.exists(kept)  # keep-on-failure: retried next pass


def test_kusto_routes_chaos_ledger_to_its_own_table(tmp_path, monkeypatch):
    # chaos-*.log ledger records are JSONL like health events: routed
    # into their own JSON-format table, never the CSV mappings
    calls = []
    _install_azure_stubs(monkeypatch, calls)
    from tpu_perf.ingest.pipeline import KustoBackend, run_ingest_pass

    backend = KustoBackend("https://ingest-x.kusto.windows.net")
    assert backend._props_chaos.table == "ChaosEventsTPU"
    assert backend._props_chaos.data_format == "json"
    rec = _mk(tmp_path, "chaos-led.log", time.time() - 100)
    n = run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend,
                        prefix="chaos")
    assert n == 1
    ingest_calls = [c for c in calls if c[0] == "ingest"]
    assert ingest_calls[-1][1] == rec
    assert ingest_calls[-1][2] is backend._props_chaos


def test_kusto_routes_tune_family_to_its_own_table(tmp_path, monkeypatch):
    # tune-*.log selection records (the eighth family, `tpu-perf tune
    # -l`) are JSONL: routed into TuneSelectionTPU with JSON props
    calls = []
    _install_azure_stubs(monkeypatch, calls)
    from tpu_perf.ingest.pipeline import KustoBackend, run_ingest_pass

    backend = KustoBackend("https://ingest-x.kusto.windows.net")
    assert backend._props_tune.table == "TuneSelectionTPU"
    assert backend._props_tune.data_format == "json"
    rec = _mk(tmp_path, "tune-sel.log", time.time() - 100)
    n = run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend,
                        prefix="tune")
    assert n == 1
    ingest_calls = [c for c in calls if c[0] == "ingest"]
    assert ingest_calls[-1][1] == rec
    assert ingest_calls[-1][2] is backend._props_tune


def test_all_passes_sweep_chaos_family_without_skip(tmp_path):
    # the fourth family rides run_all_ingest_passes with no newest-skip
    # (lazy .open contract, like health)
    from tpu_perf.ingest.pipeline import run_all_ingest_passes

    src = tmp_path / "logs"
    sink = tmp_path / "sink"
    src.mkdir()
    t = time.time()
    _mk(src, "chaos-1.log", t - 100)
    _mk(src, "chaos-2.log.open", t - 50)  # active: invisible to ingest
    n = run_all_ingest_passes(str(src), skip_newest=1,
                              backend=LocalDirBackend(str(sink)))
    assert n == 1
    assert sorted(p.name for p in src.iterdir()) == ["chaos-2.log.open"]
    assert sorted(p.name for p in sink.iterdir()) == ["chaos-1.log"]


def test_kusto_backend_env_spec_with_stubs(monkeypatch):
    calls = []
    _install_azure_stubs(monkeypatch, calls)
    monkeypatch.setenv(
        "TPU_PERF_INGEST", "kusto:https://ingest-y.kusto.windows.net,MyDb,MyTable"
    )
    from tpu_perf.ingest.pipeline import KustoBackend, build_backend_from_env

    b = build_backend_from_env()
    assert isinstance(b, KustoBackend)
    assert b._props.database == "MyDb" and b._props.table == "MyTable"


# --- serialization against a fake Kusto ENDPOINT (VERDICT r3 weak #1) ---
#
# The call-shape stubs above pin what KustoBackend invokes; this section
# pins what the SERVICE would receive: a fake queued-ingest endpoint
# that consumes each uploaded file as CSV and type-checks every row
# against the real PerfLogsMPI column schema (mpi_perf.c:550:
# Timestamp:datetime, JobId:string, Rank:int, VMCount:int,
# LocalIP:string, RemoteIP:string, NumOfFlows:int, BufferSize:int,
# NumOfBuffers:int, TimeTakenms:real, RunId:int).  A row the table's
# mapping could not ingest — wrong arity, a non-numeric real — fails
# the upload, so schema drift in LegacyRow (or in anything feeding the
# pipeline) surfaces here instead of in production telemetry.


class FakeKustoEndpoint:
    """In-memory stand-in for the queued-ingest service + table mappings
    (legacy PerfLogsMPI and the extended-schema PerfLogsTPU)."""

    _SCHEMAS = {
        "PerfLogsMPI": (
            ("Timestamp", "datetime"), ("JobId", "string"), ("Rank", "int"),
            ("VMCount", "int"), ("LocalIP", "string"), ("RemoteIP", "string"),
            ("NumOfFlows", "int"), ("BufferSize", "int"),
            ("NumOfBuffers", "int"), ("TimeTakenms", "real"), ("RunId", "int"),
        ),
        # schema.ResultRow's columns (15 + the adaptive sampling
        # triple, ISSUE 5, + the trailing SpanId join key, ISSUE 6, +
        # the trailing Algo column, ISSUE 10, + the trailing SkewUs
        # arrival-spread coordinate, ISSUE 11 — untraced/native/
        # synchronized rows omit the trailers, which Kusto CSV mappings
        # ingest as empty; upload_csv mirrors that trailing-optional
        # behavior)
        "PerfLogsTPU": (
            ("Timestamp", "datetime"), ("JobId", "string"),
            ("Backend", "string"), ("Op", "string"), ("NBytes", "int"),
            ("Iters", "int"), ("RunId", "int"), ("NDevices", "int"),
            ("LatUs", "real"), ("AlgbwGbps", "real"), ("BusbwGbps", "real"),
            ("TimeMs", "real"), ("Dtype", "string"), ("Mode", "string"),
            ("OverheadUs", "real"), ("RunsRequested", "int"),
            ("RunsTaken", "int"), ("CiRel", "real"),
            ("SpanId", "string"), ("Algo", "string"), ("SkewUs", "int"),
            ("Imbalance", "int"),
        ),
    }

    def __init__(self):
        self.tables = {}  # (db, table) -> list of typed row tuples

    def upload_csv(self, path, database, table):
        import datetime

        columns = self._SCHEMAS[table]
        rows = []
        with open(path) as fh:
            for lineno, line in enumerate(fh, 1):
                line = line.rstrip("\n")
                if not line:
                    continue
                parts = line.split(",")
                if table == "PerfLogsTPU":
                    # untraced/native/synchronized/balanced rows omit
                    # the trailing SpanId/Algo/SkewUs/Imbalance
                    # columns; a CSV mapping ingests the absent
                    # trailers as empty
                    while len(parts) in (len(columns) - 4,
                                         len(columns) - 3,
                                         len(columns) - 2,
                                         len(columns) - 1):
                        parts.append("")
                if len(parts) != len(columns):
                    raise RuntimeError(
                        f"{path}:{lineno}: {len(parts)} fields, table "
                        f"{table} has {len(columns)} columns"
                    )
                typed = []
                for (col, kind), raw in zip(columns, parts):
                    try:
                        if raw == "" and kind in ("int", "real") \
                                and col in ("SkewUs", "Imbalance"):
                            # the absent numeric trailer: a Kusto CSV
                            # mapping ingests an empty cell as null
                            typed.append(None)
                        elif kind == "int":
                            typed.append(int(raw))
                        elif kind == "real":
                            typed.append(float(raw))
                        elif kind == "datetime":
                            typed.append(datetime.datetime.strptime(
                                raw, "%Y-%m-%d %H:%M:%S.%f"
                            ) if "." in raw else datetime.datetime.strptime(
                                raw, "%Y-%m-%d %H:%M:%S"
                            ))
                        else:
                            typed.append(raw)
                    except ValueError as e:
                        raise RuntimeError(
                            f"{path}:{lineno}: column {col}:{kind} cannot "
                            f"ingest {raw!r}: {e}"
                        ) from None
                rows.append(tuple(typed))
        self.tables.setdefault((database, table), []).extend(rows)


def _install_azure_endpoint(monkeypatch, endpoint):
    """The call-shape stubs wired into ``endpoint`` (one installer, one
    place to track the SDK surface)."""
    _install_azure_stubs(
        monkeypatch, [],
        on_ingest=lambda path, props: endpoint.upload_csv(
            path, props.database, props.table
        ),
    )


def test_kusto_endpoint_ingests_real_legacy_rows(tmp_path, monkeypatch):
    # real LegacyRow emission -> KustoBackend -> fake endpoint: every row
    # must type-check against the PerfLogsMPI schema
    from tpu_perf.schema import LegacyRow

    endpoint = FakeKustoEndpoint()
    _install_azure_endpoint(monkeypatch, endpoint)
    from tpu_perf.ingest.pipeline import KustoBackend, run_ingest_pass

    rows = [
        LegacyRow(timestamp="2026-07-30 12:00:00.123", job_id="j-1",
                  rank=1, vm_count=2, local_ip="10.0.0.2",
                  remote_ip="10.0.0.3", num_flows=10, buffer_size=456131,
                  num_buffers=10, time_taken_ms=1.5, run_id=1),
        # extreme values the table's int/real columns must still take
        LegacyRow(timestamp="2026-07-30 12:00:01.000", job_id="x" * 36,
                  rank=0, vm_count=1 << 20, local_ip="0.0.0.0",
                  remote_ip="255.255.255.255", num_flows=1,
                  buffer_size=1 << 30, num_buffers=1,
                  time_taken_ms=0.001, run_id=10 ** 12),
    ]
    p = tmp_path / "tcp-x.log"
    p.write_text("".join(r.to_csv() + "\n" for r in rows))
    os.utime(p, (time.time() - 100,) * 2)

    backend = KustoBackend("https://ingest-x.kusto.windows.net")
    n = run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend)
    assert n == 1
    stored = endpoint.tables[("WarpPPE", "PerfLogsMPI")]
    assert len(stored) == 2
    assert stored[0][2] == 1 and stored[0][9] == 1.5  # Rank, TimeTakenms
    assert stored[1][10] == 10 ** 12
    assert not p.exists()  # delete-after-success


def test_kusto_endpoint_rejects_drifted_rows(tmp_path, monkeypatch):
    # a row the table mapping cannot ingest fails the pass and KEEPS the
    # file (delete-only-after-success): schema drift is loud, not silent
    endpoint = FakeKustoEndpoint()
    _install_azure_endpoint(monkeypatch, endpoint)
    from tpu_perf.ingest.pipeline import KustoBackend, run_ingest_pass

    bad = tmp_path / "tcp-bad.log"
    # 12 fields: an extended-schema row in a legacy log
    bad.write_text("2026-07-30 12:00:00.1,j,jax,ring,1,2,3,4,5.0,6,7,8\n")
    os.utime(bad, (time.time() - 100,) * 2)
    backend = KustoBackend("https://ingest-x.kusto.windows.net")
    with pytest.raises(RuntimeError, match="12 fields"):
        run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend)
    assert bad.exists()

    nonnum = tmp_path / "tcp-nonnum.log"
    nonnum.write_text(
        "2026-07-30 12:00:00.1,j,1,2,ip,ip,3,4,5,NaNms,6\n")
    os.utime(nonnum, (time.time() - 100,) * 2)
    bad.unlink()
    with pytest.raises(RuntimeError, match="TimeTakenms:real"):
        run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend)
    assert nonnum.exists()


def test_kusto_routes_extended_rows_to_their_own_table(tmp_path, monkeypatch):
    # tpu-*.log rows carry 15 columns; landing them in the 11-column
    # PerfLogsMPI table would fail every row's mapping — KustoBackend
    # routes by filename prefix, matching how the CLI ingest pass scans
    # both prefixes into one backend
    from tpu_perf.schema import ResultRow

    endpoint = FakeKustoEndpoint()
    _install_azure_endpoint(monkeypatch, endpoint)
    from tpu_perf.ingest.pipeline import KustoBackend, run_ingest_pass

    row = ResultRow(
        timestamp="2026-07-30 12:00:00.123", job_id="j", backend="jax",
        op="hbm_stream", nbytes=1 << 20, iters=25, run_id=1, n_devices=1,
        lat_us=816.4, algbw_gbps=328.8, busbw_gbps=657.6, time_ms=20.4,
        dtype="float32", mode="daemon", overhead_us=12.5,
        runs_requested=12, runs_taken=7, ci_rel=0.031,
    )
    p = tmp_path / "tpu-x.log"
    p.write_text(row.to_csv() + "\n")
    os.utime(p, (time.time() - 100,) * 2)

    backend = KustoBackend("https://ingest-x.kusto.windows.net")
    n = run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend,
                        prefix="tpu")
    assert n == 1
    assert ("WarpPPE", "PerfLogsMPI") not in endpoint.tables
    (stored,) = endpoint.tables[("WarpPPE", "PerfLogsTPU")]
    assert stored[3] == "hbm_stream" and stored[10] == 657.6
    assert stored[13] == "daemon" and stored[14] == 12.5
    # the adaptive sampling triple lands typed too (ISSUE 5), and an
    # untraced native row's absent SpanId/Algo columns ingest as empty
    # (ISSUE 6 / ISSUE 10)
    assert stored[15] == 12 and stored[16] == 7 and stored[17] == 0.031
    assert stored[18] == "" and stored[19] == ""


def test_kusto_ingests_traced_rows_with_span_column(tmp_path, monkeypatch):
    # a --spans row carries the 19th SpanId column; it must land typed
    # in PerfLogsTPU (ISSUE 6: the cross-family join key is queryable)
    from tpu_perf.schema import ResultRow

    endpoint = FakeKustoEndpoint()
    _install_azure_endpoint(monkeypatch, endpoint)
    from tpu_perf.ingest.pipeline import KustoBackend, run_ingest_pass

    row = ResultRow(
        timestamp="2026-07-30 12:00:00.123", job_id="j", backend="jax",
        op="ring", nbytes=64, iters=5, run_id=3, n_devices=8,
        lat_us=10.0, algbw_gbps=1.0, busbw_gbps=1.75, time_ms=0.05,
        span_id="r3",
    )
    p = tmp_path / "tpu-traced.log"
    p.write_text(row.to_csv() + "\n")
    os.utime(p, (time.time() - 100,) * 2)
    backend = KustoBackend("https://ingest-x.kusto.windows.net")
    assert run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend,
                           prefix="tpu") == 1
    (stored,) = endpoint.tables[("WarpPPE", "PerfLogsTPU")]
    assert stored[18] == "r3" and stored[19] == ""


def test_kusto_ingests_arena_rows_with_algo_column(tmp_path, monkeypatch):
    # an arena row carries the 20th Algo column (ISSUE 10); it must land
    # typed in PerfLogsTPU so per-algorithm crossover queries work in
    # the telemetry store, and a traced-but-native 19-field row in the
    # same file keeps ingesting with Algo empty
    from tpu_perf.schema import ResultRow

    endpoint = FakeKustoEndpoint()
    _install_azure_endpoint(monkeypatch, endpoint)
    from tpu_perf.ingest.pipeline import KustoBackend, run_ingest_pass

    def row(**kw):
        return ResultRow(
            timestamp="2026-08-03 12:00:00.123", job_id="j", backend="jax",
            op="allreduce", nbytes=64, iters=5, run_id=3, n_devices=8,
            lat_us=10.0, algbw_gbps=1.0, busbw_gbps=1.75, time_ms=0.05,
            **kw,
        )

    p = tmp_path / "tpu-arena.log"
    p.write_text(row(algo="ring", span_id="r9").to_csv() + "\n"
                 + row(span_id="r9").to_csv() + "\n")
    os.utime(p, (time.time() - 100,) * 2)
    backend = KustoBackend("https://ingest-x.kusto.windows.net")
    assert run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend,
                           prefix="tpu") == 1
    arena, native = endpoint.tables[("WarpPPE", "PerfLogsTPU")]
    assert arena[19] == "ring" and arena[18] == "r9"
    assert native[19] == "" and native[18] == "r9"


def test_kusto_ingests_skew_rows_with_skew_column(tmp_path, monkeypatch):
    # a skew-axis row carries the 21st SkewUs column (ISSUE 11); it must
    # land typed in PerfLogsTPU so straggler-cost queries work in the
    # telemetry store, and the narrower widths in the same file — a
    # zero-skew 18-field row, an arena 20-field row — keep ingesting
    # with the absent trailers null/empty (the trailing-optional CSV
    # mapping behavior)
    from tpu_perf.schema import ResultRow

    endpoint = FakeKustoEndpoint()
    _install_azure_endpoint(monkeypatch, endpoint)
    from tpu_perf.ingest.pipeline import KustoBackend, run_ingest_pass

    def row(**kw):
        return ResultRow(
            timestamp="2026-08-03 12:00:00.123", job_id="j", backend="jax",
            op="allreduce", nbytes=64, iters=5, run_id=3, n_devices=8,
            lat_us=10.0, algbw_gbps=1.0, busbw_gbps=1.75, time_ms=0.05,
            **kw,
        )

    skew_row = row(skew_us=1000, algo="ring")
    assert len(skew_row.to_csv().split(",")) == 21
    p = tmp_path / "tpu-skew.log"
    p.write_text(skew_row.to_csv() + "\n"
                 + row(algo="ring", span_id="r9").to_csv() + "\n"
                 + row().to_csv() + "\n")
    os.utime(p, (time.time() - 100,) * 2)
    backend = KustoBackend("https://ingest-x.kusto.windows.net")
    assert run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend,
                           prefix="tpu") == 1
    skewed, arena, plain = endpoint.tables[("WarpPPE", "PerfLogsTPU")]
    assert skewed[20] == 1000 and skewed[19] == "ring"
    assert arena[20] is None and arena[19] == "ring"
    assert plain[20] is None and plain[19] == "" and plain[18] == ""


def test_kusto_ingests_imbalance_rows_with_imbalance_column(
        tmp_path, monkeypatch):
    # an imbalance-axis row carries the 22nd Imbalance column
    # (ISSUE 15); it must land typed in PerfLogsTPU so imbalance-cost
    # queries work in the telemetry store, and every narrower width in
    # the same file keeps ingesting with the absent trailers
    # null/empty (the trailing-optional CSV mapping behavior)
    from tpu_perf.schema import ResultRow

    endpoint = FakeKustoEndpoint()
    _install_azure_endpoint(monkeypatch, endpoint)
    from tpu_perf.ingest.pipeline import KustoBackend, run_ingest_pass

    def row(**kw):
        base = dict(
            timestamp="2026-08-03 12:00:00.123", job_id="j", backend="jax",
            op="allgatherv", nbytes=64, iters=5, run_id=3, n_devices=8,
            lat_us=10.0, algbw_gbps=1.0, busbw_gbps=1.75, time_ms=0.05,
        )
        base.update(kw)
        return ResultRow(**base)

    imb_row = row(imbalance=8)
    scn_row = row(op="scenario", algo="moe-dispatch-combine", imbalance=2)
    assert len(imb_row.to_csv().split(",")) == 22
    p = tmp_path / "tpu-imb.log"
    p.write_text(imb_row.to_csv() + "\n"
                 + scn_row.to_csv() + "\n"
                 + row(skew_us=1000).to_csv() + "\n"
                 + row().to_csv() + "\n")
    os.utime(p, (time.time() - 100,) * 2)
    backend = KustoBackend("https://ingest-x.kusto.windows.net")
    assert run_ingest_pass(str(tmp_path), skip_newest=0, backend=backend,
                           prefix="tpu") == 1
    imb, scn, skewed, plain = endpoint.tables[("WarpPPE", "PerfLogsTPU")]
    assert imb[21] == 8 and imb[3] == "allgatherv"
    assert scn[21] == 2 and scn[19] == "moe-dispatch-combine" \
        and scn[3] == "scenario"
    assert skewed[21] is None and skewed[20] == 1000
    assert plain[21] is None and plain[20] is None


def test_kusto_env_spec_table_ext(monkeypatch):
    calls = []
    _install_azure_stubs(monkeypatch, calls)
    monkeypatch.setenv(
        "TPU_PERF_INGEST",
        "kusto:https://ingest-y.kusto.windows.net,MyDb,MyTable,MyExtTable",
    )
    from tpu_perf.ingest.pipeline import KustoBackend, build_backend_from_env

    b = build_backend_from_env()
    assert isinstance(b, KustoBackend)
    assert b._props.table == "MyTable"
    assert b._props_ext.table == "MyExtTable"
    assert b._props_ext.database == "MyDb"
