import os
import time

import pytest

from tpu_perf.ingest import (
    LocalDirBackend,
    NullBackend,
    build_backend_from_env,
    eligible_files,
    run_ingest_pass,
)


def _mk(folder, name, mtime):
    p = folder / name
    p.write_text("row\n")
    os.utime(p, (mtime, mtime))
    return str(p)


def test_eligible_files_skips_newest(tmp_path):
    """kusto_ingest.py:32-40: tcp* only, oldest first, newest N skipped."""
    t = time.time()
    old = _mk(tmp_path, "tcp-a.log", t - 300)
    mid = _mk(tmp_path, "tcp-b.log", t - 200)
    new = _mk(tmp_path, "tcp-c.log", t - 100)
    _mk(tmp_path, "other.log", t - 500)  # non-tcp prefix ignored
    got = eligible_files(str(tmp_path), 1)
    assert got == [old, mid]
    assert eligible_files(str(tmp_path), 0) == [old, mid, new]
    assert eligible_files(str(tmp_path), 5) == []  # skip more than exist


def test_eligible_files_missing_folder():
    assert eligible_files("/nonexistent/nowhere", 10) == []


def test_eligible_files_validation(tmp_path):
    with pytest.raises(ValueError):
        eligible_files(str(tmp_path), -1)


def test_run_ingest_pass_local_backend(tmp_path):
    src = tmp_path / "logs"
    sink = tmp_path / "sink"
    src.mkdir()
    t = time.time()
    _mk(src, "tcp-1.log", t - 300)
    _mk(src, "tcp-2.log", t - 200)
    _mk(src, "tcp-3.log", t - 100)
    n = run_ingest_pass(str(src), skip_newest=1, backend=LocalDirBackend(str(sink)))
    assert n == 2
    # ingested files deleted from source (kusto_ingest.py:41-44)
    assert sorted(p.name for p in src.iterdir()) == ["tcp-3.log"]
    assert sorted(p.name for p in sink.iterdir()) == ["tcp-1.log", "tcp-2.log"]


def test_failed_ingest_keeps_file(tmp_path):
    t = time.time()
    _mk(tmp_path, "tcp-1.log", t - 300)
    _mk(tmp_path, "tcp-2.log", t - 200)

    class Boom(NullBackend):
        def ingest(self, path):
            raise IOError("upload failed")

    with pytest.raises(IOError):
        run_ingest_pass(str(tmp_path), skip_newest=0, backend=Boom())
    # nothing deleted: retry next pass
    assert len(list(tmp_path.iterdir())) == 2


def test_backend_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv("TPU_PERF_INGEST", raising=False)
    assert isinstance(build_backend_from_env(), NullBackend)
    monkeypatch.setenv("TPU_PERF_INGEST", "none")
    assert isinstance(build_backend_from_env(), NullBackend)
    monkeypatch.setenv("TPU_PERF_INGEST", f"local:{tmp_path}")
    b = build_backend_from_env()
    assert isinstance(b, LocalDirBackend)
    assert b.sink_dir == str(tmp_path)
    monkeypatch.setenv("TPU_PERF_INGEST", "local:")
    with pytest.raises(ValueError):
        build_backend_from_env()
    monkeypatch.setenv("TPU_PERF_INGEST", "bogus:x")
    with pytest.raises(ValueError):
        build_backend_from_env()
