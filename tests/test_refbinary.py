"""Row-level interop with the GENUINE reference binary (VERDICT r3 #5).

``/root/reference/mpi_perf.c`` is compiled UNMODIFIED against the
process-per-rank shim (``backends/mpi/procshim/``: mpi.h + uuid/uuid.h
compat headers over a Unix-socket transport, launched by shim_mpirun) and
run as a real 2-rank job.  Its tcp-*.log output — written by the
reference's own fprintf at mpi_perf.c:550-554 — must flow through
``report --legacy`` and the ingest pipeline, proving the framework
interoperates with the actual artifact, not just with the repo's
re-implementation of it (``tpu_mpi_perf.c``).

Skipped when the reference tree or a C compiler is absent.
"""

import os
import shutil
import subprocess

import pytest

from tpu_perf.schema import LegacyRow

BACKEND_DIR = os.path.join(os.path.dirname(__file__), "..", "backends", "mpi")
REF_SRC = os.environ.get("TPU_PERF_REF_SRC", "/root/reference/mpi_perf.c")

pytestmark = [
    pytest.mark.skipif(not os.path.isfile(REF_SRC),
                       reason=f"reference source not present: {REF_SRC}"),
    pytest.mark.skipif(shutil.which("gcc") is None and
                       shutil.which("cc") is None,
                       reason="no C compiler"),
]


@pytest.fixture(scope="module")
def ref_binary():
    subprocess.run(
        ["make", "-C", BACKEND_DIR, "procshim", "ref", f"REF_SRC={REF_SRC}"],
        check=True, capture_output=True,
    )
    return (os.path.join(BACKEND_DIR, "shim_mpirun"),
            os.path.join(BACKEND_DIR, "ref_mpi_perf"))


def _run_ref(ref_binary, tmp_path, extra, np=2, ppn=1):
    launcher, binary = ref_binary
    hosts = tmp_path / "group1.txt"
    # group 1 = the LAST host; shim_mpirun names host h "127.0.<2+h>.1"
    # (numeric so the reference's getaddrinfo resolves it; host index in
    # the third octet so no name is a strnicmp prefix of another)
    n_hosts = np // ppn
    hosts.write_text(f"127.0.{1 + n_hosts}.1\n")
    logdir = tmp_path / "logs"
    logdir.mkdir(exist_ok=True)
    cmd = [launcher, "-np", str(np), "-p", str(ppn), "--", binary,
           "-f", str(hosts), "-n", "1", "-p", str(ppn),
           "-l", str(logdir)] + extra
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    return logdir, proc


def test_ref_binary_pingpong_rows(ref_binary, tmp_path):
    logdir, proc = _run_ref(
        ref_binary, tmp_path, ["-i", "5", "-b", "65536", "-r", "3"]
    )
    # the reference prints its job UUID and the rank-0 stats heartbeat
    assert "UUID:" in proc.stderr
    assert "Total time" in proc.stderr

    rows = []
    for log in sorted(logdir.glob("tcp-*.log")):
        for line in log.read_text().splitlines():
            rows.append(LegacyRow.from_csv(line))
    # 3 runs, run 0 skipped as warm-up (mpi_perf.c:545), group-1 rank only
    assert len(rows) == 2
    assert [r.run_id for r in rows] == [1, 2]
    for r in rows:
        assert r.rank == 1  # rank 1 is the group-1 side of a 2-rank job
        assert r.vm_count == 2 and r.num_flows == 1
        assert r.buffer_size == 65536 and r.num_buffers == 5
        assert r.time_taken_ms > 0
        assert r.local_ip == "127.0.3.1" and r.remote_ip == "127.0.2.1"


@pytest.mark.parametrize("extra", [
    ["-i", "3", "-b", "456131", "-u", "1", "-r", "2"],   # unidir + 1-byte ack
    ["-i", "600", "-b", "4096", "-x", "1", "-r", "2"],   # crosses the 256-slot
                                                         # window (mpi_perf.c:88)
])
def test_ref_binary_other_kernels(ref_binary, tmp_path, extra):
    logdir, _ = _run_ref(ref_binary, tmp_path, extra)
    rows = [LegacyRow.from_csv(ln) for log in sorted(logdir.glob("tcp-*.log"))
            for ln in log.read_text().splitlines()]
    assert len(rows) == 1  # 2 runs - warm-up, one group-1 rank
    assert rows[0].buffer_size == int(extra[3])


def test_ref_binary_four_ranks_two_flows(ref_binary, tmp_path):
    # ppr:2:node analogue: 4 ranks on 2 "hosts", both group-1 ranks log
    logdir, _ = _run_ref(
        ref_binary, tmp_path, ["-i", "4", "-b", "8192", "-r", "2"],
        np=4, ppn=2,
    )
    rows = [LegacyRow.from_csv(ln) for log in sorted(logdir.glob("tcp-*.log"))
            for ln in log.read_text().splitlines()]
    assert len(rows) == 2
    assert sorted(r.rank for r in rows) == [2, 3]
    assert all(r.vm_count == 2 and r.num_flows == 2 for r in rows)


def test_run_count_semantics_vs_genuine_binary(ref_binary, tmp_path):
    """VERDICT r4 weak #4, pinned side by side: the SAME ``-r 3`` yields
    2 logged rows from the genuine binary (it counts the warm-up inside
    N, mpi_perf.c:474,545) and 3 from this repo's driver (one unlogged
    warm-up PLUS N logged rows).  Documented in tpu_mpi_perf.c's usage();
    a side-by-side fleet config must match sample sizes accordingly."""
    launcher, _ = ref_binary
    subprocess.run(["make", "-C", BACKEND_DIR, "proc"],
                   check=True, capture_output=True)
    ours = os.path.join(BACKEND_DIR, "mpi_perf_proc")

    logdir, _ = _run_ref(ref_binary, tmp_path,
                         ["-i", "4", "-b", "8192", "-r", "3"])
    ref_rows = [ln for log in sorted(logdir.glob("tcp-*.log"))
                for ln in log.read_text().splitlines()]
    assert len(ref_rows) == 2  # N-1

    hosts = tmp_path / "g1b.txt"
    hosts.write_text("127.0.3.1\n")
    ourdir = tmp_path / "ourlogs"
    ourdir.mkdir()
    proc = subprocess.run(
        [launcher, "-np", "2", "-p", "1", "--", ours, "-f", str(hosts),
         "-i", "4", "-b", "8192", "-r", "3", "-l", str(ourdir)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    our_rows = [ln for log in sorted(ourdir.glob("tcp-*.log"))
                for ln in log.read_text().splitlines()]
    assert len(our_rows) == 3  # N
    # and the divergence is spelled out where an operator will see it
    # (-h needs the shim env, so run it under the launcher; the non-zero
    # exit is usage()'s normal path)
    usage = subprocess.run([launcher, "-np", "1", "--", ours, "-h"],
                           capture_output=True, text=True, timeout=60)
    assert "logs N-1" in usage.stderr


def test_ref_binary_rows_through_report_legacy(ref_binary, tmp_path, capsys):
    from tpu_perf.cli import main

    logdir, _ = _run_ref(
        ref_binary, tmp_path, ["-i", "5", "-b", "65536", "-r", "3"]
    )
    assert main(["report", str(logdir / "tcp-*.log"), "--legacy"]) == 0
    out = capsys.readouterr().out
    assert "| 64K | 1 | 2 | 5 | 2 | 1 |" in out


def test_ref_binary_rows_through_ingest(ref_binary, tmp_path):
    from tpu_perf.ingest.pipeline import LocalDirBackend, run_ingest_pass

    logdir, _ = _run_ref(
        ref_binary, tmp_path, ["-i", "2", "-b", "4096", "-r", "2"]
    )
    files = list(logdir.glob("tcp-*.log"))
    assert files
    sink = tmp_path / "sink"
    n = run_ingest_pass(str(logdir), skip_newest=0,
                        backend=LocalDirBackend(str(sink)))
    assert n == len(files)
    # delete-after-ingest contract (kusto_ingest.py:41-44)
    assert not list(logdir.glob("tcp-*.log"))
    ingested = [LegacyRow.from_csv(ln) for f in sink.glob("tcp-*.log")
                for ln in f.read_text().splitlines()]
    assert ingested and all(r.buffer_size == 4096 for r in ingested)
