"""The print-only external-launcher mode (the reference's vestigial dotnet
path, mpi_perf.c:147-168, 504-507): command rendering from the pair
topology, kernel-selection precedence, and the full driver loop emitting
rows without compiling any kernel."""

import io

import pytest

from tpu_perf.config import Options
from tpu_perf.extern_launch import (
    DEF_PORT,
    DEFAULT_TEMPLATE,
    pair_for_rank,
    render_extern_command,
)
from tpu_perf.runner import op_for_options


def test_pair_single_process_is_loopback_server():
    assert pair_for_rank(0, 1) == (1, 0)


def test_pair_two_groups():
    # first half clients (group 0), second half servers (group 1),
    # equal group-rank pairing (mpi_perf.c:225-234)
    assert pair_for_rank(0, 4) == (0, 2)
    assert pair_for_rank(1, 4) == (0, 3)
    assert pair_for_rank(2, 4) == (1, 0)
    assert pair_for_rank(3, 4) == (1, 1)


def test_pair_odd_count_rejected():
    with pytest.raises(ValueError):
        pair_for_rank(0, 3)


def test_render_server_and_client():
    kw = dict(my_ip="10.0.0.2", peer_ip="10.0.0.1", ppn=10, buff_sz=456131,
              iters=10)
    server = render_extern_command(
        DEFAULT_TEMPLATE, group=1, rank=3, peer_rank=1, **kw
    )
    # server advertises its own ip on DEF_PORT + its world rank
    # (mpi_perf.c:155-156)
    assert server == f"extern-bench server 10.0.0.2 {DEF_PORT + 3} 10 456131 10"
    client = render_extern_command(
        DEFAULT_TEMPLATE, group=0, rank=1, peer_rank=3, **kw
    )
    # client dials the server's ip and port (mpi_perf.c:162-163)
    assert client == f"extern-bench client 10.0.0.1 {DEF_PORT + 3} 10 456131 10"


def test_render_bad_placeholder():
    with pytest.raises(ValueError):
        render_extern_command(
            "x {nope}", group=1, rank=0, peer_rank=0, my_ip="a", peer_ip="b",
            ppn=1, buff_sz=1, iters=1,
        )


def test_extern_takes_precedence_over_kernels():
    # mpi_perf.c:504-523: dotnet > nonblocking > unidir > blocking
    opts = Options(extern_cmd=DEFAULT_TEMPLATE, nonblocking=True)
    assert op_for_options(opts) == "extern"


def test_driver_extern_loop(eight_devices):
    from tpu_perf.driver import Driver
    from tpu_perf.parallel import make_mesh

    opts = Options(extern_cmd="run {role} {ip}:{port} b={bytes}", num_runs=3,
                   buff_sz=4096)
    err = io.StringIO()
    rows = Driver(opts, make_mesh(), err=err).run()
    assert len(rows) == 3
    assert all(r.op == "extern" for r in rows)
    assert all(r.busbw_gbps == 0.0 for r in rows)
    # one command per run, single process = loopback server on DEF_PORT
    lines = [ln for ln in err.getvalue().splitlines() if ln.startswith("run ")]
    assert len(lines) == 3
    assert lines[0].startswith(f"run server ") and f":{DEF_PORT} " in lines[0]
    assert "b=4096" in lines[0]


def test_cli_extern_flag(capfd, eight_devices):
    from tpu_perf.cli import main

    rc = main(["run", "-d", "-r", "2", "-b", "1K"])
    assert rc == 0
    out = capfd.readouterr()
    assert "extern-bench server" in out.err
    assert "extern,1024" in out.out.replace(" ", "")


def test_op_extern_requires_template():
    with pytest.raises(ValueError):
        Options(op="extern")


def test_run_point_rejects_extern(eight_devices):
    from tpu_perf.parallel import make_mesh
    from tpu_perf.runner import run_point

    opts = Options(extern_cmd=DEFAULT_TEMPLATE)
    with pytest.raises(ValueError):
        run_point(opts, make_mesh(), 64)


def test_cli_legacy_dash_d_one(capfd, eight_devices):
    # the reference's boolean spelling `-d 1` (mpi_perf.c:292) selects the
    # default template instead of printing a literal "1"
    from tpu_perf.cli import main

    rc = main(["run", "-d", "1", "-r", "1", "-b", "1K"])
    assert rc == 0
    assert "extern-bench server" in capfd.readouterr().err
