"""Adaptive sampling engine (ISSUE 5): variance-targeted early stopping,
rank-lockstep stop votes, the chaos/synthetic determinism bypass, and
--precompile auto depth tuning."""

import glob
import io
import json
import random

import pytest

from tpu_perf.adaptive import (
    AdaptiveConfig, PointController, PrecompileTuner, t_critical,
)
from tpu_perf.config import Options
from tpu_perf.driver import Driver
from tpu_perf.parallel import make_mesh
from tpu_perf.schema import ResultRow


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh()


# --- the t table -------------------------------------------------------


def test_t_critical_pinned_rows():
    assert t_critical(1, 0.95) == 12.706
    assert t_critical(4, 0.95) == 2.776
    assert t_critical(30, 0.95) == 2.042
    assert t_critical(4, 0.90) == 2.132
    assert t_critical(4, 0.99) == 4.604


def test_t_critical_between_pins_is_conservative():
    # df 35 is not pinned: the df-30 value (larger => wider CI) is used
    assert t_critical(35, 0.95) == t_critical(30, 0.95)
    # past the last pin: the normal limit
    assert t_critical(1000, 0.95) == 1.960
    assert t_critical(1000, 0.99) == 2.576


def test_t_critical_rejects_unknown_confidence_and_bad_df():
    with pytest.raises(ValueError, match="confidence"):
        t_critical(4, 0.80)
    with pytest.raises(ValueError, match="freedom"):
        t_critical(0, 0.95)


# --- config validation -------------------------------------------------


def test_adaptive_config_validation():
    with pytest.raises(ValueError, match="ci_rel"):
        AdaptiveConfig(ci_rel=0.0)
    with pytest.raises(ValueError, match="ci_rel"):
        AdaptiveConfig(ci_rel=1.5)
    with pytest.raises(ValueError, match="confidence"):
        AdaptiveConfig(confidence=0.5)
    with pytest.raises(ValueError, match="min_runs"):
        AdaptiveConfig(min_runs=1)
    with pytest.raises(ValueError, match="max_runs"):
        AdaptiveConfig(min_runs=10, max_runs=5)


def test_options_validate_adaptive_knobs():
    with pytest.raises(ValueError, match="ci_rel"):
        Options(ci_rel=0.0)
    with pytest.raises(ValueError, match="ci_confidence"):
        Options(ci_confidence=0.42)
    with pytest.raises(ValueError, match="min_runs"):
        Options(min_runs=1)
    with pytest.raises(ValueError, match="max_runs"):
        Options(adaptive_max_runs=0)
    # finite run + --max-runs without --ci-rel: nothing would consult
    # the cap — loud error, not a silent 5x-the-wall no-op
    with pytest.raises(ValueError, match="needs --ci-rel"):
        Options(adaptive_max_runs=10, num_runs=50)
    Options(adaptive_max_runs=10, num_runs=-1)   # daemon valve: fine
    Options(adaptive_max_runs=10, ci_rel=0.05)   # adaptive cap: fine


# --- controller convergence -------------------------------------------


def _drive(controller, series):
    """Run the caller loop until the controller stops; returns the run
    count executed."""
    runs = 0
    for t in series:
        runs += 1
        controller.observe(t)
        if controller.should_stop(runs):
            return runs
    raise AssertionError("series exhausted before the controller stopped")


def _tight_series(n=1000, rel=0.01, base=1e-3, seed=7):
    rnd = random.Random(seed)
    return [base * (1.0 + rel * (rnd.random() - 0.5)) for _ in range(n)]


def test_tight_series_stops_at_min_runs():
    cfg = AdaptiveConfig(ci_rel=0.05, min_runs=5, max_runs=50)
    c = PointController(cfg)
    runs = _drive(c, _tight_series())
    assert runs == 5
    assert c.stopped_at == 5
    assert 0.0 < c.ci_rel() <= 0.05
    s = c.summary()
    assert s["requested"] == 50 and s["attempted"] == 5 and s["saved"] == 45
    assert s["ci_rel"] == round(c.ci_rel(), 6)


def test_heavy_tailed_series_runs_to_max():
    # alternating 1x / 10x: relative half-width stays enormous
    cfg = AdaptiveConfig(ci_rel=0.05, min_runs=5, max_runs=40)
    c = PointController(cfg)
    series = [1e-3 if i % 2 else 1e-2 for i in range(100)]
    runs = _drive(c, series)
    assert runs == 40
    assert c.summary()["saved"] == 0
    assert c.ci_rel() > 0.05


def test_min_runs_counts_recorded_samples_not_drops():
    # 3 drops then tight samples: the stop rule must wait for min_runs
    # RECORDED samples (drops shape no moment), so 3 + 5 rounds run
    cfg = AdaptiveConfig(ci_rel=0.05, min_runs=5, max_runs=50)
    c = PointController(cfg)
    series = [None] * 3 + _tight_series()
    runs = _drive(c, series)
    assert runs == 8
    s = c.summary()
    assert s["dropped"] == 3 and s["taken"] == 5 and s["attempted"] == 8


def test_ci_is_inf_before_two_samples_and_on_degenerate_mean():
    import math

    cfg = AdaptiveConfig()
    c = PointController(cfg)
    assert math.isinf(c.ci_rel())
    c.observe(1.0)
    assert math.isinf(c.ci_rel())
    c.observe(2.0)
    assert math.isfinite(c.ci_rel())
    z = PointController(cfg)
    z.observe(0.0)
    z.observe(0.0)
    assert math.isinf(z.ci_rel())  # zero mean: never satisfies the target


# --- rank lockstep -----------------------------------------------------


def test_lockstep_vote_all_ranks_stop_together():
    """Two simulated ranks with different noise: the shared unanimous
    vote makes both execute the SAME number of runs — the slowest rank
    to converge sets the count (collective order stays identical)."""
    cfg = AdaptiveConfig(ci_rel=0.05, min_runs=5, max_runs=50)
    # the simulated allreduce: each round's per-rank locals are gathered
    # first (exactly what the real collective sees), the unanimous AND
    # is the decision every rank receives; the vote hook asserts each
    # controller passed its own true local verdict in
    round_locals: dict[str, bool] = {}

    def vote_for(rank):
        def vote(local):
            assert local == round_locals[rank], \
                "controller voted something other than its local verdict"
            return all(round_locals.values())
        return vote

    a = PointController(cfg, n_hosts=2, vote=vote_for("a"))
    b = PointController(cfg, n_hosts=2, vote=vote_for("b"))
    # rank a converges immediately; rank b needs more samples (its first
    # ones are noisy, then it tightens)
    series_a = _tight_series(seed=1)
    series_b = [1e-3, 2e-3, 1e-3, 2e-3, 1.5e-3] + _tight_series(
        base=1.5e-3, seed=2)
    runs = 0
    it_a, it_b = iter(series_a), iter(series_b)
    a_alone = None  # when rank a WOULD have stopped on its own
    while True:
        runs += 1
        a.observe(next(it_a))
        b.observe(next(it_b))
        round_locals.update(a=a._local_stop(runs), b=b._local_stop(runs))
        if round_locals["a"] and a_alone is None:
            a_alone = runs
        stop_a = a.should_stop(runs)
        stop_b = b.should_stop(runs)
        assert stop_a == stop_b, "ranks diverged on the stop decision"
        if stop_a:
            break
    assert a_alone is not None and runs > a_alone  # b's noise held a back
    assert a.stopped_at == b.stopped_at == runs


def test_single_host_vote_is_local():
    cfg = AdaptiveConfig(ci_rel=0.05, min_runs=5, max_runs=50)
    c = PointController(cfg, n_hosts=1)
    assert _drive(c, _tight_series()) == 5


def test_vote_skipped_during_deterministic_warmup_rounds():
    # while runs_done < min_runs no rank can stop (same runs_done
    # everywhere), so the cross-host collective must not be issued at
    # all — min_runs-1 pointless allreduces per point otherwise
    cfg = AdaptiveConfig(ci_rel=0.05, min_runs=5, max_runs=50)
    votes = []
    c = PointController(cfg, n_hosts=2, vote=lambda local: votes.append(local) or local)
    runs = _drive(c, _tight_series())
    assert runs == 5
    assert len(votes) == 1  # only round 5 voted; rounds 1-4 skipped


def test_allreduce_times_accepts_numpy_scalars():
    """Satellite (multihost.py): the lockstep vote allreduces controller
    scalars, which may be numpy types — np.float64/np.float32 used to
    fail the isinstance((int, float)) check and crash on list()."""
    import numpy as np

    from tpu_perf.parallel import allreduce_times

    out = allreduce_times(np.float64(2.5))
    assert out == {"min": 2.5, "max": 2.5, "avg": 2.5}
    out = allreduce_times(np.float32(1.5))
    assert out["min"] == pytest.approx(1.5)
    out = allreduce_times(np.int32(3))
    assert out["avg"] == 3.0
    # windows (lists/arrays) still reduce locally first
    out = allreduce_times(np.asarray([1.0, 3.0]))
    assert out == {"min": 1.0, "max": 3.0, "avg": 2.0}


# --- driver integration ------------------------------------------------


class SeededDriver(Driver):
    """Driver whose _measure is a seeded per-point series (tight 1%
    noise): deterministic convergence without touching the injector —
    whose presence would, by design, bypass the controller."""

    def _measure(self, built, built_hi):
        counts = self.__dict__.setdefault("_seed_counts", {})
        key = (built.name, built.nbytes)
        n = counts[key] = counts.get(key, 0) + 1
        rnd = random.Random(f"{built.name}:{built.nbytes}:{n}")
        return 1e-3 * (1.0 + 0.01 * (rnd.random() - 0.5))


def test_driver_adaptive_early_stop_rows_and_savings(mesh, tmp_path):
    err = io.StringIO()
    opts = Options(op="ring", sweep="8,64", iters=1, num_runs=30,
                   fence="block", logfolder=str(tmp_path),
                   ci_rel=0.05, min_runs=5)
    d = SeededDriver(opts, mesh, err=err)
    rows = d.run()
    # 2 points x 30 fixed would be 60; tight noise stops each at 5
    assert len(rows) == 10
    for (op, nbytes) in {(r.op, r.nbytes) for r in rows}:
        grp = [r for r in rows if (r.op, r.nbytes) == (op, nbytes)]
        final = max(grp, key=lambda r: r.run_id)
        assert final.runs_requested == 30
        assert final.runs_taken == len(grp) == 5
        assert 0.0 < final.ci_rel <= 0.05
    assert d.adaptive_totals == pytest.approx({
        "points": 2, "runs_requested": 60, "runs_attempted": 10,
        "runs_saved": 50,
        "wall_saved_s": d.adaptive_totals["wall_saved_s"],
    })
    assert d.adaptive_totals["wall_saved_s"] > 0
    assert "adaptive: ring/8 stopped after 5/30 runs" in err.getvalue()
    # the columns round-trip through the rotating log (floats are CSV-
    # rounded, so compare the adaptive triple + identity, not the object)
    (log,) = glob.glob(str(tmp_path / "tpu-*.log"))
    key = lambda r: (r.op, r.nbytes, r.run_id, r.runs_requested,
                     r.runs_taken, round(r.ci_rel, 6))
    with open(log) as fh:
        parsed = [ResultRow.from_csv(ln) for ln in fh.read().splitlines()]
    assert [key(r) for r in parsed] == [key(r) for r in rows]


def test_driver_adaptive_heartbeat_and_sidecar_carry_savings(mesh, tmp_path):
    err = io.StringIO()
    # stats_every below min_runs so boundaries fire despite early stops
    opts = Options(op="ring", sweep="8,64", iters=1, num_runs=30,
                   fence="block", logfolder=str(tmp_path),
                   stats_every=2, heartbeat_format="json",
                   ci_rel=0.05, min_runs=5)
    SeededDriver(opts, mesh, err=err).run()
    beats = [json.loads(ln) for ln in err.getvalue().splitlines()
             if ln.startswith("{")]
    assert beats, err.getvalue()
    assert all("adaptive" in b for b in beats)
    # the second point's boundary sees the first point's savings
    assert beats[-1]["adaptive"]["runs_saved"] >= 25
    (sidecar,) = glob.glob(str(tmp_path / "phase-*.json"))
    with open(sidecar) as fh:
        data = json.load(fh)
    assert data["adaptive"]["points"] == 2
    assert data["adaptive"]["runs_saved"] == 50


def test_driver_max_runs_flag_caps_the_budget(mesh):
    # --max-runs overrides -r as the adaptive cap; a noisy stream runs
    # exactly to it
    class NoisyDriver(Driver):
        def _measure(self, built, built_hi):
            n = self.__dict__.setdefault("_n", [0])
            n[0] += 1
            return 1e-3 if n[0] % 2 else 1e-2

    opts = Options(op="ring", buff_sz=8, iters=1, num_runs=50,
                   fence="block", ci_rel=0.05, min_runs=5,
                   adaptive_max_runs=12)
    rows = NoisyDriver(opts, make_mesh(), err=io.StringIO()).run()
    assert len(rows) == 12
    assert rows[-1].runs_requested == 12


def test_driver_never_exceeds_the_requested_budget(mesh):
    """-r is the user's ceiling: a budget not above --min-runs bypasses
    the controller (loudly) instead of silently raising the cap — a
    savings feature must never cost extra wall time."""
    err = io.StringIO()
    opts = Options(op="ring", buff_sz=8, iters=1, num_runs=3,
                   fence="block", ci_rel=0.05)  # min_runs default 5 > 3
    d = SeededDriver(opts, mesh, err=err)
    rows = d.run()
    assert len(rows) == 3  # exactly the -r budget, not min_runs
    assert all(r.runs_requested == 0 for r in rows)  # fixed-budget rows
    assert "bypassed" in err.getvalue() and "nothing to save" \
        in err.getvalue()


def test_driver_bypasses_controller_under_injector(mesh, tmp_path):
    """The determinism contract: with --synthetic/--faults the run
    sequence must not change when --ci-rel is set — same rows, and a
    byte-identical chaos ledger."""

    def soak(sub, **kw):
        folder = tmp_path / sub
        opts = Options(op="ring", sweep="8,32", iters=1, num_runs=20,
                       fence="block", synthetic_s=1e-3, fault_seed=7,
                       faults=[], logfolder=str(folder),
                       stats_every=10, **kw)
        err = io.StringIO()
        rows = Driver(opts, mesh, err=err).run()
        (ledger,) = glob.glob(str(folder / "chaos-*.log"))
        with open(ledger) as fh:
            return rows, fh.read(), err.getvalue()

    rows_fixed, ledger_fixed, _ = soak("fixed")
    rows_ci, ledger_ci, err_ci = soak("ci", ci_rel=0.05, min_runs=5)
    assert ledger_ci == ledger_fixed
    # row streams identical run for run (timestamps aside)
    strip = lambda rows: [(r.op, r.nbytes, r.run_id, r.time_ms,
                           r.runs_requested, r.ci_rel) for r in rows]
    assert strip(rows_ci) == strip(rows_fixed)
    assert all(r.runs_requested == 0 for r in rows_ci)  # fixed-budget rows
    assert "bypassed" in err_ci


def test_driver_bypasses_controller_under_trace_fence(mesh):
    err = io.StringIO()
    opts = Options(op="ring", buff_sz=8, iters=1, num_runs=4,
                   fence="trace", ci_rel=0.05)
    d = Driver(opts, mesh, err=err)
    assert d._adaptive_cfg is None
    assert "bypassed" in err.getvalue()


def test_daemon_notes_adaptive_as_inapplicable(mesh):
    err = io.StringIO()
    opts = Options(op="ring", buff_sz=8, iters=1, num_runs=-1,
                   ci_rel=0.05)
    d = Driver(opts, mesh, err=err)
    assert d._adaptive_cfg is None
    assert "daemon" in err.getvalue()


# --- run_point / bench path -------------------------------------------


def test_run_point_adaptive_block_fence(mesh):
    from tpu_perf.runner import run_point

    opts = Options(op="ring", buff_sz=8, iters=1, num_runs=8,
                   fence="block")
    cfg = AdaptiveConfig(ci_rel=0.9, confidence=0.90, min_runs=2,
                         max_runs=8)
    point = run_point(opts, mesh, 8, adaptive=cfg)
    assert point.adaptive is not None
    assert 2 <= point.adaptive["attempted"] <= 8
    assert point.runs_requested == 8
    rows = point.rows("job")
    assert len(rows) == len(point.times.samples)
    assert rows[-1].runs_requested == 8
    assert rows[-1].runs_taken == len(rows)


def test_bench_payload_reports_adaptive_savings(monkeypatch, capsys):
    """bench runs its instruments under the controller (budget becomes a
    cap) and the payload carries the savings."""
    import tpu_perf.timing as timing
    from tpu_perf import bench

    monkeypatch.setattr(timing, "trace_fence_available", lambda: False)

    class FakeRow:
        def __init__(self, v):
            self.busbw_gbps = v
            self.lat_us = 1.0

    def fake_run_point(opts, mesh, nbytes, phases=None, adaptive=None):
        from tpu_perf.runner import SweepPointResult
        from tpu_perf.timing import RunTimes

        assert adaptive is not None and adaptive.max_runs == opts.num_runs
        n = adaptive.min_runs  # pretend the controller stopped at the floor
        summary = {"requested": adaptive.max_runs, "attempted": n,
                   "taken": n, "dropped": 0,
                   "saved": adaptive.max_runs - n, "ci_rel": 0.01}
        return SweepPointResult(
            op=opts.op, nbytes=nbytes, iters=opts.iters, n_devices=8,
            times=RunTimes(samples=[1e-3] * n, warmup_s=0.0,
                           overhead_s=0.0),
            runs_requested=adaptive.max_runs, ci_rel=0.01,
            adaptive=summary,
        )

    import tpu_perf.runner as runner

    monkeypatch.setattr(runner, "run_point", fake_run_point)
    # conftest's 8 virtual devices select the n>=2 allreduce instrument
    bench.main()
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["adaptive"]["points"] == 1
    assert payload["adaptive"]["runs_saved"] == \
        payload["adaptive"]["runs_requested"] - \
        payload["adaptive"]["runs_attempted"]
    assert payload["adaptive"]["runs_saved"] > 0


# --- precompile auto ---------------------------------------------------


def test_precompile_tuner_from_planted_ratios():
    t = PrecompileTuner(min_points=2, max_depth=8, initial=1)
    assert t.update(10.0, 1.0) == 1      # warm-up point 1: no steering
    assert t.update(10.0, 1.0) == 1      # warm-up point 2: totals still
    #                                      carry the first-compile burst
    assert t.update(10.0, 1.0) == 8      # ratio 10 -> capped at 8
    assert t.update(3.0, 1.0) == 3       # ratio 3 -> depth 3
    assert t.update(0.5, 10.0) == 1      # compile-cheap -> minimum
    assert t.update(0.0, 1.0) == 1       # no compile signal: hold
    with pytest.raises(ValueError):
        PrecompileTuner(initial=0)


def test_pipeline_set_depth_live():
    import threading

    from tpu_perf.compilepipe import CompilePipeline

    gate = threading.Event()
    built = []

    def build(key):
        built.append(key)
        return key

    pipe = CompilePipeline(build, ["a", "b", "c", "d"], depth=1)
    try:
        assert pipe.get("a") == "a"
        assert pipe.depth == 1
        pipe.set_depth(3)
        assert pipe.depth == 3
        with pytest.raises(ValueError):
            pipe.set_depth(0)
        for k in ("b", "c", "d"):
            assert pipe.get(k) == k
    finally:
        pipe.close()
        gate.set()
    assert built == ["a", "b", "c", "d"]


def test_driver_precompile_auto_tunes_depth(mesh, tmp_path, monkeypatch):
    """--precompile auto: with planted phase totals (compile-heavy), the
    driver widens the pipeline's look-ahead after the warm-up points and
    records the landed depth in the phase sidecar."""
    opts = Options(op="ring", sweep="8,64,4096,65536", iters=1, num_runs=1,
                   fence="block", precompile=1, precompile_auto=True,
                   logfolder=str(tmp_path))
    d = SeededDriver(opts, mesh, err=io.StringIO())
    # plant a compile-dominated ratio so the tuner must widen
    monkeypatch.setattr(
        d.phases, "snapshot",
        lambda: {"compile_s": 4.0, "measure_s": 1.0, "log_s": 0.0},
    )
    d.run()
    assert d._pipe_tuner is not None
    assert d._pipe_tuner.depth == 4
    (sidecar,) = glob.glob(str(tmp_path / "phase-*.json"))
    with open(sidecar) as fh:
        data = json.load(fh)
    assert data["precompile"] == "auto"
    assert data["precompile_depth"] == 4


# --- CLI surface -------------------------------------------------------


def test_cli_adaptive_flags_parse():
    from tpu_perf.cli import _options_from, build_parser

    args = build_parser().parse_args([
        "run", "--op", "ring", "-r", "40", "--ci-rel", "0.05",
        "--ci-confidence", "0.99", "--min-runs", "3", "--max-runs", "20",
        "--precompile", "auto",
    ])
    opts = _options_from(args)
    assert opts.ci_rel == 0.05
    assert opts.ci_confidence == 0.99
    assert opts.min_runs == 3
    assert opts.adaptive_max_runs == 20
    assert opts.precompile == 1 and opts.precompile_auto is True


def test_cli_precompile_rejects_garbage():
    from tpu_perf.cli import build_parser

    with pytest.raises(SystemExit):
        build_parser().parse_args(["run", "--precompile", "fast"])


def test_cli_monitor_max_runs_still_bounds_the_daemon(mesh, tmp_path):
    from tpu_perf.cli import main

    rc = main(["monitor", "--op", "ring", "-b", "8", "-i", "1",
               "--max-runs", "3", "-l", str(tmp_path)])
    assert rc == 0
    (log,) = glob.glob(str(tmp_path / "tcp-*.log"))
    with open(log) as fh:
        assert len(fh.read().splitlines()) == 3


# --- report savings table ---------------------------------------------


def test_report_adaptive_savings_from_rows():
    from tpu_perf.report import adaptive_savings, adaptive_to_markdown

    def row(run_id, taken, ci, requested=20, op="ring", job="jobA"):
        return ResultRow(
            timestamp="t", job_id=job, backend="jax", op=op, nbytes=64,
            iters=1, run_id=run_id, n_devices=8, lat_us=100.0,
            algbw_gbps=1.0, busbw_gbps=2.0, time_ms=0.5,
            runs_requested=requested, runs_taken=taken, ci_rel=ci,
        )

    rows = [row(1, 1, 0.0), row(2, 2, 0.2), row(3, 3, 0.04),
            # a fixed-budget row must not render
            ResultRow(timestamp="t", job_id="j", backend="jax",
                      op="other", nbytes=8, iters=1, run_id=1,
                      n_devices=8, lat_us=1.0, algbw_gbps=0.0,
                      busbw_gbps=0.0, time_ms=0.1)]
    (p,) = adaptive_savings(rows)
    assert p.op == "ring"
    assert p.runs_requested == 20 and p.runs_attempted == 3
    assert p.ci_rel == 0.04
    assert p.wall_saved_s == pytest.approx(17 * 0.5e-3)
    md = adaptive_to_markdown([p])
    assert "| ring |" in md and "4.00%" in md
    assert "| 17 " in md
    assert "**total**" in md and "(85%)" in md


def test_report_adaptive_savings_keeps_jobs_apart():
    # two adaptive jobs sharing one log folder must report two verdicts
    # per point, not one blended row hiding a job's budget
    from tpu_perf.report import adaptive_savings

    def row(job, run_id):
        return ResultRow(
            timestamp="t", job_id=job, backend="jax", op="ring", nbytes=64,
            iters=1, run_id=run_id, n_devices=8, lat_us=100.0,
            algbw_gbps=1.0, busbw_gbps=2.0, time_ms=0.5,
            runs_requested=30, runs_taken=run_id, ci_rel=0.03,
        )

    rows = [row("jobA", i) for i in (1, 2, 3, 4, 5)] + \
           [row("jobB", i) for i in range(1, 21)]
    points = adaptive_savings(rows)
    assert len(points) == 2
    by_job = {p.job_id: p for p in points}
    assert by_job["jobA"].runs_attempted == 5
    assert by_job["jobB"].runs_attempted == 20


def test_report_savings_empty_for_fixed_rows():
    from tpu_perf.report import adaptive_savings

    row = ResultRow(timestamp="t", job_id="j", backend="jax", op="ring",
                    nbytes=8, iters=1, run_id=1, n_devices=8, lat_us=1.0,
                    algbw_gbps=0.0, busbw_gbps=0.0, time_ms=0.1)
    assert adaptive_savings([row]) == []


# --- exporter phase gauges (ROADMAP PR-4 follow-on) --------------------


def test_render_textfile_phase_gauges():
    from tpu_perf.health.exporter import render_textfile

    out = render_textfile([], {}, {}, phases={
        "compile_s": 1.25, "measure_s": 3.5, "log_s": 0.125,
    })
    assert 'tpu_perf_harness_phase_seconds{phase="compile"} 1.25' in out
    assert 'tpu_perf_harness_phase_seconds{phase="measure"} 3.5' in out
    assert 'tpu_perf_harness_phase_seconds{phase="log"} 0.125' in out
    # absent phases -> no family at all (pre-existing consumers see the
    # exact old rendering)
    assert "phase" not in render_textfile([], {}, {})


def test_driver_health_textfile_carries_phase_gauges(mesh, tmp_path):
    prom = tmp_path / "tpu-perf.prom"
    opts = Options(op="ring", buff_sz=8, iters=1, num_runs=3,
                   fence="block", health=True,
                   health_textfile=str(prom))
    Driver(opts, mesh, err=io.StringIO()).run()
    content = prom.read_text()
    assert 'tpu_perf_harness_phase_seconds{phase="compile"}' in content
    assert 'tpu_perf_health_lat_p50_us{' in content
