"""The device-kind → spec table (VERDICT r4 #1: bench/grid portability
beyond v5e)."""

import io

import pytest

from tpu_perf.chips import CHIPS, V5E, ChipSpec, chip_spec


def test_v5e_entry_is_the_defended_one():
    spec = chip_spec("TPU v5 lite")
    assert spec is V5E and spec.defended
    # the constants rounds 2-4 measured (BASELINE.md)
    assert spec.hbm_gbps == 819.0
    assert spec.mxu_bf16_tflops == 197.0
    assert spec.stream_floor_gbps == 600.0
    assert spec.mxu_floor_tflops == 160.0
    assert spec.allreduce_nominal_gbps == 25.0


@pytest.mark.parametrize("kind,key", [
    ("TPU v5p", "v5p"),
    ("TPU v5", "v5p"),        # runtime spelling variant
    ("tpu v5e", "v5e"),
    ("TPU v4", "v4"),
    ("TPU v6 lite", "v6e"),
    ("TPU v6e", "v6e"),
    ("TPU v3", "v3"),
])
def test_kind_aliases(kind, key):
    assert chip_spec(kind) is CHIPS[key]


def test_derived_entries_are_internally_consistent():
    for spec in CHIPS.values():
        assert isinstance(spec, ChipSpec)
        # floors/nominals must sit under the physical peaks, or the
        # degraded-window rule could never pass a healthy chip
        assert 0 < spec.stream_nominal_gbps < spec.hbm_gbps
        assert 0 < spec.stream_floor_gbps < spec.hbm_gbps
        assert 0 < spec.triad_nominal_gbps < spec.hbm_gbps
        assert 0 < spec.mxu_nominal_tflops < spec.mxu_bf16_tflops
        assert 0 < spec.mxu_floor_tflops < spec.mxu_bf16_tflops
        assert 0 < spec.allreduce_nominal_gbps < spec.ici_gbps
        assert spec.vmem_bytes > 0


def test_v5p_scales_from_its_own_peaks():
    v5p = chip_spec("TPU v5p")
    assert not v5p.defended
    assert v5p.hbm_gbps == 2765
    # ratio-derived: same measured-to-peak fractions as v5e
    assert v5p.stream_floor_gbps == pytest.approx(
        2765 * 600 / 819, abs=1.0)
    assert v5p.mxu_floor_tflops == pytest.approx(459 * 160 / 197, abs=1.0)


def test_unknown_kind_falls_back_to_v5e_with_note():
    err = io.StringIO()
    spec = chip_spec("cpu", err=err)
    assert spec is V5E
    assert "unknown device kind" in err.getvalue()


def test_default_kind_comes_from_jax_devices(eight_devices, monkeypatch):
    import jax

    fake = type("D", (), {"device_kind": "TPU v4"})()
    monkeypatch.setattr(jax, "devices", lambda: [fake])
    assert chip_spec() is CHIPS["v4"]


def test_chips_cli_table(capsys):
    from tpu_perf.cli import main as cli_main

    assert cli_main(["chips", "--kind", "TPU v5p"]) == 0
    out = capsys.readouterr().out
    assert "| v5p (detected) |" in out
    assert "| v5e |" in out and "measured" in out and "derived" in out
    # an unknown kind must NOT be dressed up as a positive match: no row
    # is marked detected and the fallback note rides stdout
    assert cli_main(["chips", "--kind", "gpu-h100"]) == 0
    out = capsys.readouterr().out
    assert "(detected)" not in out
    assert "not in the table" in out


def test_grid_spec_flag_pulls_chip_table(monkeypatch, capsys):
    # `grid --spec mxu` fills spec/floor from the chip table; explicit
    # flags override individual values
    import tpu_perf.chips as chips
    from tpu_perf.cli import main as cli_main

    v5p = chips.CHIPS["v5p"]
    monkeypatch.setattr(chips, "chip_spec", lambda *a, **k: v5p)
    seen = {}

    def fake_run_grid(mesh, ops, sizes, iters_list, **kw):
        seen.update(kw)
        return []

    import tpu_perf.grid as grid_mod

    monkeypatch.setattr(grid_mod, "run_grid", fake_run_grid)
    rc = cli_main(["grid", "--op", "mxu_gemm", "--sizes", "32K",
                   "--iters", "2", "--spec", "mxu",
                   "--floor-tflops", "123"])
    assert rc == 0
    assert seen["spec_tflops"] == v5p.mxu_bf16_tflops
    assert seen["floor_tflops"] == 123.0  # explicit flag wins
