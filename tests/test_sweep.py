import pytest

from tpu_perf.sweep import (
    DEF_BUF_SZ,
    LEGACY_BW_BUF_SZ,
    format_size,
    parse_size,
    parse_sweep,
    sweep_sizes,
)


def test_parse_size():
    assert parse_size("8") == 8
    assert parse_size("64K") == 64 * 1024
    assert parse_size("4M") == 4 * 1024 * 1024
    assert parse_size("1G") == 1024**3
    assert parse_size("4MiB") == 4 * 1024 * 1024
    assert parse_size("1g") == 1024**3
    with pytest.raises(ValueError):
        parse_size("banana")
    with pytest.raises(ValueError):
        parse_size("-8")


def test_format_size_roundtrip():
    for text in ("8", "64K", "4M", "1G"):
        assert format_size(parse_size(text)) == text
    assert format_size(DEF_BUF_SZ) == str(DEF_BUF_SZ)


def test_sweep_default_range_includes_legacy_points():
    sizes = sweep_sizes()
    assert sizes[0] == 8
    assert sizes[-1] == 1024**3
    assert DEF_BUF_SZ in sizes
    assert LEGACY_BW_BUF_SZ in sizes
    # powers of two are all present
    n = 8
    while n <= 1024**3:
        assert n in sizes
        n *= 2
    # sorted, unique
    assert sizes == sorted(set(sizes))


def test_sweep_narrow_range_excludes_legacy():
    sizes = sweep_sizes(8, 1024)
    assert DEF_BUF_SZ not in sizes
    assert sizes == [8, 16, 32, 64, 128, 256, 512, 1024]


def test_sweep_alignment():
    sizes = sweep_sizes(8, 1024**2, align=4)
    assert all(s % 4 == 0 for s in sizes)
    # the odd legacy size 456131 rounds up to a multiple of 4
    assert -(-456131 // 4) * 4 in sizes


def test_sweep_bad_range():
    with pytest.raises(ValueError):
        sweep_sizes(0, 8)
    with pytest.raises(ValueError):
        sweep_sizes(1024, 8)


def test_parse_sweep_forms():
    assert parse_sweep("4M") == [4 * 1024 * 1024]
    assert parse_sweep("8,64K,8") == [8, 64 * 1024]
    full = parse_sweep("8:1G")
    assert full == sweep_sizes(8, 1024**3)
    aligned = parse_sweep("6,10", align=4)
    assert aligned == [8, 12]
