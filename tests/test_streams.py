"""Multi-stream dispatch + contention arena (ISSUE 17): the static
stream plans, the K-lane engine's lockstep contract, per-stream span
lanes, donated-buffer canon refcounting under overlapped sweeps,
split-channel numerics parity against the single-channel spelling, and
the interference-matrix report view."""

import contextlib
import dataclasses
import io

import numpy as np
import pytest

from tpu_perf.config import Options
from tpu_perf.driver import Driver
from tpu_perf.parallel import make_mesh
from tpu_perf.report import (
    aggregate, interference_matrix, interference_to_markdown,
)
from tpu_perf.spans import NULL_TRACER, SpanTracer
from tpu_perf.streams.contend import (
    COMPUTE_LOADS, SYNTHETIC_CONTENTION, build_split_steps, run_contend,
)
from tpu_perf.streams.engine import StreamEngine
from tpu_perf.streams.plans import lane_schedules, split_slices, wave_plan


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh()


class FakeNs:
    """Deterministic perf_ns: +1 µs per call."""

    def __init__(self):
        self.t = 0

    def __call__(self):
        self.t += 1000
        return self.t


class FakeClock:
    """Deterministic seconds clock: +0.25 s per call."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 0.25
        return self.t


class RecordingTracer:
    """Minimal tracer double: logs (stream_id, kind, label) tuples."""

    def __init__(self):
        self.events = []

    @contextlib.contextmanager
    def stream_span(self, stream_id, kind, **attrs):
        self.events.append((stream_id, kind, attrs.get("label", "")))
        yield ""


# -- stream plans (pure functions of the static sweep plan) -------------


def test_wave_plan_round_robin_chunks():
    pts = ["a", "b", "c", "d", "e"]
    assert wave_plan(pts, 2) == [
        [(0, "a"), (1, "b")],
        [(0, "c"), (1, "d")],
        [(0, "e")],
    ]


def test_wave_plan_k1_is_the_serial_plan():
    pts = [10, 20, 30]
    assert wave_plan(pts, 1) == [[(0, 10)], [(0, 20)], [(0, 30)]]


def test_wave_plan_k_exceeding_plan_is_one_wave():
    assert wave_plan(["x"], 8) == [[(0, "x")]]
    assert wave_plan([], 4) == []


def test_wave_plan_rejects_bad_k():
    with pytest.raises(ValueError, match="k must be >= 1"):
        wave_plan(["a"], 0)


def test_split_slices_even_on_itemsize_grid():
    sizes = split_slices(1024, 3, itemsize=4)
    # 256 elems -> 86 + 85 + 85, scaled back to bytes
    assert sizes == [344, 340, 340]
    assert all(s % 4 == 0 for s in sizes)
    assert sum(sizes) >= 1024


def test_split_slices_never_starves_a_lane():
    # payload smaller than K lanes: every lane still gets one element
    assert split_slices(2, 4, itemsize=4) == [4, 4, 4, 4]


def test_split_slices_k1_is_the_full_payload():
    assert split_slices(1000, 1, itemsize=4) == [1000]
    # off-grid payloads round UP to a whole element
    assert split_slices(1001, 1, itemsize=4) == [1004]


def test_split_slices_rejects_bad_args():
    with pytest.raises(ValueError):
        split_slices(0, 2)
    with pytest.raises(ValueError):
        split_slices(8, 0)
    with pytest.raises(ValueError):
        split_slices(8, 2, itemsize=0)


def test_lane_schedules_cycles_in_order():
    assert lane_schedules(["s0", "s1"], 2) == ["s0", "s1"]
    assert lane_schedules(["s0", "s1"], 5) == ["s0", "s1", "s0", "s1", "s0"]


def test_lane_schedules_rejects_empty():
    with pytest.raises(ValueError, match="no schedules"):
        lane_schedules([], 2)
    with pytest.raises(ValueError, match="k must be >= 1"):
        lane_schedules(["s0"], 0)


# -- engine: lockstep, drain order, lane discipline ---------------------


def _drive(plan, tracer):
    """One simulated rank: dispatch the static plan, then drain."""
    eng = StreamEngine(4, tracer=tracer, perf_clock=FakeClock())
    for lane, label in plan:
        eng.dispatch(lane, lambda x: x, label, label=label)
    return eng.fence_all()


def test_engine_lockstep_two_ranks_identical_order():
    # Two "ranks" driven by the same static plan must issue the same
    # dispatch/fence sequence — the lockstep contract the R2 lint rule
    # proves at parse time, observed here at runtime.
    plan = [(2, "p0"), (0, "p1"), (3, "p2"), (1, "p3")]
    tracers = [RecordingTracer(), RecordingTracer()]
    walls = [_drive(plan, tr) for tr in tracers]
    assert tracers[0].events == tracers[1].events
    assert list(walls[0]) == list(walls[1])


def test_engine_fence_all_drains_in_dispatch_order():
    # lanes dispatched out of lane order: drain follows dispatch order
    # (the seq counter), never ascending lane id
    tr = RecordingTracer()
    walls = _drive([(3, "a"), (1, "b"), (2, "c")], tr)
    assert list(walls) == [3, 1, 2]
    fences = [e for e in tr.events if e[1] == "stream_fence"]
    assert [lane for lane, _, _ in fences] == [3, 1, 2]
    assert all(w > 0 for w in walls.values())


def test_engine_occupied_lane_is_an_error():
    eng = StreamEngine(2, perf_clock=FakeClock())
    eng.dispatch(0, lambda x: x, 1, label="first")
    with pytest.raises(RuntimeError, match="already has a program"):
        eng.dispatch(0, lambda x: x, 2, label="second")
    assert eng.in_flight == (0,)
    eng.fence(0)
    assert eng.in_flight == ()


def test_engine_lane_range_and_empty_fence_errors():
    eng = StreamEngine(2, perf_clock=FakeClock())
    with pytest.raises(ValueError, match="out of range"):
        eng.dispatch(2, lambda x: x, 0)
    with pytest.raises(ValueError, match="out of range"):
        eng.fence(-1)
    with pytest.raises(RuntimeError, match="nothing in flight"):
        eng.fence(0)
    with pytest.raises(ValueError, match="n_streams"):
        StreamEngine(0)
    with pytest.raises(ValueError, match="fence_mode"):
        StreamEngine(1, fence_mode="bogus")


def test_engine_wall_covers_dispatch_to_fence():
    # FakeClock ticks 0.25 s per read; dispatch reads once (t0), fence
    # reads once after the wait — one lane alone measures one full gap.
    eng = StreamEngine(1, perf_clock=FakeClock())
    eng.dispatch(0, lambda x: x + 1, 41)
    assert eng.fence(0) == pytest.approx(0.25)


# -- per-stream span lanes ----------------------------------------------


def test_stream_span_ids_ride_per_stream_lanes():
    tr = SpanTracer("job", rank=0, retain=True, perf_ns=FakeNs())
    with tr.stream_span(0, "dispatch", label="a"):
        pass
    with tr.stream_span(1, "dispatch", label="b"):
        pass
    with tr.stream_span(0, "stream_fence", label="a"):
        pass
    recs = tr.records
    assert [r["span_id"] for r in recs] == ["s0.1", "s1.1", "s0.2"]
    assert [r["attrs"]["stream"] for r in recs] == [0, 1, 0]
    assert recs[0]["attrs"]["label"] == "a"


def test_engine_emits_stream_spans_through_real_tracer():
    tr = SpanTracer("job", rank=0, retain=True, perf_ns=FakeNs())
    eng = StreamEngine(2, tracer=tr, perf_clock=FakeClock())
    eng.dispatch(1, lambda x: x, 7, label="ring/8")
    eng.dispatch(0, lambda x: x, 7, label="ring/64")
    eng.fence_all()
    kinds = [(r["attrs"]["stream"], r["kind"]) for r in tr.records]
    assert kinds == [(1, "dispatch"), (0, "dispatch"),
                     (1, "stream_fence"), (0, "stream_fence")]


def test_null_tracer_stream_span_is_inert():
    with NULL_TRACER.stream_span(3, "dispatch", label="x"):
        pass  # no-op context, no state


# -- overlapped driver: row identity + canon refcounting ----------------


def _row_key(rows):
    return sorted((r.op, r.nbytes, r.run_id) for r in rows)


def test_overlapped_rows_match_serial_set(mesh):
    base = dict(op="allreduce", sweep="8,64,512", iters=1, num_runs=2,
                warmup_runs=0)
    serial = Driver(Options(**base), mesh, err=io.StringIO()).run()
    lanes = Driver(Options(**base, streams=2), mesh,
                   err=io.StringIO()).run()
    assert _row_key(serial) == _row_key(lanes)
    assert {r.stream for r in serial} == {0}
    # 3 sweep points at K=2: wave 1 on lanes 1,2 — wave 2 on lane 1
    assert {r.stream for r in lanes} == {1, 2}


def test_overlapped_canon_refcount_drains(mesh):
    opts = Options(op="allreduce,ppermute", sweep="8,64", iters=1,
                   num_runs=2, warmup_runs=0, streams=4)
    drv = Driver(opts, mesh, err=io.StringIO())
    rows = drv.run()
    # 4 quads in flight at once, each with a donated buffer pair —
    # every pair must be retired once its lane's point completes
    assert drv._canon == {}
    assert drv._canon_refs == {}
    assert {r.stream for r in rows} == {1, 2, 3, 4}


# -- split-channel numerics parity --------------------------------------


def test_split_channel_numerics_parity(mesh):
    # K lanes pinned to the SAME schedule, each moving slice i of the
    # payload, reassembled shard-by-shard == the single-channel
    # full-payload spelling on the whole payload.
    from tpu_perf.linkmap.plan import plan_mesh_links

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    n = mesh.size
    nbytes, iters, k = 1024, 3, 3
    sched = plan_mesh_links((n,), ("x",), wrap=True)[0]
    sharding = NamedSharding(mesh, P("x"))

    def put(arr):
        return jax.device_put(jnp.asarray(arr, dtype=jnp.float32),
                              sharding)

    single = build_split_steps(mesh, nbytes, iters, 1, schedules=[sched])
    lanes = build_split_steps(mesh, nbytes, iters, k, schedules=[sched])
    assert len(single) == 1 and len(lanes) == k
    assert all(name == sched.name for _, _, _, name in lanes)

    per_dev = sum(sz // 4 for _, _, sz, _ in lanes)
    assert per_dev == single[0][2] // 4  # split moves the same elems
    full = np.arange(n * per_dev, dtype=np.float32)
    out_full = np.asarray(single[0][0](put(full)))

    # slice each device's shard of the full payload into the K lanes
    offs = np.cumsum([0] + [sz // 4 for _, _, sz, _ in lanes])
    outs = []
    for i, (step, _example, sz, _name) in enumerate(lanes):
        e = sz // 4
        xi = np.concatenate([
            full[j * per_dev + offs[i]:j * per_dev + offs[i] + e]
            for j in range(n)
        ])
        outs.append(np.asarray(step(put(xi))))

    # reassemble shard-by-shard and compare exactly
    rebuilt = np.concatenate([
        np.concatenate([
            outs[i][j * (sz // 4):(j + 1) * (sz // 4)]
            for i, (_s, _e, sz, _n2) in enumerate(lanes)
        ])
        for j in range(n)
    ])
    np.testing.assert_array_equal(rebuilt, out_full)


# -- contend runner (synthetic: no devices needed) ----------------------


def _contend_opts(**kw):
    base = dict(op="allreduce", buff_sz=32768, iters=10, num_runs=6,
                synthetic_s=0.001, fault_seed=7, load="hbm_stream")
    base.update(kw)
    return Options(**base)


def test_run_contend_synthetic_emits_idle_and_loaded_twins():
    rows = run_contend(_contend_opts(), n_devices=8)
    idle = [r for r in rows if r.load == ""]
    loaded = [r for r in rows if r.load == "hbm_stream"]
    assert len(idle) == len(loaded) == 6
    assert {r.stream for r in idle} == {0}
    assert {r.stream for r in loaded} == {1}
    assert {r.op for r in rows} == {"allreduce"}
    assert all(r.mode == "oneshot" for r in rows)


def test_run_contend_synthetic_slowdown_near_constant():
    rows = run_contend(_contend_opts(num_runs=12), n_devices=8)
    cells = interference_matrix(aggregate(rows))
    assert len(cells) == 1
    cell = cells[0]
    assert cell.load == "hbm_stream"
    assert cell.idle is not None and cell.loaded is not None
    # seeded jitter around the deterministic contention constant
    assert cell.slowdown == pytest.approx(SYNTHETIC_CONTENTION, rel=0.2)


def test_run_contend_validation_errors():
    with pytest.raises(ValueError, match="load selection"):
        run_contend(_contend_opts(load=""), n_devices=8)
    with pytest.raises(ValueError, match="single victim"):
        run_contend(_contend_opts(op="allreduce,psum"), n_devices=8)
    with pytest.raises(ValueError, match="per-run fence"):
        run_contend(_contend_opts(fence="slope"), n_devices=8)
    with pytest.raises(ValueError, match="ppermute"):
        run_contend(_contend_opts(load="split:2"), n_devices=8)
    assert "mxu_gemm" in COMPUTE_LOADS and "hbm_stream" in COMPUTE_LOADS


# -- interference matrix report view ------------------------------------


def test_interference_matrix_drops_load_free_keys():
    rows = run_contend(_contend_opts(), n_devices=8)
    quiet = [r for r in rows if r.load == ""]
    assert interference_matrix(aggregate(quiet)) == []


def test_interference_matrix_keeps_one_sided_loaded_rows():
    rows = run_contend(_contend_opts(), n_devices=8)
    loaded = [r for r in rows if r.load != ""]
    cells = interference_matrix(aggregate(loaded))
    assert len(cells) == 1
    assert cells[0].idle is None
    assert cells[0].slowdown is None


def test_interference_matrix_excludes_chaos_rows():
    rows = [dataclasses.replace(r, mode="chaos")
            for r in run_contend(_contend_opts(), n_devices=8)]
    assert interference_matrix(aggregate(rows)) == []


def test_interference_markdown_renders():
    rows = run_contend(_contend_opts(), n_devices=8)
    md = interference_to_markdown(interference_matrix(aggregate(rows)))
    assert "| load |" in md
    assert "slowdown" in md
    assert "hbm_stream" in md
