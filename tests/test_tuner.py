"""Crossover auto-tuner (tpu_perf.tuner): the measure→select loop.

Coverage contract:

* the selection artifact round-trips (build → JSON → load) byte-stably,
  refuses foreign schema versions, and carries margins/samples/mesh
  fingerprint per entry;
* `LoadedSelection.resolve` walks the documented fallback ladder —
  exact winner, nearest size bucket by log-distance (ties to the
  smaller), loud native on unmeasured groups, low margins, stale
  artifacts, and foreign fingerprints — and dedups its notes;
* two simulated ranks holding the same artifact bytes resolve an entire
  sweep grid identically (the R2-lockstep property, pinned end to end
  through `algos_for_options`);
* a seeded arena sweep → `tune` → `--algo auto` run produces rows whose
  algo column matches the artifact's winners exactly;
* `tune --check` exits 10 when a measured crossover moved against the
  published table, 0 on a noise-level reshuffle below --margin;
* the artifact flattens into the eighth rotating family (tune-*.log)
  and rides the standard ingest pass;
* a chaos soak under `--algo auto` writes a byte-identical ledger to
  the native soak's (the provably-inert plumbing precedent).
"""

import glob
import io
import json
import os

import pytest

from tpu_perf.config import Options
from tpu_perf.report import aggregate
from tpu_perf.schema import ResultRow, timestamp_now
from tpu_perf.tuner import (
    TUNER_SCHEMA_VERSION,
    LoadedSelection,
    SelectionArtifact,
    SelectionEntry,
    TuneRecord,
    build_selection,
    check_drift,
    load_artifact,
    read_artifact,
    write_artifact,
)


def _row(**kw):
    base = dict(
        timestamp=timestamp_now(), job_id="j", backend="jax",
        op="allreduce", nbytes=1024, iters=4, run_id=1, n_devices=8,
        lat_us=10.0, algbw_gbps=1.0, busbw_gbps=1.75, time_ms=0.04,
    )
    base.update(kw)
    return ResultRow(**base)


def _mk_rows(op, algo, lat_us, nbytes=1024, mode="oneshot", n=3):
    return [
        _row(op=op, algo="" if algo == "native" else algo,
             nbytes=nbytes, lat_us=lat_us, busbw_gbps=1000.0 / lat_us,
             mode=mode, run_id=i + 1)
        for i in range(n)
    ]


def _arena_rows(winners):
    """Synthetic arena race: per (nbytes -> (native_lat, ring_lat)),
    three runs each of native, ring, and a slower bruck."""
    rows = []
    for nbytes, (native_lat, ring_lat) in winners.items():
        rows += _mk_rows("allreduce", "native", native_lat, nbytes=nbytes)
        rows += _mk_rows("allreduce", "ring", ring_lat, nbytes=nbytes)
        rows += _mk_rows("allreduce", "bruck",
                         max(native_lat, ring_lat) * 2, nbytes=nbytes)
    return rows


def _build(winners, **kw):
    kw.setdefault("generated", "2026-01-01T00:00:00Z")
    kw.setdefault("generated_unix", 1000.0)
    return build_selection(aggregate(_arena_rows(winners)), **kw)


# ----------------------------------------------------------- artifact


def test_build_selection_entries_and_margins():
    art = _build({64: (5.0, 9.0), 1 << 20: (100.0, 50.0)})
    assert art.version == TUNER_SCHEMA_VERSION
    assert [(e.nbytes, e.winner) for e in art.entries] == \
        [(64, "native"), (1 << 20, "ring")]
    small, large = art.entries
    # margin = runner-up p50 / winner p50
    assert small.margin == pytest.approx(9.0 / 5.0)
    assert large.margin == pytest.approx(100.0 / 50.0)
    assert large.native_vs_best == pytest.approx(2.0)
    assert large.runner_up == "native"
    assert small.samples == 3 and small.n_devices == 8
    assert set(small.algos) == {"native", "ring", "bruck"}
    assert art.fingerprint["n_devices"] == 8
    assert art.fingerprint["tuner_schema"] == TUNER_SCHEMA_VERSION


def test_artifact_json_roundtrip_and_atomic_write(tmp_path):
    art = _build({64: (5.0, 9.0)}, device_kind="cpu", source="unit")
    path = str(tmp_path / "sel.json")
    write_artifact(art, path)
    assert not os.path.exists(path + ".tmp")  # renamed, not left torn
    back = read_artifact(path)
    assert back == art
    # two writes of the same verdicts are byte-identical
    write_artifact(back, str(tmp_path / "sel2.json"))
    assert open(path).read() == open(str(tmp_path / "sel2.json")).read()


def test_artifact_version_refused():
    art = _build({64: (5.0, 9.0)})
    data = json.loads(art.to_json())
    data["version"] = TUNER_SCHEMA_VERSION + 1
    with pytest.raises(ValueError, match="version"):
        SelectionArtifact.from_json(json.dumps(data))
    with pytest.raises(ValueError, match="version"):
        SelectionArtifact.from_json("[]")


def test_load_artifact_missing_or_garbage_is_loud(tmp_path):
    with pytest.raises(ValueError, match="does not exist"):
        load_artifact(str(tmp_path / "nope.json"))
    bad = tmp_path / "bad.json"
    bad.write_text("not json {")
    with pytest.raises(ValueError, match="not a JSON"):
        load_artifact(str(bad))


def test_one_sided_slot_reads_low_confidence():
    # a slot that raced only one algorithm has margin 0.0 — below any
    # valid --tune-margin, so resolve falls back to native
    rows = _mk_rows("all_gather", "ring", 5.0, nbytes=256)
    art = build_selection(aggregate(rows), generated="g",
                          generated_unix=1.0)
    (e,) = art.entries
    assert e.winner == "ring" and e.margin == 0.0 and e.runner_up == ""
    sel = LoadedSelection(art)
    assert sel.resolve("all_gather", 256, "float32",
                       margin_min=1.0) == "native"


# ------------------------------------------------------ resolve ladder


def test_resolve_exact_and_nearest_bucket():
    art = _build({1 << 10: (5.0, 9.0), 1 << 20: (100.0, 50.0)})
    sel = LoadedSelection(art)
    kw = dict(margin_min=1.0, n_devices=8)
    assert sel.resolve("allreduce", 1 << 10, "float32", **kw) == "native"
    assert sel.resolve("allreduce", 1 << 20, "float32", **kw) == "ring"
    # log-distance interpolation: 8K is 3 octaves from 1K, 7 from 1M
    assert sel.resolve("allreduce", 8 << 10, "float32", **kw) == "native"
    # 256K is 2 octaves from 1M, 8 from 1K
    assert sel.resolve("allreduce", 256 << 10, "float32", **kw) == "ring"
    # exact midpoint (32K: 5 octaves both ways) ties to the smaller
    assert sel.resolve("allreduce", 32 << 10, "float32", **kw) == "native"


def test_resolve_unmeasured_group_falls_back_loudly():
    art = _build({1 << 10: (9.0, 5.0)})
    sel = LoadedSelection(art)
    err = io.StringIO()
    assert sel.resolve("all_gather", 1 << 10, "float32",
                       margin_min=1.0, err=err) == "native"
    assert sel.resolve("allreduce", 1 << 10, "bfloat16",
                       margin_min=1.0, err=err) == "native"
    assert sel.resolve("allreduce", 1 << 10, "float32", skew_us=500,
                       margin_min=1.0, err=err) == "native"
    text = err.getvalue()
    assert "no measured entry" in text and "native" in text
    # one note per distinct cause, not one per repeat
    before = err.getvalue()
    sel.resolve("all_gather", 1 << 10, "float32", margin_min=1.0, err=err)
    assert err.getvalue() == before


def test_resolve_low_margin_falls_back_loudly():
    # ring wins 1K by only 1.01x: below the 1.02 default confidence bar
    art = _build({1 << 10: (5.05, 5.0)})
    sel = LoadedSelection(art)
    err = io.StringIO()
    assert sel.resolve("allreduce", 1 << 10, "float32",
                       margin_min=1.02, err=err) == "native"
    assert "margin" in err.getvalue()
    # a looser bar accepts the same entry
    assert sel.resolve("allreduce", 1 << 10, "float32",
                       margin_min=1.0) == "ring"


def test_stale_artifact_falls_back_entirely():
    art = _build({1 << 10: (9.0, 5.0)}, generated_unix=1000.0)
    err = io.StringIO()
    sel = LoadedSelection(art, max_age_sec=60.0, now=2000.0, err=err)
    assert sel.stale
    assert "stale" in err.getvalue()
    assert sel.resolve("allreduce", 1 << 10, "float32",
                       margin_min=1.0) == "native"
    # age inside the horizon: usable; max_age 0 disables the clock
    assert not LoadedSelection(art, max_age_sec=60.0, now=1030.0).stale
    assert not LoadedSelection(art, max_age_sec=0.0, now=None).stale


def test_foreign_fingerprint_falls_back_entirely():
    art = _build({1 << 10: (9.0, 5.0)}, device_kind="TPU v4")
    err = io.StringIO()
    sel = LoadedSelection(art, device_kind="TPU v5e", err=err)
    assert sel.foreign and "foreign" in err.getvalue()
    assert sel.resolve("allreduce", 1 << 10, "float32",
                       margin_min=1.0) == "native"
    # same kind: usable; either side blank: no judgement possible
    assert not LoadedSelection(art, device_kind="TPU v4").foreign
    assert not LoadedSelection(art, device_kind="").foreign
    # device-count mismatch is foreign too (the rows ran n_devices=8)
    assert LoadedSelection(art, n_devices=4).foreign
    assert not LoadedSelection(art, n_devices=8).foreign


def test_resolve_is_pure_and_lockstep_across_ranks(tmp_path):
    """Two simulated ranks load the same artifact bytes and resolve an
    entire sweep grid: the plans must be identical element-for-element
    (any divergence = cross-rank deadlock at the first collective)."""
    path = str(tmp_path / "sel.json")
    write_artifact(_build({1 << 10: (5.0, 9.0), 1 << 20: (100.0, 50.0)},
                          device_kind="cpu"), path)
    grid = [("allreduce", 1 << s, "float32")
            for s in range(3, 24)] + [("all_gather", 4096, "float32")]
    plans = []
    for rank in range(2):
        sel = load_artifact(path, n_devices=8, device_kind="cpu",
                            err=io.StringIO())
        plans.append([sel.resolve(op, nb, dt, margin_min=1.02,
                                  n_devices=8, err=io.StringIO())
                      for op, nb, dt in grid])
    assert plans[0] == plans[1]
    assert "ring" in plans[0] and "native" in plans[0]


# ------------------------------------------------- algos_for_options


def _sel_of(art, **kw):
    return LoadedSelection(art, **kw)


def test_auto_algos_requires_selection_and_point():
    from tpu_perf.runner import algos_for_options

    opts = Options(op="allreduce", algo="auto", algo_artifact="x.json")
    with pytest.raises(ValueError, match="selection"):
        algos_for_options(opts, "allreduce", 8, nbytes=1024)
    with pytest.raises(ValueError, match="per sweep point"):
        algos_for_options(opts, "allreduce", 8,
                          selection=_sel_of(_build({1024: (9.0, 5.0)})))


def test_auto_algos_resolves_winner_per_point():
    from tpu_perf.runner import algos_for_options

    opts = Options(op="allreduce", algo="auto", algo_artifact="x.json",
                   tune_margin=1.0)
    sel = _sel_of(_build({1 << 10: (9.0, 5.0), 1 << 20: (50.0, 100.0)}))
    assert algos_for_options(opts, "allreduce", 8, nbytes=1 << 10,
                             selection=sel) == ["ring"]
    assert algos_for_options(opts, "allreduce", 8, nbytes=1 << 20,
                             selection=sel) == ["native"]


def test_auto_algos_unbuildable_winner_falls_back_loudly():
    from tpu_perf.runner import algos_for_options

    # the artifact crowns a hierarchical winner, but this job's mesh is
    # single-axis: auto must not crash the build — loud native instead
    entry = SelectionEntry(
        op="allreduce", nbytes=1024, dtype="float32", skew_us=0,
        imbalance=1, load="", winner="hier-ring", winner_p50_us=5.0,
        runner_up="native", runner_up_p50_us=9.0, margin=1.8,
        native_p50_us=9.0, native_vs_best=1.8, n_devices=8,
        mesh="2x(4)", samples=3, algos=("hier-ring", "native"),
    )
    art = SelectionArtifact(
        version=TUNER_SCHEMA_VERSION, generated="g", generated_unix=1.0,
        fingerprint={"tuner_schema": TUNER_SCHEMA_VERSION,
                     "device_kind": "", "chip": "", "n_devices": 8},
        entries=(entry,))
    err = io.StringIO()
    opts = Options(op="allreduce", algo="auto", algo_artifact="x.json",
                   tune_margin=1.0)
    out = algos_for_options(opts, "allreduce", 8, err=err,
                            mesh_axes=("x",), nbytes=1024,
                            selection=_sel_of(art))
    assert out == ["native"]
    assert "hier-ring" in err.getvalue()


# --------------------------------------------------------- end to end


def _mesh(shape=(), axes=()):
    from tpu_perf.parallel import make_mesh

    return make_mesh(shape, axes)


def _read_algo_by_size(folder):
    from tpu_perf.report import collect_paths, read_rows

    out = {}
    for r in read_rows(collect_paths(str(folder))):
        out.setdefault(r.nbytes, set()).add(r.algo or "native")
    return out


def test_sweep_tune_auto_roundtrip(eight_devices, tmp_path):
    """The whole loop on real (CPU) collectives: arena sweep → tune →
    auto run whose rows carry exactly the artifact's winners."""
    from tpu_perf.cli import main
    from tpu_perf.driver import Driver

    arena_dir = tmp_path / "arena"
    opts = Options(op="allreduce", algo="all", sweep="256,4096", iters=2,
                   num_runs=3, logfolder=str(arena_dir), stats_every=100)
    Driver(opts, _mesh(), err=io.StringIO()).run()

    art = str(tmp_path / "selection.json")
    assert main(["tune", "-d", str(arena_dir), "-o", art]) == 0
    loaded = read_artifact(art)
    winners = {e.nbytes: e.winner for e in loaded.entries}
    assert set(winners) == {256, 4096}

    auto_dir = tmp_path / "auto"
    opts = Options(op="allreduce", algo="auto", algo_artifact=art,
                   tune_margin=1.0, sweep="256,4096", iters=2,
                   num_runs=2, logfolder=str(auto_dir), stats_every=100)
    Driver(opts, _mesh(), err=io.StringIO()).run()
    by_size = _read_algo_by_size(auto_dir)
    assert by_size == {nb: {w} for nb, w in winners.items()}


def test_chaos_ledger_identical_under_auto(eight_devices, tmp_path):
    # auto plumbing is provably inert for the chaos plane: the same
    # seeded synthetic soak writes byte-identical ledgers whether the
    # plan came from --algo native or from an artifact lookup that
    # resolved (to native) at plan time
    from tpu_perf.driver import Driver
    from tpu_perf.faults import FaultSpec

    art = str(tmp_path / "sel.json")
    write_artifact(_build({1 << 10: (9.0, 5.0)}), art)
    ledgers = []
    for sub, algo, artifact in (("a", "native", None), ("b", "auto", art)):
        folder = tmp_path / sub
        opts = Options(op="ring", sweep="8,32", iters=1, num_runs=-1,
                       algo=algo, algo_artifact=artifact,
                       synthetic_s=0.001, fault_seed=7,
                       faults=[FaultSpec(kind="spike", op="ring",
                                         nbytes=32, start=3, end=5,
                                         magnitude=10.0)],
                       logfolder=str(folder), stats_every=5)
        Driver(opts, _mesh(), err=io.StringIO(), max_runs=20).run()
        text = b"".join(
            open(p, "rb").read() for p in
            sorted(glob.glob(str(folder / "chaos-*.log"))))
        ledgers.append(text)
    assert ledgers[0] == ledgers[1] and ledgers[0]


# ---------------------------------------------------------- drift gate


def test_check_drift_flags_flips_above_margin():
    published = _build({1 << 10: (5.0, 9.0), 1 << 20: (100.0, 50.0)})
    # fresh rows: the 1K winner flipped to ring with a 1.8x margin; the
    # 1M verdict held
    fresh = _build({1 << 10: (9.0, 5.0), 1 << 20: (100.0, 50.0)})
    (f,) = check_drift(published, fresh, margin_min=1.02)
    assert (f.op, f.nbytes) == ("allreduce", 1 << 10)
    assert f.published == "native" and f.fresh_winner == "ring"
    assert "lost to" in f.describe()
    # the same flip under a bar above its margin is a noise reshuffle
    assert check_drift(published, fresh, margin_min=2.0) == []
    # identical verdicts never drift
    assert check_drift(published, published, margin_min=1.0) == []


def test_cli_tune_check_exit_codes(tmp_path, capsys):
    from tpu_perf.cli import main
    from tpu_perf.schema import RESULT_HEADER

    def write_rows(folder, rows):
        folder.mkdir(exist_ok=True)
        with open(folder / "tpu-j-0.log", "w") as fh:
            fh.write(RESULT_HEADER + "\n")
            for r in rows:
                fh.write(r.to_csv() + "\n")

    good = tmp_path / "good"
    write_rows(good, _arena_rows({1 << 10: (5.0, 9.0)}))
    art = str(tmp_path / "sel.json")
    assert main(["tune", "-d", str(good), "-o", art]) == 0
    capsys.readouterr()
    # same rows re-graded: no drift
    assert main(["tune", "-d", str(good), "--check", art]) == 0
    # planted regression: the native kernel got 3x slower, flipping the
    # 1K crossover to ring — the gate must fail with the tuner exit code
    bad = tmp_path / "bad"
    write_rows(bad, _arena_rows({1 << 10: (15.0, 9.0)}))
    capsys.readouterr()
    assert main(["tune", "-d", str(bad), "--check", art]) == 10
    # a nonsense published path is config error, not drift
    assert main(["tune", "-d", str(good),
                 "--check", str(tmp_path / "none.json")]) == 2


# ------------------------------------------------------- eighth family


def test_tune_records_and_ingest_roundtrip(tmp_path, capsys):
    from tpu_perf.cli import main
    from tpu_perf.ingest.pipeline import LocalDirBackend, run_all_ingest_passes
    from tpu_perf.schema import RESULT_HEADER

    rows_dir = tmp_path / "rows"
    rows_dir.mkdir()
    with open(rows_dir / "tpu-j-0.log", "w") as fh:
        fh.write(RESULT_HEADER + "\n")
        for r in _arena_rows({1 << 10: (9.0, 5.0)}):
            fh.write(r.to_csv() + "\n")
    logdir = tmp_path / "logs"
    art = str(tmp_path / "sel.json")
    assert main(["tune", "-d", str(rows_dir), "-o", art,
                 "-l", str(logdir)]) == 0
    capsys.readouterr()
    (path,) = glob.glob(str(logdir / "tune-*.log"))
    assert not path.endswith(".open")  # lazy close renamed it
    recs = [TuneRecord.from_json(line).data
            for line in open(path) if line.strip()]
    kinds = [r["record"] for r in recs]
    assert kinds.count("tune_fingerprint") == 1
    assert kinds.count("tune_entry") == len(read_artifact(art).entries)
    entry = next(r for r in recs if r["record"] == "tune_entry")
    assert entry["winner"] == "ring" and entry["nbytes"] == 1 << 10
    fp = next(r for r in recs if r["record"] == "tune_fingerprint")
    assert fp["version"] == TUNER_SCHEMA_VERSION and "fp_n_devices" in fp
    # the eighth family rides the same ingest pass into its own sink
    sink = str(tmp_path / "sink")
    n = run_all_ingest_passes(str(logdir), backend=LocalDirBackend(sink))
    assert n == 1
    assert glob.glob(os.path.join(sink, "tune-*.log"))
    assert not glob.glob(str(logdir / "tune-*.log"))


# ------------------------------------------------------- fleet rollup


def _host_roll(host, rows):
    from tpu_perf.fleet.rollup import HostRollup

    roll = HostRollup(host, f"/x/{host}")
    for r in rows:
        roll.fold_row(r)
    return roll


def test_host_winner_table_derives_from_decorated_points():
    from tpu_perf.fleet.rollup import host_winner_table

    roll = _host_roll("h0", _arena_rows({1 << 10: (9.0, 5.0)})
                      + _mk_rows("allreduce", "ring", 1.0, nbytes=64,
                                 mode="chaos"))
    table = host_winner_table(roll)
    # the chaos-mode point never crowns a winner (64B dropped)
    (key,) = table
    assert key == ("allreduce", 1 << 10, "float32", 0, 1, "")
    row = table[key]
    assert row["winner"] == "ring"
    assert row["margin"] == pytest.approx(9.0 / 5.0)
    assert row["native_p50_us"] == pytest.approx(9.0)
    assert set(row["algos"]) == {"native", "ring", "bruck"}


def test_fleet_winners_majority_and_disagreement():
    from tpu_perf.fleet.rollup import fleet_winners

    hosts = {
        "host-a": _host_roll("host-a", _arena_rows({1024: (9.0, 5.0)})),
        "host-b": _host_roll("host-b", _arena_rows({1024: (9.0, 5.0)})),
        # host-c's fabric degrades ring: its local winner is native
        "host-c": _host_roll("host-c", _arena_rows({1024: (9.0, 50.0)})),
    }
    majority, disagreements = fleet_winners(hosts)
    (m,) = majority
    assert m["winner"] == "ring" and m["votes"] == 2 and m["hosts"] == 3
    (d,) = disagreements
    assert d.host == "host-c"
    assert d.local_winner == "native" and d.fleet_winner == "ring"
    assert d.to_record().data["record"] == "tune_disagreement"
    assert "host-c" in d.describe()


def test_merge_fleet_selection_is_auto_food(tmp_path):
    from tpu_perf.fleet.rollup import merge_fleet_selection

    hosts = {
        "host-a": _host_roll("host-a", _arena_rows({1024: (9.0, 5.0)})),
        "host-b": _host_roll("host-b", _arena_rows({1024: (9.0, 5.0)})),
    }
    merged = merge_fleet_selection(hosts, generated="g",
                                   generated_unix=1.0, source="fleet:/x")
    (e,) = merged.entries
    assert e.winner == "ring" and e.samples == 6  # winner runs x 2 voters
    assert merged.fingerprint["hosts"] == 2
    # the merged artifact is loadable by the same --algo auto path
    path = str(tmp_path / "fleet-sel.json")
    write_artifact(merged, path)
    sel = load_artifact(path, n_devices=8)
    assert sel.resolve("allreduce", 1024, "float32",
                       margin_min=1.02, n_devices=8) == "ring"


def test_fleet_report_surfaces_disagreements(tmp_path, capsys):
    """fleet report names disagreeing hosts in markdown + JSON, writes
    the merged artifact via --tune-out, and records tune_disagreement
    rows in the fleet family."""
    from tpu_perf.cli import main
    from tpu_perf.fleet import read_fleet_records
    from tpu_perf.schema import RESULT_HEADER

    root = tmp_path / "fleet"
    for host, winners in (("host-a", {1024: (9.0, 5.0)}),
                          ("host-b", {1024: (9.0, 5.0)}),
                          ("host-c", {1024: (9.0, 50.0)})):
        folder = root / host
        folder.mkdir(parents=True)
        with open(folder / "tpu-j-0.log", "w") as fh:
            fh.write(RESULT_HEADER + "\n")
            for r in _arena_rows(winners):
                fh.write(r.to_csv() + "\n")
    art = str(tmp_path / "fleet-sel.json")
    logdir = str(tmp_path / "rollup")
    rc = main(["fleet", "report", str(root), "--stale-after", "1e18",
               "--tune-out", art, "-l", logdir])
    out = capsys.readouterr().out
    # host-c's degraded ring curve trips the cross-host grader too
    # (exit 9): the disagreement and the sick verdict tell one story
    assert rc == 9
    assert "Crossover winners" in out and "Crossover disagreements" in out
    assert "host-c" in out.split("Crossover disagreements")[1]
    merged = read_artifact(art)
    assert [(e.nbytes, e.winner) for e in merged.entries] == \
        [(1024, "ring")]
    (path,) = glob.glob(os.path.join(logdir, "fleet-*.log"))
    recs = read_fleet_records([path])
    (td,) = [r for r in recs if r["record"] == "tune_disagreement"]
    assert td["host"] == "host-c" and td["fleet_winner"] == "ring"
