"""Unit behavior of the fault-injection subsystem (tpu_perf.faults):
schedule parsing, per-kind perturbation semantics, determinism, the
hook-failure machinery, and payload corruption.  End-to-end chaos soaks
live in test_chaos.py; conformance judging in its own section there."""

import json

import numpy as np
import pytest

from tpu_perf.faults import (
    FaultInjector,
    FaultSpec,
    InjectedHookFailure,
    load_spec,
    parse_fault_arg,
    parse_spec,
)


class LedgerSpy:
    """Collects ChaosRecord rows like the rotating chaos log would."""

    def __init__(self):
        self.rows = []

    def write_row(self, row):
        self.rows.append(json.loads(row.to_csv()))

    def maybe_rotate(self):
        pass

    def close(self):
        pass


# --- schedule format ----------------------------------------------------


def test_spec_defaults_and_matching():
    f = FaultSpec(kind="delay")
    assert (f.op, f.nbytes, f.start, f.end) == ("*", 0, 1, None)
    assert f.magnitude == 1.0  # per-kind default
    assert f.critical
    assert f.matches("ring", 32, 1) and f.matches("x", 99, 10**9)
    g = FaultSpec(kind="spike", op="ring", nbytes=32, start=10, end=20)
    assert not g.matches("ring", 32, 9)
    assert g.matches("ring", 32, 10) and g.matches("ring", 32, 20)
    assert not g.matches("ring", 32, 21)
    assert not g.matches("ring", 8, 15) and not g.matches("halo", 32, 15)


def test_spec_validation():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec(kind="meltdown")
    with pytest.raises(ValueError, match="start"):
        FaultSpec(kind="delay", start=0)
    with pytest.raises(ValueError, match="empty"):
        FaultSpec(kind="delay", start=10, end=9)
    with pytest.raises(ValueError, match="positive magnitude"):
        FaultSpec(kind="delay", magnitude=0.0)
    with pytest.raises(ValueError, match="jitter magnitude"):
        FaultSpec(kind="jitter", magnitude=1.5)
    # corrupt runs a selftest per named op; a wildcard is unbounded
    with pytest.raises(ValueError, match="concrete op"):
        FaultSpec(kind="corrupt")


def test_parse_spec_shapes_and_unknown_keys():
    faults = parse_spec([{"kind": "delay", "op": "ring"}])
    assert faults[0].op == "ring"
    faults = parse_spec({"faults": [{"kind": "spike", "nbytes": "64K"}]})
    assert faults[0].nbytes == 65536  # size suffixes accepted
    with pytest.raises(ValueError, match="unknown key"):
        parse_spec([{"kind": "delay", "magntiude": 2.0}])  # the typo trap
    with pytest.raises(ValueError, match="'faults' list"):
        parse_spec({"fault": []})
    with pytest.raises(ValueError, match="must be a list"):
        parse_spec("delay")


def test_load_spec_file(tmp_path):
    p = tmp_path / "spec.json"
    p.write_text('{"faults": [{"kind": "flatline", "start": 5, "end": 9}]}')
    (f,) = load_spec(str(p))
    assert (f.kind, f.start, f.end) == ("flatline", 5, 9)
    p.write_text("{nope")
    with pytest.raises(ValueError, match="bad fault spec"):
        load_spec(str(p))


def test_parse_fault_arg_forms():
    f = parse_fault_arg("delay:ring:32:100-400:2.0")
    assert (f.kind, f.op, f.nbytes, f.start, f.end, f.magnitude) == (
        "delay", "ring", 32, 100, 400, 2.0)
    f = parse_fault_arg("drop_run")
    assert (f.kind, f.op, f.start, f.end) == ("drop_run", "*", 1, None)
    f = parse_fault_arg("hook_fail::0:110-115")  # empty op field = wildcard
    assert (f.kind, f.op, f.start, f.end) == ("hook_fail", "*", 110, 115)
    assert parse_fault_arg("spike:ring:64K:7").end == 7  # single-run window
    assert parse_fault_arg("spike:ring:64K:7-").end is None  # open end
    # linkmap probe ops carry a colon of their own; the parser re-joins
    # that split so localization targets are spellable inline
    f = parse_fault_arg("spike:link:(1,2)>(1,3):0:1-:30")
    assert (f.op, f.nbytes, f.start, f.end, f.magnitude) == (
        "link:(1,2)>(1,3)", 0, 1, None, 30.0)
    assert parse_fault_arg("delay:link:(0)>(1)").op == "link:(0)>(1)"
    with pytest.raises(ValueError):
        parse_fault_arg("delay:ring:32:1-2:3:extra")
    with pytest.raises(ValueError):
        parse_fault_arg("")


# --- per-kind injection semantics --------------------------------------


def _injector(faults, **kw):
    kw.setdefault("ledger", LedgerSpy())
    kw.setdefault("stats_every", 10)
    return FaultInjector(faults, **kw)


def test_delay_scales_matching_runs_only():
    inj = _injector([FaultSpec(kind="delay", op="ring", nbytes=32,
                               start=3, end=4, magnitude=1.0)])
    assert inj.apply("ring", 32, 1, 1.0) == 1.0
    assert inj.apply("ring", 32, 3, 1.0) == 2.0
    assert inj.apply("ring", 8, 4, 1.0) == 1.0   # wrong size
    assert inj.apply("halo", 32, 4, 1.0) == 1.0  # wrong op
    assert inj.apply("ring", 32, 5, 1.0) == 1.0  # window over
    kinds = [r["kind"] for r in inj.ledger.rows if r["record"] == "fault"]
    assert kinds == ["delay"]


def test_spike_fires_once_per_window():
    inj = _injector([FaultSpec(kind="spike", start=2, end=9, magnitude=10.0)])
    assert inj.apply("ring", 32, 2, 1.0) == 10.0
    assert inj.apply("ring", 32, 3, 1.0) == 1.0  # one-shot
    recs = [r for r in inj.ledger.rows if r["record"] == "fault"]
    assert len(recs) == 1 and recs[0]["run_id"] == 2


def test_flatline_pins_to_first_window_sample():
    inj = _injector([FaultSpec(kind="flatline", start=2, end=9)])
    assert inj.apply("ring", 32, 2, 1.25) == 1.25
    assert inj.apply("ring", 32, 3, 1.5) == 1.25
    assert inj.apply("ring", 32, 9, 0.5) == 1.25
    assert inj.apply("ring", 32, 10, 0.5) == 0.5  # window over


def test_drop_run_returns_none_and_short_circuits():
    inj = _injector([
        FaultSpec(kind="drop_run", start=2, end=2),
        FaultSpec(kind="delay", magnitude=1.0),
    ])
    assert inj.apply("ring", 32, 1, 1.0) == 2.0   # delay only
    assert inj.apply("ring", 32, 2, 1.0) is None  # dropped
    # a naturally dropped run stays dropped and is never perturbed
    assert inj.apply("ring", 32, 3, None) is None


def test_jitter_is_seeded_and_bounded():
    spec = [FaultSpec(kind="jitter", magnitude=0.5)]
    a = _injector(spec, seed=7)
    b = _injector(spec, seed=7)
    c = _injector(spec, seed=8)
    xs_a = [a.apply("ring", 32, i, 1.0) for i in range(1, 50)]
    xs_b = [b.apply("ring", 32, i, 1.0) for i in range(1, 50)]
    xs_c = [c.apply("ring", 32, i, 1.0) for i in range(1, 50)]
    assert xs_a == xs_b          # same seed => same stream
    assert xs_a != xs_c          # different seed => different stream
    assert all(0.5 <= x <= 1.5 for x in xs_a)
    assert len(set(xs_a)) > 40   # it actually jitters


def test_ledger_is_deterministic_for_seed_and_spec():
    spec = [
        FaultSpec(kind="delay", op="ring", nbytes=32, start=3, end=6),
        FaultSpec(kind="jitter", op="ring", start=1, end=10, magnitude=0.2),
        FaultSpec(kind="spike", start=5, end=9, magnitude=10.0),
    ]
    runs = [("ring", 32), ("ring", 8)] * 6
    ledgers = []
    for _ in range(2):
        inj = _injector(spec, seed=42)
        inj.write_meta()
        for i, (op, nb) in enumerate(runs, start=1):
            inj.apply(op, nb, i, 1.0)
        ledgers.append(inj.ledger.rows)
    assert ledgers[0] == ledgers[1]
    assert ledgers[0][0]["record"] == "meta"
    assert ledgers[0][0]["seed"] == 42
    # no wall-clock field anywhere: run_id is the ledger's only clock
    assert not any("timestamp" in r for r in ledgers[0])


def test_rank_filter_matches_one_host_only():
    # multi-host fault placement (ROADMAP): a rank-filtered spec fires
    # only on the named process — the "which host is sick" injection
    spec = [FaultSpec(kind="delay", rank=1, magnitude=1.0)]
    r0 = _injector(spec, rank=0)
    r1 = _injector(spec, rank=1)
    assert r0.apply("ring", 32, 1, 1.0) == 1.0   # wrong rank: untouched
    assert r1.apply("ring", 32, 1, 1.0) == 2.0
    # the linkmap prober overrides the rank per probe (the link's owner)
    assert r0.apply("ring", 32, 2, 1.0, rank=1) == 2.0
    assert r1.apply("ring", 32, 2, 1.0, rank=0) == 1.0
    with pytest.raises(ValueError, match="rank filter"):
        FaultSpec(kind="delay", rank=-1)


def test_rank_filter_gates_hook_fail_and_corrupt():
    spec = [FaultSpec(kind="hook_fail", rank=0, start=1, end=9),
            FaultSpec(kind="corrupt", op="ring", rank=2)]
    wrong = _injector(spec, rank=1)
    rank0 = _injector(spec, rank=0)  # the only rank with an ingest hook
    rank2 = _injector(spec, rank=2)
    for inj in (wrong, rank0, rank2):
        inj.apply("ring", 32, 1, 1.0)
    assert not wrong.hook_armed() and not wrong.take_forced_rotation()
    assert rank0.hook_armed() and rank0.take_forced_rotation()
    assert wrong.corrupt_ops() == [] and rank2.corrupt_ops() == ["ring"]
    x = np.linspace(1.0, 2.0, 16)
    assert np.array_equal(wrong.corrupt_payload("ring", x.copy()), x)
    assert not np.array_equal(rank2.corrupt_payload("ring", x.copy()), x)
    # a hook_fail pinned to a non-zero rank could NEVER fire (only rank 0
    # wires the hook) and would deterministically fail verify: rejected
    with pytest.raises(ValueError, match="hook_fail rank"):
        FaultSpec(kind="hook_fail", rank=2)


# --- heavy-tailed jitter shapes ----------------------------------------


def test_jitter_shape_validation():
    with pytest.raises(ValueError, match="unknown jitter shape"):
        FaultSpec(kind="jitter", shape="cauchy")
    with pytest.raises(ValueError, match="only applies to jitter"):
        FaultSpec(kind="delay", shape="pareto")
    # JSON spec round-trips the new fields
    (f,) = parse_spec([{"kind": "jitter", "shape": "lognormal",
                        "magnitude": 0.1, "rank": 1}])
    assert (f.shape, f.rank) == ("lognormal", 1)


@pytest.mark.parametrize("shape", ["lognormal", "pareto"])
def test_heavy_tailed_jitter_is_seeded_and_heavy(shape):
    spec = [FaultSpec(kind="jitter", magnitude=0.2, shape=shape)]
    a = _injector(spec, seed=7)
    b = _injector(spec, seed=7)
    xs = [a.apply("ring", 32, i, 1.0) for i in range(1, 2001)]
    ys = [b.apply("ring", 32, i, 1.0) for i in range(1, 2001)]
    assert xs == ys                       # same seed => same tail draws
    assert all(x > 0 for x in xs)
    assert len(set(xs)) > 1900            # it actually jitters
    # heavy tail: some samples beyond the uniform shape's hard 1.2 cap
    assert max(xs) > 1.2
    # ...but the BULK stays near 1 (detectors must not be tripped by the
    # typical sample, only the occasional tail draw they must tolerate)
    med = sorted(xs)[len(xs) // 2]
    assert 0.8 < med < 1.3
    # the ledger records the multiplier, seeded (no wall clock)
    recs = [r for r in a.ledger.rows if r["record"] == "fault"]
    assert recs and all("m" in r for r in recs)


def test_pareto_jitter_is_median_preserving():
    """The jitter contract is NOISE (no detector may fire): the pareto
    draw's raw median is 2**magnitude, which at magnitude 0.8 would be
    a sustained +74% level shift — exactly what the regression detector
    exists to catch.  The normalized multiplier must sit at median ~1."""
    spec = [FaultSpec(kind="jitter", magnitude=0.8, shape="pareto")]
    inj = _injector(spec, seed=3)
    xs = [inj.apply("ring", 32, i, 1.0) for i in range(1, 2001)]
    med = sorted(xs)[len(xs) // 2]
    assert 0.9 < med < 1.1
    assert max(xs) > 2.0  # the tail is still heavy


def test_uniform_jitter_draw_stream_unchanged():
    """The shape refactor must not move the uniform stream: the PR-2
    byte-identical-ledger contract pins the (seed, spec, run) draw."""
    import random

    inj = _injector([FaultSpec(kind="jitter", magnitude=0.5)], seed=7)
    got = inj.apply("ring", 32, 3, 1.0)
    u = 2.0 * random.Random("7:0:3").random() - 1.0
    assert got == pytest.approx(1.0 + 0.5 * u)


# --- hook_fail machinery ------------------------------------------------


def test_hook_fail_forces_rotation_and_raises_in_window():
    inj = _injector([FaultSpec(kind="hook_fail", start=5, end=7)])
    inner_calls = []
    hook = inj.wrap_hook(lambda: inner_calls.append(1))
    inj.apply("ring", 32, 4, 1.0)
    assert not inj.take_forced_rotation()
    hook()  # outside the window: delegates
    assert inner_calls == [1]
    inj.apply("ring", 32, 5, 1.0)
    assert inj.take_forced_rotation()       # fires once, at window start
    assert not inj.take_forced_rotation()   # one-shot flag
    with pytest.raises(InjectedHookFailure):
        hook()
    inj.apply("ring", 32, 7, 1.0)
    assert not inj.take_forced_rotation()   # once per window
    with pytest.raises(InjectedHookFailure):
        hook()  # still armed anywhere in the window
    inj.apply("ring", 32, 8, 1.0)
    hook()  # window over: delegates again
    assert inner_calls == [1, 1]
    recs = [r for r in inj.ledger.rows if r["record"] == "fault"]
    assert [r["run_id"] for r in recs] == [5]


def test_wrap_hook_without_inner_hook():
    # a chaos run without a configured ingest command still exercises
    # the never-fatal contract: the wrapper alone raises when armed
    inj = _injector([FaultSpec(kind="hook_fail", start=1, end=1)])
    hook = inj.wrap_hook(None)
    inj.apply("ring", 32, 1, 1.0)
    with pytest.raises(InjectedHookFailure):
        hook()
    inj.apply("ring", 32, 2, 1.0)
    hook()  # disarmed: no-op


# --- synthetic timing source -------------------------------------------


def test_synthetic_series_deterministic_and_never_flat():
    a = FaultInjector([], seed=3, synthetic_s=1e-3)
    b = FaultInjector([], seed=3, synthetic_s=1e-3)
    xs = [a.synthetic_sample("ring", 32) for _ in range(100)]
    ys = [b.synthetic_sample("ring", 32) for _ in range(100)]
    assert xs == ys
    assert len(set(xs)) == 100  # never bit-identical: no false flatline
    assert all(abs(x / 1e-3 - 1.0) < 1e-2 for x in xs)
    # per-point streams are independent
    assert a.synthetic_sample("ring", 8) != b.synthetic_sample("ring", 32)
    assert a.synthetic and not FaultInjector([]).synthetic


# --- payload corruption -------------------------------------------------


def test_corrupt_payload_flips_one_deterministic_element():
    spec = [FaultSpec(kind="corrupt", op="ring")]
    a = _injector(spec, seed=1)
    b = _injector(spec, seed=1)
    x = np.linspace(1.0, 2.0, 64, dtype=np.float64)
    ya = a.corrupt_payload("ring", x.copy())
    yb = b.corrupt_payload("ring", x.copy())
    assert not np.array_equal(ya, x)
    # deterministic flip (the flipped element may come out NaN — a high
    # exponent bit can complete an all-ones exponent)
    assert np.array_equal(ya, yb, equal_nan=True)
    changed = np.flatnonzero(~np.isclose(ya, x) | ~np.isfinite(ya))
    assert changed.size == 1  # exactly one element, far outside any rtol
    # ops not named by a corrupt fault pass through untouched
    assert np.array_equal(a.corrupt_payload("halo", x.copy()), x)
    assert a.corrupt_ops() == ["ring"]
    recs = [r for r in a.ledger.rows if r["record"] == "fault"]
    assert recs[0]["kind"] == "corrupt" and recs[0]["bit"] == 62


def test_corrupt_caught_by_selftest_rx_validation(eight_devices):
    """The chaos contract for `corrupt`: the selftest numerics pass MUST
    flag the op whose payload was flipped, and only that op."""
    from tpu_perf.parallel import make_mesh
    from tpu_perf.selftest import run_selftest

    mesh = make_mesh()
    inj = _injector([FaultSpec(kind="corrupt", op="ring")], seed=7)
    results = {r.op: r for r in run_selftest(
        mesh, ops=["ring", "halo"], injector=inj)}
    assert results["ring"].status == "fail"
    assert results["halo"].status == "ok"
    recs = [r for r in inj.ledger.rows if r["record"] == "fault"]
    assert len(recs) == 1 and recs[0]["op"] == "ring"
