"""`tpu-perf run --backend mpi` drives the native C baseline (VERDICT r2
item 1): the CLI renders/executes the same command line the profile
scripts produce, so one operator surface covers both backends and one
logfolder holds both backends' rows for `report --compare`."""

import os
import shutil
import subprocess

import pytest

from tpu_perf.cli import main
from tpu_perf.config import Options
from tpu_perf.mpi_launch import backend_dir, mpi_sizes_for, plan_command

@pytest.fixture(scope="module")
def shim_binary():
    if shutil.which("gcc") is None and shutil.which("cc") is None:
        pytest.skip("no C compiler")
    subprocess.run(["make", "shim"], cwd=backend_dir(), check=True,
                   capture_output=True)
    return backend_dir() / "mpi_perf_shim"


def test_plan_shim_pairwise_command_pinned(tmp_path):
    # the exact rendered line, auto-generated group file included
    opts = Options(op="exchange", nonblocking=True, buff_sz=65536, iters=40,
                   num_runs=3, logfolder=str(tmp_path))
    cmd = plan_command(opts, 65536)
    assert cmd[0] == str(backend_dir() / "mpi_perf_shim")
    assert cmd[1:4] == ["-np", "2", "--"]
    flags = cmd[4:]
    group = flags[flags.index("-f") + 1]
    assert open(group).read() == "shimhost1\n"
    assert flags[: flags.index("-f")] == [
        "-x", "1", "-i", "40", "-b", "65536", "-r", "3", "-p", "1",
    ]
    assert flags[-2:] == ["-l", str(tmp_path)]


def test_plan_shim_collective_world_from_mesh():
    opts = Options(op="allreduce", buff_sz=4096, mesh_shape=(8,),
                   mesh_axes=("x",))
    cmd = plan_command(opts, 4096)
    assert cmd[1:4] == ["-np", "8", "--"]
    assert cmd[4:6] == ["-o", "allreduce"]
    assert "-f" not in cmd  # collectives run over the whole world


def test_plan_mpirun_command_matches_monitor_script(tmp_path):
    # the run-mpi-monitor.sh shape (mpirun -np 2*FLOWS --host ...
    # --map-by ppr:FLOWS:node ... -f GROUP1 ... run-mpi-monitor.sh:53-56)
    group = tmp_path / "group1"
    group.write_text("host1\n")
    opts = Options(op="pingpong_unidir", uni_dir=True, buff_sz=456131,
                   iters=10, num_runs=-1, ppn=10, group1_file=str(group),
                   n_group1=1, logfolder="/mnt/tcp-logs")
    cmd = plan_command(opts, 456131, hosts="host0,host1")
    # -x forwards the rotation-ingest env var to remote ranks, exactly as
    # run-mpi-monitor.sh:51 does — without it Open MPI drops the var
    assert cmd[:10] == ["mpirun", "-np", "20", "--host", "host0,host1",
                        "--map-by", "ppr:10:node",
                        "-x", "TPU_PERF_INGEST_CMD",
                        str(backend_dir() / "mpi_perf")]
    assert cmd[10:] == ["-u", "1", "-i", "10", "-b", "456131", "-r", "-1",
                        "-p", "10", "-f", str(group), "-n", "1",
                        "-l", "/mnt/tcp-logs"]


def test_mpirun_mesh_topology_conflict_rejected(tmp_path):
    opts = Options(op="allreduce", buff_sz=4096, mesh_shape=(8,),
                   mesh_axes=("x",))
    with pytest.raises(ValueError, match="conflicts with --hosts"):
        plan_command(opts, 4096, hosts="h0,h1")


def test_extern_cmd_rejected_for_mpi_backend(capsys):
    rc = main(["run", "--backend", "mpi", "-d", "srv {role}", "--dry-run",
               "--op", "pingpong"])
    assert rc == 2
    assert "jax-backend only" in capsys.readouterr().err


def test_mpirun_pairwise_without_group_file_rejected():
    opts = Options(op="pingpong", buff_sz=4096)
    with pytest.raises(ValueError, match="group1-file"):
        plan_command(opts, 4096, hosts="h0,h1")


def test_jax_only_op_rejected():
    # mxu_gemm is a TPU compute instrument with no C analogue
    # (hbm_stream, by contrast, grew a host-DRAM kernel in round 3)
    opts = Options(op="mxu_gemm", buff_sz=4096)
    with pytest.raises(ValueError, match="no mpi-backend kernel"):
        plan_command(opts, 4096)


def test_non_f32_dtype_rejected(capsys):
    rc = main(["run", "--backend", "mpi", "--op", "allreduce",
               "--dtype", "bfloat16", "--dry-run"])
    assert rc == 2
    assert "jax-backend only" in capsys.readouterr().err


def test_daemon_sweep_rejected():
    opts = Options(op="pingpong", num_runs=-1, sweep="8,64K")
    with pytest.raises(ValueError, match="single size"):
        mpi_sizes_for(opts)


def test_dry_run_sweep_renders_one_line_per_size(capsys):
    rc = main(["run", "--backend", "mpi", "--op", "allreduce",
               "--sweep", "8,64K,1M", "--dry-run"])
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 3
    assert [l.split("-b ")[1].split()[0] for l in lines] == ["8", "65536", "1048576"]


def test_cli_populates_both_backends_and_compare_pairs(
    shim_binary, tmp_path, eight_devices, capsys
):
    # THE Done criterion: one CLI invocation writes backend=mpi rows, a
    # second writes backend=jax rows, and report --compare pairs them
    logs = tmp_path / "logs"
    logs.mkdir()
    env_backup = os.environ.get("TPU_PERF_INGEST_CMD")
    os.environ["TPU_PERF_INGEST_CMD"] = "true"  # no ingest in this test
    try:
        rc = main(["run", "--backend", "mpi", "--op", "exchange",
                   "-b", "64K", "-i", "40", "-r", "3", "-l", str(logs)])
    finally:
        if env_backup is None:
            del os.environ["TPU_PERF_INGEST_CMD"]
        else:
            os.environ["TPU_PERF_INGEST_CMD"] = env_backup
    assert rc == 0
    rc = main(["run", "--backend", "jax", "--op", "exchange",
               "-b", "64K", "-i", "10", "-r", "3", "-l", str(logs)])
    assert rc == 0
    capsys.readouterr()

    assert main(["report", str(logs), "--compare"]) == 0
    out = capsys.readouterr().out
    (row,) = [l for l in out.splitlines() if l.startswith("| exchange")]
    cells = [c.strip() for c in row.split("|")]
    # both backends' p50 columns populated and a real ratio — no dashes
    assert "—" not in row
    assert cells[10] == "8/2"  # jax mesh vs the 2-rank shim pair


def test_jax_backend_rejects_hosts(capsys):
    rc = main(["run", "--backend", "jax", "--hosts", "h0,h1"])
    assert rc == 2
    assert "--hosts" in capsys.readouterr().err
