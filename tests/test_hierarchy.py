"""Hierarchical multislice collectives (ISSUE 13): registry + keying,
numerics parity vs the native flat lowering on mixed meshes, the
bytes-per-axis accounting model, plan expansion/degradation, report
surfaces (mesh-shaped crossover + DCN model), and linkmap cross-sweep
diffing."""

import dataclasses
import io

import jax
import numpy as np
import pytest

from tpu_perf.arena.hierarchy import (
    HIER_ALGORITHMS,
    axis_bytes,
    dcn_bound_bytes,
    flat_dcn_bytes,
    hier_algos_for,
    hier_axis_pairs,
    hier_bases_for,
    is_hier,
    is_hier_compatible,
    mesh_shape_label,
    phase_traffic,
    resolve_hier,
)
from tpu_perf.config import Options
from tpu_perf.ops import build_op
from tpu_perf.parallel import make_mesh
from tpu_perf.runner import algos_for_options
from tpu_perf.schema import ResultRow, base_op, decorate_op

MESH_AXES = (("dcn", 2), ("ici", 4))
KEY = "dcn=2+ici=4"


@pytest.fixture(scope="module")
def mesh24(eight_devices):
    return make_mesh((2, 4), ("dcn", "ici"))


@pytest.fixture(scope="module")
def mesh42(eight_devices):
    return make_mesh((4, 2), ("dcn", "ici"))


# --- registry + name grammar -----------------------------------------


def test_registry_shape():
    # every collective has the native-primitive composition plus at
    # least two hand-built per-axis variants; an inner algorithm is
    # registered only when it covers every phase its composition needs
    assert hier_bases_for("allreduce") == ("hier", "hier-rhd", "hier-ring")
    assert hier_bases_for("all_gather") == (
        "hier", "hier-bruck", "hier-rhd", "hier-ring")
    assert hier_bases_for("reduce_scatter") == (
        "hier", "hier-binomial", "hier-rhd", "hier-ring")
    # bruck has no reduce_scatter phase, binomial no allgather — the
    # missing combos must be absent, not silently patched
    assert ("allreduce", "hier-bruck") not in HIER_ALGORITHMS
    assert ("allreduce", "hier-binomial") not in HIER_ALGORITHMS
    assert ("all_gather", "hier-binomial") not in HIER_ALGORITHMS
    assert ("reduce_scatter", "hier-bruck") not in HIER_ALGORITHMS


def test_is_hier_and_axis_pairs():
    assert is_hier("hier") and is_hier("hier-ring")
    assert is_hier(f"hier-ring:{KEY}")
    assert not is_hier("ring") and not is_hier("native")
    # "hierarchical" is not in the grammar — the prefix must be exact
    assert not is_hier("hierarch")
    assert hier_axis_pairs(f"hier:{KEY}") == MESH_AXES
    assert hier_axis_pairs("hier") is None       # bare base: no key
    assert hier_axis_pairs("ring") is None       # foreign algo
    assert hier_axis_pairs("hier:garbage") is None  # never raises


def test_resolve_hier_keys_per_mesh():
    keyed = resolve_hier("allreduce", "hier-ring", ("dcn", "ici"), (2, 4))
    assert keyed == f"hier-ring:{KEY}"
    # idempotent: resolving the keyed name on the same mesh is a no-op
    assert resolve_hier("allreduce", keyed, ("dcn", "ici"), (2, 4)) == keyed


def test_resolve_hier_loud_errors():
    with pytest.raises(ValueError, match="no hierarchical"):
        resolve_hier("ring", "hier", ("dcn", "ici"), (2, 4))
    with pytest.raises(ValueError, match="registered"):
        resolve_hier("allreduce", "hier-bruck", ("dcn", "ici"), (2, 4))
    with pytest.raises(ValueError, match="no slow hop"):
        resolve_hier("allreduce", "hier", ("x",), (8,))
    with pytest.raises(ValueError, match="exactly two"):
        resolve_hier("allreduce", "hier", ("a", "b", "c"), (2, 2, 2))
    with pytest.raises(ValueError, match="power-of-two"):
        resolve_hier("allreduce", "hier-rhd", ("dcn", "ici"), (3, 4))
    # a keyed name from another mesh's artifact cannot run here
    with pytest.raises(ValueError, match="another mesh"):
        resolve_hier("allreduce", f"hier:{KEY}", ("dcn", "ici"), (4, 2))


def test_is_hier_compatible_per_axis_pow2():
    assert is_hier_compatible("allreduce", "hier", (3, 5))
    assert is_hier_compatible("allreduce", "hier-rhd", (2, 4))
    assert not is_hier_compatible("allreduce", "hier-rhd", (3, 4))
    assert not is_hier_compatible("allreduce", "hier", (8,))
    assert not is_hier_compatible("allreduce", "nope", (2, 4))


def test_hier_algos_for_skips_pow2_with_note():
    err = io.StringIO()
    algos = hier_algos_for("allreduce", (("dcn", 3), ("ici", 4)), err=err)
    assert algos == ["hier:dcn=3+ici=4", "hier-ring:dcn=3+ici=4"]
    assert "hier-rhd" in err.getvalue()
    assert "power-of-two" in err.getvalue()


def test_hier_algos_for_three_axes_names_the_real_reason():
    # a 3-axis mesh fails on the axis COUNT: one note saying so, never
    # a per-variant pow2 misdiagnosis (the sizes here ARE powers of 2)
    err = io.StringIO()
    algos = hier_algos_for(
        "allreduce", (("a", 2), ("b", 2), ("c", 2)), err=err)
    assert algos == []
    note = err.getvalue()
    assert "exactly two mesh axes" in note
    assert "power-of-two" not in note
    assert note.count("skipping") == 1


def test_decorated_label_round_trip():
    label = decorate_op("allreduce", f"hier:{KEY}")
    assert label == f"allreduce[hier:{KEY}]"
    assert base_op(label) == "allreduce"
    assert base_op(decorate_op("allreduce", f"hier:{KEY}", 500)) == \
        "allreduce"


# --- numerics parity on mixed meshes ---------------------------------


@pytest.mark.parametrize("coll,base", sorted(HIER_ALGORITHMS))
def test_parity_vs_native_2x4(mesh24, coll, base):
    # 260 B = 65 f32 elements: exercises the allreduce virtual-padding
    # path (65 is not a multiple of the 4-wide ici axis)
    native = build_op(coll, mesh24, 260, 2)
    hier = build_op(coll, mesh24, 260, 2, algo=base)
    assert hier.algo == f"{base}:{KEY}"
    assert hier.nbytes == native.nbytes
    want = np.asarray(jax.block_until_ready(
        native.step(native.example_input)), dtype=np.float64)
    got = np.asarray(jax.block_until_ready(
        hier.step(hier.example_input)), dtype=np.float64)
    if coll == "all_gather":
        # pure movement: bit-identical to the native lowering
        np.testing.assert_array_equal(got, want)
    else:
        np.testing.assert_allclose(got, want, rtol=5e-6)


@pytest.mark.parametrize("coll", ["allreduce", "all_gather",
                                  "reduce_scatter"])
def test_parity_vs_native_4x2(mesh42, coll):
    # the transposed split: 4 slices of 2 — the block transposes must
    # track the axis sizes, not assume the 2x4 shape
    native = build_op(coll, mesh42, 512, 2)
    hier = build_op(coll, mesh42, 512, 2, algo="hier-ring")
    assert hier.algo == "hier-ring:dcn=4+ici=2"
    want = np.asarray(jax.block_until_ready(
        native.step(native.example_input)), dtype=np.float64)
    got = np.asarray(jax.block_until_ready(
        hier.step(hier.example_input)), dtype=np.float64)
    np.testing.assert_allclose(got, want, rtol=5e-6)


def test_parity_bf16_tolerance(mesh24):
    native = build_op("allreduce", mesh24, 1024, 2, dtype="bfloat16")
    hier = build_op("allreduce", mesh24, 1024, 2, dtype="bfloat16",
                    algo="hier")
    want = np.asarray(jax.block_until_ready(
        native.step(native.example_input)), dtype=np.float64)
    got = np.asarray(jax.block_until_ready(
        hier.step(hier.example_input)), dtype=np.float64)
    np.testing.assert_allclose(got, want, rtol=2e-2)


def test_all_gather_int32_bit_exact(mesh24):
    # movement compositions relocate bytes; an integer payload must
    # survive bit-for-bit through both gather phases and the transpose
    native = build_op("all_gather", mesh24, 512, 2, dtype="int32")
    hier = build_op("all_gather", mesh24, 512, 2, dtype="int32",
                    algo="hier-bruck")
    want = np.asarray(jax.block_until_ready(
        native.step(native.example_input)))
    got = np.asarray(jax.block_until_ready(
        hier.step(hier.example_input)))
    np.testing.assert_array_equal(got, want)


def test_hier_allreduce_legacy_op_agrees(mesh24):
    # the PR-era hier_allreduce kernel is the same construction under
    # its old spelling — the two must never drift
    legacy = build_op("hier_allreduce", mesh24, 4096, 2)
    modern = build_op("allreduce", mesh24, 4096, 2, algo="hier")
    np.testing.assert_allclose(
        np.asarray(jax.block_until_ready(
            legacy.step(legacy.example_input)), dtype=np.float64),
        np.asarray(jax.block_until_ready(
            modern.step(modern.example_input)), dtype=np.float64),
        rtol=5e-6)


def test_build_op_single_axis_hier_is_loud(eight_devices):
    mesh = make_mesh()
    with pytest.raises(ValueError, match="no slow hop"):
        build_op("allreduce", mesh, 1024, 2, algo="hier")


def test_build_op_flat_algo_on_mixed_mesh_is_loud(mesh24):
    with pytest.raises(ValueError, match="single mesh axis"):
        build_op("allreduce", mesh24, 1024, 2, algo="ring")


def test_compile_spec_keys_on_keyed_algo():
    from tpu_perf.compilepipe import CompileSpec

    a = CompileSpec.make("allreduce", 1024, 2, algo=f"hier:{KEY}")
    b = CompileSpec.make("allreduce", 1024, 2, algo="hier:dcn=4+ici=2")
    c = CompileSpec.make("allreduce", 1024, 2, algo="native")
    assert len({a, b, c}) == 3


# --- bytes-per-axis accounting model ---------------------------------


def test_dcn_bound_identities():
    m, n, n_slice = 1 << 20, 8, 4
    # THE identity: hier ships payload/n_slice across the slow axis,
    # the flat schedule payload*(n-1)/n
    assert dcn_bound_bytes("allreduce", m, MESH_AXES) == m / n_slice
    assert flat_dcn_bytes("allreduce", m, n) == m * (n - 1) / n
    # all_gather: only the foreign slices' shards cross
    assert dcn_bound_bytes("all_gather", m, MESH_AXES) == m * 1 / 8
    # reduce_scatter: the partial shard, once per foreign slice share
    assert dcn_bound_bytes("reduce_scatter", m, MESH_AXES) == \
        m / 4 * 1 / 2
    for coll in ("allreduce", "all_gather", "reduce_scatter"):
        assert dcn_bound_bytes(coll, m, MESH_AXES) < \
            flat_dcn_bytes(coll, m, n)
    with pytest.raises(ValueError, match="no hierarchical"):
        dcn_bound_bytes("ring", m, MESH_AXES)


def test_phase_traffic_walks_the_composition():
    m = 1 << 20
    phases = phase_traffic("allreduce", m, MESH_AXES)
    assert [(p.phase, p.axis) for p in phases] == [
        ("reduce_scatter", "ici"), ("allreduce", "dcn"),
        ("all_gather", "ici"),
    ]
    rs, ar, ag = phases
    assert rs.payload_bytes == m and rs.wire_bytes == m * 3 / 4
    assert ar.payload_bytes == m / 4 and ar.wire_bytes == 2 * (m / 4) / 2
    assert ag.payload_bytes == m / 4 and ag.wire_bytes == (m / 4) * 3
    per_axis = axis_bytes("allreduce", m, MESH_AXES)
    assert per_axis == {"ici": rs.wire_bytes + ag.wire_bytes,
                        "dcn": ar.wire_bytes}
    # all_gather: slow axis first, on the small shard
    phases = phase_traffic("all_gather", m, MESH_AXES)
    assert [(p.phase, p.axis) for p in phases] == [
        ("all_gather", "dcn"), ("all_gather", "ici")]
    assert phases[0].payload_bytes == m / 8   # the per-device shard


def test_mesh_shape_label():
    assert mesh_shape_label(MESH_AXES) == "2x(4)"
    assert mesh_shape_label(None) == "flat"


# --- plan expansion / degradation ------------------------------------


def test_algos_for_options_all_on_mixed_mesh():
    opts = Options(algo="all")
    err = io.StringIO()
    algos = algos_for_options(opts, "allreduce", 8, err=err,
                              mesh_axes=MESH_AXES)
    assert algos == ["native", f"hier:{KEY}", f"hier-rhd:{KEY}",
                     f"hier-ring:{KEY}"]
    # the flat single-axis schedules cannot build over two axes: the
    # skip is noted, never silent
    assert "flat single-axis schedules are skipped" in err.getvalue()


def test_algos_for_options_explicit_hier_family():
    opts = Options(algo="hier,native")
    algos = algos_for_options(opts, "allreduce", 8, mesh_axes=MESH_AXES)
    assert algos == [f"hier:{KEY}", "native"]


def test_algos_for_options_single_axis_degrades_loudly():
    opts = Options(algo="hier")
    err = io.StringIO()
    algos = algos_for_options(opts, "allreduce", 8, err=err,
                              mesh_axes=(("x", 8),))
    assert algos == ["native"]
    assert "2-axis" in err.getvalue()
    assert "native lowering" in err.getvalue()
    # ...and the fallback dedupes against an explicit native entry
    opts = Options(algo="hier,native")
    algos = algos_for_options(opts, "allreduce", 8, err=io.StringIO(),
                              mesh_axes=(("x", 8),))
    assert algos == ["native"]


def test_algos_for_options_flat_algo_on_mixed_mesh_raises():
    opts = Options(algo="ring")
    with pytest.raises(ValueError, match="single-axis flat"):
        algos_for_options(opts, "allreduce", 8, mesh_axes=MESH_AXES)


def test_algos_for_options_flat_mesh_unchanged():
    # the pre-hier flat expansion is byte-identical: no hier entries,
    # no new notes
    from tpu_perf.arena import algorithms_for

    opts = Options(algo="all")
    err = io.StringIO()
    algos = algos_for_options(opts, "allreduce", 8, err=err,
                              mesh_axes=(("x", 8),))
    assert algos == ["native"] + list(algorithms_for("allreduce"))
    assert err.getvalue() == ""


# --- rows / report surfaces ------------------------------------------


def _row(op, nbytes, lat_us, algo="", n=8, mode="oneshot"):
    return ResultRow(
        timestamp="2026-01-01 00:00:00.000", job_id="j", backend="jax",
        op=op, nbytes=nbytes, iters=1, run_id=1, n_devices=n,
        lat_us=lat_us, algbw_gbps=1.0, busbw_gbps=1.0,
        time_ms=lat_us / 1e3, mode=mode, algo=algo,
    )


def test_keyed_algo_row_round_trip():
    row = _row("allreduce", 1024, 10.0, algo=f"hier-ring:{KEY}")
    line = row.to_csv()
    assert len(line.split(",")) == 20  # the arena width, unchanged
    back = ResultRow.from_csv(line)
    assert back.algo == f"hier-ring:{KEY}"


def test_compare_arena_mesh_dimension():
    from tpu_perf.report import aggregate, arena_to_markdown, compare_arena

    rows = [_row("allreduce", 1024, 20.0),
            _row("allreduce", 1024, 10.0, algo=f"hier:{KEY}")]
    cross = compare_arena(aggregate(rows))
    assert len(cross) == 1
    c = cross[0]
    assert c.mesh_axes == MESH_AXES and c.mesh == "2x(4)"
    assert c.best[0] == f"hier:{KEY}"
    assert c.native_vs_best == pytest.approx(2.0)
    md = arena_to_markdown(cross)
    assert "| mesh |" in md and "| 2x(4) |" in md
    # a flat-arena table renders NO mesh column — byte-stable pre-hier
    flat = compare_arena(aggregate([
        _row("allreduce", 1024, 20.0),
        _row("allreduce", 1024, 12.0, algo="ring"),
    ]))
    assert flat[0].mesh == "flat"
    assert "| mesh |" not in arena_to_markdown(flat)


def test_hier_traffic_table():
    from tpu_perf.report import (
        aggregate, hier_traffic, hier_traffic_to_markdown,
    )

    rows = [_row("allreduce", 1024, 20.0),
            _row("allreduce", 1024, 10.0, algo=f"hier:{KEY}"),
            # chaos and skewed rows never enter the model
            _row("allreduce", 1024, 5.0, algo=f"hier:{KEY}",
                 mode="chaos")]
    model = hier_traffic(aggregate(rows))
    assert len(model) == 1
    m = model[0]
    assert m.dcn_bytes_hier == 1024 / 4
    assert m.dcn_bytes_flat == 1024 * 7 / 8
    assert m.dcn_reduction == pytest.approx(3.5)
    assert m.native_vs_hier == pytest.approx(2.0)
    assert m.hier.lat_us["p50"] == 10.0  # the chaos row lost no pivot
    md = hier_traffic_to_markdown(model)
    assert "dcn B/dev (hier)" in md and "2x(4)" in md


def test_hier_traffic_native_must_match_device_count():
    # the native control pairs per device count: a 4-device native
    # curve must never be ratioed against an 8-device hier point (a
    # different fabric claiming the hier point's mesh)
    from tpu_perf.report import aggregate, hier_traffic

    rows = [_row("allreduce", 1024, 5.0, n=4),
            _row("allreduce", 1024, 10.0, algo=f"hier:{KEY}", n=8)]
    model = hier_traffic(aggregate(rows))
    assert len(model) == 1 and model[0].native is None
    rows.append(_row("allreduce", 1024, 20.0, n=8))
    model = hier_traffic(aggregate(rows))
    assert model[0].native is not None
    assert model[0].native.n_devices == 8
    assert model[0].native_vs_hier == pytest.approx(2.0)


def test_clean_pivots_exclude_hier_rows():
    from tpu_perf.report import aggregate, compare, compare_pallas

    rows = [_row("allreduce", 1024, 20.0),
            _row("allreduce", 1024, 1.0, algo=f"hier:{KEY}")]
    points = aggregate(rows)
    for cmp in compare(points):
        assert cmp.jax is None or cmp.jax.algo == "native"
    for cmp in compare_pallas(points):
        assert cmp.xla is None or cmp.xla.algo == "native"


def test_driver_label_decorates_keyed_algo():
    from tpu_perf.driver import _op_label

    built = dataclasses.make_dataclass(
        "B", [("name", str), ("algo", str)])("allreduce", f"hier:{KEY}")
    assert _op_label(built) == f"allreduce[hier:{KEY}]"
    assert _op_label(built, 500) == f"allreduce[hier:{KEY}]@500us"


# --- driver e2e on the mixed mesh ------------------------------------


def test_driver_e2e_mixed_mesh(tmp_path, mesh24):
    from tpu_perf.driver import Driver

    opts = Options(op="allreduce", algo="hier,native", buff_sz=256,
                   iters=1, num_runs=2, warmup_runs=1)
    rows = Driver(opts, mesh24, err=io.StringIO()).run()
    algos = {r.algo for r in rows}
    assert algos == {f"hier:{KEY}", ""}
    assert all(r.op == "allreduce" for r in rows)
    assert len(rows) == 4  # 2 algos x 2 runs


# --- linkmap cross-sweep diffing (carried PR-3 satellite) ------------


def _verdict(src, dst, lat_us, verdict="ok", axis="ici"):
    return {"op": f"link:(0,{src})>(0,{dst})", "axis": axis, "src": src,
            "dst": dst, "lat_us": lat_us, "verdict": verdict}


def test_diff_linkmaps_degradation_gate():
    from tpu_perf.linkmap import diff_linkmaps

    base = [_verdict(0, 1, 100.0), _verdict(1, 2, 100.0),
            _verdict(2, 3, 100.0), _verdict(3, 4, 100.0)]
    new = [_verdict(0, 1, 101.0),          # ok
           _verdict(1, 2, 140.0),          # degraded (inside MAD band!)
           _verdict(2, 3, 60.0),           # improved
           _verdict(3, 4, None, "dead")]   # died since base
    diffs = diff_linkmaps(base, new, threshold_pct=30.0)
    by = {(d["src"], d["dst"]): d for d in diffs}
    assert by[(0, 1)]["diff"] == "ok"
    assert by[(1, 2)]["diff"] == "degraded"
    assert by[(1, 2)]["delta_pct"] == pytest.approx(40.0)
    assert by[(2, 3)]["diff"] == "improved"
    assert by[(3, 4)]["diff"] == "degraded"
    assert "died" in by[(3, 4)]["detail"]


def test_diff_linkmaps_coverage_and_threshold():
    from tpu_perf.linkmap import (
        diff_linkmaps, linkdiff_summary, linkdiff_to_markdown,
    )

    base = [_verdict(0, 1, 100.0), _verdict(1, 2, 100.0)]
    new = [_verdict(1, 2, 100.0), _verdict(2, 3, 100.0)]
    diffs = diff_linkmaps(base, new)
    by = {(d["src"], d["dst"]): d for d in diffs}
    assert by[(0, 1)]["diff"] == "base-only"
    assert by[(2, 3)]["diff"] == "new-only"
    assert by[(1, 2)]["diff"] == "ok"
    md = linkdiff_to_markdown(diffs)
    assert "base-only" in md and "new-only" in md
    assert "none degraded" in linkdiff_summary(diffs, 30.0)
    with pytest.raises(ValueError, match="positive"):
        diff_linkmaps(base, new, threshold_pct=0)


def test_load_linkmap_artifact_rejects_foreign_json(tmp_path):
    from tpu_perf.linkmap import load_linkmap_artifact

    p = tmp_path / "foreign.json"
    p.write_text('{"not": "a linkmap artifact"}')
    with pytest.raises(ValueError, match="artifact"):
        load_linkmap_artifact(str(p))


# --- dcn roofline (linkmap fidelity on the slow axis) ----------------


def test_dcn_roofline_grades_the_slow_axis():
    from tpu_perf.linkmap.grade import GradeConfig, _roofline_for

    cfg = GradeConfig(roofline_gbps=100.0, roofline_axes=("ici",),
                      dcn_roofline_gbps=10.0)
    assert _roofline_for("ici", cfg) == 100.0
    assert _roofline_for("dcn", cfg) == 10.0   # its OWN spec
    assert _roofline_for("DCN0", cfg) == 10.0  # naming convention, any case
    assert _roofline_for("pair", cfg) is None  # un-modeled axes stay MAD-only
    # without the dcn knob, dcn axes keep MAD-only grading
    cfg = GradeConfig(roofline_gbps=100.0, roofline_axes=("ici",))
    assert _roofline_for("dcn", cfg) is None
    with pytest.raises(ValueError, match="dcn_roofline"):
        GradeConfig(dcn_roofline_gbps=-1.0)
