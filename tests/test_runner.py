import pytest

from tpu_perf.config import Options
from tpu_perf.parallel import make_mesh
from tpu_perf.runner import op_for_options, run_point, run_sweep
from tpu_perf.schema import RESULT_HEADER


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh()


def test_op_selection_precedence():
    # mirrors mpi_perf.c:506-523 kernel selection
    assert op_for_options(Options()) == "pingpong"
    assert op_for_options(Options(uni_dir=True)) == "pingpong_unidir"
    assert op_for_options(Options(nonblocking=True)) == "exchange"
    assert op_for_options(Options(op="allreduce")) == "allreduce"


def test_run_point_rows(mesh):
    opts = Options(op="allreduce", iters=2, num_runs=3, buff_sz=64)
    point = run_point(opts, mesh, 64)
    assert len(point.times.samples) == 3
    rows = point.rows(opts.uuid)
    assert len(rows) == 3
    for i, row in enumerate(rows, start=1):
        assert row.run_id == i  # run 0 was the warm-up, rows start at 1
        assert row.op == "allreduce"
        assert row.n_devices == 8
        assert row.nbytes == 64
        assert row.busbw_gbps > 0
        assert len(row.to_csv().split(",")) == len(RESULT_HEADER.split(","))


def test_pingpong_latency_is_half_rtt(mesh):
    opts = Options(iters=1, num_runs=2, buff_sz=64)
    point = run_point(opts, mesh, 64)
    rows = point.rows(opts.uuid)
    t_us = point.times.samples[0] * 1e6
    assert rows[0].lat_us == pytest.approx(t_us / 2, rel=1e-6)


def test_pl_pingpong_rows_and_half_rtt(mesh):
    # end-to-end over the pallas path: row emission must not raise (bus
    # factor present) and the latency convention matches the XLA pingpong
    opts = Options(op="pl_pingpong", iters=1, num_runs=2, buff_sz=64)
    point = run_point(opts, mesh, 64)
    rows = point.rows(opts.uuid)
    assert rows[0].busbw_gbps > 0
    t_us = point.times.samples[0] * 1e6
    assert rows[0].lat_us == pytest.approx(t_us / 2, rel=1e-6)


def test_pl_all_gather_bidir_rows(mesh):
    opts = Options(op="pl_all_gather_bidir", iters=1, num_runs=1, buff_sz=256)
    point = run_point(opts, mesh, 256)
    rows = point.rows(opts.uuid)
    assert rows[0].busbw_gbps > 0
    assert point.nbytes == 256  # 8 devices x 8-elem even chunk x 4 B


def test_run_sweep_sizes(mesh):
    opts = Options(op="ring", iters=1, num_runs=1, sweep="8,32")
    points = list(run_sweep(opts, mesh))
    assert [p.nbytes for p in points] == [8, 32]


def test_run_sweep_single_point_uses_buff_sz(mesh):
    opts = Options(op="ring", iters=1, num_runs=1, buff_sz=128)
    points = list(run_sweep(opts, mesh))
    assert len(points) == 1
    assert points[0].nbytes == 128


def test_hier_allreduce_point(eight_devices):
    mesh2 = make_mesh((2, 4), ("dcn", "ici"))
    opts = Options(op="hier_allreduce", iters=1, num_runs=1)
    point = run_point(opts, mesh2, 256)
    assert point.n_devices == 8
    rows = point.rows(opts.uuid)
    assert rows[0].busbw_gbps > 0  # uses the allreduce bus factor
