"""Optimized irregular-payload schedules (ISSUE 20): the v-variant
arena registry (sortring / doubling / vhier), the standalone
all_to_all_v op, the segmented generalized allreduce, their NumPy
parity at imbalance ratios {1, 2, 8} on 1D and 2D meshes, int32
bit-exactness for the movement ops, the static-schedule (lockstep)
proof, the wire-bytes models, the algo-aware Imbalance-cost table
(satellite 1), and the tuner round trip for imbalanced coordinates
(satellite 2)."""

from __future__ import annotations

import io

import numpy as np
import pytest

from tpu_perf.arena import valgos
from tpu_perf.config import Options
from tpu_perf.metrics import imbalance_volume_scale, metric_op
from tpu_perf.schema import ResultRow, timestamp_now
from tpu_perf.scenarios import vops


def _mesh(shape=(), axes=()):
    from tpu_perf.parallel import make_mesh

    return make_mesh(shape, axes)


def _host_shards(built):
    x = np.asarray(built.example_input)
    return x.reshape(built.n_devices, -1)


def _step_out(built):
    import jax

    return np.asarray(
        jax.block_until_ready(built.step(built.example_input))
    ).reshape(built.n_devices, -1)


def _expected_gatherv(shards, counts, offsets, elems):
    gathered = np.concatenate(
        [shards[r][: counts[r]] for r in range(len(counts))])
    return np.stack([gathered[offsets[d]: offsets[d] + elems]
                     for d in range(len(counts))])


# ------------------------------------------------- registry structure


def test_v_registry_contents():
    assert valgos.v_algorithms_for("allgatherv") == ("doubling", "sortring")
    assert valgos.v_algorithms_for("reduce_scatter_v") == ("sortring",)
    assert valgos.v_algorithms_for("all_to_all_v") == ("doubling", "ring")
    assert valgos.v_algorithms_for("seg_allreduce") == (
        "binomial", "bruck", "rhd", "ring")


def test_v_registry_errors_are_loud():
    with pytest.raises(ValueError, match="no v-variant decompositions"):
        valgos.v_body_builder_for("allreduce", "sortring", 8)
    with pytest.raises(ValueError, match="registered"):
        valgos.v_body_builder_for("allgatherv", "nope", 8)
    # rhd is pow2-only; a non-pow2 mesh names the constraint
    with pytest.raises(ValueError, match="power-of-two"):
        valgos.v_body_builder_for("seg_allreduce", "rhd", 6)
    assert not valgos.v_is_compatible("seg_allreduce", "rhd", 6)
    assert valgos.v_is_compatible("seg_allreduce", "rhd", 8)


def test_vhier_resolution_contract():
    assert valgos.is_vhier("vhier")
    assert valgos.is_vhier("vhier:dcn=2+ici=4")
    assert not valgos.is_vhier("hier-ring")
    keyed = valgos.resolve_vhier("allgatherv", "vhier", ("dcn", "ici"),
                                 (2, 4))
    assert keyed == "vhier:dcn=2+ici=4"
    # re-resolving the keyed name against its own mesh is idempotent
    assert valgos.resolve_vhier("allgatherv", keyed, ("dcn", "ici"),
                                (2, 4)) == keyed
    with pytest.raises(ValueError, match="allgatherv"):
        valgos.resolve_vhier("reduce_scatter_v", "vhier", ("dcn", "ici"),
                             (2, 4))
    with pytest.raises(ValueError):
        valgos.resolve_vhier("allgatherv", "vhier", ("x",), (8,))
    with pytest.raises(ValueError, match="keyed"):
        valgos.resolve_vhier("allgatherv", "vhier:dcn=4+ici=2",
                             ("dcn", "ici"), (2, 4))


def test_algos_for_options_v_expansion():
    from tpu_perf.runner import algos_for_options

    err = io.StringIO()
    out = algos_for_options(Options(op="allgatherv", algo="all"),
                            "allgatherv", 8, err=err)
    assert out == ["native", "doubling", "sortring"]
    out = algos_for_options(Options(op="all_to_all_v", algo="all"),
                            "all_to_all_v", 8, err=err)
    assert out == ["native", "doubling", "ring"]
    out = algos_for_options(Options(op="seg_allreduce", algo="all"),
                            "seg_allreduce", 8, err=err)
    assert out == ["native", "binomial", "bruck", "rhd", "ring"]
    # non-pow2 mesh: rhd skipped with a note
    err = io.StringIO()
    out = algos_for_options(Options(op="seg_allreduce", algo="all"),
                            "seg_allreduce", 6, err=err)
    assert "rhd" not in out and "rhd" in err.getvalue()
    # multi-axis mesh: the keyed vhier composition (allgatherv only)
    err = io.StringIO()
    out = algos_for_options(Options(op="allgatherv", algo="all"),
                            "allgatherv", 8, err=err,
                            mesh_axes=(("dcn", 2), ("ici", 4)))
    assert out == ["native", "vhier:dcn=2+ici=4"]
    err = io.StringIO()
    out = algos_for_options(Options(op="all_to_all_v", algo="all"),
                            "all_to_all_v", 8, err=err,
                            mesh_axes=(("dcn", 2), ("ici", 4)))
    assert out == ["native"] and "v-composition" in err.getvalue()
    # explicit vhier on a flat axis degrades loudly to native
    err = io.StringIO()
    out = algos_for_options(Options(op="allgatherv", algo="vhier"),
                            "allgatherv", 8, err=err)
    assert out == ["native"] and "vhier" in err.getvalue()
    # a flat v-schedule cannot span a multi-axis mesh
    with pytest.raises(ValueError, match="single-axis"):
        algos_for_options(Options(op="allgatherv", algo="sortring"),
                          "allgatherv", 8,
                          mesh_axes=(("dcn", 2), ("ici", 4)))


# ------------------------------------- numerics vs NumPy (satellite 3)


@pytest.mark.parametrize("algo", ["sortring", "doubling"])
@pytest.mark.parametrize("ratio", [1, 2, 8])
def test_allgatherv_algos_match_numpy(eight_devices, algo, ratio):
    from tpu_perf.ops import build_op

    built = build_op("allgatherv", _mesh(), 4 * 44, 2, imbalance=ratio,
                     algo=algo)
    counts, offsets, elems, _ = vops.v_counts(
        "allgatherv", 4 * 44, 8, 4, ratio)
    want = _expected_gatherv(_host_shards(built), counts, offsets, elems)
    np.testing.assert_array_equal(_step_out(built), want)
    assert built.algo == algo


@pytest.mark.parametrize("algo", ["sortring", "doubling"])
@pytest.mark.parametrize("ratio", [1, 2, 8])
def test_allgatherv_algos_match_numpy_on_2d_mesh(eight_devices, algo,
                                                 ratio):
    from tpu_perf.ops import build_op

    built = build_op("allgatherv", _mesh((2, 4), ("a", "b")), 4 * 20, 1,
                     axis="b", imbalance=ratio, algo=algo)
    counts, offsets, elems, _ = vops.v_counts(
        "allgatherv", 4 * 20, 4, 4, ratio)
    want = _expected_gatherv(_host_shards(built), counts, offsets, elems)
    np.testing.assert_array_equal(_step_out(built), want)


@pytest.mark.parametrize("algo", ["sortring", "doubling"])
def test_allgatherv_algos_int32_bit_exact(eight_devices, algo):
    from tpu_perf.ops import build_op

    built = build_op("allgatherv", _mesh(), 4 * 44, 2, dtype="int32",
                     imbalance=8, algo=algo)
    counts, offsets, elems, _ = vops.v_counts(
        "allgatherv", 4 * 44, 8, 4, 8)
    want = _expected_gatherv(_host_shards(built), counts, offsets, elems)
    out = _step_out(built)
    assert out.dtype == np.int32
    np.testing.assert_array_equal(out, want)


@pytest.mark.parametrize("ratio", [1, 2, 8])
def test_reduce_scatter_v_sortring_matches_numpy(eight_devices, ratio):
    from tpu_perf.ops import build_op

    built = build_op("reduce_scatter_v", _mesh(), 4 * 50, 1,
                     imbalance=ratio, algo="sortring")
    counts, offsets, _, _ = vops.v_counts(
        "reduce_scatter_v", 4 * 50, 8, 4, ratio)
    shards = _host_shards(built).astype(np.float64)
    out = _step_out(built)
    mean = shards.mean(axis=0)
    for d in range(8):
        want = shards[d].copy()
        o, c = offsets[d], counts[d]
        want[o:o + c] = mean[o:o + c]
        np.testing.assert_allclose(out[d], want, rtol=1e-6,
                                   err_msg=f"dev {d}")


def _expected_a2av(shards, blocks, roffs):
    """Destination d's valid regions: one block per source, source
    order, block r drawn from source r's per-destination layout."""
    n = len(blocks)
    out = []
    for d in range(n):
        row = {}
        for r in range(n):
            b = blocks[r]
            row[r] = shards[r][d * b: (d + 1) * b]
        out.append(row)
    return out


@pytest.mark.parametrize("algo", ["native", "ring", "doubling"])
@pytest.mark.parametrize("ratio", [1, 2, 8])
def test_all_to_all_v_matches_numpy(eight_devices, algo, ratio):
    from tpu_perf.ops import build_op

    kw = {} if algo == "native" else {"algo": algo}
    built = build_op("all_to_all_v", _mesh(), 4 * 64, 1,
                     imbalance=ratio, **kw)
    blocks, roffs, _, _ = vops.v_counts("all_to_all_v", 4 * 64, 8, 4,
                                        ratio)
    shards = _host_shards(built)
    out = _step_out(built)
    want = _expected_a2av(shards, blocks, roffs)
    for d in range(8):
        for r in range(8):
            np.testing.assert_array_equal(
                out[d][roffs[r]: roffs[r] + blocks[r]], want[d][r],
                err_msg=f"dest {d} src {r} algo {algo} ratio {ratio}")


@pytest.mark.parametrize("algo", ["ring", "doubling"])
def test_all_to_all_v_int32_bit_exact(eight_devices, algo):
    from tpu_perf.ops import build_op

    built = build_op("all_to_all_v", _mesh(), 4 * 64, 1, dtype="int32",
                     imbalance=8, algo=algo)
    blocks, roffs, _, _ = vops.v_counts("all_to_all_v", 4 * 64, 8, 4, 8)
    shards = _host_shards(built)
    out = _step_out(built)
    assert out.dtype == np.int32
    for d in range(8):
        for r in range(8):
            b = blocks[r]
            np.testing.assert_array_equal(
                out[d][roffs[r]: roffs[r] + b],
                shards[r][d * b: (d + 1) * b])


@pytest.mark.parametrize(
    "algo", ["native", "ring", "rhd", "bruck", "binomial"])
@pytest.mark.parametrize("ratio", [1, 2, 8])
def test_seg_allreduce_matches_numpy(eight_devices, algo, ratio):
    from tpu_perf.ops import build_op

    kw = {} if algo == "native" else {"algo": algo}
    built = build_op("seg_allreduce", _mesh(), 4 * 64, 1,
                     imbalance=ratio, **kw)
    counts, _, elems, _ = vops.v_counts("seg_allreduce", 4 * 64, 8, 4,
                                        ratio)
    w = sum(counts)
    assert w == len(counts) * counts[0] and elems == 8 * counts[0]
    shards = _host_shards(built).astype(np.float64)
    out = _step_out(built)
    mean = shards.mean(axis=0)
    for d in range(8):
        np.testing.assert_allclose(out[d][:w], mean[:w], rtol=1e-5,
                                   err_msg=f"dev {d} algo {algo}")
        # the unselected tail is carried through bit-exactly
        np.testing.assert_array_equal(out[d][w:],
                                      _host_shards(built)[d][w:])


def test_seg_allreduce_rejects_int_dtype(eight_devices):
    from tpu_perf.ops import build_op

    with pytest.raises(ValueError, match="float dtype"):
        build_op("seg_allreduce", _mesh(), 4 * 64, 1, dtype="int32")


@pytest.mark.parametrize("shape", [(2, 4), (4, 2)])
@pytest.mark.parametrize("ratio", [1, 4])
def test_vhier_allgatherv_matches_numpy(eight_devices, shape, ratio):
    from tpu_perf.ops import build_op

    built = build_op("allgatherv", _mesh(shape, ("dcn", "ici")),
                     4 * 44, 2, imbalance=ratio, algo="vhier")
    assert built.algo == f"vhier:dcn={shape[0]}+ici={shape[1]}"
    counts, offsets, elems, _ = vops.v_counts(
        "allgatherv", 4 * 44, 8, 4, ratio)
    want = _expected_gatherv(_host_shards(built), counts, offsets, elems)
    np.testing.assert_array_equal(_step_out(built), want)


def test_vhier_allgatherv_int32_bit_exact(eight_devices):
    from tpu_perf.ops import build_op

    built = build_op("allgatherv", _mesh((2, 4), ("dcn", "ici")),
                     4 * 44, 1, dtype="int32", imbalance=8, algo="vhier")
    counts, offsets, elems, _ = vops.v_counts(
        "allgatherv", 4 * 44, 8, 4, 8)
    want = _expected_gatherv(_host_shards(built), counts, offsets, elems)
    out = _step_out(built)
    assert out.dtype == np.int32
    np.testing.assert_array_equal(out, want)


@pytest.mark.parametrize("ratio", [1, 8])
def test_native_vops_run_over_full_multi_axis_mesh(eight_devices, ratio):
    # a tuple of axis names linearizes row-major under ppermute, so the
    # native v-schedule is the honest whole-mesh baseline for the
    # vhier race
    from tpu_perf.ops import build_op

    built = build_op("allgatherv", _mesh((2, 4), ("a", "b")), 4 * 44, 1,
                     imbalance=ratio)
    assert built.n_devices == 8
    counts, offsets, elems, _ = vops.v_counts(
        "allgatherv", 4 * 44, 8, 4, ratio)
    want = _expected_gatherv(_host_shards(built), counts, offsets, elems)
    np.testing.assert_array_equal(_step_out(built), want)


# --------------------------------------- lockstep proof (satellite 3)


def test_v_schedules_have_no_rank_control_flow(eight_devices):
    """Every new (op, algo) pair traces to ONE program: the only
    conditionals are data selects — never cond/while on axis_index
    (the R2-lockstep proof, extended to the optimized schedules)."""
    import jax

    from tpu_perf.ops import build_op

    pairs = [("allgatherv", "sortring"), ("allgatherv", "doubling"),
             ("reduce_scatter_v", "sortring"), ("all_to_all_v", "ring"),
             ("all_to_all_v", "doubling"), ("seg_allreduce", "ring"),
             ("seg_allreduce", "rhd"), ("seg_allreduce", "bruck"),
             ("seg_allreduce", "binomial")]
    for op, algo in pairs:
        built = build_op(op, _mesh(), 4 * 64, 1, imbalance=8, algo=algo)
        text = str(jax.make_jaxpr(built.step)(built.example_input))
        assert "cond[" not in text and "while[" not in text, (op, algo)
    built = build_op("allgatherv", _mesh((2, 4), ("dcn", "ici")),
                     4 * 64, 1, imbalance=8, algo="vhier")
    text = str(jax.make_jaxpr(built.step)(built.example_input))
    assert "cond[" not in text and "while[" not in text


def test_two_simulated_ranks_agree_on_v_algo_run_stream(
        eight_devices, tmp_path):
    """The PR-11 lockstep pattern with the optimized schedules in the
    plan: the same imbalanced --algo all job executed twice yields
    identical (op, size, algo, ratio, run) streams — plan and schedule
    derive only from static coordinates."""
    from tpu_perf.cli import main

    streams = []
    for rank in ("a", "b"):
        log = tmp_path / rank
        assert main(["run", "--op", "allgatherv", "--algo", "all",
                     "--imbalance", "1,8", "-b", "4K", "-i", "1",
                     "-r", "2", "-l", str(log)]) == 0
        rows = []
        for p in sorted(log.glob("tpu-*.log")):
            rows += [ResultRow.from_csv(ln)
                     for ln in p.read_text().splitlines()]
        streams.append([(r.op, r.nbytes, r.algo, r.imbalance, r.run_id)
                        for r in rows])
    assert streams[0] == streams[1]
    assert {a for _, _, a, _, _ in streams[0]} == {"", "sortring",
                                                   "doubling"}


# --------------------------------------------------- wire-bytes models


def test_allgatherv_wire_model_identities():
    counts = (1,) * 7 + (8,)
    assert valgos.allgatherv_wire_elems("native", counts) == 7 * 15
    assert valgos.allgatherv_wire_elems("sortring", counts) == 7 * 15
    # balanced pow2: doubling's window sums telescope to exactly the
    # ring volume (sum min(w, n-w) over rounds == n-1)
    bal = (3,) * 8
    assert valgos.allgatherv_wire_elems("doubling", bal) == \
        valgos.allgatherv_wire_elems("ring", bal)
    # imbalanced: independent re-derivation of the window sums
    want = 0
    for w in (1, 2, 4):
        cnt = min(w, 8 - w)
        want += sum(sum(counts[(i + t) % 8] for t in range(cnt))
                    for i in range(8))
    assert valgos.allgatherv_wire_elems("doubling", counts) == want
    with pytest.raises(ValueError, match="wire model"):
        valgos.allgatherv_wire_elems("nope", counts)


def test_a2av_wire_model_identities():
    blocks = (1,) * 7 + (8,)
    assert valgos.a2av_wire_elems("native", blocks) == 7 * 15
    assert valgos.a2av_wire_elems("ring", blocks) == 15 * 8 * 7 // 2
    # doubling pads to the hot block: n * maxb * (bit-selected slots)
    assert valgos.a2av_wire_elems("doubling", blocks) == 8 * 8 * 12
    # balanced: native is the floor; the schedules trade volume for
    # round count / group structure
    bal = (2,) * 8
    assert valgos.a2av_wire_elems("native", bal) <= \
        valgos.a2av_wire_elems("ring", bal)


def test_seg_wire_model_identities():
    w, n = 100, 8
    chunk = -(-w // n)
    assert valgos.seg_wire_elems("ring", w, n) == n * 2 * (n - 1) * chunk
    assert valgos.seg_wire_elems("rhd", w, n) == 2 * n * (n - 1) * chunk
    assert valgos.seg_wire_elems("bruck", w, n) == n * w * 7
    assert valgos.seg_wire_elems("binomial", w, n) == 2 * (n - 1) * w
    # density proportionality: half the selected width, half the wire
    assert valgos.seg_wire_elems("binomial", 50, n) * 2 == \
        valgos.seg_wire_elems("binomial", 100, n)
    assert valgos.seg_wire_elems("ring", w, 1) == 0


def test_vhier_wire_model():
    counts, _, _, _ = vops.v_counts("allgatherv", 4 * 44, 8, 4, 4)
    c = counts[0]
    slow, fast = valgos.vhier_wire_elems(counts, (2, 4))
    # phase A: F parallel v-rings over S on the padded (c, 4c) table;
    # phase B: S parallel v-rings over F on the true bundle widths
    assert slow == 4 * (2 - 1) * (c + 4 * c)
    assert fast == 2 * (4 - 1) * (2 * c + 2 * c + 2 * c + 5 * c)


def test_imbalance_volume_scale():
    assert imbalance_volume_scale("allgatherv", 8, 8) == 1.0
    assert imbalance_volume_scale("all_to_all_v", 1, 8) == 1.0
    assert imbalance_volume_scale("all_to_all_v", 8, 8) == 15 / 64
    assert imbalance_volume_scale("seg_allreduce", 8, 8) == 1 / 8
    assert imbalance_volume_scale("seg_allreduce", 3, 8) == 3 / 8
    assert metric_op("all_to_all_v") == "all_to_all"
    assert metric_op("seg_allreduce") == "allreduce"


# --------------------------- algo-aware Imbalance-cost (satellite 1)


def _row(**kw):
    base = dict(
        timestamp=timestamp_now(), job_id="j", backend="jax",
        op="allgatherv", nbytes=4096, iters=4, run_id=1, n_devices=8,
        lat_us=10.0, algbw_gbps=1.0, busbw_gbps=1.75, time_ms=0.04,
    )
    base.update(kw)
    return ResultRow(**base)


def _v_rows(algo, imb_lat, base_lat, nbytes=4096):
    algo_cell = "" if algo == "native" else algo
    rows = []
    for i in range(3):
        rows.append(_row(algo=algo_cell, imbalance=8, lat_us=imb_lat,
                         nbytes=nbytes, run_id=i + 1))
        rows.append(_row(algo=algo_cell, imbalance=1, lat_us=base_lat,
                         nbytes=nbytes, run_id=i + 1))
    return rows


def test_imbalance_cost_best_algo_annotation():
    from tpu_perf.report import aggregate, imbalance_cost

    rows = _v_rows("native", 10.0, 5.0) + _v_rows("sortring", 4.0, 5.0)
    cmp = imbalance_cost(aggregate(rows))
    assert len(cmp) == 2
    for c in cmp:
        assert c.raced == 2
        assert c.best_algo == "sortring"
        assert c.best_vs_native == pytest.approx(0.4)
    assert {c.algo for c in cmp} == {"native", "sortring"}


def test_imbalance_markdown_best_algo_column():
    from tpu_perf.report import (aggregate, imbalance_cost,
                                 imbalance_to_markdown)

    rows = _v_rows("native", 10.0, 5.0) + _v_rows("sortring", 4.0, 5.0)
    md = imbalance_to_markdown(imbalance_cost(aggregate(rows)))
    assert "| best algo | best/naive |" in md
    assert "| sortring | 0.4 |" in md


def test_imbalance_markdown_single_algo_byte_identical():
    """Pre-arena artifacts (one algo per coordinate) render the legacy
    9-column table with not a byte of drift — no best-algo column, no
    dashes."""
    from tpu_perf.report import (aggregate, imbalance_cost,
                                 imbalance_to_markdown)

    cmp = imbalance_cost(aggregate(_v_rows("native", 10.0, 5.0)))
    assert [c.raced for c in cmp] == [1]
    md = imbalance_to_markdown(cmp)
    assert "best algo" not in md
    header = md.splitlines()[0]
    assert header.count("|") == 10  # 9 columns exactly, legacy shape
    assert md.splitlines()[1] == "|---|---|---|---|---|---|---|---|---|"


def test_imbalance_markdown_mixed_race_dashes():
    from tpu_perf.report import (aggregate, imbalance_cost,
                                 imbalance_to_markdown)

    rows = (_v_rows("native", 10.0, 5.0) + _v_rows("sortring", 4.0, 5.0)
            + _v_rows("native", 9.0, 6.0, nbytes=65536))
    cmp = imbalance_cost(aggregate(rows))
    raced = {c.nbytes: c.raced for c in cmp}
    assert raced[4096] == 2 and raced[65536] == 1
    md = imbalance_to_markdown(cmp)
    [solo] = [ln for ln in md.splitlines() if "64K" in ln]
    assert solo.endswith("| — | — |")


# --------------------------------- tuner round trip (satellite 2)


def test_tuner_resolves_imbalanced_v_coordinate():
    """An arena race at an imbalanced coordinate round-trips through
    build_selection → LoadedSelection → --algo auto; an unmeasured
    ratio at the same size falls back to native LOUDLY."""
    from tpu_perf.report import aggregate
    from tpu_perf.runner import algos_for_options
    from tpu_perf.tuner import LoadedSelection, build_selection

    rows = _v_rows("native", 10.0, 5.0) + _v_rows("sortring", 4.0, 5.0)
    art = build_selection(aggregate(rows), generated="g",
                          generated_unix=1000.0)
    imbs = {e.imbalance: e.winner for e in art.entries}
    assert imbs[8] == "sortring"
    sel = LoadedSelection(art)
    opts = Options(op="allgatherv", algo="auto", algo_artifact="x.json",
                   tune_margin=1.0)
    out = algos_for_options(opts, "allgatherv", 8, nbytes=4096,
                            imbalance=8, selection=sel)
    assert out == ["sortring"]
    # unmeasured ratio: loud native fallback, never a silent guess
    err = io.StringIO()
    out = algos_for_options(opts, "allgatherv", 8, nbytes=4096,
                            imbalance=4, selection=sel, err=err)
    assert out == ["native"]
    assert err.getvalue()


def test_auto_vhier_winner_requires_multi_axis_mesh():
    from tpu_perf.runner import algos_for_options
    from tpu_perf.tuner import (
        TUNER_SCHEMA_VERSION, LoadedSelection, SelectionArtifact,
        SelectionEntry,
    )

    entry = SelectionEntry(
        op="allgatherv", nbytes=4096, dtype="float32", skew_us=0,
        imbalance=8, load="", winner="vhier:dcn=2+ici=4",
        winner_p50_us=5.0, runner_up="native", runner_up_p50_us=9.0,
        margin=1.8, native_p50_us=9.0, native_vs_best=1.8, n_devices=8,
        mesh="2x(4)", samples=3,
        algos=("vhier:dcn=2+ici=4", "native"),
    )
    art = SelectionArtifact(
        version=TUNER_SCHEMA_VERSION, generated="g", generated_unix=1.0,
        fingerprint={"tuner_schema": TUNER_SCHEMA_VERSION,
                     "device_kind": "", "chip": "", "n_devices": 8},
        entries=(entry,))
    opts = Options(op="allgatherv", algo="auto", algo_artifact="x.json",
                   tune_margin=1.0)
    # on the artifact's own mesh the keyed winner resolves
    out = algos_for_options(opts, "allgatherv", 8, nbytes=4096,
                            imbalance=8, selection=LoadedSelection(art),
                            mesh_axes=(("dcn", 2), ("ici", 4)))
    assert out == ["vhier:dcn=2+ici=4"]
    # on a flat mesh the winner is unbuildable: loud native fallback
    err = io.StringIO()
    out = algos_for_options(opts, "allgatherv", 8, nbytes=4096,
                            imbalance=8, selection=LoadedSelection(art),
                            mesh_axes=(("x", 8),), err=err)
    assert out == ["native"]
    assert "vhier" in err.getvalue()
