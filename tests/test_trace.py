"""Trace fence: device-clock timing from jax.profiler captures.

The parser is pinned against a synthesized trace-viewer JSON with the
exact structure the TPU runtime writes (verified live on v5e:
process_name "/device:TPU:0", thread "XLA Modules", one X event per
executable launch named jit_<jitname>(<fingerprint>)).  CPU runtimes
record host lanes only, so the live-capture path asserts the loud
failure instead of a silent wrong number.
"""

import gzip
import json
import os

import pytest

from tpu_perf.timing import time_trace
from tpu_perf.traceparse import TraceParseError, device_module_durations


def _write_trace(tmp_path, events, session="2026_07_30_12_00_00",
                 host="vm"):
    d = tmp_path / "plugins" / "profile" / session
    os.makedirs(d, exist_ok=True)
    payload = json.dumps({"traceEvents": events}).encode()
    with gzip.open(d / f"{host}.trace.json.gz", "wb") as fh:
        fh.write(payload)
    return str(tmp_path)


def _tpu_events(durs_us, name="jit_tpuperf_ring(123)", t0=1000.0):
    evs = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 701, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 3, "tid": 7, "name": "thread_name",
         "args": {"name": "XLA Modules"}},
        {"ph": "M", "pid": 3, "tid": 8, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        # host-side event with the same name must NOT count
        {"ph": "X", "pid": 701, "tid": 1, "name": name, "ts": 1.0,
         "dur": 9999.0},
        # per-op device event on another thread must NOT count either
        {"ph": "X", "pid": 3, "tid": 8, "name": "fusion.1", "ts": 2.0,
         "dur": 5.0},
    ]
    for i, d in enumerate(durs_us):
        evs.append({"ph": "X", "pid": 3, "tid": 7, "name": name,
                    "ts": t0 + 100.0 * i, "dur": d})
    return evs


def test_parse_device_module_durations(tmp_path):
    trace = _write_trace(tmp_path, _tpu_events([611.5, 612.0, 611.8]))
    durs = device_module_durations(trace, "tpuperf_ring")
    assert durs == pytest.approx([611.5e-6, 612.0e-6, 611.8e-6])


def test_parse_orders_by_timestamp(tmp_path):
    evs = _tpu_events([2.0], t0=5000.0) + [
        {"ph": "X", "pid": 3, "tid": 7, "name": "jit_tpuperf_ring(123)",
         "ts": 100.0, "dur": 1.0},
    ]
    trace = _write_trace(tmp_path, evs)
    assert device_module_durations(trace, "tpuperf_ring") == \
        pytest.approx([1.0e-6, 2.0e-6])


def test_parse_hint_filters_other_modules(tmp_path):
    evs = _tpu_events([3.0]) + [
        {"ph": "X", "pid": 3, "tid": 7, "name": "jit_other(9)", "ts": 1.0,
         "dur": 42.0},
    ]
    trace = _write_trace(tmp_path, evs)
    assert device_module_durations(trace, "tpuperf_ring") == \
        pytest.approx([3.0e-6])
    # no hint: every module event counts
    assert len(device_module_durations(trace, None)) == 2


def test_parse_newest_session_wins(tmp_path):
    _write_trace(tmp_path, _tpu_events([1.0]), session="2026_01_01_00_00_00")
    trace = _write_trace(tmp_path, _tpu_events([2.0]),
                         session="2026_06_01_00_00_00")
    assert device_module_durations(trace, "tpuperf_ring") == \
        pytest.approx([2.0e-6])


def test_parse_multi_device_lanes_use_one_lane(tmp_path):
    # a multi-device host records one XLA Modules lane PER device; lumping
    # them would double the event count and break (lo, hi) pairing —
    # one lane's view is the sample
    evs = _tpu_events([20.0, 50.0]) + [
        {"ph": "M", "pid": 4, "name": "process_name",
         "args": {"name": "/device:TPU:1"}},
        {"ph": "M", "pid": 4, "tid": 9, "name": "thread_name",
         "args": {"name": "XLA Modules"}},
        {"ph": "X", "pid": 4, "tid": 9, "name": "jit_tpuperf_ring(123)",
         "ts": 1001.0, "dur": 20.5},
        {"ph": "X", "pid": 4, "tid": 9, "name": "jit_tpuperf_ring(123)",
         "ts": 1101.0, "dur": 50.5},
    ]
    trace = _write_trace(tmp_path, evs)
    durs = device_module_durations(trace, "tpuperf_ring")
    assert durs == pytest.approx([20.0e-6, 50.0e-6])  # lowest pid's lane


def test_parse_errors_are_loud(tmp_path):
    with pytest.raises(TraceParseError, match="no profiler capture"):
        device_module_durations(str(tmp_path), None)
    # host-only trace (what a CPU runtime records)
    host_only = [
        {"ph": "M", "pid": 701, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 701, "tid": 1, "name": "PjitFunction(f)",
         "ts": 1.0, "dur": 2.0},
    ]
    trace = _write_trace(tmp_path, host_only)
    with pytest.raises(TraceParseError, match="no /device:"):
        device_module_durations(trace, None)
    # device lanes present but the hint matches nothing
    trace2 = _write_trace(tmp_path, _tpu_events([1.0]),
                          session="2026_12_01_00_00_00")
    with pytest.raises(TraceParseError, match="jit_tpuperf_ring"):
        device_module_durations(trace2, "tpuperf_nope")


def test_time_trace_fails_loudly_on_cpu(eight_devices):
    # CPU runtimes trace host lanes only; the fence must refuse rather
    # than return host numbers dressed up as device time
    from tpu_perf.ops import build_op
    from tpu_perf.parallel import make_mesh

    built = build_op("ring", make_mesh(), 64, 1)
    built_hi = build_op("ring", make_mesh(), 64, 4,
                        reuse_input=built.example_input)
    with pytest.raises(TraceParseError):
        time_trace(built.step, built_hi.step, built.example_input, 1, 4, 2,
                   name_hint="tpuperf_ring")


def test_time_trace_device_slope_math(tmp_path, monkeypatch):
    # pair (lo, hi) module durations -> marginal per-iteration samples;
    # the per-execution constant (e.g. the module's input copy) cancels
    import pathlib

    import tpu_perf.timing as timing_mod

    pending = {"events": None}

    class _P:  # stand-in profiler: writes the staged capture at start_trace
        @staticmethod
        def start_trace(d):
            _write_trace(pathlib.Path(d), pending["events"])

        @staticmethod
        def stop_trace():
            pass

    monkeypatch.setattr(timing_mod.jax, "profiler", _P)
    import jax.numpy as jnp

    step = lambda x: jnp.zeros(4)  # noqa: E731 — fenceable stand-in
    # constant 10 us + 2 us/iter: lo(5 iters)=20, hi(20 iters)=50
    pending["events"] = _tpu_events([20.0, 50.0, 20.3, 50.3])
    times = time_trace(step, step, None, 5, 20, 2,
                       name_hint="tpuperf_ring", trace_dir=str(tmp_path))
    assert times.samples == pytest.approx([2e-6, 2e-6])
    # kept captures get a unique subdir per capture: a second same-second
    # capture must not overwrite the first (sessions are named by SECOND)
    assert len(list(tmp_path.glob("capture_*"))) == 1

    # a non-positive device-time pair is a parse failure, not noise
    pending["events"] = _tpu_events([50.0, 20.0])
    with pytest.raises(TraceParseError, match="non-positive"):
        time_trace(step, step, None, 5, 20, 1,
                   name_hint="tpuperf_ring", trace_dir=str(tmp_path))

    # wrong event count (hint caught someone else / dropped launches)
    pending["events"] = _tpu_events([20.0, 50.0, 21.0])
    with pytest.raises(TraceParseError, match="expected 4"):
        time_trace(step, step, None, 5, 20, 2,
                   name_hint="tpuperf_ring", trace_dir=str(tmp_path))
    assert len(list(tmp_path.glob("capture_*"))) == 3


def test_driver_trace_fence_rows(eight_devices, monkeypatch):
    # marginal device samples become whole-run samples: lat/bw unchanged
    import io

    import tpu_perf.timing as timing_mod
    from tpu_perf.config import Options
    from tpu_perf.driver import Driver
    from tpu_perf.parallel import make_mesh
    from tpu_perf.timing import RunTimes

    calls = []

    def fake_time_trace(step_lo, step_hi, x, iters_lo, iters_hi, num_runs,
                        *, warmup_runs=1, name_hint=None, trace_dir=None):
        calls.append((iters_lo, iters_hi, num_runs, name_hint))
        return RunTimes(samples=[0.5e-6] * num_runs, warmup_s=0.0,
                        overhead_s=0.0)

    monkeypatch.setattr(timing_mod, "time_trace", fake_time_trace)
    opts = Options(op="ring", iters=4, num_runs=3, buff_sz=1024,
                   fence="trace")
    rows = Driver(opts, make_mesh(), err=io.StringIO()).run()
    assert len(rows) == 3
    # finite runs: ONE capture covers all 3 runs at iters and 4x iters
    assert calls == [(4, 16, 3, "tpuperf_ring")]
    assert [r.run_id for r in rows] == [1, 2, 3]
    # 0.5 µs marginal per op; whole-run = 4 ops = 2 µs
    assert rows[0].lat_us == pytest.approx(0.5)
    assert rows[0].time_ms == pytest.approx(2e-3)


def test_daemon_trace_fence_drops_transient_glitches(eight_devices, monkeypatch):
    # a capture that transiently drops a launch must cost one sample,
    # not the whole monitoring daemon (cf. the slope fence's None drops);
    # a runtime without device lanes must still fail fast
    import io

    import tpu_perf.timing as timing_mod
    from tpu_perf.config import Options
    from tpu_perf.driver import Driver
    from tpu_perf.parallel import make_mesh
    from tpu_perf.timing import RunTimes
    from tpu_perf.traceparse import TraceParseError, TraceUnavailableError

    calls = {"n": 0}

    def flaky_time_trace(step_lo, step_hi, x, iters_lo, iters_hi, num_runs,
                         *, warmup_runs=0, name_hint=None, trace_dir=None):
        calls["n"] += 1
        if calls["n"] == 2:
            raise TraceParseError("expected 2 module events, trace has 1")
        return RunTimes(samples=[1e-6] * num_runs, warmup_s=0.0,
                        overhead_s=0.0)

    monkeypatch.setattr(timing_mod, "time_trace", flaky_time_trace)
    err = io.StringIO()
    opts = Options(op="ring", iters=2, num_runs=-1, buff_sz=64, fence="trace")
    d = Driver(opts, make_mesh(), err=err, max_runs=3)
    d.run()
    assert "trace capture inconsistent, run dropped" in err.getvalue()

    def dead_time_trace(*a, **kw):
        raise TraceUnavailableError("no /device:* lanes")

    monkeypatch.setattr(timing_mod, "time_trace", dead_time_trace)
    d = Driver(opts, make_mesh(), err=io.StringIO(), max_runs=2)
    with pytest.raises(TraceUnavailableError):
        d.run()


def test_finite_trace_skip_keeps_lockstep(eight_devices, monkeypatch):
    # ADVICE r4 (medium): a point whose capture fails every attempt must
    # yield num_runs None records — every heartbeat boundary still driven
    # — not an empty list; and multi-host gets NO retry (a one-host
    # re-execution of the collectives would desync the peers)
    import io

    import tpu_perf.timing as timing_mod
    from tpu_perf.config import Options
    from tpu_perf.driver import Driver
    from tpu_perf.parallel import make_mesh
    from tpu_perf.traceparse import TraceParseError

    calls = {"n": 0}

    def broken_time_trace(*a, **kw):
        calls["n"] += 1
        raise TraceParseError("expected 8 module events, trace has 7")

    monkeypatch.setattr(timing_mod, "time_trace", broken_time_trace)
    err = io.StringIO()
    opts = Options(op="ring", iters=2, num_runs=4, buff_sz=64,
                   fence="trace", stats_every=2)
    heartbeats = {"n": 0}
    d = Driver(opts, make_mesh(), err=err)
    orig_hb = d._heartbeat

    def counting_hb(run_id, samples):
        heartbeats["n"] += 1
        return orig_hb(run_id, samples)

    d._heartbeat = counting_hb
    rows = d.run()
    assert rows == [] and calls["n"] == 2  # single-host: one retry
    assert "skipped" in err.getvalue()
    # all 4 run boundaries were driven: 2 stats boundaries reached
    assert heartbeats["n"] == 2

    # multi-host: exactly one attempt, still num_runs boundaries (wrap
    # THIS driver's bound heartbeat so its n_hosts=2 path really runs)
    calls["n"] = 0
    heartbeats["n"] = 0
    err2 = io.StringIO()
    d = Driver(opts, make_mesh(), err=err2)
    d.n_hosts = 2
    orig_hb2 = d._heartbeat

    def counting_hb2(run_id, samples):
        heartbeats["n"] += 1
        return orig_hb2(run_id, samples)

    d._heartbeat = counting_hb2
    rows = d.run()
    assert rows == [] and calls["n"] == 1
    assert heartbeats["n"] == 2
    # the all-dropped windows stay loud at every boundary
    assert err2.getvalue().count("no samples this window") == 2


def test_run_point_trace_fence(eight_devices, monkeypatch):
    import tpu_perf.runner as runner_mod
    from tpu_perf.config import Options
    from tpu_perf.parallel import make_mesh
    from tpu_perf.runner import run_point
    from tpu_perf.timing import RunTimes

    def fake_time_trace(step_lo, step_hi, x, iters_lo, iters_hi, num_runs,
                        *, warmup_runs=1, name_hint=None, trace_dir=None):
        assert name_hint == "tpuperf_hbm_stream"
        assert (iters_lo, iters_hi) == (2, 8)
        return RunTimes(samples=[5e-6] * num_runs, warmup_s=0.0,
                        overhead_s=0.0)

    monkeypatch.setattr(runner_mod, "time_trace", fake_time_trace)
    opts = Options(op="hbm_stream", iters=2, num_runs=4, buff_sz=4096,
                   fence="trace")
    point = run_point(opts, make_mesh(), 4096)
    assert len(point.times.samples) == 4
    rows = point.rows("job")
    assert rows[0].lat_us == pytest.approx(5.0)  # 5 µs marginal per op


def test_cli_accepts_trace_fence():
    from tpu_perf.cli import build_parser

    args = build_parser().parse_args(["run", "--fence", "trace"])
    assert args.fence == "trace"


def test_parse_corrupt_capture_is_trace_parse_error(tmp_path):
    # a truncated capture (disk full mid-write) must surface as
    # TraceParseError so drop-the-sample handlers see the type they catch
    import os

    d = tmp_path / "plugins" / "profile" / "2026_07_30_12_00_00"
    os.makedirs(d)
    (d / "vm.trace.json.gz").write_bytes(b"\x1f\x8b\x08\x00garbage")
    with pytest.raises(TraceParseError, match="unreadable capture"):
        device_module_durations(str(tmp_path), None)
